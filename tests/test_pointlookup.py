"""Point-lookup acceleration (plan/pointlookup.py) — the index /
AO-block-directory analog: WHERE col = const on a big RAM table binds
the scan to the sorted-sidecar-matched rows instead of the whole
table/shard; results must be identical to the full masked scan."""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config

N = 200_000


def _mk(nseg, point=True, n=N):
    ov = {"n_segments": nseg}
    if not point:
        ov["planner.enable_point_lookup"] = False
    s = cb.Session(Config(n_segments=nseg).with_overrides(**ov))
    rng = np.random.default_rng(0)
    s.sql("create table pts (k bigint, v bigint, d decimal(8,2), "
          "c text) distributed by (k)")
    from cloudberry_tpu.columnar.dictionary import StringDictionary

    d = StringDictionary()
    codes = np.asarray([d.add(f"s{i % 50}") for i in range(50)])
    s.catalog.table("pts").set_data({
        "k": rng.permutation(n),
        "v": rng.integers(0, 100, n),
        "d": rng.integers(0, 10**6, n),
        "c": codes[rng.integers(0, 50, n)]}, {"c": d})
    return s


def test_point_lookup_matches_full_scan():
    a = _mk(1)
    b = _mk(1, point=False)
    q = "select k, v, d, c from pts where k = 12345"
    assert "point-lookup" in a.explain(q)
    assert "point-lookup" not in b.explain(q)
    assert a.sql(q).to_pandas().equals(b.sql(q).to_pandas())
    # a miss returns zero rows, not an error
    assert len(a.sql("select v from pts where k = 987654321")
               .to_pandas()) == 0


def test_point_lookup_extra_conjuncts_still_filter():
    a = _mk(1)
    b = _mk(1, point=False)
    q = "select k, v from pts where k = 777 and v > 50"
    assert a.sql(q).to_pandas().equals(b.sql(q).to_pandas())


def test_point_lookup_string_eq_via_codes():
    """Dictionary equality binds as a code literal: 1/50 of 200k rows
    (~4000 matches) clears the point guard and indexes; results match
    the full scan exactly."""
    a = _mk(1)
    b = _mk(1, point=False)
    q = "select count(*) as n, sum(v) as sv from pts where c = 's7'"
    assert "point-lookup" in a.explain(q)
    assert a.sql(q).to_pandas().equals(b.sql(q).to_pandas())


def test_non_selective_eq_stays_a_scan():
    """A flag-like equality matching a visible fraction of the table is
    NOT a point — the guard (max(4096, n/64) matched rows) keeps the
    masked scan and the stable plan shape."""
    a = _mk(1)
    q = "select count(*) as n from pts where v = 7"  # ~1/100 of 200k
    # v has 100 values over 200k rows -> ~2000 matches: POINT binds;
    # the truly non-selective case is a 2-value flag
    s = cb.Session()
    rng = np.random.default_rng(1)
    s.sql("create table flags (f bigint, v bigint)")
    s.catalog.table("flags").set_data({
        "f": rng.integers(0, 2, 100_000), "v": rng.integers(0, 9, 100_000)})
    q2 = "select count(*) as n from flags where f = 1"
    assert "point-lookup" not in s.explain(q2)
    assert int(s.sql(q2).to_pandas()["n"][0]) > 40_000


def test_insert_invalidates_sidecar():
    a = _mk(1)
    assert len(a.sql("select v from pts where k = 987654321")
               .to_pandas()) == 0
    a.sql("insert into pts values (987654321, 7, 1.25, 's1')")
    df = a.sql("select v from pts where k = 987654321").to_pandas()
    assert list(df["v"]) == [7]


def test_point_lookup_under_direct_dispatch():
    """Dist-key equality routes to one segment AND the sidecar narrows
    that shard (shards must clear the size floor)."""
    a = _mk(8, n=400_000)
    b = _mk(8, point=False, n=400_000)
    q = "select k, v, d from pts where k = 12345"
    ex = a.explain(q)
    assert "Direct dispatch" in ex
    assert "point-lookup" in ex
    want = b.sql(q).to_pandas()
    got = a.sql(q).to_pandas()
    assert want.equals(got)


def test_small_tables_skip_the_sidecar():
    s = cb.Session()
    s.sql("create table tiny (k bigint, v bigint)")
    s.sql("insert into tiny values (1, 10), (2, 20)")
    q = "select v from tiny where k = 2"
    assert "point-lookup" not in s.explain(q)
    assert list(s.sql(q).to_pandas()["v"]) == [20]
