"""Wire-error taxonomy round-trip — every class, both transports.

Raises each wire-error class through a serving stack on BOTH transports
(event-loop asyncore default + threaded fallback) and asserts:

- the response carries the ``retryable`` stamp and it equals
  ``lifecycle.is_retryable``'s verdict (one classifier, both sides);
- ``etype`` round-trips the class NAME (the client's by-name channel);
- a ``retry_reads`` client actually retries exactly the retryable
  verdicts (transient failure → success) and gives up immediately on
  semantic ones;
- the runtime registry matches the STATIC model graftlint's taxonomy
  pass extracts from lifecycle.py — the lint gate and the live server
  can never disagree about what is retryable.
"""

import ast
import os

import pytest

import cloudberry_tpu as cb
from cloudberry_tpu import lifecycle
from cloudberry_tpu.config import Config
from cloudberry_tpu.exec.resource import TenantQueueFull
from cloudberry_tpu.sched.dispatcher import SchedDeadline, SchedQueueFull
from cloudberry_tpu.serve import Client, Server, ServerError

WIRE_ERRORS = [
    # (class, expected retryable)
    (lifecycle.StatementTimeout, True),
    (lifecycle.ServerDraining, True),
    (lifecycle.BreakerOpen, True),
    (lifecycle.ServerBusy, True),
    (lifecycle.StatementCancelled, False),
    (SchedQueueFull, True),
    (SchedDeadline, True),
    (TenantQueueFull, True),
    # storage taxonomy (ISSUE 19): an OS-layer write failure is
    # transient (the previous snapshot is intact — retry); bytes that
    # fail their content checksum are not coming back on a retry
    (lifecycle.StorageIOError, True),
    (lifecycle.StorageCorruptionError, False),
    (ValueError, False),          # ordinary semantic failure
]


class _FakeResult:
    def decoded_columns(self):
        return {"a": [1]}


@pytest.fixture(scope="module", params=["asyncore", "threaded"])
def wire(request):
    over = {"serve.threaded": request.param == "threaded"}
    sess = cb.Session(Config().with_overrides(**over))
    srv = Server(session=sess).start()
    yield sess, srv
    srv.stop()


@pytest.mark.parametrize("err_cls,expect_retryable",
                         WIRE_ERRORS, ids=lambda v: getattr(
                             v, "__name__", str(v)))
def test_stamp_and_etype_round_trip(wire, err_cls, expect_retryable):
    sess, srv = wire
    orig = sess.sql
    sess.sql = lambda q, **kw: (_ for _ in ()).throw(
        err_cls(f"injected {err_cls.__name__}"))
    try:
        with Client(srv.host, srv.port) as c:
            with pytest.raises(ServerError) as ei:
                c.sql("select a from nowhere")
        assert ei.value.etype == err_cls.__name__
        assert ei.value.retryable is expect_retryable
        # one classifier for both sides: the stamp is exactly
        # is_retryable — as an instance AND by name
        assert lifecycle.is_retryable(err_cls("x")) is expect_retryable
        assert lifecycle.is_retryable(err_cls.__name__) \
            is expect_retryable
    finally:
        sess.sql = orig


@pytest.mark.parametrize("err_cls,expect_retryable",
                         WIRE_ERRORS, ids=lambda v: getattr(
                             v, "__name__", str(v)))
def test_client_retry_follows_the_taxonomy(wire, err_cls,
                                           expect_retryable):
    """Transient failure (fails once, then succeeds): a retry_reads
    client recovers exactly when the taxonomy says retry."""
    sess, srv = wire
    orig = sess.sql
    calls = {"n": 0}

    def flaky(q, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise err_cls(f"injected {err_cls.__name__}")
        return _FakeResult()

    sess.sql = flaky
    try:
        with Client(srv.host, srv.port, retry_reads=True,
                    max_retries=2, backoff_s=0.01) as c:
            if expect_retryable:
                out = c.sql("select a from nowhere")
                assert out["rows"] == [[1]]
                assert calls["n"] == 2  # failed once, retried once
            else:
                with pytest.raises(ServerError) as ei:
                    c.sql("select a from nowhere")
                assert ei.value.etype == err_cls.__name__
                assert calls["n"] == 1  # semantic: no retry
    finally:
        sess.sql = orig


def test_runtime_registry_matches_lint_static_model():
    """The set the lint taxonomy pass reads out of lifecycle.py IS the
    runtime set — the gate's model can never drift from the server's."""
    from cloudberry_tpu.lint.passes.taxonomy import _str_set_literal

    path = os.path.join(os.path.dirname(os.path.abspath(cb.__file__)),
                        "lifecycle.py")
    with open(path) as f:
        tree = ast.parse(f.read())
    static = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and getattr(node.targets[0], "id", "") \
                == "_RETRYABLE_NAMES":
            static = _str_set_literal(node.value)
    assert static == set(lifecycle._RETRYABLE_NAMES)
    # and every expectation this test file pins agrees with it
    for err_cls, expect in WIRE_ERRORS:
        if err_cls is ValueError:
            continue
        assert (err_cls.__name__ in static) is expect
