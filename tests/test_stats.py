"""Statistics + cost model + DP join ordering (the ANALYZE / pg_statistic /
CJoinOrderDP analog)."""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.plan import cost as C
from cloudberry_tpu.plan import nodes as N


@pytest.fixture
def s():
    s = cb.Session()
    s.sql("create table f (k bigint, g bigint, v bigint) "
          "distributed by (k)")
    n = 1000
    rows = ",".join(f"({i}, {i % 10}, {i % 100})" for i in range(n))
    s.sql(f"insert into f values {rows}")
    s.sql("create table d (k bigint, name bigint) distributed by (k)")
    rows = ",".join(f"({i}, {i})" for i in range(50))
    s.sql(f"insert into d values {rows}")
    return s


def _plan(s, sql):
    from cloudberry_tpu.plan.binder import Binder
    from cloudberry_tpu.sql.parser import parse_sql

    return Binder(s.catalog).bind_query(parse_sql(sql))


def test_ndv_lazy_and_analyze(s):
    t = s.catalog.table("f")
    assert t.ndv("g") == 10
    assert t.ndv("v") == 100
    out = s.sql("analyze f")
    assert "ANALYZE" in str(out)
    assert t.stats.ndv["k"] == 1000


def test_analyze_persists_for_cold_tables(tmp_path):
    cfg = Config().with_overrides(**{"storage.root": str(tmp_path)})
    s = cb.Session(cfg)
    s.sql("create table t (a bigint, g bigint) distributed by (a)")
    s.sql("insert into t values " +
          ",".join(f"({i}, {i % 7})" for i in range(100)))
    s.sql("analyze t")
    s2 = cb.Session(cfg)
    t = s2.catalog.table("t")
    assert t.cold
    assert t.ndv("g") == 7  # from the manifest, no data load
    assert t.cold


def test_analyze_in_rolled_back_txn_not_durable(tmp_path):
    """Regression: ANALYZE inside BEGIN..ROLLBACK must not publish stats
    computed from rolled-back rows."""
    cfg = Config().with_overrides(**{"storage.root": str(tmp_path)})
    s = cb.Session(cfg)
    s.sql("create table t (a bigint, g bigint) distributed by (a)")
    s.sql("insert into t values " +
          ",".join(f"({i}, {i % 5})" for i in range(50)))
    s.sql("begin")
    s.sql("insert into t values " +
          ",".join(f"({i + 100}, {i})" for i in range(50)))
    s.sql("analyze t")
    s.sql("rollback")
    s2 = cb.Session(cfg)
    assert s2.catalog.table("t").ndv("g") in (None, 5)
    # and a committed ANALYZE does persist
    s.sql("analyze t")
    s3 = cb.Session(cfg)
    assert s3.catalog.table("t").ndv("g") == 5


def test_filter_selectivity_estimates(s):
    cat = s.catalog
    p = _plan(s, "select k from f where g = 3")
    est = C.estimate_rows(p, cat)
    assert 50 <= est <= 200  # 1000/10 = 100
    p2 = _plan(s, "select k from f where k < 250")
    est2 = C.estimate_rows(p2, cat)
    assert 150 <= est2 <= 350  # ~25%


def test_join_estimate(s):
    cat = s.catalog
    p = _plan(s, "select f.k from f, d where f.v = d.k")
    est = C.estimate_rows(p, cat)
    # 1000 × 50 / max(100, 50) = 500
    assert 300 <= est <= 800


def test_dp_join_order_small_side_becomes_build(s):
    # d (50 unique rows) should be the lookup build side under f (1000)
    p = _plan(s, "select f.k from f, d where f.k = d.k")
    joins = []

    def walk(n):
        if isinstance(n, N.PJoin):
            joins.append(n)
        for c in n.children():
            walk(c)

    walk(p)
    assert len(joins) == 1
    j = joins[0]
    assert j.unique_build
    # the build subtree scans d
    from cloudberry_tpu.exec.executor import scans_of

    assert {sc.table_name for sc in scans_of(j.build)} == {"d"}


def test_where_edge_inside_explicit_join_is_filter(s):
    """Regression: WHERE equality between two already-joined aliases must
    filter, not vanish (pre-DP planner silently dropped it)."""
    s.sql("create table t2 (a int, b int) distributed by (a)")
    s.sql("create table u2 (a int, d int) distributed by (a)")
    s.sql("insert into t2 values (1, 100), (2, 200)")
    s.sql("insert into u2 values (1, 100), (2, 999)")
    out = s.sql("select t2.a from t2 join u2 on t2.a = u2.a "
                "where t2.b = u2.d").to_pandas()
    assert out.a.tolist() == [1]


def test_unique_not_propagated_through_expansion_join(s):
    """Regression: an expansion (many-to-many) join duplicates probe rows,
    so probe-side uniqueness must not survive it (wrong PK-join plans)."""
    s.sql("create table m1 (a bigint, g bigint) distributed by (a)")
    s.sql("create table m2 (b bigint, g bigint) distributed by (b)")
    s.sql("create table pk (a bigint) distributed by (a)")
    s.sql("insert into m1 values (1, 5), (2, 5)")
    s.sql("insert into m2 values (10, 5), (11, 5)")
    s.sql("insert into pk values (1), (2)")
    # m1⋈m2 on g is many-to-many (4 pairs; 'a' duplicates), then join pk
    out = s.sql("select count(*) as n from m1, m2, pk "
                "where m1.g = m2.g and m1.a = pk.a").to_pandas()
    assert out.n[0] == 4
