"""COPY single-row error handling — the cdbsreh.c analog.

Reference: COPY ... SEGMENT REJECT LIMIT n [ROWS|PERCENT] [LOG ERRORS]
tolerates malformed rows up to the limit (logging them for
gp_read_error_log) instead of aborting the load; past the limit the load
aborts with nothing appended.
"""

import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.plan.binder import BindError


@pytest.fixture
def sess():
    s = cb.Session(Config(n_segments=1))
    s.sql("create table ld (k bigint, amt decimal(8,2), name text)")
    return s


def _write(tmp_path, text):
    p = tmp_path / "in.csv"
    p.write_text(text)
    return str(p)


GOOD_AND_BAD = ("1|10.50|aa\n"
                "oops|20.00|bb\n"      # bad int
                "3|not-a-num|cc\n"     # bad decimal
                "4|40.25|dd\n"
                "5|50.00\n"            # short row
                "6|60.75|ff\n")


def test_reject_limit_tolerates(sess, tmp_path):
    path = _write(tmp_path, GOOD_AND_BAD)
    res = sess.sql(f"copy ld from '{path}' with segment reject limit 5 "
                   "log errors")
    assert res == "COPY 3 (rejected 3 rows)"
    df = sess.sql("select k, name from ld order by k").to_pandas()
    assert list(df["k"]) == [1, 4, 6]
    log = sess.read_error_log("ld")
    assert len(log) == 3
    assert set(log["line"]) == {2, 3, 5}
    assert any("columns" in m for m in log["errmsg"])


def test_reject_limit_trips_aborts_whole_load(sess, tmp_path):
    path = _write(tmp_path, GOOD_AND_BAD)
    with pytest.raises(BindError, match="reject limit"):
        sess.sql(f"copy ld from '{path}' with segment reject limit 2")
    # nothing appended on abort
    assert sess.sql("select count(*) as c from ld").to_pandas()["c"].iloc[0] \
        == 0
    # cdbsreh.c semantics: REACHING the limit aborts (3 bad rows, limit 3)
    with pytest.raises(BindError, match="reject limit"):
        sess.sql(f"copy ld from '{path}' with segment reject limit 3")
    res = sess.sql(f"copy ld from '{path}' with segment reject limit 4")
    assert res.startswith("COPY 3")


def test_reject_percent(sess, tmp_path):
    path = _write(tmp_path, GOOD_AND_BAD)  # 3/6 = 50% rejected
    res = sess.sql(f"copy ld from '{path}' with segment reject limit 60 "
                   "percent")
    assert res.startswith("COPY 3")
    with pytest.raises(BindError, match="PERCENT"):
        sess.sql(f"copy ld from '{path}' with segment reject limit 40 "
                 "percent")


def test_nulls_and_not_null_rejects(sess, tmp_path):
    sess.sql("create table nn (k bigint not null, v bigint)")
    path = _write(tmp_path, "1|10\n\\N|20\n3|\\N\n")
    res = sess.sql(f"copy nn from '{path}' with segment reject limit 5 "
                   "log errors")
    assert res == "COPY 2 (rejected 1 rows)"
    df = sess.sql("select k from nn order by k").to_pandas()
    assert list(df["k"]) == [1, 3]
    assert "NOT NULL" in sess.read_error_log("nn")["errmsg"].iloc[0]


def test_out_of_range_values_reject_not_wrap(sess, tmp_path):
    sess.sql("create table narrow (k integer, v bigint)")  # int32 column
    path = _write(tmp_path, "1|10\n5000000000|20\n"
                            "3|99999999999999999999\n4|40\n")
    res = sess.sql(f"copy narrow from '{path}' with segment reject limit 5 "
                   "log errors")
    # int32 overflow and int64 overflow both REJECT (never wrap, never
    # abort the whole load)
    assert res == "COPY 2 (rejected 2 rows)"
    df = sess.sql("select k from narrow order by k").to_pandas()
    assert list(df["k"]) == [1, 4]
    assert all("out of range" in m
               for m in sess.read_error_log("narrow")["errmsg"])


def test_without_sreh_still_aborts(sess, tmp_path):
    path = _write(tmp_path, GOOD_AND_BAD)
    with pytest.raises(BindError):
        sess.sql(f"copy ld from '{path}'")
