"""Asynchronous scan pipeline (exec/scanpipe.py) — prefetch + parallel
decode + device double-buffering over the tiled executors.

The contract under test: pipeline on/off is BIT-IDENTICAL across every
tiled mode (agg/topn/sort/window, single-node and dist8) because the
pipeline only moves host work off the critical path; cancellation mid-
prefetch leaves no orphan reader thread; checkpoint resume with a warm
queue replays ≤ K tiles and never re-decodes consumed partitions; the
bounded queue respects its depth under a tiny-tile stress; and the
``scan_prefetch``/``scan_decode`` fault seams fire and recover.
"""

import threading
import time

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu import lifecycle
from cloudberry_tpu.config import get_config
from cloudberry_tpu.exec import scanpipe as SP
from cloudberry_tpu.utils import faultinject as FI

AGG_Q = ("SELECT g, sum(v) AS sv, count(*) AS c "
         "FROM fact JOIN dim ON fact.k = dim.k GROUP BY g ORDER BY g")
TOPN_Q = ("SELECT fact.k AS k, v, g FROM fact JOIN dim ON fact.k = dim.k "
          "WHERE v < 90 ORDER BY v, fact.k, g LIMIT 25")
SORT_Q = ("SELECT g, v FROM fact JOIN dim ON fact.k = dim.k "
          "WHERE v < 50 ORDER BY g, v DESC, fact.k")
WIN_Q = ("SELECT g, v, rank() over (partition by g order by v desc) AS r,"
         " sum(v) over (partition by g) AS sv "
         "FROM fact JOIN dim ON fact.k = dim.k")


def _load(s, n_fact=120_000, n_dim=500, n_groups=9):
    rng = np.random.default_rng(3)
    s.sql("CREATE TABLE dim (k BIGINT, g BIGINT) DISTRIBUTED BY (k)")
    s.sql("CREATE TABLE fact (k BIGINT, v BIGINT) DISTRIBUTED BY (k)")
    s.catalog.table("dim").set_data(
        {"k": np.arange(n_dim), "g": np.arange(n_dim) % n_groups})
    s.catalog.table("fact").set_data(
        {"k": rng.integers(0, n_dim, n_fact),
         "v": rng.integers(0, 100, n_fact)})


def _mk(budget=None, pipeline=None, nseg=1, **extra):
    ov = {"n_segments": nseg}
    if budget is not None:
        ov["resource.query_mem_bytes"] = budget
    if pipeline is not None:
        ov["scan_pipeline.enabled"] = pipeline
    ov.update(extra)
    return cb.Session(get_config().with_overrides(**ov))


@pytest.fixture(autouse=True)
def _clean_faults():
    FI.reset_fault()
    yield
    FI.reset_fault()


def _no_orphan_readers(timeout=5.0) -> bool:
    """True once no cbtpu-scan-reader thread is alive (join-with-timeout
    discipline: the pipeline must tear its reader down, not leak it)."""
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if not any(t.name.startswith("cbtpu-scan-reader")
                   and t.is_alive() for t in threading.enumerate()):
            return True
        time.sleep(0.05)
    return False


# ------------------------------------------------- on/off bit-identity


@pytest.fixture(scope="module")
def expected():
    s = _mk()
    _load(s)
    return {q: s.sql(q).to_pandas() for q in (AGG_Q, TOPN_Q, SORT_Q,
                                              WIN_Q)}


@pytest.mark.parametrize("q,mode", [(AGG_Q, None), (TOPN_Q, "topn"),
                                    (SORT_Q, "sort"), (WIN_Q, "window")])
def test_pipeline_on_off_bit_identical_single(expected, q, mode):
    got = {}
    for pipe in (True, False):
        s = _mk(budget=3 << 20, pipeline=pipe)
        _load(s)
        got[pipe] = s.sql(q).to_pandas()
        rep = s.last_tiled_report
        assert rep["tiled"] and rep["n_tiles"] > 1
        if mode is not None:
            assert rep["mode"] == mode
        assert rep["pipeline"]["enabled"] is pipe
    assert got[True].equals(got[False])
    if mode != "window":  # window row order is sort-compared elsewhere
        assert expected[q].equals(got[True])
    assert _no_orphan_readers()


# per-mode dist8 shapes: the (nseg, tile_rows) tile covers 8× the
# single-node rows, so agg/topn/sort stream multiple tiles at 1 MiB;
# the window path additionally needs every partition to fit one spill
# chunk, so it runs finer-grained groups (300) over more rows at the
# budget whose chunk capacity holds them
_DIST8 = [(AGG_Q, None, 1 << 20, 120_000, 9),
          (TOPN_Q, "topn", 1 << 20, 120_000, 9),
          (SORT_Q, "sort", 1 << 20, 120_000, 9),
          (WIN_Q, "window", 4 << 20, 240_000, 300)]


@pytest.mark.parametrize("q,mode,budget,n_fact,n_groups", _DIST8)
def test_pipeline_on_off_bit_identical_dist8(q, mode, budget, n_fact,
                                             n_groups):
    got = {}
    for pipe in (True, False):
        s = _mk(budget=budget, pipeline=pipe, nseg=8)
        _load(s, n_fact=n_fact, n_groups=n_groups)
        got[pipe] = s.sql(q).to_pandas()
        rep = s.last_tiled_report
        assert rep["tiled"] and rep["n_tiles"] > 1
        if mode is not None:
            assert rep["mode"] == mode
        assert rep["pipeline"]["enabled"] is pipe
    assert got[True].equals(got[False])
    assert _no_orphan_readers()


def test_cold_store_pipeline_bit_identical(tmp_path):
    """The out-of-core path proper: micro-partition files stream
    through the prefetch pipeline with column-parallel decode; on/off
    bit-identical, decode accounting stamped on the report, and the
    ``decode_seconds`` histogram feeds the registry."""
    root = str(tmp_path / "store")
    s0 = _mk(**{"storage.root": root,
                "storage.rows_per_partition": 20_000})
    _load(s0)
    exp = s0.sql(AGG_Q).to_pandas()

    got = {}
    for pipe in (True, False):
        s = _mk(budget=3 << 20, pipeline=pipe, **{"storage.root": root})
        assert s.catalog.table("fact").cold
        got[pipe] = s.sql(AGG_Q).to_pandas()
        rep = s.last_tiled_report
        assert rep["pipeline"]["enabled"] is pipe
        assert rep["pipeline"]["parts_read"] > 1
        assert rep["pipeline"]["decode_s"] >= 0.0
        if pipe:
            assert rep["pipeline"]["tiles_prefetched"] == rep["n_tiles"]
            # depth respected: the high-water mark never exceeds the
            # configured queue bound
            assert rep["pipeline"]["max_depth"] \
                <= s.config.scan_pipeline.prefetch_tiles
        h = s.stmt_log.registry.hist("decode_seconds")
        assert h is not None and h["count"] > 0
    assert got[True].equals(got[False]) and exp.equals(got[True])
    assert _no_orphan_readers()


# -------------------------------------------------------- cancellation


def test_cancel_mid_prefetch_no_orphan_reader():
    """Cancel lands while the reader is prefetching ahead (the consumer
    is slowed by a tile_step sleep, so the queue is warm): the
    statement dies with StatementCancelled, the reader thread joins,
    and a rerun on the same session is bit-identical."""
    expect_s = _mk(budget=3 << 20)
    _load(expect_s)
    expect = expect_s.sql(AGG_Q).to_pandas()

    s = _mk(budget=3 << 20)
    _load(s)
    FI.inject_fault("tile_step", "sleep", sleep_s=0.05)
    errs = []

    def bg():
        try:
            s.sql(AGG_Q)
        except BaseException as e:  # noqa: BLE001 — assertion target
            errs.append(e)

    th = threading.Thread(target=bg)
    th.start()
    act = None
    for _ in range(500):
        act = s.stmt_log.activity()
        if act:
            break
        time.sleep(0.01)
    assert act, "statement never appeared in the activity view"
    time.sleep(0.25)  # let the reader stage tiles ahead
    assert s.stmt_log.cancel(act[0]["id"])
    th.join(timeout=60)
    assert errs and isinstance(errs[0], lifecycle.StatementCancelled)
    assert _no_orphan_readers()

    FI.reset_fault()
    got = s.sql(AGG_Q).to_pandas()
    assert s.last_tiled_report is not None
    assert expect.equals(got)


# -------------------------------------------------- checkpoint/resume


def test_resume_warm_queue_replays_bounded(tmp_path):
    """Device loss mid-stream with a warm prefetch queue: the resume
    replays ≤ K tiles (staged-but-unconsumed tiles never count as
    progress) and — on the cold path — skips already-consumed
    partitions without re-decoding them."""
    root = str(tmp_path / "store")
    s0 = _mk(**{"storage.root": root,
                "storage.rows_per_partition": 20_000})
    _load(s0)
    exp = s0.sql(AGG_Q).to_pandas()

    K = 2
    # 1 MiB → 8 tiles of 16384 over the 120k-row fact: the kill at the
    # 6th tile lands well past the second checkpoint AND past whole
    # 20k-row partitions (the skip fast path has something to skip)
    s = _mk(budget=1 << 20, **{"storage.root": root,
                               "recovery.checkpoint_every": K,
                               "health.retries": 2,
                               "health.backoff_s": 0.01})
    # kill late enough that whole partitions are behind the checkpoint
    FI.inject_fault("tile_device_lost", "error", start_hit=6, end_hit=6)
    got = s.sql(AGG_Q).to_pandas()
    assert exp.equals(got)
    rep = s.last_tiled_report
    assert rep["tiles_replayed"] <= K
    assert rep["resumed_from_tile"] >= 1
    # the resumed attempt's feed skipped consumed partitions outright
    assert rep["pipeline"]["parts_skipped"] >= 1
    assert _no_orphan_readers()


# ------------------------------------------------------- queue behavior


def test_queue_bound_respected_tiny_tiles():
    """1-row-tile stress directly on the pipeline: 500 tiles through a
    depth-3 queue with a slow consumer — every tile arrives in order
    and the buffer high-water mark never exceeds the bound."""
    def gen():
        for i in range(500):
            yield ({"x": np.array([i], dtype=np.int64)}, 1)

    p = SP.ScanPipeline(gen(), depth=3)
    seen = []
    try:
        for i, (tile, n) in enumerate(p):
            assert n == 1
            seen.append(int(tile["x"][0]))
            if i % 50 == 0:
                time.sleep(0.01)  # let the reader race ahead
    finally:
        p.close()
    assert seen == list(range(500))
    assert p.max_depth <= 3
    assert p.stats()["tiles_prefetched"] == 500
    assert _no_orphan_readers()


def test_abandoned_pipeline_close_joins_reader():
    """close() mid-stream (the adaptive-retry restart shape): the
    reader joins promptly and staged buffers release."""
    def gen():
        for i in range(10_000):
            yield ({"x": np.zeros(1024, dtype=np.int64)}, 1024)

    p = SP.ScanPipeline(gen(), depth=2)
    next(iter(p))
    p.close()
    assert _no_orphan_readers()


def test_pendbuf_linear_copies():
    """The O(n²) drain fix, pinned by allocation accounting:
    chunk-exact tiles hand the decoded chunk over zero-copy; every
    other tile copies its rows EXACTLY once — never the whole pending
    tail per tile, and never a sub-chunk view (whose base would pin
    the whole partition in the prefetch queue)."""
    from cloudberry_tpu.exec.tiled import _PendBuf

    # chunk-exact: chunk size == tile size — all zero-copy handovers
    st = SP.ScanStats()
    buf = _PendBuf(st)
    src = [np.arange(c * 250, (c + 1) * 250) for c in range(16)]
    for c in src:
        buf.append({"a": c})
    outs = []
    while buf.rows >= 250:
        outs.append(buf.take(250)["a"])
    assert st.copy_rows == 0 and st.view_rows == 4_000
    for got, chunk in zip(outs, src):
        assert got is chunk  # the chunk array itself, not a copy

    # sub-chunk tiles: 64 chunks × 1000 rows, tiles of 250 — every
    # row copied exactly once, and no emitted array aliases a chunk
    # (no partition pinning)
    st1 = SP.ScanStats()
    buf1 = _PendBuf(st1)
    for _ in range(64):
        buf1.append({"a": np.arange(1000), "b": np.ones(1000)})
    out_rows = 0
    while buf1.rows >= 250:
        t = buf1.take(250)
        assert t["a"].base is None  # owned copy, not a view
        out_rows += len(t["a"])
    assert out_rows == 64_000
    assert st1.copy_rows == 64_000 and st1.view_rows == 0

    # misaligned: tiles of 300 cross chunk boundaries — copies stay
    # LINEAR in the data (each row copied at most once), and the
    # emitted stream is exactly the concatenated input
    st2 = SP.ScanStats()
    buf2 = _PendBuf(st2)
    for c in range(16):
        buf2.append({"a": np.arange(c * 1000, (c + 1) * 1000)})
    got = []
    while buf2.rows > 0:
        take = min(300, buf2.rows)
        got.append(buf2.take(take)["a"])
    assert np.array_equal(np.concatenate(got), np.arange(16_000))
    assert st2.copy_rows + st2.view_rows == 16_000
    assert st2.copy_rows <= 16_000  # linear, never the n² tail recopy


def test_pendbuf_skip_is_cursor_only():
    st = SP.ScanStats()
    from cloudberry_tpu.exec.tiled import _PendBuf

    buf = _PendBuf(st)
    for c in range(8):
        buf.append({"a": np.arange(c * 100, (c + 1) * 100)})
    buf.skip(350)  # crosses 3.5 chunks: no take, no copy
    assert st.copy_rows == 0 and st.view_rows == 0
    assert buf.rows == 450
    assert np.array_equal(buf.take(50)["a"], np.arange(350, 400))


# ----------------------------------------------------------- fault arms


def test_scan_prefetch_seam_fires_and_recovers():
    s = _mk(budget=3 << 20)
    _load(s)
    exp = s.sql(AGG_Q).to_pandas()
    FI.inject_fault("scan_prefetch", "error", start_hit=2, end_hit=2)
    with pytest.raises(FI.InjectedFault):
        s.sql(AGG_Q).to_pandas()
    assert FI.list_faults()["armed"]["scan_prefetch"]["fired"] == 1
    assert _no_orphan_readers()
    FI.reset_fault()
    assert exp.equals(s.sql(AGG_Q).to_pandas())


def test_scan_decode_seam_fires_and_recovers(tmp_path):
    root = str(tmp_path / "store")
    s0 = _mk(**{"storage.root": root,
                "storage.rows_per_partition": 20_000})
    _load(s0)
    exp = s0.sql(AGG_Q).to_pandas()
    s = _mk(budget=3 << 20, **{"storage.root": root})
    FI.inject_fault("scan_decode", "error", start_hit=2, end_hit=2)
    with pytest.raises(FI.InjectedFault):
        s.sql(AGG_Q).to_pandas()
    assert FI.list_faults()["armed"]["scan_decode"]["fired"] == 1
    assert _no_orphan_readers()
    FI.reset_fault()
    assert exp.equals(s.sql(AGG_Q).to_pandas())


# --------------------------------------------------- accounting / tools


def test_queue_charge_rides_report_and_capacity():
    s = _mk(budget=3 << 20)
    _load(s)
    s.sql(AGG_Q)
    rep = s.last_tiled_report
    assert rep["est_pipeline_bytes"] > 0
    cfg = s.config.scan_pipeline
    # the charge is the documented model: prefetch_tiles × tile bytes
    assert rep["est_pipeline_bytes"] % cfg.prefetch_tiles == 0
    assert rep["est_pipeline_bytes"] // cfg.prefetch_tiles \
        >= rep["tile_rows"]  # ≥ 1 byte per row per staged tile
    s_off = _mk(budget=3 << 20, pipeline=False)
    _load(s_off)
    s_off.sql(AGG_Q)
    assert s_off.last_tiled_report["est_pipeline_bytes"] == 0
    # capacity plane: the tiled statement's observed bytes include the
    # staging charge (histogram count grew; exact value is the model's)
    h = s.stmt_log.registry.hist("stmt_device_bytes")
    assert h is not None and h["count"] >= 1


def test_explain_analyze_tiled_trailer_shows_pipeline():
    s = _mk(budget=3 << 20)
    _load(s)
    text = s.explain_analyze(AGG_Q)
    assert "scan pipeline:" in text
    assert "stall" in text


def test_stream_loader_self_consistent(tmp_path):
    """tools/tpchgen.py stream_load_tpch: key-range chunks append
    straight into micro-partitions without a whole-table DataFrame.
    The contract is self-consistency, not byte-equality with the
    in-RAM generator: row counts match the SF model, lineitems join
    their orders, and the engine's cold aggregate equals pandas over
    the SAME loaded data."""
    import pandas as pd

    from tools.tpchgen import stream_load_tpch

    s = _mk(**{"storage.root": str(tmp_path / "st")})
    counts = stream_load_tpch(s, sf=0.01, seed=7,
                              tables=["orders", "lineitem"],
                              chunk_rows=5_000)
    assert counts["orders"] == 15_000
    assert counts["lineitem"] >= counts["orders"]  # 1..7 lines/order

    li = s.sql("select l_orderkey, l_quantity, l_returnflag, "
               "l_linestatus from lineitem").to_pandas()
    ok = s.sql("select o_orderkey from orders").to_pandas()
    assert len(li) == counts["lineitem"]
    # FK closure: every lineitem belongs to a generated order
    assert set(li["l_orderkey"]).issubset(set(ok["o_orderkey"]))

    got = s.sql("select l_returnflag, l_linestatus, "
                "sum(l_quantity) as sq, count(*) as c from lineitem "
                "group by l_returnflag, l_linestatus "
                "order by l_returnflag, l_linestatus").to_pandas()
    exp = (li.groupby(["l_returnflag", "l_linestatus"], as_index=False)
           .agg(sq=("l_quantity", "sum"), c=("l_quantity", "size"))
           .sort_values(["l_returnflag", "l_linestatus"])
           .reset_index(drop=True))
    assert list(got["c"]) == list(exp["c"])
    assert np.allclose(np.asarray(got["sq"], dtype=np.float64),
                       np.asarray(exp["sq"], dtype=np.float64))


def test_serve_bench_coldscan_smoke():
    """serve_bench --mix coldscan CPU smoke: long cold tiled scans
    (store-backed li through the scan pipeline) compete with point
    lookups on one server; both classes complete and the CSV row is
    well-formed — the multi-tenant starvation-case workload."""
    import tools.serve_bench as SB

    r = SB.run_mode("direct", "coldscan", clients=2, duration_s=1.5,
                    rows=60_000, tick_s=0.002, max_batch=8)
    assert r["requests"] > 0
    assert r["mix"] == "coldscan"
    row = SB.csv_row(r)
    assert len(row.split(",")) == len(SB.CSV_HEADER.split(","))
    assert _no_orphan_readers()


def test_scan_bench_smoke(tmp_path):
    """tools/scan_bench.py CPU smoke: the A/B harness runs end-to-end
    at a toy scale and emits well-formed CSV rows + a speedup line."""
    import tools.scan_bench as sb

    rows = sb.run_ab(sf=0.01, root=str(tmp_path / "st"), reps=1,
                     budget=1 << 20)
    assert {"on", "off"} <= {r["mode"] for r in rows}
    on = next(r for r in rows if r["mode"] == "on")
    off = next(r for r in rows if r["mode"] == "off")
    assert on["rows"] == off["rows"] > 0
    assert on["checksum"] == off["checksum"]  # bit-identical A/B
    assert on["n_tiles"] > 1
    csv = sb.to_csv(rows)
    assert csv.splitlines()[0].startswith("sf,mode,")
    point = sb.ladder_point(0.01, root=str(tmp_path / "st2"),
                            budget=1 << 20)
    assert point["rows_per_s_chip"] > 0
    assert 0.0 <= point["overlap_frac"] <= 1.0
