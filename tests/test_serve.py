"""Serving layer (the tcop/libpq analog): concurrent clients against one
server process, admission control observed (VERDICT #10)."""

import threading

import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.serve import Client, Server
from cloudberry_tpu.serve.client import ServerError


@pytest.fixture
def server():
    s = cb.Session(Config().with_overrides(
        **{"resource.max_concurrency": 2}))
    srv = Server(session=s)
    with srv:
        yield srv


def test_basic_roundtrip(server):
    with Client(server.host, server.port) as c:
        assert c.sql("create table t (a int, b int) distributed by (a)") \
            ["status"].startswith("CREATE")
        c.sql("insert into t values (1, 10), (2, null)")
        out = c.sql("select a, b from t order by a")
        assert out["columns"] == ["a", "b"]
        assert out["rows"] == [[1, 10], [2, None]]
        assert out["rowcount"] == 2


def test_errors_do_not_kill_connection(server):
    with Client(server.host, server.port) as c:
        with pytest.raises(ServerError, match="unknown table"):
            c.sql("select * from nope")
        c.sql("create table ok (x int) distributed by (x)")
        assert c.sql("select count(*) as n from ok")["rows"] == [[0]]


def test_two_concurrent_clients_with_admission(server):
    with Client(server.host, server.port) as c:
        c.sql("create table big (a bigint, g bigint) distributed by (a)")
        c.sql("insert into big values " +
              ",".join(f"({i}, {i % 50})" for i in range(5000)))

    results = []
    errors = []

    def worker(i):
        try:
            with Client(server.host, server.port) as c:
                for k in range(3):
                    out = c.sql(f"select g, count(*) as n from big "
                                f"where a > {i * 10 + k} group by g "
                                f"order by g")
                    results.append(len(out["rows"]))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors
    assert results and all(n == 50 for n in results)
    gate = server.session._gate
    # admission control observed: every statement passed the gate and
    # occupancy never exceeded the slot pool
    assert gate.total_admitted >= 12
    assert gate.peak <= gate.max_concurrency


def test_concurrent_reads_actually_overlap():
    """With 2 slots, two blocking reads can hold the gate simultaneously
    (peak 2): the serving layer is concurrent, not serialized."""
    s = cb.Session(Config().with_overrides(
        **{"resource.max_concurrency": 2}))
    s.sql("create table t (a bigint) distributed by (a)")
    s.sql("insert into t values " +
          ",".join(f"({i})" for i in range(2000)))
    with Server(session=s) as srv:
        barrier = threading.Barrier(2)

        def worker(i):
            with Client(srv.host, srv.port) as c:
                barrier.wait(timeout=30)
                for k in range(5):
                    c.sql(f"select count(*) as n from t where a > {i + k}")

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    assert s._gate.peak <= 2


def test_wire_transactions_rejected(server):
    with Client(server.host, server.port) as c:
        with pytest.raises(ServerError, match="share one session"):
            c.sql("begin")


def test_server_over_durable_store(tmp_path):
    cfg = Config().with_overrides(
        **{"storage.root": str(tmp_path / "store")})
    with Server(config=cfg) as srv:
        with Client(srv.host, srv.port) as c:
            c.sql("create table d (x bigint) distributed by (x)")
            c.sql("insert into d values (1), (2), (3)")
    # server gone; data survives for a fresh engine on the same root
    s2 = cb.Session(cfg)
    assert s2.sql("select count(*) as n from d").to_pandas().n[0] == 3
