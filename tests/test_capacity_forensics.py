"""Capacity & forensics plane (ISSUE 12): per-statement memory
accounting, per-segment skew telemetry, live progress, and the
slow-statement flight recorder.

The contracts under test:
- every dispatched statement records a device-byte estimate (histogram
  + peak gauge), and ``meta "metrics"`` refreshes a gauge per
  engine-wide memory holder at read time;
- a constructed 30% hot-key shuffle trips ``skew_events`` with the
  ratio visible in meta metrics AND the EXPLAIN ANALYZE motion
  annotation (the acceptance shuffle);
- progress fractions are MONOTONE across device-loss resume — including
  the 8→7 degraded re-shard — and exactly 1.0 iff the statement
  succeeded;
- a deliberately slowed statement produces a flight bundle that
  tools/flight_replay.py re-executes bit-identically against the store;
- RecoveryStore checkpoint pins are bounded by bytes with visible
  evictions;
- serve_bench --slow-ms emits the flight/skew/peak CSV columns.
"""

import json

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config, get_config
from cloudberry_tpu.utils import faultinject as FI


@pytest.fixture(autouse=True)
def _clean_faults():
    FI.reset_fault()
    yield
    FI.reset_fault()


# ------------------------------------------------- capacity accounting


def test_stmt_device_bytes_recorded_fresh_and_cached():
    s = cb.Session()
    s.sql("create table cap_t (k bigint, v double)")
    s.catalog.table("cap_t").set_data({
        "k": np.arange(10_000, dtype=np.int64) % 64,
        "v": np.arange(10_000, dtype=np.float64)}, {})
    q = "select k, sum(v) as sv from cap_t group by k"
    s.sql(q)
    h = s.stmt_log.registry.hist("stmt_device_bytes")
    assert h is not None and h["count"] >= 1
    n0 = h["count"]
    s.sql(q)  # cached path: observes the cached admission cost
    h = s.stmt_log.registry.hist("stmt_device_bytes")
    assert h["count"] > n0
    peak = s.stmt_log.registry.snapshot()["gauges"][
        "stmt_device_bytes_peak"]
    assert peak > 0
    # fresh plans also itemize the floor no fusion removes
    assert s.stmt_log.registry.hist("stmt_live_bytes")["count"] >= 1


def test_plan_device_bytes_itemizes_wire_and_rungs():
    """Distributed plans carry motion wire buffers and redistribute
    rung capacity on top of the admission bound."""
    from cloudberry_tpu.obs.capacity import plan_device_bytes
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sql.parser import parse_sql

    cfg = Config(n_segments=8).with_overrides(
        **{"planner.broadcast_threshold": 0,
           "planner.runtime_filter_threshold": 0})
    s = cb.Session(cfg)
    s.sql("create table w1 (a bigint, key bigint) distributed by (a)")
    s.sql("create table w2 (b bigint, key bigint) distributed by (b)")
    s.catalog.table("w1").set_data(
        {"a": np.arange(1000), "key": np.arange(1000)})
    s.catalog.table("w2").set_data(
        {"b": np.arange(1000), "key": np.arange(1000)})
    plan = plan_statement(parse_sql(
        "select count(*) as c from w1, w2 where w1.key = w2.key"),
        s, {}).plan
    d = plan_device_bytes(plan, s)
    assert d["wire_bytes"] > 0, "motions must cost wire"
    assert d["rung_rows"] > 0, "redistributes must count rung capacity"
    assert d["peak_bytes"] > d["wire_bytes"]
    assert 0 < d["live_bytes"] <= d["peak_bytes"]


def test_memory_gauges_refresh_on_meta_metrics():
    from cloudberry_tpu.serve.meta import describe

    cfg = Config().with_overrides(
        **{"resource.query_mem_bytes": 1 << 20,
           "recovery.checkpoint_every": 2})
    s = cb.Session(cfg)
    s.sql("create table gt (k bigint, v double)")
    n = 200_000
    s.catalog.table("gt").set_data({
        "k": np.arange(n, dtype=np.int64) % 97,
        "v": np.arange(n, dtype=np.float64)}, {})
    s.sql("select k, sum(v) as sv from gt group by k")  # tiled
    snap = describe(s, "metrics")
    g = snap["gauges"]
    for name in ("mem_plan_cache_skeletons", "mem_rung_cache_entries",
                 "mem_join_index_entries", "mem_recovery_pins_bytes",
                 "mem_recovery_pins", "mem_trace_ring_entries",
                 "mem_flight_ring_entries", "mem_statement_rows",
                 "mem_stmt_cache_entries", "mem_store_scan_bytes"):
        assert name in g, f"missing memory gauge {name}"
    assert g["mem_statement_rows"] >= 1
    assert g["mem_stmt_cache_entries"] >= 1
    # tiled statements observe their step working set
    assert snap["histograms"]["stmt_device_bytes"]["count"] >= 1


# ------------------------------------------------------ skew telemetry


def _hot_key_session():
    cfg = Config(n_segments=8).with_overrides(
        **{"planner.broadcast_threshold": 0,
           "planner.runtime_filter_threshold": 0})
    s = cb.Session(cfg)
    s.sql("create table h1 (a bigint, key bigint) distributed by (a)")
    s.sql("create table h2 (b bigint, key bigint, w bigint) "
          "distributed by (b)")
    n = 2000
    # 30% of probe rows share ONE join key → the probe redistribute's
    # hot destination carries 0.30·n + 0.70·n/8 ≈ 0.3875·n rows vs a
    # n/8 mean: ratio ≈ 3.1, above the default 3.0 alarm
    s.catalog.table("h1").set_data({
        "a": np.arange(n),
        "key": np.where(np.arange(n) < int(0.3 * n), 0, np.arange(n))})
    s.catalog.table("h2").set_data({
        "b": np.arange(n), "key": np.arange(n), "w": np.arange(n)})
    return s, ("select sum(h2.w) as sw from h1, h2 "
               "where h1.key = h2.key")


def test_hot_key_shuffle_trips_skew_events():
    """The acceptance shuffle: 30% hot key at 8 segments crosses the
    default skew_ratio, visible in meta metrics and EXPLAIN ANALYZE."""
    from cloudberry_tpu.serve.meta import describe

    s, q = _hot_key_session()
    expect = int(np.where(np.arange(2000) < 600, 0,
                          np.arange(2000))[600:].sum())
    out = s.sql(q).to_pandas()
    assert int(out.sw[0]) == expect  # telemetry never changes answers
    assert s.stmt_log.counter("skew_events") >= 1
    snap = describe(s, "metrics")
    h = snap["histograms"]["motion_skew_ratio"]
    assert h["count"] >= 1 and h["p99"] >= 3.0
    assert "motion_seg_rows_max" in snap["histograms"]
    assert "motion_seg_wire_bytes_max" in snap["histograms"]
    text = s.explain_analyze(q)
    skew_lines = [ln for ln in text.splitlines()
                  if "skew=" in ln and "redistribute" in ln]
    assert skew_lines, text
    assert any("hot_seg_rows=" in ln for ln in skew_lines)
    ratios = [float(ln.split("skew=")[1].split()[0])
              for ln in skew_lines]
    assert max(ratios) >= 3.0, ratios


def test_even_shuffle_records_ratio_without_alarm():
    cfg = Config(n_segments=8).with_overrides(
        **{"planner.broadcast_threshold": 0,
           "planner.runtime_filter_threshold": 0})
    s = cb.Session(cfg)
    s.sql("create table e1 (a bigint, key bigint) distributed by (a)")
    s.sql("create table e2 (b bigint, key bigint) distributed by (b)")
    n = 4000
    s.catalog.table("e1").set_data(
        {"a": np.arange(n), "key": np.arange(n)})
    s.catalog.table("e2").set_data(
        {"b": np.arange(n), "key": np.arange(n)})
    s.sql("select count(*) as c from e1, e2 where e1.key = e2.key")
    h = s.stmt_log.registry.hist("motion_skew_ratio")
    assert h is not None and h["count"] >= 1
    assert s.stmt_log.counter("skew_events") == 0


def test_skew_threshold_configurable():
    s, q = _hot_key_session()
    s2, _ = _hot_key_session()
    s2.config = s2.config.with_overrides(**{"obs.skew_ratio": 50.0})
    s2.stmt_log.configure_obs(s2.config.obs)
    s2.sql(q)
    assert s2.stmt_log.counter("skew_events") == 0
    s.config = s.config.with_overrides(**{"obs.skew_ratio": 1.01})
    s.sql(q)
    assert s.stmt_log.counter("skew_events") >= 2  # both redistributes


# -------------------------------------------------------- live progress


DIST_Q = ("SELECT g, sum(v) AS sv, count(*) AS c "
          "FROM fact JOIN dim ON fact.d = dim.d "
          "GROUP BY g ORDER BY g")


def _mk_dist(nseg=8, budget=2 << 20, n=400_000, nd=500):
    ov = {"n_segments": nseg, "resource.query_mem_bytes": budget,
          "recovery.checkpoint_every": 2,
          "planner.broadcast_threshold": 0}
    s = cb.Session(get_config().with_overrides(**ov))
    rng = np.random.default_rng(3)
    s.sql("CREATE TABLE dim (d BIGINT, g BIGINT) DISTRIBUTED BY (g)")
    s.sql("CREATE TABLE fact (k BIGINT, d BIGINT, v BIGINT) "
          "DISTRIBUTED BY (k)")
    s.catalog.table("dim").set_data(
        {"d": np.arange(nd), "g": np.arange(nd) % 9})
    s.catalog.table("fact").set_data(
        {"k": np.arange(n) % 997,
         "d": rng.integers(0, nd, n),
         "v": rng.integers(0, 100, n)})
    return s


@pytest.fixture
def frac_spy(monkeypatch):
    """Record every fraction a Progress object reports, in order."""
    from cloudberry_tpu.obs import progress as OP

    fracs: list[float] = []
    orig = OP.Progress.update

    def spy(self, *a, **k):
        orig(self, *a, **k)
        fracs.append(self.fraction)

    monkeypatch.setattr(OP.Progress, "update", spy)
    return fracs


def test_progress_monotone_single_node_device_loss(frac_spy):
    cfg = Config().with_overrides(
        **{"resource.query_mem_bytes": 1 << 20,
           "recovery.checkpoint_every": 2})
    s = cb.Session(cfg)
    n = 200_000
    s.sql("create table pt (k bigint, v bigint)")
    s.catalog.table("pt").set_data({
        "k": np.arange(n, dtype=np.int64) % 97,
        "v": np.arange(n, dtype=np.int64)}, {})
    q = "select k, sum(v) as sv from pt group by k order by k"
    clean = s.sql(q).to_pandas()
    total = s.last_tiled_report["n_tiles"]
    assert total >= 4
    assert frac_spy and frac_spy[-1] > 0.9
    assert all(a <= b for a, b in zip(frac_spy, frac_spy[1:]))
    assert s.stmt_log.recent(1)[0]["progress"] == 1.0
    # kill mid-stream: the retry resumes and the fraction NEVER dips
    frac_spy.clear()
    k = max(total // 2, 2)
    FI.inject_fault("tile_device_lost", "error",
                    start_hit=k + 1, end_hit=k + 1)
    df = s.sql(q).to_pandas()
    assert clean.equals(df)
    assert all(a <= b for a, b in zip(frac_spy, frac_spy[1:])), \
        "progress fraction decreased across device-loss resume"
    assert s.stmt_log.recent(1)[0]["progress"] == 1.0


def test_progress_monotone_degraded_8_to_7(frac_spy):
    """The acceptance centerpiece: device loss + a probe reporting one
    device gone — the 8→7 degraded resume re-tiles and re-shards, and
    the reported fraction still never decreases; success is 1.0."""
    s = _mk_dist()
    clean = s.sql(DIST_Q).to_pandas()
    total = s.last_tiled_report["n_tiles"]
    assert total >= 4
    frac_spy.clear()
    k = max(total // 2, 2)
    FI.inject_fault("tile_device_lost", "error",
                    start_hit=k + 1, end_hit=k + 1)
    FI.inject_fault("probe_degraded", "skip")  # probe sees 7 devices
    df = s.sql(DIST_Q).to_pandas()
    assert s.config.n_segments == 7
    assert clean.equals(df)
    assert frac_spy, "tile loop fed no progress"
    assert all(a <= b for a, b in zip(frac_spy, frac_spy[1:])), \
        "progress fraction decreased across the degraded resume"
    assert s.stmt_log.recent(1)[0]["progress"] == 1.0


def test_progress_error_stays_below_one():
    cfg = Config().with_overrides(
        **{"resource.query_mem_bytes": 1 << 20,
           "health.retries": 0})
    s = cb.Session(cfg)
    n = 200_000
    s.sql("create table pe (k bigint, v bigint)")
    s.catalog.table("pe").set_data({
        "k": np.arange(n, dtype=np.int64) % 97,
        "v": np.arange(n, dtype=np.int64)}, {})
    FI.inject_fault("tile_device_lost", "error", start_hit=2)
    with pytest.raises(Exception):
        s.sql("select k, sum(v) as sv from pe group by k")
    entry = s.stmt_log.recent(1)[0]
    assert entry["status"] == "error"
    assert entry["progress"] < 1.0, \
        "a failed statement must never report completion"


def test_meta_progress_lists_active_statement():
    """meta "progress" shows a mid-flight statement's fraction (driven
    from a metrics hook that fires while the statement still runs is
    racy; instead poll from a thread during a tiled statement)."""
    import threading
    import time

    from cloudberry_tpu.serve.meta import describe

    cfg = Config().with_overrides(
        **{"resource.query_mem_bytes": 1 << 20})
    s = cb.Session(cfg)
    n = 400_000
    s.sql("create table mp (k bigint, v bigint)")
    s.catalog.table("mp").set_data({
        "k": np.arange(n, dtype=np.int64) % 97,
        "v": np.arange(n, dtype=np.int64)}, {})
    seen: list = []
    stop = threading.Event()

    def poll():
        while not stop.is_set():
            for row in s.stmt_log.progress_rows():
                if row.get("fraction"):
                    seen.append(row)
            time.sleep(0.002)

    t = threading.Thread(target=poll)
    t.start()
    try:
        s.sql("select k, sum(v) as sv from mp group by k")
    finally:
        stop.set()
        t.join()
    assert seen, "no live progress row observed mid-statement"
    row = seen[-1]
    assert {"id", "sql", "state", "elapsed_s", "fraction",
            "tiles_done", "tiles_total"} <= set(row)
    # idle engine: the verb answers an empty list, not an error
    assert describe(s, "progress") == {"statements": []}


# ------------------------------------------------------ flight recorder


def _slow_session(tmp_path, nseg=1):
    cfg = Config(n_segments=nseg).with_overrides(**{
        "storage.root": str(tmp_path / "store"),
        "obs.slow_ms": 0.01})  # everything is "slow": deterministic
    s = cb.Session(cfg)
    s.sql("create table ft (k bigint, v bigint) distributed by (k)")
    s.sql("insert into ft values " +
          ",".join(f"({i},{i * 3})" for i in range(500)))
    return s


def test_flight_bundle_contents_and_ring(tmp_path):
    s = _slow_session(tmp_path)
    q = "select k, sum(v) as sv from ft where k < 400 group by k order by k"
    s.sql(q)
    assert s.stmt_log.counter("flight_captures") >= 1
    b = s.stmt_log.flights(1)[0]
    assert b["reason"] == "slow" and b["status"] == "ok"
    assert b["replayable"] is True
    for key in ("sql", "wall_s", "config_epoch", "n_segments",
                "storage_root", "skeleton", "param_fingerprint",
                "counters", "plan", "device_bytes", "rungs",
                "cache_tier", "trace", "progress", "result"):
        assert key in b, f"bundle missing {key}"
    assert b["result"]["rows"] == 400
    assert len(b["result"]["sha256"]) == 64
    # the whole bundle must be JSON-safe (wire + file contract)
    json.dumps(b)
    # the ring stays bounded
    for i in range(40):
        s.sql(f"select k from ft where k = {i}")
    assert len(s.stmt_log.flights(100)) <= s.config.obs.flight_ring


def test_flight_error_capture(tmp_path):
    import time as _t

    s = _slow_session(tmp_path)
    with pytest.raises(Exception):
        s.sql("select nope from ft")
    b = s.stmt_log.flights(1)[0]
    assert b["reason"] == "error" and b["status"] == "error"
    assert "error" in b and "result" not in b
    # error-storm protection: a second error inside the spacing window
    # is skipped and counted, never built
    n = s.stmt_log.counter("flight_captures")
    s.stmt_log._flight_last_error = _t.monotonic()
    with pytest.raises(Exception):
        s.sql("select nope2 from ft")
    assert s.stmt_log.counter("flight_captures") == n
    assert s.stmt_log.counter("flight_capture_ratelimited") >= 1
    # lifecycle verdicts capture light bundles — no re-plan
    s.stmt_log._flight_last_error = 0.0
    with pytest.raises(Exception):
        s.sql("select count(*) as c from ft",
              _deadline=_t.monotonic() - 1.0)
    b = s.stmt_log.flights(1)[0]
    assert b["reason"] == "error"
    assert b.get("plan_skipped") and "plan" not in b


def test_flight_replay_bit_identical(tmp_path):
    """The acceptance contract: a captured bundle re-executes
    bit-identically via tools/flight_replay.py — as a library call on a
    FRESH session over the same store, and through the CLI."""
    from tools import flight_replay as FR

    s = _slow_session(tmp_path)
    q = "select k, sum(v) as sv from ft where k < 400 group by k order by k"
    s.sql(q)
    bundle = next(b for b in s.stmt_log.flights(10)
                  if b.get("replayable"))
    verdict = FR.replay(bundle)  # fresh session from the bundle's root
    assert verdict["ok"], verdict
    # CLI round trip over a meta "flight"-shaped document
    p = tmp_path / "flights.json"
    p.write_text(json.dumps({"flights": s.stmt_log.flights(10)}))
    assert FR.main([str(p)]) == 0
    # a store mutation breaks bit-identity — the replay must FAIL loudly
    s.sql("insert into ft values (7, 999999)")
    bad = FR.replay(bundle)
    assert not bad["ok"]


def test_flight_captures_batched_dispatch_path():
    """Batched statements finish in the dispatcher, not session.sql —
    the slow/error capture contract must hold there too."""
    from cloudberry_tpu.sched.dispatcher import Dispatcher

    cfg = Config().with_overrides(**{
        "sched.enabled": True, "obs.slow_ms": 0.01})
    s = cb.Session(cfg)
    s.sql("create table bd (k bigint, v bigint) distributed by (k)")
    s.catalog.table("bd").set_data({
        "k": np.arange(1000, dtype=np.int64),
        "v": np.arange(1000, dtype=np.int64) * 2}, {})
    d = Dispatcher(s).start()
    try:
        import threading

        # concurrent same-skeleton submits so at least one tick batches
        threads = [threading.Thread(
            target=lambda i=i: d.submit(
                f"select k, v from bd where k = {i}"))
            for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        d.stop()
    assert d.stats["batched_requests"] >= 2, "no batch formed"
    assert s.stmt_log.counter("flight_captures") >= 1
    assert any(b["status"] == "ok" for b in s.stmt_log.flights(32))


def test_flight_meta_verb_and_disable(tmp_path):
    from cloudberry_tpu.serve.meta import describe

    s = _slow_session(tmp_path)
    s.sql("select count(*) as c from ft")
    out = describe(s, "flight", 4)
    assert out["flights"] and out["flights"][0]["sql"]
    # slow_ms=0 disables capture wholesale
    s2 = cb.Session(Config().with_overrides(**{"obs.slow_ms": 0.0}))
    s2.sql("create table z (k bigint)")
    s2.sql("insert into z values (1)")
    s2.sql("select * from z")
    assert s2.stmt_log.counter("flight_captures") == 0


# ------------------------------------------- recovery store byte bound


def test_recovery_store_bounded_by_bytes():
    from cloudberry_tpu.exec.recovery import RecoveryStore, TileCheckpoint

    class _Log:
        def __init__(self):
            self.c = {}

        def bump(self, name, n=1):
            self.c[name] = self.c.get(name, 0) + n

    log = _Log()
    st = RecoveryStore(max_statements=8, max_bytes=1 << 20, log=log)

    def ck(nbytes):
        return TileCheckpoint(
            signature=("t",), mode="agg", nseg=1, tile_rows=1,
            tiles_done=1, consumed=0,
            payload={"cols": {"x": np.zeros(nbytes // 8,
                                            dtype=np.int64)},
                     "sel": np.zeros(0, dtype=bool)})

    for i in range(5):
        st.save(i, ck(400 << 10))  # 5 × 400 KiB into a 1 MiB budget
    assert st.pinned_bytes() <= 1 << 20
    assert st.pinned_count() == 2
    assert log.c["ckpt_evictions"] == 3
    # LRU: the survivors are the most recently saved
    assert st.load(4, ("t",)) is not None
    assert st.load(0, ("t",)) is None
    # a single over-budget snapshot is refused without evicting others
    # (own counter — nothing was evicted to make room), and an earlier
    # within-budget checkpoint of the SAME statement stays pinned
    before = st.pinned_count()
    st.save(99, ck(2 << 20))
    assert st.load(99, ("t",)) is None
    assert st.pinned_count() == before
    assert log.c["ckpt_evictions"] == 3  # unchanged
    assert log.c["ckpt_oversize_refused"] == 1
    st.save(4, ck(2 << 20))  # oversize UPDATE keeps the prior pin
    assert st.load(4, ("t",)) is not None
    # discard releases bytes
    st.discard(3)
    st.discard(4)
    assert st.pinned_bytes() == 0 and st.pinned_count() == 0


def test_recovery_eviction_costs_only_replay():
    """A checkpoint the byte budget refuses degrades to a fresh run —
    correct result, full replay, counted refusal. max_bytes=1 makes
    every snapshot oversize, so nothing ever pins."""
    cfg = Config().with_overrides(
        **{"resource.query_mem_bytes": 1 << 20,
           "recovery.checkpoint_every": 2,
           "recovery.max_bytes": 1})
    s = cb.Session(cfg)
    n = 200_000
    s.sql("create table rv (k bigint, v bigint)")
    s.catalog.table("rv").set_data({
        "k": np.arange(n, dtype=np.int64) % 97,
        "v": np.arange(n, dtype=np.int64)}, {})
    q = "select k, sum(v) as sv from rv group by k order by k"
    clean = s.sql(q).to_pandas()
    total = s.last_tiled_report["n_tiles"]
    assert s.stmt_log.counter("ckpt_oversize_refused") >= 1
    assert s._recovery.pinned_bytes() == 0
    k = max(total // 2, 2)
    FI.inject_fault("tile_device_lost", "error",
                    start_hit=k + 1, end_hit=k + 1)
    df = s.sql(q).to_pandas()
    assert clean.equals(df)
    assert s.last_tiled_report["resumed_from_tile"] == 0  # no snapshot


# ----------------------------------------------- serve_bench + lint


def test_serve_bench_slow_ms_columns():
    """CPU smoke (tier-1): --slow-ms arms the recorder and the new CSV
    columns ride every row."""
    from tools import serve_bench as SB

    out = SB.main(["--mode", "direct", "--mix", "point",
                   "--clients", "2", "--duration", "0.6",
                   "--rows", "2000", "--slow-ms", "0.01"])
    assert len(out) == 1
    row = out[0]
    for col in ("flight_captures", "skew_events", "peak_stmt_mb"):
        assert col in row, f"missing CSV column {col}"
        assert col in SB.CSV_HEADER
    assert row["flight_captures"] >= 1  # every point read is "slow"
    assert row["peak_stmt_mb"] > 0
    assert SB.csv_row(row)  # the row renders against the header


def test_lint_obs_gauge_home(tmp_path):
    import textwrap

    from cloudberry_tpu.lint import run_lint
    from cloudberry_tpu.lint.config import LintConfig

    root = tmp_path / "pkg"
    (root / "exec").mkdir(parents=True)
    (root / "exec" / "thing.py").write_text(textwrap.dedent("""
        def record(log, depth):
            log.registry.gauge("queue_depth", depth)
            log.registry.gauge_max("peak", depth)
    """))
    (root / "obs").mkdir()
    (root / "obs" / "cap.py").write_text(textwrap.dedent("""
        def refresh(reg):
            reg.gauge("ok_here", 1)
    """))
    result = run_lint([str(root)], LintConfig(exclude_files=frozenset()))
    hits = [f for f in result.unsuppressed if f.rule == "obs-gauge-home"]
    assert len(hits) == 2
    assert all(f.file.endswith("exec/thing.py") for f in hits)


def test_repo_gauge_home_clean():
    """The live tree passes its own contract (direct pin, so a pass
    regression cannot mask a drift)."""
    import os

    import cloudberry_tpu
    from cloudberry_tpu.lint import run_lint

    pkg = os.path.dirname(os.path.abspath(cloudberry_tpu.__file__))
    result = run_lint([pkg])
    assert not [f for f in result.unsuppressed
                if f.rule in ("obs-gauge-home",)]


def test_meta_progress_flight_verbs_documented():
    """The new verbs ride the obs-meta-verbs contract: documented AND
    implemented (the lint pass pins both ways on the live module)."""
    import os

    import cloudberry_tpu
    from cloudberry_tpu.lint import run_lint
    from cloudberry_tpu.serve.meta import describe

    assert "progress" in describe.__doc__ and "flight" in describe.__doc__
    pkg = os.path.dirname(os.path.abspath(cloudberry_tpu.__file__))
    result = run_lint([os.path.join(pkg, "serve", "meta.py")])
    assert not [f for f in result.unsuppressed
                if f.rule == "obs-meta-verbs"]


def test_obs_off_disables_the_plane():
    """config.obs.enabled=False: no progress objects, no capacity
    histograms, no flight captures — the A/B off side really is off."""
    s = cb.Session(Config().with_overrides(**{"obs.enabled": False}))
    s.sql("create table off_t (k bigint, v bigint)")
    s.catalog.table("off_t").set_data({
        "k": np.arange(5000, dtype=np.int64) % 16,
        "v": np.arange(5000, dtype=np.int64)}, {})
    s.sql("select k, sum(v) as sv from off_t group by k")
    reg = s.stmt_log.registry
    assert reg.hist("stmt_device_bytes") is None
    assert s.stmt_log.counter("flight_captures") == 0
    assert "progress" not in s.stmt_log.recent(1)[0]
