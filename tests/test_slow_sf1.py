"""Opt-in SF1-scale evidence (VERDICT round-2 item: "nothing in CI runs
above SF0.02").

Run with CBTPU_SLOW=1 (several minutes on the 8-virtual-device CPU mesh):

- SF1 distributed correctness for the join-heavy TPC-H subset (Q3, Q5,
  Q9, Q18) — 8 segments vs the single-segment oracle at 6M lineitem rows,
  exercising redistribute buckets, runtime filters, and two-stage aggs at
  realistic cardinalities.
- a skew test at >=1M rows that actually TRIPS the expansion-overflow
  check (a correlated join the NDV model underestimates) and recovers via
  the grow-and-retry discipline (session.growth_events > 0), with results
  matching a pandas oracle.
"""

import os

import numpy as np
import pandas as pd
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config

slow = pytest.mark.skipif(
    os.environ.get("CBTPU_SLOW", "") != "1",
    reason="SF1-scale suite: set CBTPU_SLOW=1 to run")


@pytest.fixture(scope="module")
def sf1():
    from tools.tpchgen import load_tpch

    oracle = cb.Session(get_config().with_overrides(n_segments=1))
    load_tpch(oracle, sf=1.0, seed=9)
    dist = cb.Session(get_config().with_overrides(n_segments=8))
    load_tpch(dist, sf=1.0, seed=9)
    return oracle, dist


@slow
@pytest.mark.parametrize("qn", ["q3", "q5", "q9", "q18"])
def test_sf1_distributed_matches_oracle(sf1, qn):
    from tools.tpch_queries import QUERIES

    oracle, dist = sf1
    want = oracle.sql(QUERIES[qn]).to_pandas()
    got = dist.sql(QUERIES[qn]).to_pandas()
    pd.testing.assert_frame_equal(want, got, check_exact=False, rtol=1e-9)


@slow
def test_skew_trips_and_recovers_expansion_overflow():
    """1.2M probe rows, 25% on one hot key, joined to a build side with 12
    copies of that key: true pairs ~3.9M vs the NDV estimate ~1.3M — the
    expansion check trips, grow_expansion quadruples the pair buffer, the
    retry succeeds, and the answer matches pandas."""
    rng = np.random.default_rng(13)
    n = 1_200_000
    probe_k = np.where(rng.random(n) < 0.25, 0,
                       rng.integers(1, 120_000, n)).astype(np.int64)
    probe_v = rng.integers(0, 1000, n).astype(np.int64)
    build_k = np.concatenate([np.zeros(12, dtype=np.int64),
                              np.arange(1, 120_000, dtype=np.int64)])
    build_v = np.arange(len(build_k), dtype=np.int64)

    for nseg in (1, 8):
        s = cb.Session(get_config().with_overrides(n_segments=nseg))
        s.sql("create table f (k bigint, v bigint) distributed by (k)")
        s.sql("create table d (k bigint, w bigint) distributed by (k)")
        s.catalog.table("f").set_data({"k": probe_k, "v": probe_v})
        s.catalog.table("d").set_data({"k": build_k, "w": build_v})
        df = s.sql("select sum(f.v + d.w) as s, count(*) as c "
                   "from f join d on f.k = d.k").to_pandas()
        pf = pd.DataFrame({"k": probe_k, "v": probe_v})
        pdim = pd.DataFrame({"k": build_k, "w": build_v})
        j = pf.merge(pdim, on="k")
        assert df["c"][0] == len(j)
        assert df["s"][0] == int((j["v"] + j["w"]).sum())
        assert s.growth_events > 0, \
            f"nseg={nseg}: expansion overflow never tripped — the skew " \
            "construction no longer exceeds the NDV pair estimate"
