"""Windowed in-flight tile dispatch (exec/tilepipe.py) — the async
tile-step pipeline over the tiled executors.

The contract under test: window on/off is BIT-IDENTICAL across every
tiled mode (agg/topn/sort/window, single-node and dist8) because the
window only moves WHEN the host learns of a tile's control scalars,
never what executes; a capacity overflow observed up to W tiles late
replays from the last drained-clean checkpoint and still converges to
the synchronous answer; device loss mid-window resumes with ≤ W+K
tiles replayed (in-flight tiles never count as progress); cancellation
mid-window dies promptly with no orphan threads and a clean rerun; the
``tile_enqueue``/``tile_drain`` fault seams fire and recover; and the
sentinel's per-tile stat fetch is skipped outright when feedback is
off (``tile_stat_syncs`` pins the no-host-sync claim both ways).
"""

import threading
import time

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu import lifecycle
from cloudberry_tpu.config import get_config
from cloudberry_tpu.exec import tilepipe as TP
from cloudberry_tpu.utils import faultinject as FI

AGG_Q = ("SELECT g, sum(v) AS sv, count(*) AS c "
         "FROM fact JOIN dim ON fact.k = dim.k GROUP BY g ORDER BY g")
TOPN_Q = ("SELECT fact.k AS k, v, g FROM fact JOIN dim ON fact.k = dim.k "
          "WHERE v < 90 ORDER BY v, fact.k, g LIMIT 25")
SORT_Q = ("SELECT g, v FROM fact JOIN dim ON fact.k = dim.k "
          "WHERE v < 50 ORDER BY g, v DESC, fact.k")
WIN_Q = ("SELECT g, v, rank() over (partition by g order by v desc) AS r,"
         " sum(v) over (partition by g) AS sv "
         "FROM fact JOIN dim ON fact.k = dim.k")


def _load(s, n_fact=120_000, n_dim=500, n_groups=9):
    rng = np.random.default_rng(3)
    s.sql("CREATE TABLE dim (k BIGINT, g BIGINT) DISTRIBUTED BY (k)")
    s.sql("CREATE TABLE fact (k BIGINT, v BIGINT) DISTRIBUTED BY (k)")
    s.catalog.table("dim").set_data(
        {"k": np.arange(n_dim), "g": np.arange(n_dim) % n_groups})
    s.catalog.table("fact").set_data(
        {"k": rng.integers(0, n_dim, n_fact),
         "v": rng.integers(0, 100, n_fact)})


def _mk(budget=None, window=None, nseg=1, **extra):
    ov = {"n_segments": nseg}
    if budget is not None:
        ov["resource.query_mem_bytes"] = budget
    if window is not None:
        ov["tile_pipeline.inflight_tiles"] = window
    ov.update(extra)
    return cb.Session(get_config().with_overrides(**ov))


@pytest.fixture(autouse=True)
def _clean_faults():
    FI.reset_fault()
    yield
    FI.reset_fault()


# ------------------------------------------------------ window semantics


def test_effective_window_defaults():
    """auto (inflight_tiles=0) is 1 on CPU — the legacy loop exactly —
    and the accelerator default elsewhere; explicit values clamp."""
    cfg = get_config()
    assert TP.effective_window(cfg, "cpu") == 1
    assert TP.effective_window(cfg, "tpu") == TP._AUTO_ACCEL_WINDOW
    cfg3 = cfg.with_overrides(**{"tile_pipeline.inflight_tiles": 3})
    assert TP.effective_window(cfg3, "cpu") == 3
    off = cfg.with_overrides(**{"tile_pipeline.enabled": False,
                                "tile_pipeline.inflight_tiles": 8})
    assert TP.effective_window(off, "tpu") == 1
    huge = cfg.with_overrides(**{"tile_pipeline.inflight_tiles": 10_000})
    assert TP.effective_window(huge, "cpu") == TP._MAX_WINDOW


def test_step_donation_shared_rule():
    assert TP.step_donation("cpu") == ()
    assert TP.step_donation("tpu") == (4,)
    assert TP.step_donation("gpu", argnum=2) == (2,)


def test_window_charge_zero_at_one():
    """window=1 charges nothing extra (existing capacity reports and
    their pinned tests are untouched on the CPU default); wider windows
    charge (W-1) in-flight tiles."""
    s = _mk(budget=3 << 20, window=1)
    _load(s)
    s.sql(AGG_Q)
    base = s.last_tiled_report["est_pipeline_bytes"]
    s4 = _mk(budget=3 << 20, window=4)
    _load(s4)
    s4.sql(AGG_Q)
    rep = s4.last_tiled_report
    assert rep["est_pipeline_bytes"] > base
    per_tile = (rep["est_pipeline_bytes"] - base) // 3
    assert per_tile > 0  # 3 extra in-flight tiles at W=4


# ------------------------------------------------- on/off bit-identity


@pytest.fixture(scope="module")
def expected():
    s = _mk()
    _load(s)
    return {q: s.sql(q).to_pandas() for q in (AGG_Q, TOPN_Q, SORT_Q,
                                              WIN_Q)}


@pytest.mark.parametrize("q,mode", [(AGG_Q, None), (TOPN_Q, "topn"),
                                    (SORT_Q, "sort"), (WIN_Q, "window")],
                         ids=["agg", "topn", "sort", "window"])
def test_window_bit_identical_single(expected, q, mode):
    got = {}
    for w in (1, 2, 4):
        s = _mk(budget=3 << 20, window=w)
        _load(s)
        got[w] = s.sql(q).to_pandas()
        rep = s.last_tiled_report
        assert rep["tiled"] and rep["n_tiles"] > 1
        if mode is not None:
            assert rep["mode"] == mode
        assert rep["tile_window"] == w
        assert 1 <= rep["inflight_depth"] <= w
        assert rep["drain_stall_s"] >= 0.0
        if w > 1:
            assert rep["inflight_depth"] > 1
    assert got[1].equals(got[2]) and got[1].equals(got[4])
    if mode != "window":  # window row order is sort-compared elsewhere
        assert expected[q].equals(got[1])


# per-mode dist8 shapes mirror test_scan_pipeline's matrix: the window
# path needs finer groups over more rows at the budget whose spill
# chunk capacity holds a partition
_DIST8 = [(AGG_Q, None, 1 << 20, 120_000, 9),
          (TOPN_Q, "topn", 1 << 20, 120_000, 9),
          (SORT_Q, "sort", 1 << 20, 120_000, 9),
          (WIN_Q, "window", 4 << 20, 240_000, 300)]


@pytest.mark.parametrize("q,mode,budget,n_fact,n_groups", _DIST8,
                         ids=["agg", "topn", "sort", "window"])
def test_window_bit_identical_dist8(q, mode, budget, n_fact, n_groups):
    got = {}
    for w in (1, 4):
        s = _mk(budget=budget, window=w, nseg=8)
        _load(s, n_fact=n_fact, n_groups=n_groups)
        got[w] = s.sql(q).to_pandas()
        rep = s.last_tiled_report
        assert rep["tiled"] and rep["n_tiles"] > 1
        assert rep["tile_window"] == w
    assert got[1].equals(got[4])


# ------------------------------------------- deferred overflow + replay


def test_deferred_overflow_replays_bit_identical():
    """A merge overflow whose check drains AFTER newer tiles were
    dispatched: the deferral is counted, the adaptive retry replays the
    window from the last drained-clean checkpoint, and the answer (and
    grown accumulator) match the synchronous run exactly."""
    def load(s):
        rng = np.random.default_rng(3)
        s.sql("CREATE TABLE fact (k BIGINT, v BIGINT) "
              "DISTRIBUTED BY (k)")
        s.catalog.table("fact").set_data(
            {"k": rng.integers(0, 10_000, 200_000),
             "v": rng.integers(0, 100, 200_000)})

    # expression group key: NDV unknown -> sqrt estimate, true count 7k
    q = ("SELECT k % 7000 AS kk, count(*) AS c, sum(v) AS sv "
         "FROM fact GROUP BY k % 7000 ORDER BY kk LIMIT 50")
    res = {}
    for w in (1, 4):
        s = _mk(budget=4 << 20, window=w)
        load(s)
        res[w] = s.sql(q).to_pandas()
        log = s.stmt_log
        if w == 1:
            assert log.counter("tile_deferred_overflows") == 0
            assert log.counter("tile_window_replays") == 0
        else:
            assert log.counter("tile_deferred_overflows") >= 1
            assert log.counter("tile_window_replays") >= 1
        assert s.last_tiled_report["acc_capacity"] >= 7000
    assert res[1].equals(res[4])


# --------------------------------------------------- mid-window resume


def test_device_loss_mid_window_replays_at_most_w_plus_k():
    """Device loss with a full window in flight: resume from the last
    drained-clean checkpoint replays ≤ W+K tiles (in-flight launches
    never counted as progress), bit-identical."""
    W, K = 4, 2
    s0 = _mk(budget=1 << 20)
    _load(s0)
    exp = s0.sql(AGG_Q).to_pandas()
    total = s0.last_tiled_report["n_tiles"]
    assert total >= 6

    s = _mk(budget=1 << 20, window=W,
            **{"recovery.checkpoint_every": K,
               "health.retries": 2, "health.backoff_s": 0.01})
    _load(s)
    FI.inject_fault("tile_device_lost", "error", start_hit=6, end_hit=6)
    b = s.stmt_log.counter("tiles_replayed")
    got = s.sql(AGG_Q).to_pandas()
    FI.reset_fault()
    assert exp.equals(got)
    rep = s.last_tiled_report
    assert rep["resumed_from_tile"] >= 1
    assert s.stmt_log.counter("tiles_replayed") - b <= W + K


def test_degraded_8_to_7_resume_with_open_window():
    """The PR-6 acceptance centerpiece with a non-empty dispatch
    window: device loss mid-stream + a probe reporting one device gone
    resumes on the SEVEN survivors from the drained checkpoint,
    bit-identical to the clean 8-segment run."""
    s = _mk(nseg=8, budget=2 << 20, window=4,
            **{"planner.broadcast_threshold": 0,
               "recovery.checkpoint_every": 2,
               "health.retries": 2, "health.backoff_s": 0.01})
    rng = np.random.default_rng(3)
    s.sql("CREATE TABLE dim (d BIGINT, g BIGINT) DISTRIBUTED BY (g)")
    s.sql("CREATE TABLE fact (k BIGINT, d BIGINT, v BIGINT) "
          "DISTRIBUTED BY (k)")
    s.catalog.table("dim").set_data(
        {"d": np.arange(500), "g": np.arange(500) % 9})
    n = 400_000
    s.catalog.table("fact").set_data(
        {"k": np.arange(n) % 997,
         "d": rng.integers(0, 500, n),
         "v": rng.integers(0, 100, n)})
    q = ("SELECT g, sum(v) AS sv, count(*) AS c "
         "FROM fact JOIN dim ON fact.d = dim.d GROUP BY g ORDER BY g")
    clean = s.sql(q).to_pandas()
    total = s.last_tiled_report["n_tiles"]
    k = max(total // 2, 2)
    FI.inject_fault("probe_degraded", "skip")  # probe sees 7 devices
    FI.inject_fault("tile_device_lost", "error",
                    start_hit=k + 1, end_hit=k + 1)
    got = s.sql(q).to_pandas()
    FI.reset_fault()
    assert s.config.n_segments == 7
    assert clean.equals(got)
    rep = s.last_tiled_report
    assert rep["n_segments"] == 7 and rep["resumed_from_tile"] > 0


# -------------------------------------------------------- cancellation


def test_cancel_mid_window_no_orphan_inflight():
    """Cancel lands while a full window is in flight (the consumer is
    slowed by a tile_step sleep): the statement dies with
    StatementCancelled within the W-tile drain bound, no stray threads
    survive, and a rerun on the same session is bit-identical."""
    expect_s = _mk(budget=1 << 20)
    _load(expect_s)
    expect = expect_s.sql(AGG_Q).to_pandas()

    s = _mk(budget=1 << 20, window=4)
    _load(s)
    FI.inject_fault("tile_step", "sleep", sleep_s=0.05)
    errs = []

    def bg():
        try:
            s.sql(AGG_Q)
        except BaseException as e:  # noqa: BLE001 — assertion target
            errs.append(e)

    th = threading.Thread(target=bg)
    th.start()
    act = None
    for _ in range(500):
        act = s.stmt_log.activity()
        if act:
            break
        time.sleep(0.01)
    assert act, "statement never appeared in the activity view"
    time.sleep(0.25)  # let the window fill behind the slow steps
    assert s.stmt_log.cancel(act[0]["id"])
    th.join(timeout=60)
    assert errs and isinstance(errs[0], lifecycle.StatementCancelled)
    # abandoned in-flight launches leave no threads behind (JAX's async
    # dispatch completes into garbage-collected buffers)
    assert not any(t.name.startswith("cbtpu-")
                   and t.is_alive() for t in threading.enumerate())

    FI.reset_fault()
    got = s.sql(AGG_Q).to_pandas()
    assert expect.equals(got)


# --------------------------------------------------------- fault seams


def test_enqueue_drain_seams_fire_and_recover():
    """The new dispatch seams are live: an error on either raises out
    of the statement (counted by the registry), a sleep on tile_drain
    lands in drain_stall_s, and a reset rerun is bit-identical."""
    s = _mk(budget=3 << 20, window=4)
    _load(s)
    exp = s.sql(AGG_Q).to_pandas()

    for seam in ("tile_enqueue", "tile_drain"):
        FI.inject_fault(seam, "error", start_hit=2, end_hit=2)
        with pytest.raises(Exception) as ei:
            s.sql(AGG_Q)
        assert seam in str(ei.value)
        FI.reset_fault()
        assert exp.equals(s.sql(AGG_Q).to_pandas())
    assert {"tile_enqueue", "tile_drain"} <= set(FI.known_fault_points())

    FI.inject_fault("tile_drain", "sleep", sleep_s=0.02)
    assert exp.equals(s.sql(AGG_Q).to_pandas())
    FI.reset_fault()
    assert s.last_tiled_report["drain_stall_s"] >= 0.02


# ------------------------------------------- no-host-sync stat fetches


def test_stat_sync_skipped_when_feedback_off():
    """Satellite pin for the removed per-tile host sync: with feedback
    disabled the sentinel's srows never leave the device (zero
    tile_stat_syncs); enabled, the drains fold them as before."""
    def load(s):
        rng = np.random.default_rng(3)
        s.sql("CREATE TABLE dim (d BIGINT, g BIGINT) DISTRIBUTED BY (g)")
        s.sql("CREATE TABLE fact (k BIGINT, d BIGINT, v BIGINT) "
              "DISTRIBUTED BY (k)")
        s.catalog.table("dim").set_data(
            {"d": np.arange(500), "g": np.arange(500) % 9})
        n = 200_000
        s.catalog.table("fact").set_data(
            {"k": np.arange(n) % 997,
             "d": rng.integers(0, 500, n),
             "v": rng.integers(0, 100, n)})

    q = ("SELECT g, sum(v) AS sv FROM fact JOIN dim ON fact.d = dim.d "
         "GROUP BY g ORDER BY g")
    res = {}
    for fb in (False, True):
        s = _mk(budget=2 << 20, window=2, nseg=8,
                **{"planner.broadcast_threshold": 0,
                   "feedback.enabled": fb})
        load(s)
        res[fb] = s.sql(q).to_pandas()
        assert s.last_tiled_report["n_tiles"] > 1
        syncs = s.stmt_log.counter("tile_stat_syncs")
        if fb:
            assert syncs > 0
        else:
            assert syncs == 0
    assert res[False].equals(res[True])


# ----------------------------------------------------- trailer / gauge


def test_explain_analyze_dispatch_trailer():
    """EXPLAIN ANALYZE's tiled trailer grows a dispatch line only when
    a window was open — window=1 keeps the legacy trailer exactly."""
    for w, present in ((1, False), (4, True)):
        s = _mk(budget=1 << 20, window=w)
        s.sql("create table big (k bigint, v double)")
        n = 200_000
        s.catalog.table("big").set_data({
            "k": np.arange(n, dtype=np.int64) % 97,
            "v": np.arange(n, dtype=np.float64)}, {})
        text = s.explain_analyze(
            "select k, sum(v) as sv from big group by k")
        assert "Tiled execution" in text, text
        assert ("tile dispatch: window" in text) is present, text
        if present:
            assert f"window {w}" in text
            g = s.stmt_log.registry.snapshot()["gauges"]
            assert g.get("tile_inflight", 0) > 1
