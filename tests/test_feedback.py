"""Feedback-driven re-optimization (plan/feedback.py) — ISSUE 17.

The contract under test: motion telemetry from one execution folds into
per-(table, key-set) sketches that (1) persist across sessions on
store-backed scopes, (2) invalidate by construction on DML / config /
topology token movement, (3) seed capacity rungs so the SECOND execution
of a mis-estimated statement beats the first by at least one capacity
rung — fewer recompiles on under-estimates, less padded wire on
over-estimates — and (4) replan a tiled statement MID-STREAM through
the checkpoint store when per-tile skew crosses the alarm, with results
bit-identical to the in-memory run and every adapted plan verified.
"""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config
from cloudberry_tpu.plan import feedback as FB
from cloudberry_tpu.utils import faultinject as FI

JOIN_GROUP_Q = ("SELECT g, sum(v) AS sv, count(*) AS c "
                "FROM fact JOIN dim ON fact.d = dim.d "
                "GROUP BY g ORDER BY g")

# selective probe filter: ~2% of rows survive, while the planner's
# static selectivity guess prices the redistribute at a far higher rung
FILTERED_Q = ("SELECT g, sum(v) AS sv, count(*) AS c "
              "FROM fact JOIN dim ON fact.d = dim.d "
              "WHERE fact.v < 2 GROUP BY g ORDER BY g")

AGG_Q = "SELECT g, sum(v) AS sv, count(*) AS c FROM fact GROUP BY g ORDER BY g"


@pytest.fixture(autouse=True)
def _clean_faults():
    FI.reset_fault()
    yield
    FI.reset_fault()


def _mk(budget=None, **extra):
    ov = {"n_segments": 8,
          # keep the small dim out of broadcast so the probe redistributes
          "planner.broadcast_threshold": 0}
    if budget is not None:
        ov["resource.query_mem_bytes"] = budget
    ov.update(extra)
    return cb.Session(get_config().with_overrides(**ov))


def _load(session, n_fact=120_000, n_dim=500, seed=3,
          hot_key=None, hot_frac=0.0):
    """fact JOIN dim on d, dim distributed on g != d so the probe side
    redistributes. hot_key/hot_frac mis-state the d distribution."""
    rng = np.random.default_rng(seed)
    session.sql("CREATE TABLE dim (d BIGINT, g BIGINT) DISTRIBUTED BY (g)")
    session.sql("CREATE TABLE fact (k BIGINT, d BIGINT, v BIGINT) "
                "DISTRIBUTED BY (k)")
    session.catalog.table("dim").set_data(
        {"d": np.arange(n_dim), "g": np.arange(n_dim) % 9})
    d = rng.integers(0, n_dim, n_fact)
    if hot_key is not None:
        d[rng.random(n_fact) < hot_frac] = hot_key
    session.catalog.table("fact").set_data(
        {"k": np.arange(n_fact) % 997, "d": d,
         "v": rng.integers(0, 100, n_fact)})


def _plan(session, q):
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sql.parser import parse_sql

    return plan_statement(parse_sql(q), session, {},
                          explain_only=True).plan


def _redists(plan):
    """src -> PMotion for every learnable redistribute in the plan."""
    from cloudberry_tpu.exec.executor import all_nodes
    from cloudberry_tpu.plan import nodes as N

    out = {}
    for n in all_nodes(plan):
        if isinstance(n, N.PMotion) and n.kind == "redistribute":
            src = FB.resolve_sources(n.child, n.hash_keys)
            if src is not None:
                out[src] = n
    return out


# -------------------------------------------------------- fold + lookup


def test_fold_from_execution_populates_store():
    s = _mk()
    _load(s, n_fact=60_000)
    s.sql(JOIN_GROUP_Q)
    store = FB.store_for(s)
    snap = store.snapshot()
    assert snap["sketches"] >= 1 and snap["folds"] >= 1
    assert s.stmt_log.counter("feedback_folds") >= 1
    # the probe-side shuffle's sketch is live and carries real telemetry
    srcs = _redists(_plan(s, JOIN_GROUP_Q))
    assert srcs, "join plan lost its learnable redistribute"
    sk = next(filter(None, (store.lookup(s, "redist", src)
                            for src in srcs)), None)
    assert sk is not None
    assert sk.demand_max > 0 and sk.rows_total > 0
    assert sk.statements >= 1


def test_steady_state_folds_do_not_churn_gen():
    """Re-executions that reproduce their stats must not bump the store
    generation — cached statements stay warm (no recompile churn)."""
    s = _mk()
    _load(s, n_fact=60_000)
    s.sql(JOIN_GROUP_Q)                       # learn (material: new sketch)
    s.sql(JOIN_GROUP_Q)                       # replan under the sketch
    store = FB.store_for(s)
    gen2, folds2 = store.gen, store.folds
    compiles2 = s.stmt_log.counter("compiles")
    s.sql(JOIN_GROUP_Q)                       # steady state
    assert store.folds > folds2               # still learning...
    assert store.gen == gen2                  # ...without churning the cache
    assert s.stmt_log.counter("compiles") == compiles2


# ------------------------------------------------ persistence + tokens


def test_sketch_persistence_round_trip(tmp_path):
    root = str(tmp_path / "store")
    w = cb.Session(get_config().with_overrides(**{
        "n_segments": 8, "storage.root": root}))
    rng = np.random.default_rng(5)
    w.sql("CREATE TABLE fact (k BIGINT, g BIGINT, v BIGINT) "
          "DISTRIBUTED BY (k)")
    w.catalog.table("fact").set_data({
        "k": np.arange(60_000, dtype=np.int64) % 997,
        "g": rng.integers(0, 9, 60_000).astype(np.int64),
        "v": rng.integers(0, 100, 60_000).astype(np.int64)})
    w.sql(AGG_Q)                      # two-stage agg: merge redistribute on g
    assert (tmp_path / "store" / "_FEEDBACK.json").exists()

    # a FRESH store over the same root re-loads the sketch, and its
    # validity tokens still match a fresh session's view of the tables
    s2 = cb.Session(get_config().with_overrides(**{
        "n_segments": 8, "storage.root": root}))
    st = FB.FeedbackStore(str(tmp_path / "store" / "_FEEDBACK.json"))
    srcs = _redists(_plan(s2, AGG_Q))
    assert srcs
    sk = next(filter(None, (st.lookup(s2, "redist", src)
                            for src in srcs)), None)
    assert sk is not None and sk.demand_max > 0

    # config swaps that change what the observation MEANS invalidate:
    # same root, different capacity factor -> every lookup misses
    s3 = cb.Session(get_config().with_overrides(**{
        "n_segments": 8, "storage.root": root,
        "interconnect.capacity_factor": 9.5}))
    st2 = FB.FeedbackStore(str(tmp_path / "store" / "_FEEDBACK.json"))
    assert all(st2.lookup(s3, "redist", src) is None for src in srcs)


def test_invalidation_on_dml_and_topology(monkeypatch):
    s = _mk()
    _load(s, n_fact=60_000)
    s.sql(JOIN_GROUP_Q)
    store = FB.store_for(s)
    srcs = list(_redists(_plan(s, JOIN_GROUP_Q)))
    live = [src for src in srcs
            if store.lookup(s, "redist", src) is not None]
    assert live

    # topology epoch flip: every sketch folded under the old epoch drops
    from cloudberry_tpu.sched import sharedcache as SC
    real = SC.topology_token
    monkeypatch.setattr(SC, "topology_token", lambda sess: ("epoch", -1))
    assert all(store.lookup(s, "redist", src) is None for src in srcs)
    monkeypatch.setattr(SC, "topology_token", real)

    # sketches re-learn (the rung-program cache hit must not drop the
    # telemetry), then a DML version bump invalidates — scoped to the
    # written table: dim's sketches survive a write to fact
    s.sql(JOIN_GROUP_Q)
    assert any(store.lookup(s, "redist", src) is not None for src in live)
    t = s.catalog.table("fact")
    t.set_data({c: t.to_pandas()[c].to_numpy() for c in ("k", "d", "v")})
    fact_srcs = [src for src in live
                 if any(tab == "fact" for tab, _ in src)]
    dim_srcs = [src for src in live
                if all(tab == "dim" for tab, _ in src)]
    assert fact_srcs and dim_srcs
    assert all(store.lookup(s, "redist", src) is None for src in fact_srcs)
    assert any(store.lookup(s, "redist", src) is not None
               for src in dim_srcs)


def test_planck_mutation_class_registered():
    """The mutation fuzzer carries a forged-feedback-rung class; the
    planverify suite executes it — pin the registration here."""
    from cloudberry_tpu.plan.mutate import MUTATIONS

    _, _, expected = MUTATIONS["feedback-rung-forged"]
    assert "motion-rung-feedback-forged" in expected


# ------------------------------------------- acceptance: second execution


def test_second_execution_downgrades_rung_and_wire():
    """Over-stated demand (selective filter the static estimate misses):
    run 2 plans the probe redistribute at least one capacity rung BELOW
    run 1's, with strictly less padded wire — and every feedback-seeded
    plan passes the planck verifier (debug.verify_plans on)."""
    from cloudberry_tpu.obs import capacity as CAP

    s = _mk(**{"debug.verify_plans": True})
    _load(s)
    p1 = _plan(s, FILTERED_Q)
    b1 = CAP.plan_device_bytes(p1, s)
    got1 = s.sql(FILTERED_Q).to_pandas()

    p2 = _plan(s, FILTERED_Q)
    b2 = CAP.plan_device_bytes(p2, s)
    assert s.stmt_log.counter("feedback_seeded") >= 1
    assert s.stmt_log.counter("rung_downgrades") >= 1
    assert b2["wire_bytes"] < b1["wire_bytes"]

    # the seeded motion sits >= one pow2 rung under its static rung
    r1, r2 = _redists(p1), _redists(p2)
    seeded = {src: m for src, m in r2.items()
              if getattr(m, "_feedback_seed", None) is not None}
    assert seeded
    assert any(2 * m.bucket_cap <= r1[src].bucket_cap
               for src, m in seeded.items() if src in r1)
    assert "feedback:" in s.explain(FILTERED_Q)

    got2 = s.sql(FILTERED_Q).to_pandas()
    assert got1.equals(got2)


def test_second_execution_upgrade_saves_recompiles():
    """Under-stated skew (a projection hides the base scan from the
    exact bucket sizer and a hot key blows through the fair-share
    estimate — the PR-8 promotion workload): run 1 pays the overflow
    grow-and-retry recompile; run 2 seeds the rung at observed demand
    and compiles strictly fewer programs."""
    s = _mk(**{"planner.runtime_filter_threshold": 0})
    s.sql("CREATE TABLE j1 (a BIGINT, key BIGINT) DISTRIBUTED BY (a)")
    s.sql("CREATE TABLE j2 (b BIGINT, key BIGINT, w BIGINT) "
          "DISTRIBUTED BY (b)")
    n = 2000
    s.catalog.table("j1").set_data({
        "a": np.arange(n, dtype=np.int64),
        "key": np.where(np.arange(n) < 1500, 0, np.arange(n))})
    s.catalog.table("j2").set_data({
        "b": np.arange(n, dtype=np.int64),
        "key": np.arange(n, dtype=np.int64),
        "w": np.arange(n, dtype=np.int64)})
    q = ("SELECT sum(j2.w) AS sw FROM (SELECT key AS kk FROM j1) x "
         "JOIN j2 ON kk = j2.key")

    c0 = s.stmt_log.counter("compiles")
    got1 = s.sql(q).to_pandas()
    c1 = s.stmt_log.counter("compiles")
    assert s.growth_events >= 1, "run 1 should have overflowed the rung"
    assert c1 - c0 >= 2, "run 1 should have paid an overflow recompile"

    grown = s.growth_events
    got2 = s.sql(q).to_pandas()
    c2 = s.stmt_log.counter("compiles")
    assert got1.equals(got2)
    assert c2 - c1 < c1 - c0            # fewer recompiles than run 1
    assert s.growth_events == grown     # and no overflow at all
    assert s.stmt_log.counter("rung_upgrades") >= 1


# --------------------------------------- acceptance: mid-statement replan


@pytest.fixture(scope="module")
def adaptive_expected():
    s = _mk()
    _load(s, n_fact=400_000, hot_key=7, hot_frac=0.85)
    return s.sql(JOIN_GROUP_Q).to_pandas()


def test_midstatement_adaptive_replan(adaptive_expected):
    """A tiled-dist statement whose cumulative redistribute skew crosses
    the alarm checkpoints, replans through the memo with the partial
    sketch, and resumes — bit-identical to the in-memory run, with the
    adapted plan planck-verified (debug.verify_plans on)."""
    s = _mk(budget=2 << 20, **{"debug.verify_plans": True})
    _load(s, n_fact=400_000, hot_key=7, hot_frac=0.85)
    got = s.sql(JOIN_GROUP_Q).to_pandas()
    assert adaptive_expected.equals(got)

    assert s.stmt_log.counter("tile_replans") == 1
    assert s.stmt_log.counter("adaptive_replans") == 1
    assert s.stmt_log.counter("tile_checkpoints") >= 1
    assert s.stmt_log.counter("tile_resumes") >= 1
    assert s.stmt_log.counter("feedback_folds") >= 2   # partial + final
    rep = s.last_tiled_report
    assert rep["tiled"] and rep["distributed"] and rep["n_tiles"] > 1


def test_fault_skip_suppresses_adaptation(adaptive_expected):
    """Chaos arm: a skipped tile_replan fault point disarms adaptation
    for the statement — the static plan finishes, results unchanged."""
    FI.inject_fault("tile_replan", action="skip")
    s = _mk(budget=2 << 20)
    _load(s, n_fact=400_000, hot_key=7, hot_frac=0.85)
    got = s.sql(JOIN_GROUP_Q).to_pandas()
    assert adaptive_expected.equals(got)
    assert s.stmt_log.counter("tile_replans") == 0
    assert s.stmt_log.counter("adaptive_replans") == 0


# ---------------------------------------------------------- bench surface


def test_bench_surfaces_adaptive_counters():
    import bench
    from tools import serve_bench as SB

    header = SB.CSV_HEADER.split(",")
    assert "adaptive_replans" in header and "rung_downgrades" in header
    assert callable(bench.adaptive_context)
    assert "feedback_fold" in FI.INVENTORY and "tile_replan" in FI.INVENTORY
