"""Auxiliary subsystems: instrumentation/metrics, resource governance,
health probing, fault injection (SURVEY §5 analogs)."""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.exec.resource import (AdmissionGate, ResourceError,
                                          estimate_plan_memory)
from cloudberry_tpu.parallel import health
from cloudberry_tpu.utils import faultinject as FI


@pytest.fixture
def sess():
    s = cb.Session()
    s.sql("create table t (k bigint, v decimal(10,2)) distributed by (k)")
    s.sql("insert into t values " + ",".join(f"({i}, {i}.5)" for i in range(50)))
    return s


def test_explain_analyze_rows(sess):
    text = sess.explain_analyze(
        "select k, sum(v) as s from t where k < 25 group by k order by s")
    assert "rows=" in text and "Execution time" in text
    # the filter output must show 25 rows
    assert any("Filter" in line and "rows=25" in line
               for line in text.splitlines()), text


def test_metrics_hook(sess):
    got = []
    sess.metrics_hooks.append(got.append)
    sess.explain_analyze("select count(*) as n from t")
    assert len(got) == 1
    m = got[0]
    assert m.rows_out == 1
    assert m.wall_s >= 0 and m.compile_s > 0
    assert any(r == 50 for _, _, r in m.node_rows)  # the scan


def test_explain_analyze_distributed():
    s = cb.Session(Config(n_segments=8))
    s.sql("create table d (k bigint) distributed by (k)")
    s.sql("insert into d values " + ",".join(f"({i})" for i in range(64)))
    text = s.explain_analyze("select count(*) as n from d")
    # the scan counts must sum across segments to 64
    assert any("Scan" in line and "rows=64" in line
               for line in text.splitlines()), text


def test_memory_estimate_and_admission(sess):
    from cloudberry_tpu.plan.binder import Binder
    from cloudberry_tpu.sql.parser import parse_sql

    plan = Binder(sess.catalog).bind_select(parse_sql("select k from t"))
    est = estimate_plan_memory(plan)
    assert est.peak_bytes > 0
    assert len(est.per_node) >= 2

    tiny = cb.Session(cb.Config().with_overrides(
        **{"resource.query_mem_bytes": 16}))
    tiny.sql("create table big (x bigint)")
    tiny.sql("insert into big values (1),(2),(3)")
    with pytest.raises(ResourceError):
        tiny.sql("select x from big")


def test_admission_gate_slots():
    gate = AdmissionGate(2)
    with gate:
        with gate:
            pass  # two concurrent slots fine
    import threading

    g1 = AdmissionGate(1)
    order = []
    with g1:
        t = threading.Thread(target=lambda: (g1.__enter__(),
                                             order.append("in"),
                                             g1.__exit__(None, None, None)))
        t.start()
        import time
        time.sleep(0.05)
        assert order == []  # blocked while slot held
    t.join()
    assert order == ["in"]


def test_health_probe():
    r = health.probe()
    assert r.ok and r.n_devices >= 1
    mon = health.HealthMonitor(interval_s=3600)
    out = mon.probe_now()
    assert out.ok and len(mon.history) == 1


def test_run_with_retry():
    calls = []

    class FakeXlaRuntimeError(RuntimeError):
        pass

    FakeXlaRuntimeError.__name__ = "XlaRuntimeError"

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise FakeXlaRuntimeError("device lost")
        return "ok"

    assert health.run_with_retry(flaky, retries=2, backoff_s=0.01) == "ok"
    assert len(calls) == 2

    def always_value_error():
        raise ValueError("not retriable")

    with pytest.raises(ValueError):
        health.run_with_retry(always_value_error, retries=3, backoff_s=0.01)


def test_fault_injection_error_and_hits(sess):
    FI.reset_fault()
    FI.inject_fault("dispatch_start", "error", start_hit=2)
    try:
        sess.sql("select k from t where k = 1")  # hit 1: passes
        with pytest.raises(FI.InjectedFault):
            sess.sql("select k from t where k = 2")  # hit 2: fires
    finally:
        FI.reset_fault()
    # after reset, clean
    assert len(sess.sql("select k from t where k = 1").to_pandas()) == 1


def test_fault_injection_storage_crash_window(tmp_path):
    """Crash between manifest write and CURRENT swap must leave the previous
    snapshot committed (the crash-safety contract)."""
    from cloudberry_tpu.storage.table_store import TableStore
    from cloudberry_tpu.types import Schema
    from cloudberry_tpu import types as T

    store = TableStore(str(tmp_path))
    schema = Schema.of(x=T.INT64)
    store.append("t", {"x": np.arange(10, dtype=np.int64)}, schema)
    FI.reset_fault()
    FI.inject_fault("storage_commit_before_current", "skip")
    try:
        store.append("t", {"x": np.arange(99, dtype=np.int64)}, schema)
    finally:
        FI.reset_fault()
    cols, _, _ = store.scan("t")
    assert len(cols["x"]) == 10  # the "crashed" commit never became visible
    # and a later commit still works (no torn state)
    store.append("t", {"x": np.arange(5, dtype=np.int64)}, schema)
    cols2, _, _ = store.scan("t")
    assert len(cols2["x"]) == 15
