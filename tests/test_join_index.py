"""Join-index cache (exec/joinindex.py): sorted-build reuse across
statements — cache hit on repeat, invalidation on any write (table
version keying), zero recompiles on the repeated-statement path, the
argsort genuinely gone from the traced program, and bit-identical
results vs the cache-disabled engine. Plus the duplicate-build-key
error surfaced as its own typed, counted error."""

import jax
import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.exec import executor as X
from cloudberry_tpu.exec.executor import DuplicateBuildKeyError
from cloudberry_tpu.plan import nodes as N

Q = ("select grp, count(*) as n, sum(p) as sp from fact, dim "
     "where grp = d group by grp order by grp")


def _mk(nseg=1, **ov):
    s = cb.Session(Config(n_segments=nseg).with_overrides(**ov))
    s.sql("create table fact (k bigint, grp bigint, v bigint) "
          "distributed by (k)")
    s.sql("create table dim (d bigint, p bigint) distributed by (d)")
    rows = ",".join(f"({i}, {i % 500}, {i % 7})" for i in range(2000))
    s.sql(f"insert into fact values {rows}")
    rows = ",".join(f"({i}, {i * 3})" for i in range(500))
    s.sql(f"insert into dim values {rows}")
    return s


def test_cache_hit_and_no_recompile_single():
    s = _mk(1)
    a = s.sql(Q).to_pandas()
    assert s.stmt_log.counter("join_index_builds") >= 1
    c0 = s.stmt_log.counter("compiles")
    h0 = s.stmt_log.counter("join_index_hits")
    b = s.sql(Q).to_pandas()
    assert a.values.tolist() == b.values.tolist()
    assert s.stmt_log.counter("join_index_hits") > h0
    assert s.stmt_log.counter("compiles") == c0, "repeat recompiled"


def test_results_match_cache_disabled():
    on = _mk(1)
    off = _mk(1, **{"join_filter.index_cache": 0})
    assert not any(hasattr(n, "_jix") for n in _plan_nodes(off, Q))
    a = on.sql(Q).to_pandas()
    b = off.sql(Q).to_pandas()
    assert a.values.tolist() == b.values.tolist()


def test_invalidate_on_write():
    s = _mk(1)
    s.sql(Q)
    b0 = s.stmt_log.counter("join_index_builds")
    s.sql("insert into dim values (500, 9999)")
    s.sql("insert into fact values (99999, 500, 1)")
    out = s.sql(Q).to_pandas()
    # the write bumped the table version → fresh index, fresh results
    assert s.stmt_log.counter("join_index_builds") > b0
    assert len(out) == 501
    assert out[out.grp == 500].sp.tolist() == [9999]


def test_dist_shard_mode_parity():
    """Colocated (redistributed-probe) build: per-segment shard indexes
    ride the program split on the segment axis."""
    ov = {"planner.broadcast_threshold": 0}  # force redist, keep shards
    on = _mk(8, **ov)
    off = _mk(8, **{**ov, "join_filter.index_cache": 0})
    plan = _plan(on, Q)
    assert any(getattr(j, "_jix", None) is not None
               and j._jix.mode == "shard" for j in _walk(plan, N.PJoin))
    a = on.sql(Q).to_pandas()
    b = off.sql(Q).to_pandas()
    assert a.values.tolist() == b.values.tolist()
    assert on.stmt_log.counter("join_index_builds") >= 1
    h0 = on.stmt_log.counter("join_index_hits")
    on.sql(Q)
    assert on.stmt_log.counter("join_index_hits") > h0


def test_dist_gathered_mode_parity():
    """Broadcast build (the common small-dim shape): the cached index
    mirrors the gathered buffer's shard-major row order."""
    # greedy rules broadcast the small build; the memo might prefer a
    # probe redistribute, which would be the 'shard' shape instead
    ov = {"planner.enable_memo": False}
    on = _mk(8, **ov)
    off = _mk(8, **{**ov, "join_filter.index_cache": 0})
    plan = _plan(on, Q)
    joins = [n for n in _walk(plan, N.PJoin)]
    assert any(getattr(j, "_jix", None) is not None
               and j._jix.mode == "gathered" for j in joins), \
        [getattr(getattr(j, "_jix", None), "mode", None) for j in joins]
    a = on.sql(Q).to_pandas()
    b = off.sql(Q).to_pandas()
    assert a.values.tolist() == b.values.tolist()


def test_expansion_join_uses_index():
    """Non-unique (many-to-many) builds ride the cached index too."""
    on = _mk(1)
    off = _mk(1, **{"join_filter.index_cache": 0})
    q = ("select f1.grp, count(*) as n from fact f1, fact f2 "
         "where f1.grp = f2.grp group by f1.grp order by f1.grp")
    a = on.sql(q).to_pandas()
    b = off.sql(q).to_pandas()
    assert a.values.tolist() == b.values.tolist()


def test_argsort_eliminated_from_program():
    """The traced program with the cached index holds strictly fewer
    sort ops than without — the argsort is gone, not just cached."""
    on = _mk(1)
    off = _mk(1, **{"join_filter.index_cache": 0})

    def sort_count(s):
        from cloudberry_tpu.plan.planner import plan_statement
        from cloudberry_tpu.sql.parser import parse_sql

        plan = plan_statement(parse_sql(Q), s, {}).plan
        exe = X.compile_plan(plan, s)
        inputs = X.prepare_inputs(exe, s)
        jaxpr = jax.make_jaxpr(exe.raw_fn)(inputs)
        return str(jaxpr).count("sort[")

    assert sort_count(on) < sort_count(off)


def _plan(s, sql):
    from cloudberry_tpu.plan.binder import Binder
    from cloudberry_tpu.plan.planner import _optimize
    from cloudberry_tpu.sql.parser import parse_sql

    return _optimize(Binder(s.catalog, s.config).bind_query(
        parse_sql(sql)), s)


def _walk(plan, kind):
    out = []

    def rec(n):
        if isinstance(n, kind):
            out.append(n)
        for c in n.children():
            rec(c)

    rec(plan)
    return out


def _plan_nodes(s, sql):
    return _walk(_plan(s, sql), N.PJoin)


# ------------------------------------------------- duplicate-build-keys


def _dup_dim_key(s):
    """Duplicate one dim key IN PLACE (same shape): d becomes
    [0, 0, 2, 3, …] — two build rows for key 0."""
    t = s.catalog.table("dim")
    data = {c: np.asarray(v).copy() for c, v in t.data.items()}
    data["d"][1] = data["d"][0]
    t.set_data(data, t.dicts)


def test_duplicate_build_key_error_end_to_end():
    """A unique_build join over data that actually holds duplicate keys
    must abort with the typed error, never return wrong rows — the plan
    was built while dim.d WAS unique (the stale-inference scenario), the
    data changed underneath, and the runtime check is the last line."""
    s = _mk(1)
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sql.parser import parse_sql

    q = ("select grp, p from fact, dim where grp = d order by grp, p "
         "limit 5")
    plan = plan_statement(parse_sql(q), s, {}).plan
    joins = [n for n in X.all_nodes(plan) if isinstance(n, N.PJoin)]
    assert joins and all(j.unique_build for j in joins)
    _dup_dim_key(s)
    from cloudberry_tpu.exec.joinindex import strip_join_index

    strip_join_index(plan)  # exercise the in-program dup check
    with pytest.raises(DuplicateBuildKeyError):
        X.execute(plan, s)


def test_duplicate_build_key_error_through_cached_index(monkeypatch):
    """Same end-to-end shape through session.sql with the JOIN-INDEX fed
    (dup_check runs on the cached sorted keys too) and the uniqueness
    inference pinned stale — the typed error surfaces and is counted."""
    s = _mk(1)
    s.sql(Q)
    _dup_dim_key(s)
    t = s.catalog.table("dim")
    monkeypatch.setattr(type(t), "is_unique_cols",
                        lambda self, cols: True)  # stale PK inference
    with pytest.raises(DuplicateBuildKeyError):
        s.sql("select grp, p from fact, dim where grp = d "
              "order by grp, p limit 5")
    assert s.stmt_log.counter("duplicate_build_key_errors") == 1
