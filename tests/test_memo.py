"""Cascades-lite memo exploration (plan/memo.py — the gporca role).

The contract under test: the memo compares motion strategies over the
WHOLE join tree including the GROUP BY's final redistribute, so it can
choose a broadcast the greedy per-join threshold would refuse when that
keeps the fact side home and the aggregation one-stage colocated — and
results never change, only motion placement."""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config

# fact hashed(k) = the GROUP BY key; dim hashed on an unrelated column.
# greedy (dim above broadcast_threshold): redistribute BOTH sides onto d,
# then a two-stage agg re-shuffles every group — three fact-scale motions.
# memo: broadcast dim once; fact never moves; the agg runs one-stage.
Q = ("SELECT k, sum(v) AS sv FROM fact JOIN dim ON fact.d = dim.d "
     "GROUP BY k ORDER BY k LIMIT 10")


def _load(s, n_fact=400_000, n_dim=150_000):
    rng = np.random.default_rng(5)
    s.sql("CREATE TABLE dim (d BIGINT, payload BIGINT) "
          "DISTRIBUTED BY (payload)")
    s.sql("CREATE TABLE fact (k BIGINT, d BIGINT, v BIGINT) "
          "DISTRIBUTED BY (k)")
    s.catalog.table("dim").set_data(
        {"d": np.arange(n_dim), "payload": np.arange(n_dim)})
    s.catalog.table("fact").set_data(
        {"k": np.arange(n_fact) % 1000,
         "d": rng.integers(0, n_dim, n_fact),
         "v": rng.integers(0, 100, n_fact)})
    s.sql("analyze dim")
    s.sql("analyze fact")


def _mk(**over):
    # every memo-suite session runs the planck gate: a plan the memo
    # stamps wrong fails verification (plan/verify.py) loudly here
    ov = {"n_segments": 8, "debug.verify_plans": True}
    ov.update(over)
    return cb.Session(get_config().with_overrides(**ov))


def test_memo_lookahead_beats_greedy_threshold():
    s_greedy = _mk(**{"planner.enable_memo": False})
    _load(s_greedy)
    s_memo = _mk()
    _load(s_memo)

    greedy_plan = s_greedy.explain(Q)
    memo_plan = s_memo.explain(Q)
    # dim (150k rows) is above the 100k greedy threshold: greedy
    # redistributes and pays a two-stage agg
    assert "Motion broadcast" not in greedy_plan
    assert "GroupAgg final" in greedy_plan
    # the memo sees the whole tree: broadcast once, aggregate in place
    assert "Motion broadcast" in memo_plan
    assert "GroupAgg single" in memo_plan
    assert "GroupAgg final" not in memo_plan
    # identical answers either way
    assert s_greedy.sql(Q).to_pandas().equals(s_memo.sql(Q).to_pandas())


def test_memo_honors_broadcast_disabled():
    # broadcast_threshold = 0 is the explicit "never broadcast" switch;
    # the memo must not override it
    s = _mk(**{"planner.broadcast_threshold": 0})
    _load(s)
    plan = s.explain(Q)
    assert "Motion broadcast" not in plan
    assert len(s.sql(Q).to_pandas()) == 10


def test_memo_sees_through_projection_renames():
    """The Project chain between the agg and the join renames the
    distribution key; the memo must test colocation on the RENAMED
    locus — exactly what Distributor._agg sees."""
    q = ("SELECT kk, sum(v) AS sv FROM "
         "(SELECT fact.k AS kk, v FROM fact JOIN dim ON fact.d = dim.d)"
         " x GROUP BY kk ORDER BY kk LIMIT 5")
    s = _mk()
    _load(s)
    plan = s.explain(q)
    assert "Motion broadcast" in plan and "GroupAgg single" in plan
    s_greedy = _mk(**{"planner.enable_memo": False})
    _load(s_greedy)
    assert s_greedy.sql(q).to_pandas().equals(s.sql(q).to_pandas())


def test_memo_region_survives_out_of_grammar_sibling():
    """A FULL JOIN (out of grammar) above a clean join subtree must not
    block that subtree's own region."""
    s = _mk()
    _load(s, n_fact=1_000_000, n_dim=150_000)
    s.sql("CREATE TABLE small (sk BIGINT, t BIGINT) DISTRIBUTED BY (sk)")
    s.catalog.table("small").set_data(
        {"sk": np.arange(50), "t": np.arange(50)})
    s.sql("analyze small")
    q = ("SELECT count(*) AS c FROM small FULL JOIN "
         "(SELECT fact.k AS jk, v FROM fact JOIN dim ON fact.d = dim.d)"
         " j ON small.sk = j.jk")
    plan = s.explain(q)
    # memo broadcasts the 150k dim inside the sibling (cheaper than
    # moving the 1M-row fact); the greedy threshold would refuse
    assert "Motion broadcast" in plan
    s_greedy = _mk(**{"planner.enable_memo": False})
    _load(s_greedy, n_fact=1_000_000, n_dim=150_000)
    s_greedy.sql("CREATE TABLE small (sk BIGINT, t BIGINT) "
                 "DISTRIBUTED BY (sk)")
    s_greedy.catalog.table("small").set_data(
        {"sk": np.arange(50), "t": np.arange(50)})
    assert "Motion broadcast" not in s_greedy.explain(q)
    assert s_greedy.sql(q).to_pandas().equals(s.sql(q).to_pandas())


def _load_hot(s, hot=True, n_dim=150_000, n_fact=300_000):
    rng = np.random.default_rng(7)
    s.sql("CREATE TABLE hdim (d BIGINT, pl BIGINT) DISTRIBUTED BY (pl)")
    s.sql("CREATE TABLE hfact (k BIGINT, d BIGINT) DISTRIBUTED BY (k)")
    s.catalog.table("hdim").set_data(
        {"d": np.arange(n_dim), "pl": np.arange(n_dim)})
    d = rng.integers(0, n_dim, n_fact)
    if hot:
        d[:int(n_fact * 0.75)] = 17  # one value owns 75% of the probe
    s.catalog.table("hfact").set_data({"k": np.arange(n_fact), "d": d})
    s.sql("analyze hdim")
    s.sql("analyze hfact")


def test_memo_skew_aware_redistribute_cost():
    """A hot probe key makes one redistribute destination serialize the
    motion; the histogram exposes it and the memo broadcasts the build
    side instead — the cdbpath.c skew-sensitive costing role."""
    q = "SELECT count(*) AS c FROM hfact JOIN hdim ON hfact.d = hdim.d"
    s_hot = _mk()
    _load_hot(s_hot, hot=True)
    assert "Motion broadcast" in s_hot.explain(q)
    # same tables, uniform key: moving the probe is cheaper — no skew
    # penalty, no broadcast
    s_uni = _mk()
    _load_hot(s_uni, hot=False)
    assert "Motion broadcast" not in s_uni.explain(q)
    # answers match the greedy plans either way
    s_greedy = _mk(**{"planner.enable_memo": False})
    _load_hot(s_greedy, hot=True)
    assert s_greedy.sql(q).to_pandas().equals(s_hot.sql(q).to_pandas())


def test_analyze_invalidates_statement_cache():
    """Memo choices ride statistics: fresh stats must re-plan a cached
    statement (the relcache-invalidation role of ANALYZE)."""
    s = _mk()
    _load_hot(s, hot=True)
    q = "SELECT count(*) AS c FROM hfact JOIN hdim ON hfact.d = hdim.d"
    s.sql(q)
    assert s._cached_statement(q) is not None
    s.sql("analyze hfact")
    assert s._cached_statement(q) is None
    s.sql(q)  # replans and re-caches cleanly
    assert s._cached_statement(q) is not None


def test_memo_equivalence_random_queries():
    """Motion placement may differ; answers may not."""
    queries = [
        "SELECT count(*) AS c FROM fact JOIN dim ON fact.d = dim.d "
        "WHERE v < 50",
        "SELECT d.payload % 7 AS p, min(v) AS mn, max(k) AS mk "
        "FROM fact JOIN dim d ON fact.d = d.d GROUP BY d.payload % 7 "
        "ORDER BY p",
        "SELECT k FROM fact JOIN dim ON fact.d = dim.d "
        "WHERE payload < 100 ORDER BY k, v LIMIT 20",
    ]
    s_greedy = _mk(**{"planner.enable_memo": False})
    _load(s_greedy, n_fact=50_000, n_dim=20_000)
    s_memo = _mk()
    _load(s_memo, n_fact=50_000, n_dim=20_000)
    for q in queries:
        exp = s_greedy.sql(q).to_pandas()
        got = s_memo.sql(q).to_pandas()
        assert exp.equals(got), q


# ---------------------------------------------------------------- joint
# Join ORDER and motion strategy explored in ONE search (the
# CJoinOrderDPv2/CMemo marriage, plan/memo.joint_search): the row-count
# DP prefers joining the mildly-reducing wide dim first, which forces a
# 26x more expensive broadcast; the joint search sees that joining the
# colocated dim first costs zero motion and ships only the narrow
# intermediate.

def _load_joint(s):
    rng = np.random.default_rng(11)
    n_f, n_a, n_b = 50_000, 40_000, 40_500
    # fact hashed on k1 (colocated with dim a); k2 joins wide dim b
    s.sql("CREATE TABLE fact (k1 BIGINT, k2 BIGINT, v BIGINT, g BIGINT) "
          "DISTRIBUTED BY (k1)")
    s.sql("CREATE TABLE a (ak BIGINT, av BIGINT) DISTRIBUTED BY (ak)")
    wide = ", ".join(f"w{i} BIGINT" for i in range(18))
    s.sql(f"CREATE TABLE b (bk BIGINT, {wide}) DISTRIBUTED BY (bk)")
    s.catalog.table("fact").set_data(
        {"k1": rng.integers(0, n_a, n_f),
         "k2": rng.integers(0, 45_000, n_f),
         "v": rng.integers(0, 100, n_f),
         "g": rng.integers(0, 50, n_f)})
    s.catalog.table("a").set_data(
        {"ak": np.arange(n_a), "av": rng.integers(0, 100, n_a)})
    bcols = {"bk": rng.permutation(45_000)[:n_b]}
    for i in range(18):
        bcols[f"w{i}"] = rng.integers(0, 1000, n_b)
    s.catalog.table("b").set_data(bcols)
    for t in ("fact", "a", "b"):
        s.sql(f"analyze {t}")


JOINT_Q = ("SELECT g, sum(v) AS sv, sum(w0) AS sw FROM fact, a, b "
           "WHERE fact.k1 = a.ak AND fact.k2 = b.bk "
           "GROUP BY g ORDER BY g")


def test_joint_order_beats_row_dp():
    s_dp = _mk(**{"planner.enable_memo": False})
    _load_joint(s_dp)
    s_joint = _mk()
    _load_joint(s_joint)
    dp_plan = s_dp.explain(JOINT_Q)
    joint_plan = s_joint.explain(JOINT_Q)
    # row-count DP orders the wide dim b first (est 45k < 50k), and the
    # greedy rule then broadcasts its ~45 MB under the row threshold
    assert "Motion broadcast" in dp_plan
    # the joint search joins the colocated dim a first (zero motion) and
    # ships only the ~2 MB narrow intermediate to meet b
    assert "Motion broadcast" not in joint_plan
    assert "Motion redistribute" in joint_plan
    # same rows either way
    pd_dp = s_dp.sql(JOINT_Q).to_pandas()
    pd_joint = s_joint.sql(JOINT_Q).to_pandas()
    assert pd_dp.equals(pd_joint)


def test_joint_search_time_bounded():
    """An 8-relation chain-and-star mix must plan in bounded time (the
    verdict's planning-time criterion; q8 is the TPC-H worst case)."""
    import time

    s = _mk()
    rng = np.random.default_rng(3)
    n = 20_000
    s.sql("CREATE TABLE hub (x0 BIGINT, x1 BIGINT, x2 BIGINT, x3 BIGINT, "
          "x4 BIGINT, x5 BIGINT, x6 BIGINT, m BIGINT) DISTRIBUTED BY (x0)")
    cols = {f"x{i}": rng.integers(0, 5_000, n) for i in range(7)}
    cols["m"] = rng.integers(0, 100, n)
    s.catalog.table("hub").set_data(cols)
    for i in range(7):
        s.sql(f"CREATE TABLE d{i} (k{i} BIGINT, p{i} BIGINT) "
              f"DISTRIBUTED BY (k{i})")
        s.catalog.table(f"d{i}").set_data(
            {f"k{i}": np.arange(5_000), f"p{i}": np.arange(5_000)})
    for t in ["hub"] + [f"d{i}" for i in range(7)]:
        s.sql(f"analyze {t}")
    q = ("SELECT sum(m) AS sm FROM hub, " +
         ", ".join(f"d{i}" for i in range(7)) + " WHERE " +
         " AND ".join(f"hub.x{i} = d{i}.k{i}" for i in range(7)))
    t0 = time.time()
    s.explain(q)
    assert time.time() - t0 < 5.0  # 8 relations, bounded search
    got = s.sql(q).to_pandas()
    assert got["sm"][0] == int(cols["m"].sum())


def test_memo_abstention_marked_in_explain():
    """An out-of-grammar region (set-op inside the join tree) makes the
    memo abstain — and the abstention is pinned in plan text ("memo:
    abstained" on the region root), so golden plans catch plan-quality
    regressions in abstaining regions (round-5 verdict item 6)."""
    s = _mk()
    s.sql("CREATE TABLE a (k BIGINT, v BIGINT) DISTRIBUTED BY (k)")
    s.sql("CREATE TABLE b (k BIGINT, w BIGINT) DISTRIBUTED BY (k)")
    s.sql("CREATE TABLE c (k BIGINT, u BIGINT) DISTRIBUTED BY (k)")
    s.sql("INSERT INTO a VALUES (1, 10), (2, 20)")
    s.sql("INSERT INTO b VALUES (1, 1), (2, 2)")
    s.sql("INSERT INTO c VALUES (1, 5), (3, 7)")
    txt = s.explain(
        "SELECT a.k, sum(a.v) AS sv FROM a "
        "JOIN (SELECT k FROM b UNION ALL SELECT k FROM c) d ON a.k = d.k "
        "GROUP BY a.k")
    assert "memo: abstained" in txt
    # a fully in-grammar query carries no abstention mark
    clean = s.explain("SELECT a.k, sum(a.v) AS sv FROM a "
                      "JOIN b ON a.k = b.k GROUP BY a.k")
    assert "memo: abstained" not in clean


# ------------------------------------------------------------ planck
# Randomized schema / join-graph sweep: whatever join order and motion
# strategy the memo's joint_search OR the DP+greedy fallback choose,
# the emitted plan must verify clean against the derived-vs-required
# property rules (plan/verify.py). Seeded — a failure names its seed.


def _random_join_case(seed):
    """Build a random star/chain schema + a matching query: 3-5 tables,
    random distribution keys (sometimes deliberately NOT the join key,
    sometimes RANDOMLY distributed), random join tree, optional GROUP
    BY / ORDER BY+LIMIT tops."""
    rng = np.random.default_rng(seed)
    nt = int(rng.integers(3, 6))
    dom = int(rng.integers(50, 2_000))
    ddls, loads, anas = [], [], []
    for i in range(nt):
        n = int(rng.integers(200, 4_000))
        dist = ["k%d" % i, "p%d" % i, None][int(rng.integers(0, 3))]
        by = f"DISTRIBUTED BY ({dist})" if dist else ""
        ddls.append(f"CREATE TABLE t{i} (k{i} BIGINT, p{i} BIGINT, "
                    f"v{i} BIGINT) {by}")
        loads.append((f"t{i}", {
            f"k{i}": np.arange(n, dtype=np.int64) % dom,
            f"p{i}": rng.integers(0, dom, n),
            f"v{i}": rng.integers(0, 100, n)}))
        anas.append(f"analyze t{i}")
    conds = []
    for i in range(1, nt):
        j = int(rng.integers(0, i))
        conds.append(f"t{j}.p{j} = t{i}.k{i}")
    frm = ", ".join(f"t{i}" for i in range(nt))
    where = " AND ".join(conds)
    shape = int(rng.integers(0, 3))
    if shape == 0:
        sql = (f"SELECT t0.k0 AS g, sum(t{nt-1}.v{nt-1}) AS s "
               f"FROM {frm} WHERE {where} GROUP BY t0.k0")
    elif shape == 1:
        sql = (f"SELECT count(*) AS c FROM {frm} WHERE {where}")
    else:
        sql = (f"SELECT t0.k0 AS g, t1.v1 AS w FROM {frm} "
               f"WHERE {where} ORDER BY g, w LIMIT 25")
    return ddls, loads, anas, sql


def _sweep_one(seed):
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.plan.verify import verify_plan
    from cloudberry_tpu.sql.parser import parse_sql

    ddls, loads, anas, sql = _random_join_case(seed)
    for memo in (True, False):  # joint_search AND the DP+greedy path
        s = _mk(**{"planner.enable_memo": memo})
        for d in ddls:
            s.sql(d)
        for name, cols in loads:
            s.catalog.table(name).set_data(cols)
        for a in anas:
            s.sql(a)
        plan = plan_statement(parse_sql(sql), s, {}).plan
        findings = verify_plan(plan, s)
        assert findings == [], (
            f"seed {seed} memo={memo}: {sql}\n"
            + "\n".join(f.render() for f in findings))


@pytest.mark.parametrize("seed", range(6))
def test_random_join_graphs_verify_clean(seed):
    _sweep_one(seed)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(6, 30))
def test_random_join_graphs_verify_clean_full(seed):
    _sweep_one(seed)
