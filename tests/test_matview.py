"""Materialized views: AQUMV rewrite + incremental maintenance.

Reference: CREATE/REFRESH MATERIALIZED VIEW (commands/matview.c), the
answer-query-using-matview rewrite (optimizer/plan/aqumv.c), and IMMV
incremental maintenance (matview.c immv triggers, gp_matview_aux).
"""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.plan.binder import BindError


@pytest.fixture
def sess():
    s = cb.Session(Config(n_segments=1))
    s.sql("create table sales (region text not null, day bigint not null, "
          "amt decimal(12,2) not null, qty bigint not null)")
    rows = []
    rng = np.random.default_rng(3)
    for i in range(300):
        rows.append(f"('r{int(rng.integers(0, 4))}', {int(rng.integers(0, 30))}, "
                    f"{int(rng.integers(1, 500))}.25, {int(rng.integers(1, 9))})")
    s.sql("insert into sales values " + ", ".join(rows))
    return s


MV = ("create incremental materialized view mv_sales as "
      "select region, sum(amt) as s_amt, count(*) as cnt, "
      "min(qty) as mn_q, max(qty) as mx_q from sales group by region")


def test_matview_basics(sess):
    sess.sql(MV)
    df = sess.sql("select region, s_amt from mv_sales order by region") \
        .to_pandas()
    oracle = sess.sql("select region, sum(amt) as s_amt from sales "
                      "group by region order by region").to_pandas()
    assert np.allclose(df["s_amt"], oracle["s_amt"])


def test_aqumv_rewrite_used(sess):
    sess.sql(MV)
    q = "select region, sum(amt) as s from sales group by region order by region"
    exp = sess.explain(q)
    assert "AQUMV" in exp and "mv_sales" in exp
    got = sess.sql(q).to_pandas()
    sess.config = sess.config.with_overrides(**{"planner.enable_aqumv": False})
    want = sess.sql(q + " limit 100").to_pandas()  # different text, no cache
    assert np.allclose(got["s"], want["s"])


def test_aqumv_global_agg_and_filter(sess):
    sess.sql(MV)
    q = "select sum(amt) as s, count(*) as c from sales where region = 'r1'"
    assert "AQUMV" in sess.explain(q)
    got = sess.sql(q).to_pandas()
    direct = sess.sql(
        "select sum(amt) as s, count(*) as c from sales "
        "where region = 'r1' and 1 = 1").to_pandas()
    assert np.allclose(got["s"], direct["s"]) and got["c"].iloc[0] \
        == direct["c"].iloc[0]


def test_aqumv_not_used_when_not_derivable(sess):
    sess.sql(MV)
    # avg is not stored in the view; predicate over a non-key breaks too
    assert "AQUMV" not in sess.explain(
        "select region, avg(amt) as a from sales group by region")
    assert "AQUMV" not in sess.explain(
        "select sum(amt) as s from sales where qty > 3")


def test_ivm_insert_maintains(sess):
    sess.sql(MV)
    sess.sql("insert into sales values ('r1', 99, 1000.50, 100), "
             "('r9', 1, 7.00, 2)")
    df = sess.sql("select region, s_amt, cnt, mn_q, mx_q from mv_sales "
                  "order by region").to_pandas()
    oracle = sess.sql(
        "select region, sum(amt) as s_amt, count(*) as cnt, min(qty) as "
        "mn_q, max(qty) as mx_q from sales group by region "
        "order by region  ").to_pandas()  # trailing spaces: bypass AQUMV? no
    assert list(df["region"]) == list(oracle["region"])  # includes new 'r9'
    assert np.allclose(df["s_amt"], oracle["s_amt"])
    assert list(df["cnt"]) == list(oracle["cnt"])
    assert list(df["mx_q"]) == list(oracle["mx_q"])


def test_ivm_stays_fresh_for_aqumv(sess):
    sess.sql(MV)
    sess.sql("insert into sales values ('r0', 5, 1.00, 1)")
    q = "select region, count(*) as c from sales group by region order by region"
    assert "AQUMV" in sess.explain(q)
    got = sess.sql(q).to_pandas()
    # oracle computed with AQUMV disabled
    cfg = sess.config
    sess.config = cfg.with_overrides(**{"planner.enable_aqumv": False})
    want = sess.sql(q + " limit 999").to_pandas()
    sess.config = cfg
    assert list(got["c"]) == list(want["c"])


def test_plain_matview_goes_stale_and_refreshes(sess):
    sess.sql("create materialized view mv2 as "
             "select region, sum(qty) as q from sales group by region")
    assert "AQUMV" in sess.explain(
        "select region, sum(qty) as q from sales group by region")
    sess.sql("insert into sales values ('r0', 5, 1.00, 1)")
    # stale now: the rewrite must NOT fire
    assert "AQUMV" not in sess.explain(
        "select region, sum(qty) as q from sales group by region")
    sess.sql("refresh materialized view mv2")
    assert "AQUMV" in sess.explain(
        "select region, sum(qty) as q from sales group by region")


def test_update_delete_force_refresh(sess):
    sess.sql(MV)
    sess.sql("delete from sales where region = 'r2'")
    df = sess.sql("select region from mv_sales order by region").to_pandas()
    assert "r2" not in list(df["region"])


def test_incremental_requires_not_null():
    s = cb.Session(Config(n_segments=1))
    s.sql("create table nn (k bigint, v bigint)")  # nullable
    with pytest.raises(BindError):
        s.sql("create incremental materialized view bad as "
              "select k, sum(v) as s from nn group by k")
    # non-incremental is fine
    s.sql("create materialized view ok as "
          "select k, sum(v) as s from nn group by k")


def test_matview_persists_across_sessions(tmp_path):
    cfg = Config(n_segments=1).with_overrides(
        **{"storage.root": str(tmp_path / "store")})
    a = cb.Session(cfg)
    a.sql("create table t (k bigint not null, v bigint not null)")
    a.sql("insert into t values (1, 10), (1, 20), (2, 5)")
    a.sql("create incremental materialized view m as "
          "select k, sum(v) as s from t group by k")
    b = cb.Session(cfg)
    df = b.sql("select k, s from m order by k").to_pandas()
    assert list(df["s"]) == [30, 5]
    # fresh across sessions: the rewrite fires in session b too
    assert "AQUMV" in b.explain("select k, sum(v) as s from t group by k")


def test_rollback_invalidates(sess):
    sess.sql(MV)
    sess.sql("begin")
    sess.sql("insert into sales values ('r0', 5, 1.00, 1)")
    sess.sql("rollback")
    # conservative: no AQUMV until refreshed
    assert "AQUMV" not in sess.explain(
        "select region, sum(amt) as s from sales group by region")
    sess.sql("refresh materialized view mv_sales")
    assert "AQUMV" in sess.explain(
        "select region, sum(amt) as s from sales group by region")


def test_aqumv_having_and_order_by_agg(sess):
    sess.sql(MV)
    q = ("select region, sum(amt) as s from sales group by region "
         "having sum(amt) > 6 order by sum(amt) desc")
    assert "AQUMV" in sess.explain(q)
    got = sess.sql(q).to_pandas()
    cfg = sess.config
    sess.config = cfg.with_overrides(**{"planner.enable_aqumv": False})
    want = sess.sql(q + " limit 999").to_pandas()
    sess.config = cfg
    assert np.allclose(got["s"], want["s"])


def test_explain_statement_shows_aqumv(sess):
    sess.sql(MV)
    out = sess.sql("explain select region, sum(amt) as s from sales "
                   "group by region")
    assert "AQUMV" in out


def test_incremental_unknown_table_is_bind_error():
    s = cb.Session(Config(n_segments=1))
    with pytest.raises(BindError):
        s.sql("create incremental materialized view m as "
              "select k, sum(v) as s from nosuch group by k")


def test_drop_base_table_refused_with_dependents(sess):
    sess.sql(MV)
    with pytest.raises(BindError, match="depend"):
        sess.sql("drop table sales")
    sess.sql("drop materialized view mv_sales")
    sess.sql("drop table sales")  # fine once the dependent is gone


def test_dml_into_matview_rejected(sess):
    sess.sql(MV)
    with pytest.raises(BindError, match="materialized view"):
        sess.sql("insert into mv_sales values ('zz', 1.00, 1, 1, 1)")
    with pytest.raises(BindError, match="materialized view"):
        sess.sql("delete from mv_sales where cnt > 0")
    with pytest.raises(BindError, match="materialized view"):
        sess.sql("update mv_sales set cnt = 0")


def test_rolled_back_create_leaves_no_durable_def(tmp_path):
    cfg = Config(n_segments=1).with_overrides(
        **{"storage.root": str(tmp_path / "store")})
    a = cb.Session(cfg)
    a.sql("create table t (k bigint not null, v bigint not null)")
    a.sql("insert into t values (1, 10)")
    a.sql("begin")
    a.sql("create materialized view m as select k, sum(v) as s from t "
          "group by k")
    a.sql("rollback")
    b = cb.Session(cfg)
    assert "m" not in b.catalog.matviews
    # base-table queries in the new session are unaffected
    assert b.sql("select k, sum(v) as s from t group by k") \
        .to_pandas()["s"].iloc[0] == 10


def test_drop_matview(sess):
    sess.sql(MV)
    sess.sql("drop materialized view mv_sales")
    assert "AQUMV" not in sess.explain(
        "select region, sum(amt) as s from sales group by region")
    with pytest.raises(Exception):
        sess.sql("select * from mv_sales")


MV_DELTA = ("create incremental materialized view mv_delta as "
            "select region, sum(amt) as s_amt, sum(qty) as s_q, "
            "count(*) as cnt from sales group by region")


def _oracle(sess):
    return sess.sql("select region, sum(amt) as s_amt, sum(qty) as s_q, "
                    "count(*) as cnt from sales group by region "
                    "order by region").to_pandas()


def test_ivm_update_delete_delta_no_refresh(sess, monkeypatch):
    """UPDATE and DELETE maintain sum/count views through the captured
    (subtract, add) delta — never a re-materialization (the
    matview.c:594-640 IMMV delta discipline)."""
    from cloudberry_tpu.plan import matview as MVmod

    sess.sql(MV_DELTA)
    calls = []
    orig = MVmod.refresh_matview
    monkeypatch.setattr(MVmod, "refresh_matview",
                        lambda s, n: calls.append(n) or orig(s, n))
    sess.sql("update sales set amt = amt + 10.50, qty = qty + 1 "
             "where region = 'r1'")
    sess.sql("delete from sales where qty > 7")
    sess.sql("update sales set qty = qty * 2 where day < 5")
    got = sess.sql("select region, s_amt, s_q, cnt from mv_delta "
                   "order by region").to_pandas()
    exp = _oracle(sess)
    assert list(got["s_amt"]) == list(exp["s_amt"])
    assert list(got["s_q"]) == list(exp["s_q"])
    assert list(got["cnt"]) == list(exp["cnt"])
    assert calls == []  # every maintenance took the delta path
    # and the view stayed FRESH for AQUMV throughout
    assert "AQUMV" in sess.explain(
        "select region, sum(amt) as s from sales group by region")


def test_ivm_delete_empties_group(sess, monkeypatch):
    from cloudberry_tpu.plan import matview as MVmod

    sess.sql(MV_DELTA)
    calls = []
    orig = MVmod.refresh_matview
    monkeypatch.setattr(MVmod, "refresh_matview",
                        lambda s, n: calls.append(n) or orig(s, n))
    sess.sql("delete from sales where region = 'r2'")
    got = sess.sql("select region from mv_delta order by region").to_pandas()
    assert "r2" not in list(got["region"])
    assert calls == []


def test_ivm_minmax_still_refreshes(sess, monkeypatch):
    """min/max are not invertible under deletion: those views
    re-materialize (correctness first)."""
    from cloudberry_tpu.plan import matview as MVmod

    sess.sql(MV)  # includes min/max aggregates
    calls = []
    orig = MVmod.refresh_matview
    monkeypatch.setattr(MVmod, "refresh_matview",
                        lambda s, n: calls.append(n) or orig(s, n))
    sess.sql("delete from sales where qty = 8")
    assert calls == ["mv_sales"]
    got = sess.sql("select region, mn_q, mx_q from mv_sales "
                   "order by region").to_pandas()
    exp = sess.sql("select region, min(qty) as mn, max(qty) as mx "
                   "from sales group by region order by region").to_pandas()
    assert list(got["mn_q"]) == list(exp["mn"])
    assert list(got["mx_q"]) == list(exp["mx"])


def test_ivm_update_string_key(sess, monkeypatch):
    """An UPDATE that MOVES rows between groups (key column changes)
    subtracts from the old group and adds to the new one."""
    from cloudberry_tpu.plan import matview as MVmod

    sess.sql(MV_DELTA)
    calls = []
    orig = MVmod.refresh_matview
    monkeypatch.setattr(MVmod, "refresh_matview",
                        lambda s, n: calls.append(n) or orig(s, n))
    sess.sql("update sales set region = 'r9' where region = 'r0' "
             "and day < 10")
    got = sess.sql("select region, s_amt, s_q, cnt from mv_delta "
                   "order by region").to_pandas()
    exp = _oracle(sess)
    assert list(got["region"]) == list(exp["region"])
    assert list(got["cnt"]) == list(exp["cnt"])
    assert list(got["s_amt"]) == list(exp["s_amt"])
    assert calls == []
