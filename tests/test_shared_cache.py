"""Shared cache tier (sched/sharedcache.py): cross-session zero-recompile
reuse over a durable store, version/config-epoch invalidation, and
thread-stress on the shared LRUs (ISSUE-7 satellite)."""

import threading

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config


@pytest.fixture(autouse=True, scope="module")
def _lock_witness():
    # runtime lock-order witness (lint/witness.py): the shared-LRU
    # stress below runs its lock traffic under declared-order checking
    from cloudberry_tpu.lint import witness

    with witness.watching():
        yield


def _store_cfg(tmp_path):
    return Config().with_overrides(
        **{"storage.root": str(tmp_path / "store")})


def _seed(cfg, rows=64):
    s = cb.Session(cfg)
    s.sql("create table d (x bigint, y bigint) distributed by (x)")
    s.sql("insert into d values " +
          ",".join(f"({i}, {i * 3})" for i in range(rows)))
    s.sql("create table dim (k bigint, name bigint) distributed by (k)")
    s.sql("insert into dim values " +
          ",".join(f"({i}, {i + 100})" for i in range(16)))
    return s


def test_cross_session_zero_recompile(tmp_path):
    """ISSUE-7 acceptance pin: tenant B's identical-skeleton statement
    over the same store compiles NOTHING — it re-binds tenant A's
    compiled generic plan (StatementLog compile counter)."""
    cfg = _store_cfg(tmp_path)
    _seed(cfg)

    a = cb.Session(cfg)  # tenant A backend (cold register, like a server)
    a.sql("select x, y from d where x = 1")
    assert a.stmt_log.counter("compiles") >= 1

    b = cb.Session(cfg)  # tenant B backend
    c0 = b.stmt_log.counter("compiles")
    out = b.sql("select x, y from d where x = 7").to_pandas()
    assert out.values.tolist() == [[7, 21]]
    assert b.stmt_log.counter("compiles") - c0 == 0
    assert b.stmt_log.counter("generic_hits") >= 1
    # the scope really is shared, and it is the store kind
    assert a._cache_scope is b._cache_scope
    assert a._cache_scope.kind == "store"


def test_version_bump_invalidates_shared_entries(tmp_path):
    """A write through one backend bumps the store version; the other
    backend's next same-skeleton statement must NOT reuse the stale
    entry (fresh results prove it; the generic cache key carries the
    store version)."""
    cfg = _store_cfg(tmp_path)
    _seed(cfg)
    a = cb.Session(cfg)
    b = cb.Session(cfg)
    assert b.sql("select y from d where x = 3").to_pandas()\
        .values.tolist() == [[9]]
    a.sql("update d set y = 999 where x = 3")
    out = b.sql("select y from d where x = 3").to_pandas()
    assert out.values.tolist() == [[999]]


def test_config_epoch_invalidates(tmp_path):
    """The config OBJECT identity is the config epoch: a session under a
    different (even equal-valued) Config object never reuses entries
    built under another epoch."""
    cfg = _store_cfg(tmp_path)
    _seed(cfg)
    a = cb.Session(cfg)
    a.sql("select x, y from d where x = 1")
    # a new frozen tree with an execution-irrelevant knob changed: same
    # plans, DIFFERENT epoch — entries must not bleed across
    b = cb.Session(cfg.with_overrides(**{"health.retries": 2}))
    c0 = b.stmt_log.counter("compiles")
    b.sql("select x, y from d where x = 2")
    assert b.stmt_log.counter("compiles") - c0 >= 1  # no epoch bleed


def test_private_scope_for_storeless_sessions():
    """Storeless sessions keep private scopes (their tables have no
    cross-session identity): no sharing, the pre-tier behavior."""
    a = cb.Session(Config())
    b = cb.Session(Config())
    assert a._cache_scope is not b._cache_scope
    assert a._cache_scope.kind == "session"


def test_join_index_shared_across_backends(tmp_path):
    """The join-index cache rides the same tier: backend B's first join
    reuses backend A's sorted-build scaffolding (hits with zero
    builds)."""
    cfg = _store_cfg(tmp_path)
    _seed(cfg)
    q = ("select dim.name, count(*) as n, sum(d.y) as sy from d, dim "
         "where d.x = dim.k group by dim.name order by dim.name")

    def warm(s):
        # cold store tables scan via pruned store reads (per-statement
        # row sets — never index-eligible); a loaded table scans whole
        for name in ("d", "dim"):
            s.catalog.table(name).ensure_loaded()

    a = cb.Session(cfg)
    warm(a)
    ra = a.sql(q).to_pandas()
    assert a.stmt_log.counter("join_index_builds") >= 1
    b = cb.Session(cfg)
    warm(b)
    rb = b.sql(q).to_pandas()
    assert rb.values.tolist() == ra.values.tolist()
    assert b.stmt_log.counter("join_index_hits") >= 1
    assert b.stmt_log.counter("join_index_builds") == 0


def test_in_transaction_entries_stay_private(tmp_path):
    """Mid-transaction table state has no store identity: entries built
    inside a transaction key on the table OBJECT (uid), so another
    backend can never hit them — and results stay correct."""
    cfg = _store_cfg(tmp_path)
    _seed(cfg)
    a = cb.Session(cfg)
    b = cb.Session(cfg)
    a.txn("begin")
    a.sql("update d set y = 5555 where x = 5")
    assert a.sql("select y from d where x = 5").to_pandas()\
        .values.tolist() == [[5555]]
    # b sees the committed (old) value despite a's in-txn entries
    assert b.sql("select y from d where x = 5").to_pandas()\
        .values.tolist() == [[15]]
    a.txn("rollback")
    assert a.sql("select y from d where x = 5").to_pandas()\
        .values.tolist() == [[15]]


def test_shared_lru_thread_stress(tmp_path):
    """Thread-stress the shared scope: several backends hammer the same
    skeletons (generic cache) and join indexes concurrently while a
    writer bumps versions — no exceptions, correct results throughout."""
    cfg = _store_cfg(tmp_path)
    _seed(cfg, rows=128)
    sessions = [cb.Session(cfg) for _ in range(3)]
    errors = []
    stop = threading.Event()

    def reader(s, seed):
        rng = np.random.default_rng(seed)
        try:
            while not stop.is_set():
                k = int(rng.integers(0, 100))
                out = s.sql(f"select x, y from d where x = {k}")
                rows = out.to_pandas().values.tolist()
                if rows and rows[0][0] != k:
                    errors.append(f"wrong row for {k}: {rows}")
        except Exception as e:  # pragma: no cover
            errors.append(f"{type(e).__name__}: {e}")

    def writer(s):
        try:
            i = 0
            while not stop.is_set():
                s.sql(f"insert into dim values ({1000 + i}, {i})")
                i += 1
        except Exception as e:  # pragma: no cover
            errors.append(f"writer {type(e).__name__}: {e}")

    threads = [threading.Thread(target=reader, args=(s, i))
               for i, s in enumerate(sessions)]
    threads.append(threading.Thread(target=writer,
                                    args=(cb.Session(cfg),)))
    for t in threads:
        t.start()
    import time

    time.sleep(1.5)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors[:3]


def test_meta_sched_reports_shared_cache(tmp_path):
    from cloudberry_tpu.serve.meta import describe

    cfg = _store_cfg(tmp_path)
    s = _seed(cfg)
    s.sql("select x from d where x = 1")
    info = describe(s, "sched")["shared_cache"]
    assert info["kind"] in ("store", "session")
    assert "generic_skeletons" in info
