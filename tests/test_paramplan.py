"""Parameterized generic plans (sched/paramplan.py, the plan_cache.c
analog): skeleton normalization, zero-recompile rebinding with
bit-identical results, non-generic opt-outs, and the statement-cache
keying audit (user params + config epoch)."""

import threading

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.sched import paramplan


def _pts_session(nseg=1, rows=100_000, generic=True):
    s = cb.Session(Config(n_segments=nseg).with_overrides(
        **{"sched.generic_plans": generic}))
    s.sql("create table pts (k bigint, v bigint, w double) "
          "distributed by (k)")
    s.catalog.table("pts").set_data({
        "k": np.arange(rows, dtype=np.int64),
        "v": (np.arange(rows, dtype=np.int64) * 7) % 1000,
        "w": np.arange(rows, dtype=np.float64) * 0.5}, {})
    return s


# ------------------------------------------------------------- skeletons


def test_normalize_same_shape_collides():
    a = paramplan.normalize("select k from t where k = 42")
    b = paramplan.normalize("select k from t where k = 99")
    assert a is not None and a[0] == b[0]
    assert a[1] == ("42",) and b[1] == ("99",)


def test_normalize_structural_literals_stay():
    # LIMIT/OFFSET and INTERVAL quantities shape the plan — never params
    a = paramplan.normalize("select k from t where k > 1 limit 5")
    b = paramplan.normalize("select k from t where k > 1 limit 7")
    assert a[0] != b[0]
    assert a[1] == ("1",)
    c = paramplan.normalize(
        "select k from t where d < date '1994-01-01' + interval '1' year")
    assert c[1] == ("1994-01-01",)  # the date is a param, the '1' is not


def test_normalize_rejects_non_queries():
    assert paramplan.normalize("insert into t values (1)") is None
    assert paramplan.normalize("create table t (a int)") is None


# --------------------------------------------- zero-recompile acceptance


@pytest.mark.parametrize("nseg", [1, 8])
def test_point_lookup_rebinds_without_recompiling(nseg):
    """ISSUE-3 acceptance: a repeated point lookup with DIFFERENT literals
    triggers zero recompiles after the first execution (compile counter in
    StatementLog) and returns bit-identical results vs the
    unparameterized path."""
    s = _pts_session(nseg=nseg)
    off = _pts_session(nseg=nseg, generic=False)
    q = "select k, v, w from pts where k = {}"
    s.sql(q.format(4242))  # warmup: builds the generic plan
    c0 = s.stmt_log.counter("compiles")
    for key in (7, 999, 31337, 77777):
        got = s.sql(q.format(key))
        want = off.sql(q.format(key))
        gsel, wsel = np.asarray(got.sel), np.asarray(want.sel)
        for name in got.columns:
            np.testing.assert_array_equal(
                np.asarray(got.columns[name])[gsel],
                np.asarray(want.columns[name])[wsel], err_msg=name)
    assert s.stmt_log.counter("compiles") - c0 == 0
    # per-statement observability: the history rows carry compiles=0
    rec = s.stmt_log.recent(3)
    assert all(e["compiles"] == 0 for e in rec)


@pytest.mark.parametrize("nseg", [1, 8])
def test_parameterized_q6_shape_zero_recompiles(nseg):
    s = cb.Session(Config(n_segments=nseg))
    off = cb.Session(Config(n_segments=nseg).with_overrides(
        **{"sched.generic_plans": False}))
    rng = np.random.default_rng(5)
    m = 40_000
    data = {"qty": rng.integers(1, 5000, m).astype(np.int64),
            "price": rng.integers(100, 10000, m).astype(np.int64),
            "disc": rng.integers(0, 11, m).astype(np.int64),
            "sd": rng.integers(8000, 12000, m).astype(np.int32)}
    for sess in (s, off):
        sess.sql("create table li (qty decimal(2), price decimal(2), "
                 "disc decimal(2), sd date)")
        sess.catalog.table("li").set_data(dict(data), {})
    q = ("select sum(price * disc) as rev from li where sd >= "
         "date '1994-01-01' and disc between 0.0{lo} and 0.0{hi} "
         "and qty < {q}.0")
    s.sql(q.format(lo=5, hi=7, q=24))
    c0 = s.stmt_log.counter("compiles")
    for lo, hi, qty in ((3, 5, 20), (1, 9, 48), (6, 8, 10)):
        got = s.sql(q.format(lo=lo, hi=hi, q=qty)).to_pandas()
        want = off.sql(q.format(lo=lo, hi=hi, q=qty)).to_pandas()
        # DECIMAL sums are exact int64 fixed-point — bit-identical
        assert got.rev[0] == want.rev[0]
    assert s.stmt_log.counter("compiles") - c0 == 0
    assert s.stmt_log.counter("generic_hits") >= 3


def test_date_literal_rebinds():
    s = _pts_session(rows=1000)
    s.sql("create table ev (d date, x bigint)")
    s.catalog.table("ev").set_data({
        "d": np.arange(8000, 9000, dtype=np.int32),
        "x": np.arange(1000, dtype=np.int64)}, {})
    q = "select count(*) as n from ev where d >= date '{}'"
    assert s.sql(q.format("1991-01-01")).to_pandas().n[0] == 1000
    c0 = s.stmt_log.counter("compiles")
    # 8500 days ≈ 1993-04; exact oracle via numpy
    got = s.sql(q.format("1993-04-14")).to_pandas().n[0]
    from cloudberry_tpu.types import date_to_days

    assert got == int((np.arange(8000, 9000)
                       >= date_to_days("1993-04-14")).sum())
    assert s.stmt_log.counter("compiles") == c0


# ------------------------------------------------- non-generic opt-outs


def test_nextval_stays_non_generic():
    s = cb.Session(Config())
    s.sql("create sequence sq")
    a = s.sql("select nextval('sq') as n").to_pandas().n[0]
    b = s.sql("select nextval('sq') as n").to_pandas().n[0]
    assert (a, b) == (1, 2)  # a cached/generic replay would repeat 1
    assert not s._generic_cache  # declared itself non-generic


def test_point_match_count_change_is_a_new_variant():
    """A point lookup whose MATCH COUNT changes folds a different row
    slice shape at plan time — the signature refuses the rebind and a
    separate variant compiles; results stay exact."""
    s = _pts_session(rows=100_000)
    # duplicate key 55 once: k=55 now matches 2 rows
    t = s.catalog.table("pts")
    data = {c: np.concatenate([np.asarray(v), np.asarray(v[55:56])])
            for c, v in t.data.items()}
    t.set_data(data, {})
    q = "select k, v from pts where k = {}"
    assert s.sql(q.format(7)).num_rows() == 1
    got = s.sql(q.format(55))
    assert got.num_rows() == 2  # the 2-row variant, not a stale 1-row one
    assert s.sql(q.format(8)).num_rows() == 1


def test_growth_retry_over_generic_plan_recovers():
    """Expansion overflow on a generic-built (rewritten) plan: the retry
    loop recompiles the plan on whichever path it takes — the kept Param
    values must bake as constants there (no $params input), and the
    post-growth rebind must still work."""
    s = cb.Session(Config())
    rng = np.random.default_rng(13)
    n = 40_000
    s.sql("create table probe (k bigint, x bigint) distributed by (k)")
    s.sql("create table build (k bigint, y bigint) distributed by (k)")
    pk = np.where(rng.random(n) < 0.3, 0,
                  rng.integers(1, 30_000, n)).astype(np.int64)
    s.catalog.table("probe").set_data(
        {"k": pk, "x": np.ones(n, dtype=np.int64)}, {})
    bk = np.concatenate([np.zeros(12, dtype=np.int64),
                         np.arange(1, 2000, dtype=np.int64)])
    s.catalog.table("build").set_data(
        {"k": bk, "y": np.arange(len(bk), dtype=np.int64)}, {})
    q = ("select count(*) as n from probe, build "
         "where probe.k = build.k and probe.x > {}")
    import pandas as pd

    want = pd.DataFrame({"k": pk}).merge(
        pd.DataFrame({"k": bk}), on="k").shape[0]
    assert s.sql(q.format(0)).to_pandas().n[0] == want
    assert s.growth_events > 0  # the overflow actually tripped
    # rebind with a different literal AFTER the growth
    assert s.sql(q.format(-1)).to_pandas().n[0] == want


def test_version_bump_invalidates_generic():
    s = _pts_session(rows=40_000)
    q = "select sum(v) as sv from pts where k < {}"
    r1 = s.sql(q.format(1000)).to_pandas().sv[0]
    s.sql("insert into pts values (1000000, 123, 0.5)")
    r2 = s.sql(q.format(1000)).to_pandas().sv[0]
    assert r1 == r2 == int(((np.arange(1000) * 7) % 1000).sum())
    s.sql("insert into pts values (500, 500, 0.5)")  # inside the range
    r3 = s.sql(q.format(1000)).to_pandas().sv[0]
    assert r3 == r1 + 500


# --------------------------------- statement-cache keying audit (S1)


def test_stmt_cache_keys_on_user_params():
    """sql(query, **params) with the same text but different params must
    not share a cache entry (the prepared-statement parameter-signature
    rule)."""
    s = _pts_session(rows=1024)
    q = "select count(*) as n from pts"
    s.sql(q, tenant=1)
    s.sql(q, tenant=2)
    keys = list(s._stmt_cache)
    assert len([k for k in keys if k.startswith(q)]) == 2
    assert s._stmt_cache_key(q, {"a": 1}) != s._stmt_cache_key(q, {"a": 2})
    assert s._stmt_cache_key(q, {}) == q


def test_stmt_cache_config_epoch_invalidates():
    """A config swap (with_overrides / degraded mesh) must drop cached
    runners — the entry pins the config object identity."""
    s = _pts_session(rows=1024)
    q = "select count(*) as n from pts"
    s.sql(q)
    assert s._cached_statement(q) is not None
    s.config = s.config.with_overrides(**{"exec.use_pallas": True})
    assert s._cached_statement(q) is None  # stale under the new epoch


def test_generic_cache_cleared_on_mesh_degrade():
    s = _pts_session(nseg=8, rows=50_000)
    s.sql("select k, v from pts where k = 77")
    assert s._generic_cache
    assert s.degrade_mesh(4)
    assert not s._generic_cache


# ------------------------------------------- thread-stress the LRU (S2)


def test_stmt_cache_lru_thread_stress():
    """Concurrent sql() across threads while the 64-entry LRU evicts:
    pins the PR-2 lock-guarded LRU claim (hits mutate the dict)."""
    s = _pts_session(rows=2048)
    errors = []

    def worker(wid):
        try:
            for i in range(40):
                # > _STMT_CACHE_MAX distinct texts across threads, plus
                # a shared hot statement that must keep hitting
                key = (wid * 40 + i) % 90
                n = s.sql("select count(*) as n from pts "
                          f"where k >= {key}").to_pandas().n[0]
                assert n == 2048 - key, (key, n)
                hot = s.sql("select count(*) as n from pts").to_pandas()
                assert hot.n[0] == 2048
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert not errors
    assert len(s._stmt_cache) <= s._STMT_CACHE_MAX


def test_generic_rebind_thread_stress():
    """Concurrent rebinding of one skeleton: the generic cache is shared
    state; results must stay exact and compiles bounded."""
    s = _pts_session(rows=100_000)
    s.sql("select k, v, w from pts where k = 1")  # build once
    c0 = s.stmt_log.counter("compiles")
    errors = []

    def worker(wid):
        try:
            for i in range(25):
                key = wid * 1000 + i
                got = s.sql(f"select k, v, w from pts where k = {key}")
                df = got.to_pandas()
                assert df.k[0] == key and df.v[0] == (key * 7) % 1000
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert not errors
    assert s.stmt_log.counter("compiles") == c0  # zero recompiles
