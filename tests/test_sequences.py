"""Sequences — the gp_fastsequence / QD-owned nextval analog.

Reference: sequences live at the coordinator; segments fetch value ranges
via the '?' wire message (src/backend/commands/sequence.c:141, QD reply
postgres.c:6244). Here the coordinator-owned number line is the catalog
(storeless) or the store's locked _SEQUENCES.json (durable, shared by every
session on the root); nextval never rolls back.
"""

import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.plan.binder import BindError


@pytest.fixture
def sess():
    return cb.Session(Config(n_segments=1))


def test_nextval_basics(sess):
    sess.sql("create sequence s")
    assert sess.sql("select nextval('s') as v").to_pandas()["v"].iloc[0] == 1
    assert sess.sql("select nextval('s') as v").to_pandas()["v"].iloc[0] == 2
    assert sess.sql("select currval('s') as v").to_pandas()["v"].iloc[0] == 2


def test_start_increment(sess):
    sess.sql("create sequence s2 start with 100 increment by 5")
    vals = [sess.sql("select nextval('s2') as v").to_pandas()["v"].iloc[0]
            for _ in range(3)]
    assert vals == [100, 105, 110]


def test_setval(sess):
    sess.sql("create sequence s3")
    sess.sql("select setval('s3', 41) as v")
    assert sess.sql("select nextval('s3') as v").to_pandas()["v"].iloc[0] == 42


def test_insert_values_nextval(sess):
    sess.sql("create sequence ids")
    sess.sql("create table t (id bigint, v bigint)")
    sess.sql("insert into t values (nextval('ids'), 10), "
             "(nextval('ids'), 20), (nextval('ids'), 30)")
    df = sess.sql("select id, v from t order by id").to_pandas()
    assert list(df["id"]) == [1, 2, 3]


def test_currval_before_nextval_errors(sess):
    sess.sql("create sequence s4")
    with pytest.raises(BindError):
        sess.sql("select currval('s4')")


def test_unknown_sequence_errors(sess):
    with pytest.raises(BindError):
        sess.sql("select nextval('nope')")


def test_drop_sequence(sess):
    sess.sql("create sequence s5")
    sess.sql("drop sequence s5")
    with pytest.raises(BindError):
        sess.sql("select nextval('s5')")
    sess.sql("drop sequence if exists s5")  # no error


def test_durable_sequences_shared_across_sessions(tmp_path):
    cfg = Config(n_segments=1).with_overrides(
        **{"storage.root": str(tmp_path / "store")})
    a = cb.Session(cfg)
    a.sql("create sequence gid start with 7")
    assert a.sql("select nextval('gid') as v").to_pandas()["v"].iloc[0] == 7
    # a SECOND session on the same root continues the same number line
    b = cb.Session(cfg)
    assert b.sql("select nextval('gid') as v").to_pandas()["v"].iloc[0] == 8
    assert a.sql("select nextval('gid') as v").to_pandas()["v"].iloc[0] == 9


def test_nextval_survives_rollback(tmp_path):
    cfg = Config(n_segments=1).with_overrides(
        **{"storage.root": str(tmp_path / "store")})
    s = cb.Session(cfg)
    s.sql("create sequence r")
    s.sql("begin")
    assert s.sql("select nextval('r') as v").to_pandas()["v"].iloc[0] == 1
    s.sql("rollback")
    # PostgreSQL semantics: nextval is never undone by ROLLBACK
    assert s.sql("select nextval('r') as v").to_pandas()["v"].iloc[0] == 2


def test_explain_does_not_consume_values(sess):
    sess.sql("create sequence e1")
    sess.explain("select nextval('e1')")
    sess.sql("explain select nextval('e1')")  # plain EXPLAIN: side-effect free
    assert sess.sql("select nextval('e1') as v").to_pandas()["v"].iloc[0] == 1


def test_setval_negative(sess):
    sess.sql("create sequence n1 start with -5 increment by -1")
    assert sess.sql("select nextval('n1') as v").to_pandas()["v"].iloc[0] == -5
    sess.sql("select setval('n1', -10)")
    assert sess.sql("select nextval('n1') as v") \
        .to_pandas()["v"].iloc[0] == -11


def test_concurrent_nextval_unique(sess):
    # server handler threads share one storeless Session — allocation must
    # be race-free (catalog._seq_lock)
    import threading

    sess.sql("create sequence cc")
    got, errs = [], []

    def worker():
        try:
            for _ in range(50):
                got.append(sess.catalog.seq_nextval("cc"))
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=worker) for _ in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert not errs
    assert len(set(got)) == 200


def test_increment_zero_rejected(sess):
    with pytest.raises(Exception):
        sess.sql("create sequence z increment by 0")


def test_statement_cache_not_poisoned(sess):
    sess.sql("create sequence c1")
    q = "select nextval('c1') as v"
    assert sess.sql(q).to_pandas()["v"].iloc[0] == 1
    # the identical text must NOT replay a cached program/value
    assert sess.sql(q).to_pandas()["v"].iloc[0] == 2
