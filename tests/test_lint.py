"""graftlint seeded-bug fixtures: each pass must CATCH its target class
with the right rule id and file:line, suppressions must silence exactly
their site, and syntax-error inputs must become findings, not crashes.

The fixtures are written into tmp trees shaped like the package (the
rule scoping keys off module path suffixes), then linted with a config
whose excludes do not skip them.
"""

import textwrap

import pytest

from cloudberry_tpu.lint import run_lint
from cloudberry_tpu.lint.config import LintConfig


def _lint_tree(tmp_path, files: dict):
    """Write {relpath: source} under tmp_path and lint the tree."""
    root = tmp_path / "pkg"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return run_lint([str(root)], LintConfig(exclude_files=frozenset()))


def _by_rule(result, rule):
    return [f for f in result.unsuppressed if f.rule == rule]


# ------------------------------------------------------------ lock pass


LOCK_CYCLE_SRC = """
    import threading


    class Exchange:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def forward(self):
            with self._a:
                with self._b:
                    return 1

        def backward(self):
            with self._b:
                with self._a:
                    return 2
"""


def test_lock_order_cycle_detected(tmp_path):
    result = _lint_tree(tmp_path, {"exchange.py": LOCK_CYCLE_SRC})
    hits = _by_rule(result, "lock-order")
    assert hits, [f.render() for f in result.findings]
    assert hits[0].file.endswith("exchange.py")
    # the cycle names both locks and anchors at a real acquisition line
    assert "Exchange._a" in hits[0].message
    assert "Exchange._b" in hits[0].message
    assert hits[0].line in (12, 13, 17, 18)


def test_lock_cycle_through_cross_class_call(tmp_path):
    """The graph must see acquisitions made INSIDE a call performed
    while a lock is held (the AST-invisible half is the witness's job;
    the call-visible half is this pass's)."""
    src = """
    import threading


    class StatementLog:
        def __init__(self):
            self._lock = threading.Lock()

        def bump(self):
            with self._lock:
                return 1


    class Dispatcher:
        def __init__(self, stmt_log):
            self._cond = threading.Condition()
            self.stmt_log = stmt_log

        def tick(self):
            with self._cond:
                self.stmt_log.bump()
    """
    result = _lint_tree(tmp_path, {"sched.py": src})
    assert not _by_rule(result, "lock-order")  # acyclic is clean
    # now close the cycle: the log calls back into the dispatcher
    # while holding its own lock
    src2 = src.replace(
        """
        def bump(self):
            with self._lock:
                return 1
""",
        """
        def bump(self):
            with self._lock:
                self.dispatcher.tick()
""")
    result2 = _lint_tree(tmp_path, {"sched.py": src2})
    hits = _by_rule(result2, "lock-order")
    assert hits, [f.render() for f in result2.findings]
    assert "Dispatcher._cond" in hits[0].message
    assert "StatementLog._lock" in hits[0].message


def test_unguarded_mixed_write_detected(tmp_path):
    src = """
    import threading


    class Dispatcher:
        def __init__(self):
            self._cond = threading.Condition()
            self.stats = {"enqueued": 0, "expired": 0}

        def enqueue(self):
            with self._cond:
                self.stats["enqueued"] += 1

        def worker_tick(self):
            self.stats["expired"] += 1
    """
    result = _lint_tree(tmp_path, {"disp.py": src})
    hits = _by_rule(result, "lock-unguarded")
    assert len(hits) == 1
    assert hits[0].line == 15
    assert "Dispatcher.stats" in hits[0].message


def test_self_deadlock_reacquire_detected(tmp_path):
    src = """
    import threading


    class Store:
        def __init__(self):
            self._lock = threading.Lock()

        def size(self):
            with self._lock:
                return 1

        def snapshot(self):
            with self._lock:
                return self.size()
    """
    result = _lint_tree(tmp_path, {"store.py": src})
    hits = _by_rule(result, "lock-held-call")
    assert hits and hits[0].line == 15  # the re-acquiring call site
    assert "Store.size" in hits[0].message


def test_nested_function_writes_are_audited(tmp_path):
    """Closures/callbacks are part of the method's body for the lock
    pass (with a fresh held stack — they run later): a bare write to a
    mixed-guard attribute inside a nested def is still a finding."""
    src = """
    import threading


    class FE:
        def __init__(self):
            self._cond = threading.Condition()
            self.stats = {"done": 0}

        def locked(self):
            with self._cond:
                self.stats["done"] += 1

        def submit(self):
            def on_done():
                self.stats["done"] += 1
            return on_done
    """
    result = _lint_tree(tmp_path, {"fe.py": src})
    hits = _by_rule(result, "lock-unguarded")
    assert [f.line for f in hits] == [16]  # the write inside on_done


def test_annotated_lock_and_stamp_forms_recognized(tmp_path):
    """`self._lock: threading.Lock = threading.Lock()` is discovered,
    and `retryable: bool = True` counts as an explicit stamp."""
    src = """
    import threading

    _RETRYABLE_NAMES = frozenset({"Typed"})


    class StatementError(RuntimeError):
        retryable = False


    class Typed(StatementError):
        retryable: bool = True


    class C:
        def __init__(self):
            self._lock: threading.Lock = threading.Lock()
            self.n = 0

        def locked(self):
            with self._lock:
                self.n += 1

        def bare(self):
            self.n += 1
    """
    result = _lint_tree(tmp_path, {"lifecycle.py": src})
    assert not _by_rule(result, "tax-retryable-missing")
    assert not _by_rule(result, "tax-retryable-mismatch")
    hits = _by_rule(result, "lock-unguarded")
    assert [f.line for f in hits] == [25]  # the annotated lock counted


def test_attribute_base_subclass_still_audited(tmp_path):
    """`class X(lifecycle.StatementError)` cannot dodge the stamp
    rules by importing the module instead of the class."""
    src = """
    _RETRYABLE_NAMES = frozenset({"StatementTimeout"})


    class StatementError(RuntimeError):
        retryable = False


    class StatementTimeout(StatementError):
        retryable = True
    """
    other = """
    from pkg import lifecycle


    class NodeGone(lifecycle.StatementError):
        pass
    """
    result = _lint_tree(tmp_path, {"lifecycle.py": src,
                                   "errs.py": other})
    hits = _by_rule(result, "tax-retryable-missing")
    assert len(hits) == 1 and "NodeGone" in hits[0].message


def test_suppression_silences_only_its_site(tmp_path):
    src = """
    import threading


    class Dispatcher:
        def __init__(self):
            self._cond = threading.Condition()
            self.stats = {"a": 0, "b": 0}

        def locked_write(self):
            with self._cond:
                self.stats["a"] += 1

        def bare_one(self):
            # graftlint: ignore[lock-unguarded] single-owner worker field
            self.stats["a"] += 1

        def bare_two(self):
            self.stats["b"] += 1
    """
    result = _lint_tree(tmp_path, {"disp.py": src})
    hits = _by_rule(result, "lock-unguarded")
    assert len(hits) == 1 and hits[0].line == 19
    sup = [f for f in result.suppressed if f.rule == "lock-unguarded"]
    assert len(sup) == 1 and sup[0].line == 16
    assert sup[0].justification == "single-owner worker field"


def test_bare_suppression_tag_fails_the_gate(tmp_path):
    """A matching suppression WITHOUT a justification is itself a
    finding — the CLI/CI gate enforces the policy, not just the test
    suite (they must never disagree about a tree)."""
    src = """
    import threading


    class C:
        def __init__(self):
            self._cond = threading.Condition()
            self.n = 0

        def locked(self):
            with self._cond:
                self.n += 1

        def bare(self):
            # graftlint: ignore[lock-unguarded]
            self.n += 1
    """
    result = _lint_tree(tmp_path, {"c.py": src})
    assert not _by_rule(result, "lock-unguarded")  # suppression holds
    hits = _by_rule(result, "unjustified-suppression")
    assert len(hits) == 1 and hits[0].line == 15  # the comment's line
    assert result.unsuppressed  # → CLI exit 1, gate ok:false


def test_stale_suppression_is_a_finding(tmp_path):
    src = """
    import threading


    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0

        def bump(self):
            # graftlint: ignore[lock-unguarded] was racy once, fixed
            self.n += 1
    """
    result = _lint_tree(tmp_path, {"c.py": src})
    hits = _by_rule(result, "unused-suppression")
    assert len(hits) == 1 and hits[0].line == 11  # the comment's line
    assert "lock-unguarded" in hits[0].message


# ---------------------------------------------------------- purity pass


def test_tracer_item_detected(tmp_path):
    src = """
    import jax
    import jax.numpy as jnp
    import numpy as np


    @jax.jit
    def bad_kernel(x):
        total = jnp.sum(x)
        return total.item()
    """
    result = _lint_tree(tmp_path, {"exec/kernels.py": src})
    hits = _by_rule(result, "purity-coerce")
    assert hits, [f.render() for f in result.findings]
    assert hits[0].line == 10
    assert hits[0].file.endswith("exec/kernels.py")


def test_host_np_and_tracer_branch_detected(tmp_path):
    src = """
    import jax
    import jax.numpy as jnp
    import numpy as np


    @jax.jit
    def bad(x):
        y = np.cumsum(x)
        if jnp.any(x > 0):
            y = y + 1
        return y
    """
    result = _lint_tree(tmp_path, {"exec/kernels.py": src})
    assert [f.line for f in _by_rule(result, "purity-host-np")] == [9]
    assert [f.line for f in _by_rule(result, "purity-branch")] == [10]


def test_f32_accum_of_int64_detected_and_limb_exempt(tmp_path):
    src = """
    import jax
    import jax.numpy as jnp


    @jax.jit
    def sum_money(vals_int64):
        return jnp.sum(vals_int64.astype(jnp.float32))


    @jax.jit
    def sum_money_limbs(vals_int64):
        return jnp.sum(vals_int64.astype(jnp.float32))
    """
    result = _lint_tree(tmp_path, {"exec/kernels.py": src})
    hits = _by_rule(result, "purity-f32-accum")
    assert [f.line for f in hits] == [8]  # the limb variant is exempt


def test_host_function_in_kernel_module_not_flagged(tmp_path):
    """np.* in a plain host helper (the joinindex numpy mirror) is
    legal — only traced bodies are kernel scope."""
    src = """
    import numpy as np


    def host_mirror(arr):
        order = np.argsort(arr)
        return float(order[0])
    """
    result = _lint_tree(tmp_path, {"exec/kernels.py": src})
    assert not _by_rule(result, "purity-host-np")
    assert not _by_rule(result, "purity-coerce")


# -------------------------------------------------------- taxonomy pass


def test_unstamped_wire_error_detected(tmp_path):
    src = """
    def refuse(reason):
        return {"ok": False, "etype": "ValueError",
                "error": f"refused: {reason}"}


    def refuse_stamped(reason):
        return {"ok": False, "etype": "ValueError", "retryable": False,
                "error": f"refused: {reason}"}
    """
    result = _lint_tree(tmp_path, {"serve/server.py": src})
    hits = _by_rule(result, "tax-unstamped")
    assert [f.line for f in hits] == [3]


def test_retryable_name_must_exist(tmp_path):
    src = """
    _RETRYABLE_NAMES = frozenset({
        "StatementTimeout", "NoSuchError",
    })


    class StatementError(RuntimeError):
        retryable = False


    class StatementTimeout(StatementError):
        retryable = True
    """
    result = _lint_tree(tmp_path, {"lifecycle.py": src})
    hits = _by_rule(result, "tax-name-unknown")
    assert len(hits) == 1
    assert "NoSuchError" in hits[0].message


def test_retryable_stamp_registry_mismatch(tmp_path):
    src = """
    _RETRYABLE_NAMES = frozenset({"StatementTimeout"})


    class StatementError(RuntimeError):
        retryable = False


    class StatementTimeout(StatementError):
        retryable = True


    class ServerDraining(StatementError):
        retryable = True  # but NOT in the registry


    class Unstamped(StatementError):
        pass
    """
    result = _lint_tree(tmp_path, {"lifecycle.py": src})
    mism = _by_rule(result, "tax-retryable-mismatch")
    assert len(mism) == 1 and "ServerDraining" in mism[0].message
    missing = _by_rule(result, "tax-retryable-missing")
    assert len(missing) == 1 and "Unstamped" in missing[0].message


# ------------------------------------------------------------ seam pass


def test_orphan_fault_point_detected(tmp_path):
    files = {
        "utils/faultinject.py": """
            INVENTORY = frozenset({"known_seam", "stale_seam"})


            def fault_point(name):
                return False
        """,
        "exec/thing.py": """
            from pkg.utils.faultinject import fault_point


            def step():
                fault_point("known_seam")
                fault_point("orphan_seam")
        """,
    }
    result = _lint_tree(tmp_path, files)
    unknown = _by_rule(result, "seam-unknown")
    assert len(unknown) == 1
    assert "orphan_seam" in unknown[0].message
    assert unknown[0].file.endswith("exec/thing.py")
    assert unknown[0].line == 7
    stale = _by_rule(result, "seam-stale")
    assert len(stale) == 1 and "stale_seam" in stale[0].message


def test_unbounded_loop_without_cancel_seam(tmp_path):
    src = """
    def run_adaptive(execute, check_cancel):
        while True:
            try:
                return execute()
            except RuntimeError:
                continue


    def run_adaptive_good(execute, check_cancel):
        while True:
            check_cancel()
            try:
                return execute()
            except RuntimeError:
                continue


    def plan_walk(node):
        out = []
        while True:
            if isinstance(node, tuple):
                out.append(node)
                node = node[0]
            else:
                return out


    def busy_spin(flag):
        while True:
            if flag[0]:
                break
    """
    result = _lint_tree(tmp_path, {"exec/tiled.py": src})
    hits = _by_rule(result, "seam-loop")
    # good loop + pure walk exempt; the call-free spin is NOT a walk
    assert [f.line for f in hits] == [3, 30]


# ------------------------------------------------------- driver behavior


def test_syntax_error_is_a_finding_not_a_crash(tmp_path):
    result = _lint_tree(tmp_path, {
        "broken.py": """
            def f(:
                return 1
        """,
        "fine.py": "X = 1\n",
    })
    hits = _by_rule(result, "syntax")
    assert len(hits) == 1
    assert hits[0].file.endswith("broken.py")
    assert hits[0].line >= 1


def test_default_scope_excludes_tests_and_pycache(tmp_path):
    root = tmp_path / "pkg"
    (root / "tests").mkdir(parents=True)
    (root / "__pycache__").mkdir()
    (root / "tests" / "test_x.py").write_text("def f(:\n")
    (root / "__pycache__" / "junk.py").write_text("def f(:\n")
    (root / "ok.py").write_text("X = 1\n")
    result = run_lint([str(root)])  # DEFAULT config
    assert [m.relpath for m in result.modules] == ["pkg/ok.py"]
    assert not result.findings


def test_cli_exit_codes(tmp_path):
    from cloudberry_tpu.lint.__main__ import main

    clean = tmp_path / "clean"
    clean.mkdir()
    (clean / "a.py").write_text("X = 1\n")
    assert main([str(clean)]) == 0
    dirty = tmp_path / "dirty"
    dirty.mkdir()
    (dirty / "b.py").write_text("def f(:\n")
    assert main([str(dirty), "--json"]) == 1
    assert main([str(tmp_path / "nope")]) == 2


# ---------------------------------------------------------- the witness


def test_witness_fires_on_constructed_violation():
    """A reversed acquisition of two DECLARED locks is recorded with
    the offending pair; correct order stays silent."""
    from cloudberry_tpu.exec.instrument import StatementLog
    from cloudberry_tpu.lifecycle import CancelToken, CircuitBreaker
    from cloudberry_tpu.lint import witness

    witness.install()
    try:
        witness.reset_violations()
        assert witness.witnessed_site_count() > 0
        cb = CircuitBreaker()           # rank 2
        log = StatementLog()            # rank 3
        tok = CancelToken()             # rank 4
        with cb._lock:
            with log._lock:
                with tok._lock:
                    pass
        assert witness.violations() == []
        with tok._lock:
            with cb._lock:
                pass
        vs = witness.violations()
        assert len(vs) == 1
        assert vs[0].acquiring == "CircuitBreaker._lock"
        assert vs[0].holding[-1][0] == "CancelToken._lock"
        # cascade visibility: with the stack already non-monotonic,
        # a further same-rank acquisition is STILL recorded (the check
        # compares against every held lock, not just the top)
        witness.reset_violations()
        log2 = StatementLog()           # rank 3
        with tok._lock:                 # r4
            with log._lock:             # r3 — violation 1
                with log2._lock:        # r3 vs held r4/r3 — violation 2
                    pass
        assert len(witness.violations()) == 2
    finally:
        witness.uninstall()
        witness.reset_violations()


def test_witness_condition_wait_reacquire_is_clean():
    """Condition.wait releases and re-acquires through the proxy: no
    phantom violations, and the held stack stays balanced."""
    import threading as _t

    from cloudberry_tpu.lint import witness
    from cloudberry_tpu.sched.tenancy import TenantScheduler

    witness.install()
    try:
        witness.reset_violations()
        from cloudberry_tpu.config import get_config

        sched = TenantScheduler(get_config().tenancy)
        done = []

        def consumer():
            for _ in range(20):
                got = sched.pick(4)
                done.extend(got)

        threads = [_t.Thread(target=consumer) for _ in range(2)]
        for th in threads:
            th.start()
        for i in range(10):
            sched.enqueue("gold", f"item{i}")
        for th in threads:
            th.join()
        assert witness.violations() == []
    finally:
        witness.uninstall()
        witness.reset_violations()


def test_witness_wraps_import_time_module_locks():
    """Module-global locks (faultinject._lock, sharedcache._tier_lock)
    exist before install() can patch threading — the witness swaps the
    module attribute in place, so their rank-4 leaf discipline is
    runtime-enforced too, and uninstall() restores the raw lock."""
    from cloudberry_tpu.lint import witness
    from cloudberry_tpu.lint.witness import WitnessedLock
    from cloudberry_tpu.utils import faultinject

    witness.install()
    try:
        witness.reset_violations()
        assert isinstance(faultinject._lock, WitnessedLock)
        # the seam still works through the proxy
        faultinject.fault_point("lint_witness_probe_seam")
        assert "lint_witness_probe_seam" in faultinject.known_fault_points()
        # holding the leaf lock while taking a higher-tier lock fires
        from cloudberry_tpu.lifecycle import CircuitBreaker

        cb = CircuitBreaker()
        with faultinject._lock:
            with cb._lock:
                pass
        assert any(v.acquiring == "CircuitBreaker._lock"
                   for v in witness.violations())
    finally:
        witness.uninstall()
        witness.reset_violations()
    assert not isinstance(faultinject._lock, WitnessedLock)


def test_witness_rlock_reentry_allowed():
    import _thread

    from cloudberry_tpu.lint import witness

    witness.install()
    try:
        witness.reset_violations()
        # an RLock created at a declared site; re-entry must not trip
        from cloudberry_tpu.lint.witness import WitnessedLock

        wl = WitnessedLock(_thread.RLock(), "X", 2, reentrant=True)
        with wl:
            with wl:
                pass
        assert witness.violations() == []
    finally:
        witness.uninstall()
        witness.reset_violations()


# ------------------------------------------------------- planprops pass


PLANPROPS_NODES_SRC = """
    class PlanNode:
        pass


    class PGood(PlanNode):
        pass


    class PRogue(PlanNode):
        pass
"""

PLANPROPS_VERIFY_SRC = """
    RULES = {}


    def rule(*names, doc=""):
        def deco(fn):
            for n in names:
                RULES[n] = fn
            return fn
        return deco


    @rule("PGood")
    def _r_good(v, node, kids, path):
        return None


    @rule("PGone", doc="stale")
    def _r_gone(v, node, kids, path):
        return None
"""


def test_planprops_unruled_and_orphan_detected(tmp_path):
    result = _lint_tree(tmp_path, {
        "plan/nodes.py": PLANPROPS_NODES_SRC,
        "plan/verify.py": PLANPROPS_VERIFY_SRC,
    })
    unruled = _by_rule(result, "planprops-unruled")
    assert len(unruled) == 1, [f.render() for f in result.findings]
    assert unruled[0].file.endswith("plan/nodes.py")
    assert "PRogue" in unruled[0].message
    # anchored at the class definition line
    src_lines = textwrap.dedent(PLANPROPS_NODES_SRC).splitlines()
    assert "class PRogue" in src_lines[unruled[0].line - 1]
    orphan = _by_rule(result, "planprops-orphan-rule")
    assert len(orphan) == 1
    assert orphan[0].file.endswith("plan/verify.py")
    assert "PGone" in orphan[0].message


def test_planprops_single_file_invocation_does_not_false_positive(
        tmp_path):
    """Linting plan/nodes.py WITHOUT verify.py in the set must not
    declare every class unruled (and vice versa for the orphan
    direction)."""
    result = _lint_tree(tmp_path / "a",
                        {"plan/nodes.py": PLANPROPS_NODES_SRC})
    assert not _by_rule(result, "planprops-unruled")
    result = _lint_tree(tmp_path / "b",
                        {"plan/verify.py": PLANPROPS_VERIFY_SRC})
    assert not _by_rule(result, "planprops-orphan-rule")


def test_planprops_ckpt_mode_drift_detected(tmp_path):
    result = _lint_tree(tmp_path, {
        "exec/tiled.py": """
            CHECKPOINT_MODES = ("agg", "zap")
        """,
        "exec/recovery.py": """
            REPLACEABLE = {
                "agg": "round-robin partials",
                "sort": "pooled",
            }
        """,
    })
    hits = _by_rule(result, "planprops-ckpt-mode")
    msgs = " | ".join(f.message for f in hits)
    assert len(hits) == 2, [f.render() for f in result.findings]
    assert "'zap'" in msgs          # checkpoints, no re-placement rule
    assert "'sort'" in msgs         # stale re-placement rule


def test_planprops_clean_tables_are_silent(tmp_path):
    result = _lint_tree(tmp_path, {
        "plan/nodes.py": """
            class PlanNode:
                pass


            class PGood(PlanNode):
                pass
        """,
        "plan/verify.py": """
            def rule(*names, doc=""):
                def deco(fn):
                    return fn
                return deco


            @rule("PGood")
            def _r_good(v, node, kids, path):
                return None
        """,
        "exec/tiled.py": 'CHECKPOINT_MODES = ("agg",)\n',
        "exec/recovery.py": 'REPLACEABLE = {"agg": "rr"}\n',
    })
    for r in ("planprops-unruled", "planprops-orphan-rule",
              "planprops-ckpt-mode"):
        assert not _by_rule(result, r), r
