import numpy as np
import pandas as pd

from cloudberry_tpu.columnar import ColumnBatch, StringDictionary
from cloudberry_tpu.types import DType, Schema


def test_dictionary_roundtrip():
    d = StringDictionary()
    codes = d.encode(np.array(["b", "a", "b", "c"]))
    assert codes.tolist() == [0, 1, 0, 2]
    assert d.decode(codes).tolist() == ["b", "a", "b", "c"]
    assert d.code_of("a") == 1
    assert d.code_of("zzz") == -1


def test_dictionary_like_and_rank():
    d = StringDictionary(["apple", "banana", "cherry"])
    t = d.like_table("%an%")
    assert t.tolist() == [False, True, False]
    r = d.rank_table()
    assert r.tolist() == [0, 1, 2]
    d2 = StringDictionary(["z", "a", "m"])
    r2 = d2.rank_table()
    assert r2[1] < r2[2] < r2[0]


def test_batch_from_pandas_roundtrip():
    df = pd.DataFrame({
        "k": np.array([1, 2, 3], dtype=np.int64),
        "v": np.array([1.5, 2.5, 3.5]),
        "s": ["x", "y", "x"],
        "d": pd.to_datetime(["1995-01-01", "1996-06-15", "1992-12-31"]),
    })
    b = ColumnBatch.from_pandas(df, capacity=8)
    assert b.capacity == 8
    assert b.num_rows() == 3
    assert b.columns["s"].dtype == np.int32
    out = b.to_pandas()
    assert out["k"].tolist() == [1, 2, 3]
    assert out["s"].tolist() == ["x", "y", "x"]
    assert str(out["d"].iloc[1])[:10] == "1996-06-15"


def test_schema_of():
    s = Schema.of(a=DType.INT64, b=DType.STRING)
    assert s.names == ["a", "b"]
    assert "a" in s and "c" not in s
