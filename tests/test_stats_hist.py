"""Histograms, autostats, and z-order clustering (round 4).

- equi-depth histograms from ANALYZE feed range selectivity
  (pg_statistic histogram_bounds role): on skewed data the histogram
  estimate must beat uniform [min,max] interpolation by an order of
  magnitude (plan/cost.py:_hist_le_frac).
- autostats (gp_autostats_mode analog, autostats.c:283): DML on a
  never-analyzed table triggers ANALYZE; "on_change" re-triggers on
  row-count drift.
- CLUSTER t BY (a, b): z-order rewrite (zorder_clustering.cc role) makes
  micro-partition min/max tight, so pruning skips most files.
"""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config


def _mk(**ov):
    over = {"n_segments": 1}
    over.update(ov)
    return cb.Session(get_config().with_overrides(**over))


# ------------------------------------------------------------ histograms


def _filter_estimate(s, q):
    from cloudberry_tpu.plan import cost
    from cloudberry_tpu.plan import nodes as N
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sql.parser import parse_sql

    res = plan_statement(parse_sql(q), s, {})
    node = res.plan
    while node is not None and not isinstance(node, N.PFilter):
        node = node.children()[0] if node.children() else None
    assert node is not None, "no filter in plan"
    return cost.estimate_rows(node, s.catalog)


def test_histogram_beats_uniform_on_skew():
    s = _mk(**{"planner.autostats": "none"})
    s.sql("create table sk (v bigint) distributed randomly")
    # 90% of rows in [0, 10], 10% spread to 1000: uniform interpolation
    # puts P(v <= 10) at ~1%, reality is ~90%
    rng = np.random.default_rng(7)
    vals = np.concatenate([rng.integers(0, 11, 9000),
                           rng.integers(11, 1001, 1000)])
    s.catalog.table("sk").set_data({"v": vals.astype(np.int64)})

    uniform_est = _filter_estimate(s, "select * from sk where v <= 10")
    s.sql("analyze sk")
    hist_est = _filter_estimate(s, "select * from sk where v <= 10")
    true_rows = int((vals <= 10).sum())
    # uniform is off by ~80x; the histogram must land within 20%
    assert uniform_est < true_rows * 0.2
    assert abs(hist_est - true_rows) < true_rows * 0.2
    # and the complementary estimate stays consistent
    hi_est = _filter_estimate(s, "select * from sk where v > 10")
    assert abs(hi_est - (len(vals) - true_rows)) < len(vals) * 0.05


def test_histogram_persists_cold(tmp_path):
    a = cb.Session(get_config().with_overrides(
        **{"storage.root": str(tmp_path)}))
    a.sql("create table h (x bigint)")
    a.sql("insert into h values " +
          ",".join(f"({i * i})" for i in range(100)))
    a.sql("analyze h")
    assert a.catalog.table("h").stats.hist["x"]
    b = cb.Session(get_config().with_overrides(
        **{"storage.root": str(tmp_path)}))
    t = b.catalog.table("h")
    assert t.cold and len(t.stats.hist["x"]) == t.HIST_BUCKETS + 1
    assert t.stats.analyzed_rows == 100


# ------------------------------------------------------------- autostats


def test_autostats_on_no_stats():
    s = _mk()  # default mode: on_no_stats
    s.sql("create table aa (x bigint)")
    s.sql("insert into aa values (1),(2),(3)")
    t = s.catalog.table("aa")
    assert t.stats.analyzed_rows == 3  # DML triggered ANALYZE
    assert t.stats.ndv["x"] == 3
    s.sql("insert into aa values (4)")
    # on_no_stats: no re-trigger once stats exist
    assert t.stats.analyzed_rows == 3


def test_autostats_on_change():
    s = _mk(**{"planner.autostats": "on_change",
               "planner.autostats_threshold": 0.5})
    s.sql("create table ac (x bigint)")
    s.sql("insert into ac values (1),(2),(3),(4)")
    t = s.catalog.table("ac")
    assert t.stats.analyzed_rows == 4
    s.sql("insert into ac values (5)")  # +25% < 50% threshold
    assert t.stats.analyzed_rows == 4
    s.sql("insert into ac values (6),(7),(8)")  # 8 rows: +100% drift
    assert t.stats.analyzed_rows == 8


def test_autostats_none():
    s = _mk(**{"planner.autostats": "none"})
    s.sql("create table an (x bigint)")
    s.sql("insert into an values (1)")
    assert s.catalog.table("an").stats.analyzed_rows == -1


# ------------------------------------------------------------ clustering


def test_zorder_key_locality():
    from cloudberry_tpu.utils.zorder import zorder_key

    # the four quadrants of (x, y) space must occupy disjoint key ranges
    rng = np.random.default_rng(3)
    x = rng.integers(0, 1000, 4000)
    y = rng.integers(0, 1000, 4000)
    k = zorder_key([x, y])
    order = np.argsort(k)
    half = len(k) // 2
    # the low-key half must be exactly the low-x AND... not strictly; but
    # the top-left quadrant (x<500, y<500) sorts entirely before the
    # bottom-right (x>=500, y>=500): their z-keys differ in the top bits
    q_ll = k[(x < 500) & (y < 500)]
    q_hh = k[(x >= 500) & (y >= 500)]
    assert q_ll.max() < q_hh.min()
    assert len(order) == half * 2


def test_cluster_sharpens_pruning(tmp_path):
    """After CLUSTER BY (a, b), a range predicate on either column must
    prune most micro-partition files; before, random order means every
    file spans the full range and nothing prunes."""
    s = cb.Session(get_config().with_overrides(
        **{"storage.root": str(tmp_path),
           "storage.rows_per_partition": 512,
           "planner.autostats": "none"}))
    s.sql("create table zt (a bigint, b bigint, payload bigint)")
    rng = np.random.default_rng(11)
    n = 16384
    s.catalog.table("zt").set_data({
        "a": rng.integers(0, 10_000, n).astype(np.int64),
        "b": rng.integers(0, 10_000, n).astype(np.int64),
        "payload": np.arange(n, dtype=np.int64)})

    def pruned(q):
        fresh = cb.Session(get_config().with_overrides(
            **{"storage.root": str(tmp_path), "planner.autostats": "none"}))
        from cloudberry_tpu.exec import executor as X
        from cloudberry_tpu.plan.planner import plan_statement
        from cloudberry_tpu.sql.parser import parse_sql

        res = plan_statement(parse_sql(q), fresh, {})
        scan = next(iter(X.scans_of(res.plan)))
        rep = scan._prune_report
        return rep["skipped_minmax"], rep["candidates"]

    q = "select sum(payload) from zt where a <= 500"
    skipped_before, cand = pruned(q)
    assert cand == 32  # 16384 / 512
    assert skipped_before == 0  # random order: every file spans all of a

    s.sql("cluster zt by (a, b)")
    skipped_a, cand2 = pruned(q)
    assert cand2 == 32
    # ~5% of the value space -> the z-curve confines it to a few files
    assert skipped_a >= cand2 // 2, skipped_a
    # pruning works on the SECOND clustered column too (the z-order win
    # over plain sorting)
    skipped_b, _ = pruned("select sum(payload) from zt where b <= 500")
    assert skipped_b >= cand2 // 4, skipped_b
    # correctness: clustered result == original (payload rode the permute)
    t = s.catalog.table("zt")
    expect = int(np.sum(t.data["payload"][t.data["a"] <= 500]))
    fresh = cb.Session(get_config().with_overrides(
        **{"storage.root": str(tmp_path), "planner.autostats": "none"}))
    got = fresh.sql(q).to_pandas().iloc[0, 0]
    assert int(got) == expect


def test_cluster_rejects_bad_columns():
    from cloudberry_tpu.plan.binder import BindError

    s = _mk()
    s.sql("create table cb1 (x bigint, s text)")
    s.sql("insert into cb1 values (1, 'a')")
    with pytest.raises(BindError):
        s.sql("cluster cb1 by (nope)")
    with pytest.raises(BindError):
        s.sql("cluster cb1 by (s)")
