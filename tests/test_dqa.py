"""Distinct-qualified aggregates — the multi-DQA / TupleSplit surface.

The reference splits input tuples per-DQA and runs 2/3-stage plans
(src/backend/executor/nodeTupleSplit.c, src/backend/cdb/
cdbgroupingpaths.c); here each distinct argument class plans as its own
inner-distinct + outer-aggregate subplan over a shared scan, zipped with
1:1 joins on the group keys (plan/binder.py _plan_dqa). These tests pin
the semantics against a pandas oracle in single and 8-segment modes —
including the shapes the pre-rewrite code got WRONG (two different
distinct arguments; sum/avg DISTINCT silently dropping the qualifier).
"""

import numpy as np
import pandas as pd
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config


def _mk(nseg=1):
    s = cb.Session(Config(n_segments=nseg)) if nseg > 1 else cb.Session()
    s.sql("create table t (k bigint, a bigint, b bigint, c text) "
          "distributed by (a)")
    s.sql("insert into t values "
          "(1, 1, 10, 'x'), (1, 1, 20, 'x'), (1, 2, 10, 'y'), "
          "(1, null, 20, null), (2, 3, 30, 'z'), (2, 3, 30, 'z'), "
          "(2, null, null, null), (null, 4, 40, 'x'), (null, 4, null, 'w')")
    return s


@pytest.fixture(scope="module", params=[1, 8], ids=["single", "dist8"])
def s(request):
    return _mk(request.param)


def _pdf():
    return pd.DataFrame({
        "k": [1, 1, 1, 1, 2, 2, 2, None, None],
        "a": [1, 1, 2, None, 3, 3, None, 4, 4],
        "b": [10, 20, 10, 20, 30, 30, None, 40, None],
        "c": ["x", "x", "y", None, "z", "z", None, "x", "w"],
    })


def test_two_distinct_args(s):
    """Two different DISTINCT arguments must count independently — the
    old single-split plan counted distinct (a, b) PAIRS."""
    df = s.sql("select k, count(distinct a) as ca, count(distinct b) as cb_"
               " from t group by k order by k").to_pandas()
    o = _pdf().groupby("k", dropna=False).agg(
        ca=("a", "nunique"), cb_=("b", "nunique")).reset_index()
    assert list(df["ca"]) == list(o["ca"])
    assert list(df["cb_"]) == list(o["cb_"])


def test_mixed_distinct_and_plain(s):
    df = s.sql("select k, count(distinct a) as ca, sum(b) as sb, "
               "count(*) as n, min(b) as mb from t group by k "
               "order by k").to_pandas()
    o = _pdf().groupby("k", dropna=False).agg(
        ca=("a", "nunique"), sb=("b", "sum"), n=("k", "size"),
        mb=("b", "min")).reset_index()
    assert list(df["ca"]) == list(o["ca"])
    assert [x if x is not None else None for x in df["sb"]] == \
        [None if pd.isna(x) else x for x in o["sb"]]
    assert list(df["n"]) == list(o["n"])


def test_sum_avg_distinct(s):
    """sum/avg(DISTINCT x) aggregate the distinct SET (previously the
    qualifier was silently dropped)."""
    df = s.sql("select k, sum(distinct a) as sd, avg(distinct a) as ad "
               "from t group by k order by k").to_pandas()
    o = _pdf().groupby("k", dropna=False)["a"].agg(
        sd=lambda x: x.dropna().drop_duplicates().sum(),
        ad=lambda x: x.dropna().drop_duplicates().mean()).reset_index()
    assert list(df["sd"]) == list(o["sd"])
    assert np.allclose(list(df["ad"]), list(o["ad"]))


def test_global_mixed(s):
    df = s.sql("select count(distinct a) as ca, count(distinct c) as cc, "
               "sum(b) as sb, count(*) as n from t").to_pandas()
    p = _pdf()
    assert df["ca"][0] == p["a"].nunique()
    assert df["cc"][0] == p["c"].nunique()
    assert df["sb"][0] == p["b"].sum()
    assert df["n"][0] == len(p)


def test_global_empty_input(s):
    s.sql("create table if not exists e0 (k bigint, a bigint, b bigint)")
    df = s.sql("select count(distinct a) as ca, sum(b) as sb, "
               "count(*) as n from e0").to_pandas()
    assert df["ca"][0] == 0 and df["sb"][0] is None and df["n"][0] == 0


def test_string_distinct_arg(s):
    df = s.sql("select k, count(distinct c) as cc, count(c) as nc "
               "from t group by k order by k").to_pandas()
    o = _pdf().groupby("k", dropna=False)["c"].agg(
        cc="nunique", nc="count").reset_index()
    assert list(df["cc"]) == list(o["cc"])
    assert list(df["nc"]) == list(o["nc"])


def test_having_and_exprs_over_mixed(s):
    df = s.sql("select k, count(distinct a) + count(*) as x from t "
               "group by k having sum(b) > 25 order by k").to_pandas()
    p = _pdf()
    o = p.groupby("k", dropna=False).agg(
        ca=("a", "nunique"), n=("k", "size"), sb=("b", "sum"))
    o = o[o["sb"] > 25]
    assert list(df["x"]) == list(o["ca"] + o["n"])


def test_order_by_distinct_agg(s):
    df = s.sql("select k, count(distinct b) as cb_ from t group by k "
               "order by count(distinct b) desc, k").to_pandas()
    vals = list(df["cb_"])
    assert vals == sorted(vals, reverse=True)


def test_avg_distinct_nullable(s):
    """avg(DISTINCT nullable) decomposes into the sum/count DQA pair."""
    df = s.sql("select avg(distinct b) as ab from t").to_pandas()
    want = _pdf()["b"].dropna().drop_duplicates().mean()
    assert np.isclose(df["ab"][0], want)


def test_min_max_distinct_noop(s):
    df = s.sql("select k, min(distinct a) as mn, max(distinct a) as mx "
               "from t group by k order by k").to_pandas()
    o = _pdf().groupby("k", dropna=False)["a"].agg(
        mn="min", mx="max").reset_index()
    assert [x for x in df["mn"]] == \
        [None if pd.isna(x) else x for x in o["mn"]]
    assert [x for x in df["mx"]] == \
        [None if pd.isna(x) else x for x in o["mx"]]


def test_duplicate_distinct_calls_fold(s):
    """The same DISTINCT aggregate written twice binds once."""
    df = s.sql("select count(distinct a) as x, count(distinct a) as y "
               "from t").to_pandas()
    assert df["x"][0] == df["y"][0] == _pdf()["a"].nunique()


def test_random_mixed_oracle():
    rng = np.random.default_rng(7)
    n = 3000
    ks = rng.integers(0, 40, n)
    as_ = rng.integers(0, 150, n).astype(object)
    bs = rng.integers(0, 500, n).astype(object)
    as_[rng.random(n) < 0.1] = None
    bs[rng.random(n) < 0.1] = None
    s = cb.Session(Config(n_segments=8))
    s.sql("create table r (k bigint, a bigint, b bigint) "
          "distributed by (k)")
    rows = ",".join(
        f"({k},{'null' if a is None else a},{'null' if b is None else b})"
        for k, a, b in zip(ks, as_, bs))
    s.sql(f"insert into r values {rows}")
    df = s.sql("select k, count(distinct a) as ca, count(distinct b) as cb_,"
               " sum(a) as sa, count(*) as n, sum(distinct b) as sdb "
               "from r group by k order by k").to_pandas()
    p = pd.DataFrame({"k": ks, "a": as_, "b": bs})
    o = p.groupby("k").agg(
        ca=("a", "nunique"), cb_=("b", "nunique"),
        sa=("a", lambda x: x.dropna().sum()), n=("k", "size"),
        sdb=("b", lambda x: x.dropna().drop_duplicates().sum()),
    ).reset_index()
    assert list(df["ca"]) == list(o["ca"])
    assert list(df["cb_"]) == list(o["cb_"])
    assert list(df["sa"]) == list(o["sa"])
    assert list(df["n"]) == list(o["n"])
    assert list(df["sdb"]) == list(o["sdb"])
