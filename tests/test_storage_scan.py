"""Storage on the query scan path (VERDICT #4).

Cold tables live only in micro-partition files; scans bind to pruned
partition lists at plan time (plan/scanprune.py), read ONLY referenced
columns host-side, and skip files via manifest min/max (no IO) and footer
bloom filters (footer-only IO) — the PAX sparse-filter / PartitionSelector
moves (contrib/pax_storage micro_partition_stats.cc,
nodePartitionSelector.c).
"""

import os

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.storage import micropartition as mp


def _cfg(tmp_path, nseg=1, rpp=50):
    return Config(n_segments=nseg).with_overrides(**{
        "storage.root": str(tmp_path / "store"),
        "storage.rows_per_partition": rpp,
    })


def _mk_store(tmp_path, nseg=1, rpp=50):
    s = cb.Session(_cfg(tmp_path, nseg, rpp))
    s.sql("create table t (a bigint, b bigint, c text, d double) "
          "distributed by (a)")
    rows = ",".join(f"({i}, {i * 10}, '{'xyz'[i % 3]}', {i}.5)"
                    for i in range(200))
    s.sql(f"insert into t values {rows}")
    return s


def _scan_of(session, sql):
    from cloudberry_tpu.plan.binder import Binder
    from cloudberry_tpu.plan.planner import _optimize
    from cloudberry_tpu.sql.parser import parse_sql

    plan = _optimize(Binder(session.catalog).bind_query(parse_sql(sql)),
                     session)
    scans = []

    def walk(n):
        if isinstance(n, N.PScan):
            scans.append(n)
        for c in n.children():
            walk(c)

    walk(plan)
    assert len(scans) == 1
    return scans[0]


def test_durability_across_sessions(tmp_path):
    _mk_store(tmp_path)
    s2 = cb.Session(_cfg(tmp_path))
    t = s2.catalog.table("t")
    assert t.cold and t.num_rows == 200
    assert s2.sql("select count(*) as n from t").to_pandas().n[0] == 200
    assert t.cold  # queries never forced materialization


def test_minmax_file_skip_counts(tmp_path):
    _mk_store(tmp_path)  # 200 rows / 50 per part = 4 partitions
    s2 = cb.Session(_cfg(tmp_path))
    scan = _scan_of(s2, "select b from t where a >= 150")
    rep = scan._prune_report
    assert rep["candidates"] == 4
    assert rep["skipped_minmax"] == 3
    assert len(scan._store_parts) == 1
    assert scan.capacity == 50
    out = s2.sql("select b from t where a >= 150 order by b").to_pandas()
    assert out.b.tolist() == [i * 10 for i in range(150, 200)]


def test_bloom_file_skip(tmp_path):
    # interleaved values: every partition's [min,max] covers the range, so
    # only the bloom can exclude files for a point predicate
    s = cb.Session(_cfg(tmp_path))
    s.sql("create table t (a bigint, b bigint) distributed by (a)")
    vals = list(range(0, 1000, 7)) + list(range(3, 1000, 11))
    s.sql("insert into t values " +
          ",".join(f"({v}, {v * 2})" for v in vals))
    s2 = cb.Session(_cfg(tmp_path))
    scan = _scan_of(s2, "select b from t where a = 700")
    rep = scan._prune_report
    assert rep["skipped_bloom"] >= 1
    assert s2.sql("select b from t where a = 700").to_pandas() \
        .b.tolist() == [1400]


def test_column_projection_never_reads_unreferenced(tmp_path, monkeypatch):
    _mk_store(tmp_path)
    s2 = cb.Session(_cfg(tmp_path))
    read_log = []
    orig = mp.read_columns

    def spy(path, names=None, footer=None, **kw):
        read_log.append(sorted(names) if names is not None else None)
        return orig(path, names, footer, **kw)

    monkeypatch.setattr(mp, "read_columns", spy)
    out = s2.sql("select b from t where a >= 150 order by b").to_pandas()
    assert len(out) == 50
    assert read_log, "expected store reads"
    for names in read_log:
        assert names == ["a", "b"], \
            f"unreferenced columns were read: {names}"


def test_cold_dml_append_and_update(tmp_path):
    _mk_store(tmp_path)
    s2 = cb.Session(_cfg(tmp_path))
    s2.sql("insert into t values (500, 5000, 'w', 0.5)")
    s2.sql("update t set b = -1 where a = 0")
    s2.sql("delete from t where a = 1")
    s3 = cb.Session(_cfg(tmp_path))
    df = s3.sql("select count(*) as n, min(b) as mb from t").to_pandas()
    assert df.n[0] == 200 and df.mb[0] == -1


def test_nulls_roundtrip_cold_scan(tmp_path):
    s = cb.Session(_cfg(tmp_path))
    s.sql("create table t (a int, b int) distributed by (a)")
    s.sql("insert into t values (1, 10), (2, null), (3, 30)")
    s2 = cb.Session(_cfg(tmp_path))
    assert s2.catalog.table("t").cold
    out = s2.sql("select a from t where b is null").to_pandas()
    assert out.a.tolist() == [2]
    df = s2.sql("select sum(b) as s, count(b) as c from t").to_pandas()
    assert df.s[0] == 40 and df.c[0] == 2


def test_distributed_mode_on_stored_tables(tmp_path):
    _mk_store(tmp_path)
    s8 = cb.Session(_cfg(tmp_path, nseg=8))
    df = s8.sql("select c, count(*) as n, sum(b) as sb from t "
                "group by c order by c").to_pandas()
    s1 = cb.Session(_cfg(tmp_path))
    df1 = s1.sql("select c, count(*) as n, sum(b) as sb from t "
                 "group by c order by c").to_pandas()
    assert df.values.tolist() == df1.values.tolist()


def test_drop_table_removes_files(tmp_path):
    s = _mk_store(tmp_path)
    root = s.config.storage.root
    assert os.path.isdir(os.path.join(root, "t"))
    s.sql("drop table t")
    assert not os.path.isdir(os.path.join(root, "t"))
    s2 = cb.Session(_cfg(tmp_path))
    with pytest.raises(Exception):
        s2.sql("select * from t")


def test_unique_stats_survive_cold_registration(tmp_path):
    """PK detection (lookup-join planning) must work without loading
    data: uniqueness flags persist in the manifest."""
    _mk_store(tmp_path)
    s2 = cb.Session(_cfg(tmp_path))
    t = s2.catalog.table("t")
    assert t.cold
    assert t.is_unique("a") is True
    assert t.is_unique("c") is False


def test_rollback_never_truncates_cold_table(tmp_path):
    """BEGIN..ROLLBACK around a cold table must not persist its placeholder
    (empty) arrays — the round-2 review's data-loss finding."""
    _mk_store(tmp_path)
    s2 = cb.Session(_cfg(tmp_path))
    assert s2.catalog.table("t").cold
    s2.sql("begin")
    s2.sql("insert into t values (999, 1, 'x', 0.1)")
    s2.sql("rollback")
    assert s2.sql("select count(*) as n from t").to_pandas().n[0] == 200
    s3 = cb.Session(_cfg(tmp_path))
    assert s3.sql("select count(*) as n from t").to_pandas().n[0] == 200


def test_rolled_back_ddl_not_durable(tmp_path):
    """CREATE+INSERT inside a rolled-back transaction must not persist."""
    _mk_store(tmp_path)
    s2 = cb.Session(_cfg(tmp_path))
    s2.sql("begin")
    s2.sql("create table x (a int) distributed by (a)")
    s2.sql("insert into x values (1)")
    s2.sql("rollback")
    s3 = cb.Session(_cfg(tmp_path))
    assert "x" not in s3.catalog.tables


def test_txn_commit_persists(tmp_path):
    _mk_store(tmp_path)
    s2 = cb.Session(_cfg(tmp_path))
    s2.sql("begin")
    s2.sql("insert into t values (999, 1, 'x', 0.1)")
    s2.sql("commit")
    s3 = cb.Session(_cfg(tmp_path))
    assert s3.sql("select count(*) as n from t").to_pandas().n[0] == 201


def test_copy_to_from_cold_table(tmp_path):
    _mk_store(tmp_path)
    s2 = cb.Session(_cfg(tmp_path))
    out = tmp_path / "out.csv"
    s2.sql(f"copy t to '{out}'")
    assert len(out.read_text().splitlines()) == 200


def test_not_null_survives_cold_registration(tmp_path):
    s = cb.Session(_cfg(tmp_path))
    s.sql("create table nn (a bigint not null, b bigint) "
          "distributed by (a)")
    s.sql("insert into nn values (1, 2)")
    s2 = cb.Session(_cfg(tmp_path))
    with pytest.raises(Exception, match="NOT NULL"):
        s2.sql("insert into nn values (null, 3)")


def test_insert_appends_incrementally(tmp_path):
    """A single-row INSERT into a durable table writes one new partition,
    not a full rewrite of every file."""
    s = _mk_store(tmp_path, rpp=50)
    tdir = os.path.join(s.config.storage.root, "t")
    before = {f for f in os.listdir(tdir) if f.endswith(".cbmp")}
    s.sql("insert into t values (1000, 1, 'x', 0.1)")
    man = s.store.read_manifest("t")
    files_now = [p["file"] for p in man["partitions"]]
    # all previous manifest files still referenced, exactly one new
    assert len([f for f in files_now if f not in before]) == 1
    assert len(files_now) == len(before) + 1
    s2 = cb.Session(_cfg(tmp_path))
    assert s2.sql("select count(*) as n from t").to_pandas().n[0] == 201


def test_zero_row_append_is_not_a_duplication(tmp_path):
    """Regression: appended=0 must not re-append the whole table."""
    s = _mk_store(tmp_path)
    s.sql("create table e (a bigint, b bigint) distributed by (a)")
    s.sql("insert into e values (1, 2)")
    empty = tmp_path / "empty.csv"
    empty.write_text("")
    s.sql(f"copy e from '{empty}'")
    s2 = cb.Session(_cfg(tmp_path))
    assert s2.sql("select count(*) as n from e").to_pandas().n[0] == 1


def test_rollback_keeps_cold_stats(tmp_path):
    """Regression: ROLLBACK must not wipe a cold table's manifest stats
    (row counts / uniqueness drive the planner)."""
    _mk_store(tmp_path)
    s2 = cb.Session(_cfg(tmp_path))
    t = s2.catalog.table("t")
    assert t.num_rows == 200 and t.is_unique("a")
    s2.sql("begin")
    s2.sql("create table scratch (x int) distributed by (x)")
    s2.sql("rollback")
    t = s2.catalog.table("t")
    assert t.cold and t.num_rows == 200 and t.is_unique("a")


def test_subquery_cold_scan_is_pruned(tmp_path):
    """Scalar subqueries in WHERE bind their cold scans to pruned reads
    instead of silently materializing the table."""
    _mk_store(tmp_path)
    s2 = cb.Session(_cfg(tmp_path))
    out = s2.sql("select count(*) as n from t "
                 "where b > (select max(b) from t where a < 50)").to_pandas()
    assert out.n[0] == 150
    assert s2.catalog.table("t").cold  # never materialized


def test_ctas_persists(tmp_path):
    s = _mk_store(tmp_path)
    s.sql("create table t2 as select a, b from t where a < 10 "
          "distributed by (a)")
    s2 = cb.Session(_cfg(tmp_path))
    assert s2.sql("select count(*) as n from t2").to_pandas().n[0] == 10


def test_store_scan_cache_is_lru(monkeypatch):
    """Scan-cache eviction is LRU, not FIFO: a hit moves the entry to
    most-recently-used, so a hot table's scan survives a burst of
    one-off queries (exec/executor.py _load_store_scan)."""
    from cloudberry_tpu.exec import executor as X

    class FakeStore:
        def __init__(self):
            self.reads = []

        def effective_version(self, name):
            return 1

        def read_partitions(self, name, parts, cols):
            self.reads.append(name)
            return {c: np.zeros(4) for c in cols}, {}

    class Holder:
        pass

    import threading

    sess = Holder()
    sess._store_scan_cache = {}
    sess._store_scan_lock = threading.Lock()
    sess.catalog = Holder()
    sess.catalog.store = FakeStore()

    def scan(name):
        s = N.PScan(name, {"c": "c"}, 4)
        s._store_parts = [{"file": f"{name}.part"}]
        return s

    monkeypatch.setattr(X, "_STORE_SCAN_CACHE_MAX", 2)
    X._load_store_scan(scan("hot"), sess)    # miss
    X._load_store_scan(scan("one"), sess)    # miss — cache full
    X._load_store_scan(scan("hot"), sess)    # hit: hot becomes MRU
    X._load_store_scan(scan("two"), sess)    # miss: evicts "one", not "hot"
    X._load_store_scan(scan("hot"), sess)    # must still be a hit
    assert sess.catalog.store.reads == ["hot", "one", "two"]
    # FIFO would have evicted "hot" at the "two" insert and re-read it
