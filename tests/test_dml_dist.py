"""Distributed DML write path (plan/planner.py) — the nodeSplitUpdate.c
role: ship decisions and changed values through the executor, never the
whole table.

Contracts under test:
- UPDATE/DELETE on the 8-segment mesh produce the same rows as single-node
  execution, in the SAME canonical row order (distributed results scatter
  back through the placement permutation);
- only the predicate / SET expressions flow through the executor — an
  untouched column's host array is passed to set_data by REFERENCE;
- INSERT ... SELECT appends physical columns directly (no pandas decode):
  decimals survive digit-exact past 2^53.
"""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config


def _mk(nseg=8):
    return cb.Session(get_config().with_overrides(n_segments=nseg))


def _loadstr(s, n):
    from cloudberry_tpu.columnar.batch import ColumnBatch

    rng = np.random.default_rng(11)
    import pandas as pd

    df = pd.DataFrame({"k": np.arange(n),
                       "a": rng.integers(0, 1000, n),
                       "b": rng.integers(0, 1000, n),
                       "s": np.array(["x", "y", "z"])[np.arange(n) % 3]})
    b = ColumnBatch.from_pandas(df)
    t = s.catalog.table("t")
    t.set_data(dict(b.columns), dict(b.dicts))


def _fixture(nseg):
    s = _mk(nseg)
    s.sql("CREATE TABLE t (k BIGINT, a BIGINT, b BIGINT, s TEXT) "
          "DISTRIBUTED BY (k)")
    _loadstr(s, 50_000)
    return s


@pytest.mark.parametrize("dml", [
    "UPDATE t SET a = a + b WHERE b % 7 = 0",
    "UPDATE t SET s = 'w' WHERE a < 100",
    "DELETE FROM t WHERE a % 5 = 1",
])
def test_dist_dml_matches_single_node(dml):
    s1, s8 = _fixture(1), _fixture(8)
    r1, r8 = s1.sql(dml), s8.sql(dml)
    assert r1 == r8
    q = "SELECT k, a, b, s FROM t ORDER BY k"
    assert s1.sql(q).to_pandas().equals(s8.sql(q).to_pandas())
    # canonical row order is stable under DML — even distributed
    t1, t8 = s1.catalog.table("t"), s8.catalog.table("t")
    np.testing.assert_array_equal(t1.data["k"], t8.data["k"])


def test_update_leaves_untouched_columns_uncopied():
    s = _fixture(8)
    t = s.catalog.table("t")
    b_before = t.data["b"]
    s.sql("UPDATE t SET a = a * 2 WHERE b > 500")
    assert t.data["b"] is b_before  # untouched column: same array object


def test_dml_ships_only_needed_columns(monkeypatch):
    """The internal DML query's plan projects the predicate / SET outputs,
    not every table column — the whole-table materialization the round-2
    review flagged is gone."""
    from cloudberry_tpu.plan import planner as P

    seen = []
    orig = P._run_internal

    def spy(session, query):
        batch = orig(session, query)
        seen.append([f.name for f in batch.schema.fields])
        return batch

    monkeypatch.setattr(P, "_run_internal", spy)
    s = _fixture(8)
    s.sql("DELETE FROM t WHERE a % 5 = 1")
    assert seen[-1] == ["keep"]
    s.sql("UPDATE t SET a = a + 1 WHERE b = 3")
    assert seen[-1] == ["a", "$updated"]


def test_dml_never_touches_pandas(monkeypatch):
    from cloudberry_tpu.columnar.batch import ColumnBatch

    def boom(self):
        raise AssertionError("DML must not round-trip through pandas")

    s = _fixture(8)
    monkeypatch.setattr(ColumnBatch, "to_pandas", boom)
    s.sql("UPDATE t SET a = b WHERE a < 10")
    s.sql("DELETE FROM t WHERE a > 990")
    s.sql("CREATE TABLE t2 (k BIGINT, a BIGINT, b BIGINT, s TEXT) "
          "DISTRIBUTED BY (k)")
    s.sql("INSERT INTO t2 SELECT k, a, b, s FROM t WHERE a < 500")


def test_insert_select_decimal_exact_past_2_53():
    """Raw int64 fixed-point copies exactly; the old pandas float
    round-trip would corrupt the low digits past 2^53."""
    s = _mk(1)
    s.sql("CREATE TABLE src (d DECIMAL(2)) DISTRIBUTED BY (d)")
    s.sql("CREATE TABLE dst (d DECIMAL(2)) DISTRIBUTED BY (d)")
    s.sql("INSERT INTO src VALUES (123456789012345.67), "
          "(-98765432109876.54)")
    s.sql("INSERT INTO dst SELECT d FROM src")
    raw = s.catalog.table("dst").data["d"]
    np.testing.assert_array_equal(
        np.sort(raw), np.sort(np.asarray([-9876543210987654,
                                          12345678901234567])))


def test_insert_select_string_dict_translation():
    """A query whose string output uses a different dictionary than the
    target table translates codes through values, extending the target's
    dictionary as needed."""
    s = _mk(8)
    s.sql("CREATE TABLE a (k BIGINT, s TEXT) DISTRIBUTED BY (k)")
    s.sql("CREATE TABLE b (k BIGINT, s TEXT) DISTRIBUTED BY (k)")
    s.sql("INSERT INTO a VALUES (1, 'alpha'), (2, 'beta')")
    s.sql("INSERT INTO b VALUES (3, 'gamma')")
    s.sql("INSERT INTO b SELECT k, s FROM a")
    got = s.sql("SELECT s FROM b ORDER BY k").to_pandas()["s"].tolist()
    assert got == ["alpha", "beta", "gamma"]


def test_dist_insert_select_validity_carries():
    s = _mk(8)
    s.sql("CREATE TABLE src (k BIGINT, v BIGINT) DISTRIBUTED BY (k)")
    s.sql("CREATE TABLE dst (k BIGINT, v BIGINT) DISTRIBUTED BY (k)")
    s.sql("INSERT INTO src VALUES (1, 10), (2, NULL), (3, 30)")
    s.sql("INSERT INTO dst SELECT k, v FROM src")
    got = s.sql("SELECT k, v FROM dst ORDER BY k").to_pandas()
    assert got["v"].isna().tolist() == [False, True, False]
