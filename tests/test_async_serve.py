"""Event-loop serving core (serve/asyncore.py): concurrency far past the
worker pool, pipelined framing, the connection cap, drain semantics, and
the threaded fallback (ISSUE-7 tentpole)."""

import json
import socket
import threading
import time

import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.serve import Client, Server, ServerError


@pytest.fixture(autouse=True, scope="module")
def _lock_witness():
    # runtime lock-order witness (lint/witness.py): the event-loop
    # front end + tenancy scheduler run under declared-order checking
    from cloudberry_tpu.lint import witness

    with witness.watching():
        yield


def _session(**over):
    s = cb.Session(Config().with_overrides(**over) if over else Config())
    s.sql("create table t (a bigint, b bigint) distributed by (a)")
    s.sql("insert into t values " +
          ",".join(f"({i}, {i * 2})" for i in range(500)))
    return s


def test_async_is_the_default_transport():
    from cloudberry_tpu.serve.asyncore import AsyncFrontEnd

    with Server(session=_session()) as srv:
        assert isinstance(srv._transport, AsyncFrontEnd)
        with Client(srv.host, srv.port) as c:
            assert c.sql("select count(*) as n from t")["rows"] == [[500]]


def test_threaded_fallback_still_works():
    from cloudberry_tpu.serve.server import _ThreadedTransport

    s = _session(**{"serve.threaded": True})
    with Server(session=s) as srv:
        assert isinstance(srv._transport, _ThreadedTransport)
        with Client(srv.host, srv.port) as c:
            assert c.sql("select count(*) as n from t")["rows"] == [[500]]


def test_many_connections_few_threads():
    """64 concurrent connections — an order of magnitude past the worker
    pool — all served, with correct per-connection results."""
    s = _session(**{"serve.workers": 4, "serve.io_threads": 2})
    errors = []
    with Server(session=s) as srv:
        before = threading.active_count()

        def one(i):
            try:
                with Client(srv.host, srv.port) as c:
                    out = c.sql(f"select b from t where a = {i}")
                    if out["rows"] != [[i * 2]]:
                        errors.append(f"wrong row for {i}: {out['rows']}")
            except Exception as e:  # pragma: no cover
                errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(64)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=120)
        # the server side added no per-connection threads (the client
        # side owns the 64; server threads stay a small constant)
        assert threading.active_count() - before <= 70
    assert not errors, errors[:3]


def test_pipelined_requests_answered_in_order():
    """A client that writes N requests before reading any gets N
    responses in request order — the per-connection serialization
    guarantee of the event loop."""
    with Server(session=_session()) as srv:
        sock = socket.create_connection((srv.host, srv.port), timeout=30)
        try:
            payload = b"".join(
                json.dumps({"sql": f"select b from t where a = {i}"})
                .encode() + b"\n" for i in range(10))
            sock.sendall(payload)
            f = sock.makefile("rb")
            for i in range(10):
                resp = json.loads(f.readline())
                assert resp["ok"] and resp["rows"] == [[i * 2]], (i, resp)
        finally:
            sock.close()


def test_connection_cap_returns_retryable_server_busy():
    s = _session(**{"serve.max_connections": 2})
    with Server(session=s) as srv:
        held = [Client(srv.host, srv.port) for _ in range(2)]
        try:
            c3 = Client(srv.host, srv.port)
            with pytest.raises(ServerError) as ei:
                c3.sql("select count(*) as n from t")
            assert ei.value.etype == "ServerBusy"
            assert ei.value.retryable
        finally:
            for c in held:
                c.close()
        # slots free again after the held connections close
        deadline = time.monotonic() + 10
        while True:
            try:
                with Client(srv.host, srv.port) as c:
                    assert c.sql("select count(*) as n from t")[
                        "rows"] == [[500]]
                break
            except ServerError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)


def test_server_busy_client_reconnect_retry():
    """ISSUE-7 satellite: the retry policy honors SERVER_BUSY by name
    and reconnects (the refusal closes the socket), so a client riding
    a briefly-full server eventually succeeds."""
    s = _session(**{"serve.max_connections": 1})
    with Server(session=s) as srv:
        blocker = Client(srv.host, srv.port)

        def free_slot():
            time.sleep(0.15)
            blocker.close()

        threading.Thread(target=free_slot).start()
        with Client(srv.host, srv.port, retry_reads=True, max_retries=6,
                    backoff_s=0.05) as c:
            out = c.sql("select count(*) as n from t")
            assert out["rows"] == [[500]]


def test_async_drain_never_drops_accepted_requests():
    """Server.stop(drain_s) on the event-loop core: every accepted
    request gets its answer (result or the retryable drain refusal)."""
    s = _session()
    srv = Server(session=s).start()
    results = []
    errors = []
    stop_client = threading.Event()

    def pound(i):
        try:
            with Client(srv.host, srv.port) as c:
                while not stop_client.is_set():
                    try:
                        out = c.sql(f"select b from t where a = {i}")
                        results.append(out["rows"][0][0])
                    except ServerError as e:
                        if e.etype in ("ServerDraining",) or \
                                str(e).startswith(
                                    "server closed the connection"):
                            return  # visible refusal/shutdown: fine
                        raise
                    except OSError:
                        # a reset mid-send during shutdown is a VISIBLE
                        # connection failure (the request was never
                        # accepted), not a silent drop
                        return
        except Exception as e:  # pragma: no cover
            errors.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=pound, args=(i,))
               for i in range(6)]
    for th in threads:
        th.start()
    time.sleep(0.4)
    srv.stop(drain_s=10.0)
    stop_client.set()
    for th in threads:
        th.join(timeout=30)
    assert not errors, errors[:3]
    assert results  # real work flowed before the drain


def test_async_per_connection_txn_rolls_back_on_disconnect(tmp_path):
    """Per-connection backends over a durable store: a dropped
    connection aborts its open wire transaction (the backend-exit
    rollback), same as the threaded transport."""
    cfg = Config().with_overrides(
        **{"storage.root": str(tmp_path / "store")})
    with Server(config=cfg) as srv:
        with Client(srv.host, srv.port) as c:
            c.sql("create table d (x bigint) distributed by (x)")
            c.sql("insert into d values (1)")
        c2 = Client(srv.host, srv.port)
        c2.sql("begin")
        c2.sql("insert into d values (2)")
        c2.close()  # connection dies with the transaction open
        deadline = time.monotonic() + 10
        while True:
            with Client(srv.host, srv.port) as c3:
                n = c3.sql("select count(*) as n from d")["rows"][0][0]
            if n == 1 or time.monotonic() > deadline:
                break
            time.sleep(0.05)
        assert n == 1  # the in-txn insert rolled back


def test_async_auth_and_lockout():
    with Server(session=_session(), auth_token="hunter2",
                max_login_failures=2, lockout_s=30.0) as srv:
        for _ in range(2):
            with pytest.raises(ServerError, match="authentication"):
                Client(srv.host, srv.port, token="nope")
        with pytest.raises(ServerError, match="locked"):
            Client(srv.host, srv.port, token="hunter2")
