"""General NULL semantics — the full-SQL three-valued-logic surface.

The reference inherits NULL handling from PostgreSQL (per-datum null flags);
here validity is compiled structure: expression-level validity exprs in the
binder, hidden "$vm"/"$nn:" bool columns at plan boundaries, identity-filled
aggregate args with valid-count companions (plan/binder.py). These tests pin
the observable semantics against PostgreSQL behavior.
"""

import numpy as np
import pandas as pd
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config


def _mk(nseg=1):
    s = cb.Session(Config(n_segments=nseg)) if nseg > 1 else cb.Session()
    s.sql("create table t (a int, b int, c text, f double) "
          "distributed by (a)")
    s.sql("insert into t values "
          "(1, 10, 'x', 1.5), (2, null, 'y', null), "
          "(3, 30, null, 3.5), (4, null, null, null), (5, 0, 'x', 0.0)")
    s.sql("create table u (a int, d int) distributed by (a)")
    s.sql("insert into u values (1, 100), (3, 300), (6, null)")
    return s


@pytest.fixture(scope="module", params=[1, 8], ids=["single", "dist8"])
def s(request):
    return _mk(request.param)


def _norm(vals):
    """pandas renders string/float NULLs as NaN, object NULLs as None —
    normalize both to None for comparison."""
    return [None if (v is None or (isinstance(v, float) and np.isnan(v))
                     or v is pd.NA) else v for v in vals]


def rows(s, q):
    return [_norm(r) for r in s.sql(q).to_pandas().values.tolist()]


def col(s, q, name=None):
    df = s.sql(q).to_pandas()
    return _norm(df[name if name else df.columns[0]].tolist())


# ------------------------------------------------------------- predicates


def test_where_null_excluded(s):
    # b > 5 is NULL for NULL b: those rows are excluded, not errors
    assert col(s, "select a from t where b > 5 order by a") == [1, 3]


def test_where_not_null_excluded(s):
    # NOT (NULL) is still NULL -> excluded (3VL, not two-valued negation)
    assert col(s, "select a from t where not (b > 5) order by a") == [5]


def test_is_null_and_not_null(s):
    assert col(s, "select a from t where b is null order by a") == [2, 4]
    assert col(s, "select a from t where b is not null order by a") \
        == [1, 3, 5]


def test_3vl_or_and(s):
    # (b > 5 OR a = 2): NULL OR TRUE = TRUE keeps row 2
    assert col(s, "select a from t where b > 5 or a = 2 order by a") \
        == [1, 2, 3]
    # (b > 5 AND a < 10): NULL AND TRUE = NULL -> excluded
    assert col(s, "select a from t where b > 5 and a < 10 order by a") \
        == [1, 3]


def test_null_literal_comparison(s):
    assert col(s, "select a from t where b = null") == []
    assert col(s, "select a from t where null = null") == []


def test_in_list_with_null_value(s):
    assert col(s, "select a from t where b in (10, 30) order by a") == [1, 3]
    # NOT IN over a nullable column: NULL b is excluded
    assert col(s, "select a from t where b not in (10) order by a") == [3, 5]


# ------------------------------------------------------------ expressions


def test_arithmetic_propagates_null(s):
    out = col(s, "select b + 1 from t order by a")
    assert out == [11, None, 31, None, 1]
    out = col(s, "select b * 2 - a from t order by a")
    assert out == [19, None, 57, None, -5]


def test_coalesce(s):
    assert col(s, "select coalesce(b, -1) from t order by a") \
        == [10, -1, 30, -1, 0]
    assert col(s, "select coalesce(b, a) from t order by a") \
        == [10, 2, 30, 4, 0]
    assert col(s, "select coalesce(c, 'missing') from t order by a") \
        == ["x", "y", "missing", "missing", "x"]


def test_case_implicit_null(s):
    out = col(s, "select case when b > 15 then 'big' end from t order by a")
    assert out == [None, None, "big", None, None]
    out = col(s, "select case when b > 15 then b else null end "
                 "from t order by a")
    assert out == [None, None, 30, None, None]


def test_case_null_condition_falls_through(s):
    # b > 5 NULL for rows 2/4 -> fall to ELSE
    out = col(s, "select case when b > 5 then 1 else 0 end "
                 "from t order by a")
    assert out == [1, 0, 1, 0, 0]


# ------------------------------------------------------------- aggregates


def test_aggregates_skip_nulls(s):
    df = s.sql("select count(*) as n, count(b) as nb, sum(b) as sb, "
               "avg(b) as ab, min(b) as mb, max(b) as xb from t").to_pandas()
    assert df.n[0] == 5 and df.nb[0] == 3
    assert df.sb[0] == 40 and df.mb[0] == 0 and df.xb[0] == 30
    assert abs(df.ab[0] - 40 / 3) < 1e-9


def test_empty_aggregates_are_null(s):
    df = s.sql("select sum(b) as sb, min(b) as mb, avg(b) as ab, "
               "count(b) as nb from t where a > 100").to_pandas()
    assert df.sb[0] is None and df.mb[0] is None and df.ab[0] is None
    assert df.nb[0] == 0


def test_all_null_group_aggregate(s):
    # group c=NULL has b values {30, NULL}; group 'y' has only NULL b
    out = rows(s, "select c, sum(b), count(b) from t group by c order by c")
    assert out == [["x", 10, 2], ["y", None, 0], [None, 30, 1]]


def test_group_by_nullable_key(s):
    # NULLs form ONE group, distinct from real values (incl. 0-adjacent)
    out = rows(s, "select b, count(*) from t group by b order by b")
    assert out == [[0, 1], [10, 1], [30, 1], [None, 2]]


def test_count_distinct_skips_nulls(s):
    assert col(s, "select count(distinct c) from t") == [2]
    assert col(s, "select count(distinct b) from t") == [3]


def test_avg_nullable_distributed_split(s):
    out = rows(s, "select c, avg(b) from t group by c order by c")
    assert out[0][0] == "x" and abs(out[0][1] - 5.0) < 1e-9
    assert out[1][0] == "y" and out[1][1] is None
    assert out[2][0] is None and abs(out[2][1] - 30.0) < 1e-9


# ------------------------------------------------------------------ joins


def test_null_keys_never_match(s):
    # u has a NULL d; t row 5 has b=0 — NULL keys must not pair up
    out = rows(s, "select t.a, u.a from t join u on t.b = u.d")
    assert out == []


def test_left_join_nullable_payload(s):
    out = rows(s, "select t.a, u.d from t left join u on t.a = u.a "
                  "order by t.a")
    assert out == [[1, 100], [2, None], [3, 300], [4, None], [5, None]]


def test_null_provenance_through_derived_table(s):
    # the round-1 "$lost" case: nullable column re-exported by a subquery
    q = ("select * from (select t.a as a, u.d as d from t "
         "left join u on t.a = u.a) v where d is null order by a")
    assert col(s, q) == [2, 4, 5]
    q2 = ("select count(d) from (select t.a as a, u.d as d from t "
          "left join u on t.a = u.a) v")
    assert col(s, q2) == [2]
    q3 = ("select avg(d) from (select t.a as a, u.d as d from t "
          "left join u on t.a = u.a) v")
    assert abs(col(s, q3)[0] - 200.0) < 1e-9


def test_double_nullable_masks_conjoin(s):
    # nullable through TWO outer joins: validity is the mask conjunction
    q = ("select t.a, w.d2 from t "
         "left join (select u.a as a2, u.d as d2 from u) w on t.a = w.a2 "
         "order by t.a")
    assert rows(s, q) == [[1, 100], [2, None], [3, 300], [4, None],
                          [5, None]]


def test_not_in_null_aware(s):
    # u.d contains NULL -> x NOT IN (select d from u) is never TRUE
    assert col(s, "select a from t where a not in (select d from u)") == []
    # without the NULL, normal anti semantics
    assert col(s, "select a from t where a not in "
                  "(select d from u where d is not null) order by a") \
        == [1, 2, 3, 4, 5]


# ------------------------------------------------------- sort / distinct


def test_null_sort_order(s):
    # ascending: NULLS LAST; descending: NULLS FIRST (PostgreSQL default)
    assert col(s, "select b from t order by b") == [0, 10, 30, None, None]
    assert col(s, "select b from t order by b desc, a") \
        == [None, None, 30, 10, 0]


def test_distinct_groups_nulls(s):
    assert col(s, "select distinct b from t order by b") \
        == [0, 10, 30, None]
    assert col(s, "select distinct c from t order by c") == ["x", "y", None]


def test_union_intersect_except_with_nulls(s):
    assert col(s, "select b from t union select d from u order by b") \
        == [0, 10, 30, 100, 300, None]
    # INTERSECT: NULL equals NULL for set ops
    assert col(s, "select b from t intersect select d from u "
                  "order by b") == [None]
    assert col(s, "select b from t except select b from t where b is null "
                  "order by b") == [0, 10, 30]


# --------------------------------------------------------------- DML / IO


def test_update_set_null_and_delete_3vl():
    s2 = _mk(1)
    s2.sql("update t set b = null where a = 1")
    assert col(s2, "select a from t where b is null order by a") == [1, 2, 4]
    # DELETE where b > 5: NULL predicate rows must be KEPT
    s2.sql("delete from t where b > 5")
    assert col(s2, "select a from t order by a") == [1, 2, 4, 5]


def test_ctas_preserves_validity():
    s2 = _mk(1)
    s2.sql("create table t2 as select a, b from t distributed by (a)")
    assert col(s2, "select a from t2 where b is null order by a") == [2, 4]


def test_insert_select_preserves_validity():
    s2 = _mk(1)
    s2.sql("create table t3 (a int, b int) distributed by (a)")
    s2.sql("insert into t3 select a, b from t")
    assert col(s2, "select a from t3 where b is null order by a") == [2, 4]


def test_copy_null_roundtrip(tmp_path):
    s2 = _mk(1)
    p = tmp_path / "t.csv"
    s2.sql(f"copy t to '{p}'")
    text = p.read_text()
    assert "\\N" in text
    s2.sql("create table tc (a int, b int, c text, f double) "
           "distributed by (a)")
    s2.sql(f"copy tc from '{p}'")
    a = s2.sql("select a, b, c from tc order by a").to_pandas()
    b = s2.sql("select a, b, c from t order by a").to_pandas()
    pd.testing.assert_frame_equal(a, b)


def test_copy_fast_path_preserves_existing_validity(tmp_path):
    """A NULL-free COPY file takes the native fast path — it must EXTEND
    existing validity masks, not erase the table's stored NULLs."""
    s2 = cb.Session()
    s2.sql("create table t5 (a int, b int) distributed by (a)")
    s2.sql("insert into t5 values (1, null), (2, 20)")
    p = tmp_path / "clean.csv"
    p.write_text("3|30\n4|40\n")
    s2.sql(f"copy t5 from '{p}'")
    assert col(s2, "select a from t5 where b is null") == [1]
    assert col(s2, "select a from t5 where b is not null order by a") \
        == [2, 3, 4]


def test_scalar_subquery_null_result():
    """A scalar subquery whose single row is NULL yields NULL, not a
    sentinel value (the value and validity share one subplan)."""
    s2 = _mk(1)
    out = col(s2, "select (select max(b) from t where a > 100)")
    assert out == [None]
    # and in a comparison: NULL never matches
    assert col(s2, "select a from t where b = "
                   "(select max(b) from t where a > 100)") == []
    # non-null scalar still works
    assert col(s2, "select (select max(b) from t)") == [30]


def test_cte_does_not_leak_into_view():
    """A view's internal table references are fixed at creation and must
    not resolve to the caller's same-named CTE (PostgreSQL semantics)."""
    s2 = cb.Session()
    s2.sql("create table base (x int) distributed by (x)")
    s2.sql("insert into base values (10), (20)")
    s2.sql("create view vsum as select sum(x) as s from base")
    out = col(s2, "with base as (select 1 as x) select s from vsum", "s")
    assert out == [30]


def test_not_null_constraint_rejected():
    s2 = cb.Session()
    s2.sql("create table nn (a int not null, b int) distributed by (a)")
    with pytest.raises(Exception, match="NOT NULL"):
        s2.sql("insert into nn values (null, 1)")


def test_having_on_nullable_agg():
    s2 = _mk(1)
    out = rows(s2, "select c, sum(b) as sb from t group by c "
                   "having sum(b) > 5 order by c")
    # 'y' group's sum is NULL -> HAVING NULL excludes that group
    assert out == [["x", 10], [None, 30]]


def test_not_in_null_aware_cross_segment():
    """The NULL build row may live on a DIFFERENT segment than the probe
    rows: the has-NULL test must reduce across the whole mesh (psum)."""
    s8 = cb.Session(Config(n_segments=2))
    s8.sql("create table tt (a int, b int) distributed by (b)")
    s8.sql("insert into tt values (1, 1), (2, 2), (3, 3), (4, 4)")
    s8.sql("create table uu (x int) distributed by (x)")
    s8.sql("insert into uu values (10), (null)")
    assert rows(s8, "select a from tt where b not in (select x from uu)") \
        == []


def test_window_partition_by_nullable_key():
    s2 = _mk(1)
    # c has values x,y,NULL,NULL,x — the NULL partition must be its own,
    # distinct from any canonical value
    out = rows(s2, "select a, count(*) over (partition by c) as n "
                   "from t order by a")
    assert out == [[1, 2], [2, 1], [3, 2], [4, 2], [5, 2]]


def test_order_by_hidden_sort_column_null_order():
    s2 = _mk(1)
    # ORDER BY a non-output nullable column goes through the hidden
    # sort-column path; NULLS LAST must still hold
    out = col(s2, "select a from t order by b, a")
    assert out == [5, 1, 3, 2, 4]


def test_null_flows_through_motions():
    """Redistribute a nullable column across 8 segments: masks ride the
    all_to_all like any other column."""
    s8 = _mk(8)
    out = rows(s8, "select b, count(*) as n from t group by b order by b")
    assert out == [[0, 1], [10, 1], [30, 1], [None, 2]]
