"""Distributed tiled execution (exec/tiled_dist.py) — spill on the mesh.

The contract under test: an admission-rejected DISTRIBUTED statement (8
segments) completes by streaming per-segment tiles through the plan's
motions — redistribute per tile, per-segment accumulators, one finalize
SPMD program — and produces exactly the same result as the in-memory
distributed run. The workfile_mgr.c / nodeHash.c batch discipline
interacting with Motion, on the segment mesh."""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config

# dim is distributed on a DIFFERENT key than the join key, so the probe
# side (fact) must redistribute — the motion then runs inside every tile
JOIN_GROUP_Q = ("SELECT g, sum(v) AS sv, count(*) AS c "
                "FROM fact JOIN dim ON fact.d = dim.d "
                "GROUP BY g ORDER BY g")


def _load(session, n_fact=400_000, n_dim=500, seed=3):
    rng = np.random.default_rng(seed)
    session.sql("CREATE TABLE dim (d BIGINT, g BIGINT) DISTRIBUTED BY (g)")
    session.sql("CREATE TABLE fact (k BIGINT, d BIGINT, v BIGINT) "
                "DISTRIBUTED BY (k)")
    session.catalog.table("dim").set_data(
        {"d": np.arange(n_dim), "g": np.arange(n_dim) % 9})
    # k: 997 distinct values — a colocatable GROUP BY key whose group
    # count stays far below the per-segment row count
    session.catalog.table("fact").set_data(
        {"k": np.arange(n_fact) % 997,
         "d": rng.integers(0, n_dim, n_fact),
         "v": rng.integers(0, 100, n_fact)})


def _mk(budget=None, **extra):
    ov = {"n_segments": 8,
          # keep the small dim out of broadcast so the probe redistributes
          "planner.broadcast_threshold": 0}
    if budget is not None:
        ov["resource.query_mem_bytes"] = budget
    ov.update(extra)
    return cb.Session(get_config().with_overrides(**ov))


@pytest.fixture(scope="module")
def expected():
    s = _mk()
    _load(s)
    return s.sql(JOIN_GROUP_Q).to_pandas()


def test_dist_tiled_join_group_matches_in_memory(expected):
    s = _mk(budget=2 << 20)
    _load(s)
    got = s.sql(JOIN_GROUP_Q).to_pandas()
    assert expected.equals(got)
    rep = s.last_tiled_report
    assert rep["tiled"] and rep["distributed"] and rep["n_tiles"] > 1
    assert rep["n_segments"] == 8
    assert rep["stream_table"] == "fact"
    assert rep["est_step_bytes"] <= rep["budget_bytes"] == 2 << 20


def test_dist_tiled_statement_cache_reuses_runner(expected):
    s = _mk(budget=2 << 20)
    _load(s)
    got1 = s.sql(JOIN_GROUP_Q).to_pandas()
    got2 = s.sql(JOIN_GROUP_Q).to_pandas()
    assert expected.equals(got1) and expected.equals(got2)


def test_dist_tiled_global_agg():
    q = ("SELECT sum(v) AS sv, min(v) AS mn, max(v) AS mx, "
         "count(*) AS c, avg(v) AS av FROM fact")
    big = _mk()
    _load(big)
    exp = big.sql(q).to_pandas()
    s = _mk(budget=256 << 10)
    _load(s)
    got = s.sql(q).to_pandas()
    rep = s.last_tiled_report
    assert rep["distributed"] and rep["n_tiles"] > 1
    for c in exp.columns:
        np.testing.assert_allclose(got[c].to_numpy().astype(float),
                                   exp[c].to_numpy().astype(float))


def test_dist_tiled_colocated_one_stage_agg():
    """Grouping on the distribution key: the distributed plan keeps a
    one-stage colocated aggregation — the accumulator IS the final
    per-segment state and finalize needs no merge motion."""
    q = "SELECT k, sum(v) AS sv FROM fact GROUP BY k ORDER BY k LIMIT 20"
    big = _mk()
    _load(big)
    exp = big.sql(q).to_pandas()
    s = _mk(budget=1 << 20)
    _load(s)
    got = s.sql(q).to_pandas()
    assert exp.equals(got)
    assert s.last_tiled_report["n_tiles"] > 1


def test_dist_merge_overflow_grows_accumulator():
    """Under-estimated group count grows the per-segment accumulator and
    restarts the stream rather than truncating groups. The budget leaves
    room for the finalize program (nseg x grown-accumulator rows), which
    est_finalize_bytes now accounts for."""
    s = _mk(budget=10 << 20)
    _load(s, n_fact=800_000, n_dim=10_000)
    q = ("SELECT d % 7000 AS dd, count(*) AS c, sum(v) AS sv "
         "FROM fact GROUP BY d % 7000 ORDER BY dd LIMIT 50")
    big = _mk()
    _load(big, n_fact=800_000, n_dim=10_000)
    exp = big.sql(q).to_pandas()
    got = s.sql(q).to_pandas()
    assert exp.equals(got)
    assert s.last_tiled_report["acc_capacity"] >= 7000


def test_dist_spill_disabled_refuses():
    from cloudberry_tpu.exec.resource import ResourceError

    s = _mk(budget=4 << 20, **{"resource.enable_spill": False})
    _load(s)
    with pytest.raises(ResourceError, match="memory estimate"):
        s.sql(JOIN_GROUP_Q)


TOPN_Q = ("SELECT fact.k AS k, fact.d AS d, v, g FROM fact JOIN dim "
          "ON fact.d = dim.d WHERE v < 90 "
          "ORDER BY v, fact.k, fact.d, g LIMIT 25")


def test_dist_tiled_topn_matches_in_memory():
    """ORDER BY + LIMIT over a redistribute-join spine with no
    aggregation: per-segment bounded top-N accumulators, finalize through
    the original gather + global sort."""
    big = _mk()
    _load(big)
    exp = big.sql(TOPN_Q).to_pandas()
    assert big.last_tiled_report is None

    s = _mk(budget=12 << 20)
    _load(s)
    got = s.sql(TOPN_Q).to_pandas()
    assert exp.equals(got)
    rep = s.last_tiled_report
    assert rep["tiled"] and rep["distributed"] and rep["n_tiles"] > 1
    assert rep["mode"] == "topn"
    assert rep["acc_capacity"] == 25
    assert rep["est_step_bytes"] <= rep["budget_bytes"]


def test_dist_tiled_topn_offset():
    big = _mk()
    _load(big)
    q = ("SELECT v, fact.k AS k FROM fact JOIN dim ON fact.d = dim.d "
         "ORDER BY v DESC, fact.k DESC, fact.d DESC LIMIT 10 OFFSET 5")
    exp = big.sql(q).to_pandas()
    s = _mk(budget=6 << 20)
    _load(s)
    got = s.sql(q).to_pandas()
    assert exp.equals(got)
    rep = s.last_tiled_report
    assert rep["mode"] == "topn" and rep["acc_capacity"] == 15


def test_tpch_q5_q9_tiled_distributed():
    """The round-2 done-criterion: admission-rejected Q5/Q9-shape queries
    complete on the 8-device mesh under a small per-segment budget with
    results matching the in-memory run and n_tiles > 1."""
    from tools.tpch_oracle import ORACLES
    from tools.tpch_queries import QUERIES
    from tools.tpchgen import load_tpch

    big = cb.Session(get_config().with_overrides(n_segments=8))
    load_tpch(big, sf=0.02, seed=7)
    tables = {n: t.to_pandas() for n, t in big.catalog.tables.items()}

    # per-SEGMENT budgets: SF0.02 shards are ~1/8 of the single-node test's
    # working set, so each budget sits just under that query's untiled
    # estimate (q9's resident builds + accumulator need more floor than q5)
    for qn, budget in (("q5", 1 << 20), ("q9", 3 << 20)):
        s = cb.Session(get_config().with_overrides(
            n_segments=8, **{"resource.query_mem_bytes": budget}))
        load_tpch(s, sf=0.02, seed=7)
        got = s.sql(QUERIES[qn]).to_pandas()
        rep = s.last_tiled_report
        assert rep and rep["n_tiles"] > 1, f"{qn} did not tile"
        assert rep["distributed"] and rep["est_step_bytes"] <= budget
        exp = ORACLES[qn](tables)
        assert len(got) == len(exp)
        for gc, ec in zip(got.columns, exp.columns):
            g, e = got[gc].to_numpy(), exp[ec].to_numpy()
            if g.dtype.kind == "f" or e.dtype.kind == "f":
                np.testing.assert_allclose(
                    g.astype(np.float64), e.astype(np.float64),
                    rtol=1e-9, atol=1e-2, err_msg=f"{qn}.{gc}")
            else:
                np.testing.assert_array_equal(g, e, err_msg=f"{qn}.{gc}")
