"""Directory tables (storage/dirtable.py) — files as catalog objects.

Uploads land in table-managed storage; SQL sees one metadata row per
file (fresh per statement); content round-trips through the Session API;
TDE encrypts file contents at rest.
"""

import hashlib

import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config
from cloudberry_tpu.storage.dirtable import DirTableError


def _cfg(tmp_path, **ov):
    over = {"storage.root": str(tmp_path)}
    over.update(ov)
    return get_config().with_overrides(**over)


def test_directory_table_upload_query_read(tmp_path):
    s = cb.Session(_cfg(tmp_path))
    s.sql("create directory table docs")
    assert len(s.sql("select * from docs").to_pandas()) == 0
    s.dir_upload("docs", "a/report.txt", b"hello world")
    s.dir_upload("docs", "b.bin", b"\x00\x01\x02")
    df = s.sql("select relative_path, size, md5 from docs "
               "order by relative_path").to_pandas()
    assert df["relative_path"].tolist() == ["a/report.txt", "b.bin"]
    assert df["size"].tolist() == [11, 3]
    assert df["md5"][0] == hashlib.md5(b"hello world").hexdigest()
    assert s.dir_read("docs", "a/report.txt") == b"hello world"
    # SQL over the metadata relation composes like any table
    big = s.sql("select count(*) from docs where size > 5").to_pandas()
    assert big.iloc[0, 0] == 1
    s.dir_remove("docs", "b.bin")
    assert len(s.sql("select * from docs").to_pandas()) == 1


def test_directory_table_needs_store():
    s = cb.Session()
    from cloudberry_tpu.plan.binder import BindError

    with pytest.raises(BindError, match="durable storage"):
        s.sql("create directory table nope")


def test_directory_table_path_safety(tmp_path):
    s = cb.Session(_cfg(tmp_path))
    s.sql("create directory table dt")
    with pytest.raises(DirTableError, match="bad relative path"):
        s.dir_upload("dt", "../escape.txt", b"x")
    with pytest.raises(DirTableError, match="no file"):
        s.dir_read("dt", "missing.txt")


def test_directory_table_tde(tmp_path):
    s = cb.Session(_cfg(tmp_path,
                        **{"storage.encryption_key": "k1"}))
    s.sql("create directory table sec")
    s.dir_upload("sec", "secret.txt", b"the payload text")
    # content encrypted at rest
    on_disk = (tmp_path / "_dirtab" / "sec" / "secret.txt").read_bytes()
    assert b"the payload text" not in on_disk
    # round-trips through the cipher; md5 is of the DECRYPTED content
    assert s.dir_read("sec", "secret.txt") == b"the payload text"
    df = s.sql("select md5, size from sec").to_pandas()
    assert df["md5"][0] == hashlib.md5(b"the payload text").hexdigest()
    assert df["size"][0] == 16
