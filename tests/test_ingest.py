"""Streaming ingest plane (storage/ingest.py + the wire "append" verb)
— ISSUE 18.

Pinned here:

- wire appends are BIT-IDENTICAL to the equivalent single-statement
  INSERT sequence, on both transports (the tentpole contract: the flush
  renders real INSERTs through the one write path);
- group commit: concurrent appenders share flushes (flushes < appends)
  and the size/age thresholds actually gate them;
- backpressure: a full buffer refuses with the RETRYABLE
  IngestQueueFull (counter bumped), and a later retry succeeds;
- device-loss mid-flush (ingest_flush 'error' seam): the WHOLE batch
  fails before any statement commits — every covered appender sees the
  error, nothing partial is durable, a retry after recovery lands;
- drain flush-on-stop: stop() commits every buffered row, then refuses;
- lifecycle: per-append deadlines raise StatementTimeout;
- observability: meta "ingest", ingest_* counters, and the
  mem_ingest_buffer_bytes capacity gauge.
"""

import threading
import time

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu import lifecycle
from cloudberry_tpu.config import get_config
from cloudberry_tpu.storage.ingest import IngestService, render_insert
from cloudberry_tpu.utils import faultinject as FI


@pytest.fixture(autouse=True)
def _clean_faults():
    FI.reset_fault()
    yield
    FI.reset_fault()


def _store_session(tmp_path, **ov):
    over = {"storage.root": str(tmp_path),
            "storage.rows_per_partition": 256,
            "ingest.flush_rows": 8, "ingest.flush_ms": 10.0}
    over.update(ov)
    s = cb.Session(get_config().with_overrides(**over))
    s.sql("create table ev (k bigint, v bigint)")
    t = s.catalog.table("ev")
    t.set_data({"k": np.arange(16, dtype=np.int64),
                "v": np.arange(16, dtype=np.int64) * 3}, {})
    return s


# ----------------------------------------------------------- unit: render


def test_render_insert_literals():
    sql = render_insert("t", ("k", "s"),
                        [[1, "it's"], [None, "x"], [True, "y"]])
    assert sql == ("INSERT INTO t (k, s) VALUES "
                   "(1, 'it''s'), (NULL, 'x'), (TRUE, 'y')")
    assert render_insert("t", None, [[1.5]]) \
        == "INSERT INTO t VALUES (1.5)"
    with pytest.raises(ValueError):
        render_insert("t", None, [[object()]])


def test_append_validation(tmp_path):
    s = _store_session(tmp_path)
    ing = IngestService(s)
    with pytest.raises(ValueError):
        ing.append("ev; drop table ev", [[1, 2]])
    with pytest.raises(ValueError):
        ing.append("ev", [[1, 2]], columns=["k", "v; --"])
    with pytest.raises(ValueError):
        ing.append("ev", [])
    with pytest.raises(ValueError):
        ing.append("ev", [[1, 2], [3]])
    ing.stop()


# ------------------------------------------------- wire-level bit identity


@pytest.mark.parametrize("threaded", [True, False],
                         ids=["threaded", "async"])
def test_wire_append_bit_identical_to_inserts(tmp_path, threaded):
    """The tentpole pin: the same logical rows, once through the append
    verb and once as hand-written INSERT statements, produce
    bit-identical relations — mixed types, NULLs, explicit column lists,
    quotes, floats and all."""
    from cloudberry_tpu.serve.client import Client
    from cloudberry_tpu.serve.server import Server

    cfg = get_config().with_overrides(**{
        "storage.root": str(tmp_path), "serve.threaded": threaded,
        "storage.rows_per_partition": 64,
        "ingest.flush_rows": 4, "ingest.flush_ms": 5.0})
    rows = [[i, i * 0.25, f"n'{i}", i % 2 == 0] for i in range(23)]
    with Server(config=cfg, auth_token="t") as srv:
        c = Client(srv.host, srv.port, token="t")
        for name in ("a", "b"):
            c.sql(f"create table {name} (k bigint, "
                  "v double, s text, f boolean)")
        for i, r in enumerate(rows):
            if i % 3 == 0:  # exercise the explicit-columns path too
                got = c.append("a", [r], columns=["k", "v", "s", "f"])
            else:
                got = c.append("a", [r])
            assert got == 1
        c.append("a", [[99, None, None, None]])
        for i, r in enumerate(rows):
            cols = " (k, v, s, f)" if i % 3 == 0 else ""
            lit = (f"({r[0]}, {r[1]!r}, '{r[2]}'".replace("n'", "n''")
                   + f", {'TRUE' if r[3] else 'FALSE'})")
            c.sql(f"INSERT INTO b{cols} VALUES {lit}")
        c.sql("INSERT INTO b VALUES (99, NULL, NULL, NULL)")
        a = c.sql("select k, v, s, f from a order by k, v")
        b = c.sql("select k, v, s, f from b order by k, v")
        assert a["rows"] == b["rows"]
        assert a["columns"] == b["columns"]
        snap = c.meta("ingest")
        assert snap["enabled"] and snap["rows"] == 24
        assert snap["flushes"] >= 1
        c.close()


# ----------------------------------------------- thresholds / group commit


def test_group_commit_shares_flushes(tmp_path):
    """8 concurrent appenders over a 10ms age window commit in FEWER
    flushes than appends — the group-commit economics the plane exists
    for — and every appender's rows are durable at its return."""
    s = _store_session(tmp_path, **{"ingest.flush_rows": 64,
                                    "ingest.flush_ms": 20.0})
    ing = IngestService(s)
    errs = []

    def feed(base):
        try:
            for j in range(10):
                ing.append("ev", [[10_000 + base * 100 + j, base]])
        except BaseException as e:  # pragma: no cover - surfaced below
            errs.append(e)

    threads = [threading.Thread(target=feed, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ing.stop()
    assert not errs
    log = s.stmt_log
    assert log.counter("ingest_rows") == 80
    assert log.counter("ingest_appends") == 80
    assert 0 < log.counter("ingest_flushes") < 80
    got = s.sql("select count(*) c from ev where k >= 10000").to_pandas()
    assert int(got["c"][0]) == 80


def test_size_threshold_flushes_immediately(tmp_path):
    s = _store_session(tmp_path, **{"ingest.flush_rows": 4,
                                    "ingest.flush_ms": 10_000.0})
    ing = IngestService(s)
    # one appender delivering >= flush_rows rows flushes at once — the
    # age window (10s here) never gates a full buffer
    t0 = time.monotonic()
    ing.append("ev", [[100 + i, i] for i in range(8)])
    assert time.monotonic() - t0 < 5.0
    ing.stop()
    got = s.sql("select count(*) c from ev where k >= 100").to_pandas()
    assert int(got["c"][0]) == 8


# ------------------------------------------------------------ backpressure


def test_queue_full_is_retryable(tmp_path):
    s = _store_session(tmp_path, **{"ingest.max_buffered_rows": 4,
                                    "ingest.flush_rows": 100,
                                    "ingest.flush_ms": 50.0})
    ing = IngestService(s)
    # wedge the flush path so pending rows cannot drain
    FI.inject_fault("ingest_flush", "sleep", sleep_s=0.2)
    bg = threading.Thread(target=lambda: ing.append(
        "ev", [[200 + i, 0] for i in range(4)]))
    bg.start()
    time.sleep(0.02)  # rows buffered, flush wedged in the sleep seam
    with pytest.raises(lifecycle.IngestQueueFull) as ei:
        ing.append("ev", [[300, 0]])
    assert lifecycle.is_retryable(ei.value)
    assert s.stmt_log.counter("ingest_queue_full") == 1
    bg.join()
    FI.reset_fault()
    # backpressure is WHEN, not WHETHER: the retry lands
    assert ing.append("ev", [[300, 0]]) == 1
    ing.stop()
    got = s.sql("select count(*) c from ev where k >= 200").to_pandas()
    assert int(got["c"][0]) == 5


# -------------------------------------------------- device loss mid-flush


def test_device_loss_mid_flush_fails_whole_batch(tmp_path):
    """The chaos seam: an armed ingest_flush error is a device loss
    between ack-intent and commit. The whole batch fails BEFORE any
    statement runs — appenders see the error, nothing partial lands,
    and the post-recovery retry commits."""
    s = _store_session(tmp_path)
    ing = IngestService(s)
    FI.inject_fault("ingest_flush", "error", start_hit=1, end_hit=1)
    with pytest.raises(FI.InjectedFault):
        ing.append("ev", [[400 + i, i] for i in range(10)])
    got = s.sql("select count(*) c from ev where k >= 400").to_pandas()
    assert int(got["c"][0]) == 0, "failed flush must not be durable"
    assert s.stmt_log.counter("ingest_flush_errors") == 1
    # the fault window closed: the caller's retry is clean
    assert ing.append("ev", [[400 + i, i] for i in range(10)]) == 10
    ing.stop()
    got = s.sql("select count(*) c from ev where k >= 400").to_pandas()
    assert int(got["c"][0]) == 10


# ------------------------------------------------------ drain / lifecycle


def test_stop_drains_buffered_rows(tmp_path):
    s = _store_session(tmp_path, **{"ingest.flush_rows": 1000,
                                    "ingest.flush_ms": 60_000.0})
    ing = IngestService(s)
    done = []
    bg = threading.Thread(target=lambda: done.append(
        ing.append("ev", [[500 + i, i] for i in range(6)])))
    bg.start()
    time.sleep(0.05)  # buffered: thresholds are far away
    ing.stop()  # drain flush-on-stop commits them
    bg.join()
    assert done == [6]
    got = s.sql("select count(*) c from ev where k >= 500").to_pandas()
    assert int(got["c"][0]) == 6
    with pytest.raises(lifecycle.ServerDraining):
        ing.append("ev", [[600, 0]])


def test_append_deadline_times_out(tmp_path):
    s = _store_session(tmp_path, **{"ingest.flush_rows": 1000,
                                    "ingest.flush_ms": 60_000.0,
                                    "ingest.max_buffered_rows": 10_000})
    ing = IngestService(s)
    FI.inject_fault("ingest_flush", "sleep", sleep_s=5.0)
    with pytest.raises(lifecycle.StatementTimeout):
        # a second appender makes the first one's batch flushable, but
        # the wedged flush outlives this one's deadline
        bg = threading.Thread(target=lambda: _swallow(
            lambda: ing.append("ev", [[700 + i, 0] for i in range(8)],
                               deadline_s=2.0)))
        bg.start()
        ing.append("ev", [[699, 0]], deadline_s=0.1)
    FI.reset_fault()
    bg.join()
    ing.stop()


def _swallow(fn):
    try:
        fn()
    except BaseException:
        pass


# ------------------------------------------------------ serve_bench smoke


def test_serve_bench_readwrite_smoke():
    """`--mix readwrite` drives reads and wire appends through one closed
    loop while the compaction service folds the debt in the background.
    Pins the write-plane CSV columns, the bounded-delta invariant under
    load, and — against the `--no-compact` A/B baseline (same loop, same
    append share, debt left unfolded) — that reads hold up while
    compaction runs. CPU CI uses a lenient 0.70 floor for the read-QPS
    ratio; the 15% acceptance bound is pinned on hardware runs where the
    2s-window scheduler noise dominating this smoke is absent."""
    import tools.serve_bench as SB

    on = SB.run_mode("direct", "readwrite", clients=4, duration_s=1.5,
                     rows=20_000, tick_s=0.002, max_batch=8)
    off = SB.run_mode("direct", "readwrite", clients=4, duration_s=1.5,
                      rows=20_000, tick_s=0.002, max_batch=8,
                      compact_off=True)
    assert on["requests"] > 0
    assert on["ingest_qps"] > 0 and on["_read_qps"] > 0
    assert on["flush_ms_p95"] >= 0.0
    # compaction ran DURING the measurement window, and held the
    # bounded-delta invariant the service exists for ...
    assert on["compact_chunks"] > 0
    assert on["delta_parts_max"] <= 8
    # ... while the no-compact baseline let the debt grow unbounded
    assert off["compact_chunks"] == 0
    assert off["delta_parts_max"] > 8
    assert on["_read_qps"] >= 0.70 * off["_read_qps"]
    row = SB.csv_row(on)
    assert len(row.split(",")) == len(SB.CSV_HEADER.split(","))


# ---------------------------------------------------------- observability


def test_meta_and_capacity_gauge(tmp_path):
    from cloudberry_tpu.obs import capacity
    from cloudberry_tpu.serve.meta import describe

    s = _store_session(tmp_path, **{"ingest.flush_rows": 1000,
                                    "ingest.flush_ms": 60_000.0})
    assert describe(s, "ingest") == {"enabled": False}
    ing = IngestService(s)
    s._ingest = ing
    bg = threading.Thread(target=lambda: ing.append(
        "ev", [[800, 1], [801, 2]]))
    bg.start()
    time.sleep(0.05)
    snap = describe(s, "ingest")
    assert snap["enabled"] and snap["buffered_rows"] == 2
    assert snap["buffers"][0]["table"] == "ev"
    vals = capacity.refresh_gauges(s)
    assert vals["mem_ingest_buffer_bytes"] > 0
    ing.stop()
    bg.join()
    snap = describe(s, "ingest")
    assert snap["draining"] and snap["buffered_rows"] == 0
    assert snap["flush_ms_p95"] >= 0.0
