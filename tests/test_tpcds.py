"""TPC-DS join-heavy subset (q17/q25/q29) vs a pandas oracle — single and
distributed. These exercise the composite-key PK join (store_sales ⋈
store_returns on (customer, item, ticket)), the many-to-many expansion join
to catalog_sales, three date_dim roles, and stddev_samp decomposition."""

import numpy as np
import pandas as pd
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from tools.tpcds_queries import DS_QUERIES
from tools.tpcdsgen import load_tpcds

from tests.test_tpch import assert_frames_match


@pytest.fixture(scope="module")
def ds_session():
    s = cb.Session()
    load_tpcds(s, scale=0.5, seed=11)
    tables = {n: t.to_pandas() for n, t in s.catalog.tables.items()}
    return s, tables


def _joined(t):
    ss, sr, cs = (t["store_sales"], t["store_returns"], t["catalog_sales"])
    dd, st, it = t["date_dim"], t["store"], t["item"]
    j = ss.merge(sr, left_on=["ss_customer_sk", "ss_item_sk",
                              "ss_ticket_number"],
                 right_on=["sr_customer_sk", "sr_item_sk",
                           "sr_ticket_number"])
    j = j.merge(cs, left_on=["sr_customer_sk", "sr_item_sk"],
                right_on=["cs_bill_customer_sk", "cs_item_sk"])
    j = j.merge(dd.add_prefix("d1_"), left_on="ss_sold_date_sk",
                right_on="d1_d_date_sk")
    j = j.merge(dd.add_prefix("d2_"), left_on="sr_returned_date_sk",
                right_on="d2_d_date_sk")
    j = j.merge(dd.add_prefix("d3_"), left_on="cs_sold_date_sk",
                right_on="d3_d_date_sk")
    j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    return j


def oracle_q17(t):
    j = _joined(t)
    q = ["2000Q1", "2000Q2", "2000Q3"]
    j = j[(j.d1_d_quarter_name == "2000Q1")
          & j.d2_d_quarter_name.isin(q) & j.d3_d_quarter_name.isin(q)]
    g = j.groupby(["i_item_id", "i_item_desc", "s_state"], as_index=False).agg(
        store_sales_quantitycount=("ss_quantity", "size"),
        store_sales_quantityave=("ss_quantity", "mean"),
        store_sales_quantitystdev=("ss_quantity", "std"),
        store_returns_quantitycount=("sr_return_quantity", "size"),
        store_returns_quantityave=("sr_return_quantity", "mean"),
        store_returns_quantitystdev=("sr_return_quantity", "std"),
        catalog_sales_quantitycount=("cs_quantity", "size"),
        catalog_sales_quantityave=("cs_quantity", "mean"),
        catalog_sales_quantitystdev=("cs_quantity", "std"),
    ).fillna(0.0)  # engine yields 0 where SQL would NULL (n=1 stddev)
    return g.sort_values(["i_item_id", "i_item_desc", "s_state"]) \
        .head(100).reset_index(drop=True)


def oracle_q25(t):
    j = _joined(t)
    j = j[(j.d1_d_moy == 4) & (j.d1_d_year == 2000)
          & j.d2_d_moy.between(4, 10) & (j.d2_d_year == 2000)
          & j.d3_d_moy.between(4, 10) & (j.d3_d_year == 2000)]
    g = j.groupby(["i_item_id", "i_item_desc", "s_store_id", "s_store_name"],
                  as_index=False).agg(
        store_sales_profit=("ss_net_profit", "sum"),
        store_returns_loss=("sr_net_loss", "sum"),
        catalog_sales_profit=("cs_net_profit", "sum"))
    return g.sort_values(["i_item_id", "i_item_desc", "s_store_id",
                          "s_store_name"]).head(100).reset_index(drop=True)


def oracle_q29(t):
    j = _joined(t)
    j = j[(j.d1_d_moy == 4) & (j.d1_d_year == 1999)
          & j.d2_d_moy.between(4, 7) & (j.d2_d_year == 1999)
          & j.d3_d_year.isin([1999, 2000, 2001])]
    g = j.groupby(["i_item_id", "i_item_desc", "s_store_id", "s_store_name"],
                  as_index=False).agg(
        store_sales_quantity=("ss_quantity", "sum"),
        store_returns_quantity=("sr_return_quantity", "sum"),
        catalog_sales_quantity=("cs_quantity", "sum"))
    return g.sort_values(["i_item_id", "i_item_desc", "s_store_id",
                          "s_store_name"]).head(100).reset_index(drop=True)


def _star(t):
    """store_sales ⋈ date_dim ⋈ item — the single-fact star join the
    reporting subset (q3/q42/q52/q55/q98) shares."""
    return (t["store_sales"]
            .merge(t["date_dim"], left_on="ss_sold_date_sk",
                   right_on="d_date_sk")
            .merge(t["item"], left_on="ss_item_sk", right_on="i_item_sk"))


def oracle_q3(t):
    j = _star(t)
    j = j[(j.i_manufact_id == 7) & (j.d_moy == 11)]
    g = j.groupby(["d_year", "i_brand_id", "i_brand"],
                  as_index=False).agg(sum_agg=("ss_net_profit", "sum"))
    return g.sort_values(["d_year", "sum_agg", "i_brand_id"],
                         ascending=[True, False, True]) \
        .head(100).reset_index(drop=True)


def oracle_q42(t):
    j = _star(t)
    j = j[(j.d_moy == 11) & (j.d_year == 2000)]
    g = j.groupby(["d_year", "i_category"], as_index=False) \
        .agg(total=("ss_ext_sales_price", "sum"))
    return g[["d_year", "i_category", "total"]] \
        .sort_values(["total", "d_year", "i_category"],
                     ascending=[False, True, True]) \
        .head(100).reset_index(drop=True)


def oracle_q52(t):
    j = _star(t)
    j = j[(j.i_manager_id == 1) & (j.d_moy == 12) & (j.d_year == 2000)]
    g = j.groupby(["d_year", "i_brand_id", "i_brand"],
                  as_index=False).agg(ext_price=("ss_ext_sales_price",
                                                 "sum"))
    return g.sort_values(["d_year", "ext_price", "i_brand_id"],
                         ascending=[True, False, True]) \
        .head(100).reset_index(drop=True)


def oracle_q55(t):
    j = _star(t)
    j = j[(j.i_manager_id == 3) & (j.d_moy == 11) & (j.d_year == 1999)]
    g = j.groupby(["i_brand_id", "i_brand"], as_index=False) \
        .agg(ext_price=("ss_ext_sales_price", "sum"))
    return g.sort_values(["ext_price", "i_brand_id"],
                         ascending=[False, True]) \
        .head(100).reset_index(drop=True)


def _revenue_ratio(j, price_col, categories, lo, hi):
    """q98/q12 pipeline: filter, group by the 5 item columns, revenue
    ratio within class, canonical sort."""
    j = j[j.i_category.isin(categories)
          & (j.d_date >= lo) & (j.d_date <= hi)]
    g = j.groupby(["i_item_id", "i_item_desc", "i_category", "i_class",
                   "i_current_price"], as_index=False) \
        .agg(itemrevenue=(price_col, "sum"))
    g["revenueratio"] = (g.itemrevenue * 100.0
                         / g.groupby("i_class")
                         .itemrevenue.transform("sum"))
    return g.sort_values(["i_category", "i_class", "i_item_id",
                          "i_item_desc", "revenueratio"]) \
        .head(100).reset_index(drop=True)


def oracle_q98(t):
    return _revenue_ratio(_star(t), "ss_ext_sales_price",
                          ["Books", "Music"],
                          pd.Timestamp(2000, 2, 1),
                          pd.Timestamp(2000, 3, 1))


def oracle_q27(t):
    j = _star(t).merge(t["store"], left_on="ss_store_sk",
                       right_on="s_store_sk")
    j = j[j.d_year == 2000]

    def agg(g, keys):
        out = g.groupby(keys, as_index=False).agg(
            agg1=("ss_quantity", "mean"),
            agg2=("ss_ext_sales_price", "mean"),
            agg3=("ss_net_profit", "mean"))
        return out

    lvl2 = agg(j, ["i_item_id", "s_state"])
    lvl2["g_state"] = 0
    lvl1 = agg(j, ["i_item_id"])
    lvl1["s_state"] = None
    lvl1["g_state"] = 1
    lvl0 = pd.DataFrame([{"i_item_id": None, "s_state": None,
                          "g_state": 1,
                          "agg1": j.ss_quantity.mean(),
                          "agg2": j.ss_ext_sales_price.mean(),
                          "agg3": j.ss_net_profit.mean()}])
    cols = ["i_item_id", "s_state", "g_state", "agg1", "agg2", "agg3"]
    out = pd.concat([lvl2[cols], lvl1[cols], lvl0[cols]])
    return out.sort_values(["i_item_id", "s_state"],
                           na_position="last") \
        .head(100).reset_index(drop=True)


def oracle_q65(t):
    j = t["store_sales"].merge(t["date_dim"],
                               left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j[j.d_year == 2000]
    rev = j.groupby(["ss_store_sk", "ss_item_sk"], as_index=False).agg(
        revenue=("ss_ext_sales_price", "sum"))
    ave = rev.groupby("ss_store_sk", as_index=False).agg(
        ave=("revenue", "mean"))
    m = rev.merge(ave, on="ss_store_sk")
    m = m[m.revenue <= 0.1 * m.ave]
    out = (m.merge(t["store"], left_on="ss_store_sk",
                   right_on="s_store_sk")
           .merge(t["item"], left_on="ss_item_sk", right_on="i_item_sk"))
    out = out[["s_store_name", "i_item_desc", "revenue",
               "i_current_price", "i_brand"]]
    return out.sort_values(["s_store_name", "i_item_desc", "revenue",
                            "i_current_price", "i_brand"]) \
        .head(100).reset_index(drop=True)


def _rollup_rank(agg, measure, ascending):
    """q36/q86 scaffolding: ROLLUP(i_category, i_class) levels, rank
    within parent (level-0 rows partition by their category, higher
    levels each form one partition), canonical sort."""
    lvl2 = agg(["i_category", "i_class"])
    lvl2["lochierarchy"] = 0
    lvl1 = agg(["i_category"])
    lvl1["i_class"] = np.nan
    lvl1["lochierarchy"] = 1
    lvl0 = agg([])
    lvl0["i_category"] = np.nan
    lvl0["i_class"] = np.nan
    lvl0["lochierarchy"] = 2
    allr = pd.concat([lvl2, lvl1, lvl0], ignore_index=True)
    allr["_parent"] = np.where(allr.lochierarchy == 0,
                               allr.i_category, "$none")
    allr["rank_within_parent"] = allr.groupby(
        ["lochierarchy", "_parent"])[measure] \
        .rank(method="min", ascending=ascending).astype(np.int64)
    allr["_ck"] = np.where(allr.lochierarchy == 0,
                           allr.i_category, np.nan)
    allr = allr.sort_values(["lochierarchy", "_ck", "rank_within_parent"],
                            ascending=[False, True, True],
                            na_position="last")
    cols = [measure, "i_category", "i_class", "lochierarchy",
            "rank_within_parent"]
    return allr[cols].head(100).reset_index(drop=True)


def oracle_q36(t):
    j = _star(t).merge(t["store"], left_on="ss_store_sk",
                       right_on="s_store_sk")
    j = j[(j.d_year == 2001)
          & j.s_state.isin(["TN", "CA", "TX", "WA"])]

    def agg(keys):
        if keys:
            g = j.groupby(keys, as_index=False).agg(
                np_=("ss_net_profit", "sum"),
                sp=("ss_ext_sales_price", "sum"))
        else:
            g = pd.DataFrame([{"np_": j.ss_net_profit.sum(),
                               "sp": j.ss_ext_sales_price.sum()}])
        g["gross_margin"] = g.np_ / g.sp
        return g

    return _rollup_rank(agg, "gross_margin", ascending=True)


def _web_star(t):
    return (t["web_sales"]
            .merge(t["item"], left_on="ws_item_sk", right_on="i_item_sk")
            .merge(t["date_dim"], left_on="ws_sold_date_sk",
                   right_on="d_date_sk"))


def oracle_q12(t):
    return _revenue_ratio(_web_star(t), "ws_ext_sales_price",
                          ["Sports", "Books"],
                          pd.Timestamp(1999, 2, 22),
                          pd.Timestamp(1999, 3, 24))


def _cat_star(t):
    return (t["catalog_sales"]
            .merge(t["item"], left_on="cs_item_sk", right_on="i_item_sk")
            .merge(t["date_dim"], left_on="cs_sold_date_sk",
                   right_on="d_date_sk"))


def oracle_q20(t):
    return _revenue_ratio(_cat_star(t), "cs_ext_sales_price",
                          ["Sports", "Music"],
                          pd.Timestamp(1999, 2, 22),
                          pd.Timestamp(1999, 3, 24))


def oracle_q21(t):
    j = (t["inventory"]
         .merge(t["warehouse"], left_on="inv_warehouse_sk",
                right_on="w_warehouse_sk")
         .merge(t["item"], left_on="inv_item_sk", right_on="i_item_sk")
         .merge(t["date_dim"], left_on="inv_date_sk",
                right_on="d_date_sk"))
    pivot = pd.Timestamp(2000, 3, 11)
    j = j[(j.i_current_price >= 0.99) & (j.i_current_price <= 10.00)
          & (j.d_date >= pivot - pd.Timedelta(days=30))
          & (j.d_date <= pivot + pd.Timedelta(days=30))]
    j = j.assign(
        before=np.where(j.d_date < pivot, j.inv_quantity_on_hand, 0),
        after=np.where(j.d_date >= pivot, j.inv_quantity_on_hand, 0))
    g = j.groupby(["w_warehouse_name", "i_item_id"], as_index=False) \
        .agg(inv_before=("before", "sum"), inv_after=("after", "sum"))
    ratio = np.where(g.inv_before > 0,
                     g.inv_after / np.maximum(g.inv_before, 1), np.nan)
    g = g[(ratio >= 2.0 / 3.0) & (ratio <= 3.0 / 2.0)]
    return g.sort_values(["w_warehouse_name", "i_item_id"]) \
        .head(100).reset_index(drop=True)


def oracle_q86(t):
    j = _web_star(t)
    j = j[j.d_year == 2000]

    def agg(keys):
        if keys:
            return j.groupby(keys, as_index=False).agg(
                total_sum=("ws_net_profit", "sum"))
        return pd.DataFrame([{"total_sum": j.ws_net_profit.sum()}])

    return _rollup_rank(agg, "total_sum", ascending=False)


ORACLES = {"q17": oracle_q17, "q25": oracle_q25, "q29": oracle_q29,
           "q3": oracle_q3, "q42": oracle_q42, "q52": oracle_q52,
           "q55": oracle_q55, "q98": oracle_q98, "q27": oracle_q27,
           "q65": oracle_q65, "q36": oracle_q36,
           "q12": oracle_q12, "q21": oracle_q21, "q86": oracle_q86,
           "q20": oracle_q20}


@pytest.mark.parametrize("qname", sorted(ORACLES))
def test_tpcds_query(ds_session, qname):
    session, tables = ds_session
    got = session.sql(DS_QUERIES[qname]).to_pandas()
    exp = ORACLES[qname](tables)
    assert len(exp) > 0, "oracle result is vacuous — fix the generator"
    assert_frames_match(got, exp, qname)


@pytest.fixture(scope="module")
def ds_dist_session():
    s = cb.Session(Config(n_segments=8))
    load_tpcds(s, scale=0.5, seed=11)
    tables = {n: t.to_pandas() for n, t in s.catalog.tables.items()}
    return s, tables


@pytest.mark.parametrize("qname", sorted(ORACLES))
def test_tpcds_distributed(ds_dist_session, qname):
    s, tables = ds_dist_session
    got = s.sql(DS_QUERIES[qname]).to_pandas()
    exp = ORACLES[qname](tables)
    assert_frames_match(got, exp, qname)
