"""Scheduled statements (serve/cron.py) — the pg_cron analog."""

import time

import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config
from cloudberry_tpu.serve.client import Client
from cloudberry_tpu.serve.cron import CronError, Scheduler
from cloudberry_tpu.serve.server import Server


def test_scheduler_runs_jobs_deterministically(tmp_path):
    s = cb.Session(get_config().with_overrides(
        **{"storage.root": str(tmp_path)}))
    s.sql("create table log (x bigint)")
    sched = Scheduler(s)
    sched.schedule("tick", 10.0, "insert into log values (1)")
    now = time.monotonic()
    assert sched.run_due(now + 11) == 1
    assert sched.run_due(now + 12) == 0   # not due again yet
    assert sched.run_due(now + 22) == 1
    assert s.sql("select count(*) from log").to_pandas().iloc[0, 0] == 2
    st = sched.status()[0]
    assert st["runs"] == 2 and st["failures"] == 0


def test_job_failure_isolated(tmp_path):
    s = cb.Session(get_config().with_overrides(
        **{"storage.root": str(tmp_path)}))
    sched = Scheduler(s)
    sched.schedule("bad", 5.0, "select * from missing_table")
    now = time.monotonic()
    assert sched.run_due(now + 6) == 1  # ran, failed, scheduler alive
    st = sched.status()[0]
    assert st["failures"] == 1 and "missing_table" in st["last_error"]


def test_jobs_persist_across_restart(tmp_path):
    cfg = get_config().with_overrides(**{"storage.root": str(tmp_path)})
    a = cb.Session(cfg)
    Scheduler(a).schedule("keep", 60.0, "select 1")
    b = Scheduler(cb.Session(cfg)).load()
    assert [j["name"] for j in b.status()] == ["keep"]
    b.unschedule("keep")
    c = Scheduler(cb.Session(cfg)).load()
    assert c.status() == []
    with pytest.raises(CronError):
        c.unschedule("keep")


def test_cron_over_the_wire(tmp_path):
    cfg = get_config().with_overrides(**{"storage.root": str(tmp_path)})
    boot = cb.Session(cfg)
    boot.sql("create table wlog (x bigint)")
    with Server(config=cfg, port=0) as srv:
        srv.cron.tick_s = 0.05
        with Client(srv.host, srv.port) as c:
            c._request({"cron": {"op": "schedule", "name": "w",
                                 "interval_s": 0.1,
                                 "sql": "insert into wlog values (1)"}})
            deadline = time.time() + 15
            while time.time() < deadline:
                n = c.rows("select count(*) from wlog")[0][0]
                if n >= 2:
                    break
                time.sleep(0.1)
            assert n >= 2, "cron job never ran over the wire"
            jobs = c._request({"cron": {"op": "status"}})["jobs"]
            assert jobs[0]["name"] == "w" and jobs[0]["runs"] >= 2
            c._request({"cron": {"op": "unschedule", "name": "w"}})
            assert c._request({"cron": {"op": "status"}})["jobs"] == []


def test_cron_uses_server_statement_lock():
    """In shared-session mode the scheduler must run job SQL through the
    Server's readers-writer lock, not raw session.sql — a scheduled
    write would otherwise race concurrent client reads (advisor r4)."""
    sess = cb.Session()  # explicit session => shared (legacy) mode
    sess.sql("create table clk (x bigint)")
    with Server(session=sess, port=0) as srv:
        assert srv.per_connection is False
        assert srv.cron.execute == srv._cron_execute
        # the executor path itself must work for both classes
        srv._cron_execute("insert into clk values (1)")
        assert srv._cron_execute(
            "select count(*) from clk").to_pandas().iloc[0, 0] == 1
        srv.cron.schedule("j", 0.05, "insert into clk values (2)")
        deadline = time.time() + 10
        while time.time() < deadline:
            if srv.cron.status()[0]["runs"] >= 1:
                break
            srv.cron.run_due(time.monotonic() + 1)
        assert srv.cron.status()[0]["failures"] == 0
