"""Faulty-IO shim, checksums, fsck/GC, stale-lock recovery (ISSUE 19).

The in-process half of the crash-only story: every fault the torture
harness provokes by killing a real server has a deterministic unit test
here — torn/short/dropped-fsync/ENOSPC/EIO writes through the iofault
shim, content-checksum detection of flipped bits, fsck's corruption/
orphan split, and the stale-lock break a killed writer leaves behind.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu import lifecycle
from cloudberry_tpu.config import Config
from cloudberry_tpu.storage import iofault
from cloudberry_tpu.storage import micropartition as mp
from cloudberry_tpu.storage.fsck import fsck
from cloudberry_tpu.storage.table_store import TableStore
from cloudberry_tpu.utils import faultinject


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.reset_fault()
    iofault.reset_counters()
    yield
    faultinject.reset_fault()
    iofault.reset_counters()


def _sess(tmp_path, **over):
    cfg = Config().with_overrides(
        **{"storage.root": str(tmp_path / "store"), **over})
    return cb.Session(cfg)


def _insert(s, n0=0, n=4):
    s.sql("create table t (k bigint, v bigint) distributed by (k)")
    vals = ", ".join(f"({k}, {k * 7})" for k in range(n0, n0 + n))
    s.sql(f"insert into t values {vals}")


def _rows(s):
    df = s.sql("select k, v from t order by k").to_pandas()
    return list(zip(df["k"].tolist(), df["v"].tolist()))


# ------------------------------------------------------------- the shim


def test_torn_manifest_write_keeps_old_snapshot(tmp_path):
    s = _sess(tmp_path)
    _insert(s)
    before = _rows(s)
    faultinject.inject_fault("io_manifest_write", "torn")
    with pytest.raises(lifecycle.StorageIOError):
        s.sql("insert into t values (100, 700)")
    faultinject.reset_fault()
    assert iofault.io_error_count() == 1
    # the torn v{N}.json is unreachable: reads still serve the old
    # snapshot, in this session and a fresh one
    assert _rows(s) == before
    s2 = _sess(tmp_path)
    assert _rows(s2) == before
    # and fsck calls the residue an orphan, never corruption
    rep = fsck(str(tmp_path / "store"), deep=True)
    assert rep["clean"], rep["problems"]


@pytest.mark.parametrize("action", ["enospc", "eio", "short"])
def test_io_failures_surface_typed_and_counted(tmp_path, action):
    s = _sess(tmp_path)
    _insert(s)
    before = _rows(s)
    faultinject.inject_fault("io_partition_write", action)
    with pytest.raises(lifecycle.StorageIOError) as ei:
        s.sql("insert into t values (100, 700)")
    assert lifecycle.is_retryable(ei.value) is True
    assert iofault.io_error_count() == 1
    faultinject.reset_fault()
    assert _rows(s) == before
    # the retry goes through clean — transient means transient
    s.sql("insert into t values (100, 700)")
    assert (100, 700) in _rows(s)


def test_dropped_fsync_lost_at_crash_is_caught_by_fsck(tmp_path):
    """The latent bug this shim closed: a partition that only reached
    the page cache when the manifest committed. fsync_drop + simulated
    power loss reproduces it; fsck --deep names the missing file."""
    s = _sess(tmp_path)
    _insert(s)
    faultinject.inject_fault("io_partition_write", "fsync_drop")
    s.sql("insert into t values (100, 700)")  # acked!
    faultinject.reset_fault()
    assert iofault.unsynced_paths()
    lost = iofault.simulated_crash()
    assert len(lost) == 1
    rep = fsck(str(tmp_path / "store"), deep=True)
    assert not rep["clean"]
    assert any("missing" in p for p in rep["problems"])


def test_crash_action_exits_hard(tmp_path, monkeypatch):
    codes = []
    monkeypatch.setattr(os, "_exit", lambda c: codes.append(c))
    faultinject.inject_fault("io_manifest_write", "crash")
    s = _sess(tmp_path)
    _insert(s)
    assert codes and codes[0] == 137


def test_atomic_json_fsync_drop_loses_destination_at_crash(tmp_path):
    """The dropped fsync must follow the os.replace rename: the bytes at
    risk live at the DESTINATION once the temp file is renamed onto it,
    so simulated power loss tears the target — not a vanished temp name
    (which would make the fault a silent no-op on every atomic-JSON
    seam: CURRENT discipline, sequences, journal, feedback)."""
    path = str(tmp_path / "obj.json")
    iofault.atomic_json(path, {"v": 1})
    faultinject.inject_fault("io_atomic_json", "fsync_drop")
    faultinject.fault_point("io_atomic_json")  # stash like a caller
    iofault.atomic_json(path, {"v": 2})
    faultinject.reset_fault()
    assert iofault.unsynced_paths() == [path]
    assert iofault.simulated_crash() == [path]
    # rewrite of an existing file: the buffered bytes are gone
    assert open(path, "rb").read() == b""
    # a FIRST write (no prior file) vanishes entirely instead
    fresh = str(tmp_path / "fresh.json")
    faultinject.inject_fault("io_atomic_json", "fsync_drop")
    faultinject.fault_point("io_atomic_json")
    iofault.atomic_json(fresh, {"v": 1})
    faultinject.reset_fault()
    assert iofault.simulated_crash() == [fresh]
    assert not os.path.exists(fresh)


def test_atomic_json_failure_leaves_target_intact(tmp_path):
    path = str(tmp_path / "obj.json")
    iofault.atomic_json(path, {"v": 1})
    faultinject.inject_fault("io_atomic_json", "torn")
    faultinject.fault_point("io_atomic_json")  # stash like a caller
    with pytest.raises(lifecycle.StorageIOError):
        iofault.atomic_json(path, {"v": 2})
    with open(path) as f:
        assert json.load(f) == {"v": 1}
    # no tmp droppings either — the failed replace cleans up
    assert [f for f in os.listdir(tmp_path) if f.startswith("tmp")] == []


def test_arm_from_env_parses_windows():
    n = faultinject.arm_from_env(
        "io_manifest_write=crash@3; io_partition_write=torn ;bad")
    assert n == 2
    armed = faultinject.list_faults()["armed"]
    assert armed["io_manifest_write"]["action"] == "crash"
    assert armed["io_manifest_write"]["start_hit"] == 3
    assert armed["io_partition_write"]["action"] == "torn"


# ----------------------------------------------------------- checksums


def test_bit_flip_raises_corruption_not_wrong_answer(tmp_path):
    s = _sess(tmp_path)
    _insert(s, n=8)
    part = next(f for f in os.listdir(tmp_path / "store" / "t")
                if f.endswith(".cbmp"))
    path = str(tmp_path / "store" / "t" / part)
    raw = bytearray(open(path, "rb").read())
    raw[len(mp.MAGIC) + 3] ^= 0x40  # flip one bit inside a column blob
    open(path, "wb").write(bytes(raw))
    s2 = _sess(tmp_path)
    with pytest.raises(lifecycle.StorageCorruptionError) as ei:
        # select BOTH columns: the flipped byte is in the first column
        # blob, and only decoded columns are verified
        s2.sql("select k, v from t").to_pandas()
    assert lifecycle.is_retryable(ei.value) is False
    # fsck --deep reaches the same verdict offline
    rep = fsck(str(tmp_path / "store"), deep=True)
    assert not rep["clean"]
    assert any("checksum" in p for p in rep["problems"])


def test_unknown_cksum_algo_flagged_offline_lenient_online(tmp_path):
    """A bit flip can hit the 'crc32:' label itself. Offline, fsck must
    report it — 'unverifiable' reading as 'clean' would silently disable
    checking for that blob. The hot read path alone stays lenient (a
    genuinely newer algorithm must not brick older readers)."""
    s = _sess(tmp_path)
    _insert(s, n=8)
    part = next(f for f in os.listdir(tmp_path / "store" / "t")
                if f.endswith(".cbmp"))
    path = str(tmp_path / "store" / "t" / part)
    raw = open(path, "rb").read()
    idx = raw.rindex(b"crc32:")  # last occurrence = inside the footer
    open(path, "wb").write(raw[:idx] + b"crc99:" + raw[idx + 6:])
    problems = mp.verify_file(path)
    assert any("unknown checksum algorithm" in p for p in problems)
    rep = fsck(str(tmp_path / "store"), deep=True)
    assert not rep["clean"]
    # online: still served (lenient), never a wrong answer from it
    s2 = _sess(tmp_path)
    assert len(_rows(s2)) == 8


def test_verify_off_is_a_config_choice(tmp_path):
    s = _sess(tmp_path, **{"storage.verify_checksums": False})
    assert s.store.verify_checksums is False
    s2 = _sess(tmp_path)
    assert s2.store.verify_checksums is True


def test_footer_checksums_survive_compaction(tmp_path):
    s = _sess(tmp_path)
    _insert(s)
    s.sql("insert into t values (50, 350)")
    from cloudberry_tpu.storage.compact import CompactionService

    CompactionService(s).run_once()
    for f in os.listdir(tmp_path / "store" / "t"):
        if not f.endswith(".cbmp"):
            continue
        footer = mp.read_footer(str(tmp_path / "store" / "t" / f))
        assert all("cksum" in c for c in footer["columns"])
        assert mp.verify_file(str(tmp_path / "store" / "t" / f)) == []


# ------------------------------------------------------------- fsck/GC


def test_fsck_orphans_grace_and_gc(tmp_path):
    s = _sess(tmp_path)
    _insert(s)
    root = str(tmp_path / "store")
    orphan = os.path.join(root, "t", "part-deadbeef.cbmp")
    open(orphan, "wb").write(b"not a partition")
    # young orphan: reported, protected by grace
    rep = fsck(root, grace_s=3600.0, gc=True)
    assert rep["clean"]
    assert [o["path"] for o in rep["orphans"]] == ["t/part-deadbeef.cbmp"]
    assert not rep["orphans"][0]["collectable"]
    assert os.path.exists(orphan)
    # past grace: collected
    rep = fsck(root, grace_s=0.0, gc=True)
    assert rep["collected"] == ["t/part-deadbeef.cbmp"]
    assert not os.path.exists(orphan)
    assert fsck(root)["orphans"] == []


def test_fsck_protects_journal_pending_files(tmp_path):
    s = _sess(tmp_path)
    _insert(s)
    root = str(tmp_path / "store")
    pend = os.path.join(root, "t", "part-pending.cbmp")
    open(pend, "wb").write(b"replacement-in-flight")
    with open(os.path.join(root, "_COMPACTION.json"), "w") as f:
        json.dump({"counters": {}, "pending":
                   {"table": "t", "files": ["part-pending.cbmp"]}}, f)
    rep = fsck(root, grace_s=0.0, gc=True)
    assert os.path.exists(pend)  # the journal owns it, GC must not
    assert all(o["path"] != "t/part-pending.cbmp"
               for o in rep["orphans"])


def test_fsck_gc_refuses_census_when_current_is_torn(tmp_path):
    """The one state fsck exists to diagnose must never trigger GC data
    loss: with CURRENT's manifest torn, the referenced-set is unknowable,
    so NOTHING in the table may be classified (or collected) as an
    orphan — not even with grace_s=0."""
    s = _sess(tmp_path)
    _insert(s)
    root = str(tmp_path / "store")
    tdir = os.path.join(root, "t")
    parts = sorted(f for f in os.listdir(tdir) if f.endswith(".cbmp"))
    assert parts
    with open(os.path.join(tdir, "_manifests", "CURRENT")) as f:
        v = f.read().strip()
    mpath = os.path.join(tdir, "_manifests", f"v{v}.json")
    raw = open(mpath, "rb").read()
    open(mpath, "wb").write(raw[:len(raw) // 2])  # tear it
    rep = fsck(root, grace_s=0.0, gc=True)
    assert not rep["clean"]
    assert any("CURRENT manifest unreadable" in p for p in rep["problems"])
    assert rep["census_skipped"] == ["t"]
    assert rep["orphans"] == [] and rep["collected"] == []
    # every data file and manifest survived
    assert sorted(f for f in os.listdir(tdir)
                  if f.endswith(".cbmp")) == parts
    assert os.path.exists(mpath)


def test_fsck_census_skipped_for_table_with_problems(tmp_path):
    """A table that recorded ANY problem keeps its unreferenced files:
    'orphan' may mean 'live file we failed to account for'."""
    s = _sess(tmp_path)
    _insert(s)
    root = str(tmp_path / "store")
    part = next(f for f in os.listdir(os.path.join(root, "t"))
                if f.endswith(".cbmp"))
    os.unlink(os.path.join(root, "t", part))  # referenced-but-missing
    stray = os.path.join(root, "t", "part-deadbeef.cbmp")
    open(stray, "wb").write(b"unreferenced")
    rep = fsck(root, grace_s=0.0, gc=True)
    assert not rep["clean"]
    assert rep["census_skipped"] == ["t"]
    assert rep["collected"] == [] and os.path.exists(stray)


def test_fsck_flags_delete_vector_out_of_range(tmp_path):
    s = _sess(tmp_path)
    _insert(s)
    store = TableStore(str(tmp_path / "store"))
    man = store.read_manifest("t")
    man["partitions"][0]["deleted"] = [10_000]
    with store.lock():
        store._commit("t", man)
    rep = fsck(str(tmp_path / "store"))
    assert not rep["clean"]
    assert any("out of range" in p for p in rep["problems"])


# --------------------------------------------------- crash-safe store lock
# flock(2), not a pid-stamped O_EXCL file: the kernel releases the lock
# when the holder dies, so a killed writer needs no stale-lock breaking —
# and breaking-by-unlink had a TOCTOU that could evict a LIVE holder.


def test_leftover_lock_file_from_dead_holder_does_not_block(tmp_path):
    store = TableStore(str(tmp_path / "store"))
    lockfile = os.path.join(store.root, "_LOCK")
    # what a SIGKILLed writer leaves: the file (with its pid), no flock
    with open(lockfile, "w") as f:
        f.write("999999999")
    with store.lock(timeout_s=2.0):
        assert open(lockfile).read() == str(os.getpid())
    # released: the file persists (unlink-on-release would re-open the
    # unlinked-inode race), its pid content is cleared
    assert open(lockfile).read() == ""


def test_live_lock_is_respected(tmp_path):
    import fcntl

    store = TableStore(str(tmp_path / "store"))
    lockfile = os.path.join(store.root, "_LOCK")
    fd = os.open(lockfile, os.O_CREAT | os.O_RDWR)
    fcntl.flock(fd, fcntl.LOCK_EX)  # a live holder (separate fd = own OFD)
    try:
        with pytest.raises(RuntimeError, match="lock timeout"):
            with store.lock(timeout_s=0.3):
                pass
    finally:
        os.close(fd)
    with store.lock(timeout_s=2.0):  # released → acquirable again
        pass


def test_lock_releases_when_holder_process_dies(tmp_path):
    store = TableStore(str(tmp_path / "store"))
    lockfile = os.path.join(store.root, "_LOCK")
    code = ("import fcntl, os, sys\n"
            f"fd = os.open({lockfile!r}, os.O_CREAT | os.O_RDWR)\n"
            "fcntl.flock(fd, fcntl.LOCK_EX)\n"
            "sys.stdout.write('locked'); sys.stdout.flush()\n"
            "os._exit(137)\n")
    p = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True)
    assert p.stdout == "locked"
    with store.lock(timeout_s=2.0):  # no operator, no breaking logic
        pass


# ------------------------------------------------- durable write basics


def test_durable_write_and_checksum_helpers(tmp_path):
    p = str(tmp_path / "f.bin")
    iofault.durable_write(p, b"hello")
    assert open(p, "rb").read() == b"hello"
    h = iofault.content_hash(b"hello")
    assert h.startswith("crc32:")
    assert iofault.hash_matches(h, b"hello")
    assert not iofault.hash_matches(h, b"hellp")
    assert iofault.hash_matches("xxh3:feed", b"anything")  # unknown algo
    assert iofault.hash_verdict(h, b"hello") == "ok"
    assert iofault.hash_verdict(h, b"hellp") == "mismatch"
    assert iofault.hash_verdict("xxh3:feed", b"anything") == "unknown"
