"""Spill shapes beyond agg/top-N: full external ORDER BY and windows
(exec/tiled.py SortTiledExecutable / WindowTiledExecutable and their
distributed twins) — the tuplesort.c spill-to-tape and nodeWindowAgg.c
disciplines with host RAM as the workfile.

Contract: an admission-rejected unbounded-sort or windowed statement
completes tiled (n_tiles > 1) with results exactly equal to the
all-in-memory path, single-node and on the 8-segment mesh.
"""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config

SORT_Q = ("SELECT g, v, w FROM fact JOIN dim ON fact.k = dim.k "
          "WHERE v < 50 ORDER BY g, v DESC, w")
# the ROWS frame orders by (v, w): w is ~unique, making the frame
# deterministic — with ties the frame content would legitimately differ
# between execution orders
WIN_Q = ("SELECT g, v, rank() over (partition by g order by v desc) AS r,"
         " sum(v) over (partition by g) AS sv, "
         "avg(w) over (partition by g order by v, w "
         "rows between 2 preceding and current row) AS aw "
         "FROM fact JOIN dim ON fact.k = dim.k")


def _load(s, n_fact=200_000, n_dim=500):
    rng = np.random.default_rng(3)
    s.sql("CREATE TABLE dim (k BIGINT, g BIGINT) DISTRIBUTED BY (k)")
    s.sql("CREATE TABLE fact (k BIGINT, v BIGINT, w DOUBLE) "
          "DISTRIBUTED BY (k)")
    s.catalog.table("dim").set_data(
        {"k": np.arange(n_dim), "g": np.arange(n_dim) % 300})
    s.catalog.table("fact").set_data(
        {"k": rng.integers(0, n_dim, n_fact),
         "v": rng.integers(0, 100, n_fact),
         "w": rng.standard_normal(n_fact)})


def _mk(nseg, budget=None):
    ov = {"n_segments": nseg}
    if budget is not None:
        ov["resource.query_mem_bytes"] = budget
    s = cb.Session(get_config().with_overrides(**ov))
    _load(s)
    return s


@pytest.fixture(scope="module", params=[1, 8], ids=["single", "dist8"])
def pair(request):
    return (_mk(request.param), _mk(request.param, budget=4 << 20),
            request.param)


def test_external_sort_matches_in_memory(pair):
    ref, tiled, nseg = pair
    want = ref.sql(SORT_Q).to_pandas()
    got = tiled.sql(SORT_Q).to_pandas()
    assert want.equals(got)
    rep = tiled.last_tiled_report
    assert rep["tiled"] and rep["mode"] == "sort" and rep["n_tiles"] > 1
    assert rep["est_step_bytes"] <= rep["budget_bytes"]


def test_window_spill_matches_in_memory(pair):
    ref, tiled, nseg = pair
    order = ["g", "v", "r", "sv", "aw"]
    want = ref.sql(WIN_Q).to_pandas().sort_values(order) \
        .reset_index(drop=True)
    got = tiled.sql(WIN_Q).to_pandas().sort_values(order) \
        .reset_index(drop=True)
    assert want[["g", "v", "r", "sv"]].equals(got[["g", "v", "r", "sv"]])
    assert np.allclose(want["aw"], got["aw"])
    rep = tiled.last_tiled_report
    assert rep["tiled"] and rep["mode"] == "window"
    assert rep["n_tiles"] > 1 and rep["n_chunks"] > 1


def test_huge_offset_limit_falls_back_to_sort(pair):
    """A LIMIT whose OFFSET exceeds any resident accumulator cannot run
    top-N; the external sort applies it host-side."""
    ref, tiled, nseg = pair
    q = SORT_Q + " LIMIT 1000 OFFSET 60000"
    want = ref.sql(q).to_pandas()
    got = tiled.sql(q).to_pandas()
    assert want.equals(got) and len(got) == 1000
    assert tiled.last_tiled_report["mode"] == "sort"


def test_single_partition_too_big_is_a_clear_error():
    s = _mk(1, budget=3 << 20)
    s.sql("CREATE TABLE one (k BIGINT, v BIGINT) DISTRIBUTED BY (k)")
    s.catalog.table("one").set_data(
        {"k": np.zeros(300_000, dtype=np.int64),
         "v": np.arange(300_000)})
    with pytest.raises(Exception, match="partition"):
        s.sql("SELECT k, sum(v) over (partition by k) AS sv FROM one")


def test_skewed_redistribute_grows_bucket():
    """An untiled skew-blown redistribute bucket grows and retries (the
    Motion receive-buffer resize) instead of failing the statement."""
    s = _mk(8)
    df = s.sql(WIN_Q).to_pandas()
    assert len(df) == 200_000
