"""Packed-wire motion (exec/kernels.py wire format + the fused one-
collective-per-motion paths in exec/dist_executor.py): bit-identical to
the legacy per-column launches for every dtype, every motion kind, and
1- and 8-segment meshes — plus the adaptive capacity-rung ladder end to
end (skew overflow promotes a rung and retries without intervention)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.exec import kernels as K
from cloudberry_tpu.plan import nodes as N


# ----------------------------------------------------------- kernel level


def _roundtrip(cols, sel):
    lay = K.wire_layout({k: v.dtype for k, v in cols.items()})
    buf = jax.jit(lambda c, s: K.pack_wire(c, s, lay))(cols, sel)
    assert buf.dtype == jnp.uint32 and buf.shape == (sel.shape[0],
                                                     lay.width)
    out, osel = jax.jit(lambda b: K.unpack_wire(b, lay))(buf)
    assert np.array_equal(np.asarray(osel), np.asarray(sel))
    for k, v in cols.items():
        a, b = np.asarray(v), np.asarray(out[k])
        assert a.dtype == b.dtype, k
        if a.dtype == np.bool_:
            assert np.array_equal(a, b), k
        else:
            w = f"u{a.dtype.itemsize}"
            assert np.array_equal(a.view(w), b.view(w)), k
    return lay


def test_wire_roundtrip_all_dtypes_bit_identical():
    rng = np.random.default_rng(5)
    n = 33
    cols = {
        "b": jnp.asarray(rng.integers(0, 2, n).astype(bool)),
        "i32": jnp.asarray(np.concatenate(
            [[0, -1, 2**31 - 1, -2**31],
             rng.integers(-10**9, 10**9, n - 4)]).astype(np.int32)),
        "i64": jnp.asarray(np.concatenate(
            [[0, -1, 2**63 - 1, -2**63],
             rng.integers(-2**62, 2**62, n - 4)])),
        "f32": jnp.asarray(np.array(
            [0.0, -0.0, np.nan, np.inf, -np.inf, 1e-39]
            + list(rng.standard_normal(n - 6)), dtype=np.float32)),
        "f64": jnp.asarray(np.array(
            [0.0, -0.0, np.nan, np.inf, 1e308, 5e-324]
            + list(rng.standard_normal(n - 6)))),
    }
    sel = jnp.asarray(rng.integers(0, 2, n).astype(bool))
    lay = _roundtrip(cols, sel)
    # int64-limb transport convention: the two u32 words reassemble the
    # exact bit pattern (PR 1's DECIMAL/int64 discipline on the wire)
    assert lay.width == 1 + 1 + 2 + 1 + 2
    # an all-zero slot (an unfilled redistribute bucket) is INVALID
    zero = jnp.zeros((4, lay.width), jnp.uint32)
    _, zsel = K.unpack_wire(zero, lay)
    assert not bool(np.asarray(zsel).any())


def test_wire_roundtrip_many_bools_spill_flag_words():
    # >31 bool columns must spill into a second flag word
    rng = np.random.default_rng(6)
    n = 16
    cols = {f"b{i:02d}": jnp.asarray(rng.integers(0, 2, n).astype(bool))
            for i in range(40)}
    sel = jnp.asarray(rng.integers(0, 2, n).astype(bool))
    lay = _roundtrip(cols, sel)
    assert lay.width == 2  # 41 bits of flags -> two words, zero payload


def test_rung_ladder_is_pow2_and_monotone():
    assert [K.rung_up(x) for x in (0, 1, 8, 9, 500, 512, 513)] == \
        [8, 8, 8, 16, 512, 512, 1024]


# ------------------------------------------------------------ query level


def _dist_plan(s, sql):
    """Bound + distributed plan regardless of n_segments (the 1-segment
    mesh still exercises real collectives through execute_distributed,
    unlike the loopback single-program path)."""
    from cloudberry_tpu.plan.binder import Binder
    from cloudberry_tpu.plan.cost import annotate_pack_bits
    from cloudberry_tpu.plan.distribute import distribute_plan
    from cloudberry_tpu.plan.prune import prune_plan
    from cloudberry_tpu.sql.parser import parse_sql

    plan = prune_plan(Binder(s.catalog).bind_query(parse_sql(sql)))
    annotate_pack_bits(plan, s.catalog)
    return distribute_plan(plan, s)


def _session(nseg, packed, **over):
    cfg = Config(n_segments=nseg).with_overrides(
        **{"interconnect.packed_wire": packed, **over})
    return cb.Session(cfg)


def _fill(s):
    s.sql("create table t (k bigint, i int, d decimal(10,2), "
          "f float8, dt date, txt text, v bigint) "
          "distributed by (k)")
    rows = []
    for i in range(160):
        v = "null" if i % 11 == 0 else str(i * 3 - 200)
        rows.append(f"({i}, {i % 37 - 18}, {i}.{i % 100:02d}, "
                    f"{(i - 80) * 1.25e-3}, date '1995-0{i % 9 + 1}-17', "
                    f"'s{i % 5}', {v})")
    s.sql("insert into t values " + ",".join(rows))
    s.sql("create table dim (j bigint, j2 bigint, w float8) "
          "distributed by (j2)")
    s.sql("insert into dim values " + ",".join(
        f"({i - 15}, {i}, {i * 0.5 - 3})" for i in range(30)))


# gather (sort), broadcast (small build, probe keys ≠ distribution), and
# redistribute (two-stage group-by forced past GATHER_SINGLE)
_QUERIES = [
    "select k, i, d, f, dt, txt, v from t order by k",
    "select t.k, t.f, dim.w, t.v from t join dim on t.i = dim.j "
    "order by t.k",
    "select i, sum(v) as sv, count(*) as c, max(f) as mf from t "
    "group by i order by i",
]


def _assert_batches_bit_identical(a, b, ctx=""):
    assert np.array_equal(np.asarray(a.sel), np.asarray(b.sel)), ctx
    m = np.asarray(a.sel)
    assert set(a.columns) == set(b.columns), ctx
    for name in a.columns:
        x = np.asarray(a.columns[name])[m]
        y = np.asarray(b.columns[name])[m]
        assert x.dtype == y.dtype, (ctx, name)
        if x.dtype.kind == "f":
            w = f"u{x.dtype.itemsize}"
            assert np.array_equal(x.view(w), y.view(w)), (ctx, name)
        else:
            assert np.array_equal(x, y), (ctx, name)
    for name in set(a.validity) | set(b.validity):
        assert np.array_equal(np.asarray(a.validity[name])[m],
                              np.asarray(b.validity[name])[m]), (ctx, name)


_FILL_SESSIONS: dict = {}


def _fill_session(nseg, packed):
    # gather_single_threshold=0 only affects the group-by query (forces
    # its merge onto a redistribute), so one session per (nseg, packed)
    # serves all three motion kinds
    key = (nseg, packed)
    if key not in _FILL_SESSIONS:
        s = _session(nseg, packed,
                     **{"planner.gather_single_threshold": 0})
        _fill(s)
        _FILL_SESSIONS[key] = s
    return _FILL_SESSIONS[key]


@pytest.mark.parametrize("nseg", [1, 8], ids=["seg1", "seg8"])
@pytest.mark.parametrize("qi", range(len(_QUERIES)),
                         ids=["gather", "broadcast", "redistribute"])
def test_packed_matches_percol_all_motion_kinds(nseg, qi):
    from cloudberry_tpu.exec.dist_executor import execute_distributed

    batches = {}
    for packed in (False, True):
        s = _fill_session(nseg, packed)
        plan = _dist_plan(s, _QUERIES[qi])
        kinds = {n.kind for n in _walk_motions(plan)}
        if qi == 1:
            assert "broadcast" in kinds
        if qi == 2:
            assert "redistribute" in kinds
        batches[packed] = execute_distributed(plan, s)
    _assert_batches_bit_identical(batches[True], batches[False],
                                  f"nseg={nseg} q={qi}")


def _walk_motions(plan):
    out = []

    def walk(n):
        if isinstance(n, N.PMotion):
            out.append(n)
        for c in n.children():
            walk(c)

    walk(plan)
    return out


_TPCH_SESSIONS: dict = {}


def _tpch_session(nseg, packed):
    """One loaded session per (nseg, packed) for the whole module — the
    Q3/Q10 pins share them."""
    from tools.tpchgen import load_tpch

    key = (nseg, packed)
    if key not in _TPCH_SESSIONS:
        s = _session(nseg, packed)
        load_tpch(s, sf=0.01, seed=7)
        _TPCH_SESSIONS[key] = s
    return _TPCH_SESSIONS[key]


@pytest.mark.parametrize("nseg", [1, 8], ids=["seg1", "seg8"])
@pytest.mark.parametrize("qname", ["q3", "q10"])
def test_tpch_packed_parity_pinned(nseg, qname):
    """Acceptance pin: packed motion is bit-identical to the per-column
    path across TPC-H Q3/Q10 at 1 and 8 segments."""
    from cloudberry_tpu.exec.dist_executor import execute_distributed
    from tools.tpch_queries import QUERIES

    batches = {}
    for packed in (False, True):
        s = _tpch_session(nseg, packed)
        plan = _dist_plan(s, QUERIES[qname])
        batches[packed] = execute_distributed(plan, s)
    _assert_batches_bit_identical(batches[True], batches[False],
                                  f"{qname} nseg={nseg}")


# --------------------------------------------- adaptive rung ladder, e2e


def test_skewed_rung_promotion_end_to_end():
    """A hot join key behind a projection (so the exact plan-time bucket
    sizer cannot see the base scan) overflows the estimate-seeded rung;
    the retry must promote to the rung fitting the OBSERVED bucket
    demand and finish with no user action — and every compiled rung
    lands in the session's executable cache."""
    cfg = Config(n_segments=8).with_overrides(**{
        "planner.broadcast_threshold": 0,
        "planner.runtime_filter_threshold": 0,
    })
    s = cb.Session(cfg)
    s.sql("create table j1 (a bigint, key bigint) distributed by (a)")
    s.sql("create table j2 (b bigint, key bigint, w bigint) "
          "distributed by (b)")
    s.sql("insert into j1 values " +
          ",".join(f"({i}, {0 if i < 1500 else i})" for i in range(2000)))
    s.sql("insert into j2 values " +
          ",".join(f"({i}, {i}, {i})" for i in range(2000)))
    # the projection hides the base scan from _exact_bucket_cap: the
    # probe redistribute is sized from the fair-share estimate, which
    # the 75%-hot key blows through
    q = ("select sum(j2.w) as sw from (select key as kk from j1) x "
         "join j2 on kk = j2.key")
    out = s.sql(q).to_pandas()
    assert out.sw[0] == 0 * 1500 + sum(range(1500, 2000))

    # the seed rung overflowed at least once and promotion recovered
    assert s.growth_events >= 1
    # every promoted rung signature has its own session-cached executable
    assert len(s._rung_cache) >= 2
    for (_, _, _, _, _, rung_sig) in s._rung_cache:
        for entry in rung_sig:
            if entry[0] == "redistribute":
                bucket_cap = entry[1]
                assert bucket_cap & (bucket_cap - 1) == 0, \
                    f"bucket cap {bucket_cap} is off the pow2 ladder"

    # re-execution reuses the promoted runner: no further growth
    before = s.growth_events
    out2 = s.sql(q).to_pandas()
    assert out2.equals(out)
    assert s.growth_events == before


def test_stmt_cache_is_lru_and_bounded():
    """Satellite: the prepared-statement cache evicts least-recently-USED
    (hits reorder), not first-inserted, and stays bounded."""
    s = cb.Session()
    s.sql("create table lt (a bigint)")
    s.sql("insert into lt values (1),(2),(3)")
    s._STMT_CACHE_MAX = 4
    qs = [f"select a + {i} as x from lt" for i in range(4)]
    for q in qs:
        s.sql(q)
    assert all(q in s._stmt_cache for q in qs)
    s.sql(qs[0])                       # touch the oldest -> MRU
    s.sql("select a + 99 as x from lt")  # evicts qs[1], not qs[0]
    assert qs[0] in s._stmt_cache
    assert qs[1] not in s._stmt_cache
    assert len(s._stmt_cache) <= 4
