"""Resource queues, statement prioritization, and the vmem red zone.

Reference: cost/count-based resource queues with waiters
(resscheduler/resqueue.c), priority weights (postmaster/backoff.c), and
the engine-wide memory red line with runaway termination
(redzone_handler.c, runaway_cleaner.c).
"""

import threading
import time

import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.exec.resource import (QueueManager, ResourceError,
                                          ResourceQueue, VmemTracker)
from cloudberry_tpu.plan.binder import BindError


def test_create_drop_resource_queue_sql():
    s = cb.Session(Config(n_segments=1))
    s.sql("create resource queue etl with (active_statements=2, "
          "priority='high')")
    q = s.catalog.resource_queues["etl"]
    assert q.active_statements == 2 and q.priority == "high"
    with pytest.raises(BindError):
        s.sql("create resource queue etl")
    s.sql("drop resource queue etl")
    with pytest.raises(BindError):
        s.sql("drop resource queue etl")
    with pytest.raises(BindError):
        s.sql("drop resource queue default")


def test_max_cost_rejects_expensive_statements():
    s = cb.Session(Config(n_segments=1).with_overrides(
        **{"resource.queue": "small"}))
    s.sql("create resource queue small with (max_cost=1024)")
    s.sql("create table big (k bigint, v bigint)")
    s.sql("insert into big values " +
          ", ".join(f"({i}, {i})" for i in range(500)))
    with pytest.raises(ResourceError, match="MAX_COST"):
        s.sql("select sum(v) as s from big")


def test_active_statements_bounds_concurrency():
    qm = QueueManager()
    q = ResourceQueue("q", active_statements=2)
    running, peak, done = [0], [0], []
    lock = threading.Lock()

    def work(i):
        with qm.slot(q, 0, "medium"):
            with lock:
                running[0] += 1
                peak[0] = max(peak[0], running[0])
            time.sleep(0.05)
            with lock:
                running[0] -= 1
            done.append(i)

    ts = [threading.Thread(target=work, args=(i,)) for i in range(6)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert len(done) == 6
    assert peak[0] <= 2  # the queue's whole point


def test_priority_orders_waiters():
    qm = QueueManager()
    q = ResourceQueue("q", active_statements=1)
    order = []
    hold = threading.Event()
    started = threading.Event()

    def holder():
        with qm.slot(q, 0, "medium"):
            started.set()
            hold.wait(5)

    def waiter(prio, tag, delay):
        time.sleep(delay)
        with qm.slot(q, 0, prio):
            order.append(tag)

    th = threading.Thread(target=holder)
    th.start()
    started.wait(5)
    ws = [threading.Thread(target=waiter, args=("low", "low", 0.0)),
          threading.Thread(target=waiter, args=("max", "max", 0.1))]
    [w.start() for w in ws]
    time.sleep(0.3)  # both queued: low arrived first, max outranks it
    hold.set()
    th.join()
    [w.join() for w in ws]
    assert order[0] == "max"


def test_vmem_red_zone_blocks_then_admits():
    vm = VmemTracker(1000)
    vm.reserve(1, 800)
    t0 = time.monotonic()
    done = []

    def second():
        vm.reserve(2, 500, timeout_s=10)
        done.append(time.monotonic() - t0)
        vm.release(2)

    th = threading.Thread(target=second)
    th.start()
    time.sleep(0.15)
    assert not done  # still waiting: 800 + 500 > 1000
    vm.release(1)
    th.join(5)
    assert done and done[0] >= 0.1


def test_runaway_growth_terminated():
    vm = VmemTracker(1000)
    vm.reserve(1, 400)
    vm.reserve(2, 400)
    vm.grow(1, 550)  # fits: 550 + 400
    with pytest.raises(ResourceError, match="runaway"):
        vm.grow(1, 700)  # 700 + 400 > 1000
    vm.release(1)
    vm.grow(2, 900)  # after the release there is room


def test_queue_admission_visible_through_session():
    s = cb.Session(Config(n_segments=1).with_overrides(
        **{"resource.queue": "one"}))
    s.sql("create resource queue one with (active_statements=1)")
    s.sql("create table t (k bigint)")
    s.sql("insert into t values (1), (2)")
    # statements run (and release their slot) normally
    assert s.sql("select count(*) as c from t").to_pandas()["c"].iloc[0] == 2
    q = s.catalog.resource_queues["one"]
    assert q.active == 0 and q.waiting == 0
