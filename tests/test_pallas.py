"""Pallas fused dense-aggregation kernel — interpret mode on CPU (the
hardware path compiles the same kernel; see exec/pallas_kernels.py)."""

import jax.numpy as jnp
import numpy as np

from cloudberry_tpu.exec.pallas_kernels import dense_agg_pallas


def test_dense_agg_pallas_matches_numpy():
    rng = np.random.default_rng(0)
    n, k, cells, tile = 8192, 3, 6, 2048
    gid = rng.integers(0, cells, n).astype(np.int32)
    vals = rng.normal(size=(k, n)).astype(np.float32)
    sel = rng.random(n) > 0.25

    counts, sums = dense_agg_pallas(
        jnp.asarray(gid), jnp.asarray(vals), jnp.asarray(sel),
        n_cells=cells, tile=tile, interpret=True)

    exp_counts = np.zeros(cells)
    exp_sums = np.zeros((k, cells))
    for c in range(cells):
        m = (gid == c) & sel
        exp_counts[c] = m.sum()
        exp_sums[:, c] = vals[:, m].sum(axis=1)
    np.testing.assert_allclose(np.asarray(counts), exp_counts)
    np.testing.assert_allclose(np.asarray(sums), exp_sums, rtol=1e-5,
                               atol=1e-4)


def test_dense_agg_pallas_empty_selection():
    n, k, cells = 4096, 2, 4
    counts, sums = dense_agg_pallas(
        jnp.zeros(n, jnp.int32), jnp.ones((k, n), jnp.float32),
        jnp.zeros(n, bool), n_cells=cells, tile=1024, interpret=True)
    assert float(np.asarray(counts).sum()) == 0.0
    assert float(np.abs(np.asarray(sums)).sum()) == 0.0


def test_use_pallas_config_end_to_end():
    """The config gate routes dense aggregation through the Pallas kernel
    (interpret mode on CPU) with correct results."""
    import cloudberry_tpu as cb

    s = cb.Session(cb.Config().with_overrides(**{"exec.use_pallas": True}))
    s.sql("create table pt (g text, v decimal(10,2))")
    s.sql("insert into pt values ('a',1.5),('a',2.5),('b',10.0),('b',0.5),('a',1.0)")
    df = s.sql("select g, sum(v) as sv, count(*) as n, avg(v) as a "
               "from pt group by g order by g").to_pandas()
    assert df["g"].tolist() == ["a", "b"]
    assert df["sv"].tolist() == [5.0, 10.5]
    assert df["n"].tolist() == [3, 2]
    np.testing.assert_allclose(df["a"].to_numpy(), [5.0 / 3, 5.25], rtol=1e-6)
