"""Pallas fused dense-aggregation kernel — interpret mode on CPU (the
hardware path compiles the same kernel; see exec/pallas_kernels.py)."""

import jax.numpy as jnp
import numpy as np

from cloudberry_tpu.exec.pallas_kernels import dense_agg_pallas


def test_dense_agg_pallas_matches_numpy():
    rng = np.random.default_rng(0)
    n, k, cells, tile = 8192, 3, 6, 2048
    gid = rng.integers(0, cells, n).astype(np.int32)
    vals = rng.normal(size=(k, n)).astype(np.float32)
    sel = rng.random(n) > 0.25

    counts, sums = dense_agg_pallas(
        jnp.asarray(gid), jnp.asarray(vals), jnp.asarray(sel),
        n_cells=cells, tile=tile, interpret=True)

    exp_counts = np.zeros(cells)
    exp_sums = np.zeros((k, cells))
    for c in range(cells):
        m = (gid == c) & sel
        exp_counts[c] = m.sum()
        exp_sums[:, c] = vals[:, m].sum(axis=1)
    np.testing.assert_allclose(np.asarray(counts), exp_counts)
    np.testing.assert_allclose(np.asarray(sums), exp_sums, rtol=1e-5,
                               atol=1e-4)


def test_dense_agg_pallas_empty_selection():
    n, k, cells = 4096, 2, 4
    counts, sums = dense_agg_pallas(
        jnp.zeros(n, jnp.int32), jnp.ones((k, n), jnp.float32),
        jnp.zeros(n, bool), n_cells=cells, tile=1024, interpret=True)
    assert float(np.asarray(counts).sum()) == 0.0
    assert float(np.abs(np.asarray(sums)).sum()) == 0.0


def test_use_pallas_config_end_to_end():
    """The config gate routes dense aggregation through the Pallas kernel
    (interpret mode on CPU) with correct results."""
    import cloudberry_tpu as cb

    s = cb.Session(cb.Config().with_overrides(**{"exec.use_pallas": True}))
    s.sql("create table pt (g text, v decimal(10,2))")
    s.sql("insert into pt values ('a',1.5),('a',2.5),('b',10.0),('b',0.5),('a',1.0)")
    df = s.sql("select g, sum(v) as sv, count(*) as n, avg(v) as a "
               "from pt group by g order by g").to_pandas()
    assert df["g"].tolist() == ["a", "b"]
    assert df["sv"].tolist() == [5.0, 10.5]
    assert df["n"].tolist() == [3, 2]
    np.testing.assert_allclose(df["a"].to_numpy(), [5.0 / 3, 5.25], rtol=1e-6)


def test_limb_round_trip_exact():
    from cloudberry_tpu.exec.pallas_kernels import (int64_to_limbs,
                                                    limbs_to_int64)

    rng = np.random.default_rng(1)
    vals = np.concatenate([
        rng.integers(-2**62, 2**62, 1000),
        np.array([0, 1, -1, 2**62, -2**62, 2**21, 2**42, -2**42])])
    l0, l1, l2 = int64_to_limbs(jnp.asarray(vals))
    back = np.asarray(limbs_to_int64(l0, l1, l2))
    assert (back == vals).all()


def test_probe_join_pallas_matches_numpy():
    from cloudberry_tpu.exec.pallas_kernels import (int64_to_limbs,
                                                    limbs_to_int64,
                                                    probe_join_pallas)

    rng = np.random.default_rng(2)
    b, n, tile = 256, 4096, 1024
    bkeys = rng.permutation(10_000)[:b].astype(np.uint32)
    bsel = rng.random(b) > 0.1
    pkeys = rng.choice(bkeys, n).astype(np.uint32)
    miss = rng.random(n) < 0.3
    pkeys[miss] = (pkeys[miss] + 1_000_000).astype(np.uint32)
    psel = rng.random(n) > 0.2
    payload = rng.integers(-10**12, 10**12, b)

    rows = int64_to_limbs(jnp.asarray(payload))
    match_f, gathered = probe_join_pallas(
        jnp.asarray(bkeys), jnp.asarray(bsel), jnp.asarray(pkeys),
        jnp.asarray(psel), jnp.stack(rows), tile=tile, interpret=True)
    got_match = np.asarray(match_f) > 0.5
    got_pay = np.asarray(limbs_to_int64(gathered[0], gathered[1],
                                        gathered[2]))

    lookup = {k: v for k, v, s in zip(bkeys, payload, bsel) if s}
    exp_match = np.array([s and (k in lookup)
                          for k, s in zip(pkeys, psel)])
    np.testing.assert_array_equal(got_match, exp_match)
    for i in range(n):
        if exp_match[i]:
            assert got_pay[i] == lookup[pkeys[i]], i


def test_probe_join_pallas_detects_duplicate_build():
    from cloudberry_tpu.exec.pallas_kernels import probe_join_pallas

    bkeys = jnp.asarray(np.array([5, 5, 7, 9], dtype=np.uint32))
    bsel = jnp.ones(4, bool)
    pkeys = jnp.asarray(np.full(1024, 5, dtype=np.uint32))
    psel = jnp.ones(1024, bool)
    pay = jnp.zeros((1, 4), jnp.float32)
    match_f, _ = probe_join_pallas(bkeys, bsel, pkeys, psel, pay,
                                   tile=1024, interpret=True)
    assert float(np.asarray(match_f).max()) > 1.5


def test_fused_probe_join_end_to_end_parity():
    """The whole q3-class star join, use_pallas on vs off — identical
    rows (integer payloads ride the limb transport exactly)."""
    import cloudberry_tpu as cb
    from cloudberry_tpu.config import get_config

    from cloudberry_tpu.exec import pallas_kernels as PK

    calls = []
    orig = PK.probe_join_pallas

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    PK.probe_join_pallas = spy

    def run(use_pallas):
        s = cb.Session(get_config().with_overrides(
            **{"exec.use_pallas": use_pallas}))
        rng = np.random.default_rng(4)
        s.sql("create table dim (k bigint, name text, grp bigint) "
              "distributed by (k)")
        s.sql("create table fact (k bigint, v bigint, amt decimal(12,2)) "
              "distributed by (k)")
        nd, nf = 500, 40_000
        s.sql("insert into dim values " + ", ".join(
            f"({i}, 'n{i % 37}', {int(rng.integers(0, 9))})"
            for i in range(nd)))
        s.catalog.table("fact").set_data({
            "k": rng.integers(0, nd + 50, nf),  # some misses
            "v": rng.integers(0, 1000, nf),
            "amt": rng.integers(0, 10**6, nf)})
        return s.sql(
            "select grp, name, sum(v) as sv, sum(amt) as sa, count(*) "
            "as n from fact join dim on fact.k = dim.k "
            "group by grp, name order by grp, name").to_pandas()

    try:
        a = run(False)
        b = run(True)
    finally:
        PK.probe_join_pallas = orig
    assert calls, "the fused probe-join path never fired"
    assert a.equals(b)
