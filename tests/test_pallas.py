"""Pallas fused aggregation/join kernels — interpret mode on CPU (the
hardware path compiles the same kernels; see exec/pallas_kernels.py)."""

import jax.numpy as jnp
import numpy as np

from cloudberry_tpu.exec import kernels as K
from cloudberry_tpu.exec import pallas_kernels as PK
from cloudberry_tpu.exec.pallas_kernels import dense_agg_pallas


def test_dense_agg_pallas_matches_numpy():
    rng = np.random.default_rng(0)
    n, k, cells, tile = 8192, 3, 6, 2048
    gid = rng.integers(0, cells, n).astype(np.int32)
    vals = rng.normal(size=(k, n)).astype(np.float32)
    sel = rng.random(n) > 0.25

    counts, sums = dense_agg_pallas(
        jnp.asarray(gid), jnp.asarray(vals), jnp.asarray(sel),
        n_cells=cells, tile=tile, interpret=True)

    exp_counts = np.zeros(cells)
    exp_sums = np.zeros((k, cells))
    for c in range(cells):
        m = (gid == c) & sel
        exp_counts[c] = m.sum()
        exp_sums[:, c] = vals[:, m].sum(axis=1)
    np.testing.assert_allclose(np.asarray(counts), exp_counts)
    np.testing.assert_allclose(np.asarray(sums), exp_sums, rtol=1e-5,
                               atol=1e-4)


def test_dense_agg_pallas_empty_selection():
    n, k, cells = 4096, 2, 4
    counts, sums = dense_agg_pallas(
        jnp.zeros(n, jnp.int32), jnp.ones((k, n), jnp.float32),
        jnp.zeros(n, bool), n_cells=cells, tile=1024, interpret=True)
    assert float(np.asarray(counts).sum()) == 0.0
    assert float(np.abs(np.asarray(sums)).sum()) == 0.0


def test_use_pallas_config_end_to_end():
    """The config gate routes dense aggregation through the Pallas kernel
    (interpret mode on CPU) with correct results."""
    import cloudberry_tpu as cb

    s = cb.Session(cb.Config().with_overrides(**{"exec.use_pallas": True}))
    s.sql("create table pt (g text, v decimal(10,2))")
    s.sql("insert into pt values ('a',1.5),('a',2.5),('b',10.0),('b',0.5),('a',1.0)")
    df = s.sql("select g, sum(v) as sv, count(*) as n, avg(v) as a "
               "from pt group by g order by g").to_pandas()
    assert df["g"].tolist() == ["a", "b"]
    assert df["sv"].tolist() == [5.0, 10.5]
    assert df["n"].tolist() == [3, 2]
    np.testing.assert_allclose(df["a"].to_numpy(), [5.0 / 3, 5.25], rtol=1e-6)


def test_limb_round_trip_exact():
    from cloudberry_tpu.exec.pallas_kernels import (int64_to_limbs,
                                                    limbs_to_int64)

    rng = np.random.default_rng(1)
    vals = np.concatenate([
        rng.integers(-2**62, 2**62, 1000),
        np.array([0, 1, -1, 2**62, -2**62, 2**21, 2**42, -2**42])])
    l0, l1, l2 = int64_to_limbs(jnp.asarray(vals))
    back = np.asarray(limbs_to_int64(l0, l1, l2))
    assert (back == vals).all()


def test_probe_join_pallas_matches_numpy():
    from cloudberry_tpu.exec.pallas_kernels import (int64_to_limbs,
                                                    limbs_to_int64,
                                                    probe_join_pallas)

    rng = np.random.default_rng(2)
    b, n, tile = 256, 4096, 1024
    bkeys = rng.permutation(10_000)[:b].astype(np.uint32)
    bsel = rng.random(b) > 0.1
    pkeys = rng.choice(bkeys, n).astype(np.uint32)
    miss = rng.random(n) < 0.3
    pkeys[miss] = (pkeys[miss] + 1_000_000).astype(np.uint32)
    psel = rng.random(n) > 0.2
    payload = rng.integers(-10**12, 10**12, b)

    rows = int64_to_limbs(jnp.asarray(payload))
    match_f, gathered = probe_join_pallas(
        jnp.asarray(bkeys), jnp.asarray(bsel), jnp.asarray(pkeys),
        jnp.asarray(psel), jnp.stack(rows), tile=tile, interpret=True)
    got_match = np.asarray(match_f) > 0.5
    got_pay = np.asarray(limbs_to_int64(gathered[0], gathered[1],
                                        gathered[2]))

    lookup = {k: v for k, v, s in zip(bkeys, payload, bsel) if s}
    exp_match = np.array([s and (k in lookup)
                          for k, s in zip(pkeys, psel)])
    np.testing.assert_array_equal(got_match, exp_match)
    for i in range(n):
        if exp_match[i]:
            assert got_pay[i] == lookup[pkeys[i]], i


def test_probe_join_pallas_detects_duplicate_build():
    from cloudberry_tpu.exec.pallas_kernels import probe_join_pallas

    bkeys = jnp.asarray(np.array([5, 5, 7, 9], dtype=np.uint32))
    bsel = jnp.ones(4, bool)
    pkeys = jnp.asarray(np.full(1024, 5, dtype=np.uint32))
    psel = jnp.ones(1024, bool)
    pay = jnp.zeros((1, 4), jnp.float32)
    match_f, _ = probe_join_pallas(bkeys, bsel, pkeys, psel, pay,
                                   tile=1024, interpret=True)
    assert float(np.asarray(match_f).max()) > 1.5


def test_dense_agg_limb_transport_exact():
    """int64 sums through the 13-bit limb MXU path reproduce numpy's
    int64 arithmetic bit for bit — values far beyond f32/f64 precision."""
    rng = np.random.default_rng(5)
    n, cells, tile = 8192, 6, 2048
    gid = rng.integers(0, cells, n).astype(np.int32)
    sel = rng.random(n) > 0.25
    vals = rng.integers(-10**17, 10**17, n)  # |v| ≫ 2^53: f64 would round

    limbs = PK.int64_to_agg_limbs(
        jnp.where(jnp.asarray(sel), jnp.asarray(vals), 0))
    tiles = PK.dense_agg_tiles_pallas(
        jnp.asarray(gid), jnp.stack(limbs), jnp.asarray(sel),
        n_cells=cells, tile=tile, interpret=True)
    counts = jnp.sum(jnp.round(tiles[:, 0]).astype(jnp.int64), axis=0)
    totals = [jnp.sum(jnp.round(tiles[:, 1 + i]).astype(jnp.int64), axis=0)
              for i in range(len(PK.AGG_LIMB_BITS))]
    sums = PK.agg_limbs_to_int64(totals)

    exp_counts = np.array([((gid == c) & sel).sum() for c in range(cells)])
    exp_sums = np.array([vals[(gid == c) & sel].sum() for c in range(cells)])
    np.testing.assert_array_equal(np.asarray(counts), exp_counts)
    np.testing.assert_array_equal(np.asarray(sums), exp_sums)


def _assert_seg_parity(keys, v, sel, cap, tile=2048):
    """sorted_segment_aggregate must match group_aggregate bit for bit
    (keys, sums, counts, avg, selection, group count)."""
    specs = [K.AggSpec("sum", "s"), K.AggSpec("count", "c"),
             K.AggSpec("avg", "a")]
    av = {"s": jnp.asarray(v), "c": None, "a": jnp.asarray(v)}
    kc = {"k": jnp.asarray(keys)}
    sj = jnp.asarray(sel)
    ok1, oa1, os1, ng1 = K.group_aggregate(kc, av, specs, sj, cap)
    ok2, oa2, os2, ng2 = PK.sorted_segment_aggregate(
        kc, av, specs, sj, cap, tile=tile, interpret=True)
    assert int(ng1) == int(ng2)
    np.testing.assert_array_equal(np.asarray(os1), np.asarray(os2))
    np.testing.assert_array_equal(np.asarray(ok1["k"]), np.asarray(ok2["k"]))
    for name in ("s", "c", "a"):
        x, y = np.asarray(oa1[name]), np.asarray(oa2[name])
        assert x.dtype == y.dtype
        np.testing.assert_array_equal(x, y, err_msg=name)
    return int(ng1)


def test_sorted_segment_boundary_shapes():
    """Oracle parity at the shapes that stress the carry/flush logic:
    group count == capacity, a single group spanning every tile, an
    all-filtered input, and one hot group larger than several tiles.
    All cases share one (n, cap, tile) signature so the interpret-mode
    program compiles once and replays four times."""
    rng = np.random.default_rng(6)
    n, cap, tile = 1536, 512, 512

    def pad(keys, sel):
        m = n - keys.shape[0]
        return (np.concatenate([keys, np.zeros(m, np.int64)]),
                np.concatenate([sel, np.zeros(m, bool)]))

    # group count == capacity: exactly cap distinct keys survive
    k1, s1 = pad(np.repeat(np.arange(cap, dtype=np.int64), 3),
                 np.ones(cap * 3, bool))
    # a single group spanning every tile (the SMEM carry path)
    k2, s2 = np.zeros(n, np.int64), np.ones(n, bool)
    # all-filtered: zero groups, zero flushes
    k3, s3 = rng.integers(0, 50, n).astype(np.int64), np.zeros(n, bool)
    # one hot group (> tile rows once sorted) between smaller groups
    k4 = np.concatenate([rng.integers(0, 40, 300), np.full(900, 40),
                         rng.integers(41, 80, 336)]).astype(np.int64)
    rng.shuffle(k4)
    s4 = rng.random(n) > 0.2
    expected = {0: cap, 1: 1, 2: 0}
    for i, (keys, sel) in enumerate([(k1, s1), (k2, s2), (k3, s3),
                                     (k4, s4)]):
        v = rng.integers(-10**12, 10**12, n)
        ng = _assert_seg_parity(keys, v, sel, cap, tile=tile)
        if i in expected:
            assert ng == expected[i], i


def test_sorted_segment_beyond_dense_domain():
    """Oracle parity at 2^16 groups — far beyond any one-hot cell domain
    (the acceptance bar for the mid-cardinality kernel). Every group id
    appears, so the group count is exactly 2^16."""
    rng = np.random.default_rng(10)
    groups = 1 << 16
    keys0 = np.concatenate([np.arange(groups, dtype=np.int64),
                            rng.integers(0, groups, groups)])
    sel0 = np.ones(keys0.shape[0], bool)
    # filter only duplicate-half rows: every group keeps one survivor
    sel0[groups:] = rng.random(groups) > 0.25
    perm = rng.permutation(keys0.shape[0])
    keys, sel = keys0[perm], sel0[perm]  # 2^17 rows
    v = rng.integers(-10**13, 10**13, keys.shape[0])
    ng = _assert_seg_parity(keys, v, sel, 1 << 17)
    assert ng == groups


def test_sorted_segment_end_to_end_sql():
    """Mid-cardinality GROUP BY through the session takes the fused path
    (spied) and matches the XLA path exactly."""
    import cloudberry_tpu as cb
    from cloudberry_tpu.config import get_config

    calls = []
    orig = PK.sorted_segment_aggregate

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    PK.sorted_segment_aggregate = spy

    def run(up):
        s = cb.Session(get_config().with_overrides(
            **{"exec.use_pallas": up}))
        rng = np.random.default_rng(11)
        s.sql("create table f (k bigint, v bigint, amt decimal(12,2))")
        s.catalog.table("f").set_data({
            "k": rng.integers(0, 8_000, 30_000),
            "v": rng.integers(-10**12, 10**12, 30_000),
            "amt": rng.integers(0, 10**8, 30_000)})
        return s.sql(
            "select k, sum(v) as sv, sum(amt) as sa, avg(v) as av, "
            "count(*) as n from f group by k order by k").to_pandas()

    try:
        a = run(False)
        n0 = len(calls)
        b = run(True)
    finally:
        PK.sorted_segment_aggregate = orig
    assert len(calls) > n0, "the sorted-segment path never fired"
    assert a.equals(b)


def test_q1_money_sums_fused_parity():
    """TPC-H Q1 end to end in interpret mode: the money sums take the
    fused dense path (spied) and every column — int64-cent SUMs and the
    f64 AVGs alike — is bit-identical to the XLA path."""
    import cloudberry_tpu as cb
    from cloudberry_tpu.config import get_config
    from tools.tpch_queries import QUERIES
    from tools.tpchgen import load_tpch

    calls = []
    orig = PK.dense_agg_tiles_pallas

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    PK.dense_agg_tiles_pallas = spy

    def run(up):
        s = cb.Session(get_config().with_overrides(
            **{"exec.use_pallas": up}))
        load_tpch(s, sf=0.01, seed=1, tables=["lineitem"])
        return s.sql(QUERIES["q1"]).to_pandas()

    try:
        a = run(False)
        n0 = len(calls)
        b = run(True)
    finally:
        PK.dense_agg_tiles_pallas = orig
    assert len(calls) > n0, "Q1's aggregation never took the fused path"
    assert a.equals(b)


def test_tiled_matches_oneshot_fused():
    """A fused-agg query produces IDENTICAL results one-shot and tiled:
    per-tile partials flow through the same limb representation, and
    int64 partial merges are exact on both sides."""
    import cloudberry_tpu as cb
    from cloudberry_tpu.config import get_config

    nf = 60_000
    q = ("select g, sum(amt) as sa, count(*) as n from f "
         "group by g order by g")

    def run(mem):
        s = cb.Session(get_config().with_overrides(**{
            "resource.query_mem_bytes": mem, "exec.use_pallas": True}))
        s.sql("create table f (g bigint, amt decimal(12,2))")
        rng = np.random.default_rng(12)
        s.catalog.table("f").set_data({
            "g": rng.integers(0, 1500, nf),
            "amt": rng.integers(-10**9, 10**9, nf)})
        return s, s.sql(q).to_pandas()

    _, one = run(4 << 30)
    s2, tiled = run(1 << 20)
    rep = s2.last_tiled_report
    assert rep and rep.get("n_tiles", 0) > 1, rep
    assert one.equals(tiled)


def test_tiled_dist_matches_xla_fused():
    """The DISTRIBUTED tiled merge also dispatches through
    merge_group_aggregate: the sorted-segment kernel executing inside
    the shard_map step must match the XLA side exactly."""
    import cloudberry_tpu as cb
    from cloudberry_tpu.config import get_config

    nf = 400_000
    q = ("select g, sum(amt) as sa, count(*) as n from f "
         "group by g order by g limit 40")

    def run(up):
        s = cb.Session(get_config().with_overrides(**{
            "n_segments": 8, "resource.query_mem_bytes": 2 << 20,
            "exec.use_pallas": up}))
        s.sql("create table f (g bigint, amt decimal(12,2)) "
              "distributed by (g)")
        rng = np.random.default_rng(13)
        s.catalog.table("f").set_data({
            "g": rng.integers(0, 1000, nf),
            "amt": rng.integers(-10**9, 10**9, nf)})
        return s, s.sql(q).to_pandas()

    s1, fused = run(True)
    rep = s1.last_tiled_report
    assert rep and rep.get("tiled"), rep
    _, xla = run(False)
    assert fused.equals(xla)


def test_kernel_bench_grouped_agg_smoke():
    """The grouped-agg cardinality sweep runs on CPU in interpret mode
    and emits both strategies per ladder point."""
    import json
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "-m", "tools.kernel_bench", "grouped-agg",
         "--rows", "4096", "--ladder", "4,4", "--reps", "1",
         "--interpret"],
        capture_output=True, text=True, timeout=300,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    recs = [json.loads(ln) for ln in out.stdout.splitlines()
            if ln.startswith("{")]
    strategies = {r["strategy"] for r in recs}
    assert strategies == {"xla_sort", "pallas_sorted_segment"}
    assert all(r["groups"] == 16 and r["mrows_per_s"] > 0 for r in recs)


def test_fused_probe_join_end_to_end_parity():
    """The whole q3-class star join, use_pallas on vs off — identical
    rows (integer payloads ride the limb transport exactly)."""
    import cloudberry_tpu as cb
    from cloudberry_tpu.config import get_config

    from cloudberry_tpu.exec import pallas_kernels as PK

    calls = []
    orig = PK.probe_join_pallas

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    PK.probe_join_pallas = spy

    def run(use_pallas):
        s = cb.Session(get_config().with_overrides(
            **{"exec.use_pallas": use_pallas}))
        rng = np.random.default_rng(4)
        s.sql("create table dim (k bigint, name text, grp bigint) "
              "distributed by (k)")
        s.sql("create table fact (k bigint, v bigint, amt decimal(12,2)) "
              "distributed by (k)")
        nd, nf = 500, 40_000
        s.sql("insert into dim values " + ", ".join(
            f"({i}, 'n{i % 37}', {int(rng.integers(0, 9))})"
            for i in range(nd)))
        s.catalog.table("fact").set_data({
            "k": rng.integers(0, nd + 50, nf),  # some misses
            "v": rng.integers(0, 1000, nf),
            "amt": rng.integers(0, 10**6, nf)})
        return s.sql(
            "select grp, name, sum(v) as sv, sum(amt) as sa, count(*) "
            "as n from fact join dim on fact.k = dim.k "
            "group by grp, name order by grp, name").to_pandas()

    try:
        a = run(False)
        b = run(True)
    finally:
        PK.probe_join_pallas = orig
    assert calls, "the fused probe-join path never fired"
    assert a.equals(b)
