import numpy as np

from cloudberry_tpu.utils import hashing


def test_splitmix_consistency_np_jnp():
    import jax.numpy as jnp

    x = np.arange(100, dtype=np.int64)
    a = hashing.splitmix64_np(x.view(np.uint64))
    b = np.asarray(hashing.splitmix64_jnp(jnp.asarray(x).view(jnp.uint64)))
    np.testing.assert_array_equal(a, b)


def test_hash_columns_matches_device_host():
    import jax.numpy as jnp

    k1 = np.array([1, 2, 3, 4], dtype=np.int64)
    k2 = np.array([10.5, 0.0, -3.25, 10.5])
    a = hashing.hash_columns_np([k1, k2])
    b = np.asarray(hashing.hash_columns_jnp([jnp.asarray(k1), jnp.asarray(k2)]))
    np.testing.assert_array_equal(a, b)
    assert len(set(a.tolist())) == 4


def test_jump_consistent_hash_minimal_movement():
    keys = hashing.splitmix64_np(np.arange(20000, dtype=np.uint64))
    b8 = hashing.jump_consistent_hash_np(keys, 8)
    b9 = hashing.jump_consistent_hash_np(keys, 9)
    assert b8.min() >= 0 and b8.max() == 7
    moved = (b8 != b9).mean()
    # jump hash moves ~1/9 of keys on 8→9 resize (vs ~8/9 for modulo)
    assert moved < 0.15
    # everything that moved went to the new bucket
    assert set(b9[b8 != b9].tolist()) == {8}
    # rough balance
    counts = np.bincount(b8, minlength=8)
    assert counts.min() > 0.8 * counts.mean()
