"""The graftlint tier-1 gate: the repo must be finding-free.

``python -m cloudberry_tpu.lint cloudberry_tpu/`` exits 0 — zero
unsuppressed findings — and every suppression carries a justification.
A new finding here means a concurrency/kernel/taxonomy/seam invariant
regressed (or a pass needs a justified ``# graftlint: ignore[rule]``
at the site — with the reasoning, not just the tag).
"""

import functools
import os

import cloudberry_tpu
from cloudberry_tpu.lint import run_lint

PKG = os.path.dirname(os.path.abspath(cloudberry_tpu.__file__))


@functools.lru_cache(maxsize=1)
def _result():
    return run_lint([PKG])


def test_repo_is_finding_free():
    result = _result()
    msgs = [f.render() for f in result.unsuppressed]
    assert not msgs, "graftlint findings:\n" + "\n".join(msgs)


def test_every_suppression_has_a_justification():
    result = _result()
    bare = [f.render() for f in result.suppressed
            if not f.justification.strip()]
    assert not bare, ("suppressions without a justification:\n"
                      + "\n".join(bare))


def test_gate_runner_agrees():
    """tools/lint_gate.py emits the same verdict the in-process API
    gives (the CI entry point must never drift from the tests)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "lint_gate", os.path.join(os.path.dirname(PKG), "tools",
                                  "lint_gate.py"))
    gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gate)
    rec = gate.gate_record()
    assert rec["ok"] is True
    assert rec["findings"] == []
    assert rec["suppressions"] >= 1  # the documented deliberate sites
    assert all(s["justification"] for s in rec["suppression_sites"])


def test_fault_point_inventory_in_sync():
    """Pinned both-ways sync between the faultinject INVENTORY and the
    engine's fault_point call sites (the seam pass's model, asserted
    directly so a pass regression cannot mask an inventory drift)."""
    import ast

    from cloudberry_tpu.utils.faultinject import INVENTORY

    sites = set()
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn)) as f:
                tree = ast.parse(f.read())
            for node in ast.walk(tree):
                if isinstance(node, ast.Call) and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    fname = getattr(node.func, "id",
                                    getattr(node.func, "attr", ""))
                    if fname == "fault_point":
                        sites.add(node.args[0].value)
    assert sites == set(INVENTORY), (
        f"missing from INVENTORY: {sorted(sites - set(INVENTORY))}; "
        f"stale in INVENTORY: {sorted(set(INVENTORY) - sites)}")


def test_witness_order_covers_discovered_locks():
    """Every lock the static pass discovers in the concurrent-core
    modules either has a declared witness rank or is a known
    per-object/private lock — the declared order cannot silently rot
    as modules grow."""
    from cloudberry_tpu.lint.config import witness_ranks

    result = _result()
    ranks = witness_ranks()
    resolved = 0
    for name, (_f, _l, _kind, alias) in result.lock_sites.items():
        if name in ranks or (alias and alias in ranks):
            resolved += 1
    # the declared order must cover a healthy majority of real sites
    assert resolved >= 15, (resolved, sorted(result.lock_sites))


def test_planprops_rule_table_exhaustive_live():
    """The live mirror of the planprops pass: plan/verify.py RULES
    covers every PlanNode subclass actually importable from the
    package, both ways — so the static rule and the runtime table can
    never drift apart."""
    from cloudberry_tpu.exec.tiled import _AccLeaf  # noqa: F401
    from cloudberry_tpu.plan import nodes as N
    from cloudberry_tpu.plan.verify import RULES

    def subclasses(cls):
        for sub in cls.__subclasses__():
            yield sub
            yield from subclasses(sub)

    live = {c.__name__ for c in subclasses(N.PlanNode)}
    assert live <= set(RULES), sorted(live - set(RULES))
    assert set(RULES) <= live, sorted(set(RULES) - live)


def test_planprops_mode_tables_agree_live():
    from cloudberry_tpu.exec.recovery import REPLACEABLE
    from cloudberry_tpu.exec.tiled import CHECKPOINT_MODES

    assert set(CHECKPOINT_MODES) == set(REPLACEABLE)
