"""WITH (common table expressions) + materialize-once sharing.

The reference evaluates a multiply-referenced CTE once and shares the
tuplestore across slices via ShareInputScan (nodeShareInputScan.c:31-45).
Here every reference to a CTE holds the SAME bound subplan behind a PShare
node; plan rewrites and lowering memoize on its identity, so the subplan is
traced once per XLA program.
"""

import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.plan import nodes as N
from tools.tpchgen import load_tpch


@pytest.fixture(scope="module", params=[1, 8], ids=["single", "dist8"])
def s(request):
    sess = cb.Session(Config(n_segments=request.param)) \
        if request.param > 1 else cb.Session()
    load_tpch(sess, sf=0.01, seed=7)
    return sess


def test_basic_cte(s):
    q = ("with big as (select l_orderkey, sum(l_quantity) as q "
         "from lineitem group by l_orderkey) "
         "select count(*) as n from big where q > 100")
    direct = ("select count(*) as n from (select l_orderkey, "
              "sum(l_quantity) as q from lineitem group by l_orderkey) v "
              "where q > 100")
    assert s.sql(q).to_pandas().n[0] == s.sql(direct).to_pandas().n[0]


def test_chained_ctes(s):
    q = ("with a as (select l_orderkey as k, l_quantity as q from lineitem "
         "where l_quantity > 30), "
         "b as (select k, count(*) as n from a group by k) "
         "select count(*) as n from b where n >= 2")
    assert s.sql(q).to_pandas().n[0] > 0


def test_shared_cte_self_join(s):
    # both references must see the SAME materialization: equal keys imply
    # equal revenues, so the strict inequality self-join is empty
    q = ("with r as (select l_suppkey as sk, sum(l_extendedprice) as rev "
         "from lineitem group by l_suppkey) "
         "select count(*) as n from r a, r b "
         "where a.rev > b.rev and a.sk = b.sk")
    assert s.sql(q).to_pandas().n[0] == 0


def test_share_is_one_object(s):
    from cloudberry_tpu.plan.binder import Binder
    from cloudberry_tpu.sql.parser import parse_sql

    q = ("with r as (select l_suppkey as sk, count(*) as n from lineitem "
         "group by l_suppkey) "
         "select a.sk from r a, r b where a.sk = b.sk")
    plan = Binder(s.catalog).bind_query(parse_sql(q))
    shares = []

    def walk(n):
        if isinstance(n, N.PShare):
            shares.append(n)
        for c in n.children():
            walk(c)

    walk(plan)
    assert len(shares) == 2
    assert shares[0].child is shares[1].child  # materialize-once contract


def test_cte_visible_in_subquery(s):
    q = ("with r as (select l_suppkey as sk, sum(l_quantity) as q "
         "from lineitem group by l_suppkey) "
         "select count(*) as n from r "
         "where q = (select max(q) from r)")
    assert s.sql(q).to_pandas().n[0] >= 1


def test_q15_as_cte(s):
    """TPC-H Q15 spelled with WITH instead of repeated derived tables."""
    from tools.tpch_queries import QUERIES

    q15_with = """
    with revenue as (
        select l_suppkey as supplier_no,
               sum(l_extendedprice * (1 - l_discount)) as total_revenue
        from lineitem
        where l_shipdate >= date '1996-01-01'
            and l_shipdate < date '1996-01-01' + interval '3' month
        group by l_suppkey
    )
    select s_suppkey, s_name, s_address, s_phone, total_revenue
    from supplier, revenue
    where s_suppkey = supplier_no
      and total_revenue = (select max(total_revenue) from revenue)
    order by s_suppkey
    """
    a = s.sql(q15_with).to_pandas()
    b = s.sql(QUERIES["q15"]).to_pandas()
    assert a.values.tolist() == b.values.tolist()


def test_cte_in_ctas():
    s2 = cb.Session()
    s2.sql("create table t (a int, b int) distributed by (a)")
    s2.sql("insert into t values (1, 10), (2, 20)")
    s2.sql("create table t2 as with d as (select a, b * 2 as b2 from t) "
           "select * from d distributed by (a)")
    assert s2.sql("select b2 from t2 order by b2").to_pandas() \
        .b2.tolist() == [20, 40]


def test_cte_with_nulls():
    s2 = cb.Session()
    s2.sql("create table t (a int, b int) distributed by (a)")
    s2.sql("insert into t values (1, 10), (2, null), (3, 30)")
    q = ("with d as (select a, b from t) "
         "select count(*) as n from d x, d y "
         "where x.a = y.a and x.b is null")
    assert s2.sql(q).to_pandas().n[0] == 1


def test_cte_name_shadows_table():
    s2 = cb.Session()
    s2.sql("create table t (a int) distributed by (a)")
    s2.sql("insert into t values (1), (2), (3)")
    out = s2.sql("with t as (select a from t where a > 1) "
                 "select count(*) as n from t").to_pandas()
    assert out.n[0] == 2
