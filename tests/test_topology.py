"""Online topology changes (parallel/topology.py) — ISSUE 13.

Epoch-versioned placement: statements pin a TopologyEpoch at dispatch;
expand/shrink creates a successor epoch, a background rebalancer moves
only the jump-hash minimal delta (OCC-committed, journal-resumable
chunks for store-backed tables), and cutover is a breaker-guarded atomic
flip. Failover-as-shrink promotes persistent device loss to an automatic
shrink epoch; device recovery expands back. Pinned here:

- moved rows within 1.25x of the delta/N minimal-movement bound, RAM
  and store layers, with bit-identical results across the flip;
- store movement is resumable (chunk fault -> re-begin resumes from the
  journal without re-moving) and delta partitions are destination-tagged;
- cutover under load: concurrent clients over the wire survive a
  mid-load online expand AND a fault-driven failover shrink with ZERO
  dropped requests and results bit-identical to a static cluster, every
  replan passing the planck verifier at the new nseg;
- shared-cache-tier keys carry the topology-epoch token (a stale-nseg
  compiled program can never serve after cutover — forced via a
  config-uid collision);
- mid-statement cutover: a checkpointed tiled statement resumes across
  the epoch boundary through the degraded re-shard path;
- mgmt expand --online is pinned equivalent to the offline path;
- meta "topology" verb + topo gauges; serve_bench chaos columns.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config, get_config
from cloudberry_tpu.parallel.topology import TopologyError
from cloudberry_tpu.utils import faultinject as FI


@pytest.fixture(autouse=True)
def _clean_faults():
    FI.reset_fault()
    yield
    FI.reset_fault()


def _mk(nseg=4, **ov):
    over = {"n_segments": nseg,
            "health.backoff_s": 0.01, "health.backoff_max_s": 0.05}
    over.update(ov)
    return cb.Session(get_config().with_overrides(**over))


def _load(s, n=20000, name="t"):
    s.sql(f"create table {name} (k bigint, v bigint) distributed by (k)")
    t = s.catalog.table(name)
    t.set_data({"k": np.arange(n, dtype=np.int64),
                "v": (np.arange(n, dtype=np.int64) * 3) % 97}, {})
    return t


_Q = "select sum(v) as sv, count(*) as c from t"


# ------------------------------------------------------ epochs + resize


def test_online_expand_minimal_movement_and_identical_results():
    s = _mk(4)
    _load(s)
    before = s.sql(_Q).to_pandas()
    assert s._topology.current.epoch_id == 1
    out = s._topology.online_resize(6)
    assert out["epoch"] == 2 and s.config.n_segments == 6
    reb = out["rebalance"]
    frac = reb["moved_rows"] / reb["total_rows"]
    bound = reb["minimal_bound"]
    assert bound == pytest.approx(1 / 3, abs=1e-4)
    # the acceptance bound: measured movement within 1.25x of delta/N
    assert frac <= 1.25 * bound, (frac, bound)
    assert frac >= 0.5 * bound  # and it genuinely moved the delta
    after = s.sql(_Q).to_pandas()
    assert before.equals(after)
    assert s.stmt_log.counter("epoch_flips") == 1
    assert s.stmt_log.counter("topo_moved_rows") == reb["moved_rows"]


def test_online_shrink_back_identical():
    s = _mk(6)
    _load(s)
    before = s.sql(_Q).to_pandas()
    out = s._topology.online_resize(4)
    assert out["reason"] == "shrink"
    reb = out["rebalance"]
    assert reb["minimal_bound"] == pytest.approx(2 / 6, abs=1e-4)
    assert reb["moved_rows"] / reb["total_rows"] <= 1.25 * reb[
        "minimal_bound"]
    assert before.equals(s.sql(_Q).to_pandas())


def test_staged_assignment_matches_fresh_hash():
    """The rebalancer's staged successor assignment is bit-equal to the
    jump hash the placement layer would derive — one derivation rule."""
    s = _mk(4)
    t = _load(s)
    state = s._topology.begin(6)
    s._topology.rebalance()
    staged = t._topo_assign
    assert staged[1] == 6
    t2 = type(t)(t.name, t.schema, t.policy)
    t2.data = t.data
    t2.stats.row_count = t.num_rows
    assert np.array_equal(staged[2], t2.shard_assignment(6))
    assert state.done
    s._topology.cutover()
    # post-cutover the staged array IS what sharded placement consumes
    assert np.array_equal(t.shard_assignment(6), staged[2])


def test_begin_refuses_second_change_and_oversize():
    s = _mk(2)
    _load(s, n=64)
    s._topology.begin(4)
    with pytest.raises(TopologyError):
        s._topology.begin(3)
    s._topology.abandon()
    with pytest.raises(TopologyError):
        s._topology.begin(4096)  # more segments than visible devices
    with pytest.raises(TopologyError):
        s._topology.cutover()  # nothing in flight after abandon


def test_planned_cutover_refuses_while_breaker_open():
    s = _mk(2)
    _load(s, n=64)
    s._topology.begin(4)
    s._topology.rebalance()
    s._breaker.state = "open"
    s._breaker._opened_at = time.monotonic()
    with pytest.raises(TopologyError):
        s._topology.cutover()
    s._breaker.state = "closed"
    out = s._topology.cutover()
    assert out["nseg"] == 4


def test_statement_pins_epoch_on_handle():
    s = _mk(2)
    _load(s, n=64)
    s.sql(_Q)
    rec = s.stmt_log.recent(1)[0]
    assert rec["sql"].startswith("select")
    # active pin count returns to zero after the statement
    assert s._topology.active_on(1) == 0
    s._topology.online_resize(3)
    s.sql(_Q)
    assert s._topology.active_on(2) == 0


# ------------------------------------------------------- store movement


def _store_session(tmp_path, nseg=4, n=5000, parts=1000, **ov):
    over = {"n_segments": nseg, "storage.root": str(tmp_path),
            "storage.rows_per_partition": parts}
    over.update(ov)
    s = cb.Session(get_config().with_overrides(**over))
    s.sql("create table t (k bigint, v bigint) distributed by (k)")
    t = s.catalog.table("t")
    t.set_data({"k": np.arange(n, dtype=np.int64),
                "v": (np.arange(n, dtype=np.int64) * 3) % 97}, {})
    t._store_version = s.store.save_table(t, rows_per_partition=parts)
    s._sync_store()
    return s


def test_store_rebalance_moves_minimal_delta(tmp_path):
    s = _store_session(tmp_path)
    before = s.sql(_Q).to_pandas()
    rows_before = s.sql("select k, v from t order by k").to_pandas()
    out = s._topology.online_resize(6)
    reb = out["rebalance"]
    frac = reb["moved_rows"] / reb["total_rows"]
    assert frac <= 1.25 * reb["minimal_bound"]
    assert frac >= 0.5 * reb["minimal_bound"]
    man = s.store.read_manifest("t")
    delta = [p for p in man["partitions"] if p.get("seg_nseg") == 6]
    assert delta, "physical movement must produce delta partitions"
    assert sum(p["num_rows"] for p in delta) == reb["moved_rows"]
    # every delta partition is destination-pure at the new nseg
    for p in delta:
        assert 0 <= p["seg"] < 6
    # content is unchanged as a relation (movement only reorders rows)
    assert before.equals(s.sql(_Q).to_pandas())
    assert rows_before.equals(
        s.sql("select k, v from t order by k").to_pandas())
    # a FRESH session over the store adopts the committed epoch
    s2 = cb.Session(get_config().with_overrides(
        **{"n_segments": 6, "storage.root": str(tmp_path)}))
    assert s2._topology.current.epoch_id == out["epoch"]
    assert rows_before.equals(
        s2.sql("select k, v from t order by k").to_pandas())


def test_store_rebalance_resumes_from_journal(tmp_path):
    s = _store_session(tmp_path)
    expected = s.sql("select k, v from t order by k").to_pandas()
    s._topology.begin(6)
    FI.inject_fault("topo_rebalance_chunk", "error", start_hit=3,
                    end_hit=3)
    with pytest.raises(FI.InjectedFault):
        s._topology.rebalance()
    FI.reset_fault()
    journal = json.loads(
        open(os.path.join(str(tmp_path), "_TOPOLOGY.json")).read())
    done_before = sum(len(v) for v in
                      journal["pending"]["done_files"].values())
    assert done_before >= 1
    moved_partial = journal["pending"]["moved_rows"]
    # a FRESH manager (crash-restart analog) resumes from the journal:
    # already-processed partitions are not re-moved
    s2 = cb.Session(get_config().with_overrides(
        **{"n_segments": 4, "storage.root": str(tmp_path)}))
    state = s2._topology.begin(6)
    assert state.moved_rows == moved_partial
    assert sum(len(v) for v in state.done_files.values()) == done_before
    s2._topology.rebalance()
    out = s2._topology.cutover()
    reb = out["rebalance"]
    frac = reb["moved_rows"] / max(reb["total_rows"], 1)
    # resumed totals still respect the minimal-movement bound — nothing
    # was moved twice
    assert frac <= 1.25 * reb["minimal_bound"]
    assert expected.equals(
        s2.sql("select k, v from t order by k").to_pandas())


def test_store_rebalance_occ_survives_concurrent_append(tmp_path):
    """A concurrent commit mid-rebalance loses nothing: the chunk's OCC
    check re-reads, and rows appended during the move keep serving."""
    s = _store_session(tmp_path)
    stop = threading.Event()

    def writer():
        s2 = cb.Session(get_config().with_overrides(
            **{"n_segments": 4, "storage.root": str(tmp_path)}))
        t = s2.catalog.table("t")
        t.ensure_loaded()
        s2.store.append(
            "t", {"k": np.arange(90000, 90007, dtype=np.int64),
                  "v": np.full(7, 7, dtype=np.int64)},
            t.schema, rows_per_partition=1000)

    w = threading.Thread(target=writer)
    s._topology.begin(6)
    w.start()
    s._topology.rebalance(throttle_s=0.002)
    w.join()
    stop.set()
    s._topology.cutover()
    df = s.sql("select count(*) as c, sum(v) as sv from t").to_pandas()
    base = int(((np.arange(5000) * 3) % 97).sum())
    assert int(df["c"][0]) == 5007
    assert int(df["sv"][0]) == base + 49


# --------------------------------------------- failover / recovery path


def test_failover_promotion_then_recovery_expand():
    s = _mk(8, **{"health.retries": 3, "topology.promote_after": 2,
                  "topology.recover_after": 2})
    _load(s, n=8000)
    before = s.sql(_Q).to_pandas()
    # persistent loss: every probe reports one device gone, and two
    # statements each hit a transient loss -> probe -> degrade -> the
    # SAME survivor set observed repeatedly promotes to a formal
    # failover-shrink epoch (8 -> 7)
    FI.inject_fault("probe_degraded", "skip", end_hit=1 << 30)
    FI.inject_fault("exec_device_lost", "error", start_hit=1, end_hit=2)
    assert before.equals(s.sql(_Q).to_pandas())
    snap = s._topology.snapshot()
    assert snap["reason"] == "failover" and snap["nseg"] == 7
    assert snap["promotions"] == 1
    assert s.config.n_segments == 7
    # the devices come back: consecutive clean probes trigger the
    # symmetric online expand back to the pre-failover count
    FI.reset_fault()
    for _ in range(2):
        s._topology.probe_and_heal()
    snap = s._topology.snapshot()
    assert snap["reason"] == "recover" and snap["nseg"] == 8
    assert s.config.n_segments == 8
    assert before.equals(s.sql(_Q).to_pandas())


def test_promote_seam_suppresses_promotion():
    s = _mk(8, **{"health.retries": 3, "topology.promote_after": 1})
    _load(s, n=2000)
    FI.inject_fault("probe_degraded", "skip", end_hit=1 << 30)
    FI.inject_fault("topo_promote", "skip", end_hit=1 << 30)
    FI.inject_fault("exec_device_lost", "error", start_hit=1, end_hit=1)
    s.sql(_Q)
    snap = s._topology.snapshot()
    # the per-statement degrade minted its (versioned) degrade epoch,
    # but the FORMAL failover promotion was suppressed by the seam
    assert snap["promotions"] == 0 and snap["reason"] == "degrade"
    assert s.config.n_segments == 7


def test_second_deeper_loss_promotes_again():
    """An 8->7 failover followed by ANOTHER dead device promotes again
    (to 6) — the already-formalized guard keys on the survivor count,
    not just the epoch reason — and recovery returns to the ORIGINAL
    pre-failover size."""
    from cloudberry_tpu.parallel.health import ProbeResult

    s = _mk(8, **{"topology.promote_after": 1,
                  "topology.recover_after": 2})
    _load(s, n=256)
    s._topology.note_probe(ProbeResult(True, 7, 0.0,
                                       live=list(range(7))))
    snap = s._topology.snapshot()
    assert snap["reason"] == "failover" and snap["nseg"] == 7
    s._topology.note_probe(ProbeResult(True, 6, 0.0,
                                       live=list(range(6))))
    snap = s._topology.snapshot()
    assert snap["nseg"] == 6 and snap["promotions"] == 2
    # repeating the SAME survivor set does not re-promote
    s._topology.note_probe(ProbeResult(True, 6, 0.0,
                                       live=list(range(6))))
    assert s._topology.snapshot()["promotions"] == 2
    for _ in range(2):
        s._topology.note_probe(ProbeResult(True, 8, 0.0,
                                           live=list(range(8))))
    snap = s._topology.snapshot()
    assert snap["reason"] == "recover" and snap["nseg"] == 8


def test_planned_resize_resets_failover_baseline():
    """An operator resize AFTER a failover establishes a new healthy
    baseline: stale pre-failover state must not promote the cluster
    back toward a size the operator resized away from."""
    from cloudberry_tpu.parallel.health import ProbeResult

    s = _mk(8, **{"topology.promote_after": 1,
                  "topology.recover_after": 1})
    _load(s, n=256)
    s._topology.note_probe(ProbeResult(True, 7, 0.0,
                                       live=list(range(7))))
    assert s._topology.snapshot()["reason"] == "failover"
    s._topology.online_resize(4)
    # 7 live devices is neither a loss (healthy is now 4) nor a
    # recovery trigger (no failover outstanding)
    s._topology.note_probe(ProbeResult(True, 7, 0.0,
                                       live=list(range(7))))
    snap = s._topology.snapshot()
    assert snap["reason"] == "shrink" and snap["nseg"] == 4
    assert s.config.n_segments == 4


def test_recovery_deferred_while_breaker_open():
    """Auto-recover never expands back into a flap: an open breaker
    defers the promotion (without killing the probe path), and the next
    clean probe after it closes completes it."""
    from cloudberry_tpu.parallel.health import ProbeResult

    s = _mk(8, **{"topology.promote_after": 1,
                  "topology.recover_after": 1})
    _load(s, n=256)
    s._topology.note_probe(ProbeResult(True, 7, 0.0,
                                       live=list(range(7))))
    assert s._topology.snapshot()["reason"] == "failover"
    s._breaker.state = "open"
    s._breaker._opened_at = time.monotonic()
    out = s._topology.note_probe(ProbeResult(True, 8, 0.0,
                                             live=list(range(8))))
    assert out is None
    assert s._topology.snapshot()["nseg"] == 7  # deferred, not dead
    s._breaker.state = "closed"
    s._topology.note_probe(ProbeResult(True, 8, 0.0,
                                       live=list(range(8))))
    snap = s._topology.snapshot()
    assert snap["reason"] == "recover" and snap["nseg"] == 8


def test_health_monitor_feeds_topology():
    from cloudberry_tpu.parallel import health

    s = _mk(8, **{"topology.promote_after": 2})
    _load(s, n=500)
    mon = health.HealthMonitor(interval_s=3600, topology=s._topology)
    FI.inject_fault("probe_degraded", "skip", end_hit=1 << 30)
    mon.probe_now()
    mon.probe_now()
    snap = s._topology.snapshot()
    assert snap["reason"] == "failover" and snap["nseg"] == 7


# --------------------------------------- shared-cache epoch token (fix)


def test_epoch_token_rides_every_shared_cache_key():
    from cloudberry_tpu.sched import sharedcache

    s = _mk(4)
    _load(s, n=512)
    tok1 = sharedcache.topology_token(s)
    pe1 = sharedcache.plan_epoch(s)
    rt1 = sharedcache.rung_scope_token(s)
    s._topology.online_resize(6)
    tok2 = sharedcache.topology_token(s)
    assert tok2 == tok1 + 1
    assert tok1 in pe1 and tok2 in sharedcache.plan_epoch(s)
    assert tok1 in rt1 and tok2 in sharedcache.rung_scope_token(s)


def test_stale_nseg_program_never_serves_after_cutover(
        tmp_path, monkeypatch):
    """Force the stale hit the fix targets: collapse config_uid (the
    identity component shared rung keys otherwise rely on — and the one
    that can genuinely alias, since it is an id()-keyed map) so that
    after a 4->6->4 round trip the epoch-1 and epoch-3 key prefixes are
    IDENTICAL except for the topology token. Without the token the
    epoch-1 compiled program would serve at epoch 3; with it, every
    shared key differs in exactly that component."""
    from cloudberry_tpu.sched import sharedcache

    s = _store_session(tmp_path, nseg=4, n=2000)
    monkeypatch.setattr(sharedcache, "config_uid", lambda cfg: 0)
    q = "select k % 8 as g, sum(v) as sv from t group by g order by g"
    first = s.sql(q).to_pandas()
    rt1 = sharedcache.rung_scope_token(s)
    pe1 = sharedcache.plan_epoch(s)
    assert rt1[0] == "shared" and pe1[0] == "store"
    s._topology.online_resize(6)
    s._topology.online_resize(4)  # same nseg as epoch 1 again
    rt3 = sharedcache.rung_scope_token(s)
    pe3 = sharedcache.plan_epoch(s)
    # with config_uid collapsed, the token is the ONLY differing
    # component — remove it and the keys alias (the stale-hit hazard)
    assert rt1 != rt3 and pe1 != pe3
    assert (rt1[0],) + rt1[2:] == (rt3[0],) + rt3[2:]
    assert (pe1[0],) + pe1[2:] == (pe3[0],) + pe3[2:]
    assert rt3[1] == rt1[1] + 2 and pe3[1] == pe1[1] + 2
    # end-to-end: the round trip never serves a stale program and the
    # answer stays bit-identical
    c1 = s.stmt_log.counter("compiles")
    assert first.equals(s.sql(q).to_pandas())
    assert s.stmt_log.counter("compiles") > c1, \
        "epoch-1 program served at epoch 3 (stale-nseg cache hit)"


def test_join_index_key_carries_epoch_token(tmp_path):
    from cloudberry_tpu.sched import sharedcache

    s = _store_session(tmp_path, nseg=2, n=512)
    s.sql("create table d (k bigint, w bigint) distributed by (k)")
    d = s.catalog.table("d")
    d.set_data({"k": np.arange(64, dtype=np.int64),
                "w": np.arange(64, dtype=np.int64)}, {})
    q = "select sum(t.v) as sv from t join d on t.k = d.k"
    r1 = s.sql(q).to_pandas()
    keys_before = list(s._cache_scope.joinindex)
    s._topology.online_resize(3)
    assert r1.equals(s.sql(q).to_pandas())
    tok = sharedcache.topology_token(s)
    new_keys = [k for k in s._cache_scope.joinindex
                if k not in keys_before]
    if keys_before or new_keys:  # join-index eligible plan
        for k in new_keys:
            assert k[-1] == tok
        for k in keys_before:
            assert k[-1] != tok


# ------------------------------------------------- observability plane


def test_meta_topology_verb_and_gauges():
    from cloudberry_tpu.serve import meta

    s = _mk(2)
    _load(s, n=256)
    s._topology.online_resize(3)
    snap = meta.describe(s, "topology")
    assert snap["enabled"] and snap["epoch"] == 2 and snap["nseg"] == 3
    assert snap["flips"] == 1 and snap["history"][-1]["reason"] == "expand"
    m = meta.describe(s, "metrics")
    assert m["gauges"]["topo_epoch"] == 2
    assert m["gauges"]["topo_nseg"] == 3
    assert m["gauges"]["topo_rebalance_fraction"] == 1.0
    assert m["gauges"]["topo_moved_bytes"] > 0
    assert m["counters"]["epoch_flips"] == 1


# -------------------------------------------------- mid-statement flip


def test_checkpointed_statement_resumes_across_expand_cutover():
    """A tiled distributed statement killed mid-stream resumes AFTER an
    online expand cutover landed between attempts: the PR-6 degraded
    re-shard path re-places its checkpoint at the LARGER nseg,
    bit-identical (the 'resume through re-shard' arm of cutover)."""
    s = _mk(6, **{"resource.query_mem_bytes": 512 << 10,
                  "recovery.checkpoint_every": 2,
                  "health.retries": 2, "health.backoff_s": 1.0,
                  "health.backoff_max_s": 1.0})
    # distributed by k, grouped by g: a TWO-STAGE agg (merge motion),
    # whose placement-free partials re-shard across a changed nseg —
    # a colocated one-stage agg would decline by design
    s.sql("create table big (k bigint, g bigint, v bigint) "
          "distributed by (k)")
    n = 400000
    rng = np.random.default_rng(7)
    s.catalog.table("big").set_data(
        {"k": np.arange(n, dtype=np.int64) % 997,
         "g": rng.integers(0, 9, n).astype(np.int64),
         "v": rng.integers(0, 1000, n).astype(np.int64)}, {})
    q = "select g, sum(v) as sv from big group by g order by g"
    expected = s.sql(q).to_pandas()
    assert s.last_tiled_report is not None, "must exercise the tiled path"
    # kill the stream mid-tiles; while the retry backs off, flip 6 -> 8
    FI.inject_fault("tile_device_lost", "error", start_hit=4, end_hit=4)
    done = {}

    def run():
        done["df"] = s.sql(q).to_pandas()

    th = threading.Thread(target=run)
    th.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        rows = [r for r in s.stmt_log.activity()
                if r.get("state") == "recovering"]
        if rows:
            break
        time.sleep(0.01)
    assert rows, "statement never entered recovery"
    s._topology.begin(8)
    s._topology.rebalance()
    s._topology.cutover(wait_s=0.0)  # flip under the in-flight statement
    th.join(timeout=60)
    assert "df" in done and expected.equals(done["df"])
    assert s.config.n_segments == 8
    assert s.stmt_log.counter("tile_resumes") >= 1
    assert s.stmt_log.counter("topo_resharded_resumes") >= 1


# --------------------------------------------------- cutover under load


def _serve_load(nseg, actions, clients=8, verify_plans=True):
    """serve_bench-style harness: ``clients`` closed-loop wire clients
    issue deterministic statements against a shared-session server while
    ``actions(session)`` lands topology changes mid-load. Every response
    is recorded; ANY non-retryable error fails the run (zero-drop pin).
    Returns (session, {sql: rows}) for the bit-identical check."""
    from cloudberry_tpu.serve import Client, Server, ServerError

    over = {"n_segments": nseg, "health.retries": 4,
            "health.backoff_s": 0.01, "health.backoff_max_s": 0.05,
            "topology.promote_after": 2,
            # serialize SPMD programs: on the virtual CPU mesh two
            # concurrent multi-device programs can interleave on the
            # shared per-device streams in opposite orders and deadlock
            # in their collectives' rendezvous (a CPU-backend property,
            # not an engine one — real TPU meshes queue per-core);
            # clients still hammer concurrently, statements queue at
            # the admission gate
            "resource.max_concurrency": 1,
            "debug.verify_plans": verify_plans}
    s = cb.Session(get_config().with_overrides(**over))
    _load(s, n=4000)
    s.sql("create table pts (k bigint, v bigint) distributed by (k)")
    s.catalog.table("pts").set_data(
        {"k": np.arange(2000, dtype=np.int64),
         "v": (np.arange(2000, dtype=np.int64) * 11) % 1009}, {})

    def sql_for(i):
        if i % 3 == 0:
            return ("select sum(v) as sv, count(*) as c from t "
                    f"where k < {1000 + (i % 7) * 100}")
        if i % 3 == 1:
            return f"select k, v from pts where k = {(i * 37) % 2000}"
        return ("select k % 5 as g, sum(v) as sv from t "
                f"where v < {90 - (i % 4)} group by g order by g")

    results: dict[str, list] = {}
    res_lock = threading.Lock()
    errors: list[str] = []
    stop = threading.Event()

    def client(wid):
        try:
            with Client(srv.host, srv.port) as c:
                i = wid * 1009
                while not stop.is_set():
                    q = sql_for(i)
                    i += 1
                    try:
                        out = c.sql(q)
                    except ServerError as e:
                        if getattr(e, "retryable", False):
                            continue
                        raise
                    with res_lock:
                        results.setdefault(q, out.get("rows"))
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(f"{type(e).__name__}: {e}")

    with Server(session=s) as srv:
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(clients)]
        for t in threads:
            t.start()
        try:
            actions(s)
        finally:
            time.sleep(0.4)
            stop.set()
            for t in threads:
                t.join(timeout=60)
    assert not errors, f"dropped/errored requests: {errors[:3]}"
    assert results, "load loop produced no results"
    return s, results


def test_cutover_under_load_expand_and_failover_shrink():
    """The acceptance run: concurrent wire clients survive a mid-load
    online expand (4 -> 8) AND a fault-driven failover shrink (8 -> 7)
    with zero dropped requests; every recorded response is bit-identical
    to a static cluster's, and every replan passed the planck verifier
    (debug.verify_plans ON for the serving session)."""

    def actions(s):
        time.sleep(0.3)
        out = s._topology.online_resize(8)
        assert out["nseg"] == 8
        time.sleep(0.3)
        # persistent device loss under load: probes keep reporting the
        # 7 survivors, two transient losses promote failover-as-shrink
        FI.inject_fault("probe_degraded", "skip", end_hit=1 << 30)
        FI.inject_fault("exec_device_lost", "error", start_hit=1,
                        end_hit=2)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if s._topology.snapshot()["reason"] == "failover":
                break
            time.sleep(0.02)
        FI.reset_fault()
        snap = s._topology.snapshot()
        assert snap["reason"] == "failover" and snap["nseg"] == 7
        time.sleep(0.3)

    s, results = _serve_load(4, actions)
    assert s.stmt_log.counter("epoch_flips") >= 2
    assert s.stmt_log.counter("topo_promotions") >= 1
    # bit-identical vs a STATIC cluster: re-run every recorded
    # statement on a fresh fixed-topology session and compare rows
    static = cb.Session(get_config().with_overrides(
        **{"n_segments": 4}))
    _load(static, n=4000)
    static.sql("create table pts (k bigint, v bigint) "
               "distributed by (k)")
    static.catalog.table("pts").set_data(
        {"k": np.arange(2000, dtype=np.int64),
         "v": (np.arange(2000, dtype=np.int64) * 11) % 1009}, {})
    from cloudberry_tpu.serve.server import _json_safe

    def wire_rows(result):
        cols = result.decoded_columns()
        arrays = list(cols.values())
        n = len(arrays[0]) if arrays else 0
        return [[_json_safe(a[i]) for a in arrays] for i in range(n)]

    for q, rows in sorted(results.items()):
        want = wire_rows(static.sql(q))
        assert rows == want, f"divergent result for {q!r}"


def test_serve_bench_expand_shrink_columns():
    """serve_bench --expand-at/--shrink-at smoke (CPU tier-1): the
    topology chaos columns ride the CSV and the run drops nothing."""
    import tools.serve_bench as SB

    r = SB.run_mode("direct", "point", clients=4, duration_s=1.6,
                    rows=4000, tick_s=0.002, max_batch=8, segments=2,
                    expand_at=(0.3, 4), shrink_at=(0.8, 3))
    assert r["epoch_flips"] == 2
    assert r["cutover_ms"] > 0
    assert r["moved_rows"] > 0
    assert r["requests"] > 0
    row = SB.csv_row(r)
    assert row.count(",") == SB.CSV_HEADER.count(",")


# ------------------------------------------------------------ mgmt CLI


def _init_store(tmp_path, name, nseg=4, n=3000):
    from cloudberry_tpu.mgmt import cli

    root = os.path.join(str(tmp_path), name)
    assert cli.main(["--store", root, "init",
                     "--segments", str(nseg)]) == 0
    s = cb.Session(Config(n_segments=nseg).with_overrides(
        **{"storage.root": root}))
    s.sql("create table t (k bigint, v bigint) distributed by (k)")
    t = s.catalog.table("t")
    t.set_data({"k": np.arange(n, dtype=np.int64),
                "v": (np.arange(n, dtype=np.int64) * 3) % 97}, {})
    t._store_version = s.store.save_table(t, rows_per_partition=500)
    return root


def test_mgmt_expand_online_reports_bound_and_matches_offline(
        tmp_path, capsys):
    from cloudberry_tpu.mgmt import cli

    on_root = _init_store(tmp_path, "on")
    off_root = _init_store(tmp_path, "off")
    assert cli.main(["--store", on_root, "expand", "--segments", "6",
                     "--online"]) == 0
    out = capsys.readouterr().out
    assert "ONLINE" in out and "minimal-movement bound" in out
    assert cli.main(["--store", off_root, "expand",
                     "--segments", "6"]) == 0
    assert json.load(open(os.path.join(
        on_root, "cluster.json")))["n_segments"] == 6
    # pinned equivalent: both paths land on the same derived placement
    # and the same relation content
    son = cb.Session(Config(n_segments=6).with_overrides(
        **{"storage.root": on_root}))
    soff = cb.Session(Config(n_segments=6).with_overrides(
        **{"storage.root": off_root}))
    q = "select k, v from t order by k"
    assert son.sql(q).to_pandas().equals(soff.sql(q).to_pandas())
    ton, toff = son.catalog.table("t"), soff.catalog.table("t")
    ton.ensure_loaded()
    toff.ensure_loaded()
    an = ton.shard_assignment(6)[np.argsort(
        np.asarray(ton.data["k"]), kind="stable")]
    aoff = toff.shard_assignment(6)[np.argsort(
        np.asarray(toff.data["k"]), kind="stable")]
    assert np.array_equal(an, aoff)
    # the online store reached a newer epoch; the offline store did not
    assert son._topology.current.epoch_id >= 2
    assert soff._topology.current.epoch_id == 1


def test_post_cutover_replans_pass_planck():
    """Golden-plan re-verification at the new nseg: after an online
    expand, fresh plans run through the planck gate clean (the gate is
    ON, so a derived-vs-required property violation would refuse)."""
    s = _mk(4, **{"debug.verify_plans": True})
    _load(s, n=4000)
    s.sql("create table d (k bigint, w bigint) distributed by (k)")
    s.catalog.table("d").set_data(
        {"k": np.arange(256, dtype=np.int64),
         "w": np.arange(256, dtype=np.int64)}, {})
    qs = [_Q,
          "select k % 7 as g, sum(v) as sv from t group by g order by g",
          "select sum(t.v) as sv from t join d on t.k = d.k",
          # k breaks v-ties: a nondeterministic tie order would differ
          # across segment layouts regardless of topology correctness
          "select k, v from t order by v desc, k limit 5"]
    before = [s.sql(q).to_pandas() for q in qs]
    s._topology.online_resize(8)
    for q, b in zip(qs, before):
        assert b.equals(s.sql(q).to_pandas())
    # and the verify window armed by adoption really decrements
    assert s._verify_next_plans >= 0


def test_adoption_verify_window_fires_without_debug_gate(monkeypatch):
    """config.topology.verify_replans: the first fresh plans after an
    epoch adoption are planck-verified even with debug.verify_plans
    off."""
    calls = []
    from cloudberry_tpu.plan import verify as V

    real = V.check_plan

    def spy(plan, session, context="", **kw):
        calls.append(context)
        return real(plan, session, context, **kw)

    monkeypatch.setattr(V, "check_plan", spy)
    s = _mk(2)
    _load(s, n=256)
    s.sql(_Q)
    assert not calls  # gate off, no verification
    s._topology.online_resize(3)
    s.sql("select sum(v) as x from t where k < 100")
    assert calls, "post-cutover replan skipped the planck gate"


@pytest.mark.slow
def test_cutover_under_load_1k_clients_8_to_12():
    """The ISSUE's headline numbers: 1000 simulated clients on the
    event-loop core survive an 8->12 online expand and a 12->7 shrink
    mid-load. Runs serve_bench in a SUBPROCESS with 12 virtual devices
    (the in-process suite is pinned at 8)."""
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=12"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "serve_bench.py"),
         "--mode", "direct", "--mix", "point", "--clients", "1000",
         "--duration", "8", "--rows", "20000", "--segments", "8",
         "--driver-threads", "8",
         "--expand-at", "2:12", "--shrink-at", "5:7"],
        capture_output=True, text=True, timeout=560, env=env, cwd=repo)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    header = lines[0].split(",")
    row = dict(zip(header, lines[1].split(",")))
    assert int(row["epoch_flips"]) == 2
    assert float(row["cutover_ms"]) > 0
    assert int(row["moved_rows"]) > 0
    assert int(row["requests"]) > 0
