"""Per-tenant fair scheduling (sched/tenancy.py): DWRR weights, aging,
backpressure taxonomy, dispatcher fairness under saturation, and the
client-retry regression for a saturated tenant (ISSUE-7)."""

import threading
import time

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config, TenancyConfig, TenantSpec
from cloudberry_tpu.exec.resource import TenantQueueFull
from cloudberry_tpu.sched.tenancy import TenantScheduler


def _sched(tenants, **kv):
    cfg = TenancyConfig(enabled=True, tenants=tuple(tenants), **kv)
    return TenantScheduler(cfg)


class _Item:
    """Opaque schedulable item (the dispatcher's _Request stand-in)."""


# ------------------------------------------------------------------ DWRR


def test_dwrr_picks_proportional_to_weight():
    """Deterministic core property: with both queues saturated and no
    aging, pick order serves tenants exactly 3:1."""
    s = _sched([TenantSpec("gold", weight=3, max_queue=1000),
                TenantSpec("silver", weight=1, max_queue=1000)],
               aging_s=3600.0)
    now = time.monotonic()
    items = {}
    for name in ("gold", "silver"):
        for _ in range(120):
            it = _Item()
            items[id(it)] = name
            s.enqueue(name, it)
    picked = []
    while True:
        batch = s.pick(8, now=now)
        if not batch:
            break
        picked.extend(items[id(it)] for it in batch)
        for it in batch:
            s.finish(s.group(items[id(it)]))
    # while BOTH queues were non-empty (first 160 picks), the ratio is
    # exactly 3:1 per round
    head = picked[:160]
    g = head.count("gold")
    sv = head.count("silver")
    assert g == 3 * sv, (g, sv)
    assert len(picked) == 240  # nothing lost


def test_aging_overrides_deficit_order():
    """A head waiting past aging_s is picked FIRST (oldest first), no
    matter how heavy the competing tenant — the starvation bound."""
    s = _sched([TenantSpec("heavy", weight=100, max_queue=1000),
                TenantSpec("starved", weight=1, max_queue=1000)],
               aging_s=0.5)
    t0 = time.monotonic()
    old = _Item()
    s.enqueue("starved", old)
    for _ in range(50):
        s.enqueue("heavy", _Item())
    # 10s later: the starved head is over-age and goes first
    batch = s.pick(4, now=t0 + 10.0)
    assert batch[0] is old
    assert s.snapshot()["starved"]["aged"] == 1


def test_max_concurrency_respected_even_by_aging():
    s = _sched([TenantSpec("t", weight=1, max_concurrency=1,
                           max_queue=10)], aging_s=0.01)
    a, b = _Item(), _Item()
    s.enqueue("t", a)
    s.enqueue("t", b)
    t0 = time.monotonic()
    assert s.pick(8, now=t0 + 5.0) == [a]  # the slot cap holds
    assert s.pick(8, now=t0 + 5.0) == []   # a still running
    s.finish(s.group("t"))
    assert s.pick(8, now=t0 + 5.0) == [b]


def test_tenant_queue_full_is_retryable_by_name():
    from cloudberry_tpu.lifecycle import is_retryable

    s = _sched([TenantSpec("t", weight=1, max_queue=2)])
    s.enqueue("t", _Item())
    s.enqueue("t", _Item())
    with pytest.raises(TenantQueueFull):
        s.enqueue("t", _Item(), wait_s=0.0)
    assert is_retryable("TenantQueueFull")
    assert is_retryable("ServerBusy")
    assert s.snapshot()["t"]["rejected"] == 1


def test_unknown_tenant_gets_default_group():
    s = _sched([TenantSpec("gold", weight=3)])
    s.enqueue("walkin", _Item())
    snap = s.snapshot()
    assert "walkin" in snap and snap["walkin"]["weight"] == 1
    s.enqueue(None, _Item())
    assert "default" in s.snapshot()


def test_slot_gates_direct_path_concurrency():
    s = _sched([TenantSpec("t", weight=1, max_concurrency=1,
                           max_queue=1)], slot_wait_s=0.05)
    entered = threading.Event()
    release = threading.Event()

    def holder():
        with s.slot("t"):
            entered.set()
            release.wait(timeout=30)

    th = threading.Thread(target=holder)
    th.start()
    assert entered.wait(timeout=5)
    with pytest.raises(TenantQueueFull):
        with s.slot("t", wait_s=0.05):
            pass
    release.set()
    th.join(timeout=10)
    with s.slot("t"):
        pass  # slot free again


# ------------------------------------------- dispatcher-level fairness


def _point_session(**over):
    cfg = Config().with_overrides(**over)
    s = cb.Session(cfg)
    s.sql("create table pts (k bigint, v bigint) distributed by (k)")
    s.catalog.table("pts").set_data({
        "k": np.arange(20_000, dtype=np.int64),
        "v": np.arange(20_000, dtype=np.int64) * 3}, {})
    return s


def test_dispatcher_fairness_3_to_1_under_saturation():
    """ISSUE-7 acceptance: two tenants at 3:1 weights under saturation
    observe dispatch throughput within 15% of the weight ratio (pinned
    on the scheduler's pick counters — picks ARE throughput while both
    queues stay backlogged)."""
    from cloudberry_tpu.sched import Dispatcher, TenantScheduler as TS

    s = _point_session(**{
        "sched.enabled": True, "sched.tick_s": 0.001,
        "sched.max_batch": 8, "sched.max_queue": 2048})
    s.sql("select k, v from pts where k = 1")  # warm the generic plan
    tcfg = TenancyConfig(
        enabled=True, aging_s=3600.0,
        tenants=(TenantSpec("gold", weight=3, max_queue=1000),
                 TenantSpec("silver", weight=1, max_queue=1000)))
    sched = TS(tcfg)
    d = Dispatcher(s, tenancy=sched)
    done = [0, 0]
    lock = threading.Lock()

    def _mark(idx):
        def f(r):
            with lock:
                done[idx] += 1
        return f

    # pre-fill BOTH queues (saturation by construction), then serve
    for i in range(150):
        d.submit_nowait(f"select k, v from pts where k = {i}",
                        tenant="gold", on_done=_mark(0))
        d.submit_nowait(f"select k, v from pts where k = {10_000 + i}",
                        tenant="silver", on_done=_mark(1))
    d.start()
    end = time.monotonic() + 120
    # sample while both queues are still non-empty: picks ratio == 3:1
    while time.monotonic() < end:
        snap = sched.snapshot()
        if snap["gold"]["picks"] + snap["silver"]["picks"] >= 120:
            break
        time.sleep(0.01)
    snap = sched.snapshot()
    try:
        g, sv = snap["gold"]["picks"], snap["silver"]["picks"]
        assert sv > 0
        ratio = g / sv
        assert 3.0 * 0.85 <= ratio <= 3.0 * 1.15, (g, sv, ratio)
        assert sched.fairness_index() > 0.9
    finally:
        d.drain(120)
        d.stop()
    assert sum(done) == 300  # every request answered


def test_dispatcher_aging_bounds_starved_wait():
    """A weight-1 tenant flooded out by a weight-20 neighbor still sees
    its requests served: aging picks over-age heads first, so the
    starved tenant's worst wait stays near the aging bound + one batch,
    not the whole backlog."""
    from cloudberry_tpu.sched import Dispatcher, TenantScheduler as TS

    s = _point_session(**{
        "sched.enabled": True, "sched.tick_s": 0.001,
        "sched.max_batch": 8, "sched.max_queue": 4096})
    s.sql("select k, v from pts where k = 1")
    tcfg = TenancyConfig(
        enabled=True, aging_s=0.05,
        tenants=(TenantSpec("heavy", weight=20, max_queue=40_000),
                 TenantSpec("starved", weight=1, max_queue=100)))
    sched = TS(tcfg)
    d = Dispatcher(s, tenancy=sched)
    for i in range(20_000):
        d.submit_nowait(f"select k, v from pts where k = {i % 2000}",
                        tenant="heavy", on_done=None)
    waits = []
    lock = threading.Lock()

    def _rec(t0):
        def f(r):
            with lock:
                waits.append(time.monotonic() - t0)
        return f

    d.start()
    time.sleep(0.05)
    for i in range(5):
        d.submit_nowait(f"select k, v from pts where k = {15_000 + i}",
                        tenant="starved", on_done=_rec(time.monotonic()))
    end = time.monotonic() + 120
    while time.monotonic() < end:
        with lock:
            if len(waits) == 5:
                break
        time.sleep(0.01)
    try:
        assert len(waits) == 5
        snap = sched.snapshot()
        # served long before the 20k-deep heavy backlog drained: the
        # starved tenant's worst wait is bounded by the DWRR round +
        # aging channel, not by its neighbor's queue depth
        assert snap["heavy"]["queued"] > 0, \
            "backlog drained too fast to observe starvation"
        assert max(waits) < 5.0, waits
        assert snap["starved"]["wait_max_ms"] < 5000.0
    finally:
        d.stop()


# ---------------------------------------------------- wire-level pieces


def test_server_tenant_backpressure_and_client_retry():
    """ISSUE-7 satellite: a saturated tenant's reads fail with the
    retryable TenantQueueFull and a retry_reads client eventually
    succeeds once the queue drains."""
    from cloudberry_tpu.serve import Client, Server, ServerError

    s = _point_session(**{
        "tenancy.enabled": True,
        "tenancy.slot_wait_s": 0.02,
        "tenancy.tenants": (
            TenantSpec("small", weight=1, max_concurrency=1,
                       max_queue=1),)})
    with Server(session=s) as srv:
        # saturate the tenant deterministically: hold its single slot
        # via the server's own scheduler, then observe the wire refusal
        ts = srv.tenancy
        with ts.slot("small"):
            with pytest.raises(TenantQueueFull):
                with ts.slot("small", wait_s=0.01):
                    pass
            # wire-level: the refusal reaches the client as retryable
            with Client(srv.host, srv.port, tenant="small") as c:
                with pytest.raises(ServerError) as ei:
                    c.sql("select count(*) as n from pts "
                          "group by k order by n limit 1")
                assert ei.value.etype == "TenantQueueFull"
                assert ei.value.retryable
        # slot free now: a retry_reads client gets through
        with Client(srv.host, srv.port, tenant="small",
                    retry_reads=True, max_retries=5,
                    backoff_s=0.02) as c:
            out = c.sql("select count(*) as n from pts "
                        "group by k order by n limit 1")
            assert out["rowcount"] == 1


def test_serve_bench_tenants_smoke():
    """CPU smoke of the ISSUE-7 bench mode: the multiplexed driver runs
    declared tenants through the event-loop core and the CSV rows carry
    the per-tenant QPS / p50 / p99 / queue-depth / fairness columns."""
    import tools.serve_bench as SB

    tenants = SB.parse_tenantspec("gold:3,silver:1", 24)
    r = SB.run_mode("batched", "point", clients=24, duration_s=1.5,
                    rows=20_000, tick_s=0.002, max_batch=8,
                    tenants=tenants)
    assert r["requests"] > 0
    assert len(SB.csv_row(r).split(",")) == len(SB.CSV_HEADER.split(","))
    per = {t["tenant"]: t for t in r["_tenants"]}
    assert set(per) == {"gold", "silver"}
    for row in per.values():
        assert row["tenant_qps"] > 0
        assert len(SB.csv_row(row).split(",")) == \
            len(SB.CSV_HEADER.split(","))
    # saturated 3:1 weights: gold at least keeps ahead (the strict ±15%
    # ratio pin lives in test_dispatcher_fairness_3_to_1_under_saturation
    # where saturation is constructed, not load-dependent)
    assert per["gold"]["tenant_qps"] >= per["silver"]["tenant_qps"]
    assert 0.0 < r["fairness_index"] <= 1.0


def test_meta_tenants_over_the_wire():
    from cloudberry_tpu.serve import Client, Server

    s = _point_session(**{
        "tenancy.enabled": True,
        "tenancy.tenants": (TenantSpec("gold", weight=3),)})
    with Server(session=s) as srv:
        with Client(srv.host, srv.port, tenant="gold") as c:
            c.sql("select k, v from pts where k = 42")
            t = c.meta("tenants")
            assert t["enabled"]
            assert t["groups"]["gold"]["weight"] == 3
            assert 0.0 < t["fairness_index"] <= 1.0
