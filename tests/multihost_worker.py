"""Worker process for the multi-host (DCN-analog) test.

Each worker is one "host": it joins the cluster via
``mesh.init_distributed`` (CBTPU_* env), owns 4 local virtual devices, and
runs the SAME statements over the 8-segment mesh that now spans both
processes — collectives cross the process boundary the way the
reference's interconnect crosses machines (ic_udpifc.c). Results print as
JSON for the parent to compare across hosts and against the single-host
oracle.

The spawner provides the per-host env (JAX_PLATFORMS=cpu, XLA_FLAGS with
4 local devices, CBTPU_* cluster coordinates) — this module must NOT
mutate os.environ, because the test imports it for QUERIES/load."""

import json
import sys

import jax

jax.config.update("jax_platforms", "cpu")

from cloudberry_tpu.parallel.mesh import (init_distributed,  # noqa: E402
                                          mesh_topology)

init_distributed()

import numpy as np  # noqa: E402

import cloudberry_tpu as cb  # noqa: E402
from cloudberry_tpu.config import get_config  # noqa: E402

QUERIES = [
    # redistribute + two-stage agg + gathered sort
    ("SELECT g, sum(v) AS sv, count(*) AS c FROM fact "
     "JOIN dim ON fact.k = dim.k GROUP BY g ORDER BY g"),
    # broadcast join (small build) + filter
    ("SELECT count(*) AS n FROM fact JOIN dim ON fact.k = dim.k "
     "WHERE g < 3"),
    # top-N pushdown through the gather motion
    ("SELECT k, v FROM fact ORDER BY v DESC, k LIMIT 7"),
]


def load(session):
    rng = np.random.default_rng(11)  # identical on every host
    session.sql("CREATE TABLE dim (k BIGINT, g BIGINT) DISTRIBUTED BY (k)")
    session.sql(
        "CREATE TABLE fact (k BIGINT, v BIGINT) DISTRIBUTED BY (k)")
    session.catalog.table("dim").set_data(
        {"k": np.arange(400), "g": np.arange(400) % 6})
    session.catalog.table("fact").set_data(
        {"k": rng.integers(0, 400, 20_000),
         "v": rng.integers(0, 1000, 20_000)})


def main():
    topo = mesh_topology(8)
    assert topo["n_hosts"] == 2, f"expected 2 hosts, got {topo}"
    session = cb.Session(get_config().with_overrides(n_segments=8))
    load(session)
    results = []
    for q in QUERIES:
        df = session.sql(q).to_pandas()
        results.append({c: df[c].tolist() for c in df.columns})
    # the same statements over the TWO-LEVEL motion path (hierarchical
    # redistribute/gather/broadcast + the host-combined agg merge) on
    # the REAL 2-process cluster — collectives genuinely cross the
    # process boundary here, and results must be bit-identical to flat
    hier = cb.Session(get_config().with_overrides(**{
        "n_segments": 8,
        "interconnect.hierarchical": "on",
    }))
    load(hier)
    hier_results = []
    for q, flat_res in zip(QUERIES, results):
        df = hier.sql(q).to_pandas()
        got = {c: df[c].tolist() for c in df.columns}
        assert got == flat_res, \
            f"hierarchical differs from flat for {q!r}"
        hier_results.append(got)
    print("RESULT " + json.dumps(
        {"host": topo["this_host"], "results": results,
         "hier_results": hier_results}), flush=True)


if __name__ == "__main__":
    sys.exit(main())
