"""Foreign data wrappers (storage/fdw.py) — the FDW / CustomScan hook.

A FOREIGN TABLE re-fetches from its server per referencing statement, so
queries track the source; the sqlite built-in covers the
contrib-wrapper role and register_fdw() is the custom-provider hook.
"""

import sqlite3

import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.plan.binder import BindError
from cloudberry_tpu.storage.fdw import FdwError, register_fdw


@pytest.fixture
def db(tmp_path):
    path = str(tmp_path / "src.db")
    con = sqlite3.connect(path)
    con.execute("create table emp (id integer, name text, sal real, "
                "hired text)")
    con.executemany("insert into emp values (?,?,?,?)", [
        (1, "ann", 100.5, "2024-01-02"),
        (2, "bob", 90.0, "2023-06-30"),
        (3, None, None, "2022-12-01")])
    con.commit()
    con.close()
    return path


def test_sqlite_foreign_table_scans_and_joins(db):
    s = cb.Session()
    s.sql(f"""create foreign table femp
              (id bigint, name text, sal double, hired date)
              server sqlite options (database '{db}', table 'emp')""")
    df = s.sql("select id, name, sal from femp order by id").to_pandas()
    assert df["id"].tolist() == [1, 2, 3]
    assert df["name"].tolist()[:2] == ["ann", "bob"]
    assert df["name"][2] is None or df["name"].isna()[2]  # NULL survives
    # joins against native tables work like any table
    s.sql("create table bonus (id bigint, b bigint)")
    s.sql("insert into bonus values (1, 10), (3, 30)")
    df = s.sql("select f.id, b.b from femp f join bonus b on f.id = b.id "
               "order by f.id").to_pandas()
    assert df.values.tolist() == [[1, 10], [3, 30]]
    # date typing round-trips
    df = s.sql("select id from femp where hired >= date '2023-01-01' "
               "order by id").to_pandas()
    assert df["id"].tolist() == [1, 2]


def test_foreign_table_tracks_source(db):
    s = cb.Session()
    s.sql(f"create foreign table ft (id bigint, name text, sal double, "
          f"hired date) server sqlite options (database '{db}', "
          f"table 'emp')")
    assert s.sql("select count(*) from ft").to_pandas().iloc[0, 0] == 3
    con = sqlite3.connect(db)
    con.execute("insert into emp values (4, 'dee', 70.0, '2025-01-01')")
    con.commit()
    con.close()
    # next statement re-fetches: the source's new row is visible
    assert s.sql("select count(*) from ft").to_pandas().iloc[0, 0] == 4


def test_foreign_query_option(db):
    s = cb.Session()
    s.sql(f"""create foreign table top (name text) server sqlite
              options (database '{db}',
                       query 'select name from emp where sal > 95')""")
    assert s.sql("select name from top").to_pandas()["name"].tolist() \
        == ["ann"]


def test_unknown_server_and_bad_source(db, tmp_path):
    s = cb.Session()
    with pytest.raises(BindError, match="unknown foreign server"):
        s.sql("create foreign table x (a int) server nope")
    s.sql(f"create foreign table y (a int) server sqlite "
          f"options (database '{tmp_path}/missing.db', table 'emp')")
    with pytest.raises(FdwError):
        s.sql("select * from y")


def test_register_custom_provider():
    """register_fdw is the CustomScan-style hook: any callable becomes a
    scannable relation."""
    register_fdw("range", lambda opts, schema:
                 ((i, i * i) for i in range(int(opts.get("n", "5")))))
    s = cb.Session()
    s.sql("create foreign table sq (i bigint, isq bigint) server range "
          "options (n '4')")
    df = s.sql("select sum(isq) as t from sq where i > 0").to_pandas()
    assert df["t"][0] == 1 + 4 + 9
