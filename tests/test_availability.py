"""Service availability + governance long tail (round 4).

- Hot standby (hot_standby / mirroring analog): a second read-only server
  over the shared store serves fresh reads (epoch sync = the replication
  stream) and refuses writes; "promotion" is restarting without the flag.
- Login monitor: token auth with address lockout after repeated failures.
- Disk quota (diskquota extension analog): writes refused once store
  usage reaches storage.quota_bytes; deletes/drops reclaim.
"""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config
from cloudberry_tpu.serve.client import Client, ServerError
from cloudberry_tpu.serve.server import Server


def _cfg(tmp_path, **ov):
    over = {"storage.root": str(tmp_path)}
    over.update(ov)
    return get_config().with_overrides(**over)


# ------------------------------------------------------------ hot standby


def test_hot_standby_serves_fresh_reads_refuses_writes(tmp_path):
    cfg = _cfg(tmp_path)
    primary = cb.Session(cfg)
    primary.sql("create table ht (x bigint)")
    primary.sql("insert into ht values (1),(2)")
    with Server(config=cfg, port=0, read_only=True) as standby:
        with Client(standby.host, standby.port) as c:
            assert c.rows("select count(*) from ht") == [[2]]
            # the primary commits; the standby's next read sees it
            # (snapshot manifests are the replication stream)
            primary.sql("insert into ht values (3)")
            assert c.rows("select count(*) from ht") == [[3]]
            with pytest.raises(ServerError, match="read-only standby"):
                c.sql("insert into ht values (99)")
            with pytest.raises(ServerError, match="read-only standby"):
                c.sql("create table nope (x int)")
            with pytest.raises(ServerError, match="read-only standby"):
                c.sql("begin")
    # nothing leaked through
    assert primary.sql("select count(*) from ht").to_pandas().iloc[0, 0] == 3


def test_standby_refuses_sequence_allocation(tmp_path):
    """`select nextval(...)` LOOKS like a read but durably advances the
    sequence — the standby must classify it as a write (the shared
    sql/classify.py gate)."""
    cfg = _cfg(tmp_path)
    primary = cb.Session(cfg)
    primary.sql("create sequence sq")
    with Server(config=cfg, port=0, read_only=True) as standby:
        with Client(standby.host, standby.port) as c:
            with pytest.raises(ServerError, match="read-only standby"):
                c.sql("select nextval('sq')")
            # parenthesized set ops are reads and pass the gate
            assert c.rows("(select 1) union (select 2)")


def test_promotion_is_restart_without_flag(tmp_path):
    cfg = _cfg(tmp_path)
    boot = cb.Session(cfg)
    boot.sql("create table pt (x bigint)")
    with Server(config=cfg, port=0, read_only=True) as standby:
        with Client(standby.host, standby.port) as c:
            with pytest.raises(ServerError):
                c.sql("insert into pt values (1)")
    with Server(config=cfg, port=0) as promoted:
        with Client(promoted.host, promoted.port) as c:
            c.sql("insert into pt values (1)")
            assert c.rows("select count(*) from pt") == [[1]]


# ---------------------------------------------------------- login monitor


def test_auth_required_and_lockout(tmp_path):
    cfg = _cfg(tmp_path)
    cb.Session(cfg).sql("create table au (x bigint)")
    with Server(config=cfg, port=0, auth_token="sekret",
                max_login_failures=2, lockout_s=30.0) as srv:
        # no auth -> refused, connection closed
        with pytest.raises(ServerError, match="authentication required"):
            Client(srv.host, srv.port).sql("select 1")
        # wrong token (failure 2 of 2 -> lockout armed)
        with pytest.raises(ServerError, match="authentication failed"):
            Client(srv.host, srv.port, token="wrong")
        # locked out now — even the RIGHT token is refused
        with pytest.raises(ServerError, match="locked"):
            Client(srv.host, srv.port, token="sekret")


def test_auth_success_path(tmp_path):
    cfg = _cfg(tmp_path)
    boot = cb.Session(cfg)
    boot.sql("create table av (x bigint)")
    boot.sql("insert into av values (7)")
    with Server(config=cfg, port=0, auth_token="sekret") as srv:
        with Client(srv.host, srv.port, token="sekret") as c:
            assert c.rows("select x from av") == [[7]]


# ------------------------------------------------------------- disk quota


def test_disk_quota_blocks_writes_delete_reclaims(tmp_path):
    s = cb.Session(_cfg(tmp_path, **{"storage.quota_bytes": 20_000}))
    s.sql("create table q (x bigint)")
    # incompressible payload: random full-range int64 defeats the
    # delta-varint/zstd encoders, pushing the store past the 20kB quota
    rng = np.random.default_rng(5)
    s.catalog.table("q").set_data(
        {"x": rng.integers(-(2**62), 2**62, 8192).astype(np.int64)})
    from cloudberry_tpu.storage.table_store import QuotaError

    assert s.store.disk_usage(fresh=True) >= 20_000
    with pytest.raises(QuotaError, match="disk quota exceeded"):
        s.sql("insert into q values (1)")
    # reads still fine, and the refused INSERT did NOT land in RAM either
    # (set_data restores on persist failure — no RAM/disk divergence)
    assert s.sql("select count(*) from q").to_pandas().iloc[0, 0] == 8192
    # DROP reclaims; writes work again
    s.sql("drop table q")
    s.sql("create table q2 (x bigint)")
    s.sql("insert into q2 values (1)")
    assert s.sql("select count(*) from q2").to_pandas().iloc[0, 0] == 1


def test_quota_zero_is_unlimited(tmp_path):
    s = cb.Session(_cfg(tmp_path))
    s.sql("create table uq (x bigint)")
    s.catalog.table("uq").set_data({"x": np.arange(100_000,
                                                   dtype=np.int64)})
    s.sql("insert into uq values (1)")  # no quota, no refusal
