"""Durable multi-session transactions — single-writer OCC over snapshot
manifests (VERDICT #9; reference role: cdbtm.c 2PC + distributed snapshots,
re-expressed as first-committer-wins over atomic manifest versions)."""

import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.session import SerializationError


def _cfg(tmp_path):
    return Config().with_overrides(**{"storage.root": str(tmp_path / "s")})


def _mk(tmp_path):
    s = cb.Session(_cfg(tmp_path))
    s.sql("create table t (a bigint, v bigint) distributed by (a)")
    s.sql("insert into t values (1, 10), (2, 20)")
    return s


def test_commit_is_durable_across_crash(tmp_path):
    """Crash after COMMIT = abandon the session; a fresh session sees the
    committed state."""
    a = _mk(tmp_path)
    a.sql("begin")
    a.sql("insert into t values (3, 30)")
    a.sql("commit")
    del a  # "crash"
    b = cb.Session(_cfg(tmp_path))
    assert b.sql("select count(*) as n from t").to_pandas().n[0] == 3


def test_crash_during_commit_preserves_old_snapshot(tmp_path):
    """A crash in the window after the manifest is written but before the
    CURRENT pointer swaps must leave the previous snapshot intact."""
    from cloudberry_tpu.utils import faultinject

    a = _mk(tmp_path)
    a.sql("begin")
    a.sql("insert into t values (3, 30)")
    faultinject.inject_fault("storage_commit_before_current", "skip")
    try:
        a.sql("commit")
    finally:
        faultinject.reset_fault("storage_commit_before_current")
    b = cb.Session(_cfg(tmp_path))
    assert b.sql("select count(*) as n from t").to_pandas().n[0] == 2


def test_concurrent_writer_conflict(tmp_path):
    """First committer wins for REWRITES: a COMMIT whose UPDATE/DELETE
    target moved past the BEGIN snapshot fails with a serialization error
    and rolls back. (Append-only transactions MERGE instead — see
    test_occ_merge.py.)"""
    a = _mk(tmp_path)
    b = cb.Session(_cfg(tmp_path))
    a.sql("begin")
    a.sql("update t set v = v + 1 where a = 1")
    # B commits first (autocommit)
    b.sql("insert into t values (200, 2)")
    with pytest.raises(SerializationError, match="another\\s+session"):
        a.sql("commit")
    # A rolled back; next statement syncs to B's committed state
    out = a.sql("select a from t order by a").to_pandas()
    assert out.a.tolist() == [1, 2, 200]
    c = cb.Session(_cfg(tmp_path))
    assert c.sql("select a from t order by a").to_pandas() \
        .a.tolist() == [1, 2, 200]


def test_non_conflicting_tables_commit_fine(tmp_path):
    a = _mk(tmp_path)
    b = cb.Session(_cfg(tmp_path))
    b.sql("create table u (x bigint) distributed by (x)")
    a.sql("begin")
    a.sql("insert into t values (100, 1)")
    b.sql("insert into u values (7)")  # different table: no conflict
    a.sql("commit")
    c = cb.Session(_cfg(tmp_path))
    assert c.sql("select count(*) as n from t").to_pandas().n[0] == 3
    assert c.sql("select count(*) as n from u").to_pandas().n[0] == 1


def test_cross_session_visibility(tmp_path):
    a = _mk(tmp_path)
    b = cb.Session(_cfg(tmp_path))
    b.sql("insert into t values (3, 30)")
    assert a.sql("select count(*) as n from t").to_pandas().n[0] == 3
    b.sql("create table fresh (x bigint) distributed by (x)")
    assert a.sql("select count(*) as n from fresh").to_pandas().n[0] == 0
    b.sql("drop table fresh")
    with pytest.raises(Exception):
        a.sql("select * from fresh")


def test_analyze_then_drop_in_txn_no_ghost(tmp_path):
    """Regression: ANALYZE then DROP in one txn must not resurrect the
    table as a ghost manifest at COMMIT."""
    a = _mk(tmp_path)
    a.sql("begin")
    a.sql("analyze t")
    a.sql("drop table t")
    a.sql("commit")
    assert a.store.table_names() == []
    b = cb.Session(_cfg(tmp_path))
    assert "t" not in b.catalog.tables


def test_snapshot_isolation_within_txn(tmp_path):
    """Reads inside BEGIN..COMMIT pin the store versions current at BEGIN:
    another session's commits stay invisible until the txn ends."""
    a = _mk(tmp_path)
    b = cb.Session(_cfg(tmp_path))
    a.sql("begin")
    assert a.sql("select count(*) as n from t").to_pandas().n[0] == 2
    b.sql("insert into t values (3, 30)")
    assert a.sql("select count(*) as n from t").to_pandas().n[0] == 2
    a.sql("commit")  # read-only txn: nothing written, no conflict
    assert a.sql("select count(*) as n from t").to_pandas().n[0] == 3
