"""Window long tail — lead/lag/ntile/first_value/last_value — and scalar
subquery row-count semantics (0 rows → NULL, >1 rows → error).

The reference executes these in nodeWindowAgg.c with per-call frame logic;
here positional window functions are gathers inside the sorted partition
(exec/executor.py window()), with '<func>@mask' companion calls carrying
the per-row null mask, and scalar-subquery presence is a mode="exists"
SubqueryScalar validity term (plan/binder.py _bind_uncorrelated_scalar).
Both single-segment and 8-segment modes run (windows redistribute on
PARTITION BY keys).
"""

import numpy as np
import pandas as pd
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.exec.executor import ExecError
from cloudberry_tpu.plan.binder import BindError


def _mk(nseg=1):
    s = cb.Session(Config(n_segments=nseg)) if nseg > 1 else cb.Session()
    s.sql("create table w (g text, o int, v int, s text) "
          "distributed by (o)")
    s.sql("insert into w values "
          "('a', 1, 10, 'x'), ('a', 2, null, 'y'), ('a', 3, 30, null), "
          "('b', 1, 100, 'p'), ('b', 2, 200, 'q'), "
          "('c', 1, null, 'z')")
    return s


@pytest.fixture(scope="module", params=[1, 8], ids=["single", "dist8"])
def s(request):
    return _mk(request.param)


def _norm(vals):
    return [None if (v is None or (isinstance(v, float) and np.isnan(v))
                     or v is pd.NA) else v for v in vals]


def col(s, q, name=None):
    df = s.sql(q).to_pandas()
    return _norm(df[name if name else df.columns[0]].tolist())


# ------------------------------------------------------------- lead / lag


def test_lead_basic(s):
    out = col(s, "select lead(o) over (partition by g order by o) as x "
                 "from w order by g, o", "x")
    # past the partition end -> NULL
    assert out == [2, 3, None, 2, None, None]


def test_lag_basic(s):
    out = col(s, "select lag(o) over (partition by g order by o) as x "
                 "from w order by g, o", "x")
    assert out == [None, 1, 2, None, 1, None]


def test_lead_offset_and_default(s):
    out = col(s, "select lead(o, 2) over (partition by g order by o) as x "
                 "from w order by g, o", "x")
    assert out == [3, None, None, None, None, None]
    out = col(s, "select lead(o, 2, -1) over (partition by g order by o) "
                 "as x from w order by g, o", "x")
    assert out == [3, -1, -1, -1, -1, -1]
    out = col(s, "select lag(o, 1, 0) over (partition by g order by o) "
                 "as x from w order by g, o", "x")
    assert out == [0, 1, 2, 0, 1, 0]


def test_lead_lag_nullable_arg(s):
    # v holds NULLs: a present source row with NULL value stays NULL,
    # and an out-of-range source is NULL regardless of default absence
    out = col(s, "select lag(v) over (partition by g order by o) as x "
                 "from w order by g, o", "x")
    assert out == [None, 10, None, None, 100, None]
    # with a default: out-of-range takes the default, NULL source stays NULL
    out = col(s, "select lag(v, 1, -5) over (partition by g order by o) "
                 "as x from w order by g, o", "x")
    assert out == [-5, 10, None, -5, 100, -5]


def test_lead_strings(s):
    # dictionary-coded argument: output carries the dictionary
    out = col(s, "select lead(s) over (partition by g order by o) as x "
                 "from w order by g, o", "x")
    assert out == ["y", None, None, "q", None, None]


def test_lag_zero_offset(s):
    out = col(s, "select lag(o, 0) over (partition by g order by o) as x "
                 "from w order by g, o", "x")
    assert out == [1, 2, 3, 1, 2, 1]


def test_lead_lag_string_default(s):
    # the default encodes into the argument's dictionary (append-only)
    out = col(s, "select lead(s, 1, 'none') over (partition by g "
                 "order by o) as x from w order by g, o", "x")
    assert out == ["y", None, "none", "q", "none", "none"]
    out = col(s, "select lag(s, 2, '<pad>') over (partition by g "
                 "order by o) as x from w order by g, o", "x")
    assert out == ["<pad>", "<pad>", "x", "<pad>", "<pad>", "<pad>"]
    with pytest.raises(BindError, match="must be a string"):
        s.sql("select lead(s, 1, 42) over (order by o) from w")


def test_lead_requires_constant_offset(s):
    with pytest.raises(BindError):
        s.sql("select lead(o, o) over (order by o) from w")


def test_lead_explicit_null_default(s):
    # an explicit NULL default is the no-default case: out-of-range -> NULL
    out = col(s, "select lead(o, 1, null) over (partition by g order by o) "
                 "as x from w order by g, o", "x")
    assert out == [2, 3, None, 2, None, None]


def test_first_value_arity_checked(s):
    with pytest.raises(BindError):
        s.sql("select first_value(o, 2) over (order by o) from w")
    with pytest.raises(BindError):
        s.sql("select last_value(o, 1, 2) over (order by o) from w")


# ---------------------------------------------------------------- ntile


def test_ntile(s):
    # 6 rows, 4 buckets: sizes 2,2,1,1 (larger buckets first)
    out = col(s, "select ntile(4) over (order by g, o) as x "
                 "from w order by g, o", "x")
    assert out == [1, 1, 2, 2, 3, 4]


def test_ntile_more_buckets_than_rows(s):
    out = col(s, "select ntile(10) over (partition by g order by o) as x "
                 "from w order by g, o", "x")
    assert out == [1, 2, 3, 1, 2, 1]


def test_ntile_requires_positive_constant(s):
    with pytest.raises(BindError):
        s.sql("select ntile(0) over (order by o) from w")
    with pytest.raises(BindError):
        s.sql("select ntile(o) over (order by o) from w")


# ------------------------------------------------- first_value / last_value


def test_first_value(s):
    out = col(s, "select first_value(o) over (partition by g order by o) "
                 "as x from w order by g, o", "x")
    assert out == [1, 1, 1, 1, 1, 1]
    # nullable arg: partition 'c' has first v NULL
    out = col(s, "select first_value(v) over (partition by g order by o) "
                 "as x from w order by g, o", "x")
    assert out == [10, 10, 10, 100, 100, None]


def test_last_value_default_frame(s):
    # the SQL gotcha: default frame ends at the CURRENT peer group, so
    # last_value tracks the current row, not the partition tail
    out = col(s, "select last_value(o) over (partition by g order by o) "
                 "as x from w order by g, o", "x")
    assert out == [1, 2, 3, 1, 2, 1]
    # without ORDER BY the frame is the whole partition; which row is
    # "last" is unspecified (PG too) — but it must be one row of the
    # partition and the same for every row of the partition
    df = s.sql("select g, o, last_value(o) over (partition by g) as x "
               "from w order by g, o").to_pandas()
    for g, grp in df.groupby("g"):
        assert grp["x"].nunique() == 1
        assert grp["x"].iloc[0] in set(grp["o"])


def test_last_value_nullable(s):
    # last_value over nullable v: current row's v (peers: none here)
    out = col(s, "select last_value(v) over (partition by g order by o) "
                 "as x from w order by g, o", "x")
    assert out == [10, None, 30, 100, 200, None]


def test_first_value_strings(s):
    out = col(s, "select first_value(s) over (partition by g order by o) "
                 "as x from w order by g, o", "x")
    assert out == ["x", "x", "x", "p", "p", "z"]


def test_window_over_aggregate(s):
    """Windows OVER grouped-aggregate outputs — both the q98 ratio shape
    (partition by) and the rank-by-aggregate shape (OVER(ORDER BY
    sum(x))), whose inner aggregate folds via OrderItem recursion."""
    df = s.sql("""select g, sum(o) as t,
                  sum(sum(o)) over () as grand,
                  rank() over (order by sum(o) desc) as rk
                  from w group by g order by g""").to_pandas()
    assert df["t"].tolist() == [6, 3, 1]
    assert df["grand"].tolist() == [10, 10, 10]
    assert df["rk"].tolist() == [1, 2, 3]
    # no GROUP BY: the aggregate lives ONLY inside OVER(ORDER BY ...) —
    # _has_agg must still route through the aggregation path
    df = s.sql("select rank() over (order by sum(o)) as rk "
               "from w").to_pandas()
    assert df["rk"].tolist() == [1]


def test_positional_mixed_with_aggregates(s):
    df = s.sql("""select g, o,
                  lead(o) over (partition by g order by o) as nxt,
                  sum(o) over (partition by g order by o) as run,
                  ntile(2) over (partition by g order by o) as nt
                  from w order by g, o""").to_pandas()
    assert _norm(df["nxt"].tolist()) == [2, 3, None, 2, None, None]
    assert df["run"].tolist() == [1, 3, 6, 1, 3, 1]
    assert df["nt"].tolist() == [1, 1, 2, 1, 2, 1]


# -------------------------------------------------------- explicit frames


def test_rows_frame_moving_sum_avg(s):
    out = col(s, "select sum(o) over (partition by g order by o "
                 "rows between 1 preceding and current row) as x "
                 "from w order by g, o", "x")
    assert out == [1, 3, 5, 1, 3, 1]
    out = col(s, "select avg(o) over (partition by g order by o "
                 "rows between 1 preceding and 1 following) as x "
                 "from w order by g, o", "x")
    assert out == [1.5, 2.0, 2.5, 1.5, 1.5, 1.0]


def test_rows_frame_min_max(s):
    out = col(s, "select max(o) over (partition by g order by o "
                 "rows between 1 preceding and current row) as x "
                 "from w order by g, o", "x")
    assert out == [1, 2, 3, 1, 2, 1]
    out = col(s, "select min(o) over (partition by g order by o "
                 "rows between current row and 1 following) as x "
                 "from w order by g, o", "x")
    assert out == [1, 2, 3, 1, 2, 1]
    # sliding max over values that DECREASE then increase: v column
    out = col(s, "select max(v) over (partition by g order by o "
                 "rows between 1 preceding and 1 following) as x "
                 "from w order by g, o", "x")
    # partition a: v = 10, NULL, 30 -> windows: (10,N)=10 (N,30 incl
    # 10)=30, (N,30)=30; b: (100,200)=200 twice; c: single NULL -> NULL
    assert out == [10, 30, 30, 200, 200, None]


def test_rows_frame_can_be_empty(s):
    # frame entirely BEFORE the first row of the partition -> NULL (sum)
    out = col(s, "select sum(o) over (partition by g order by o "
                 "rows between 2 preceding and 1 preceding) as x "
                 "from w order by g, o", "x")
    assert out == [None, 1, 3, None, 1, None]
    # count over an empty frame is 0, not NULL
    out = col(s, "select count(o) over (partition by g order by o "
                 "rows between 2 preceding and 1 preceding) as x "
                 "from w order by g, o", "x")
    assert out == [0, 1, 2, 0, 1, 0]


def test_rows_frame_first_last_value(s):
    out = col(s, "select last_value(o) over (partition by g order by o "
                 "rows between unbounded preceding and unbounded "
                 "following) as x from w order by g, o", "x")
    assert out == [3, 3, 3, 2, 2, 1]  # the classic fix for last_value
    out = col(s, "select first_value(o) over (partition by g order by o "
                 "rows between 1 following and 2 following) as x "
                 "from w order by g, o", "x")
    assert out == [2, 3, None, 2, None, None]


def test_range_frame_whole_partition(s):
    out = col(s, "select max(o) over (partition by g order by o "
                 "range between unbounded preceding and unbounded "
                 "following) as x from w order by g, o", "x")
    assert out == [3, 3, 3, 2, 2, 1]
    # the default-equivalent RANGE spelling keeps peer semantics
    out = col(s, "select sum(o) over (partition by g order by o "
                 "range between unbounded preceding and current row) "
                 "as x from w order by g, o", "x")
    assert out == [1, 3, 6, 1, 3, 1]


def test_frame_bound_validation(s):
    from cloudberry_tpu.sql.parser import ParseError

    with pytest.raises(BindError, match="start is after"):
        s.sql("select sum(o) over (order by o rows between 1 following "
              "and 1 preceding) from w")
    with pytest.raises(BindError, match="start is after"):
        s.sql("select sum(o) over (order by o range between 1 following "
              "and 1 preceding) from w")
    # negative offsets are invalid SQL, never a silent direction flip
    with pytest.raises(ParseError, match="must not be negative"):
        s.sql("select sum(o) over (order by o rows between -2 following "
              "and current row) from w")
    with pytest.raises(BindError, match="ROWS frame offsets"):
        s.sql("select sum(o) over (order by o rows between 1.5 preceding "
              "and current row) from w")
    with pytest.raises(BindError, match="exactly one ORDER BY"):
        s.sql("select sum(o) over (order by g, o range between "
              "1 preceding and current row) from w")
    with pytest.raises(BindError, match="exactly one ORDER BY"):
        s.sql("select sum(o) over (range between 1 preceding "
              "and current row) from w")
    with pytest.raises(BindError, match="numeric or date"):
        s.sql("select sum(o) over (order by g range between 1 preceding "
              "and current row) from w")
    with pytest.raises(BindError, match="must be an integer"):
        s.sql("select sum(o) over (order by o range between "
              "0.5 preceding and current row) from w")
    # float() parses 'nan'/'inf' — as offsets they'd silently break
    # every comparison, so they must be rejected at parse time
    with pytest.raises(ParseError, match="expected a number"):
        s.sql("select sum(o) over (order by o range between "
              "nan preceding and current row) from w")


# --------------------------------------------- RANGE offset frames


def _mk_range(nseg=1):
    s = cb.Session(Config(n_segments=nseg)) if nseg > 1 else cb.Session()
    s.sql("create table rw (g text, k int, v int) distributed by (v)")
    # duplicate keys (peers), gaps, and NULL keys in one partition
    s.sql("insert into rw values "
          "('a', 1, 1), ('a', 2, 2), ('a', 2, 3), ('a', 5, 4), "
          "('b', 10, 5), ('b', 11, 6), "
          "('c', 3, 9), ('c', null, 7), ('c', null, 8)")
    s.sql("create table rf (k double, v int) distributed by (v)")
    s.sql("insert into rf values (0.5, 1), (1.0, 2), (1.4, 3), (3.0, 4)")
    s.sql("create table rd (k decimal(8,2), v int) distributed by (v)")
    s.sql("insert into rd values (1.00, 1), (1.25, 2), (1.50, 3), "
          "(3.00, 4)")
    return s


@pytest.fixture(scope="module", params=[1, 8], ids=["single", "dist8"])
def rs(request):
    return _mk_range(request.param)


def test_range_offset_sum(rs):
    out = col(rs, "select sum(v) over (partition by g order by k "
                  "range between 1 preceding and 1 following) as x "
                  "from rw order by g, k", "x")
    # a: k=1 sees keys 0..2 -> 1+2+3; k=2 (both peers) sees 1..3 -> 6;
    # k=5 sees only itself. c: NULL keys frame exactly their peer group.
    assert out == [6, 6, 6, 4, 11, 11, 9, 15, 15]


def test_range_offset_desc(rs):
    # DESC: PRECEDING means larger keys
    out = col(rs, "select sum(v) over (partition by g order by k desc "
                  "range between 1 preceding and current row) as x "
                  "from rw order by g, k", "x")
    assert out == [6, 5, 5, 4, 11, 6, 9, 15, 15]


def test_range_offset_can_be_empty(rs):
    out = col(rs, "select sum(v) over (partition by g order by k "
                  "range between 3 preceding and 2 preceding) as x "
                  "from rw order by g, k", "x")
    # only a:k=5 has keys in [k-3, k-2] (the k=2 peers); NULL-key rows
    # still frame their peer group (NULL ± offset is NULL)
    assert out == [None, None, None, 5, None, None, None, 15, 15]
    out = col(rs, "select count(v) over (partition by g order by k "
                  "range between 3 preceding and 2 preceding) as x "
                  "from rw order by g, k", "x")
    assert out == [0, 0, 0, 2, 0, 0, 0, 2, 2]


def test_range_offset_min_max(rs):
    out = col(rs, "select max(v) over (partition by g order by k "
                  "range between 1 preceding and 1 following) as x "
                  "from rw order by g, k", "x")
    assert out == [3, 3, 3, 4, 6, 6, 9, 8, 8]
    out = col(rs, "select min(v) over (partition by g order by k "
                  "range between 1 preceding and current row) as x "
                  "from rw order by g, k", "x")
    # CURRENT ROW as frame end = last peer (RANGE keeps peer semantics)
    assert out == [1, 1, 1, 4, 5, 5, 9, 7, 7]


def test_range_offset_first_last_value(rs):
    out = col(rs, "select first_value(v) over (partition by g order by k "
                  "range between 1 following and 2 following) as x "
                  "from rw where g = 'b' order by k", "x")
    assert out == [6, None]
    out = col(rs, "select last_value(v) over (partition by g order by k "
                  "range between current row and unbounded following) "
                  "as x from rw where g = 'b' order by k", "x")
    assert out == [6, 6]


def test_range_offset_float_key(rs):
    out = col(rs, "select sum(v) over (order by k range between "
                  "0.5 preceding and 0.5 following) as x "
                  "from rf order by k", "x")
    assert out == [3, 6, 5, 4]


def test_range_offset_decimal_key(rs):
    # the 0.25 offset scales into the DECIMAL(8,2) fixed-point domain
    out = col(rs, "select sum(v) over (order by k range between "
                  "0.25 preceding and 0.25 following) as x "
                  "from rd order by k", "x")
    assert out == [3, 6, 5, 4]
    # 0.07 * 100 is inexact in binary floats — scaling must stay exact
    out = col(rs, "select count(v) over (order by k range between "
                  "0.07 preceding and 0.07 following) as x "
                  "from rd order by k", "x")
    assert out == [1, 1, 1, 1]


def test_range_positional_shapes(rs):
    # CURRENT ROW bounds without offsets are positional peer-group
    # edges: no single-numeric-key restriction (multi-key, string keys)
    out = col(rs, "select sum(v) over (order by g, k range between "
                  "current row and unbounded following) as x "
                  "from rw order by g, k, v", "x")
    assert out == [45, 44, 44, 39, 35, 30, 24, 15, 15]
    out = col(rs, "select sum(v) over (order by g range between "
                  "current row and current row) as x "
                  "from rw order by g, k", "x")
    assert out == [10, 10, 10, 10, 11, 11, 24, 24, 24]


def test_range_offset_mixed_unbounded(rs):
    out = col(rs, "select sum(v) over (partition by g order by k "
                  "range between unbounded preceding and 1 preceding) "
                  "as x from rw order by g, k", "x")
    # unbounded start is positional (partition head); the offset end at a
    # NULL row is its last null peer — so c's NULL rows span the whole
    # partition (9+7+8), while its k=3 row has an empty frame
    assert out == [None, 1, 1, 6, None, 5, None, 24, 24]


def test_range_offset_date_interval(rs):
    rs.sql("create table rdt (dt date, v int) distributed by (v)")
    rs.sql("insert into rdt values (date '2024-01-01', 1), "
           "(date '2024-01-03', 2), (date '2024-01-04', 3), "
           "(date '2024-02-01', 4)")
    out = col(rs, "select sum(v) over (order by dt range between "
                  "interval '2' day preceding and current row) as x "
                  "from rdt order by dt", "x")
    assert out == [1, 3, 5, 4]


def test_range_offset_month_year_interval(rs):
    """Calendar RANGE offsets (timestamp.c interval_pl semantics): the
    executor shifts each row's civil date in-program with day-of-month
    clamping — Mar 31 - 1 month = Feb 28/29."""
    rs.sql("create table rmy (dt date, v int) distributed by (v)")
    rs.sql("insert into rmy values (date '2000-02-29', 1), "
           "(date '2000-03-31', 2), (date '2001-02-28', 4), "
           "(date '2001-03-01', 8), (date '2002-02-28', 16)")
    out = col(rs, "select sum(v) over (order by dt range between "
                  "interval '1' month preceding and current row) as x "
                  "from rmy order by dt", "x")
    # 2000-03-31: lo = 2000-02-29 (clamped) -> includes the leap day
    assert out == [1, 3, 4, 12, 16]
    out = col(rs, "select sum(v) over (order by dt range between "
                  "interval '1' year preceding and current row) as x "
                  "from rmy order by dt", "x")
    # 2001-02-28: lo = 2000-02-28 -> covers both 2000 rows
    assert out == [1, 3, 7, 14, 28]
    from cloudberry_tpu.plan.binder import BindError

    with pytest.raises(BindError, match="date ORDER BY"):
        rs.sql("select sum(v) over (order by v range between "
               "interval '1' month preceding and current row) from rmy")


def test_range_month_offset_oracle_random(rs):
    import calendar
    import datetime

    import pandas as pd

    rng = np.random.default_rng(31)
    base = datetime.date(1999, 6, 15)
    data = [(int(rng.integers(0, 3)),
             base + datetime.timedelta(days=int(rng.integers(0, 900))),
             int(rng.integers(1, 40))) for _ in range(300)]
    rs.sql("create table rmo (g bigint, dt date, v int) "
           "distributed by (g)")
    rs.sql("insert into rmo values " + ", ".join(
        f"({g}, date '{d}', {v})" for g, d, v in data))
    df = rs.sql("select g, dt, sum(v) over (partition by g order by dt "
                "range between interval '2' month preceding and "
                "current row) as s from rmo").to_pandas()

    def mshift(d, n):
        m = d.month - 1 + n
        y = d.year + m // 12
        m = m % 12 + 1
        return datetime.date(y, m, min(d.day,
                                       calendar.monthrange(y, m)[1]))

    exp = [(g, d, sum(vv for gg, dd, vv in data
                      if gg == g and mshift(d, -2) <= dd <= d))
           for g, d, v in data]
    edf = pd.DataFrame(exp, columns=["g", "dt", "s"]).sort_values(
        ["g", "dt", "s"]).reset_index(drop=True)
    gdf = df.copy()
    gdf["dt"] = pd.to_datetime(gdf["dt"]).dt.date
    gdf = gdf.sort_values(["g", "dt", "s"]).reset_index(drop=True)
    assert (gdf["s"].to_numpy() == edf["s"].to_numpy()).all()


def test_range_frame_oracle_random():
    """RANGE moving sums vs an O(n log n) searchsorted oracle."""
    import pandas as pd

    rng = np.random.default_rng(23)
    n = 2000
    g = rng.integers(0, 7, n)
    k = rng.integers(0, 300, n)
    v = rng.integers(-50, 50, n)
    s2 = cb.Session()
    s2.sql("create table rr (g bigint, k bigint, v bigint) "
           "distributed by (v)")
    s2.catalog.table("rr").set_data(
        {"g": g.astype(np.int64), "k": k.astype(np.int64),
         "v": v.astype(np.int64)})
    df = s2.sql(
        "select g, k, "
        "sum(v) over (partition by g order by k range between "
        "5 preceding and 3 following) as ms, "
        "count(v) over (partition by g order by k range between "
        "5 preceding and 3 following) as mc "
        "from rr order by g, k, v").to_pandas()
    pdf = pd.DataFrame({"g": g, "k": k, "v": v}).sort_values(["g", "k", "v"])
    want_s, want_c = [], []
    for _, grp in pdf.groupby("g"):
        ks, vs = grp["k"].to_numpy(), grp["v"].to_numpy()
        lo = np.searchsorted(ks, ks - 5, side="left")
        hi = np.searchsorted(ks, ks + 3, side="right")
        cs = np.concatenate([[0], np.cumsum(vs)])
        want_s += (cs[hi] - cs[lo]).tolist()
        want_c += (hi - lo).tolist()
    assert df["ms"].tolist() == want_s
    assert df["mc"].tolist() == want_c


def test_rows_frame_oracle_random():
    """Moving aggregates vs a pandas rolling oracle on 2k random rows."""
    import pandas as pd

    rng = np.random.default_rng(21)
    n = 2000
    g = rng.integers(0, 7, n)
    o = np.arange(n)
    v = rng.integers(-50, 50, n)
    s2 = cb.Session()
    s2.sql("create table r (g bigint, o bigint, v bigint) "
           "distributed by (o)")
    s2.catalog.table("r").set_data(
        {"g": g.astype(np.int64), "o": o.astype(np.int64),
         "v": v.astype(np.int64)})
    df = s2.sql(
        "select g, o, "
        "sum(v) over (partition by g order by o rows between 3 preceding "
        "and current row) as ms, "
        "min(v) over (partition by g order by o rows between 3 preceding "
        "and current row) as mn, "
        "max(v) over (partition by g order by o rows between 2 preceding "
        "and 1 following) as mx "
        "from r order by g, o").to_pandas()
    pdf = pd.DataFrame({"g": g, "o": o, "v": v}).sort_values(["g", "o"])
    grp = pdf.groupby("g")["v"]
    assert df["ms"].tolist() == \
        grp.rolling(4, min_periods=1).sum().astype(int).tolist()
    assert df["mn"].tolist() == \
        grp.rolling(4, min_periods=1).min().astype(int).tolist()
    want_mx = []
    for _, s_ in grp:
        a = s_.to_numpy()
        want_mx += [int(a[max(0, i - 2):i + 2].max())
                    for i in range(len(a))]
    assert df["mx"].tolist() == want_mx


# ------------------------------------------------- scalar subquery rows


def test_scalar_subquery_zero_rows_is_null(s):
    out = col(s, "select (select o from w where g = 'nope') as x "
                 "from w order by o limit 1", "x")
    assert out == [None]


def test_scalar_subquery_zero_rows_in_predicate(s):
    # NULL comparison -> no rows pass (not an error, not all rows)
    out = col(s, "select count(*) from w "
                 "where o > (select o from w where g = 'nope')")
    assert out == [0]


def test_scalar_subquery_one_row_still_works(s):
    out = col(s, "select (select max(o) from w) as x from w limit 1", "x")
    assert out == [3]
    # non-aggregate single-row subquery (needs the presence term)
    out = col(s, "select (select o from w where g = 'c') as x "
                 "from w limit 1", "x")
    assert out == [1]


def test_scalar_subquery_multi_row_errors(s):
    with pytest.raises(ExecError):
        s.sql("select (select o from w where g = 'a') from w").to_pandas()


def test_scalar_subquery_agg_over_zero_rows(s):
    # ungrouped aggregate of an empty set is one row: count=0, max=NULL
    assert col(s, "select (select count(*) from w where g='nope') as x "
                  "from w limit 1", "x") == [0]
    assert col(s, "select (select max(o) from w where g='nope') as x "
                  "from w limit 1", "x") == [None]
