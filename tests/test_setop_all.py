"""INTERSECT ALL / EXCEPT ALL (bag semantics) and the window gaps they
share machinery with (running min/max, nullable count/avg windows).

Reference behavior: nodeSetOp.c SETOP_HASHED *_ALL modes (per-group
counters); nodeWindowAgg.c default-frame aggregates. Oracles are computed
with pandas, same discipline as tests/test_tpch.py.
"""

import numpy as np
import pandas as pd
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config


@pytest.fixture(params=[1, 8], ids=["seg1", "seg8"])
def session(request):
    return cb.Session(Config(n_segments=request.param))


def _load(s, name, rows):
    s.sql(f"create table {name} (k bigint, v bigint)")
    vals = ", ".join(f"({k}, {v})" for k, v in rows)
    s.sql(f"insert into {name} values {vals}")


L = [(1, 1), (1, 1), (1, 2), (2, 5), (3, 7), (3, 7), (3, 7), (4, 0)]
R = [(1, 1), (1, 2), (3, 7), (3, 7), (9, 9)]


def _bag_oracle(op):
    from collections import Counter
    cl, cr = Counter(L), Counter(R)
    out = []
    for key in sorted(set(cl) | set(cr)):
        n = min(cl[key], cr[key]) if op == "intersect" \
            else max(cl[key] - cr[key], 0)
        out.extend([key] * n)
    return sorted(out)


@pytest.mark.parametrize("op", ["intersect", "except"])
def test_setop_all_bag_semantics(session, op):
    _load(session, "tl", L)
    _load(session, "tr", R)
    got = session.sql(
        f"select k, v from tl {op} all select k, v from tr "
        "order by k, v").to_pandas()
    want = _bag_oracle(op)
    assert [tuple(r) for r in got[["k", "v"]].to_numpy()] == want


def test_setop_all_with_nulls(session):
    # set ops treat NULLs as equal; ALL keeps multiplicities of NULL rows
    session.sql("create table nl (k bigint, v bigint)")
    session.sql("insert into nl values (1, null), (1, null), (1, 1)")
    session.sql("create table nr (k bigint, v bigint)")
    session.sql("insert into nr values (1, null), (2, null)")
    got = session.sql("select k, v from nl intersect all "
                      "select k, v from nr order by k").to_pandas()
    # exactly ONE (1, NULL) survives (min(2, 1))
    assert len(got) == 1
    assert got["k"].iloc[0] == 1 and pd.isna(got["v"].iloc[0])
    got2 = session.sql("select k, v from nl except all "
                       "select k, v from nr order by k, v").to_pandas()
    # exactly one (1,NULL) and one (1,1) remain
    from collections import Counter
    vals = Counter((int(k), None if pd.isna(v) else int(v))
                   for k, v in got2[["k", "v"]].to_numpy())
    assert vals == Counter([(1, 1), (1, None)])


def test_running_min_max(session):
    session.sql("create table w (g bigint, t bigint, v bigint)")
    rng = np.random.default_rng(7)
    rows = [(int(g), int(t), int(rng.integers(-50, 50)))
            for g in range(5) for t in range(17)]
    session.sql("insert into w values " +
                ", ".join(str(r) for r in rows))
    got = session.sql(
        "select g, t, v, min(v) over (partition by g order by t) as rmin, "
        "max(v) over (partition by g order by t) as rmax "
        "from w order by g, t").to_pandas()
    df = pd.DataFrame(rows, columns=["g", "t", "v"]).sort_values(["g", "t"])
    df["rmin"] = df.groupby("g")["v"].cummin()
    df["rmax"] = df.groupby("g")["v"].cummax()
    for c in ("rmin", "rmax"):
        assert list(got[c]) == list(df[c]), c


def test_running_extreme_peers_included(session):
    # RANGE frame: peers (equal order keys) are all included
    session.sql("create table p (g bigint, t bigint, v bigint)")
    session.sql("insert into p values (1,1,5), (1,1,3), (1,2,9), (1,2,1)")
    got = session.sql(
        "select t, min(v) over (partition by g order by t) as rmin "
        "from p order by t, rmin").to_pandas()
    # t=1 peers both see min(5,3)=3; t=2 peers see min over all four = 1
    assert list(got["rmin"]) == [3, 3, 1, 1]


def test_window_count_avg_nullable(session):
    session.sql("create table nv (k bigint, v bigint)")
    session.sql("insert into nv values (1,10),(1,null),(1,30),"
                "(2,null),(2,null),(3,5)")
    got = session.sql(
        "select k, count(v) over (partition by k) as c, "
        "avg(v) over (partition by k) as a, "
        "sum(v) over (partition by k) as s, "
        "min(v) over (partition by k) as mn "
        "from nv order by k").to_pandas()
    assert list(got["c"]) == [2, 2, 2, 0, 0, 1]
    assert got["a"].iloc[0] == pytest.approx(20.0)
    # all-NULL partition: every aggregate except count is NULL
    assert pd.isna(got["a"].iloc[3]) and pd.isna(got["s"].iloc[3]) \
        and pd.isna(got["mn"].iloc[3])
    assert got["s"].iloc[5] == 5 and got["mn"].iloc[5] == 5


def test_running_count_nullable(session):
    session.sql("create table rc (k bigint, t bigint, v bigint)")
    session.sql("insert into rc values (1,1,10),(1,2,null),(1,3,7)")
    got = session.sql(
        "select t, count(v) over (partition by k order by t) as c, "
        "sum(v) over (partition by k order by t) as s "
        "from rc order by t").to_pandas()
    assert list(got["c"]) == [1, 1, 2]
    assert list(got["s"]) == [10, 10, 17]


def test_window_minmax_nullable_strings(session):
    # strings order by COLLATION RANK, not dictionary code: insertion
    # order is adversarial ('zz' gets code 0) so a code-space identity
    # fill would return the wrong extreme
    session.sql("create table sw (k bigint, v text)")
    session.sql("insert into sw values (1,'zz'),(1,'aa'),(1,null),"
                "(2,null),(2,null)")
    got = session.sql(
        "select k, min(v) over (partition by k) as mn, "
        "max(v) over (partition by k) as mx from sw order by k").to_pandas()
    assert list(got["mn"][:3]) == ["aa"] * 3
    assert list(got["mx"][:3]) == ["zz"] * 3
    assert pd.isna(got["mn"].iloc[3]) and pd.isna(got["mx"].iloc[4])
    # running variant over the same adversarial dictionary
    session.sql("create table sw2 (k bigint, t bigint, v text)")
    session.sql("insert into sw2 values (1,1,'zz'),(1,2,null),(1,3,'aa')")
    got2 = session.sql(
        "select t, min(v) over (partition by k order by t) as rmn "
        "from sw2 order by t").to_pandas()
    assert list(got2["rmn"]) == ["zz", "zz", "aa"]


def test_running_extreme_null_never_beats_dtype_extreme(session):
    # a NULL lane's canonical stored value must not win a tie against a
    # VALID value equal to the dtype extreme (validity ranks above value)
    session.sql("create table ex (k bigint, t bigint, v double)")
    session.sql("insert into ex values (1,1,0.0),(1,2,null)")
    got = session.sql(
        "select t, min(v) over (partition by k order by t) as rmn "
        "from ex order by t").to_pandas()
    # at t=2 the frame is {0.0, NULL}: the answer is 0.0, not NULL's
    # canonical 0.0-by-accident — and count proves validity flowed
    assert list(got["rmn"]) == [0.0, 0.0]
    session.sql("create table ex2 (k bigint, t bigint, v bigint)")
    # int64 min: a valid lane holding the iinfo max must survive a NULL
    session.sql(f"insert into ex2 values (1,1,{(1 << 62)}),(1,2,null)")
    got2 = session.sql(
        "select t, min(v) over (partition by k order by t) as rmn "
        "from ex2 order by t").to_pandas()
    assert list(got2["rmn"]) == [1 << 62, 1 << 62]


def test_setop_all_strings(session):
    session.sql("create table sl (k bigint, name text)")
    session.sql("insert into sl values (1,'aa'),(1,'aa'),(2,'bb'),(3,'cc')")
    session.sql("create table sr (k bigint, name text)")
    session.sql("insert into sr values (1,'aa'),(3,'cc'),(3,'cc')")
    got = session.sql("select k, name from sl intersect all "
                      "select k, name from sr order by k").to_pandas()
    assert [tuple(r) for r in got.to_numpy()] == [(1, "aa"), (3, "cc")]
    got2 = session.sql("select k, name from sl except all "
                       "select k, name from sr order by k").to_pandas()
    assert [tuple(r) for r in got2.to_numpy()] == [(1, "aa"), (2, "bb")]
