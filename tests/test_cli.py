"""Management CLI (gpMgmt analog) — driven through main(argv)."""

import json
import os

import pytest

from cloudberry_tpu.mgmt.cli import main


@pytest.fixture
def store(tmp_path):
    return str(tmp_path / "cluster")


def run(capsys, *argv):
    rc = main(list(argv))
    return rc, capsys.readouterr().out


def test_init_state_sql_roundtrip(store, capsys):
    rc, out = run(capsys, "--store", store, "init", "--segments", "4")
    assert rc == 0 and "4 segments" in out
    # double init refuses without --force
    assert main(["--store", store, "init", "--segments", "2"]) == 1

    rc, _ = run(capsys, "--store", store, "sql", "--save",
                "create table kv (k bigint, v decimal(10,2)) distributed by (k)")
    assert rc == 0
    # reopen: insert + save
    rc, _ = run(capsys, "--store", store, "sql", "--save",
                "insert into kv values (1, 1.5), (2, 2.5), (3, 3.5)")
    assert rc == 0
    rc, out = run(capsys, "--store", store, "sql",
                  "select sum(v) as s, count(*) as n from kv")
    assert rc == 0 and "7.5" in out and "3" in out

    rc, out = run(capsys, "--store", store, "state")
    assert rc == 0
    assert "segments:        4" in out
    assert "health probe:    OK" in out
    assert "table kv" in out and "3 rows" in out


def test_probe(store, capsys):
    rc, out = run(capsys, "--store", store, "probe")
    assert rc == 0
    j = json.loads(out)
    assert j["ok"] and j["devices"] >= 1


def test_expand_minimal_movement(store, capsys):
    run(capsys, "--store", store, "init", "--segments", "4")
    run(capsys, "--store", store, "sql", "--save",
        "create table m (k bigint) distributed by (k)")
    rows = ",".join(f"({i})" for i in range(2000))
    run(capsys, "--store", store, "sql", "--save",
        f"insert into m values {rows}")
    rc, out = run(capsys, "--store", store, "expand", "--segments", "5")
    assert rc == 0 and "4 → 5" in out
    # jump hash moves ~1/5 = 20% on 4→5; modulo would move ~80%
    frac = float(out.split("m: ")[1].split("%")[0])
    assert frac < 30.0
    # config updated
    from cloudberry_tpu.mgmt.cli import load_cluster
    assert load_cluster(store)["n_segments"] == 5
    # queries still correct after expand
    rc, out = run(capsys, "--store", store, "sql",
                  "select count(*) as n from m")
    assert rc == 0 and "2000" in out


def test_check_detects_corruption(store, capsys):
    run(capsys, "--store", store, "init", "--segments", "2")
    run(capsys, "--store", store, "sql", "--save",
        "create table c (x bigint, s text)")
    run(capsys, "--store", store, "sql", "--save",
        "insert into c values (1, 'aa'), (2, 'bb')")
    rc, out = run(capsys, "--store", store, "check")
    assert rc == 0 and "0 problem(s)" in out
    # corrupt a partition file
    tdir = os.path.join(store, "c")
    part = [f for f in os.listdir(tdir) if f.endswith(".cbmp")][0]
    with open(os.path.join(tdir, part), "r+b") as fh:
        fh.write(b"GARBAGE!")
    rc, out = run(capsys, "--store", store, "check")
    assert rc == 1 and "CORRUPT" in out
