"""Append-merge OCC — concurrent DML that both commits.

The reference supports concurrent distributed DML, kept safe by the global
deadlock detector (src/backend/utils/gdd/README.md). This engine's analog:
commits never wait on row locks (OCC aborts instead, and the single store
commit lock is the only lock — no waits-for cycle can form), and a
transaction whose writes were ALL appends merges onto a concurrently
committed snapshot instead of aborting. Contracts under test: concurrent
INSERTs — including to different RANGE partitions of one table — both
succeed; rewrites still lose first-committer-wins; dictionaries and
uniqueness flags survive the merge correctly."""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.session import SerializationError


@pytest.fixture
def root(tmp_path):
    return str(tmp_path / "store")


def _sess(root):
    return cb.Session(Config(n_segments=1).with_overrides(
        **{"storage.root": root}))


def test_concurrent_inserts_both_commit(root):
    s1 = _sess(root)
    s1.sql("create table t (x bigint, p bigint) "
           "partition by range (p) (start 0 end 100 every 50)")
    s1.sql("insert into t values (1, 10)")
    s2 = _sess(root)

    s1.sql("begin")
    s2.sql("begin")
    s1.sql("insert into t values (2, 20)")    # partition r0
    s2.sql("insert into t values (3, 70)")    # partition r50 — disjoint
    s1.sql("commit")
    s2.sql("commit")  # append-only: merges instead of SerializationError

    s3 = _sess(root)
    got = s3.sql("select x from t order by x").to_pandas()["x"].tolist()
    assert got == [1, 2, 3]


def test_concurrent_inserts_same_partition_both_commit(root):
    s1, s2 = _sess(root), _sess(root)
    s1.sql("create table u (x bigint) distributed by (x)")
    s2.sql("select 1 as one")  # sync catalog
    s1.sql("begin")
    s2.sql("begin")
    s1.sql("insert into u values (1), (2)")
    s2.sql("insert into u values (3), (4)")
    s2.sql("commit")
    s1.sql("commit")
    got = _sess(root).sql("select x from u order by x").to_pandas()
    assert got["x"].tolist() == [1, 2, 3, 4]


def test_rewrite_still_conflicts(root):
    s1, s2 = _sess(root), _sess(root)
    s1.sql("create table r (x bigint) distributed by (x)")
    s1.sql("insert into r values (1), (2)")
    s2.sql("select 1 as one")
    s1.sql("begin")
    s2.sql("begin")
    s1.sql("insert into r values (3)")
    s2.sql("update r set x = x * 10 where x = 1")
    s1.sql("commit")
    with pytest.raises(SerializationError, match="could not serialize"):
        s2.sql("commit")
    got = _sess(root).sql("select x from r order by x").to_pandas()
    assert got["x"].tolist() == [1, 2, 3]


def test_append_after_concurrent_rewrite_merges(root):
    """The appender merges onto the rewriter's snapshot (serial order:
    rewrite first, then append)."""
    s1, s2 = _sess(root), _sess(root)
    s1.sql("create table w (x bigint) distributed by (x)")
    s1.sql("insert into w values (1), (2)")
    s2.sql("select 1 as one")
    s1.sql("begin")
    s2.sql("begin")
    s1.sql("delete from w where x = 1")   # rewrite
    s2.sql("insert into w values (9)")    # append
    s1.sql("commit")
    s2.sql("commit")  # merges onto the delete's snapshot
    got = _sess(root).sql("select x from w order by x").to_pandas()
    assert got["x"].tolist() == [2, 9]


def test_merge_reencodes_string_dictionaries(root):
    """Two sessions extend the base dictionary differently; the merge
    re-encodes the loser's tail against the winner's stored dictionary."""
    s1, s2 = _sess(root), _sess(root)
    s1.sql("create table d (s text) distributed by (s)")
    s1.sql("insert into d values ('base')")
    s2.sql("select 1 as one")
    s1.sql("begin")
    s2.sql("begin")
    s1.sql("insert into d values ('alpha')")
    s2.sql("insert into d values ('beta')")
    s1.sql("commit")
    s2.sql("commit")
    got = _sess(root).sql("select s from d order by s").to_pandas()
    assert got["s"].tolist() == ["alpha", "base", "beta"]


def test_merge_drops_broken_uniqueness(root):
    """A merged append that duplicates stored values clears the persisted
    uniqueness flag; non-overlapping appends keep it."""
    s1, s2 = _sess(root), _sess(root)
    s1.sql("create table k (id bigint) distributed by (id)")
    s1.sql("insert into k values (1), (2), (3)")
    assert _sess(root).store.read_manifest("k")["unique"]["id"]
    s2.sql("select 1 as one")
    s1.sql("begin")
    s2.sql("begin")
    s1.sql("insert into k values (4)")
    s2.sql("insert into k values (4)")  # duplicates s1's append
    s1.sql("commit")
    s2.sql("commit")
    man = _sess(root).store.read_manifest("k")
    assert man["unique"]["id"] is False
    # distinct appends keep uniqueness
    s1.sql("begin")
    s2.sql("begin")
    s1.sql("insert into k values (10)")
    s2.sql("insert into k values (11)")
    s1.sql("commit")
    s2.sql("commit")
    # flag was already False; but a fresh table with disjoint appends:
    s1.sql("create table k2 (id bigint) distributed by (id)")
    s1.sql("insert into k2 values (1)")
    s2.sql("select 1 as one")
    s1.sql("begin")
    s2.sql("begin")
    s1.sql("insert into k2 values (2)")
    s2.sql("insert into k2 values (3)")
    s1.sql("commit")
    s2.sql("commit")
    assert _sess(root).store.read_manifest("k2")["unique"]["id"] is True


def test_merged_session_sees_union_next_statement(root):
    s1, s2 = _sess(root), _sess(root)
    s1.sql("create table m (x bigint) distributed by (x)")
    s2.sql("select 1 as one")
    s1.sql("begin")
    s2.sql("begin")
    s1.sql("insert into m values (1)")
    s2.sql("insert into m values (2)")
    s1.sql("commit")
    s2.sql("commit")
    # BOTH sessions see the union afterwards (the merged session's stale
    # RAM copy was dropped at commit)
    for s in (s1, s2):
        got = s.sql("select x from m order by x").to_pandas()
        assert got["x"].tolist() == [1, 2]
