"""Distributed execution over the 8-device CPU mesh (demo-cluster analog):
every TPC-H query must produce byte-identical results to single-segment
execution, through real collectives (all_gather / all_to_all) inserted by
the distribution pass."""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from tools.tpch_oracle import ORACLES
from tools.tpch_queries import QUERIES
from tools.tpchgen import load_tpch

from tests.test_tpch import assert_frames_match


@pytest.fixture(scope="module")
def dist_session():
    # verify_plans: every distributed plan in this suite runs the
    # planck gate (plan/verify.py) before compiling — derived
    # distribution properties must match the stamps or the test fails
    # with a node-path diagnostic instead of a wrong answer
    s = cb.Session(Config(n_segments=8).with_overrides(
        **{"debug.verify_plans": True}))
    load_tpch(s, sf=0.01, seed=7)
    tables = {n: t.to_pandas() for n, t in s.catalog.tables.items()}
    return s, tables


@pytest.mark.parametrize("qname", sorted(QUERIES))
def test_tpch_distributed(dist_session, qname):
    session, tables = dist_session
    if qname not in ORACLES:
        pytest.skip(f"no oracle for {qname}")
    got = session.sql(QUERIES[qname]).to_pandas()
    exp = ORACLES[qname](tables)
    assert_frames_match(got, exp, qname)


def test_motion_plan_shapes(dist_session):
    session, _ = dist_session
    q1 = session.explain(QUERIES["q1"])
    # small group domain → GATHER_SINGLE final agg (skew-immune)
    assert "Motion gather" in q1
    assert "partial" in q1 and "final" in q1
    q6 = session.explain(QUERIES["q6"])
    assert "Motion gather" in q6  # global agg partial→gather→final
    q3 = session.explain(QUERIES["q3"])
    # customer⋈orders colocated? both hashed on different keys → motion needed
    assert "Motion" in q3


def test_colocated_join_needs_no_motion(dist_session):
    session, _ = dist_session
    # lineitem and orders are both hash-distributed on the orderkey → the
    # join is colocated and the plan must NOT redistribute either side
    plan = session.explain(
        "select count(*) from lineitem, orders where l_orderkey = o_orderkey")
    before_agg = plan.split("Agg")[-1]
    assert "Motion redistribute" not in before_agg
    assert "Motion broadcast" not in before_agg


def test_replicated_join_needs_no_motion(dist_session):
    session, _ = dist_session
    plan = session.explain(
        "select count(*) from supplier, nation where s_nationkey = n_nationkey")
    agg_input = plan.split("Agg")[-1]
    assert "Motion" not in agg_input


def test_distributed_ddl_roundtrip():
    s = cb.Session(Config(n_segments=4))
    s.sql("create table kv (k bigint, v decimal(10,2)) distributed by (k)")
    rows = ",".join(f"({i}, {i}.25)" for i in range(100))
    s.sql(f"insert into kv values {rows}")
    df = s.sql("select k, v from kv where k >= 90 order by k").to_pandas()
    assert df["k"].tolist() == list(range(90, 100))
    assert df["v"].tolist() == [k + 0.25 for k in range(90, 100)]
    agg = s.sql("select sum(v) as s, count(*) as n, avg(v) as a from kv").to_pandas()
    assert float(agg["s"][0]) == sum(k + 0.25 for k in range(100))
    assert int(agg["n"][0]) == 100


def test_left_join_replicated_probe_partitioned_build():
    # regression: left join with a REPLICATED probe and a PARTITIONED build
    # must broadcast the build side — otherwise every segment emits every
    # probe row (matched on ≤1 segment only) and the gather duplicates rows
    def run(nseg):
        s = cb.Session(Config(n_segments=nseg))
        s.sql("create table rep (x bigint) distributed replicated")
        s.sql("insert into rep values (1),(2),(3),(4),(5)")
        s.sql("create table part_t (id bigint, v bigint) distributed by (id)")
        s.sql("insert into part_t values (2,20),(4,40),(6,60)")
        return s.sql("""select x, v from rep left join part_t on id = x
                        order by x""").to_pandas()

    got = run(8)
    exp = run(1)
    assert got["x"].tolist() == exp["x"].tolist() == [1, 2, 3, 4, 5]
    assert got["v"].tolist() == exp["v"].tolist()


def test_direct_dispatch_point_query():
    s = cb.Session(Config(n_segments=8))
    s.sql("create table pk_t (id bigint, payload decimal(10,2)) distributed by (id)")
    s.sql("insert into pk_t values " + ",".join(f"({i}, {i}.25)" for i in range(200)))
    # point query on the distribution key: no motions, single-shard exec
    text = s.explain("select payload from pk_t where id = 42")
    assert "Direct dispatch: segment" in text
    assert "Motion" not in text
    df = s.sql("select payload from pk_t where id = 42").to_pandas()
    assert df["payload"].tolist() == [42.25]
    # every key routes correctly (exercises all segments)
    for k in [0, 7, 63, 199]:
        got = s.sql(f"select payload from pk_t where id = {k}").to_pandas()
        assert got["payload"].tolist() == [k + 0.25]
    # non-point query still distributes
    text2 = s.explain("select sum(payload) from pk_t where id > 5")
    assert "Direct dispatch" not in text2 and "Motion" in text2
    # disabled by config -> no direct dispatch
    s2 = cb.Session(Config(n_segments=8).with_overrides(
        **{"planner.enable_direct_dispatch": False}))
    s2.sql("create table pk_t (id bigint, payload decimal(10,2)) distributed by (id)")
    s2.sql("insert into pk_t values (1, 1.0)")
    assert "Direct dispatch" not in s2.explain(
        "select payload from pk_t where id = 1")


def test_topn_pushdown():
    s = cb.Session(Config(n_segments=8))
    s.sql("create table tn (k bigint, v bigint) distributed by (k)")
    s.sql("insert into tn values " + ",".join(f"({i},{(i*37)%1000})" for i in range(400)))
    text = s.explain("select k, v from tn order by v desc limit 5")
    # local Sort+Limit below the gather; final sort above it
    gather_idx = text.index("Motion gather")
    assert "Limit 5" in text[gather_idx:], text
    got = s.sql("select k, v from tn order by v desc, k limit 5").to_pandas()
    s1 = cb.Session()
    s1.sql("create table tn (k bigint, v bigint) distributed by (k)")
    s1.sql("insert into tn values " + ",".join(f"({i},{(i*37)%1000})" for i in range(400)))
    exp = s1.sql("select k, v from tn order by v desc, k limit 5").to_pandas()
    assert got["k"].tolist() == exp["k"].tolist()
    assert got["v"].tolist() == exp["v"].tolist()
