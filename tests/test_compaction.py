"""Background compaction service (storage/compact.py) — ISSUE 18.

Pinned here:

- the fold itself: delta partitions merge, delete vectors apply (a
  rewritten partition carries none), rows re-pack toward
  storage.rows_per_partition, and a declared range partition column
  re-sorts merged rows toward scan order;
- correctness: a compacted TPC-H store answers queries identically to
  its un-compacted self — fresh readers, buffer pool on AND off, at 1
  and 8 segments (the full query matrix runs in the slow tier, the
  writer-session subset in tier 1);
- the PR-13 fold: post-rebalance seg/seg_nseg-tagged delta partitions
  converge to a clean manifest with tags preserved (merges never cross
  destination groups) and results bit-identical;
- chaos: cancel-mid-chunk aborts cooperatively at the chunk seam with a
  consistent manifest; a crash inside the commit window leaves orphans
  the restart journal deletes, then compaction converges; a seeded
  fault soak with concurrent appends still lands the bounded
  delta-partition invariant;
- the version-bump contract: a compaction commit moves the table
  version, so pooled/cached state invalidates by construction (same
  answers through an enabled buffer pool before and after);
- observability: meta "compaction", compact_* counters, the COMPACT
  statement in the StatementLog, and the capacity gauge.
"""

import threading
import time

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu import lifecycle
from cloudberry_tpu.config import get_config
from cloudberry_tpu.storage.compact import (
    CompactionService, delta_parts)
from cloudberry_tpu.storage.ingest import IngestService
from cloudberry_tpu.utils import faultinject as FI


@pytest.fixture(autouse=True)
def _clean_faults():
    FI.reset_fault()
    yield
    FI.reset_fault()


RPP = 512


def _store_session(tmp_path, nseg=1, n=4000, **ov):
    over = {"n_segments": nseg, "storage.root": str(tmp_path),
            "storage.rows_per_partition": RPP,
            "ingest.flush_rows": 32, "ingest.flush_ms": 10.0}
    over.update(ov)
    s = cb.Session(get_config().with_overrides(**over))
    s.sql("create table t (k bigint, v bigint) distributed by (k)")
    t = s.catalog.table("t")
    t.set_data({"k": np.arange(n, dtype=np.int64),
                "v": (np.arange(n, dtype=np.int64) * 3) % 97}, {})
    s._sync_store()
    return s


def _fragment(s, lo=100_000, batches=12, rows=16):
    """Small appends (tiny tail partitions) + a visimap delete pass
    (dirty partitions) — the debt compaction exists to fold."""
    ing = IngestService(s)
    for b in range(batches):
        ing.append("t", [[lo + b * rows + j, 5] for j in range(rows)])
    ing.stop()
    s.store.delete_rows("t", lambda c: c["k"] % 11 == 3)
    s._sync_store()


_Q = "select count(*) as c, sum(v) as sv, min(k) as mn, max(k) as mx from t"


def _census(s, name="t"):
    return delta_parts(s.store.read_manifest(name), RPP, 0.5)


# -------------------------------------------------------------- the fold


def test_merge_applies_deletes_and_repacks(tmp_path):
    s = _store_session(tmp_path)
    _fragment(s)
    before = s.sql(_Q).to_pandas()
    rows_before = s.sql("select k, v from t order by k").to_pandas()
    man0 = s.store.read_manifest("t")
    assert _census(s) > 0
    assert any(p["deleted"] for p in man0["partitions"])

    comp = CompactionService(s)
    out = comp.run_once(force=True)
    assert out["chunks"] >= 1 and out["parts_merged"] >= 2

    man = s.store.read_manifest("t")
    assert _census(s) == 0, "compaction must drive the census to zero"
    assert not any(p["deleted"] for p in man["partitions"]), \
        "a rewritten partition carries no delete vector"
    live = sum(p["num_rows"] for p in man["partitions"])
    # re-packed: at most one under-filled tail remains
    assert len(man["partitions"]) <= live // RPP + 1
    assert before.equals(s.sql(_Q).to_pandas())
    assert rows_before.equals(
        s.sql("select k, v from t order by k").to_pandas())
    # a FRESH session over the compacted store reads the same relation
    s2 = cb.Session(get_config().with_overrides(
        **{"storage.root": str(tmp_path)}))
    assert rows_before.equals(
        s2.sql("select k, v from t order by k").to_pandas())


def test_resort_toward_declared_scan_order(tmp_path):
    """With a range partition column declared, merged partitions come
    out sorted by it — min/max stats tighten back to prunable."""
    s = cb.Session(get_config().with_overrides(
        **{"storage.root": str(tmp_path),
           "storage.rows_per_partition": RPP}))
    s.sql("create table t (k bigint, v bigint) "
          "partition by range (k) (start 0 end 4000 every 1000)")
    t = s.catalog.table("t")
    rng = np.random.default_rng(7)
    t.set_data({"k": rng.permutation(1000).astype(np.int64),
                "v": np.arange(1000, dtype=np.int64)}, {})
    s._sync_store()
    # shuffled small appends: each tail is internally unsorted
    ing = IngestService(s)
    for b in range(6):
        ks = rng.permutation(40) + 2000 + b * 100
        ing.append("t", [[int(k), 1] for k in ks])
    ing.stop()
    before = {p["file"] for p in
              s.store.read_manifest("t")["partitions"]}
    CompactionService(s).run_once(force=True)
    man = s.store.read_manifest("t")
    from cloudberry_tpu.storage import micropartition as mp
    import os
    written = [p for p in man["partitions"] if p["file"] not in before]
    assert written, "compaction must have rewritten the small tails"
    for p in written:
        cols = mp.read_columns(
            os.path.join(s.store.root, "t", p["file"]), ["k"])
        k = np.asarray(cols["k"])
        assert np.all(k[:-1] <= k[1:]), \
            f"partition {p['file']} not in scan order after compaction"
    # the fold lost nothing: relation is the base + every appended key
    got = s.sql("select count(*) c, sum(k) sk from t").to_pandas()
    exp_k = int(np.arange(1000).sum()
                + sum((np.arange(40) + 2000 + b * 100).sum()
                      for b in range(6)))
    assert int(got["c"][0]) == 1240 and int(got["sk"][0]) == exp_k


def test_compaction_is_a_logged_statement(tmp_path):
    s = _store_session(tmp_path)
    _fragment(s, batches=6)
    comp = CompactionService(s)
    comp.run_once(force=True)
    recent = s.stmt_log.recent(20)
    compacts = [r for r in recent if r["sql"].startswith("COMPACT ")]
    assert compacts and compacts[0]["status"] == "ok"
    assert s.stmt_log.counter("compact_chunks") >= 1
    snap = comp.snapshot()
    assert snap["enabled"] and snap["chunks"] >= 1
    assert any(row["table"] == "t" and row["delta_parts"] == 0
               for row in snap["tables"])
    # capacity gauge rides the last pass's census
    from cloudberry_tpu.obs import capacity
    s._compactor = comp
    vals = capacity.refresh_gauges(s)
    assert vals["compact_delta_parts_max"] == 0


# ----------------------------------------------- PR-13 rebalance folding


def test_post_rebalance_delta_partitions_converge(tmp_path):
    """The satellite regression: an online expand leaves seg-tagged
    delta partitions plus movement delete-vectors; compaction folds
    BOTH to a clean manifest — tags preserved (merges never cross
    destination groups), relation unchanged, fresh session identical."""
    s = _store_session(tmp_path, nseg=4, n=5000)
    rows_before = s.sql("select k, v from t order by k").to_pandas()
    s._topology.online_resize(6)
    man0 = s.store.read_manifest("t")
    tagged0 = [p for p in man0["partitions"] if p.get("seg_nseg") == 6]
    assert tagged0, "rebalance must leave destination-tagged deltas"
    assert any(p["deleted"] for p in man0["partitions"])
    assert _census(s) > 0

    CompactionService(s).run_once(force=True)
    man = s.store.read_manifest("t")
    assert _census(s) == 0
    assert not any(p["deleted"] for p in man["partitions"])
    tagged = [p for p in man["partitions"] if p.get("seg_nseg") == 6]
    # destination purity survives the fold: moved rows stay in tagged
    # partitions, exactly as many live rows as before
    assert sum(p["num_rows"] for p in tagged) \
        == sum(p["num_rows"] - len(p["deleted"]) for p in tagged0)
    for p in tagged:
        assert 0 <= p["seg"] < 6
    assert rows_before.equals(
        s.sql("select k, v from t order by k").to_pandas())
    s2 = cb.Session(get_config().with_overrides(
        **{"n_segments": 6, "storage.root": str(tmp_path)}))
    assert rows_before.equals(
        s2.sql("select k, v from t order by k").to_pandas())


# ----------------------------------------------------------------- chaos


def test_cancel_mid_chunk(tmp_path):
    """The pg_cancel_backend story holds for background work: a hang at
    the chunk seam is cancellable via the StatementLog, the pass aborts
    with a CONSISTENT manifest, and the next pass converges."""
    s = _store_session(tmp_path)
    _fragment(s)
    before = s.sql(_Q).to_pandas()
    comp = CompactionService(s)
    FI.inject_fault("compact_chunk", "hang")

    def canceller():
        for _ in range(200):
            act = [r for r in s.stmt_log.activity()
                   if r["sql"].startswith("COMPACT ")]
            if act:
                assert s.stmt_log.cancel(act[0]["id"])
                return
            time.sleep(0.01)

    bg = threading.Thread(target=canceller)
    bg.start()
    with pytest.raises(lifecycle.StatementCancelled):
        comp.run_once(force=True)
    bg.join()
    FI.reset_fault()
    assert before.equals(s.sql(_Q).to_pandas())
    comp.run_once(force=True)
    assert _census(s) == 0
    assert before.equals(s.sql(_Q).to_pandas())


def test_crash_restart_journal_resume(tmp_path):
    """An 'error' inside the locked commit window dies AFTER the
    replacement files exist: the journal's pending record survives, a
    fresh service's restore() deletes exactly the never-committed
    orphans, and the next pass converges with nothing lost."""
    import os

    s = _store_session(tmp_path)
    _fragment(s)
    before = s.sql(_Q).to_pandas()
    comp = CompactionService(s)
    FI.inject_fault("compact_commit", "error", start_hit=1, end_hit=1)
    with pytest.raises(FI.InjectedFault):
        comp.run_once(force=True)
    FI.reset_fault()
    rec = comp._read_journal(s.store)
    assert rec["pending"] and rec["pending"]["table"] == "t"
    orphans = [f for f in rec["pending"]["files"]
               if os.path.exists(os.path.join(str(tmp_path), "t", f))]
    assert orphans, "the crash left replacement files on disk"
    man = s.store.read_manifest("t")
    committed = {p["file"] for p in man["partitions"]}
    assert not (set(orphans) & committed)

    # crash-restart analog: a FRESH service restores from the journal
    comp2 = CompactionService(s)
    assert comp2._read_journal(s.store)["pending"] is None
    for f in orphans:
        assert not os.path.exists(os.path.join(str(tmp_path), "t", f))
    assert s.stmt_log.counter("compact_journal_restores") == 1
    assert before.equals(s.sql(_Q).to_pandas())
    comp2.run_once(force=True)
    assert _census(s) == 0
    assert before.equals(s.sql(_Q).to_pandas())


def test_fault_soak_holds_bounded_invariant(tmp_path):
    """Seeded chunk faults + concurrent appends, then quiesce: the
    bounded delta-partition invariant still lands and no row is lost —
    the worker survives every injected error."""
    s = _store_session(
        tmp_path, **{"compact.interval_s": 0.05,
                     "compact.max_delta_parts": 4})
    comp = CompactionService(s)
    comp.start()
    FI.inject_fault("compact_chunk", "error", p=0.3, seed=1234)
    ing = IngestService(s)
    for b in range(20):
        ing.append("t", [[200_000 + b * 8 + j, 2] for j in range(8)])
        if b == 10:
            s.store.delete_rows("t", lambda c: c["k"] % 13 == 5)
    ing.stop()
    time.sleep(0.3)
    FI.reset_fault()
    comp.wake()
    time.sleep(0.3)
    comp.stop()
    final = comp.run_once()  # census-only unless debt remains
    assert final["delta_parts_max"] <= comp.max_delta_parts
    s._sync_store()
    df = s.sql(_Q).to_pandas()
    keep = np.arange(4000)[np.arange(4000) % 13 != 5]
    app = np.arange(200_000, 200_160)
    # the delete pass ran after batch 10: only the first 11 batches'
    # rows (keys < 200_088) were durable — and deletable — then
    app_live = app[~((app % 13 == 5) & (app < 200_088))]
    assert int(df["c"][0]) == len(keep) + len(app_live)
    assert int(df["sv"][0]) == int(((keep * 3) % 97).sum()) \
        + 2 * len(app_live)


def test_worker_defers_while_breaker_open(tmp_path):
    s = _store_session(
        tmp_path, **{"compact.interval_s": 0.05,
                     "compact.max_delta_parts": 0})
    _fragment(s, batches=4)
    debt = _census(s)
    assert debt > 0

    class _Breaker:
        state = "open"

    s._breaker = _Breaker()
    comp = CompactionService(s)
    comp.start()
    comp.wake()
    time.sleep(0.2)
    assert _census(s) == debt, "an open breaker must defer compaction"
    s._breaker.state = "closed"
    comp.wake()
    for _ in range(100):
        if _census(s) == 0:
            break
        time.sleep(0.02)
    comp.stop()
    assert _census(s) == 0


# ------------------------------------------- version-bump invalidation


def test_version_bump_invalidates_pooled_state(tmp_path):
    """Compaction rewrites files under the SAME table name; correctness
    of every cache keyed by store version (buffer pool, shared plans,
    sketches) rides on the commit bumping that version."""
    s = _store_session(tmp_path, **{"bufferpool.enabled": True})
    _fragment(s)
    before = s.sql(_Q).to_pandas()  # pool now holds pre-compaction tiles
    v0 = s.store.current_version("t")
    CompactionService(s).run_once(force=True)
    assert s.store.current_version("t") > v0
    # same session: _sync_store sees the moved version, re-registers
    assert before.equals(s.sql(_Q).to_pandas())
    assert s.catalog.table("t")._store_version > v0


# ------------------------------------------------------- TPC-H identity


def _pyv(v):
    import pandas as pd
    if isinstance(v, pd.Timestamp):
        return str(v.date())
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    if isinstance(v, np.bool_):
        return bool(v)
    return v


@pytest.fixture(scope="module")
def tpch_store(tmp_path_factory):
    """A store-backed TPC-H sf=0.01 set, fragmented (duplicate tail
    appends through the ingest plane + a visimap delete pass on
    lineitem/orders), with pre-compaction answers captured, THEN
    compacted to census zero. Readers in the tests open fresh sessions
    over the compacted root."""
    from tools.tpch_queries import QUERIES
    from tools.tpchgen import load_tpch

    root = str(tmp_path_factory.mktemp("tpch_store"))
    s = cb.Session(get_config().with_overrides(**{
        "storage.root": root, "storage.rows_per_partition": 2048,
        "ingest.flush_rows": 64, "ingest.flush_ms": 10.0}))
    load_tpch(s, sf=0.01, seed=7)
    li = s.catalog.table("lineitem").to_pandas()
    ing = IngestService(s)
    for b in range(4):
        ing.append("lineitem",
                   [[_pyv(v) for v in li.iloc[-(b * 50 + j) - 1]]
                    for j in range(50)])
    ing.stop()
    s.store.delete_rows("lineitem", lambda c: c["l_orderkey"] % 37 == 0)
    s.store.delete_rows("orders", lambda c: c["o_orderkey"] % 37 == 0)
    s._sync_store()
    frag_census = delta_parts(
        s.store.read_manifest("lineitem"), 2048, 0.5)
    assert frag_census > 0
    tables = {}
    for n, t in s.catalog.tables.items():
        t.ensure_loaded()  # lineitem/orders re-registered cold above
        tables[n] = t.to_pandas()
    subset = ("q1", "q3", "q6")
    baseline = {q: s.sql(QUERIES[q]).to_pandas() for q in subset}
    out = CompactionService(s).run_once(force=True)
    assert out["chunks"] >= 1
    for name in ("lineitem", "orders"):
        assert delta_parts(s.store.read_manifest(name), 2048, 0.5) == 0
    return root, tables, baseline


@pytest.mark.parametrize("nseg,pool", [(1, True), (1, False)],
                         ids=["pool", "nopool"])
def test_tpch_compacted_identical_subset(tpch_store, nseg, pool):
    """Tier-1 cut of the acceptance matrix: fresh readers over the
    compacted store answer the captured pre-compaction results."""
    from tools.tpch_queries import QUERIES
    from tests.test_tpch import assert_frames_match

    root, _, baseline = tpch_store
    s = cb.Session(get_config().with_overrides(
        **{"n_segments": nseg, "storage.root": root,
           "bufferpool.enabled": pool}))
    for q, exp in baseline.items():
        assert_frames_match(s.sql(QUERIES[q]).to_pandas(), exp, q)


@pytest.mark.slow
@pytest.mark.parametrize("nseg,pool", [(1, True), (1, False),
                                       (8, True), (8, False)],
                         ids=["1seg-pool", "1seg-nopool",
                              "8seg-pool", "8seg-nopool"])
def test_tpch_compacted_full_matrix(tpch_store, nseg, pool):
    """The full acceptance matrix: EVERY TPC-H query over the compacted
    store, against the pandas oracle on the fragmented data (test_tpch
    pins un-compacted == oracle, so this pins compacted == un-compacted
    transitively), at 1 and 8 segments, pool on and off."""
    from tools.tpch_oracle import ORACLES
    from tools.tpch_queries import QUERIES
    from tests.test_tpch import assert_frames_match

    root, tables, _ = tpch_store
    s = cb.Session(get_config().with_overrides(
        **{"n_segments": nseg, "storage.root": root,
           "bufferpool.enabled": pool}))
    for qname in sorted(QUERIES):
        if qname not in ORACLES:
            continue
        got = s.sql(QUERIES[qname]).to_pandas()
        assert_frames_match(got, ORACLES[qname](tables), qname)
