"""Session-per-connection serving + wire transactions.

The reference forks a backend per connection (postgres.c:1655) over shared
storage; here each connection gets its own Session over the shared
TableStore. Contracts under test: wire BEGIN/COMMIT/ROLLBACK ride the
multi-session OCC (first committer wins, the loser gets
SerializationError), a dropped connection aborts its open transaction, one
connection's autocommit writes are visible to others, endpoints are
server-shared, and the shared-session rw-lock gives writers priority."""

import threading
import time

import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.serve.client import Client, ServerError
from cloudberry_tpu.serve.server import Server, _RWLock


@pytest.fixture
def store_server(tmp_path):
    cfg = Config().with_overrides(**{"storage.root": str(tmp_path / "st")})
    with Server(config=cfg) as srv:
        yield srv


def test_wire_txn_occ_conflict(store_server):
    srv = store_server
    assert srv.per_connection
    with Client(srv.host, srv.port) as c1, Client(srv.host, srv.port) as c2:
        c1.sql("create table t (x bigint) distributed by (x)")
        c1.sql("insert into t values (1)")
        assert c2.rows("select count(*) as n from t") == [[1]]  # visible
        c1.sql("begin")
        c2.sql("begin")
        c1.sql("insert into t values (2)")
        c2.sql("update t set x = x * 10 where x = 1")  # rewrite
        c1.sql("commit")  # first committer wins against the rewrite
        with pytest.raises(ServerError, match="could not serialize"):
            c2.sql("commit")
        # the loser rolled back: only the winner's row landed
        with Client(srv.host, srv.port) as c3:
            assert c3.rows("select count(*) as n from t") == [[2]]
        # append-only wire transactions MERGE instead of conflicting
        c1.sql("begin")
        c2.sql("begin")
        c1.sql("insert into t values (4)")
        c2.sql("insert into t values (5)")
        c1.sql("commit")
        c2.sql("commit")
        with Client(srv.host, srv.port) as c3:
            assert c3.rows("select count(*) as n from t") == [[4]]


def test_wire_txn_rollback_and_repeatable_reads(store_server):
    srv = store_server
    with Client(srv.host, srv.port) as c1, Client(srv.host, srv.port) as c2:
        c1.sql("create table r (x bigint) distributed by (x)")
        c1.sql("insert into r values (1), (2)")
        c2.sql("begin")
        assert c2.rows("select count(*) as n from r") == [[2]]
        c1.sql("insert into r values (3)")  # autocommit, outside c2's txn
        # snapshot isolation: c2 still sees its BEGIN snapshot
        assert c2.rows("select count(*) as n from r") == [[2]]
        c2.sql("rollback")
        assert c2.rows("select count(*) as n from r") == [[3]]


def test_disconnect_aborts_open_transaction(store_server):
    srv = store_server
    with Client(srv.host, srv.port) as c1:
        c1.sql("create table d (x bigint) distributed by (x)")
    c = Client(srv.host, srv.port)
    c.sql("begin")
    c.sql("insert into d values (7)")
    c.close()  # backend exit: the open transaction must roll back
    deadline = time.monotonic() + 10
    with Client(srv.host, srv.port) as c2:
        while time.monotonic() < deadline:
            if c2.rows("select count(*) as n from d") == [[0]]:
                break
            time.sleep(0.05)
        assert c2.rows("select count(*) as n from d") == [[0]]


def test_cursor_shared_across_connections(store_server):
    srv = store_server
    with Client(srv.host, srv.port) as c1:
        c1.sql("create table e (x bigint) distributed by (x)")
        c1.sql("insert into e values (1), (2), (3)")
        out = c1.sql("declare pc parallel retrieve cursor for "
                     "select x from e")
        token = out["token"]
        endpoints = out["endpoints"]
        # retrieve-mode connection: a DIFFERENT connection drains the
        # endpoints (the shmem endpoint directory, cdbendpoint.c)
        with Client(srv.host, srv.port) as c2:
            rows = []
            for ep in endpoints:
                got = c2.retrieve("pc", ep["segment"], token)
                rows.extend(v for row in got["rows"] for v in row)
        assert sorted(rows) == [1, 2, 3]


def test_rwlock_writer_priority():
    """A continuous stream of readers must not starve a writer: once the
    writer waits, new readers queue behind it."""
    lk = _RWLock()
    stop = threading.Event()
    in_read = threading.Event()

    def reader_loop():
        while not stop.is_set():
            lk.acquire_read()
            in_read.set()
            time.sleep(0.005)
            lk.release_read()

    threads = [threading.Thread(target=reader_loop, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    in_read.wait(5)
    got_write = threading.Event()

    def writer():
        lk.acquire_write()
        got_write.set()
        lk.release_write()

    w = threading.Thread(target=writer, daemon=True)
    w.start()
    assert got_write.wait(5), "writer starved by readers"
    stop.set()
    for t in threads:
        t.join(timeout=5)


def test_storeless_server_still_refuses_wire_txn():
    s = cb.Session(Config())
    with Server(session=s) as srv:
        assert not srv.per_connection
        with Client(srv.host, srv.port) as c:
            with pytest.raises(ServerError, match="share one session"):
                c.sql("begin")
