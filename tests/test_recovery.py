"""Mid-statement fault recovery (exec/recovery.py) — the chaos ladder.

The contract under test: killing a device (faultinject
``tile_device_lost``) at an ARBITRARY tile of a tiled or tiled_dist
statement yields bit-identical results vs the uninterrupted run, with
``tiles_replayed`` strictly less than the total tile count (resume from
the last K-tile checkpoint, not restart) — including the degraded case
where the survivor mesh has fewer segments than the original plan.
Plus the recovery/lifecycle interplay: an in-progress recovery counts
as liveness under the watchdog while the statement DEADLINE stays
enforced, retries back off with a visible budget, and the
fault-injection registry reports which seams fired."""

import time

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu import lifecycle
from cloudberry_tpu.config import get_config
from cloudberry_tpu.utils import faultinject as FI


@pytest.fixture(autouse=True)
def _clean_faults():
    FI.reset_fault()
    yield
    FI.reset_fault()


# one merge-motion aggregate (dim distributed on a DIFFERENT key than
# the join key, so the probe redistributes and the GROUP BY needs a
# merge motion — the placement-free degraded-resume case) ...
DIST_Q = ("SELECT g, sum(v) AS sv, count(*) AS c "
          "FROM fact JOIN dim ON fact.d = dim.d "
          "GROUP BY g ORDER BY g")
# ... and one COLOCATED one-stage aggregate (grouping on the
# distribution key: no merge motion, so changed-nseg resume declines)
COLOC_Q = "SELECT k, sum(v) AS sv FROM fact GROUP BY k ORDER BY k LIMIT 20"

SINGLE_Q = ("SELECT g, sum(v) AS sv, count(*) AS c "
            "FROM fact JOIN dim ON fact.k = dim.k "
            "GROUP BY g ORDER BY g")


def _mk(nseg=1, budget=2 << 20, **extra):
    ov = {"n_segments": nseg,
          "resource.query_mem_bytes": budget,
          # small K so short test streams cross several checkpoints
          "recovery.checkpoint_every": 2}
    if nseg > 1:
        ov["planner.broadcast_threshold"] = 0
    ov.update(extra)
    return cb.Session(get_config().with_overrides(**ov))


def _load_single(s, n=200_000, nd=500):
    rng = np.random.default_rng(3)
    s.sql("CREATE TABLE dim (k BIGINT, g BIGINT) DISTRIBUTED BY (k)")
    s.sql("CREATE TABLE fact (k BIGINT, v BIGINT) DISTRIBUTED BY (k)")
    s.catalog.table("dim").set_data(
        {"k": np.arange(nd), "g": np.arange(nd) % 9})
    s.catalog.table("fact").set_data(
        {"k": rng.integers(0, nd, n), "v": rng.integers(0, 100, n)})


def _load_dist(s, n=400_000, nd=500):
    rng = np.random.default_rng(3)
    s.sql("CREATE TABLE dim (d BIGINT, g BIGINT) DISTRIBUTED BY (g)")
    s.sql("CREATE TABLE fact (k BIGINT, d BIGINT, v BIGINT) "
          "DISTRIBUTED BY (k)")
    s.catalog.table("dim").set_data(
        {"d": np.arange(nd), "g": np.arange(nd) % 9})
    # k: 997 distinct values — a colocatable GROUP BY key
    s.catalog.table("fact").set_data(
        {"k": np.arange(n) % 997,
         "d": rng.integers(0, nd, n),
         "v": rng.integers(0, 100, n)})


def _arm_kill(k: int) -> None:
    """Deterministic device loss at 0-based tile ``k`` of the NEXT
    attempt (the seam is hit once per tile; the retry's later hits fall
    outside the window)."""
    FI.inject_fault("tile_device_lost", "error",
                    start_hit=k + 1, end_hit=k + 1)


def _kill_and_run(s, q, k: int):
    """Arm a kill at tile k, run, and return (df, replayed, resumed,
    report)."""
    FI.reset_fault("tile_device_lost")
    _arm_kill(k)
    b_rep = s.stmt_log.counter("tiles_replayed")
    b_res = s.stmt_log.counter("tile_resumes")
    df = s.sql(q).to_pandas()
    return (df, s.stmt_log.counter("tiles_replayed") - b_rep,
            s.stmt_log.counter("tile_resumes") - b_res,
            s.last_tiled_report)


# --------------------------------------------------- kill-at-tile matrix


def test_tiled_kill_matrix():
    """Single-node tiled agg: kill at tile 0 / mid / last — bit-identical
    results, replay bounded by K (checkpoint granularity), never a full
    restart once a checkpoint exists."""
    s = _mk()
    _load_single(s)
    clean = s.sql(SINGLE_Q).to_pandas()
    total = s.last_tiled_report["n_tiles"]
    assert total >= 4  # the matrix needs a real stream
    for k in (0, total // 2, total - 1):
        df, replayed, resumed, rep = _kill_and_run(s, SINGLE_Q, k)
        assert clean.equals(df), f"kill@{k} diverged"
        assert replayed < total, f"kill@{k} replayed everything"
        if k >= 2:  # a checkpoint existed: resumed, ≤ K tiles replayed
            assert resumed == 1 and rep["resumed_from_tile"] > 0
            assert replayed <= 2
        assert rep["n_tiles"] == total


def test_tiled_dist_kill_matrix():
    """Distributed tiled agg (merge-motion two-stage): same matrix on
    the 8-segment mesh — per-tile SPMD steps resume from the
    per-segment accumulator snapshot."""
    s = _mk(nseg=8)
    _load_dist(s)
    clean = s.sql(DIST_Q).to_pandas()
    total = s.last_tiled_report["n_tiles"]
    assert total >= 4
    for k in (0, total // 2, total - 1):
        df, replayed, resumed, rep = _kill_and_run(s, DIST_Q, k)
        assert clean.equals(df), f"kill@{k} diverged"
        assert replayed < total, f"kill@{k} replayed everything"
        if k >= 2:
            assert resumed == 1 and rep["resumed_from_tile"] > 0
            assert replayed <= 2
        assert s.config.n_segments == 8  # no degrade without a probe arm


# --------------------------------------------------- degraded-mesh resume


def test_dist_degraded_resume():
    """The acceptance centerpiece: device loss mid-stream + a probe
    reporting one device gone — the statement resumes on the SEVEN
    survivors from the checkpoint (remaining rows re-sharded by the
    placement hash, partials re-placed round-robin ahead of the merge
    motion) and the result is bit-identical to the clean 8-segment
    run."""
    s = _mk(nseg=8)
    _load_dist(s)
    clean = s.sql(DIST_Q).to_pandas()
    total = s.last_tiled_report["n_tiles"]
    k = max(total // 2, 2)
    FI.inject_fault("probe_degraded", "skip")  # probe sees 7 devices
    df, replayed, resumed, rep = _kill_and_run(s, DIST_Q, k)
    assert s.config.n_segments == 7
    assert clean.equals(df)
    assert resumed == 1 and rep["resumed_from_tile"] > 0
    assert replayed < total and replayed <= 2
    assert rep["n_segments"] == 7
    # the degraded session keeps serving (and resuming) afterwards
    FI.reset_fault()
    assert clean.equals(s.sql(DIST_Q).to_pandas())


def test_dist_degraded_colocated_declines_but_completes():
    """Colocated one-stage agg partials would need the group-key hash to
    re-place on a smaller mesh: the changed-nseg resume DECLINES (a
    counted decision, not an error) and the statement re-executes fresh
    on the survivors — correct, just not incremental."""
    s = _mk(nseg=8, budget=1 << 20)
    _load_dist(s, n=800_000)
    clean = s.sql(COLOC_Q).to_pandas()
    total = s.last_tiled_report["n_tiles"]
    assert total >= 3
    k = min(max(total // 2, 2), total - 1)
    FI.inject_fault("probe_degraded", "skip")
    b_dec = s.stmt_log.counter("tile_resume_declined")
    df, replayed, resumed, rep = _kill_and_run(s, COLOC_Q, k)
    assert s.config.n_segments == 7
    assert clean.equals(df)
    assert resumed == 0
    assert s.stmt_log.counter("tile_resume_declined") - b_dec >= 1
    assert replayed == k  # honest accounting: the fresh run replays all


def test_dist_colocated_same_mesh_resumes():
    """An UNCHANGED mesh never needs re-placement: the colocated
    one-stage agg resumes verbatim from its per-segment snapshot."""
    s = _mk(nseg=8, budget=1 << 20)
    _load_dist(s, n=800_000)
    clean = s.sql(COLOC_Q).to_pandas()
    total = s.last_tiled_report["n_tiles"]
    k = min(max(total // 2, 2), total - 1)
    df, replayed, resumed, rep = _kill_and_run(s, COLOC_Q, k)
    assert clean.equals(df)
    assert resumed == 1 and rep["resumed_from_tile"] > 0
    assert replayed <= 2 < total


# --------------------------------------------------------- other modes


def test_tiled_topn_resume():
    """Top-N mode: the bounded accumulator snapshot resumes mid-stream
    (sort-key-only projection keeps boundary ties value-identical)."""
    q = "SELECT v, k FROM fact ORDER BY v DESC, k LIMIT 25"
    s = _mk(budget=1 << 20)
    _load_single(s)
    clean = s.sql(q).to_pandas()
    total = s.last_tiled_report["n_tiles"]
    assert s.last_tiled_report["mode"] == "topn" and total >= 4
    df, replayed, resumed, rep = _kill_and_run(s, q, max(total // 2, 2))
    assert clean.equals(df)
    assert resumed == 1 and replayed <= 2 < total


def test_tiled_sort_resume():
    """External-sort mode: the host-resident run store IS the
    checkpoint payload (shallow list pins); resume streams only the
    remaining tiles into it."""
    q = "SELECT v, k FROM fact WHERE v > 90 ORDER BY v, k"
    s = _mk(budget=1 << 20)
    _load_single(s)
    clean = s.sql(q).to_pandas()
    total = s.last_tiled_report["n_tiles"]
    assert s.last_tiled_report["mode"] == "sort" and total >= 4
    df, replayed, resumed, rep = _kill_and_run(s, q, max(total // 2, 2))
    assert clean.equals(df)
    assert resumed == 1 and replayed <= 2 < total


@pytest.mark.slow
def test_dist_topn_degraded_resume():
    """Distributed top-N on a shrunken mesh: the pooled per-segment
    heaps pre-select the global best m host-side (the device's own key
    normalization) and round-robin onto the survivors."""
    q = "SELECT v, k, d FROM fact ORDER BY v DESC, k, d LIMIT 25"
    s = _mk(nseg=8, budget=1 << 20)
    _load_dist(s)
    clean = s.sql(q).to_pandas()
    total = s.last_tiled_report["n_tiles"]
    assert s.last_tiled_report["mode"] == "topn" and total >= 4
    FI.inject_fault("probe_degraded", "skip")
    df, replayed, resumed, rep = _kill_and_run(s, q, max(total // 2, 2))
    assert s.config.n_segments == 7
    assert clean.equals(df)
    assert resumed == 1 and replayed <= 2 < total


@pytest.mark.slow
def test_dist_sort_degraded_resume():
    q = "SELECT v, k FROM fact WHERE v > 90 ORDER BY v, k"
    s = _mk(nseg=8, budget=1 << 20)
    _load_dist(s)
    clean = s.sql(q).to_pandas()
    total = s.last_tiled_report["n_tiles"]
    assert s.last_tiled_report["mode"] == "sort" and total >= 4
    FI.inject_fault("probe_degraded", "skip")
    df, replayed, resumed, rep = _kill_and_run(s, q, max(total // 2, 2))
    assert s.config.n_segments == 7
    assert clean.equals(df)
    assert resumed == 1 and replayed <= 2 < total


# ------------------------------------------------- checkpoint hygiene


def test_checkpoints_die_with_their_statement():
    s = _mk()
    _load_single(s)
    s.sql(SINGLE_Q)
    assert s._recovery._ckpts == {}  # discarded at statement end
    # a kill mid-statement leaves nothing behind either once recovered
    total = s.last_tiled_report["n_tiles"]
    _arm_kill(max(total // 2, 2))
    s.sql(SINGLE_Q)
    assert s._recovery._ckpts == {}


def test_ckpt_save_skip_forces_full_restart():
    """The ckpt_save chaos arm suppresses snapshots: recovery still
    works (stateless re-execution) but replays the whole consumed
    prefix — the pre-checkpoint world, pinned as the contrast case."""
    s = _mk()
    _load_single(s)
    clean = s.sql(SINGLE_Q).to_pandas()
    total = s.last_tiled_report["n_tiles"]
    k = max(total // 2, 2)
    FI.inject_fault("ckpt_save", "skip")
    df, replayed, resumed, rep = _kill_and_run(s, SINGLE_Q, k)
    assert clean.equals(df)
    assert resumed == 0 and replayed == k


def test_ckpt_resume_skip_forces_fresh_run():
    s = _mk()
    _load_single(s)
    clean = s.sql(SINGLE_Q).to_pandas()
    total = s.last_tiled_report["n_tiles"]
    k = max(total // 2, 2)
    FI.inject_fault("ckpt_resume", "skip")
    df, replayed, resumed, rep = _kill_and_run(s, SINGLE_Q, k)
    assert clean.equals(df)
    assert resumed == 0 and replayed == k


# ------------------------------------- watchdog / deadline interplay


def test_recovery_counts_as_liveness_under_watchdog():
    """A statement recovering within its deadline must NOT be cancelled
    by the watchdog: recovery is liveness (state 'recovering' in the
    activity row), and only the DEADLINE can kill it."""
    s = _mk(**{"statement_timeout_s": 120.0, "health.backoff_s": 0.05})
    wd = lifecycle.Watchdog(s.stmt_log, interval_s=0.01).start()
    try:
        _load_single(s)
        clean = s.sql(SINGLE_Q).to_pandas()
        total = s.last_tiled_report["n_tiles"]
        df, _, resumed, _ = _kill_and_run(s, SINGLE_Q,
                                          max(total // 2, 2))
        assert clean.equals(df) and resumed == 1
        assert s.stmt_log.counter("watchdog_timeouts") == 0
    finally:
        wd.stop()


def test_deadline_enforced_during_recovery_backoff():
    """The deadline governs the RESUME too: a huge backoff must neither
    sleep past the statement deadline nor dispatch another attempt
    after it — the statement dies of StatementTimeout (the deadline
    verdict), not of a hang classification or the injected fault."""
    s = _mk(**{"statement_timeout_s": 0.5, "health.backoff_s": 30.0,
               "health.retries": 3})
    s.sql("create table t1 (x bigint)")
    s.catalog.table("t1").set_data({"x": np.arange(64, dtype=np.int64)})
    FI.inject_fault("exec_device_lost", "error")  # every dispatch
    t0 = time.monotonic()
    with pytest.raises(lifecycle.StatementTimeout):
        s.sql("select sum(x) from t1")
    assert time.monotonic() - t0 < 5.0  # not 30s of backoff


def test_retry_budget_stops_redispatch():
    """health.retry_budget_s bounds a statement's recovery spend: once
    failed attempts have consumed it, the next recoverable failure
    raises instead of retrying."""
    s = _mk(**{"health.retries": 5, "health.backoff_s": 0.01,
               "health.retry_budget_s": 1e-6})
    s.sql("create table t1 (x bigint)")
    s.catalog.table("t1").set_data({"x": np.arange(8, dtype=np.int64)})
    FI.inject_fault("exec_device_lost", "error")
    with pytest.raises(FI.InjectedFault):
        s.sql("select sum(x) from t1")
    # the budget refused every re-dispatch: exactly one attempt ran
    assert FI.list_faults()["armed"]["exec_device_lost"]["fired"] == 1


def test_retry_visible_in_activity_history():
    s = _mk(**{"health.backoff_s": 0.01})
    s.sql("create table t1 (x bigint)")
    s.catalog.table("t1").set_data({"x": np.arange(8, dtype=np.int64)})
    FI.inject_fault("exec_device_lost", "error", start_hit=1, end_hit=1)
    s.sql("select sum(x) from t1")
    entry = s.stmt_log.recent(1)[0]
    assert entry["attempts"] == 1
    assert entry["backoff_s"] > 0
    assert entry["last_error"] == "InjectedFault"
    assert s.stmt_log.counter("recoveries") == 1
    assert s.stmt_log.counter("recovery_wall_ms") >= 0


# -------------------------------------------- faultinject chaos arms


def test_probabilistic_arm_fires_reproducibly():
    def fire_count(n=200):
        fired = 0
        for _ in range(n):
            try:
                FI.fault_point("p_seam")
            except FI.InjectedFault:
                fired += 1
        return fired

    FI.inject_fault("p_seam", "error", p=0.4, seed=7)
    f1 = fire_count()
    info = FI.list_faults()["armed"]["p_seam"]
    assert info["hits"] == 200 and info["fired"] == f1
    assert 40 < f1 < 160  # probabilistic, not all-or-nothing
    # same seed → same firing sequence (reproducible soaks)
    FI.inject_fault("p_seam", "error", p=0.4, seed=7)
    assert fire_count() == f1
    assert "p_seam" in FI.list_faults()["seen"]


def test_list_faults_reports_armed_window():
    FI.inject_fault("w_seam", "skip", start_hit=3, end_hit=4)
    for _ in range(5):
        FI.fault_point("w_seam")
    info = FI.list_faults()["armed"]["w_seam"]
    assert info["hits"] == 5 and info["fired"] == 2
    assert info["start_hit"] == 3 and info["end_hit"] == 4


# ------------------------------------------------- serving / tooling


def test_serve_bench_chaos_smoke():
    """CPU smoke of the --chaos workload: the spill mix streams tiles
    under probabilistic device loss and the CSV row carries the
    recovery counters."""
    import tools.serve_bench as SB

    r = SB.run_mode("direct", "spill", clients=2, duration_s=1.0,
                    rows=200_000, tick_s=0.002, max_batch=8, chaos=0.2)
    assert r["requests"] > 0
    for k in ("recovery_count", "tiles_replayed", "recovery_ms"):
        assert k in r and r[k] >= 0
    row = SB.csv_row(r)
    assert len(row.split(",")) == len(SB.CSV_HEADER.split(","))


def test_meta_info_recovery_counters():
    from cloudberry_tpu.serve.meta import describe

    s = _mk()
    _load_single(s)
    total_before = s.sql(SINGLE_Q).to_pandas()
    _arm_kill(2)
    s.sql(SINGLE_Q)
    info = describe(s, "info")
    rec = info["recovery"]
    assert rec["recoveries"] >= 1 and rec["tile_checkpoints"] >= 1
    assert rec["tile_resumes"] >= 1
    del total_before


# ------------------------------------------------------- chaos soak


@pytest.mark.slow
def test_chaos_soak_randomized_tpch():
    """Randomized fault-point × TPC-H soak: probabilistic device losses
    across the tile stream (plus dispatch-seam losses) must never change
    results vs a clean run, and the fault registry reports exactly which
    seams fired."""
    from tools.tpch_oracle import ORACLES
    from tools.tpch_queries import QUERIES
    from tools.tpchgen import load_tpch

    big = cb.Session(get_config().with_overrides(
        **{"n_segments": 1}))
    load_tpch(big, sf=0.02, seed=7)
    tables = {n: t.to_pandas() for n, t in big.catalog.tables.items()}

    for qn in ("q5", "q9"):
        exp = ORACLES[qn](tables)
        for seed in (1, 2, 3):
            s = _mk(budget=10 << 20,
                    **{"health.retries": 6, "health.backoff_s": 0.01})
            load_tpch(s, sf=0.02, seed=7)
            FI.reset_fault()
            # bounded window: random kills early in the stream, then the
            # arm goes inert so the soak always terminates (each failing
            # attempt consumes exactly one fired hit)
            FI.inject_fault("tile_device_lost", "error", p=0.25,
                            seed=seed, end_hit=10)
            got = s.sql(QUERIES[qn]).to_pandas()
            FI.reset_fault()
            assert s.last_tiled_report["n_tiles"] > 1
            assert len(got) == len(exp), f"{qn} seed={seed}"
            for gc, ec in zip(got.columns, exp.columns):
                g, e = got[gc].to_numpy(), exp[ec].to_numpy()
                if g.dtype.kind == "f" or e.dtype.kind == "f":
                    np.testing.assert_allclose(
                        g.astype(np.float64), e.astype(np.float64),
                        rtol=1e-9, atol=1e-2,
                        err_msg=f"{qn}.{gc} seed={seed}")
                else:
                    np.testing.assert_array_equal(
                        g, e, err_msg=f"{qn}.{gc} seed={seed}")
    # the soak's report of record: the tile seam fired at least once
    # across the run (list_faults survives reset only via 'seen')
    assert "tile_device_lost" in FI.known_fault_points()
