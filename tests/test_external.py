"""External tables + the cbfdist scatter file server (gpfdist analog).

Reference: readable external tables over gpfdist:// / file:// URLs
(src/backend/access/external/external.c, src/bin/gpfdist/gpfdist.c):
every query re-reads the source; gpfdist hands each segment a disjoint
slice so the cluster reads the file exactly once.
"""

import urllib.request

import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.serve.fdist import serve


@pytest.fixture
def data_dir(tmp_path):
    (tmp_path / "t.csv").write_text(
        "".join(f"{i}|{i * 10}|n{i % 3}\n" for i in range(100)))
    return tmp_path


@pytest.fixture
def fdist(data_dir):
    srv, port = serve(str(data_dir))
    yield port
    srv.shutdown()


def test_fdist_scatter_partitions_exactly(data_dir, fdist):
    whole = urllib.request.urlopen(
        f"http://127.0.0.1:{fdist}/t.csv").read()
    stripes = [urllib.request.urlopen(
        f"http://127.0.0.1:{fdist}/t.csv?segment={i}&nseg=4").read()
        for i in range(4)]
    # disjoint and complete: stripe lines interleave back into the file
    all_lines = sorted(b"".join(stripes).splitlines())
    assert all_lines == sorted(whole.splitlines())
    assert all(len(s.splitlines()) == 25 for s in stripes)


def test_fdist_rejects_traversal(data_dir, fdist):
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(
            f"http://127.0.0.1:{fdist}/../../etc/passwd")


def test_external_table_cbfdist(data_dir, fdist):
    s = cb.Session(Config(n_segments=1))
    s.sql(f"create external table ext (k bigint, v bigint, name text) "
          f"location('cbfdist://127.0.0.1:{fdist}/t.csv')")
    df = s.sql("select count(*) as c, sum(v) as s from ext").to_pandas()
    assert df["c"].iloc[0] == 100
    assert df["s"].iloc[0] == sum(i * 10 for i in range(100))
    # joins against ordinary tables work
    s.sql("create table dim (name text, w bigint)")
    s.sql("insert into dim values ('n0', 1), ('n1', 2), ('n2', 3)")
    got = s.sql("select d.w, count(*) as c from ext e, dim d "
                "where e.name = d.name group by d.w order by d.w").to_pandas()
    assert list(got["c"]) == [34, 33, 33]


def test_external_table_rereads_source(data_dir, fdist):
    s = cb.Session(Config(n_segments=1))
    s.sql(f"create external table ext (k bigint, v bigint, name text) "
          f"location('cbfdist://127.0.0.1:{fdist}/t.csv')")
    q = "select count(*) as c from ext"
    assert s.sql(q).to_pandas()["c"].iloc[0] == 100
    with open(data_dir / "t.csv", "a") as f:
        f.write("100|1000|n0\n")
    # the SAME statement text sees the new row (no stale cache)
    assert s.sql(q).to_pandas()["c"].iloc[0] == 101


def test_external_table_file_scheme(data_dir):
    s = cb.Session(Config(n_segments=1))
    s.sql(f"create external table fx (k bigint, v bigint, name text) "
          f"location('file://{data_dir}/t.csv')")
    assert s.sql("select count(*) as c from fx").to_pandas()["c"].iloc[0] \
        == 100


def test_external_table_distributed(data_dir, fdist):
    s = cb.Session(Config(n_segments=8))
    s.sql(f"create external table ext (k bigint, v bigint, name text) "
          f"location('cbfdist://127.0.0.1:{fdist}/t.csv')")
    df = s.sql("select sum(v) as s from ext").to_pandas()
    assert df["s"].iloc[0] == sum(i * 10 for i in range(100))


def test_unreachable_location_does_not_break_other_queries(data_dir):
    s = cb.Session(Config(n_segments=1))
    s.sql("create external table dead (k bigint) "
          "location('cbfdist://127.0.0.1:1/x.csv')")
    s.sql("create table plain (k bigint)")
    s.sql("insert into plain values (1)")
    # unrelated statements never touch the dead source
    assert s.sql("select k from plain").to_pandas()["k"].iloc[0] == 1
    with pytest.raises(Exception):
        s.sql("select k from dead")


def test_dml_into_external_rejected(data_dir, fdist):
    s = cb.Session(Config(n_segments=1))
    s.sql(f"create external table ext (k bigint, v bigint, name text) "
          f"location('cbfdist://127.0.0.1:{fdist}/t.csv')")
    with pytest.raises(Exception, match="external"):
        s.sql("insert into ext values (1, 2, 'x')")


def test_no_trailing_newline_never_merges_rows(tmp_path):
    # a final unterminated line must not concatenate into the next stripe
    (tmp_path / "nt.csv").write_bytes(b"1|10\n2|20\n3|30")
    srv, port = serve(str(tmp_path))
    try:
        s = cb.Session(Config(n_segments=1))
        s.sql(f"create external table nt (k bigint, v bigint) "
              f"location('cbfdist://127.0.0.1:{port}/nt.csv')")
        df = s.sql("select k, v from nt order by k").to_pandas()
        assert [tuple(r) for r in df.to_numpy()] \
            == [(1, 10), (2, 20), (3, 30)]
    finally:
        srv.shutdown()


def test_copy_external_to_file_sees_current_source(data_dir, fdist,
                                                   tmp_path):
    s = cb.Session(Config(n_segments=1))
    s.sql(f"create external table cx (k bigint, v bigint, name text) "
          f"location('cbfdist://127.0.0.1:{fdist}/t.csv')")
    out = tmp_path / "out.csv"
    s.sql(f"copy cx to '{out}'")
    assert len(out.read_text().strip().splitlines()) == 100


def test_file_scheme_missing_is_clean_error(tmp_path):
    s = cb.Session(Config(n_segments=1))
    s.sql(f"create external table gone (k bigint) "
          f"location('file://{tmp_path}/nope.csv')")
    from cloudberry_tpu.plan.binder import BindError
    with pytest.raises(BindError, match="cannot read source"):
        s.sql("select k from gone")


def test_external_table_sreh(data_dir, fdist):
    (data_dir / "bad.csv").write_text("1|10|aa\nxx|20|bb\n3|30|cc\n")
    s = cb.Session(Config(n_segments=1))
    s.sql(f"create external table bx (k bigint, v bigint, name text) "
          f"location('cbfdist://127.0.0.1:{fdist}/bad.csv') "
          f"segment reject limit 5 log errors")
    df = s.sql("select k from bx order by k").to_pandas()
    assert list(df["k"]) == [1, 3]
    assert len(s.read_error_log("bx")) == 1
