"""Native codec (C++ via ctypes) vs numpy fallback — bit-identical."""

import numpy as np
import pytest

from cloudberry_tpu import native


@pytest.fixture(scope="module")
def lib():
    return native.load_native()


def test_native_builds(lib):
    assert lib is not None, "g++ toolchain is in the image; build must work"


def test_dvarint_roundtrip_native(lib):
    rng = np.random.default_rng(0)
    for arr in [
        np.arange(10_000, dtype=np.int64),                      # sorted
        rng.integers(-1 << 40, 1 << 40, 5000),                  # wild
        np.asarray([0, -1, 1, np.iinfo(np.int64).max,
                    np.iinfo(np.int64).min + 1], dtype=np.int64),
        np.zeros(0, dtype=np.int64),
    ]:
        buf = native.dvarint_encode(arr)
        out = native.dvarint_decode(buf, len(arr))
        np.testing.assert_array_equal(out, arr)


def test_native_matches_fallback_bits(lib):
    rng = np.random.default_rng(1)
    arr = rng.integers(-1 << 30, 1 << 30, 2000).astype(np.int64)
    assert native.dvarint_encode(arr) == native._dvarint_encode_np(arr)
    buf = native.dvarint_encode(arr)
    np.testing.assert_array_equal(native._dvarint_decode_np(buf, len(arr)),
                                  native.dvarint_decode(buf, len(arr)))


def test_dvarint_compresses_sorted_keys(lib):
    arr = np.arange(100_000, dtype=np.int64)  # the key-column shape
    buf = native.dvarint_encode(arr)
    assert len(buf) < arr.nbytes / 7  # ~1 byte/value vs 8


def test_corrupt_stream_detected(lib):
    arr = np.arange(100, dtype=np.int64)
    buf = native.dvarint_encode(arr)
    with pytest.raises(ValueError):
        native.dvarint_decode(buf[: len(buf) // 2], 100)


def test_csv_parse_columns(lib):
    buf = b"1|foo|10.25\n2|bar|-3.5\n30|baz|0.07\n"
    ids = native.parse_int64_column(buf, 0)
    np.testing.assert_array_equal(ids, [1, 2, 30])
    vals = native.parse_decimal_column(buf, 2, scale=2)
    np.testing.assert_array_equal(vals, [1025, -350, 7])
    # fallback agrees
    lib2 = native._lib
    try:
        native._lib = None
        native._tried = True
        np.testing.assert_array_equal(native.parse_int64_column(buf, 0), ids)
        np.testing.assert_array_equal(
            native.parse_decimal_column(buf, 2, scale=2), vals)
    finally:
        native._lib = lib2


def test_micropartition_uses_dvarint(tmp_path):
    from cloudberry_tpu import types as T
    from cloudberry_tpu.storage import micropartition as mp
    from cloudberry_tpu.types import Schema

    schema = Schema.of(k=T.INT64, r=T.INT64)
    rng = np.random.default_rng(2)
    data = {"k": np.arange(50_000, dtype=np.int64),
            "r": rng.integers(-1 << 62, 1 << 62, 50_000)}  # incompressible
    path = str(tmp_path / "p.cbmp")
    footer = mp.write_micropartition(path, data, schema)
    kcol = next(c for c in footer["columns"] if c["name"] == "k")
    assert kcol["encoding"] == "dvarint"
    rcol = next(c for c in footer["columns"] if c["name"] == "r")
    assert rcol["encoding"] == "raw"  # dvarint would bloat random data
    got = mp.read_columns(path)
    np.testing.assert_array_equal(got["k"], data["k"])
    np.testing.assert_array_equal(got["r"], data["r"])
