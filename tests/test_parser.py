import pytest

from cloudberry_tpu.sql import ast
from cloudberry_tpu.sql.parser import ParseError, parse_sql


def test_simple_select():
    s = parse_sql("select a, b + 1 as c from t where a > 10 order by a desc limit 5")
    assert isinstance(s, ast.Select)
    assert len(s.items) == 2
    assert s.items[1].alias == "c"
    assert isinstance(s.where, ast.BinOp) and s.where.op == ">"
    assert not s.order_by[0].ascending
    assert s.limit == 5


def test_join_syntax():
    s = parse_sql("""select t1.a from t1 inner join t2 on t1.id = t2.id
                     left join t3 on t2.x = t3.x""")
    j = s.from_refs[0]
    assert isinstance(j, ast.JoinRef) and j.kind == "left"
    assert isinstance(j.left, ast.JoinRef) and j.left.kind == "inner"


def test_case_between_in_like():
    s = parse_sql("""select case when a between 1 and 2 then 'x' else 'y' end
                     from t where b in ('p','q') and c like 'ab%' and d not in (1,2)""")
    c = s.items[0].expr
    assert isinstance(c, ast.CaseExpr)
    assert isinstance(c.whens[0][0], ast.Between)
    w = s.where
    assert isinstance(w, ast.BinOp) and w.op == "and"


def test_date_interval_extract():
    s = parse_sql("""select extract(year from o_orderdate)
                     from orders where o_orderdate < date '1995-03-15' + interval '1' year""")
    assert isinstance(s.items[0].expr, ast.ExtractExpr)
    add = s.where.right
    assert isinstance(add, ast.BinOp) and isinstance(add.right, ast.IntervalLit)
    assert add.right.unit == "year"


def test_subqueries():
    s = parse_sql("""select a from t where exists (select 1 from u where u.x = t.a)
                     and b > (select avg(b) from t) and c in (select c from v)""")
    w = s.where
    # and(and(exists, >), in)
    assert isinstance(w.right, ast.InSubquery)
    assert isinstance(w.left.left, ast.Exists)
    assert isinstance(w.left.right.right, ast.ScalarSubquery)


def test_derived_table():
    s = parse_sql("select x from (select a as x from t) as sub where x > 0")
    d = s.from_refs[0]
    assert isinstance(d, ast.DerivedTable) and d.alias == "sub"


def test_create_table_distributed():
    s = parse_sql("""create table lineitem (
        l_orderkey bigint not null, l_price decimal(12,2), l_comment varchar(44)
    ) distributed by (l_orderkey)""")
    assert isinstance(s, ast.CreateTable)
    assert s.distribution == "hash" and s.dist_keys == ("l_orderkey",)
    assert s.columns[1].scale == 2
    r = parse_sql("create table n (x int) distributed replicated")
    assert r.distribution == "replicated"


def test_insert_values():
    s = parse_sql("insert into t (a, b) values (1, 'x'), (2, 'y')")
    assert isinstance(s, ast.InsertValues)
    assert len(s.rows) == 2 and s.columns == ["a", "b"]


def test_explain():
    s = parse_sql("explain select 1")
    assert isinstance(s, ast.Explain)


def test_count_distinct_and_star():
    s = parse_sql("select count(*), count(distinct a), sum(b) from t")
    f0, f1, f2 = (i.expr for i in s.items)
    assert f0.star and not f1.star and f1.distinct
    assert f2.name == "sum"


def test_operator_precedence():
    s = parse_sql("select a + b * c - d from t")
    e = s.items[0].expr
    # ((a + (b*c)) - d)
    assert e.op == "-" and e.left.op == "+" and e.left.right.op == "*"


def test_errors():
    with pytest.raises(ParseError):
        parse_sql("select from t")
    with pytest.raises(ParseError):
        parse_sql("select a from t where")
    with pytest.raises(ParseError):
        parse_sql("selec a from t")
    with pytest.raises(ParseError):
        parse_sql("select a from t; extra garbage")


def test_string_escapes_and_comments():
    s = parse_sql("""select 'it''s' -- trailing comment
                     /* block */ from t""")
    assert s.items[0].expr.value == "it's"


def test_all_tpch_queries_parse():
    from tools.tpch_queries import QUERIES

    for name, sql in QUERIES.items():
        stmt = parse_sql(sql)
        assert isinstance(stmt, ast.Select), name
