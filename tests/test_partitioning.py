"""Table partitioning (PARTITION BY RANGE/LIST) + dynamic elimination.

Reference: the partition grammar (gram.y), partition-pure storage with
stats-based static elimination (src/backend/partitioning,
contrib/pax_storage sparse filters), and join-driven dynamic partition
elimination (nodePartitionSelector.c, nodeDynamicSeqscan.c). Here a
partitioned table routes stored writes into partition-pure micro-partition
files, so manifest min/max stats are exact partition bounds; elimination
reuses the scan-pruning machinery and the PartitionSelector analog runs the
small build side host-side first.
"""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config


@pytest.fixture
def sess(tmp_path):
    return cb.Session(Config(n_segments=1).with_overrides(**{
        "storage.root": str(tmp_path / "store"),
        "storage.rows_per_partition": 1 << 16,
    }))


def _mk_fact(s, n=3000):
    s.sql("create table fact (k bigint, d bigint, v bigint) "
          "partition by range (d) (start 0 end 100 every 10)")
    rng = np.random.default_rng(11)
    rows = ", ".join(
        f"({int(rng.integers(0, 50))}, {int(rng.integers(0, 100))}, {i})"
        for i in range(n))
    s.sql(f"insert into fact values {rows}")


def _fresh(sess):
    """Re-open the store so tables register cold (scan path hits files)."""
    return cb.Session(sess.config)


def test_partition_spec_persists(sess):
    _mk_fact(sess)
    s2 = _fresh(sess)
    t = s2.catalog.table("fact")
    assert t.partition_spec == ("range", "d", 0, 100, 10)
    man = s2.store.read_manifest("fact")
    # partition-pure files: every file's d-stats stay inside ONE bucket
    assert man["partition_spec"] == ["range", "d", 0, 100, 10]
    for p in man["partitions"]:
        lo, hi = p["stats"]["d"]
        assert hi - lo < 10 and (lo // 10) == (hi // 10)
        assert "pkey" in p


def test_static_elimination(sess):
    _mk_fact(sess)
    s2 = _fresh(sess)
    out = s2.sql("select count(*) as c from fact where d >= 20 and d < 30")
    df = out.to_pandas()
    exp = s2.explain("select count(*) from fact where d >= 20 and d < 30")
    # only 1 of 10 range buckets survives pruning
    assert "parts 1/10" in exp
    assert df["c"].iloc[0] > 0


def test_list_partitioning(sess):
    sess.sql("create table lp (r bigint, v bigint) partition by list (r)")
    sess.sql("insert into lp values " +
             ", ".join(f"({i % 4}, {i})" for i in range(400)))
    s2 = _fresh(sess)
    man = s2.store.read_manifest("lp")
    assert sorted({p["pkey"] for p in man["partitions"]}) \
        == ["l0", "l1", "l2", "l3"]
    exp = s2.explain("select count(*) from lp where r = 2")
    assert "parts 1/4" in exp
    assert s2.sql("select count(*) as c from lp where r = 2") \
        .to_pandas()["c"].iloc[0] == 100


def test_out_of_range_goes_to_default(sess):
    sess.sql("create table dr (d bigint) "
             "partition by range (d) (start 0 end 10 every 5)")
    sess.sql("insert into dr values (1), (7), (99), (-3)")
    s2 = _fresh(sess)
    man = s2.store.read_manifest("dr")
    keys = sorted(p["pkey"] for p in man["partitions"])
    assert keys == ["default", "r0", "r5"]
    # no rows are ever lost to routing
    assert s2.sql("select count(*) as c from dr").to_pandas()["c"].iloc[0] == 4


def test_dynamic_partition_elimination(sess):
    _mk_fact(sess)
    sess.sql("create table dim (d bigint, tag bigint)")
    sess.sql("insert into dim values (3, 1), (17, 1), (42, 2)")
    s2 = _fresh(sess)
    q = ("select count(*) as c from fact, dim "
         "where fact.d = dim.d and dim.tag = 1")
    exp = s2.explain(q)
    # build side has d in {3, 17} → only buckets r0 and r10 survive
    assert "partition-selector-skip 8" in exp, exp
    got = s2.sql(q).to_pandas()["c"].iloc[0]
    # oracle straight from the store
    cols, _, _ = s2.store.scan("fact", ["d"])
    want = int(np.isin(cols["d"], [3, 17]).sum())
    assert got == want


def test_dynamic_elimination_not_applied_to_left_join(sess):
    _mk_fact(sess)
    sess.sql("create table dim (d bigint, tag bigint)")
    sess.sql("insert into dim values (3, 1)")
    s2 = _fresh(sess)
    # LEFT join preserves unmatched probe rows — the selector must stay off
    q = ("select count(*) as c from fact left join dim on fact.d = dim.d")
    assert "partition-selector-skip" not in s2.explain(q)
    assert s2.sql(q).to_pandas()["c"].iloc[0] == 3000


def test_partitioned_results_match_unpartitioned(sess):
    _mk_fact(sess)
    sess.sql("create table flat (k bigint, d bigint, v bigint)")
    sess.sql("insert into flat select k, d, v from fact")
    s2 = _fresh(sess)
    a = s2.sql("select d, sum(v) as s from fact where d < 37 "
               "group by d order by d").to_pandas()
    b = s2.sql("select d, sum(v) as s from flat where d < 37 "
               "group by d order by d").to_pandas()
    assert a.equals(b)


def test_partition_column_must_exist():
    s = cb.Session(Config(n_segments=1))
    with pytest.raises(Exception):
        s.sql("create table bad (a bigint) partition by range (zz) "
              "(start 0 end 10 every 5)")
