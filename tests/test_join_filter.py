"""Digest runtime join filters (bloom + packed-key min/max digests
broadcast before the probe's redistribute — config.join_filter): results
must be BIT-IDENTICAL with the filter on or off (false positives only
ever let extra rows through), the wire must carry fewer rows, and the
TPC-H sweep pins parity at 1 and 8 segments."""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.exec import kernels as K
from cloudberry_tpu.plan import nodes as N

import jax.numpy as jnp

# digest-forcing knobs: the exact filter is disabled (threshold 0) so any
# filter in the plan is the bloom+minmax digest; small bloom so the cost
# rule fires at test-sized tables
_DIGEST = {
    "planner.broadcast_threshold": 0,
    "planner.runtime_filter_threshold": 0,
    "join_filter.bloom_bits": 4096,
}
_OFF = {**_DIGEST, "join_filter.enabled": False}


def _mk(nseg=8, **ov):
    s = cb.Session(Config(n_segments=nseg).with_overrides(**ov))
    s.sql("create table fact (k bigint, grp bigint, v bigint) "
          "distributed by (k)")
    s.sql("create table dim (d bigint, p bigint) distributed by (d)")
    n = 3000
    rows = ",".join(f"({i}, {i % 3000}, {i % 7})" for i in range(n))
    s.sql(f"insert into fact values {rows}")
    rows = ",".join(f"({i}, {i * 2})" for i in range(300))
    s.sql(f"insert into dim values {rows}")
    return s


def _plan(s, sql):
    from cloudberry_tpu.plan.binder import Binder
    from cloudberry_tpu.plan.planner import _optimize
    from cloudberry_tpu.sql.parser import parse_sql

    return _optimize(Binder(s.catalog, s.config).bind_query(
        parse_sql(sql)), s)


def _find(plan, kind):
    out = []

    def walk(n):
        if isinstance(n, kind):
            out.append(n)
        for c in n.children():
            walk(c)

    walk(plan)
    return out


Q = ("select grp, count(*) as n from fact, dim where grp = d "
     "group by grp order by grp")


def test_digest_filter_inserted_and_results_match():
    s = _mk(**_DIGEST)
    plan = _plan(s, Q)
    rfs = _find(plan, N.PRuntimeFilter)
    assert rfs and all(r.mode == "digest" for r in rfs)
    assert rfs[0].bloom_bits == 4096  # power-of-two clamp kept the knob
    with_f = s.sql(Q).to_pandas()
    s2 = _mk(**_OFF)
    assert not _find(_plan(s2, Q), N.PRuntimeFilter)
    without = s2.sql(Q).to_pandas()
    assert with_f.values.tolist() == without.values.tolist()
    assert with_f.grp.tolist() == list(range(300))


def test_digest_reduces_shipped_rows():
    s = _mk(**_DIGEST)
    s.sql(Q)
    pre = s.stmt_log.counter("jf_rows_in")
    post = s.stmt_log.counter("jf_rows_out")
    assert pre == 3000
    # exactly 300 true partners; bloom FPs may add a few — never more
    # than the unfiltered probe, and the reduction must be substantial
    assert 300 <= post < pre / 2


def test_digest_seeds_lower_capacity_rung():
    """The survivor estimate may undercut the exact (unfiltered) bucket
    bound — wire buffers shrink; skew/FP overflow would promote back up
    the ladder, so correctness never depends on the estimate."""
    def probe_rung(ov):
        s = _mk(**ov)
        plan = _plan(s, Q)
        m = [m for m in _find(plan, N.PMotion)
             if m.kind == "redistribute"
             and any(sc.table_name == "fact"
                     for sc in _find(m, N.PScan))][0]
        return m.bucket_cap
    assert probe_rung(_DIGEST) < probe_rung(_OFF)


def test_explain_shows_digest():
    s = _mk(**_DIGEST)
    assert "RuntimeFilter digest(bloom=4096)" in s.explain(Q)


def test_digest_with_null_probe_keys():
    s = _mk(**_DIGEST)
    s.sql("insert into fact values (9000, null, 1)")
    out = s.sql(Q).to_pandas()
    assert out.grp.tolist() == list(range(300))


def test_bloom_false_positive_rate_property():
    """Kernel-level property: zero false negatives, and the observed FPR
    on non-members stays near theory ((1-e^{-kn/m})^k)."""
    import math

    rng = np.random.default_rng(3)
    bits, k, n = 1 << 15, 3, 4096
    # disjoint value ranges: membership is decided by range, so dup draws
    # are harmless and no non-member can alias a member
    members = rng.integers(0, 1 << 30, size=n)
    non = (1 << 30) + rng.integers(0, 1 << 30, size=8192)
    mu = [K.sort_key_u64(jnp.asarray(members, dtype=jnp.int64))]
    words = K.bloom_build(mu, jnp.ones(n, dtype=jnp.bool_), bits, k)
    hit_m = K.bloom_test(words, mu, bits, k)
    assert bool(np.asarray(hit_m).all()), "false negative"
    nu = [K.sort_key_u64(jnp.asarray(non, dtype=jnp.int64))]
    fpr = float(np.asarray(K.bloom_test(words, nu, bits, k)).mean())
    theory = (1.0 - math.exp(-k * n / bits)) ** k
    assert fpr <= 3.0 * theory + 0.01, (fpr, theory)


def test_bloom_bits_pow2_clamp():
    assert K.bloom_bits_pow2(0) == 64
    assert K.bloom_bits_pow2(4096) == 4096
    assert K.bloom_bits_pow2(5000) == 8192


# ---------------------------------------------------------- TPC-H parity

# representative subset tier-1 (join-heavy shapes); the full both-segment
# sweep rides the slow tier like the generic-parity pin
SUBSET = ["q3", "q5", "q10"]


def _tpch_pair(nseg):
    from tools.tpchgen import load_tpch

    on = cb.Session(Config(n_segments=nseg).with_overrides(**_DIGEST))
    off = cb.Session(Config(n_segments=nseg).with_overrides(**_OFF))
    for s in (on, off):
        load_tpch(s, sf=0.01, seed=7)
    return on, off


@pytest.fixture(scope="module")
def tpch_pair8():
    return _tpch_pair(8)


@pytest.fixture(scope="module")
def tpch_pair1():
    return _tpch_pair(1)


def _assert_bit_identical(got, want, name):
    gsel, wsel = np.asarray(got.sel), np.asarray(want.sel)
    assert int(gsel.sum()) == int(wsel.sum()), name
    gcols, wcols = got.decoded_columns(), want.decoded_columns()
    assert list(gcols) == list(wcols), name
    for cname in gcols:
        g, w = np.asarray(gcols[cname]), np.asarray(wcols[cname])
        if g.dtype == object or w.dtype == object:
            np.testing.assert_array_equal(g, w, err_msg=f"{name}.{cname}")
        else:
            np.testing.assert_array_equal(
                g.view(np.uint8) if g.dtype.kind == "f" else g,
                w.view(np.uint8) if w.dtype.kind == "f" else w,
                err_msg=f"{name}.{cname}")


@pytest.mark.parametrize("qname", SUBSET)
def test_tpch_digest_parity_dist8(tpch_pair8, qname):
    from tools.tpch_queries import QUERIES

    on, off = tpch_pair8
    _assert_bit_identical(on.sql(QUERIES[qname]), off.sql(QUERIES[qname]),
                          qname)


@pytest.mark.parametrize("qname", SUBSET)
def test_tpch_digest_parity_single(tpch_pair1, qname):
    from tools.tpch_queries import QUERIES

    on, off = tpch_pair1
    _assert_bit_identical(on.sql(QUERIES[qname]), off.sql(QUERIES[qname]),
                          qname)


@pytest.mark.slow
@pytest.mark.parametrize("nseg", [1, 8])
def test_tpch_digest_parity_full_sweep(nseg):
    from tools.tpch_queries import QUERIES

    on, off = _tpch_pair(nseg)
    for qname in sorted(QUERIES):
        _assert_bit_identical(on.sql(QUERIES[qname]),
                              off.sql(QUERIES[qname]), f"{qname}@{nseg}")


def test_ic_bench_join_filter_acceptance():
    """The acceptance pin: ic_bench --join-filter on a skewed PK-FK
    shuffle shows ≥30% probe-row reduction, and the repeated-statement
    microbench shows the join-index cache serving the build argsort
    (hits > 0) with ZERO recompiles."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-m", "tools.ic_bench", "--join-filter",
         "--rows", "4000", "--dim-rows", "400", "--reps", "1"],
        capture_output=True, text=True, timeout=540, cwd=repo,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    assert out.returncode == 0, out.stderr[-2000:]
    recs = [json.loads(ln) for ln in out.stdout.splitlines()
            if ln.startswith("{")]
    summary = [r for r in recs if r["mode"] == "join_filter-summary"][0]
    assert summary["row_reduction"] >= 0.3
    assert summary["join_index_hits"] > 0
    assert summary["repeat_compiles"] == 0
    on = [r for r in recs if r.get("filter") == "on"][0]
    off = [r for r in recs if r.get("filter") == "off"][0]
    assert on["probe_rows_shipped"] < off["probe_rows_shipped"]


def test_bench_join_filter_context():
    """bench.py's per-query join_filter record: filters counted by mode
    with their estimated reduction, join-index-eligible joins counted,
    live counters attached."""
    import bench
    from tools.tpchgen import load_tpch

    s = cb.Session(Config())
    load_tpch(s, sf=0.01, seed=3,
              tables=["lineitem", "orders", "part", "partsupp",
                      "supplier", "nation"])
    jf = bench.join_filter_context(s, ["q9"], nseg=8)
    rec = jf["per_query"]["q9"]
    assert rec["filters_exact"] + rec["filters_digest"] >= 1
    assert rec["est_rows_in"] >= rec["est_rows_out"] > 0
    assert rec["indexed_joins"] >= 1
    assert "join_index_builds" in jf["counters"]
