"""Transparent data encryption (utils/tde.py) — at-rest protection.

Micro-partition files and manifests encrypt whole under the cluster key
(footers and manifests carry min/max stats and string dictionaries —
data, not metadata). Wrong key -> MAC failure, never silent garbage; no
key -> refusal; plaintext on-disk bytes must not contain row values.
"""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config
from cloudberry_tpu.utils.tde import TdeError


def _cfg(tmp_path, key=None):
    over = {"storage.root": str(tmp_path)}
    if key is not None:
        over["storage.encryption_key"] = key
    return get_config().with_overrides(**over)


def _populate(cfg):
    s = cb.Session(cfg)
    s.sql("create table sec (id bigint, name text)")
    s.sql("insert into sec values (7001, 'topsecretvalue'), "
          "(7002, 'alsosecret')")
    return s


def test_roundtrip_under_encryption(tmp_path):
    cfg = _cfg(tmp_path, "cluster-key-1")
    _populate(cfg)
    # a fresh session with the key reads everything back
    s2 = cb.Session(cfg)
    df = s2.sql("select id, name from sec order by id").to_pandas()
    assert df["name"].tolist() == ["topsecretvalue", "alsosecret"]
    # DML + pruning paths work through the cipher
    s2.sql("update sec set name = 'renamed' where id = 7001")
    s3 = cb.Session(cfg)
    assert s3.sql("select name from sec where id = 7001 "
                  ).to_pandas()["name"][0] == "renamed"


def test_no_plaintext_on_disk(tmp_path):
    _populate(_cfg(tmp_path, "cluster-key-1"))
    blob = b""
    for p in tmp_path.rglob("*"):
        if p.is_file():
            blob += p.read_bytes()
    assert b"topsecretvalue" not in blob
    assert b"7001" not in blob  # manifests/stats leak no values either


def test_plaintext_store_does_leak_for_contrast(tmp_path):
    """Sanity check on the assertion above: without TDE the dictionary IS
    on disk in the clear."""
    _populate(_cfg(tmp_path))
    blob = b""
    for p in tmp_path.rglob("*"):
        if p.is_file():
            blob += p.read_bytes()
    assert b"topsecretvalue" in blob


def test_wrong_or_missing_key_refused(tmp_path):
    _populate(_cfg(tmp_path, "cluster-key-1"))
    with pytest.raises(TdeError, match="no storage.encryption_key"):
        cb.Session(_cfg(tmp_path)).sql("select * from sec")
    with pytest.raises(TdeError, match="wrong encryption key"):
        cb.Session(_cfg(tmp_path, "not-the-key")).sql("select * from sec")
