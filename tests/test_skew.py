"""Skew strategies: GATHER_SINGLE final aggregation and exact plan-time
bucket sizing for base-scan redistributes (VERDICT #8).

The reference escapes skew via planner stats and GATHER_SINGLE motions
(plannodes.h:1638); here small-capacity final aggs gather instead of
redistributing (hash-space skew immune), and a redistribute of a (filtered)
base scan sizes its buckets from the table's TRUE per-(source, destination)
counts — any key skew is absorbed exactly instead of erroring."""

import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.plan import nodes as N


def _find(plan, kind):
    out = []

    def walk(n):
        if isinstance(n, kind):
            out.append(n)
        for c in n.children():
            walk(c)

    walk(plan)
    return out


def _plan(s, sql):
    from cloudberry_tpu.plan.binder import Binder
    from cloudberry_tpu.plan.planner import _optimize
    from cloudberry_tpu.sql.parser import parse_sql

    return _optimize(Binder(s.catalog).bind_query(parse_sql(sql)), s)


def test_many_group_aggregate_gather_single():
    """5000 distinct groups across 8 segments overflowed the partial
    redistribute's buckets (hash-space skew); GATHER_SINGLE completes."""
    s = cb.Session(Config(n_segments=8))
    s.sql("create table sk (k bigint, g bigint, v bigint) "
          "distributed by (k)")
    s.sql("insert into sk values " +
          ",".join(f"({i}, {i}, {i % 7})" for i in range(5000)))
    q = "select g, sum(v) as sv from sk group by g"
    plan = _plan(s, q)
    gathers = [m for m in _find(plan, N.PMotion) if m.kind == "gather"]
    assert gathers, "expected a GATHER_SINGLE final agg"
    out = s.sql(q + " order by g").to_pandas()
    assert len(out) == 5000
    assert out.sv.tolist() == [i % 7 for i in range(5000)]


def test_gather_single_disabled_falls_back():
    cfg = Config(n_segments=8).with_overrides(
        **{"planner.gather_single_threshold": 0,
           "interconnect.capacity_factor": 8.0})
    s = cb.Session(cfg)
    s.sql("create table sk (k bigint, g bigint, v bigint) "
          "distributed by (k)")
    s.sql("insert into sk values " +
          ",".join(f"({i}, {i}, 1)" for i in range(1000)))
    out = s.sql("select count(*) as n from "
                "(select g from sk group by g) x").to_pandas()
    assert out.n[0] == 1000


def test_hot_key_join_redistribute_completes():
    """75% of probe rows share ONE join key: the redistribute sizes its
    buckets from the true per-destination counts and completes."""
    cfg = Config(n_segments=8).with_overrides(
        **{"planner.broadcast_threshold": 0,
           "planner.runtime_filter_threshold": 0})
    s = cb.Session(cfg)
    s.sql("create table j1 (a bigint, key bigint) distributed by (a)")
    s.sql("create table j2 (b bigint, key bigint, w bigint) "
          "distributed by (b)")
    s.sql("insert into j1 values " +
          ",".join(f"({i}, {0 if i < 1500 else i})" for i in range(2000)))
    s.sql("insert into j2 values " +
          ",".join(f"({i}, {i}, {i})" for i in range(2000)))
    out = s.sql("select sum(j2.w) as sw from j1, j2 "
                "where j1.key = j2.key").to_pandas()
    assert out.sw[0] == 0 * 1500 + sum(range(1500, 2000))


def test_hot_key_join_with_runtime_filter_default_config():
    """Regression: the exact bucket bound must stay authoritative when a
    runtime filter is present — an estimate must never undercut it."""
    cfg = Config(n_segments=8).with_overrides(
        **{"planner.broadcast_threshold": 0})  # runtime filter stays on
    s = cb.Session(cfg)
    s.sql("create table j1 (a bigint, key bigint) distributed by (a)")
    s.sql("create table j2 (b bigint, key bigint, w bigint) "
          "distributed by (b)")
    s.sql("insert into j1 values " +
          ",".join(f"({i}, {0 if i < 1500 else i})" for i in range(2000)))
    s.sql("insert into j2 values " +
          ",".join(f"({i}, {i}, {i})" for i in range(2000)))
    out = s.sql("select sum(j2.w) as sw from j1, j2 "
                "where j1.key = j2.key").to_pandas()
    assert out.sw[0] == sum(range(1500, 2000))


def test_skewed_window_partition():
    """Window partition redistribute on a skewed key completes (exact
    bucket sizing covers the scan-under-motion shape)."""
    cfg = Config(n_segments=8)
    s = cb.Session(cfg)
    s.sql("create table w (k bigint, g bigint, v bigint) "
          "distributed by (k)")
    s.sql("insert into w values " +
          ",".join(f"({i}, {0 if i < 900 else i}, {i % 5})"
                   for i in range(1200)))
    out = s.sql("select max(n) as mx from (select count(*) over "
                "(partition by g) as n from w) x").to_pandas()
    assert out.mx[0] == 900
