"""Micro-batch dispatcher (sched/dispatcher.py, the gang-dispatch analog):
coalescing into stacked launches, deadlines, backpressure, fault seams,
and the serving integration + bench smoke."""

import threading
import time

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.sched import (Dispatcher, SchedDeadline,
                                  SchedQueueFull, paramplan)
from cloudberry_tpu.utils.faultinject import (InjectedFault, inject_fault,
                                              reset_fault)


@pytest.fixture(autouse=True)
def _clean_faults():
    reset_fault()
    yield
    reset_fault()


def _session(rows=60_000, **over):
    s = cb.Session(Config().with_overrides(**over))
    s.sql("create table pts (k bigint, v bigint) distributed by (k)")
    s.catalog.table("pts").set_data({
        "k": np.arange(rows, dtype=np.int64),
        "v": (np.arange(rows, dtype=np.int64) * 3) % 997}, {})
    return s


def test_run_batch_matches_sequential():
    s = _session()
    keys = [3, 1414, 500, 42, 777, 12, 59999]
    sqls = [f"select k, v from pts where k = {k}" for k in keys]
    outs = paramplan.run_batch(s, sqls)
    assert outs is not None and len(outs) == len(keys)
    for k, batch in zip(keys, outs):
        df = batch.to_pandas()
        assert list(df.k) == [k] and list(df.v) == [(k * 3) % 997]
    # a second batch reuses the rung executable: zero compiles
    c0 = s.stmt_log.counter("compiles")
    outs2 = paramplan.run_batch(
        s, [f"select k, v from pts where k = {k}" for k in
            (9, 10, 11, 12, 13, 14, 15)])
    assert outs2 is not None
    assert s.stmt_log.counter("compiles") == c0


def test_dispatcher_coalesces_and_answers():
    s = _session(**{"sched.max_batch": 8, "sched.tick_s": 0.01})
    d = Dispatcher(s).start()
    try:
        results = {}
        errors = []

        def client(k):
            try:
                out = d.submit(f"select k, v from pts where k = {k}")
                results[k] = out.to_pandas().v[0]
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(100, 124)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors
        assert results == {k: (k * 3) % 997 for k in range(100, 124)}
        snap = d.snapshot()
        assert snap["batches"] >= 1
        assert snap["batched_requests"] >= 2
        assert 0 < snap["avg_occupancy"] <= 1
    finally:
        d.stop()


def test_dispatcher_solo_and_write_fallback():
    """Non-parameterizable statements ride alone through ordinary
    dispatch — same results, no batching required."""
    s = _session()
    d = Dispatcher(s).start()
    try:
        out = d.submit("select count(*) as n from pts")
        assert out.to_pandas().n[0] == 60_000
    finally:
        d.stop()


def test_deadline_expires_before_dispatch():
    s = _session(**{"sched.tick_s": 0.05})
    d = Dispatcher(s).start()
    try:
        with pytest.raises(SchedDeadline):
            d.submit("select k from pts where k = 5", deadline_s=0.0)
    finally:
        d.stop()


def test_backpressure_bounded_queue():
    s = _session(**{"sched.max_queue": 1, "sched.tick_s": 0.0})
    # stall the worker inside group formation so the queue stays full
    inject_fault("sched_coalesce", "sleep", sleep_s=1.0)
    d = Dispatcher(s).start()
    try:
        t1 = threading.Thread(
            target=lambda: d.submit("select k from pts where k = 1"))
        t1.start()
        time.sleep(0.15)  # worker picked req 1 and is sleeping
        t2 = threading.Thread(
            target=lambda: d.submit("select k from pts where k = 2"))
        t2.start()
        time.sleep(0.15)  # req 2 occupies the single queue slot
        with pytest.raises(SchedQueueFull):
            d.submit("select k from pts where k = 3",
                     enqueue_wait_s=0.05)
        t1.join(timeout=60)
        t2.join(timeout=60)
        assert d.snapshot()["rejected"] == 1
    finally:
        d.stop()


def test_enqueue_fault_point():
    s = _session()
    d = Dispatcher(s).start()
    try:
        inject_fault("sched_enqueue", "error")
        with pytest.raises(InjectedFault):
            d.submit("select k from pts where k = 1")
        reset_fault("sched_enqueue")
        assert d.submit("select k from pts where k = 1") is not None
    finally:
        d.stop()


def test_flush_fault_falls_back_sequentially():
    """A fault at the batched-flush seam must not lose requests: the
    dispatcher surfaces the error per request (health retry semantics
    stay with the caller)."""
    s = _session()
    d = Dispatcher(s).start()
    try:
        inject_fault("sched_flush", "error", start_hit=1, end_hit=1)
        results, errors = [], []

        def client(k):
            try:
                results.append(
                    d.submit(f"select k, v from pts where k = {k}"))
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        # every request got SOME answer: a result or the injected error
        assert len(results) + len(errors) == 8
    finally:
        d.stop()


def test_server_dispatch_end_to_end():
    """Wire-level: a sched-enabled server batches concurrent reads;
    writes and metadata keep working; meta "sched" exposes the queue."""
    from cloudberry_tpu.serve import Client, Server

    s = _session(**{"sched.enabled": True, "sched.tick_s": 0.005})
    with Server(session=s) as srv:
        with Client(srv.host, srv.port) as c:
            c.sql("create table aux (a int) distributed by (a)")
            c.sql("insert into aux values (1), (2)")
            assert c.sql("select count(*) as n from aux")["rows"] == [[2]]
        results, errors = [], []

        def client(wid):
            try:
                with Client(srv.host, srv.port) as c:
                    for i in range(6):
                        k = wid * 100 + i
                        out = c.sql(f"select v from pts where k = {k}")
                        assert out["rows"] == [[(k * 3) % 997]]
                        results.append(k)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=client, args=(w,))
                   for w in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not errors and len(results) == 36
        with Client(srv.host, srv.port) as c:
            sched = c.meta("sched")
        assert sched["generic_plans"] is True
        assert sched["dispatcher"]["enqueued"] >= 36
        assert sched["counters"].get("compiles", 0) >= 1
    # after stop the dispatcher refuses cleanly
    with pytest.raises(RuntimeError):
        s._dispatcher.submit("select 1")


def test_serve_bench_smoke():
    """CPU smoke of the closed-loop bench (tier-1 wiring for the QPS
    acceptance tool): both modes run, produce sane rows, and batched
    mode actually batches."""
    import tools.serve_bench as SB

    direct = SB.run_mode("direct", "point", clients=2, duration_s=0.8,
                         rows=50_000, tick_s=0.002, max_batch=8)
    batched = SB.run_mode("batched", "point", clients=2, duration_s=0.8,
                          rows=50_000, tick_s=0.002, max_batch=8)
    assert direct["requests"] > 0 and batched["requests"] > 0
    assert direct["batches"] == 0
    assert batched["batches"] >= 1
    # generic plans: warmup compiled; the measured loop adds only rung
    # compiles (bounded by log2(max_batch)), never per-literal compiles
    assert direct["compiles"] == 0
    assert batched["compiles"] <= 4
    assert SB.csv_row(direct).startswith("direct,point,2,")
