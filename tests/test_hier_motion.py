"""Topology-aware two-level Motion (ISSUE 14): hierarchical
all_to_all / gather / broadcast over simulated ICI/DCN with host-local
combine, pinned BIT-IDENTICAL to the flat transport.

The CPU stand-in for a multi-host cluster is the env-forced process
grouping (``CBTPU_FORCE_HOSTS`` partitions the 8-virtual-device mesh
into contiguous uniform hosts — parallel/mesh.py HostTopology); the
real 2-process cluster variant lives in tests/test_multihost.py. The
transport contract is exact: ``hier_all_to_all`` returns the SAME
buffer ``lax.all_to_all`` would (route words reproduce the flat slot
layout), so every parity pin below is equality, not tolerance."""

import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config

NSEG = 8


@pytest.fixture()
def hosts4(monkeypatch):
    monkeypatch.setenv("CBTPU_FORCE_HOSTS", "4")
    return 4


@pytest.fixture()
def hosts2(monkeypatch):
    monkeypatch.setenv("CBTPU_FORCE_HOSTS", "2")
    return 2


def _mk_session(hier: str, nseg: int = NSEG, **over):
    cfg = Config(n_segments=nseg).with_overrides(**{
        "interconnect.hierarchical": hier, **over})
    s = cb.Session(cfg)
    rng = np.random.default_rng(11)
    s.sql("CREATE TABLE dim (d BIGINT, g BIGINT) DISTRIBUTED BY (d)")
    s.sql("CREATE TABLE fact (k BIGINT, grp BIGINT, v BIGINT) "
          "DISTRIBUTED BY (k)")
    s.catalog.table("dim").set_data(
        {"d": np.arange(100), "g": np.arange(100) % 6})
    s.catalog.table("fact").set_data(
        {"k": rng.integers(0, 4000, 20_000),
         "grp": rng.integers(0, 100, 20_000),
         "v": rng.integers(0, 1000, 20_000)})
    return s


QUERIES = [
    # redistribute join (both sides move) + two-stage agg + gathered sort
    "SELECT g, sum(v) AS sv, count(*) AS c FROM fact "
    "JOIN dim ON fact.grp = dim.d GROUP BY g ORDER BY g",
    # broadcast join (small build)
    "SELECT count(*) AS n FROM fact JOIN dim ON fact.grp = dim.d "
    "WHERE g < 3",
    # top-N pushdown through the gather motion
    "SELECT k, v FROM fact ORDER BY v DESC, k LIMIT 7",
    # two-stage agg on a non-distribution key: the host-combined merge
    # motion (sum/count/min/max partials — all exact merges)
    "SELECT v % 13 AS b, sum(v) AS sv, count(*) AS c, min(k) AS mn, "
    "max(k) AS mx FROM fact GROUP BY b ORDER BY b",
]


# ------------------------------------------------- transport bit-identity


@pytest.mark.parametrize("n_hosts", [2, 4])
def test_transport_bit_identical(session, monkeypatch, n_hosts):
    """hier_all_to_all and the tree all_gather return byte-for-byte the
    flat collectives' buffers on random wire blocks (validity-bit
    convention, invalid slots all-zero)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from cloudberry_tpu.exec.dist_executor import _shard_map
    from cloudberry_tpu.parallel.mesh import (SEG_AXIS, host_topology,
                                              segment_mesh)
    from cloudberry_tpu.parallel.transport import (HierarchicalCollectives,
                                                   XlaCollectives)

    monkeypatch.setenv("CBTPU_FORCE_HOSTS", str(n_hosts))
    mesh = segment_mesh(NSEG)
    topo = host_topology(NSEG)
    assert topo.n_hosts == n_hosts and topo.uniform_contiguous()
    tx, flat = HierarchicalCollectives(topo), XlaCollectives()
    S, B, W = NSEG // n_hosts, 16, 5
    rng = np.random.default_rng(3)
    x = rng.integers(0, 2 ** 31, (NSEG, NSEG, B, W)).astype(np.uint32)
    valid = rng.random((NSEG, NSEG, B)) < 0.6
    x[..., 0] = (x[..., 0] & ~np.uint32(1)) | valid.astype(np.uint32)
    x = np.where(valid[..., None], x, 0).astype(np.uint32)

    def fn(v):
        a = v[0][0]
        r_flat = flat.all_to_all(a, SEG_AXIS)
        r_hier, demand = tx.hier_all_to_all(a, SEG_AXIS,
                                            host_cap=S * S * B)
        g_flat = flat.all_gather(a.reshape(NSEG * B, W), SEG_AXIS)
        g_hier = tx.all_gather(a.reshape(NSEG * B, W), SEG_AXIS)
        return (jnp.all(r_flat == r_hier)[None].astype(jnp.int32),
                jnp.all(g_flat == g_hier)[None].astype(jnp.int32),
                demand[None])

    f = jax.jit(_shard_map(fn, mesh, ({0: P(SEG_AXIS)},),
                           (P(SEG_AXIS), P(SEG_AXIS), P(SEG_AXIS))))
    eq_a2a, eq_ag, dem = f({0: x})
    assert np.asarray(eq_a2a).all(), "hier_all_to_all != flat"
    assert np.asarray(eq_ag).all(), "tree all_gather != flat"
    # every valid row is accounted to exactly one host pair
    assert int(np.asarray(dem).sum()) == int(valid.sum())
    assert tx.launches > 0       # the ICI/DCN ppermutes really ran


# -------------------------------------------------- engine-level parity


def test_hier_queries_bit_identical(hosts4):
    """hierarchical=on vs off at a forced 4-host/8-seg split: every
    query shape (redistribute join, broadcast join, top-N gather,
    host-combined agg merge) decodes bit-identically."""
    s_off = _mk_session("off")
    s_on = _mk_session("on", **{"debug.verify_plans": True})
    for q in QUERIES:
        a = s_off.sql(q).to_pandas()
        b = s_on.sql(q).to_pandas()
        pd.testing.assert_frame_equal(a, b)


def test_host_combine_stamped_and_single_seg_parity(hosts4):
    """The two-stage agg's merge motion carries the host-combine stamp
    at 8 segments (and the planck gate accepts it); at 1 segment the
    topology gate never fires — plans stay unstamped and results match
    (the zero-regression single-host half of the satellite)."""
    from cloudberry_tpu.exec.executor import all_nodes
    from cloudberry_tpu.plan import nodes as PN
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sql.parser import parse_sql

    s_on = _mk_session("on")
    plan = plan_statement(parse_sql(QUERIES[3]), s_on, {}).plan
    stamped = [m for m in all_nodes(plan) if isinstance(m, PN.PMotion)
               and m.kind == "redistribute" and m.host_combine]
    assert stamped, "merge motion did not get the host-combine stamp"
    assert all(m.host_bucket_cap >= m.bucket_cap and m.hier_hosts == 4
               for m in stamped)

    s1_on = _mk_session("on", nseg=1)
    s1_off = _mk_session("off", nseg=1)
    for q in QUERIES:
        pd.testing.assert_frame_equal(s1_off.sql(q).to_pandas(),
                                      s1_on.sql(q).to_pandas())
    p1 = plan_statement(parse_sql(QUERIES[3]), s1_on, {}).plan
    assert all(m.host_bucket_cap == 0 and not m.host_combine
               for m in all_nodes(p1) if isinstance(m, PN.PMotion))


def test_single_host_plans_unstamped(session):
    """No CBTPU_FORCE_HOSTS, one real host: the gate never fires even
    with hierarchical=on — flat remains default-equivalent."""
    from cloudberry_tpu.exec.executor import all_nodes
    from cloudberry_tpu.plan import nodes as PN
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sql.parser import parse_sql

    s = _mk_session("on")
    for q in QUERIES:
        plan = plan_statement(parse_sql(q), s, {}).plan
        assert all(m.host_bucket_cap == 0 and m.hier_hosts == 0
                   and not m.host_combine
                   for m in all_nodes(plan) if isinstance(m, PN.PMotion))


def test_tpch_q3_hier_parity(hosts4):
    """Acceptance pin: TPC-H Q3 at 8 segments decodes bit-identically
    with the two-level transport on (Q10 rides the slow tier)."""
    _tpch_parity("q3")


@pytest.mark.slow
def test_tpch_q10_hier_parity(hosts4):
    _tpch_parity("q10")


def _tpch_parity(qname):
    from tools.tpch_queries import QUERIES as TPCH
    from tools.tpchgen import load_tpch

    flat = cb.Session(Config(n_segments=NSEG))
    load_tpch(flat, sf=0.01, seed=7)
    hier = cb.Session(Config(n_segments=NSEG).with_overrides(
        **{"interconnect.hierarchical": "on"}))
    load_tpch(hier, sf=0.01, seed=7)
    pd.testing.assert_frame_equal(flat.sql(TPCH[qname]).to_pandas(),
                                  hier.sql(TPCH[qname]).to_pandas())


# --------------------------------------------- host rung overflow ladder


def test_host_rung_overflow_promotes_and_retries(hosts4):
    """An undersized host rung is a DETECTED overflow (never silent):
    the check names the node, grow_expansion promotes straight to the
    rung fitting the observed host demand, and the retry is
    bit-identical to flat."""
    from cloudberry_tpu.exec import dist_executor as DX
    from cloudberry_tpu.exec import executor as X
    from cloudberry_tpu.exec.executor import all_nodes
    from cloudberry_tpu.plan import nodes as PN
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sql.parser import parse_sql

    q = QUERIES[0]
    # broadcast_threshold 0 forces the join onto redistributes, so the
    # probe motion carries host stamps (dim would broadcast otherwise)
    s_off = _mk_session("off", **{"planner.broadcast_threshold": 0})
    want = s_off.sql(q).to_pandas()

    s_on = _mk_session("on", **{"planner.broadcast_threshold": 0})
    plan = plan_statement(parse_sql(q), s_on, {}).plan
    motions = [m for m in all_nodes(plan) if isinstance(m, PN.PMotion)
               and m.host_bucket_cap > 0]
    # the fact-side JOIN shuffle (not the host-combined merge — its
    # post-combine demand is a handful of groups): thousands of rows
    # per host pair, so an 8-row host block must overflow
    plain = [m for m in motions if not m.host_combine]
    assert plain
    m = max(plain, key=lambda n: n.bucket_cap)
    m.host_bucket_cap = 8            # valid rung, guaranteed overflow
    fn = DX.compile_distributed(plan, s_on)
    with pytest.raises(X.ExecError) as ei:
        DX.execute_distributed(plan, s_on, fn)
    assert "host bucket overflow" in str(ei.value)
    assert getattr(m, "_observed_host_bucket", 0) > 8
    assert X.grow_expansion(plan, str(ei.value))
    assert m.host_bucket_cap >= m._observed_host_bucket
    got = DX.execute_distributed(plan, s_on).to_pandas()
    pd.testing.assert_frame_equal(got.reset_index(drop=True),
                                  want.reset_index(drop=True))


def test_segment_rung_promotion_lifts_host_rung(hosts4):
    """Promoting bucket_cap on a hier-stamped motion must keep the
    host_bucket_cap >= bucket_cap invariant AND fold in the host demand
    the failing run already observed — otherwise the retry is a
    guaranteed host-rung overflow costing one more recompile cycle."""
    from cloudberry_tpu.exec.executor import grow_expansion
    from cloudberry_tpu.plan import expr as ex
    from cloudberry_tpu.plan import nodes as PN
    from cloudberry_tpu.types import INT64

    scan = PN.PScan("t", {"k": "k"}, 64)
    m = PN.PMotion(scan, "redistribute",
                   hash_keys=[ex.ColumnRef("k", INT64)])
    m.bucket_cap, m.out_capacity = 64, 64 * NSEG
    m.host_bucket_cap, m.hier_hosts = 256, 4
    m._observed_bucket = 5000
    m._observed_host_bucket = 9000
    assert grow_expansion(m, f"redistribute overflow (node {id(m)})")
    assert m.bucket_cap == 8192
    assert m.host_bucket_cap >= max(m.bucket_cap, 9000)


# ------------------------------------------------- satellite regressions


def test_segment_mesh_stale_device_ids_raise(session):
    from cloudberry_tpu.parallel.mesh import (DeviceRestrictionError,
                                              segment_mesh)

    # formerly: `if i < len(devices)` silently skipped the hole
    with pytest.raises(DeviceRestrictionError) as ei:
        segment_mesh(4, device_ids=[0, 1, 2, 99])
    assert ei.value.kind == "stale"
    assert "99" in str(ei.value)
    with pytest.raises(DeviceRestrictionError) as ei:
        segment_mesh(2, device_ids=[0, -1])
    assert ei.value.kind == "invalid"
    with pytest.raises(DeviceRestrictionError) as ei:
        segment_mesh(2, device_ids=[0, 0, 1])
    assert ei.value.kind == "invalid"
    # a well-formed survivor restriction still builds the mesh
    mesh = segment_mesh(4, device_ids=[0, 1, 2, 3])
    assert mesh.devices.size == 4


def test_host_skew_telemetry(hosts4):
    """A host-skewed shuffle (every row to one destination host — the
    case two-level makes WORSE) alarms: per-HOST skew histograms +
    host_skew_events next to the per-segment ones."""
    cfg = Config(n_segments=NSEG).with_overrides(**{
        "interconnect.hierarchical": "on",
        "planner.broadcast_threshold": 0,    # force the redistribute
    })
    s = cb.Session(cfg)
    s.sql("CREATE TABLE dim (d BIGINT, g BIGINT) DISTRIBUTED BY (d)")
    s.sql("CREATE TABLE fact (k BIGINT, grp BIGINT) DISTRIBUTED BY (k)")
    s.catalog.table("dim").set_data(
        {"d": np.arange(100), "g": np.arange(100) % 6})
    n = 4000
    # every fact row carries the same join key -> one destination
    # segment, hence one destination host
    s.catalog.table("fact").set_data(
        {"k": np.arange(n), "grp": np.full(n, 7)})
    before = s.stmt_log.counter("host_skew_events")
    s.sql("SELECT count(*) AS n FROM fact JOIN dim "
          "ON fact.grp = dim.d")
    assert s.stmt_log.counter("host_skew_events") > before
    assert s.stmt_log.registry.hist("motion_host_skew_ratio")


def test_capacity_accounts_two_level_staging(hosts4):
    from cloudberry_tpu.exec.executor import all_nodes
    from cloudberry_tpu.obs.capacity import (plan_device_bytes,
                                             two_level_staging_bytes)
    from cloudberry_tpu.plan import nodes as PN
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sql.parser import parse_sql

    s_on = _mk_session("on", **{"planner.broadcast_threshold": 0})
    plan = plan_statement(parse_sql(QUERIES[0]), s_on, {}).plan
    stamped = [m for m in all_nodes(plan) if isinstance(m, PN.PMotion)
               and m.host_bucket_cap > 0]
    assert stamped
    assert all(two_level_staging_bytes(m) > 0 for m in stamped)
    with_staging = plan_device_bytes(plan)["wire_bytes"]
    for m in stamped:
        m.host_bucket_cap = 0
        m.hier_hosts = 0
    assert plan_device_bytes(plan)["wire_bytes"] < with_staging


def test_tiled_dist_hier_parity(hosts4):
    """The TILED distributed path runs the SAME two-level motion
    semantics as the in-memory path: an admission-rejected statement on
    a forced-4-host session streams tiles through the hierarchical
    transport (host-combined merge included) and matches the unbudgeted
    flat run exactly — a stamped plan must never pay the combine's
    grown rungs while shipping flat."""

    def mk(hier, budget=None):
        over = {"n_segments": NSEG, "planner.broadcast_threshold": 0,
                "interconnect.hierarchical": hier}
        if budget is not None:
            over["resource.query_mem_bytes"] = budget
        s = cb.Session(Config(n_segments=NSEG).with_overrides(**over))
        rng = np.random.default_rng(5)
        n = 200_000
        s.sql("CREATE TABLE dim (d BIGINT, g BIGINT) "
              "DISTRIBUTED BY (g)")
        s.sql("CREATE TABLE fact (k BIGINT, d BIGINT, v BIGINT) "
              "DISTRIBUTED BY (k)")
        s.catalog.table("dim").set_data(
            {"d": np.arange(500), "g": np.arange(500) % 9})
        s.catalog.table("fact").set_data(
            {"k": np.arange(n) % 997,
             "d": rng.integers(0, 500, n),
             "v": rng.integers(0, 100, n)})
        return s

    q = ("SELECT g, sum(v) AS sv, count(*) AS c FROM fact "
         "JOIN dim ON fact.d = dim.d GROUP BY g ORDER BY g")
    want = mk("off").sql(q).to_pandas()
    s = mk("on", budget=2 << 20)
    got = s.sql(q).to_pandas()
    pd.testing.assert_frame_equal(want, got)
    rep = s.last_tiled_report
    assert rep["tiled"] and rep["distributed"] and rep["n_tiles"] > 1


def test_ic_bench_two_level_smoke():
    """tools/ic_bench --two-level: dcn/ici split + exact checksum
    parity on the simulated 4-host split (CPU smoke; the acceptance
    measurement at 50k rows shows ~3.6x lower DCN bytes)."""
    import json
    import os

    out = subprocess.run(
        [sys.executable, "-m", "tools.ic_bench", "--two-level",
         "--hosts", "4", "--rows", "2000", "--reps", "1"],
        capture_output=True, text=True, timeout=540,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    assert out.returncode == 0, out.stderr[-2000:]
    recs = [json.loads(ln) for ln in out.stdout.splitlines()
            if ln.startswith("{")]
    by_mode = {}
    for r in recs:
        by_mode.setdefault(r["mode"], []).append(r)
    assert {"two-level", "two-level-summary"} <= set(by_mode)
    summary = by_mode["two-level-summary"][0]
    assert summary["checksums_match"] is True
    assert summary["dcn_ratio"] > 1.0
    fmts = {r["format"]: r for r in by_mode["two-level"]}
    assert fmts["hier"]["dcn_bytes"] < fmts["flat"]["dcn_bytes"]
    assert {"dcn_bytes", "ici_bytes", "launches",
            "wall_ms"} <= set(fmts["hier"])
