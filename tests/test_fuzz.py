"""Deterministic query fuzzing: random-but-seeded simple queries compared
against pandas (the gptorment.pl stress analog, aimed at planner/executor
seams rather than load). Every case is reproducible from its index."""

import numpy as np
import pandas as pd
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config

N_CASES = 40


def _make_session(nseg):
    s = cb.Session(Config(n_segments=nseg)) if nseg > 1 else cb.Session()
    rng = np.random.default_rng(99)
    n = 500
    k = rng.integers(0, 20, n)
    g = rng.choice(["aa", "bb", "cc", "dd"], n)
    v = rng.integers(-1000, 1000, n)
    d = rng.integers(0, 50, n)
    s.sql("create table f (k bigint, g text, v bigint, d bigint) "
          "distributed by (k)")
    rows = ",".join(f"({a},'{b}',{c},{e})" for a, b, c, e in zip(k, g, v, d))
    s.sql(f"insert into f values {rows}")
    df = pd.DataFrame({"k": k, "g": g, "v": v, "d": d})
    return s, df


@pytest.fixture(scope="module")
def fuzz_single():
    return _make_session(1)


@pytest.fixture(scope="module")
def fuzz_dist():
    return _make_session(8)


def _gen_case(i):
    rng = np.random.default_rng(1000 + i)
    cmp_col = rng.choice(["k", "v", "d"])
    cmp_op = rng.choice(["<", "<=", ">", ">=", "=", "<>"])
    cmp_val = int(rng.integers(-500, 500))
    g_lit = rng.choice(["aa", "bb", "cc", "zz"])
    conj = rng.choice(["and", "or"])
    where = (f"({cmp_col} {cmp_op} {cmp_val} {conj} g = '{g_lit}')")
    mode = rng.choice(["agg", "group", "plain"])
    if mode == "agg":
        sql = (f"select count(*) as n, sum(v) as sv, min(d) as md "
               f"from f where {where}")
    elif mode == "group":
        sql = (f"select g, count(*) as n, sum(v) as sv from f "
               f"where {where} group by g order by g")
    else:
        sql = (f"select k, g, v from f where {where} "
               f"order by k, g, v, d limit 20")
    pandas_where = where.replace("=", "==").replace("<>", "!=") \
        .replace("<==", "<=").replace(">==", ">=")
    return sql, pandas_where, mode


def _expect(df, pandas_where, mode):
    m = df.query(pandas_where)
    if mode == "agg":
        return pd.DataFrame({
            "n": [len(m)], "sv": [m.v.sum() if len(m) else 0],
            "md": [m.d.min() if len(m) else None]})
    if mode == "group":
        out = m.groupby("g", as_index=False).agg(n=("v", "size"),
                                                 sv=("v", "sum"))
        return out.sort_values("g").reset_index(drop=True)
    out = m[["k", "g", "v"]].sort_values(
        ["k", "g", "v"], kind="stable").head(20)
    return out.reset_index(drop=True)


@pytest.mark.parametrize("i", range(N_CASES))
def test_fuzz_single(fuzz_single, i):
    _run_case(fuzz_single, i)


@pytest.mark.parametrize("i", range(0, N_CASES, 4))
def test_fuzz_distributed(fuzz_dist, i):
    _run_case(fuzz_dist, i)


def _run_case(fixture, i):
    s, df = fixture
    sql, pw, mode = _gen_case(i)
    got = s.sql(sql).to_pandas()
    exp = _expect(df, pw, mode)
    assert len(got) == len(exp), f"case {i}: {sql}"
    if mode == "agg":
        assert int(got.n[0]) == int(exp.n[0]), f"case {i}: {sql}"
        if int(exp.n[0]) > 0:
            assert int(got.sv[0]) == int(exp.sv[0]), f"case {i}: {sql}"
            assert int(got.md[0]) == int(exp.md[0]), f"case {i}: {sql}"
        else:
            # SQL: sum/min over zero rows are NULL
            assert got.sv[0] is None and got.md[0] is None, \
                f"case {i}: {sql}"
    elif mode == "group":
        assert got.g.tolist() == exp.g.tolist(), f"case {i}: {sql}"
        assert got.n.tolist() == exp.n.tolist(), f"case {i}: {sql}"
        assert got.sv.tolist() == exp.sv.tolist(), f"case {i}: {sql}"
    else:
        for c in ("k", "g", "v"):
            assert got[c].tolist() == exp[c].tolist(), f"case {i}: {sql}"
