"""End-to-end TPC-H correctness: SQL → parse → bind/plan → jitted kernels →
result, validated against the pandas oracle (tools/tpch_oracle.py) on the
same generated data — the regress-suite analog."""

import numpy as np
import pandas as pd
import pytest

import cloudberry_tpu as cb
from tools.tpch_oracle import ORACLES
from tools.tpch_queries import QUERIES
from tools.tpchgen import load_tpch


@pytest.fixture(scope="module")
def tpch_session():
    s = cb.Session()
    load_tpch(s, sf=0.01, seed=7)
    tables = {n: t.to_pandas() for n, t in s.catalog.tables.items()}
    return s, tables


def assert_frames_match(got: pd.DataFrame, exp: pd.DataFrame, name: str):
    assert len(got) == len(exp), \
        f"{name}: row count {len(got)} != {len(exp)}"
    assert len(got.columns) == len(exp.columns), \
        f"{name}: column count {list(got.columns)} vs {list(exp.columns)}"
    for gcol, ecol in zip(got.columns, exp.columns):
        g, e = got[gcol].to_numpy(), exp[ecol].to_numpy()
        if g.dtype.kind == "f" or e.dtype.kind == "f":
            np.testing.assert_allclose(
                g.astype(np.float64), e.astype(np.float64),
                rtol=1e-9, atol=1e-2, err_msg=f"{name}.{gcol}")
        elif g.dtype == object or e.dtype == object:
            gn, en = pd.isna(g), pd.isna(e)
            np.testing.assert_array_equal(
                gn, en, err_msg=f"{name}.{gcol} (null mask)")
            np.testing.assert_array_equal(
                g[~gn], e[~en], err_msg=f"{name}.{gcol}")
        else:
            np.testing.assert_array_equal(g, e, err_msg=f"{name}.{gcol}")


@pytest.mark.parametrize("qname", sorted(QUERIES))
def test_tpch_query(tpch_session, qname):
    session, tables = tpch_session
    if qname not in ORACLES:
        pytest.skip(f"no oracle for {qname}")
    got = session.sql(QUERIES[qname]).to_pandas()
    exp = ORACLES[qname](tables)
    assert_frames_match(got, exp, qname)


def test_explain_q3(tpch_session):
    session, _ = tpch_session
    text = session.explain(QUERIES["q3"])
    assert "Join" in text and "Scan lineitem" in text and "GroupAgg" in text
