"""GROUPING SETS / ROLLUP / CUBE (nodeAgg.c grouping-sets role).

Bound as a UNION ALL of per-set aggregations: omitted keys project as
typed NULLs (the set-op alignment types NULL columns from the string
side), ORDER BY/LIMIT apply to the whole union. Validated against a
pandas oracle on both 1 and 8 segments.
"""

import numpy as np
import pandas as pd
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config


def _mk(nseg=1):
    s = cb.Session(get_config().with_overrides(**{"n_segments": nseg}))
    s.sql("create table sales (region text, product text, qty bigint, "
          "amount bigint) distributed by (qty)")
    s.sql("""insert into sales values
        ('east','a',1,10),('east','b',2,20),('east','a',3,15),
        ('west','a',4,30),('west','b',5,40),('west','b',6,25)""")
    return s


@pytest.fixture(scope="module", params=[1, 8], ids=["single", "dist8"])
def s(request):
    return _mk(request.param)


def _norm(df):
    return [[None if (isinstance(v, float) and np.isnan(v)) or v is None
             or v is pd.NA else v for v in row]
            for row in df.values.tolist()]


def test_rollup(s):
    df = s.sql("""select region, product, sum(amount) as total
                  from sales group by rollup (region, product)
                  order by region, product""").to_pandas()
    assert _norm(df) == [
        ["east", "a", 25], ["east", "b", 20], ["east", None, 45],
        ["west", "a", 30], ["west", "b", 65], ["west", None, 95],
        [None, None, 140]]


def test_cube(s):
    df = s.sql("""select region, product, count(*) as c from sales
                  group by cube (region, product)
                  order by region, product""").to_pandas()
    assert _norm(df) == [
        ["east", "a", 2], ["east", "b", 1], ["east", None, 3],
        ["west", "a", 1], ["west", "b", 2], ["west", None, 3],
        [None, "a", 3], [None, "b", 3], [None, None, 6]]


def test_grouping_sets_explicit(s):
    df = s.sql("""select region, product, sum(qty) as q from sales
                  group by grouping sets ((region), (product), ())
                  order by region, product""").to_pandas()
    assert _norm(df) == [
        ["east", None, 6], ["west", None, 15],
        [None, "a", 8], [None, "b", 13], [None, None, 21]]


def test_rollup_numeric_keys(s):
    # NULL-filled numeric keys align by type coercion, not the
    # string-side machinery
    df = s.sql("""select qty, sum(amount) as t from sales
                  where qty <= 2 group by rollup (qty)
                  order by qty""").to_pandas()
    assert _norm(df) == [[1, 10], [2, 20], [None, 30]]


def test_rollup_with_having_and_limit(s):
    df = s.sql("""select region, product, sum(amount) as total
                  from sales group by rollup (region, product)
                  having sum(amount) > 40
                  order by total desc limit 3""").to_pandas()
    assert _norm(df) == [[None, None, 140], ["west", None, 95],
                         ["west", "b", 65]]


def test_aggregate_over_grouping_key(s):
    """count(region) in the grand-total row counts ALL non-NULL regions
    — the key is NULL only as a group label, never inside aggregation."""
    df = s.sql("""select region, count(region) as c from sales
                  group by rollup (region) order by region""").to_pandas()
    assert _norm(df) == [["east", 3], ["west", 3], [None, 6]]


def test_qualified_key_matches_bare_item(s):
    df = s.sql("""select region, sum(amount) as t from sales
                  group by rollup (sales.region)
                  order by region""").to_pandas()
    assert _norm(df) == [["east", 45], ["west", 95], [None, 140]]


def test_distinct_over_grouping_sets(s):
    df = s.sql("""select distinct region from sales
                  group by grouping sets ((region), (region, product))
                  order by region""").to_pandas()
    assert _norm(df) == [["east"], ["west"]]


def test_bare_expression_grouping_set(s):
    df = s.sql("""select region, product, sum(qty) as q from sales
                  group by grouping sets (region, (region, product))
                  order by region, product""").to_pandas()
    assert _norm(df)[0] == ["east", "a", 4]
    assert ["east", None, 6] in _norm(df)


def test_column_named_rollup_still_groups(s):
    s2 = cb.Session()
    s2.sql("create table odd (rollup bigint, v bigint)")
    s2.sql("insert into odd values (1, 10), (1, 20), (2, 5)")
    df = s2.sql("select rollup, sum(v) as t from odd group by rollup "
                "order by rollup").to_pandas()
    assert df.values.tolist() == [[1, 30], [2, 5]]


def test_grouping_function(s):
    """grouping(a, b) bitmask distinguishes subtotal levels — the SQL
    disambiguator for real NULL keys vs rollup NULL labels."""
    df = s.sql("""select region, product, grouping(region, product) as g,
                  sum(amount) as t from sales
                  group by rollup (region, product)
                  order by g, region, product""").to_pandas()
    rows = _norm(df)
    assert [r[2] for r in rows] == [0, 0, 0, 0, 1, 1, 3]
    assert rows[-1] == [None, None, 3, 140]
    # single-arg form
    df = s.sql("""select region, grouping(region) as g from sales
                  group by rollup (region) order by g, region""").to_pandas()
    assert [r[1] for r in _norm(df)] == [0, 0, 1]


def test_grouping_outside_grouping_sets(s):
    """grouping() is valid in any grouped query (PG): in a plain GROUP
    BY every key is grouped, so it folds to the constant 0."""
    df = s.sql("select region, grouping(region) as g, sum(amount) as t "
               "from sales group by region order by region").to_pandas()
    assert _norm(df) == [["east", 0, 45], ["west", 0, 95]]
    df = s.sql("select region, grouping(region, region) as g from sales "
               "group by region having grouping(region) = 0 "
               "order by grouping(region), region").to_pandas()
    assert [r[1] for r in _norm(df)] == [0, 0]


def test_grouping_arg_must_be_grouped(s):
    from cloudberry_tpu.plan.binder import BindError

    with pytest.raises(BindError, match="grouping expressions"):
        s.sql("select region, grouping(amount) from sales "
              "group by region")
    # no GROUP BY at all: nothing is a grouping expression
    with pytest.raises(BindError, match="grouping expressions"):
        s.sql("select grouping(region) from sales")
    # same rule inside GROUPING SETS (the fold would otherwise silently
    # return a wrong constant)
    with pytest.raises(BindError, match="grouping expressions"):
        s.sql("select grouping(amount), sum(amount) from sales "
              "group by rollup(region)")


def test_grouping_through_select_alias(s):
    # GROUP BY r where r aliases region: region IS a grouping expression
    df = s.sql("select region as r, grouping(region) as g, "
               "sum(amount) as t from sales group by r "
               "order by r").to_pandas()
    assert _norm(df) == [["east", 0, 45], ["west", 0, 95]]


def test_window_spanning_grouping_sets_rejected(s):
    """Windows run per UNION branch of the rewrite; a PARTITION BY that
    cannot distinguish the branches would silently rank one branch where
    SQL ranks the combined output — it must be a loud error."""
    from cloudberry_tpu.plan.binder import BindError

    with pytest.raises(BindError, match="span grouping sets"):
        s.sql("select region, product, rank() over "
              "(order by sum(amount)) as r from sales "
              "group by cube(region, product)")
    # the grouping()-sum discriminates ROLLUP levels but NOT the two
    # single-key CUBE branches (both fold to 1)
    with pytest.raises(BindError, match="span grouping sets"):
        s.sql("select region, product, rank() over "
              "(partition by grouping(region) + grouping(product) "
              "order by sum(amount)) as r from sales "
              "group by cube(region, product)")
    # the full bitmask IS injective per branch: accepted, and each
    # level ranks only its own rows
    df = s.sql("select region, product, grouping(region, product) as g, "
               "rank() over (partition by grouping(region, product) "
               "order by sum(amount)) as r from sales "
               "group by rollup(region, product) "
               "order by g, r, region, product").to_pandas()
    # level 0: four (region, product) rows rank 1..4; level 1: two
    # region subtotals rank 1..2; level 3: the grand total ranks 1
    assert df["r"].tolist() == [1, 2, 3, 4, 1, 2, 1]


def test_rollup_key_inside_case(s):
    """Omitted keys replace inside CASE WHEN tuples too — the grand
    total's CASE sees NULL and takes the ELSE branch."""
    df = s.sql("""select case when region = 'east' then 'E' else 'O' end
                  as r, sum(amount) as t from sales
                  group by rollup (region) order by t""").to_pandas()
    assert _norm(df) == [["E", 45], ["O", 95], ["O", 140]]


def test_empty_grouping_set_is_one_group(s):
    # GROUP BY () = one group even with no aggregates selected
    df = s.sql("select 1 as one from sales "
               "group by grouping sets (())").to_pandas()
    assert len(df) == 1


def test_trailing_group_by_is_parse_error(s):
    from cloudberry_tpu.sql.parser import ParseError, parse_sql

    with pytest.raises(ParseError):
        parse_sql("select 1 from sales group by")


def test_rollup_matches_pandas_oracle():
    rng = np.random.default_rng(23)
    n = 5000
    g1 = rng.integers(0, 7, n)
    g2 = rng.integers(0, 5, n)
    v = rng.integers(0, 1000, n)
    s2 = cb.Session(get_config().with_overrides(**{"n_segments": 8}))
    s2.sql("create table r (a bigint, b bigint, v bigint) "
           "distributed by (v)")
    s2.catalog.table("r").set_data(
        {"a": g1.astype(np.int64), "b": g2.astype(np.int64),
         "v": v.astype(np.int64)})
    df = s2.sql("select a, b, sum(v) as s, count(*) as c from r "
                "group by rollup (a, b) order by a, b").to_pandas()
    pdf = pd.DataFrame({"a": g1, "b": g2, "v": v})
    lvl2 = pdf.groupby(["a", "b"], as_index=False).agg(
        s=("v", "sum"), c=("v", "size"))
    lvl1 = pdf.groupby(["a"], as_index=False).agg(
        s=("v", "sum"), c=("v", "size"))
    lvl1["b"] = None
    lvl0 = pd.DataFrame([{"a": None, "b": None,
                          "s": v.sum(), "c": n}])
    want = pd.concat([lvl2, lvl1[["a", "b", "s", "c"]],
                      lvl0])
    want = want.sort_values(["a", "b"],
                            na_position="last").reset_index(drop=True)
    got = _norm(df)
    exp = [[None if pd.isna(x) else int(x) for x in row]
           for row in want.values.tolist()]
    assert got == exp
