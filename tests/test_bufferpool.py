"""HBM-resident buffer pool (exec/bufferpool.py) — ISSUE 16.

The contract under test: hot scans are served from device-resident
decoded chunks with ZERO host reads/decodes once admitted (the
``bufpool_*``/``host_decodes`` counters pin it); pool-on vs pool-off is
BIT-IDENTICAL across the tiled matrix at 1 and 8 segments including
mid-statement device loss; every invalidation axis — store VERSION
bump, config-epoch swap, topology-epoch flip (forced regression via a
config-uid collision, the PR-13 stale-nseg pattern) — means a stale
entry's key can never be asked for again; admission is by observed scan
frequency with LRU-by-bytes eviction that REFUSES rather than evicting
a hotter victim; and a 4-thread admission/eviction stress stays clean
under the runtime lock-order witness.
"""

import threading
import time

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config
from cloudberry_tpu.exec import bufferpool as BUF
from cloudberry_tpu.utils import faultinject as FI

AGG_Q = "select g, sum(v) as sv, count(*) as c from fact group by g order by g"
TOPN_Q = "select k, v from fact where v < 90 order by v, k limit 25"
SORT_Q = "select k, v from fact where v < 5 order by v desc, k"
WIN_Q = ("select g, v, rank() over (partition by g order by v desc) as r,"
         " sum(v) over (partition by g) as sv from fact")


@pytest.fixture(autouse=True)
def _clean_faults():
    FI.reset_fault()
    yield
    FI.reset_fault()


def _mk_store(root, n=120_000, n_groups=9, parts=20_000, nseg=1):
    """Write a cold fact table (k, g, v) under ``root`` and return the
    writer session (readers open fresh sessions over the same root)."""
    s = cb.Session(get_config().with_overrides(**{
        "n_segments": nseg, "storage.root": root,
        "storage.rows_per_partition": parts}))
    rng = np.random.default_rng(5)
    s.sql("create table fact (k bigint, g bigint, v bigint) "
          "distributed by (k)")
    s.catalog.table("fact").set_data({
        "k": (np.arange(n, dtype=np.int64) * 7) % 997,
        "g": rng.integers(0, n_groups, n).astype(np.int64),
        "v": rng.integers(0, 100, n).astype(np.int64)})
    return s


def _open(root, nseg=1, budget=None, pool=True, **extra):
    ov = {"n_segments": nseg, "storage.root": root}
    if budget is not None:
        ov["resource.query_mem_bytes"] = budget
    if not pool:
        ov["bufferpool.enabled"] = False
    ov.update(extra)
    return cb.Session(get_config().with_overrides(**ov))


def _ent(n=512, seed=0):
    return {"cols": {"v": np.arange(seed, seed + n, dtype=np.int64)},
            "validity": {}}


_NB = 512 * 8  # _ent() bytes


# ------------------------------------------------------------ unit: policy


def test_admission_needs_min_scans():
    p = BUF.BufferPool(max_bytes=1 << 20, admit_min_scans=2)
    k = ("part", "t", 1, "p0", ("v",), 1, 0)
    assert p.lookup(k) is None           # freq 1
    assert not p.offer(k, _ent(), device=False)
    assert p.lookup(k) is None           # freq 2
    assert p.offer(k, _ent(), device=False)
    assert p.lookup(k) is not None       # resident now
    snap = p.snapshot()
    assert snap["entries"] == 1 and snap["bytes"] == _NB
    # re-offering a resident key is a no-op, not a double charge
    assert not p.offer(k, _ent(), device=False)
    assert p.snapshot()["bytes"] == _NB


def test_lru_eviction_under_byte_budget():
    p = BUF.BufferPool(max_bytes=3 * _NB, admit_min_scans=2)
    keys = [("part", "t", 1, f"p{i}", ("v",), 1, 0) for i in range(4)]
    for k in keys[:3]:
        p.lookup(k), p.lookup(k)
        assert p.offer(k, _ent(), device=False)
    p.lookup(keys[0])  # touch: k0 is now most-recent, k1 is the head
    p.lookup(keys[3]), p.lookup(keys[3])
    assert p.offer(keys[3], _ent(), device=False)
    snap = p.snapshot()
    assert snap["entries"] == 3 and snap["evictions"] == 1
    with p._lock:
        resident = set(p._entries)
    assert keys[1] not in resident  # the true LRU head went
    assert keys[0] in resident and keys[3] in resident


def test_refusal_over_evicting_hotter():
    p = BUF.BufferPool(max_bytes=_NB, admit_min_scans=2)
    hot = ("part", "t", 1, "hot", ("v",), 1, 0)
    for _ in range(5):
        p.lookup(hot)
    assert p.offer(hot, _ent(), device=False)
    cold = ("part", "t", 1, "cold", ("v",), 1, 0)
    p.lookup(cold), p.lookup(cold)
    assert not p.offer(cold, _ent(), device=False)
    snap = p.snapshot()
    assert snap["refusals"] == 1 and snap["evictions"] == 0
    assert p.lookup(hot) is not None  # the hotter victim survived


def test_oversize_chunk_refused_not_flushed():
    p = BUF.BufferPool(max_bytes=_NB, admit_min_scans=1)
    small = ("part", "t", 1, "s", ("v",), 1, 0)
    p.lookup(small)
    assert p.offer(small, _ent(), device=False)
    big = ("part", "t", 1, "b", ("v",), 1, 0)
    p.lookup(big)
    assert not p.offer(big, _ent(n=4096), device=False)
    snap = p.snapshot()
    assert snap["refusals"] == 1 and snap["entries"] == 1


def test_sweep_clear_and_grow_only():
    p = BUF.BufferPool(max_bytes=1 << 20, admit_min_scans=1)
    for i in range(3):
        k = ("part", "t", 1, f"p{i}", ("v",), 1, 0)
        p.lookup(k)
        assert p.offer(k, _ent(), device=False)
    assert p.sweep(lambda k: k[3] == "p1") == 1
    assert p.snapshot()["entries"] == 2
    assert p.snapshot()["bytes"] == 2 * _NB
    assert p.clear() == 2
    snap = p.snapshot()
    assert snap["entries"] == 0 and snap["bytes"] == 0
    assert snap["tracked_keys"] == 0  # heat resets with the placement
    p.grow(2 << 20)
    assert p.snapshot()["max_bytes"] == 2 << 20
    p.grow(1 << 10)  # never shrinks under a peer session
    assert p.snapshot()["max_bytes"] == 2 << 20


def test_fault_seams_suppress_admit_and_force_refusal():
    p = BUF.BufferPool(max_bytes=_NB, admit_min_scans=1)
    k = ("part", "t", 1, "p0", ("v",), 1, 0)
    p.lookup(k)
    FI.inject_fault("bufpool_admit", "skip")
    assert not p.offer(k, _ent(), device=False)
    FI.reset_fault("bufpool_admit")
    assert p.offer(k, _ent(), device=False)
    # pool is full: an eviction-requiring offer with the evict seam
    # armed refuses instead of displacing
    k2 = ("part", "t", 1, "p1", ("v",), 1, 0)
    p.lookup(k2), p.lookup(k2)
    FI.inject_fault("bufpool_evict", "skip")
    assert not p.offer(k2, _ent(), device=False)
    assert p.lookup(k) is not None
    FI.reset_fault("bufpool_evict")
    with pytest.raises(FI.InjectedFault):
        FI.inject_fault("bufpool_admit", "error")
        p.lookup(k2)
        p.offer(k2, _ent(), device=False)


# ------------------------------------------- hot scans serve from the pool


def test_hot_tiled_scan_zero_host_decodes(tmp_path):
    """The headline behavior: scans 1-2 observe and admit, scan 3+ of
    the same tiled statement touch NO partition files — bufpool hits
    with a zero host_decodes delta — and stay bit-identical. The
    capacity plane sees the residency (est_bufpool_bytes, mem_bufpool_*
    gauges)."""
    from cloudberry_tpu.obs import capacity

    root = str(tmp_path / "store")
    _mk_store(root)
    s = _open(root, budget=1 << 20)
    assert s.catalog.table("fact").cold

    def ctr(n):
        return s.stmt_log.counter(n)

    res, deltas = [], []
    for _ in range(4):
        before = {n: ctr(n) for n in ("bufpool_hits", "bufpool_misses",
                                      "bufpool_admits", "host_decodes")}
        res.append(s.sql(AGG_Q).to_pandas())
        deltas.append({n: ctr(n) - v for n, v in before.items()})
    assert all(res[0].equals(r) for r in res[1:])
    rep = s.last_tiled_report
    assert rep["tiled"] and rep["n_tiles"] > 1
    # scan 1: all misses, nothing admitted yet (admit_min_scans=2)
    assert deltas[0]["bufpool_misses"] > 0
    assert deltas[0]["bufpool_admits"] == 0
    # scan 2: misses again, but every partition admits
    assert deltas[1]["bufpool_admits"] == deltas[1]["bufpool_misses"] > 0
    # scans 3-4: served from HBM — zero host reads/decodes
    for d in deltas[2:]:
        assert d["bufpool_hits"] > 0 and d["bufpool_misses"] == 0
        assert d["host_decodes"] == 0
    assert rep["pipeline"]["parts_resident"] > 0
    assert rep["est_bufpool_bytes"] > 0
    vals = capacity.refresh_gauges(s)
    assert vals["mem_bufpool_bytes"] > 0
    assert vals["mem_bufpool_entries"] > 0
    assert s._cache_scope.snapshot()["bufferpool"]["hits"] > 0


def test_one_shot_scan_shares_pool_across_sessions(tmp_path):
    """One-shot (non-tiled) scans: a session's private store-scan LRU
    absorbs its own repeats, but the pool is scope-wide — a THIRD
    session's first scan of the hot table is served from HBM with zero
    host decodes (store_scan_cache_* counters track the LRU side)."""
    root = str(tmp_path / "store")
    _mk_store(root, n=40_000)
    q = "select sum(v) as sv from fact"
    # one Config OBJECT for all three sessions (the server-backend
    # shape: per-connection backends share the serving session's
    # config) — distinct configs are a different epoch by design
    cfg = get_config().with_overrides(**{"storage.root": root})
    a, b, c = cb.Session(cfg), cb.Session(cfg), cb.Session(cfg)
    a.sql(q)
    assert a.last_tiled_report is None  # one-shot path
    assert a.stmt_log.counter("host_decodes") > 0
    assert a.stmt_log.counter("store_scan_cache_misses") > 0
    a.sql(q)  # private LRU hit — no pool traffic needed
    assert a.stmt_log.counter("store_scan_cache_hits") > 0
    b.sql(q)  # freq reaches admit_min_scans: admits
    assert b.stmt_log.counter("bufpool_admits") > 0
    got = c.sql(q).to_pandas()
    assert c.stmt_log.counter("bufpool_hits") > 0
    assert c.stmt_log.counter("host_decodes") == 0
    assert got.equals(a.sql(q).to_pandas())


# ------------------------------------------------------------ invalidation


def test_version_bump_invalidates_by_key(tmp_path):
    """A DML commit publishes a new store version: the old entries'
    keys can never be asked for again (no stale hit), and the post-DML
    answer includes the new row."""
    root = str(tmp_path / "store")
    _mk_store(root, n=40_000)
    s = _open(root, budget=1 << 20)
    for _ in range(3):
        s.sql(AGG_Q)
    pool = BUF.pool_for(s)
    assert pool.snapshot()["hits"] > 0
    v0 = s.catalog.store.effective_version("fact")
    s.sql("insert into fact values (1, 0, 1000)")
    assert s.catalog.store.effective_version("fact") != v0
    h0 = pool.snapshot()["hits"]
    got = s.sql(AGG_Q).to_pandas()
    # every lookup missed: stale-version entries never matched
    assert pool.snapshot()["hits"] == h0
    fresh = _open(root, budget=1 << 20)
    assert got.equals(fresh.sql(AGG_Q).to_pandas())
    assert int(got["sv"].sum()) == int(
        _open(root).sql("select sum(v) as sv from fact")
        .to_pandas()["sv"][0])


def test_config_epoch_swap_never_serves_foreign_entries(tmp_path):
    """Two sessions over the same store root share one pool (one cache
    scope), but their keys differ in exactly the config-uid component —
    programs bake config knobs, so entries built under another Config
    object must never serve."""
    from cloudberry_tpu.sched import sharedcache

    root = str(tmp_path / "store")
    _mk_store(root, n=40_000)
    a = _open(root, budget=1 << 20)
    for _ in range(3):
        exp = a.sql(AGG_Q).to_pandas()
    b = _open(root, budget=1 << 20)
    pool = BUF.pool_for(a)
    assert BUF.pool_for(b) is pool  # shared scope, shared pool
    ka = BUF.dist_tile_key(a, "fact", (("g", "v"), ()), 1, 1024, 0)
    kb = BUF.dist_tile_key(b, "fact", (("g", "v"), ()), 1, 1024, 0)
    assert ka[:-1] == kb[:-1] and ka[-1] != kb[-1], \
        "config uid must be the (only) differing key component"
    assert sharedcache.config_uid(a.config) != \
        sharedcache.config_uid(b.config)
    h0 = pool.snapshot()["hits"]
    got = b.sql(AGG_Q).to_pandas()
    assert pool.snapshot()["hits"] == h0, \
        "a foreign config's entry served (stale config-epoch hit)"
    assert exp.equals(got)


@pytest.mark.slow  # two online rebalances on one core: ~5s of wall
def test_topology_flip_forced_regression_never_serves_stale(
        tmp_path, monkeypatch):
    """The PR-13 stale-nseg pattern aimed at the pool: collapse
    config_uid so after a 4->6->4 round trip every key component
    ALIASES except the topology token — remove the token and the
    epoch-1 entries would serve at epoch 3. With it, the keys differ in
    exactly that slot; and the cutover additionally drops the resident
    bytes eagerly (the heat sketch too: the old placement's frequency
    is not evidence about the new one)."""
    from cloudberry_tpu.sched import sharedcache

    root = str(tmp_path / "store")
    _mk_store(root, n=160_000, nseg=4)
    s = _open(root, nseg=4, budget=1 << 20)
    monkeypatch.setattr(sharedcache, "config_uid", lambda cfg: 0)
    first = None
    for _ in range(3):
        first = s.sql(AGG_Q).to_pandas()
    rep = s.last_tiled_report
    assert rep["tiled"] and rep["n_tiles"] > 1
    pool = BUF.pool_for(s)
    assert pool.snapshot()["entries"] > 0
    cols = (("g", "v"), ())

    def snap_keys():
        # collapse every ALIASABLE component (config uid, store/table
        # version — both genuinely can alias: a pure failover shrink
        # moves nothing) so the topology token is the only live
        # distinguisher, exactly the stale-nseg construction
        with monkeypatch.context() as m:
            m.setattr(sharedcache, "config_uid", lambda cfg: 0)
            m.setattr(sharedcache, "table_key",
                      lambda sess, name: (name, "sv", 7))
            m.setattr(s.catalog.store, "effective_version",
                      lambda name: 7)
            return (BUF.dist_tile_key(s, "fact", cols, 4, 1024, 0),
                    BUF.partition_key(s, "fact", {"file": "f0"},
                                      ("g", "v")))

    k1, p1 = snap_keys()
    s._topology.online_resize(6)
    s._topology.online_resize(4)  # same nseg as epoch 1 again
    # eager drop at cutover: stale keys could never serve, but the HBM
    # bytes are placement-era garbage — freed immediately
    snap = pool.snapshot()
    assert snap["entries"] == 0 and snap["bytes"] == 0
    k3, p3 = snap_keys()
    for old, new in ((k1, k3), (p1, p3)):
        assert old != new
        assert old[:-2] == new[:-2] and old[-1] == new[-1], \
            "keys must alias everywhere except the topology token"
        assert old[-2] != new[-2]
    # end-to-end: the re-warmed pool only ever holds current-token
    # entries and the answer stays bit-identical
    h0 = pool.snapshot()["hits"]
    for _ in range(3):
        assert first.equals(s.sql(AGG_Q).to_pandas())
    assert pool.snapshot()["hits"] > h0  # re-admitted AND re-served
    tok = sharedcache.topology_token(s)
    with pool._lock:
        keys = list(pool._entries)
    assert keys and all(k[-2] == tok for k in keys)


@pytest.mark.slow
def test_degraded_shrink_resume_stale_epoch_never_serves():
    """8->7 mid-statement: a tiled distributed statement killed by
    device loss resumes AFTER a shrink cutover landed during its
    backoff. The warm epoch-8 pool entries are dropped at the flip and
    the resumed attempt re-keys at the new token — bit-identical, with
    no stale-epoch entry resident afterwards."""
    from cloudberry_tpu.sched import sharedcache

    s = cb.Session(get_config().with_overrides(**{
        "n_segments": 8, "resource.query_mem_bytes": 512 << 10,
        "recovery.checkpoint_every": 2, "health.retries": 2,
        "health.backoff_s": 1.0, "health.backoff_max_s": 1.0}))
    s.sql("create table big (k bigint, g bigint, v bigint) "
          "distributed by (k)")
    n = 400_000
    rng = np.random.default_rng(7)
    s.catalog.table("big").set_data(
        {"k": np.arange(n, dtype=np.int64) % 997,
         "g": rng.integers(0, 9, n).astype(np.int64),
         "v": rng.integers(0, 1000, n).astype(np.int64)}, {})
    q = "select g, sum(v) as sv from big group by g order by g"
    expected = s.sql(q).to_pandas()
    assert s.last_tiled_report is not None
    assert s.last_tiled_report["n_tiles"] >= 3
    s.sql(q)  # second scan: partitions admit — the pool is warm
    pool = BUF.pool_for(s)
    warm = pool.snapshot()["entries"] if pool is not None else 0
    tok_before = sharedcache.topology_token(s)
    FI.inject_fault("tile_device_lost", "error", start_hit=3, end_hit=3)
    done = {}

    def run():
        done["df"] = s.sql(q).to_pandas()

    th = threading.Thread(target=run)
    th.start()
    deadline = time.monotonic() + 10
    rows = []
    while time.monotonic() < deadline:
        rows = [r for r in s.stmt_log.activity()
                if r.get("state") == "recovering"]
        if rows:
            break
        time.sleep(0.01)
    assert rows, "statement never entered recovery"
    s._topology.begin(7)
    s._topology.rebalance()
    s._topology.cutover(wait_s=0.0)  # shrink under the in-flight stmt
    th.join(timeout=60)
    assert "df" in done and expected.equals(done["df"])
    assert s.config.n_segments == 7
    assert s.stmt_log.counter("tile_resumes") >= 1
    tok = sharedcache.topology_token(s)
    assert tok != tok_before
    if pool is not None and warm:
        with pool._lock:
            keys = list(pool._entries)
        assert all(k[-2] == tok for k in keys), \
            "an epoch-8 entry survived the shrink cutover"


# --------------------------------------------- pool on/off bit-identity


# per-mode shapes mirroring test_scan_pipeline's single/dist8 matrix:
# the dist8 (nseg, tile_rows) tile covers 8x the single-node rows, so it
# streams multiple tiles at a tighter budget; the dist8 window needs
# every partition to fit one spill chunk, so it runs finer groups over
# more rows at the budget whose chunk capacity holds them
# the dist8 rows are slow-tier: they need 240k rows to stream >1 tile
# per segment, and on a single-core host the four of them cost ~20s of
# the tier-1 wall budget for coverage the single-node rows already pin
_slow = pytest.mark.slow
_MATRIX = [(AGG_Q, None, 1, 1 << 20, 120_000, 9),
           (TOPN_Q, "topn", 1, 1 << 20, 120_000, 9),
           (SORT_Q, "sort", 1, 1 << 20, 120_000, 9),
           (WIN_Q, "window", 1, 2 << 20, 60_000, 9),
           pytest.param(AGG_Q, None, 8, 1 << 20, 240_000, 9,
                        marks=_slow),
           pytest.param(TOPN_Q, "topn", 8, 1 << 20, 240_000, 9,
                        marks=_slow),
           pytest.param(SORT_Q, "sort", 8, 1 << 20, 240_000, 9,
                        marks=_slow),
           pytest.param(WIN_Q, "window", 8, 4 << 20, 240_000, 300,
                        marks=_slow)]


@pytest.mark.parametrize("q,mode,nseg,budget,n,n_groups", _MATRIX)
def test_pool_on_off_bit_identical(tmp_path, q, mode, nseg, budget, n,
                                   n_groups):
    """Every tiled mode, single-node and dist8: pool-on runs covering
    miss+admit then serve-from-HBM all equal the pool-off answer
    (admit_min_scans=1 so the second run already serves)."""
    root = str(tmp_path / "store")
    _mk_store(root, nseg=nseg, n=n, n_groups=n_groups)
    off = _open(root, nseg=nseg, budget=budget, pool=False)
    expected = off.sql(q).to_pandas()
    rep = off.last_tiled_report
    assert rep["tiled"] and rep["n_tiles"] > 1
    if mode is not None:
        assert rep["mode"] == mode
    s = _open(root, nseg=nseg, budget=budget,
              **{"bufferpool.admit_min_scans": 1})
    for i in range(2):
        h0 = s.stmt_log.counter("bufpool_hits")
        assert expected.equals(s.sql(q).to_pandas())
        if i == 1:
            assert s.stmt_log.counter("bufpool_hits") > h0, \
                "second scan must serve from the pool"


def test_pool_on_device_loss_resume_bit_identical(tmp_path):
    """Mid-statement device loss on a WARM pool: the resumed attempt
    (which mixes resident chunks, skipped partitions, and fresh reads)
    is bit-identical to the pool-off answer."""
    root = str(tmp_path / "store")
    _mk_store(root)
    off = _open(root, budget=1 << 20, pool=False)
    expected = off.sql(AGG_Q).to_pandas()
    s = _open(root, budget=1 << 20, **{
        "recovery.checkpoint_every": 2, "health.retries": 2,
        "health.backoff_s": 0.01})
    s.sql(AGG_Q), s.sql(AGG_Q)  # warm: partitions resident
    assert s.last_tiled_report["n_tiles"] >= 3
    FI.inject_fault("tile_device_lost", "error", start_hit=3, end_hit=3)
    assert expected.equals(s.sql(AGG_Q).to_pandas())
    rep = s.last_tiled_report
    assert rep["resumed_from_tile"] >= 1
    assert rep["pipeline"]["parts_resident"] > 0


# ------------------------------------------------------ concurrency/locks


@pytest.mark.slow  # the witness instruments every lock: ~4s fixed cost
def test_four_thread_stress_clean_under_witness():
    """4 threads hammer lookup/offer/sweep over overlapping keys with a
    live StatementLog: the runtime lock-order witness records zero
    violations (pool lock is a leaf; counter bumps and fault seams run
    outside it), the byte budget holds, and the accounting stays
    internally consistent."""
    from cloudberry_tpu.exec.instrument import StatementLog
    from cloudberry_tpu.lint import witness

    pool = BUF.BufferPool(max_bytes=16 * _NB, admit_min_scans=2)
    log = StatementLog()
    errs = []

    def worker(tid):
        try:
            for i in range(100):
                k = ("part", "t", 1, f"p{(tid * 7 + i) % 24}",
                     ("v",), 1, 0)
                if pool.lookup(k, log) is None:
                    pool.offer(k, _ent(seed=tid), table="t", log=log,
                               device=False)
                if i % 40 == 0:
                    pool.sweep(lambda kk: kk[3] == f"p{tid}")
        except Exception as e:  # noqa: BLE001 — assertion target
            errs.append(e)

    witness.install()
    try:
        witness.reset_violations()
        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert witness.violations() == []
    finally:
        witness.uninstall()
        witness.reset_violations()
    assert not errs
    snap = pool.snapshot()
    assert snap["bytes"] <= snap["max_bytes"]
    with pool._lock:
        assert pool.bytes == sum(nb for _, nb, _
                                 in pool._entries.values())
        assert len(pool._entries) == snap["entries"]


def test_serve_bench_hotcold_smoke():
    """serve_bench --mix hotcold CPU smoke (ISSUE 16): a hot store
    table scanned by the SAME tiled aggregate against a cold table
    under a pool budget that holds only the hot set. The run's CSV row
    carries the pool columns, and the after-window probe pins the
    acceptance claim by counters: the pool-warm hot scan pays ZERO
    host decodes and runs at higher rows/s than the cold scan of the
    same-size container."""
    import tools.serve_bench as SB

    r = SB.run_mode("direct", "hotcold", clients=2, duration_s=1.2,
                    rows=30_000, tick_s=0.002, max_batch=8)
    assert r["requests"] > 0
    assert r["mix"] == "hotcold"
    # the hot set went device-resident during the window: pool hits
    # flowed, while the cold set kept the host decoders busy
    assert r["bufpool_hit_rate"] > 0
    assert r["host_decodes"] > 0
    # the probe's counter-pinned claim: zero host reads/decodes for
    # the hot scan, at least one decode for the cold one — and the
    # pool-served scan is measurably faster on the same row count
    assert r["_hot_host_decodes"] == 0
    assert r["_cold_host_decodes"] > 0
    assert r["_hot_rows_per_s"] > r["_cold_rows_per_s"]
    row = SB.csv_row(r)
    assert len(row.split(",")) == len(SB.CSV_HEADER.split(","))


def test_scan_bench_hot_point_smoke(tmp_path):
    """tools/scan_bench.py hot_point CPU smoke: the second-pass
    buffer-pool ladder record at toy SF — the pool pass serves every
    chunk (hit rate 1.0, zero host decodes), beats no-pool wall, and
    is bit-identical to the admission pass."""
    import tools.scan_bench as sb

    p = sb.hot_point(0.01, root=str(tmp_path / "st"), budget=1 << 20)
    assert p["bufpool_hit_rate"] == 1.0
    assert p["host_decodes_pool_pass"] == 0
    assert p["bufpool_admits"] > 0
    assert p["bit_identical"]
    assert p["rows_per_s_pool"] > 0 and p["rows_per_s_cold"] > 0


# ------------------------------------------------ slow tier: SF10 TPC-H


@pytest.mark.slow
def test_tpch_tiled_dist_sf10_second_pass_hit_rates(tmp_path):
    """Carried evidence debt (ROADMAP round 15): FULL TPC-H — not just
    the scan shape — through tiled_dist at SF10 in the slow tier, each
    query run twice in ONE session with first-scan admission
    (admit_min_scans=1) so the SECOND pass is served by the buffer
    pool, recording per-query second-pass hit rates as one JSON line
    (TPCH_POOL_HIT_RATES ...). Env knobs for smaller rehearsals and
    real hardware: CBTPU_TPCH_SF (default 10), CBTPU_TPCH_BUDGET
    (tiled admission budget, default 64MB), CBTPU_POOL_BYTES (pool
    budget, default 4GB — size to the HBM actually present). Every
    completed query must be bit-identical across passes; a query the
    tiled path cannot express at this budget is recorded as refused,
    never silently skipped."""
    import json
    import os

    from tools.tpch_queries import QUERIES
    from tools.tpchgen import stream_load_tpch

    sf = float(os.environ.get("CBTPU_TPCH_SF", "10"))
    budget = int(os.environ.get("CBTPU_TPCH_BUDGET", str(64 << 20)))
    pool_bytes = int(os.environ.get("CBTPU_POOL_BYTES", str(4 << 30)))
    root = str(tmp_path / "tpch")
    loader = _open(root, nseg=8)
    stream_load_tpch(loader, sf=sf, seed=1)
    s = _open(root, nseg=8, budget=budget,
              **{"bufferpool.max_bytes": pool_bytes,
                 "bufferpool.admit_min_scans": 1})
    log = s.stmt_log
    record: dict = {}
    for qn in sorted(QUERIES):
        try:
            first = s.sql(QUERIES[qn]).to_pandas()
        except Exception as e:  # noqa: BLE001 — recorded, not hidden
            record[qn] = {"outcome":
                          f"refused: {type(e).__name__}: {e}"[:200]}
            continue
        before = {c: log.counter(c) for c in
                  ("bufpool_hits", "bufpool_misses", "host_decodes")}
        try:
            second = s.sql(QUERIES[qn]).to_pandas()
        except Exception as e:  # noqa: BLE001 — rung growth can push a
            # replay past a tight budget; record it, never hide it
            record[qn] = {"outcome":
                          f"refused_2nd: {type(e).__name__}: {e}"[:200]}
            continue
        hits = log.counter("bufpool_hits") - before["bufpool_hits"]
        miss = log.counter("bufpool_misses") - before["bufpool_misses"]
        rep = s.last_tiled_report
        record[qn] = {
            "outcome": "ok",
            "tiled": bool(rep and rep.get("tiled")),
            "bufpool_hit_rate": round(hits / (hits + miss), 4)
            if hits + miss else None,
            "host_decodes_2nd": log.counter("host_decodes")
            - before["host_decodes"],
        }
        assert list(first.columns) == list(second.columns), qn
        for col in first.columns:
            a = first[col].to_numpy()
            b = second[col].to_numpy()
            assert a.shape == b.shape, f"{qn}.{col}"
            if a.dtype.kind == "f":
                same = (a == b) | (np.isnan(a) & np.isnan(b))
            else:
                same = a == b
            assert np.all(same), f"{qn}.{col} second pass diverged"
    print("\nTPCH_POOL_HIT_RATES " + json.dumps(record, sort_keys=True))
    ok = [q for q, r in record.items() if r["outcome"] == "ok"]
    assert ok, f"no TPC-H query completed: {record}"
    served = [q for q, r in record.items()
              if (r.get("bufpool_hit_rate") or 0) > 0]
    assert served, f"no second pass saw pool traffic: {record}"
    if sf >= 1:
        # at real scale the scan-heavy core MUST run tiled with
        # second-pass pool traffic (a rehearsal SF may fit in memory)
        for qn in ("q1", "q6"):
            r = record.get(qn, {})
            assert r.get("outcome") == "ok", f"{qn}: {r}"
            assert r.get("tiled"), f"{qn} did not tile: {r}"
            assert r.get("bufpool_hit_rate") is not None, f"{qn}: {r}"
