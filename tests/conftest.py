"""Test harness: 8 virtual CPU devices — the demo-cluster analog.

The reference tests multi-node behavior with N postmasters on localhost
(gpMgmt/demo, SURVEY.md §4.2); we test multi-chip behavior with N virtual XLA
CPU devices. Must run before jax initializes.
"""

import os

# sitecustomize imports jax at interpreter start, so env-var-only control is
# too late; jax.config still works because no backend is initialized yet.
os.environ["JAX_PLATFORMS"] = "cpu"  # the terminal presets axon (real TPU)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def session():
    import cloudberry_tpu as cb

    return cb.Session()
