"""Test harness: 8 virtual CPU devices — the demo-cluster analog.

The reference tests multi-node behavior with N postmasters on localhost
(gpMgmt/demo, SURVEY.md §4.2); we test multi-chip behavior with N virtual XLA
CPU devices. Must run before jax initializes.
"""

import os

# sitecustomize imports jax at interpreter start, so env-var-only control is
# too late; jax.config still works because no backend is initialized yet.
os.environ["JAX_PLATFORMS"] = "cpu"  # the terminal presets axon (real TPU)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    # 'slow' marks the opt-out tier: tier-1 runs `-m 'not slow'` under a
    # hard wall-clock cap (ROADMAP.md); slow tests run in the full suite
    config.addinivalue_line(
        "markers", "slow: excluded from the capped tier-1 run")


# Duration-based re-tiering (tier-1 overran its 870s cap): the slowest
# tests whose coverage a cheaper tier-1 sibling retains move to the slow
# tier — single-segment variants stay for every marked dist8 case, q3
# stays for the marked q10 packed-parity pins, the memo module keeps its
# behavior tests while its perf-property searches move, and the spill
# modules keep one representative of each recognized spine. Node-id
# suffixes so fixture-parametrized products can be tiered individually.
_SLOW_TIER = (
    "test_spill_dist.py::test_dist_merge_overflow_grows_accumulator",
    "test_spill_dist.py::test_dist_tiled_topn_matches_in_memory",
    "test_spill_sort_window.py::test_window_spill_matches_in_memory"
    "[dist8]",
    "test_spill_sort_window.py::test_skewed_redistribute_grows_bucket",
    "test_spill.py::test_tiled_spine_expansion_join",
    "test_packed_motion.py::test_tpch_packed_parity_pinned[q10-seg1]",
    "test_packed_motion.py::test_tpch_packed_parity_pinned[q10-seg8]",
    "test_memo.py::test_memo_region_survives_out_of_grammar_sibling",
    "test_memo.py::test_memo_equivalence_random_queries",
    "test_memo.py::test_memo_lookahead_beats_greedy_threshold",
    "test_memo.py::test_joint_order_beats_row_dp",
    "test_tpcds_round5.py::test_tpcds_round5[dist8-q59]",
    "test_tpcds_round5.py::test_tpcds_round5[dist8-q38]",
    "test_tpcds_round5.py::test_tpcds_round5[dist8-q74]",
    "test_tpcds_round5.py::test_tpcds_round5[dist8-q33]",
    "test_tpcds.py::test_tpcds_distributed[q17]",
    "test_tpcds.py::test_tpcds_distributed[q25]",
    "test_tpcds.py::test_tpcds_distributed[q29]",
    # round 5 (PR 5 margin): more dist8 variants whose single-segment
    # sibling stays tier-1; the tiled-dist q5/q9 sweep keeps its
    # single-segment twin (test_spill.py::test_tpch_q5_q9_tiled), and
    # digest-parity q5-dist8 stays covered by the slow full sweep
    # (test_join_filter.py::test_tpch_digest_parity_full_sweep) while
    # q3/q10 dist8 + the whole single-segment subset remain tier-1.
    "test_spill_dist.py::test_tpch_q5_q9_tiled_distributed",
    "test_cte.py::test_q15_as_cte[dist8]",
    "test_cte.py::test_shared_cte_self_join[dist8]",
    "test_join_filter.py::test_tpch_digest_parity_dist8[q5]",
    "test_window_longtail.py::test_range_offset_min_max[dist8]",
    "test_window_longtail.py::test_rows_frame_min_max[dist8]",
    "test_window_longtail.py::test_range_offset_can_be_empty[dist8]",
    "test_window_longtail.py::test_range_offset_month_year_interval"
    "[dist8]",
    "test_spill_sort_window.py::test_external_sort_matches_in_memory"
    "[dist8]",
    "test_spill_dist.py::test_dist_tiled_join_group_matches_in_memory",
    "test_pallas.py::test_tiled_dist_matches_xla_fused",
    "test_cte.py::test_basic_cte[dist8]",
    "test_grouping_sets.py::test_cube[dist8]",
    "test_setop_all.py::test_running_extreme_null_never_beats_dtype_extreme"
    "[seg8]",
    "test_dqa.py::test_mixed_distinct_and_plain[dist8]",
    "test_spill_sort_window.py::test_huge_offset_limit_falls_back_to_sort"
    "[dist8]",
    "test_window_longtail.py::test_range_offset_first_last_value[dist8]",
    "test_tpcds.py::test_tpcds_distributed[q65]",
    "test_tpcds.py::test_tpcds_distributed[q98]",
    "test_distributed.py::test_tpch_distributed[q2]",
    "test_distributed.py::test_tpch_distributed[q8]",
    # round 7 (PR 7 margin): the single-node kill matrix + degraded-dist
    # recovery tests stay tier-1 while the dist8 kill matrix moves; the
    # dist topn OFFSET variant keeps its single-node twin
    # (test_spill.py::test_tiled_topn_offset_and_desc) and the plain
    # dist topn stays covered slow-tier; digest-parity q5 single rides
    # the slow full sweep like q5 dist8 already does (q3/q10 both stay).
    "test_recovery.py::test_tiled_dist_kill_matrix",
    "test_spill_dist.py::test_dist_tiled_topn_offset",
    "test_join_filter.py::test_tpch_digest_parity_single[q5]",
    # round 8 (PR 8 margin — lint gate + witness fixtures + taxonomy
    # suite joined tier-1): more dist8/heavy variants whose cheaper
    # sibling stays — dist degraded-resume keeps the colocated-declines
    # dist8 case + the single-node resume matrix; the dist statement-
    # cache/colocated-agg pair keep their single-node twins in
    # test_spill.py; digest-parity q10-dist8 keeps q3-dist8 + the q10
    # single-seg subset; lead-offset/packed-redistribute/generic-q3
    # keep their single/seg1 twins; four more TPC-H dist8 queries keep
    # their test_tpch_query single-seg siblings (q2/q8 precedent); DS
    # q86/q60 keep their single-seg runs.
    "test_recovery.py::test_dist_degraded_resume",
    "test_spill_dist.py::test_dist_tiled_statement_cache_reuses_runner",
    "test_spill_dist.py::test_dist_tiled_colocated_one_stage_agg",
    "test_join_filter.py::test_tpch_digest_parity_dist8[q10]",
    "test_window_longtail.py::test_lead_offset_and_default[dist8]",
    "test_packed_motion.py::test_packed_matches_percol_all_motion_kinds"
    "[redistribute-seg8]",
    "test_generic_parity.py::test_subset_parity_dist8[q3]",
    "test_distributed.py::test_tpch_distributed[q7]",
    "test_distributed.py::test_tpch_distributed[q13]",
    "test_distributed.py::test_tpch_distributed[q20]",
    "test_distributed.py::test_tpch_distributed[q21]",
    "test_tpcds.py::test_tpcds_distributed[q86]",
    "test_tpcds_round5.py::test_tpcds_round5[dist8-q60]",
    # round 17 (feedback/adaptive tests join tier-1): the five worst
    # remaining offenders (~72s) move — the two-host cluster parity and
    # host-rung/hier bit-identity sweeps keep their cheaper siblings
    # (test_hier_queries q3 parity, host-combine stamp parity, and the
    # ic_bench two-level smoke all stay tier-1; multihost keeps its
    # worker-level transport tests; degraded-progress monotonicity
    # keeps the single-kill recovery matrix already in tier 1).
    "test_multihost.py::test_two_host_cluster_matches_single_host",
    "test_hier_motion.py::test_hier_queries_bit_identical",
    "test_hier_motion.py::test_tiled_dist_hier_parity",
    "test_hier_motion.py::test_host_rung_overflow_promotes_and_retries",
    "test_capacity_forensics.py::test_progress_monotone_degraded_8_to_7",
    # round 18 (write-path suite joins tier-1): the two consumers of the
    # module-scoped adaptive_expected fixture move together (the fixture
    # build alone is ~39s; moving only one test would just shift it to
    # the other) — the feedback plane keeps its tier-1 coverage via the
    # fold/persistence/invalidation tests plus the rung-downgrade and
    # bench-counter paths; the expand-cutover checkpoint-resume test
    # keeps its cheaper cutover siblings (stale-nseg, epoch-pin,
    # under-load cutover) in tier 1.
    "test_feedback.py::test_midstatement_adaptive_replan",
    "test_feedback.py::test_fault_skip_suppresses_adaptation",
    "test_topology.py::test_checkpointed_statement_resumes_across_expand_cutover",
    # round 19 (crash-torture + iofault suites join tier-1): more
    # dist8/heavy variants whose cheaper sibling stays — seven more
    # TPC-H dist8 queries keep their test_tpch_query single-seg
    # siblings (q2/q8 precedent), DS distributed/round5 dist8 cases
    # keep their single-seg runs, digest-parity q3-dist8 now rides the
    # slow full sweep like q5/q10 already do (the whole single-seg
    # digest subset minus q5 stays tier-1), packed-parity q3-seg8
    # keeps q3-seg1, and the dist global agg keeps its single-node
    # twin (test_spill.py::test_tiled_global_agg).
    "test_join_filter.py::test_tpch_digest_parity_dist8[q3]",
    "test_tpcds_round5.py::test_tpcds_round5[dist8-q43]",
    "test_tpcds_round5.py::test_tpcds_round5[dist8-q94]",
    "test_tpcds_round5.py::test_tpcds_round5[dist8-q97]",
    "test_tpcds_round5.py::test_tpcds_round5[dist8-q16]",
    "test_tpcds_round5.py::test_tpcds_round5[dist8-q56]",
    "test_distributed.py::test_tpch_distributed[q9]",
    "test_distributed.py::test_tpch_distributed[q15]",
    "test_distributed.py::test_tpch_distributed[q10]",
    "test_distributed.py::test_tpch_distributed[q18]",
    "test_distributed.py::test_tpch_distributed[q17]",
    "test_distributed.py::test_tpch_distributed[q22]",
    "test_distributed.py::test_tpch_distributed[q11]",
    "test_tpcds.py::test_tpcds_distributed[q36]",
    "test_tpcds.py::test_tpcds_distributed[q20]",
    "test_tpcds.py::test_tpcds_distributed[q42]",
    "test_tpcds.py::test_tpcds_distributed[q27]",
    "test_tpcds.py::test_tpcds_distributed[q55]",
    "test_tpcds.py::test_tpcds_distributed[q12]",
    "test_packed_motion.py::test_tpch_packed_parity_pinned[q3-seg8]",
    "test_spill_dist.py::test_dist_tiled_global_agg",
    # round 20 (windowed tile-dispatch suite joins tier-1, ~75s): more
    # dist8 TPC-H/DS queries whose single-seg twins stay tier-1 (the
    # q2/q8 precedent continues), and the windowed suite's own heaviest
    # dist8 case — the window-mode spill query — rides slow while its
    # three dist8 siblings (agg/topn/sort) and the full single-node
    # W∈{1,2,4} matrix stay tier-1.
    "test_distributed.py::test_tpch_distributed[q16]",
    "test_distributed.py::test_tpch_distributed[q19]",
    "test_distributed.py::test_tpch_distributed[q12]",
    "test_tpcds.py::test_tpcds_distributed[q21]",
    "test_tpcds.py::test_tpcds_distributed[q52]",
    "test_tilepipe.py::test_window_bit_identical_dist8[window]",
)


# Environment skips, PINNED (ISSUE 19 triage): tests whose only failure
# mode is a dependency this image does not ship skip with the reason
# spelled out instead of failing — tier-1 signal must be clean so a real
# regression (e.g. in the crash matrix) is never lost in known noise.
# The pin is the explicit node-id list: only THESE tests may skip for
# the named module, and they run normally wherever the module exists.
_ENV_SKIPS = (
    ("cryptography", (
        "test_tde.py::test_roundtrip_under_encryption",
        "test_tde.py::test_no_plaintext_on_disk",
        "test_tde.py::test_wrong_or_missing_key_refused",
        "test_dirtable.py::test_directory_table_tde",
    )),
)


def _module_missing(name: str) -> bool:
    import importlib.util

    try:
        return importlib.util.find_spec(name) is None
    except (ImportError, ValueError):
        return True


def pytest_collection_modifyitems(config, items):
    env_skips = {}
    for mod, nodeids in _ENV_SKIPS:
        if _module_missing(mod):
            mark = pytest.mark.skip(
                reason=f"needs the {mod!r} package (not in this image)")
            for nid in nodeids:
                env_skips[nid] = mark
    for item in items:
        if item.nodeid.endswith(_SLOW_TIER):
            item.add_marker(pytest.mark.slow)
        for nid, mark in env_skips.items():
            if item.nodeid.endswith(nid):
                item.add_marker(mark)


@pytest.fixture
def session():
    import cloudberry_tpu as cb

    return cb.Session()
