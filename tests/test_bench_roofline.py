"""bench.py roofline context (VERDICT r5 item 8): every emitted speedup
carries a bytes-scanned ÷ HBM-bandwidth denominator, including REPLAY
mode where the bytes come from the static schema estimate."""

import bench


def test_static_scan_bytes_scales_with_sf():
    b1 = bench.static_scan_bytes("q1", 1.0)
    b01 = bench.static_scan_bytes("q1", 0.1)
    # q1 scans 44 bytes per lineitem row
    assert b1 == int(6_001_215 * 44)
    assert abs(b01 * 10 - b1) / b1 < 1e-6
    assert bench.static_scan_bytes("q99", 1.0) is None


def test_roofline_context_replay_and_live():
    # replay shape: denominator only (no wall times)
    rep = bench.roofline_context(["q1", "q3"], 1.0)
    assert rep["hbm_gbps_nominal"] > 0
    assert set(rep["per_query"]) == {"q1", "q3"}
    for rec in rep["per_query"].values():
        assert rec["bytes_scanned"] > 0
        assert "hbm_frac" not in rec
    # live shape: measured bytes + wall time → achieved GB/s + HBM frac
    live = bench.roofline_context(
        ["q1"], 1.0, bytes_by_q={"q1": 2_000_000_000},
        wall_by_q={"q1": 0.01})
    rec = live["per_query"]["q1"]
    assert rec["scan_gbps"] == 200.0
    assert 0 < rec["hbm_frac"] < 1
