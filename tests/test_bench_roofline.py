"""bench.py roofline context (VERDICT r5 item 8): every emitted speedup
carries a bytes-scanned ÷ HBM-bandwidth denominator, including REPLAY
mode where the bytes come from the static schema estimate — and, since
the packed-wire motion PR, an interconnect record (collective launches +
bytes-on-wire per query at the 8-segment plan shape)."""

import bench


def test_static_scan_bytes_scales_with_sf():
    b1 = bench.static_scan_bytes("q1", 1.0)
    b01 = bench.static_scan_bytes("q1", 0.1)
    # q1 scans 44 bytes per lineitem row
    assert b1 == int(6_001_215 * 44)
    assert abs(b01 * 10 - b1) / b1 < 1e-6
    assert bench.static_scan_bytes("q99", 1.0) is None


def test_roofline_context_replay_and_live():
    # replay shape: denominator only (no wall times)
    rep = bench.roofline_context(["q1", "q3"], 1.0)
    assert rep["hbm_gbps_nominal"] > 0
    assert set(rep["per_query"]) == {"q1", "q3"}
    for rec in rep["per_query"].values():
        assert rec["bytes_scanned"] > 0
        assert "hbm_frac" not in rec
    # live shape: measured bytes + wall time → achieved GB/s + HBM frac
    live = bench.roofline_context(
        ["q1"], 1.0, bytes_by_q={"q1": 2_000_000_000},
        wall_by_q={"q1": 0.01})
    rec = live["per_query"]["q1"]
    assert rec["scan_gbps"] == 200.0
    assert 0 < rec["hbm_frac"] < 1


def test_interconnect_context_records_shuffle_volume():
    """The bench JSON's interconnect record: metadata-only planning at 8
    segments totals every motion's launches and bytes-on-wire, packed vs
    per-column — packed must need fewer launches AND fewer bytes."""
    import cloudberry_tpu as cb
    from tools.tpchgen import load_tpch

    s = cb.Session()
    load_tpch(s, sf=0.01, seed=3, tables=["lineitem", "orders",
                                          "customer", "nation"])
    ic = bench.interconnect_context(s, ["q3", "q10"], nseg=8)
    assert ic["n_segments"] == 8
    for qn in ("q3", "q10"):
        rec = ic["per_query"][qn]
        assert rec["motions"] >= 1
        assert rec["launches_packed"] == rec["motions"]
        assert rec["launches_percol"] > rec["launches_packed"]
        # same bucket shapes in this static accounting, so packed pays
        # only the word-alignment overhead — pinned small; the real
        # padded-bytes win (adaptive rung vs worst-case static buckets)
        # is measured live by tools/ic_bench.py --format packed|percol
        assert 0 < rec["wire_bytes_packed"] \
            < 1.25 * rec["wire_bytes_percol"]
    # the metadata pass must not have materialized 8-segment shard
    # arrays on the 1-segment session (counts-only planning fast path)
    assert not any(k.endswith("@8") for k in s._shard_cache)
