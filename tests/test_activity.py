"""Statement log + activity views (exec/instrument.py StatementLog) —
the pg_stat_activity / log-collector analog."""

import threading
import time

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config
from cloudberry_tpu.serve.client import Client
from cloudberry_tpu.serve.server import Server
from cloudberry_tpu.utils import faultinject as FI


@pytest.fixture(autouse=True)
def _clean():
    FI.reset_fault()
    yield
    FI.reset_fault()


def test_statement_log_records_history():
    s = cb.Session()
    s.sql("create table a (x bigint)")
    s.sql("insert into a values (1),(2)")
    df = s.sql("select * from a")
    assert df.num_rows() == 2
    rec = s.stmt_log.recent()
    assert [r["sql"] for r in rec[:3]] == [
        "select * from a", "insert into a values (1),(2)",
        "create table a (x bigint)"]
    assert rec[0]["status"] == "ok" and rec[0]["rows"] == 2
    assert rec[1]["status"].startswith("INSERT")
    assert all(r["wall_s"] >= 0 for r in rec)


def test_statement_log_records_errors():
    s = cb.Session()
    with pytest.raises(Exception):
        s.sql("select * from nope")
    rec = s.stmt_log.recent()
    assert rec[0]["status"] == "error" and "nope" in rec[0]["error"]


def test_activity_shows_running_statement():
    s = cb.Session()
    s.sql("create table b (x bigint)")
    s.catalog.table("b").set_data({"x": np.arange(64, dtype=np.int64)})
    FI.inject_fault("dispatch_start", "sleep", sleep_s=1.5)
    seen = []

    def run():
        s.sql("select sum(x) from b")

    t = threading.Thread(target=run)
    t.start()
    deadline = time.time() + 10
    while time.time() < deadline:
        act = s.stmt_log.activity()
        if act:
            seen = act
            break
        time.sleep(0.05)
    t.join()
    assert seen and seen[0]["sql"] == "select sum(x) from b"
    assert seen[0]["elapsed_s"] >= 0
    assert s.stmt_log.activity() == []  # drained after completion


def test_activity_spans_server_connections(tmp_path):
    cfg = get_config().with_overrides(**{"storage.root": str(tmp_path)})
    boot = cb.Session(cfg)
    boot.sql("create table w (x bigint)")
    boot.sql("insert into w values (1)")
    with Server(config=cfg, port=0) as srv:
        with Client(srv.host, srv.port) as c1, \
                Client(srv.host, srv.port) as c2:
            c1.sql("select count(*) from w")
            c2.sql("select sum(x) from w")
            act = c1.meta("activity")
            sqls = [r["sql"] for r in act["recent"]]
            # BOTH connections' statements in one log, newest first
            assert "select sum(x) from w" in sqls
            assert "select count(*) from w" in sqls


def test_ring_buffer_bounded():
    from cloudberry_tpu.exec.instrument import StatementLog

    log = StatementLog(capacity=8)
    for i in range(50):
        sid = log.begin(f"q{i}")
        log.finish(sid, "ok")
    rec = log.recent(100)
    assert len(rec) == 8 and rec[0]["sql"] == "q49"
