"""ISSUE 9 observability plane: the metrics registry, statement trace
spans, the pg_stat_statements analog, EXPLAIN ANALYZE through the
statement pipeline, and the meta wire surface — all pinned."""

import json
import threading
import time

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.obs.metrics import MetricsRegistry
from cloudberry_tpu.obs.statements import StatementStats


# ------------------------------------------------------------- registry


def test_registry_counters_gauges_hists():
    r = MetricsRegistry()
    r.bump("a")
    r.bump("a", 4)
    r.bump("b", 2, tenant="gold")
    r.gauge("depth", 7)
    for v in (0.001, 0.002, 0.004, 0.1):
        r.observe("lat", v)
    assert r.counter("a") == 5
    assert r.counter("b") == 2  # labeled bumps ride the total too
    snap = r.snapshot()
    assert snap["labeled_counters"] == {"b{tenant=gold}": 2}
    assert snap["gauges"]["depth"] == 7.0
    h = snap["histograms"]["lat"]
    assert h["count"] == 4 and h["sum"] == pytest.approx(0.107)
    # log2-bucket quantiles are conservative upper bounds
    assert h["p50"] >= 0.002 and h["p99"] >= 0.1
    text = r.exposition()
    assert "# TYPE cbtpu_a counter" in text and "cbtpu_a 5" in text
    # labeled series live under a DISTINCT metric name: sum() over the
    # unlabeled total must never double-count the tenant partitions
    assert 'cbtpu_b_by_tenant{tenant="gold"} 2' in text
    assert "# TYPE cbtpu_b_by_tenant counter" in text
    assert "cbtpu_lat_bucket" in text and "cbtpu_lat_count 4" in text


def test_registry_series_bound():
    r = MetricsRegistry(max_series=4)
    for i in range(10):
        r.bump(f"c{i}")
    snap = r.snapshot()
    assert len(snap["counters"]) == 4
    assert snap["series_dropped"] == 6


def test_counter_view_is_registry_backed():
    log = cb.Session().stmt_log
    log.bump("xyz", 3)
    assert log.counters["xyz"] == 3
    assert log.counters.get("xyz") == 3
    assert log.counter_snapshot()["xyz"] == 3
    assert "xyz" in log.counters
    assert dict(log.counters.items())["xyz"] == 3


# ------------------------------------------------------ honest split


class _FakeJit:
    """No .lower(): exercises the two-call fallback. First call sleeps
    compile+execute, later calls execute only."""

    def __init__(self, compile_s, exec_s):
        self.compile_s = compile_s
        self.exec_s = exec_s
        self.calls = 0

    def __call__(self, inputs):
        self.calls += 1
        time.sleep(self.exec_s + (self.compile_s if self.calls == 1
                                  else 0.0))
        return np.zeros(1)


class _FakeAot:
    """AOT API stub: lower().compile() pays the compile cost, the
    compiled callable pays only execution."""

    def __init__(self, compile_s, exec_s):
        self.compile_s = compile_s
        self.exec_s = exec_s

    def lower(self, inputs):
        outer = self

        class _L:
            def compile(self):
                time.sleep(outer.compile_s)
                return lambda inputs: (time.sleep(outer.exec_s),
                                       np.zeros(1))[1]

        return _L()


def test_timed_compile_run_fallback_split():
    """The satellite bugfix pinned: the old code labeled the whole first
    call compile_s even though it also executed; the fallback split
    subtracts a warm execution."""
    from cloudberry_tpu.exec.instrument import _timed_compile_run

    fn = _FakeJit(compile_s=0.10, exec_s=0.03)
    _, compile_s, exec_s = _timed_compile_run(fn, {})
    assert fn.calls == 2
    assert compile_s == pytest.approx(0.10, abs=0.04)
    assert exec_s == pytest.approx(0.03, abs=0.02)
    # the honest invariant: compile_s excludes the warm execution
    assert compile_s < 0.10 + 0.03 - 0.01


def test_timed_compile_run_aot_split():
    from cloudberry_tpu.exec.instrument import _timed_compile_run

    _, compile_s, exec_s = _timed_compile_run(
        _FakeAot(compile_s=0.08, exec_s=0.03), {})
    assert compile_s == pytest.approx(0.08, abs=0.04)
    assert exec_s == pytest.approx(0.03, abs=0.02)


def test_metrics_hook_exception_safe():
    """A raising metrics hook must never abort the statement (satellite
    bugfix) — it is counted instead."""
    s = cb.Session()
    s.sql("create table hk (k bigint)")
    s.sql("insert into hk values (1), (2)")

    def bad_hook(m):
        raise RuntimeError("observer bug")

    got = []
    s.metrics_hooks.append(bad_hook)
    s.metrics_hooks.append(got.append)
    text = s.explain_analyze("select count(*) as n from hk")
    assert "rows=" in text
    assert len(got) == 1  # later hooks still fire
    assert s.stmt_log.counter("metrics_hook_errors") == 1


# ----------------------------------------- EXPLAIN ANALYZE via pipeline


@pytest.fixture(scope="module")
def dist_session():
    s = cb.Session(Config(n_segments=8))
    s.sql("create table d8 (k bigint, v bigint) distributed by (k)")
    s.sql("insert into d8 values "
          + ",".join(f"({i},{i % 7})" for i in range(64)))
    return s


def _node_rows(metrics):
    return [r for _, _, r in metrics.node_rows]


@pytest.mark.parametrize("nseg", [1, 8])
def test_pipeline_counts_match_legacy(nseg, dist_session):
    """Row counts from the pipeline path (generic-plan form, shared
    compile entry points) are identical to the legacy private-lowerer
    path at 1 and 8 segments."""
    from cloudberry_tpu.exec.instrument import (run_instrumented,
                                                run_pipeline)
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sql.parser import parse_sql

    if nseg == 1:
        s = cb.Session()
        s.sql("create table d1 (k bigint, v bigint) distributed by (k)")
        s.sql("insert into d1 values "
              + ",".join(f"({i},{i % 7})" for i in range(64)))
        q = "select v, count(*) as n from d1 where k < 32 group by v"
    else:
        s = dist_session
        q = "select v, count(*) as n from d8 where k < 32 group by v"
    p1 = plan_statement(parse_sql(q), s, {}).plan
    _, legacy = run_instrumented(p1, s, q)
    p2 = plan_statement(parse_sql(q), s, {}).plan
    batch, pipe, _ann = run_pipeline(p2, s, q)
    assert _node_rows(legacy) == _node_rows(pipe)
    assert batch.num_rows() == pipe.rows_out
    # pipeline semantics: the run is a real statement — logged, counted
    recent = s.stmt_log.recent(5)
    assert recent[0]["sql"] == q and recent[0]["status"] == "ok"
    assert recent[0]["compiles"] >= 1


def test_explain_analyze_motion_annotations(dist_session):
    s = dist_session
    text = s.explain_analyze(
        "select v, count(*) as n from d8 group by v")
    assert "launches=" in text and "wire_bytes=" in text, text


def test_explain_analyze_tiled_trailer():
    """Over-budget statements take the tiled path; EXPLAIN ANALYZE then
    reports the per-tile time distribution + tile counts."""
    cfg = Config().with_overrides(**{"resource.query_mem_bytes": 1 << 20})
    s = cb.Session(cfg)
    s.sql("create table big (k bigint, v double)")
    n = 200_000
    s.catalog.table("big").set_data({
        "k": np.arange(n, dtype=np.int64) % 97,
        "v": np.arange(n, dtype=np.float64)}, {})
    text = s.explain_analyze(
        "select k, sum(v) as sv from big group by k")
    assert "Tiled execution" in text, text
    assert "tile step: mean" in text, text
    # the tile-time histogram also lands on the engine registry
    # (``tile_seconds`` — visible in meta "metrics" without an
    # instrumented rerun)
    h = s.stmt_log.registry.hist("tile_seconds")
    assert h is not None and h["count"] >= 1


# -------------------------------------------------- statements analog


def test_statement_stats_aggregates():
    s = cb.Session()
    s.sql("create table st (k bigint, v bigint) distributed by (k)")
    s.catalog.table("st").set_data({
        "k": np.arange(500, dtype=np.int64),
        "v": np.arange(500, dtype=np.int64) * 2}, {})
    for i in range(6):
        s.sql(f"select v from st where k = {i}")
    rows = s.stmt_log.statements.snapshot()
    row = next(r for r in rows if "st" in r["query"] and "?n" in r["query"])
    assert row["calls"] == 6
    assert row["compiles"] == 1           # one generic build
    assert row["generic_hits"] == 5       # five zero-compile rebinds
    assert row["generic_hit_rate"] == pytest.approx(5 / 6, abs=0.01)
    assert row["rows"] == 6               # one row per lookup
    assert row["total_wall_s"] > 0 and row["p95_wall_s"] > 0
    assert row["errors"] == 0


def test_statement_stats_bounded_lru():
    st = StatementStats(max_rows=4)
    for i in range(10):
        st.observe({"sql": f"select {i} api_unique_{i}", "wall_s": 0.001,
                    "status": "ok", "rows": 1})
    assert len(st) == 4
    assert st.evicted == 6


def test_counters_consistency_with_history():
    """Registry totals == the sum of per-statement history records for a
    pinned single-threaded workload (the engine-wide counter and the
    per-statement attribution must never drift)."""
    s = cb.Session()
    s.sql("create table cc (k bigint, v bigint) distributed by (k)")
    s.catalog.table("cc").set_data({
        "k": np.arange(100, dtype=np.int64),
        "v": np.arange(100, dtype=np.int64)}, {})
    for i in range(5):
        s.sql(f"select v from cc where k = {i}")
    s.sql("select count(*) as n from cc")
    recent = s.stmt_log.recent(100)
    assert sum(e.get("compiles", 0) for e in recent) \
        == s.stmt_log.counter("compiles")
    assert sum(e.get("generic_hits", 0) for e in recent) \
        == s.stmt_log.counter("generic_hits")


# ------------------------------------------------------------- tracing


def _span_intervals_nest(events, eps=2.0):
    """Within each tid, spans must properly nest (contain or be
    disjoint) — the invariant Perfetto's track rendering assumes."""
    by_tid = {}
    for e in events:
        by_tid.setdefault(e["tid"], []).append(
            (e["ts"], e["ts"] + e["dur"]))
    for ivals in by_tid.values():
        ivals.sort(key=lambda p: (p[0], -p[1]))
        stack = []
        for lo, hi in ivals:
            while stack and lo >= stack[-1] - eps:
                stack.pop()
            if stack and hi > stack[-1] + eps:
                return False
            stack.append(hi)
    return True


def test_trace_q5_coverage_and_nesting():
    """The acceptance pin: a traced TPC-H Q5 statement exports
    Chrome-trace JSON whose root span covers >=95% of the externally
    measured wall time, with child spans for every pipeline stage, all
    properly nested."""
    from tools.tpch_queries import QUERIES
    from tools.tpchgen import load_tpch

    s = cb.Session()
    load_tpch(s, sf=0.01, seed=7)
    t0 = time.perf_counter()
    s.sql(QUERIES["q5"])
    wall = time.perf_counter() - t0
    tr = s.stmt_log.traces(1)[0]
    assert tr["status"] == "ok"
    root = next(e for e in tr["events"] if e["name"] == "statement")
    assert root["dur"] / 1e6 >= 0.95 * wall, (root["dur"], wall)
    names = {e["name"] for e in tr["events"]}
    assert {"parse", "plan", "queue-wait", "launch"} <= names, names
    assert _span_intervals_nest(tr["events"]), tr["events"]
    # the export is chrome-trace/perfetto shaped
    from cloudberry_tpu.obs.trace import chrome_trace

    doc = chrome_trace([tr])
    json.dumps(doc)  # JSON-serializable end to end
    assert all(e["ph"] == "X" for e in doc["traceEvents"])


def test_trace_ring_and_span_bounds():
    cfg = Config().with_overrides(**{"obs.trace_ring": 3,
                                     "obs.max_spans": 16})
    s = cb.Session(cfg)
    s.sql("create table tb (k bigint)")
    for i in range(6):
        s.sql(f"insert into tb values ({i})")
    assert len(s.stmt_log.traces(100)) == 3  # ring bound holds
    for tr in s.stmt_log.traces(100):
        assert len(tr["events"]) <= 16


def test_trace_sampling_and_disable():
    cfg = Config().with_overrides(**{"obs.trace_sample": 3})
    s = cb.Session(cfg)
    s.sql("create table ts1 (k bigint)")
    for i in range(8):
        s.sql(f"insert into ts1 values ({i})")
    n_sampled = len(s.stmt_log.traces(100))
    assert 2 <= n_sampled <= 4  # every 3rd of 9 statements

    off = cb.Session(Config().with_overrides(**{"obs.enabled": False}))
    off.sql("create table ts2 (k bigint)")
    off.sql("insert into ts2 values (1)")
    assert off.sql("select count(*) as n from ts2").num_rows() == 1
    assert off.stmt_log.traces(100) == []
    assert len(off.stmt_log.statements) == 0


def test_dispatcher_batch_trace_spans():
    """Batched statements (dispatcher worker thread) get their own
    traces: the dispatch-queue-wait span precedes the root statement
    span, and the stacked launch's spans nest on the worker."""
    from cloudberry_tpu.sched import Dispatcher

    cfg = Config().with_overrides(**{"sched.enabled": True,
                                     "sched.tick_s": 0.02})
    s = cb.Session(cfg)
    s.sql("create table db (k bigint, v bigint) distributed by (k)")
    s.catalog.table("db").set_data({
        "k": np.arange(1000, dtype=np.int64),
        "v": np.arange(1000, dtype=np.int64)}, {})
    s.sql("select v from db where k = 0")  # warm the generic plan
    d = Dispatcher(s).start()
    try:
        outs, threads = [], []
        for i in range(6):
            t = threading.Thread(
                target=lambda i=i: outs.append(
                    d.submit(f"select v from db where k = {i + 1}")))
            threads.append(t)
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert len(outs) == 6
    finally:
        d.stop()
    assert d.stats["batched_requests"] >= 2  # a batch actually formed
    batched = [tr for tr in s.stmt_log.traces(50)
               if any(e["name"] == "dispatch-queue-wait"
                      for e in tr["events"])]
    assert batched, s.stmt_log.traces(50)
    for tr in batched:
        assert _span_intervals_nest(tr["events"]), tr["events"]
        root = next(e for e in tr["events"] if e["name"] == "statement")
        qw = next(e for e in tr["events"]
                  if e["name"] == "dispatch-queue-wait")
        assert qw["ts"] + qw["dur"] <= root["ts"] + 2.0
    # worker-thread spans and caller-thread spans coexist in the export
    tids = {e["tid"] for tr in s.stmt_log.traces(50)
            for e in tr["events"]}
    assert len(tids) >= 2
    # statements-table integrity through the dispatcher: the 7 real
    # executions (1 warm + 6 submits) count once each — 'requeued'
    # bookkeeping stubs never pollute the aggregates — and batched
    # members count as generic reuses (per-entry sums == engine total)
    row = next(r for r in s.stmt_log.statements.snapshot()
               if "db" in r["query"])
    assert row["calls"] == 7, row
    assert row["generic_hits"] >= d.stats["batched_requests"] - 1, row
    recent = s.stmt_log.recent(100)
    executed = [e for e in recent if e.get("status") != "requeued"]
    assert sum(e.get("generic_hits", 0) for e in executed) \
        == s.stmt_log.counter("generic_hits")


# ------------------------------------------------------- wire surface


@pytest.mark.parametrize("threaded", [False, True],
                         ids=["async", "threaded"])
def test_meta_obs_roundtrip_both_transports(threaded):
    from cloudberry_tpu.serve import Client, Server

    cfg = Config().with_overrides(**{"serve.threaded": threaded})
    s = cb.Session(cfg)
    s.sql("create table mt (k bigint, v bigint) distributed by (k)")
    s.catalog.table("mt").set_data({
        "k": np.arange(200, dtype=np.int64),
        "v": np.arange(200, dtype=np.int64)}, {})
    with Server(session=s) as srv:
        with Client(srv.host, srv.port) as c:
            for i in range(4):
                c.sql(f"select v from mt where k = {i}")
            m = c.meta("metrics")
            assert m["counters"]["dispatches"] >= 4
            assert "statement_seconds" in m["histograms"]
            assert m["series"] > 0 and "series_dropped" in m
            prom = c.meta("metrics", "prom")
            assert "# TYPE cbtpu_dispatches counter" in prom
            st = c.meta("statements")
            row = next(r for r in st if "mt" in r["query"])
            assert row["calls"] == 4 and row["wire_bytes"] > 0
            assert row["generic_hits"] == 3
            tr = c.meta("trace", 4)
            assert len(tr["traces"]) >= 1
            assert tr["chrome"]["traceEvents"]
            acts = c.meta("activity")
            assert isinstance(acts["recent"], list)


def test_server_render_stage_recorded():
    from cloudberry_tpu.serve import Client, Server

    s = cb.Session()
    s.sql("create table rr (k bigint)")
    s.sql("insert into rr values (1), (2), (3)")
    with Server(session=s) as srv:
        with Client(srv.host, srv.port) as c:
            c.sql("select k from rr")
    h = s.stmt_log.registry.hist("stage_seconds.render")
    assert h is not None and h["count"] >= 1


# ----------------------------------------------------------- lint pass


def test_lint_obs_counter_home(tmp_path):
    import textwrap

    from cloudberry_tpu.lint import run_lint
    from cloudberry_tpu.lint.config import LintConfig

    root = tmp_path / "pkg"
    (root / "sched").mkdir(parents=True)
    (root / "sched" / "thing.py").write_text(textwrap.dedent("""
        import collections


        class T:
            def __init__(self):
                self.counters = collections.Counter()
    """))
    result = run_lint([str(root)], LintConfig(exclude_files=frozenset()))
    hits = [f for f in result.unsuppressed
            if f.rule == "obs-counter-home"]
    assert hits and hits[0].file.endswith("sched/thing.py")


def test_lint_obs_meta_verbs_both_ways(tmp_path):
    import textwrap

    from cloudberry_tpu.lint import run_lint
    from cloudberry_tpu.lint.config import LintConfig

    root = tmp_path / "pkg"
    (root / "serve").mkdir(parents=True)
    (root / "serve" / "meta.py").write_text(textwrap.dedent('''
        def describe(session, kind, arg=None):
            """Answers. Kinds: tables | ghost."""
            if kind == "tables":
                return []
            if kind == "hidden":
                return {}
            raise ValueError(kind)
    '''))
    result = run_lint([str(root)], LintConfig(exclude_files=frozenset()))
    msgs = [f.message for f in result.unsuppressed
            if f.rule == "obs-meta-verbs"]
    assert any("'hidden' is implemented but missing" in m for m in msgs)
    assert any("'ghost' is documented but not implemented" in m
               for m in msgs)


def test_repo_meta_verbs_in_sync():
    """The live serve/meta.py passes its own contract (direct pin, so a
    pass regression cannot mask a drift)."""
    import os

    import cloudberry_tpu
    from cloudberry_tpu.lint import run_lint

    pkg = os.path.dirname(os.path.abspath(cloudberry_tpu.__file__))
    result = run_lint([os.path.join(pkg, "serve", "meta.py")])
    assert not [f for f in result.unsuppressed
                if f.rule == "obs-meta-verbs"]
