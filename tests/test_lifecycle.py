"""Statement lifecycle survivability — cancellation, timeouts, watchdog,
drain, circuit breaker (the statement_timeout / pg_cancel_backend /
smart-shutdown analog suite).

Chaos discipline (faultinjector.c role): wedges and losses are provoked
deterministically at the armed seams; the assertions are the ISSUE-4
acceptance criteria — a hung statement returns a timeout WITHIN its
deadline while the serving thread survives, results after a cancel are
bit-identical on re-run, drain never silently drops an accepted request,
and the breaker walks trip → half-open → close.
"""

import threading
import time

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu import lifecycle
from cloudberry_tpu.config import get_config
from cloudberry_tpu.serve import Client, Server, ServerError
from cloudberry_tpu.utils import faultinject as FI


@pytest.fixture(autouse=True)
def _clean_faults():
    FI.reset_fault()
    yield
    FI.reset_fault()


@pytest.fixture(autouse=True, scope="module")
def _lock_witness():
    # runtime lock-order witness (lint/witness.py): every lock this
    # suite's servers/sessions create is order-checked against the
    # declared ranks; a violation anywhere in the module fails here
    from cloudberry_tpu.lint import witness

    with witness.watching():
        yield


def _mk(**ov):
    over = {"n_segments": 1}
    over.update(ov)
    return cb.Session(get_config().with_overrides(**over))


def _load(s, n=64):
    s.sql("create table t (k bigint, v bigint) distributed by (k)")
    s.catalog.table("t").set_data(
        {"k": np.arange(n, dtype=np.int64),
         "v": (np.arange(n, dtype=np.int64) * 7) % 13})


# ------------------------------------------------------------- taxonomy


def test_taxonomy_retryable_vs_semantic():
    assert lifecycle.StatementTimeout.retryable
    assert lifecycle.ServerDraining.retryable
    assert lifecycle.BreakerOpen.retryable
    assert not lifecycle.StatementCancelled.retryable
    assert lifecycle.is_retryable(lifecycle.StatementTimeout("x"))
    assert not lifecycle.is_retryable(lifecycle.StatementCancelled("x"))
    # the sched pair is retryable BY NAME (shared with the client side)
    assert lifecycle.is_retryable("SchedQueueFull")
    assert lifecycle.is_retryable("SchedDeadline")
    assert not lifecycle.is_retryable("BindError")
    assert not lifecycle.is_retryable(ValueError("nope"))


def test_cancel_token_first_reason_wins():
    tok = lifecycle.CancelToken()
    assert tok.cancel("timeout")
    assert not tok.cancel("cancelled")  # later cancels never overwrite
    with pytest.raises(lifecycle.StatementTimeout):
        tok.raise_if_cancelled()


def test_handle_deadline_records_timeout_on_token():
    h = lifecycle.StatementHandle(1, deadline=time.monotonic() - 0.01)
    with pytest.raises(lifecycle.StatementTimeout):
        h.check()
    assert h.token.cancelled and h.token.reason == "timeout"


def test_check_cancel_noop_outside_scope():
    lifecycle.check_cancel()  # no active statement: must not raise


# --------------------------------------------------- statement_timeout_s


def test_statement_timeout_config_enforced():
    s = _mk(statement_timeout_s=0.6)
    _load(s)
    s.sql("select sum(v) as sv from t")  # warm the compile cache
    FI.inject_fault("dispatch_start", "hang", start_hit=1, end_hit=1)
    t0 = time.monotonic()
    with pytest.raises(lifecycle.StatementTimeout):
        s.sql("select sum(v) as sv from t")
    assert time.monotonic() - t0 < 5.0  # nothing waits out the wedge
    assert s.stmt_log.counter("statement_timeouts") == 1
    # phantom-free: the active registry is empty, history has the error
    assert s.stmt_log.activity() == []
    assert "StatementTimeout" in s.stmt_log.recent(1)[0]["error"]


def test_per_statement_deadline_tightens():
    s = _mk()
    _load(s)
    s.sql("select sum(v) as sv from t")
    FI.inject_fault("dispatch_start", "hang", start_hit=1, end_hit=1)
    with pytest.raises(lifecycle.StatementTimeout):
        s.sql("select sum(v) as sv from t",
              _deadline=time.monotonic() + 0.3)


# ------------------------------------------------------- cancel mid-tile


def _mk_spill():
    s = _mk(**{"resource.query_mem_bytes": 4 << 20})
    rng = np.random.default_rng(3)
    s.sql("CREATE TABLE dim (k BIGINT, g BIGINT) DISTRIBUTED BY (k)")
    s.sql("CREATE TABLE fact (k BIGINT, v BIGINT) DISTRIBUTED BY (k)")
    s.catalog.table("dim").set_data(
        {"k": np.arange(500), "g": np.arange(500) % 9})
    s.catalog.table("fact").set_data(
        {"k": rng.integers(0, 500, 200_000),
         "v": rng.integers(0, 100, 200_000)})
    return s


_SPILL_Q = ("select g, sum(v) as sv from fact join dim on fact.k = dim.k "
            "group by g order by g")


def test_cancel_mid_tile_bit_identical_rerun():
    """Cancel lands between tile steps (the per-tile seam); the SAME
    session then re-runs the statement and the result is bit-identical
    to an undisturbed run — cancellation leaves no partial state."""
    expect = _mk_spill().sql(_SPILL_Q).to_pandas()

    s = _mk_spill()
    FI.inject_fault("tile_step", "sleep", sleep_s=0.05)  # slow the stream
    errs = []

    def bg():
        try:
            s.sql(_SPILL_Q)
        except BaseException as e:  # noqa: BLE001 — the assertion target
            errs.append(e)

    th = threading.Thread(target=bg)
    th.start()
    act = None
    for _ in range(500):
        act = s.stmt_log.activity()
        if act:
            break
        time.sleep(0.01)
    assert act, "statement never appeared in the activity view"
    time.sleep(0.25)  # let it get into the tile stream
    assert s.stmt_log.cancel(act[0]["id"])
    th.join(timeout=60)
    assert errs and isinstance(errs[0], lifecycle.StatementCancelled)

    FI.reset_fault()
    got = s.sql(_SPILL_Q).to_pandas()
    assert s.last_tiled_report is not None  # really the tiled path
    assert expect.equals(got)


# --------------------------------------------------------------- watchdog


def test_watchdog_cancels_over_deadline_statement():
    """Deterministic watchdog unit: an attached handle past its deadline
    is cancelled with reason 'timeout', state flips to cancelling, and
    the counter records it."""
    from cloudberry_tpu.exec.instrument import StatementLog

    log = StatementLog()
    sid = log.begin("select 1")
    h = lifecycle.StatementHandle(sid, deadline=time.monotonic() - 0.01)
    log.attach(sid, h)
    live = lifecycle.StatementHandle(
        log.begin("select 2"), deadline=time.monotonic() + 60)
    log.attach(live.statement_id, live)
    wd = lifecycle.Watchdog(log)
    assert wd.scan() == 1
    assert h.token.cancelled and h.token.reason == "timeout"
    assert not live.token.cancelled
    states = {e["id"]: e["state"] for e in log.activity()}
    assert states[sid] == "cancelling"
    assert log.counter("watchdog_timeouts") == 1
    assert wd.scan() == 0  # idempotent: already cancelled


def test_hung_statement_times_out_worker_survives():
    """ISSUE-4 acceptance: an armed `hang` at an exec seam returns a
    timeout error WITHIN the deadline, the serving thread survives, and
    the immediately following statement is bit-identical to an
    undisturbed run."""
    s = _mk()
    _load(s)
    expect = s.sql("select v, count(*) as c from t group by v "
                   "order by v").to_pandas()
    with Server(session=s) as srv:
        with Client(srv.host, srv.port) as c:
            FI.inject_fault("dispatch_start", "hang",
                            start_hit=1, end_hit=1)
            t0 = time.monotonic()
            with pytest.raises(ServerError) as ei:
                c.sql("select v, count(*) as c from t group by v "
                      "order by v", deadline_s=0.5)
            elapsed = time.monotonic() - t0
            assert ei.value.etype == "StatementTimeout"
            assert ei.value.retryable
            assert elapsed < 5.0  # bounded by deadline + poll, not 3600s
            # the SAME connection (same handler thread) keeps serving
            got = c.sql("select v, count(*) as c from t group by v "
                        "order by v")
            assert [list(r) for r in got["rows"]] == \
                expect.values.tolist()


def test_cancel_verb_over_wire():
    """pg_cancel_backend analog: a second client finds the statement in
    the activity view and cancels it by id."""
    s = _mk()
    _load(s)
    with Server(session=s) as srv:
        FI.inject_fault("dispatch_start", "hang", start_hit=1, end_hit=1)
        errs = []

        def bg():
            with Client(srv.host, srv.port) as c1:
                try:
                    c1.sql("select sum(v) as sv from t")
                except ServerError as e:
                    errs.append(e)

        th = threading.Thread(target=bg)
        th.start()
        with Client(srv.host, srv.port) as c2:
            act = None
            for _ in range(500):
                act = c2.meta("activity")["active"]
                if act:
                    break
                time.sleep(0.01)
            assert act and act[0]["state"] == "running"
            assert c2.cancel(act[0]["id"])["status"] == \
                f"CANCEL {act[0]['id']}"
            # cancelling a finished/unknown id reports cleanly
            with pytest.raises(ServerError) as ei:
                c2.cancel(999_999)
            assert ei.value.etype == "UnknownStatement"
        th.join(timeout=30)
        assert errs and errs[0].etype == "StatementCancelled"
        assert not errs[0].retryable


# ------------------------------------------------------------------ drain


def test_drain_under_load_never_drops_silently():
    """ISSUE-4 acceptance: Server.stop(drain_s) under concurrent load —
    every accepted request completes or fails with the RETRYABLE drain
    error; a closed connection is a visible client-side error, never a
    request that vanished."""
    s = _mk()
    _load(s, n=256)
    s.sql("select v, count(*) as c from t group by v")  # warm compile
    srv = Server(session=s).start()
    stop_flag = [False]
    outcomes = []  # per request: "ok" | etype | "closed"
    lock = threading.Lock()

    def worker(wid):
        try:
            with Client(srv.host, srv.port) as c:
                while not stop_flag[0]:
                    try:
                        c.sql("select v, count(*) as c from t group by v")
                        with lock:
                            outcomes.append("ok")
                    except ServerError as e:
                        with lock:
                            outcomes.append(e.etype or str(e))
                        if e.etype is None:  # connection closed
                            return
        except Exception as e:  # noqa: BLE001
            with lock:
                outcomes.append(f"conn:{type(e).__name__}")

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.4)  # real in-flight load
    srv.stop(drain_s=10.0)
    stop_flag[0] = True
    for t in threads:
        t.join(timeout=30)
    oks = outcomes.count("ok")
    assert oks > 0
    # every non-ok outcome is the retryable drain refusal or a visible
    # connection close after shutdown — never any OTHER failure
    bad = [o for o in outcomes
           if o not in ("ok", "ServerDraining")
           and not o.startswith("conn:") and o != "server closed the "
           "connection"]
    assert not bad, bad
    # drain really completed the accepted work: nothing active remains
    assert s.stmt_log.activity() == []


def test_draining_refusal_is_retryable():
    s = _mk()
    _load(s)
    srv = Server(session=s).start()
    with Client(srv.host, srv.port) as c:
        c.sql("select 1 as x")
        srv._draining = True  # refuse-new without closing the socket
        with pytest.raises(ServerError) as ei:
            c.sql("select 1 as x")
        assert ei.value.etype == "ServerDraining"
        assert ei.value.retryable
        assert "SERVER_DRAINING" in str(ei.value)
    srv.stop()


def test_dispatcher_drain_and_stop_taxonomy():
    """A stopped dispatcher fails queued work with the retryable drain
    error, and drain() reports idle correctly."""
    from cloudberry_tpu.sched.dispatcher import Dispatcher

    s = _mk(**{"sched.enabled": True})
    _load(s)
    d = Dispatcher(s).start()
    assert d.drain(1.0)  # idle: immediate
    d.stop()
    with pytest.raises(lifecycle.ServerDraining):
        d.submit("select 1")


# -------------------------------------------------------- circuit breaker


def test_breaker_trip_halfopen_close():
    # a LONG cooldown pins the refusal assertions (no wall-clock race
    # under full-suite load); the half-open phases then shorten it to 0
    # instead of sleeping — the state machine is what's under test
    s = _mk(**{"health.breaker_threshold": 2,
               "health.breaker_cooldown_s": 60.0})
    _load(s)
    # two CONSECUTIVE statements needing a device-loss recovery trip it
    for _ in range(2):
        FI.inject_fault("exec_device_lost", "error",
                        start_hit=1, end_hit=1)
        s.sql("select sum(v) as sv from t")
    assert s._breaker.snapshot()["state"] == "open"
    assert s._breaker.snapshot()["trips"] == 1
    # read-only-degraded: writes refuse retryably, reads still serve
    with pytest.raises(lifecycle.BreakerOpen):
        s.sql("create table w1 (x bigint)")
    assert s.sql("select count(*) as c from t").to_pandas()["c"][0] == 64
    # inside the cooldown the write refuses WITHOUT probing
    with pytest.raises(lifecycle.BreakerOpen):
        s.sql("create table w1 (x bigint)")
    # half-open with a FAILING probe: stays open, cooldown re-arms
    s._breaker._probe_fn = \
        lambda: type("R", (), {"ok": False, "error": "dead"})()
    s._breaker.cooldown_s = 0.0
    with pytest.raises(lifecycle.BreakerOpen):
        s.sql("create table w1 (x bigint)")
    assert s._breaker.snapshot()["state"] == "open"
    # half-open with a HEALTHY probe: the trial write closes it
    s._breaker._probe_fn = None
    assert str(s.sql("create table w1 (x bigint)")) \
        .startswith("CREATE TABLE")
    snap = s._breaker.snapshot()
    assert snap["state"] == "closed" and snap["consecutive_recoveries"] == 0


def test_breaker_success_resets_consecutive():
    s = _mk(**{"health.breaker_threshold": 2})
    _load(s)
    FI.inject_fault("exec_device_lost", "error", start_hit=1, end_hit=1)
    s.sql("select sum(v) as sv from t")   # one recovery
    s.sql("select sum(v) as sv from t")   # clean: resets the streak
    FI.inject_fault("exec_device_lost", "error", start_hit=1, end_hit=1)
    s.sql("select sum(v) as sv from t")   # one again — NOT consecutive
    assert s._breaker.snapshot()["state"] == "closed"
    assert s._breaker.snapshot()["trips"] == 0


def test_breaker_trips_on_hard_outage():
    """Recovery ATTEMPTS count even when the statement ultimately fails
    (retries exhausted): a total outage must trip the breaker, not just
    a flap mild enough for retries to win."""
    s = _mk(**{"health.breaker_threshold": 2})
    _load(s)
    for _ in range(2):
        FI.inject_fault("exec_device_lost", "error")  # EVERY attempt
        with pytest.raises(FI.InjectedFault):
            s.sql("select sum(v) as sv from t")
        FI.reset_fault()
    assert s._breaker.snapshot()["state"] == "open"


def test_breaker_trial_failure_reopens_no_wedge():
    """A half-open trial write failing for a SEMANTIC reason re-arms the
    cooldown (trial_failed) — the breaker never wedges in half-open, and
    the next post-cooldown write can still close it."""
    s = _mk(**{"health.breaker_threshold": 1,
               "health.breaker_cooldown_s": 0.0})
    _load(s)
    FI.inject_fault("exec_device_lost", "error", start_hit=1, end_hit=1)
    s.sql("select sum(v) as sv from t")  # one recovery: trips at K=1
    assert s._breaker.snapshot()["state"] == "open"
    with pytest.raises(ValueError):
        s.sql("create table t (k bigint)")  # trial write: duplicate table
    assert s._breaker.snapshot()["state"] == "open"  # re-armed, not stuck
    assert str(s.sql("create table w2 (x bigint)")) \
        .startswith("CREATE TABLE")
    assert s._breaker.snapshot()["state"] == "closed"


def test_breaker_reads_never_close_half_open():
    """Only the trial WRITE's verdict moves a half-open breaker — a
    concurrent read succeeding proves nothing about writes."""
    ok_probe = lambda: type("R", (), {"ok": True})()  # noqa: E731
    b = lifecycle.CircuitBreaker(threshold=1, cooldown_s=0.0,
                                 probe_fn=ok_probe)
    b.record_recovery()
    assert b.snapshot()["state"] == "open"
    assert b.check_write() is True  # this write is the trial
    b.record_success()              # a read completing mid-trial
    assert b.snapshot()["state"] == "half-open"
    with pytest.raises(lifecycle.BreakerOpen):
        b.check_write()             # a second write: still degraded
    b.trial_succeeded()
    assert b.snapshot()["state"] == "closed"


def test_breaker_exempts_transaction_control():
    """An open breaker must never trap a session in its transaction:
    BEGIN/ROLLBACK are host-side only and bypass the write gate."""
    s = _mk(**{"health.breaker_threshold": 1,
               "health.breaker_cooldown_s": 60.0})
    _load(s)
    s.sql("begin")
    s.sql("insert into t values (999, 0)")
    FI.inject_fault("exec_device_lost", "error", start_hit=1, end_hit=1)
    s.sql("select sum(v) as sv from t")  # trips at K=1
    assert s._breaker.snapshot()["state"] == "open"
    with pytest.raises(lifecycle.BreakerOpen):
        s.sql("insert into t values (1000, 0)")
    assert s.sql("rollback") == "ROLLBACK"  # always allowed
    assert s.sql("select count(*) as c from t").to_pandas()["c"][0] == 64


def test_breaker_raising_probe_reopens():
    """A probe that RAISES counts as a failed probe: back open with a
    fresh cooldown, never wedged in half-open."""

    def bad_probe():
        raise RuntimeError("probe transport died")

    b = lifecycle.CircuitBreaker(threshold=1, cooldown_s=0.0,
                                 probe_fn=bad_probe)
    b.record_recovery()
    with pytest.raises(lifecycle.BreakerOpen) as ei:
        b.check_write()
    assert "probe raised" in str(ei.value)
    assert b.snapshot()["state"] == "open"  # resolvable, not half-open
    b._probe_fn = lambda: type("R", (), {"ok": True})()
    assert b.check_write() is True  # the slot recovered


def test_breaker_state_in_meta_info():
    s = _mk()
    _load(s)
    with Server(session=s) as srv, Client(srv.host, srv.port) as c:
        info = c.meta("info")
    assert info["breaker"]["state"] == "closed"


# ------------------------------------------------- dispatcher lifecycle


def test_dispatcher_deadline_governs_execution():
    """The per-request deadline reaches EXECUTION on the sequential
    dispatcher path (not just time-in-queue): a wedged statement dies
    with the timeout taxonomy, and the dispatcher survives."""
    s = _mk(**{"sched.enabled": True})
    _load(s)
    s.sql("select sum(v) as sv from t")  # warm
    from cloudberry_tpu.sched.dispatcher import Dispatcher

    d = Dispatcher(s).start()
    try:
        FI.inject_fault("dispatch_start", "hang", start_hit=1, end_hit=1)
        with pytest.raises(
                (lifecycle.StatementTimeout, Exception)) as ei:
            d.submit("select sum(v) as sv from t", deadline_s=0.4)
        assert type(ei.value).__name__ in ("StatementTimeout",
                                           "SchedDeadline")
        FI.reset_fault()
        out = d.submit("select sum(v) as sv from t", deadline_s=30)
        assert out.num_rows() == 1
    finally:
        d.stop()


# ------------------------------------------------------- client retries


class _FlakyClient(Client):
    """Client whose transport fails N times with a canned response —
    unit harness for the retry policy (no server)."""

    def __init__(self, failures, etype, retryable, retry_reads=True):
        # bypass Client.__init__ (no socket)
        self.retry_reads = retry_reads
        self.max_retries = 3
        self.backoff_s = 0.001
        self.calls = 0
        self._failures = failures
        self._etype = etype
        self._retryable = retryable

    def _request(self, req):
        self.calls += 1
        if self.calls <= self._failures:
            raise ServerError("transient", etype=self._etype,
                              retryable=self._retryable)
        return {"rows": [], "columns": [], "rowcount": 0}


def test_client_retries_idempotent_reads_opt_in():
    c = _FlakyClient(2, "ServerDraining", True)
    assert c.sql("select 1")["rowcount"] == 0
    assert c.calls == 3  # two retries then success


def test_client_retry_off_by_default():
    c = _FlakyClient(1, "ServerDraining", True, retry_reads=False)
    with pytest.raises(ServerError):
        c.sql("select 1")
    assert c.calls == 1


def test_client_never_retries_writes_or_semantic_errors():
    c = _FlakyClient(1, "ServerDraining", True)
    with pytest.raises(ServerError):
        c.sql("insert into t values (1)")  # a write: never retried
    assert c.calls == 1
    c2 = _FlakyClient(1, "BindError", False)
    with pytest.raises(ServerError):
        c2.sql("select 1")  # semantic: never retried
    assert c2.calls == 1


def test_client_retry_gives_up_after_max():
    c = _FlakyClient(99, "SchedQueueFull", True)
    with pytest.raises(ServerError):
        c.sql("select 1")
    assert c.calls == c.max_retries + 1


# ----------------------------------------------------- satellite fixes


def test_hang_fault_interruptible_by_reset():
    """The `hang` action sleeps on an event reset_fault() sets — no more
    uninterruptible 3600s wedge."""
    FI.inject_fault("lifecycle_test_hang", "hang")
    done = threading.Event()

    def bg():
        FI.fault_point("lifecycle_test_hang")
        done.set()

    th = threading.Thread(target=bg, daemon=True)
    t0 = time.monotonic()
    th.start()
    time.sleep(0.15)
    assert not done.is_set()  # really wedged
    FI.reset_fault("lifecycle_test_hang")
    th.join(timeout=5)
    assert done.is_set() and time.monotonic() - t0 < 5.0


def test_health_history_bounded():
    from cloudberry_tpu.parallel import health

    mon = health.HealthMonitor(interval_s=3600, history_maxlen=4)
    for _ in range(6):
        mon.probe_now()
    assert len(mon.history) == 4  # deque dropped the oldest two


def test_occ_commit_window_cancel_aborts_clean(tmp_path):
    """Cancellation inside the OCC commit window aborts the transaction
    (nothing published) and releases the store lock."""
    s = cb.Session(get_config().with_overrides(
        **{"storage.root": str(tmp_path)}))
    s.sql("create table t (a bigint)")
    s.sql("insert into t values (1)")
    h = lifecycle.StatementHandle(0)
    h.token.cancel("cancelled")
    s.txn("begin")
    s.sql("insert into t values (2)")
    with lifecycle.statement_scope(h):
        with pytest.raises(lifecycle.StatementCancelled):
            s.txn("commit")
    # aborted: RAM restored, store untouched, lock free for the next txn
    assert s.sql("select count(*) as c from t").to_pandas()["c"][0] == 1
    s.txn("begin")
    s.sql("insert into t values (3)")
    assert s.txn("commit") == "COMMIT"
    assert s.sql("select count(*) as c from t").to_pandas()["c"][0] == 2


def test_serve_bench_cancel_mix_smoke():
    """CPU smoke of the lifecycle bench workload: deadlined requests ride
    the same closed loop and the CSV row carries the new counters."""
    import tools.serve_bench as SB

    r = SB.run_mode("direct", "point", clients=2, duration_s=0.8,
                    rows=20_000, tick_s=0.002, max_batch=8,
                    cancel_mix=0.5, deadline_s=0.004)
    assert r["requests"] > 0
    assert "deadline_misses" in r and "cancels" in r
    assert r["deadline_misses"] >= 0
    row = SB.csv_row(r)
    assert row.startswith("direct,point,2,")
    assert len(row.split(",")) == len(SB.CSV_HEADER.split(","))
