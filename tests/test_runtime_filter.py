"""Runtime filters (nodeRuntimeFilter.c analog): exact semi-join pushdown
below the probe's redistribute, with estimate-shrunk motion buffers."""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.plan import nodes as N


def _mk(threshold=1_000_000):
    cfg = Config(n_segments=8).with_overrides(**{
        "planner.broadcast_threshold": 0,   # force redistribute joins
        "planner.runtime_filter_threshold": threshold,
        "interconnect.capacity_factor": 4.0,
    })
    s = cb.Session(cfg)
    s.sql("create table fact (k bigint, grp bigint, v bigint) "
          "distributed by (k)")
    s.sql("create table dim (d bigint, flag bigint) distributed by (d)")
    n = 2000
    rows = ",".join(f"({i}, {i % 400}, {i % 7})" for i in range(n))
    s.sql(f"insert into fact values {rows}")
    rows = ",".join(f"({i}, {1 if i < 40 else 0})" for i in range(400))
    s.sql(f"insert into dim values {rows}")
    return s


def _plan(s, sql):
    from cloudberry_tpu.plan.binder import Binder
    from cloudberry_tpu.plan.planner import _optimize
    from cloudberry_tpu.sql.parser import parse_sql

    return _optimize(Binder(s.catalog).bind_query(parse_sql(sql)), s)


def _find(plan, kind):
    out = []

    def walk(n):
        if isinstance(n, kind):
            out.append(n)
        for c in n.children():
            walk(c)

    walk(plan)
    return out


# d < 40 keeps 10% of dim (min/max range estimate sees that), so the
# runtime filter's semi estimate is far below the probe's capacity
Q = ("select grp, count(*) as n from fact, dim "
     "where grp = d and d < 40 group by grp order by grp")


def test_filter_inserted_and_results_match():
    s = _mk()
    plan = _plan(s, Q)
    assert _find(plan, N.PRuntimeFilter), "expected a runtime filter"
    with_f = s.sql(Q).to_pandas()
    s2 = _mk(threshold=0)
    assert not _find(_plan(s2, Q), N.PRuntimeFilter)
    without = s2.sql(Q).to_pandas()
    assert with_f.values.tolist() == without.values.tolist()
    assert with_f.grp.tolist() == list(range(40))
    assert set(with_f.n.tolist()) == {5}


def test_filter_shrinks_motion_buffers():
    # probe through a projection: the exact per-bucket sizer can't see the
    # base scan, so the bucket size comes from capacity vs the runtime
    # filter's semi-join estimate
    q = ("select g2, count(*) as n from "
         "(select grp as g2 from fact) f2, dim "
         "where g2 = d and d < 40 group by g2 order by g2")

    def probe_motion(plan):
        return [m for m in _find(plan, N.PMotion)
                if m.kind == "redistribute"
                and any(sc.table_name == "fact"
                        for sc in _find(m, N.PScan))][0]

    shrunk = probe_motion(_plan(_mk(), q)).bucket_cap
    raw = probe_motion(_plan(_mk(threshold=0), q)).bucket_cap
    assert shrunk < raw
    s = _mk()
    out = s.sql(q).to_pandas()
    assert out.g2.tolist() == list(range(40))


def test_filter_with_null_probe_keys():
    s = _mk()
    s.sql("insert into fact values (9000, null, 1)")
    out = s.sql(Q).to_pandas()
    assert out.grp.tolist() == list(range(40))  # NULL key dropped


def test_semi_join_filtered():
    s = _mk()
    q = ("select count(*) as n from fact where grp in "
         "(select d from dim where d < 40)")
    out = s.sql(q).to_pandas()
    assert out.n[0] == 200  # 40 groups × 5 rows


def test_left_join_not_filtered():
    """LEFT joins keep unmatched probe rows — no runtime filter allowed."""
    s = _mk()
    q = ("select count(*) as n from fact left join dim "
         "on fact.grp = dim.d and dim.d < 40")
    plan = _plan(s, q)
    assert not _find(plan, N.PRuntimeFilter)
    assert s.sql(q).to_pandas().n[0] == 2000
