import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.plan.binder import BindError


@pytest.fixture
def sess():
    return cb.Session()


def test_create_insert_select(sess):
    sess.sql("""create table items (id bigint not null, price decimal(10,2),
                name text, sold date) distributed by (id)""")
    sess.sql("""insert into items values
                (1, 9.99, 'apple', '2024-01-05'),
                (2, 12.50, 'pear', '2024-02-01'),
                (3, 0.99, 'fig', '2024-01-20')""")
    out = sess.sql("select name, price from items where price > 5 order by price desc")
    df = out.to_pandas()
    assert df["name"].tolist() == ["pear", "apple"]
    assert df["price"].tolist() == [12.50, 9.99]


def test_group_and_having(sess):
    sess.sql("create table s (k text, v int) distributed randomly")
    sess.sql("insert into s values ('a',1),('a',2),('b',5),('b',7),('c',1)")
    df = sess.sql("""select k, sum(v) as total, count(*) as n from s
                     group by k having sum(v) > 2 order by total desc""").to_pandas()
    assert df["k"].tolist() == ["b", "a"]
    assert df["total"].tolist() == [12, 3]
    assert df["n"].tolist() == [2, 2]


def test_string_order_by_uses_collation(sess):
    sess.sql("create table t (s text) distributed randomly")
    sess.sql("insert into t values ('pear'),('apple'),('zebra'),('fig')")
    df = sess.sql("select s from t order by s").to_pandas()
    assert df["s"].tolist() == ["apple", "fig", "pear", "zebra"]


def test_distinct(sess):
    sess.sql("create table d (x int) distributed randomly")
    sess.sql("insert into d values (3),(1),(3),(2),(1)")
    df = sess.sql("select distinct x from d order by x").to_pandas()
    assert df["x"].tolist() == [1, 2, 3]


def test_case_expression(sess):
    sess.sql("create table c (v int) distributed randomly")
    sess.sql("insert into c values (1),(5),(10)")
    df = sess.sql("""select case when v < 3 then 'small'
                                when v < 8 then 'mid'
                                else 'big' end as bucket
                     from c order by v""").to_pandas()
    assert df["bucket"].tolist() == ["small", "mid", "big"]


def test_drop_and_errors(sess):
    sess.sql("create table gone (x int)")
    sess.sql("drop table gone")
    with pytest.raises(KeyError):
        sess.sql("select * from gone")
    sess.sql("create table there (x int)")
    with pytest.raises(BindError):
        sess.sql("select nosuchcol from there")


def test_decimal_exactness(sess):
    # classic float-sum trap: 0.1 + 0.2 — int64 fixed point stays exact
    sess.sql("create table m (v decimal(10,2))")
    rows = ",".join(["(0.10)"] * 100)
    sess.sql(f"insert into m values {rows}")
    df = sess.sql("select sum(v) as s from m").to_pandas()
    assert df["s"][0] == 10.0  # exactly, no 9.999999...


def test_set_operations(sess):
    sess.sql("create table sa (x int, s text)")
    sess.sql("insert into sa values (1,'a'),(2,'b'),(2,'b'),(3,'c')")
    sess.sql("create table sb (x int, s text)")
    sess.sql("insert into sb values (2,'b'),(4,'d'),(3,'zz')")

    df = sess.sql("select x, s from sa union all select x, s from sb "
                  "order by x, s").to_pandas()
    assert len(df) == 7 and df["x"].tolist() == [1, 2, 2, 2, 3, 3, 4]
    assert df["s"].tolist() == ["a", "b", "b", "b", "c", "zz", "d"]

    df = sess.sql("select x, s from sa union select x, s from sb "
                  "order by x, s").to_pandas()
    assert list(zip(df["x"], df["s"])) == [
        (1, "a"), (2, "b"), (3, "c"), (3, "zz"), (4, "d")]

    df = sess.sql("select x, s from sa intersect select x, s from sb "
                  "order by x").to_pandas()
    assert list(zip(df["x"], df["s"])) == [(2, "b")]

    df = sess.sql("select x, s from sa except select x, s from sb "
                  "order by x").to_pandas()
    assert list(zip(df["x"], df["s"])) == [(1, "a"), (3, "c")]


def test_set_op_type_coercion(sess):
    sess.sql("create table ca (v int)")
    sess.sql("insert into ca values (1),(2)")
    sess.sql("create table cb (v decimal(10,2))")
    sess.sql("insert into cb values (2.5),(1.0)")
    df = sess.sql("select v from ca union all select v from cb "
                  "order by v").to_pandas()
    assert df["v"].tolist() == [1.0, 1.0, 2.0, 2.5]


def test_set_op_arity_error(sess):
    sess.sql("create table e1 (a int, b int)")
    with pytest.raises(BindError):
        sess.sql("select a, b from e1 union select a from e1")


def test_window_functions(sess):
    sess.sql("create table w (g text, o int, v decimal(10,2))")
    sess.sql("""insert into w values
        ('a', 1, 10.0), ('a', 2, 20.0), ('a', 2, 5.0), ('a', 3, 1.0),
        ('b', 1, 100.0), ('b', 2, 50.0)""")
    df = sess.sql("""select g, o, v,
                row_number() over (partition by g order by o, v) as rn,
                rank() over (partition by g order by o) as rk,
                dense_rank() over (partition by g order by o) as dr,
                sum(v) over (partition by g order by o) as running,
                sum(v) over (partition by g) as total,
                count(*) over (partition by g) as n,
                max(v) over (partition by g) as mx
            from w order by g, o, v""").to_pandas()
    assert df["rn"].tolist() == [1, 2, 3, 4, 1, 2]
    assert df["rk"].tolist() == [1, 2, 2, 4, 1, 2]
    assert df["dr"].tolist() == [1, 2, 2, 3, 1, 2]
    # running sum with ORDER BY includes peers (RANGE frame)
    assert df["running"].tolist() == [10.0, 35.0, 35.0, 36.0, 100.0, 150.0]
    assert df["total"].tolist() == [36.0] * 4 + [150.0] * 2
    assert df["n"].tolist() == [4, 4, 4, 4, 2, 2]
    assert df["mx"].tolist() == [20.0] * 4 + [100.0] * 2


def test_window_no_partition(sess):
    sess.sql("create table wn (v int)")
    sess.sql("insert into wn values (3),(1),(2)")
    df = sess.sql("select v, row_number() over (order by v) as rn, "
                  "sum(v) over () as t from wn order by v").to_pandas()
    assert df["rn"].tolist() == [1, 2, 3]
    assert df["t"].tolist() == [6, 6, 6]


def test_window_string_order_collation(sess):
    # dictionary insertion order deliberately != lexical order
    sess.sql("create table wc (s text)")
    sess.sql("insert into wc values ('pear'),('apple'),('zebra')")
    df = sess.sql("select s, row_number() over (order by s) as rn "
                  "from wc order by s").to_pandas()
    assert list(zip(df.s, df.rn)) == [("apple", 1), ("pear", 2), ("zebra", 3)]


def test_intersect_precedence(sess):
    sess.sql("create table p1 (x int)"); sess.sql("insert into p1 values (1)")
    sess.sql("create table p2 (x int)"); sess.sql("insert into p2 values (2)")
    # 1 UNION (2 INTERSECT 2) = {1,2}; left-assoc would give {2}
    df = sess.sql("select x from p1 union select x from p2 "
                  "intersect select x from p2 order by x").to_pandas()
    assert df["x"].tolist() == [1, 2]


def test_except_all_supported(sess):
    sess.sql("create table q1 (x int)")
    sess.sql("insert into q1 values (1), (1), (2)")
    sess.sql("create table q2 (x int)")
    sess.sql("insert into q2 values (1)")
    df = sess.sql("select x from q1 except all "
                  "select x from q2").to_pandas()
    # bag semantics: ONE copy of 1 removed, the other and the 2 remain
    assert sorted(df["x"].tolist()) == [1, 2]


def test_explain_does_not_mutate_dictionary(sess):
    sess.sql("create table da (s text)"); sess.sql("insert into da values ('a')")
    sess.sql("create table db2 (s text)"); sess.sql("insert into db2 values ('zzz')")
    before = list(sess.catalog.table("da").dicts["s"].values)
    sess.explain("select s from da union select s from db2")
    assert sess.catalog.table("da").dicts["s"].values == before


def test_delete(sess):
    sess.sql("create table del_t (k int, v decimal(10,2))")
    sess.sql("insert into del_t values (1,1.0),(2,2.0),(3,3.0),(4,4.0)")
    assert sess.sql("delete from del_t where k > 2") == "DELETE 2"
    df = sess.sql("select k from del_t order by k").to_pandas()
    assert df["k"].tolist() == [1, 2]
    assert sess.sql("delete from del_t") == "DELETE 2"
    assert len(sess.sql("select k from del_t").to_pandas()) == 0


def test_update(sess):
    sess.sql("create table up_t (k int, v decimal(10,2), s text)")
    sess.sql("insert into up_t values (1,1.0,'a'),(2,2.0,'b'),(3,3.0,'c')")
    assert sess.sql("update up_t set v = v * 2 where k >= 2") == "UPDATE 2"
    df = sess.sql("select k, v from up_t order by k").to_pandas()
    assert df["v"].tolist() == [1.0, 4.0, 6.0]
    # string update with a NEW literal value
    assert sess.sql("update up_t set s = 'zzz' where k = 1") == "UPDATE 1"
    df = sess.sql("select s from up_t order by k").to_pandas()
    assert df["s"].tolist() == ["zzz", "b", "c"]
    # unconditional update
    assert sess.sql("update up_t set v = 0.5") == "UPDATE 3"
    assert sess.sql("select sum(v) as t from up_t").to_pandas()["t"][0] == 1.5


def test_insert_select(sess):
    sess.sql("create table src_t (k int, s text)")
    sess.sql("insert into src_t values (1,'x'),(2,'y')")
    sess.sql("create table dst_t (k int, s text)")
    assert sess.sql("insert into dst_t select k * 10, s from src_t") == "INSERT 2"
    assert sess.sql("insert into dst_t select k, s from src_t where k = 1") == "INSERT 1"
    df = sess.sql("select k, s from dst_t order by k").to_pandas()
    assert list(zip(df.k, df.s)) == [(1, "x"), (10, "x"), (20, "y")]


def test_dml_distributed():
    s = cb.Session(cb.Config(n_segments=4))
    s.sql("create table dd (k bigint, v decimal(10,2)) distributed by (k)")
    s.sql("insert into dd values " + ",".join(f"({i},{i}.0)" for i in range(40)))
    assert s.sql("delete from dd where k >= 30") == "DELETE 10"
    assert s.sql("update dd set v = v + 100.0 where k < 10") == "UPDATE 10"
    df = s.sql("select count(*) as n, sum(v) as t from dd").to_pandas()
    assert int(df["n"][0]) == 30
    assert float(df["t"][0]) == sum(i + 100 for i in range(10)) + sum(range(10, 30))


def test_statement_cache_reuse_and_invalidation(sess):
    sess.sql("create table sc (k int)")
    sess.sql("insert into sc values (1),(2),(3)")
    q = "select sum(k) as s from sc"
    assert sess.sql(q).to_pandas()["s"][0] == 6
    runner1 = sess._stmt_cache[q][4]
    assert sess.sql(q).to_pandas()["s"][0] == 6
    assert sess._stmt_cache[q][4] is runner1  # reused, not rebuilt
    # DML bumps the table version -> cache invalidated, result fresh
    sess.sql("insert into sc values (10)")
    assert sess.sql(q).to_pandas()["s"][0] == 16
    assert sess._stmt_cache[q][4] is not runner1


def test_statement_cache_drop_recreate_not_stale(sess):
    sess.sql("create table scd (s text)")
    sess.sql("insert into scd values ('a'),('b'),('b')")
    q = "select count(*) as n from scd where s = 'b'"
    assert int(sess.sql(q).to_pandas()["n"][0]) == 2
    sess.sql("drop table scd")
    sess.sql("create table scd (s text)")
    sess.sql("insert into scd values ('b'),('z'),('z')")
    # recreated table: dictionary codes differ; cache must NOT replay
    assert int(sess.sql(q).to_pandas()["n"][0]) == 1


def test_views(sess):
    sess.sql("create table vt (k int, v decimal(10,2))")
    sess.sql("insert into vt values (1,10.0),(2,20.0),(1,5.0)")
    sess.sql("create view vsum as select k, sum(v) as total from vt group by k")
    df = sess.sql("select k, total from vsum where total > 12 order by k").to_pandas()
    assert list(zip(df.k, df.total)) == [(1, 15.0), (2, 20.0)]
    # views track base-table changes (re-bound per statement)
    sess.sql("insert into vt values (2, 1.0)")
    df = sess.sql("select total from vsum where k = 2").to_pandas()
    assert df["total"].tolist() == [21.0]
    # view joins a table
    df = sess.sql("""select a.k from vsum a, vt b
                     where a.k = b.k and b.v = 5.0""").to_pandas()
    assert df["k"].tolist() == [1]
    sess.sql("drop view vsum")
    with pytest.raises(Exception):
        sess.sql("select * from vsum")


def test_view_ddl_invalidates_cache(sess):
    sess.sql("create table vb1 (x int)"); sess.sql("insert into vb1 values (1)")
    sess.sql("create table vb2 (x int)"); sess.sql("insert into vb2 values (2)")
    sess.sql("create view vv as select x from vb1")
    q = "select x from vv"
    assert sess.sql(q).to_pandas()["x"].tolist() == [1]
    sess.sql("drop view vv")
    sess.sql("create view vv as select x from vb2")
    assert sess.sql(q).to_pandas()["x"].tolist() == [2]  # not the stale plan
    with pytest.raises(BindError):
        sess.sql("create view vv as select 1")  # no OR REPLACE
    with pytest.raises(BindError):
        sess.sql("drop view no_such_view")
    with pytest.raises(BindError):
        sess.sql("create table vv (y int)")  # view shadow guard


def test_create_table_as_select(sess):
    sess.sql("create table base (k int, s text, v decimal(10,2))")
    sess.sql("insert into base values (1,'a',10.0),(2,'b',20.0),(3,'a',5.0)")
    out = sess.sql("""create table summary distributed by (s) as
                      select s, sum(v) as total, count(*) as n
                      from base group by s""")
    assert out == "SELECT 2"
    df = sess.sql("select s, total, n from summary order by s").to_pandas()
    assert list(zip(df.s, df.total, df.n)) == [("a", 15.0, 2), ("b", 20.0, 1)]
    from cloudberry_tpu.catalog.catalog import DistributionPolicy
    assert sess.catalog.table("summary").policy == DistributionPolicy.hashed("s")
    with pytest.raises(BindError):
        sess.sql("create table bad distributed by (nope) as select s from base")


def test_ctas_trailing_distributed_and_if_not_exists(sess):
    sess.sql("create table cb2 (k int)"); sess.sql("insert into cb2 values (1),(2)")
    # canonical trailing DISTRIBUTED BY form (query ends in a table name)
    sess.sql("create table c2 as select k from cb2 distributed by (k)")
    assert len(sess.sql("select k from c2").to_pandas()) == 2
    # IF NOT EXISTS no-ops on rerun
    out = sess.sql("create table if not exists c2 as select k from cb2")
    assert "skipped" in out
    with pytest.raises(BindError):
        sess.sql("create table c2 as select k from cb2")


def test_copy_from_and_to(sess, tmp_path):
    p = tmp_path / "in.tbl"
    p.write_text("1|9.99|apple|2024-01-05\n"
                 "2|12.50|pear|2024-02-01\n"
                 "3|0.07|fig|2024-01-20\n")
    sess.sql("create table cp (id bigint, price decimal(10,2), name text, d date)")
    out = sess.sql(f"copy cp from '{p}'")
    assert out == "COPY 3"
    df = sess.sql("select id, price, name from cp order by id").to_pandas()
    assert df["price"].tolist() == [9.99, 12.50, 0.07]
    assert df["name"].tolist() == ["apple", "pear", "fig"]
    # append semantics + header + custom delimiter
    p2 = tmp_path / "in2.csv"
    p2.write_text("id,price,name,d\n4,1.25,kiwi,2024-03-01\n")
    assert sess.sql(f"copy cp from '{p2}' with delimiter ',' header") == "COPY 1"
    assert len(sess.sql("select id from cp").to_pandas()) == 4
    # unload round-trip
    p3 = tmp_path / "out.tbl"
    assert sess.sql(f"copy cp to '{p3}'") == "COPY 4"
    sess.sql("create table cp2 (id bigint, price decimal(10,2), name text, d date)")
    assert sess.sql(f"copy cp2 from '{p3}'") == "COPY 4"
    a = sess.sql("select sum(price) as s from cp").to_pandas()["s"][0]
    b = sess.sql("select sum(price) as s from cp2").to_pandas()["s"][0]
    assert a == b


def test_copy_edge_cases(sess, tmp_path):
    sess.sql("create table ce (b boolean, f double, s text)")
    bad = tmp_path / "b.tbl"
    bad.write_text("maybe|1.5|x\n")
    with pytest.raises(BindError):
        sess.sql(f"copy ce from '{bad}'")  # bad boolean rejected
    bad2 = tmp_path / "b2.tbl"
    bad2.write_text("true|oops|x\n")
    with pytest.raises(BindError):
        sess.sql(f"copy ce from '{bad2}'")  # bad double rejected
    # delimiter inside a string value refuses to unload corruptly
    sess.sql("insert into ce values (true, 1.0, 'a|b')")
    with pytest.raises(BindError):
        sess.sql(f"copy ce to '{tmp_path / 'o.tbl'}'")
    # big exact decimal round-trips through COPY TO text
    sess.sql("create table bd (v decimal(18,2))")
    sess.sql("insert into bd values (90071992547409.93)")
    out = tmp_path / "bd.tbl"
    sess.sql(f"copy bd to '{out}'")
    assert out.read_text().strip() == "90071992547409.93"


def test_full_outer_join(sess):
    sess.sql("create table fa (k int, a int)")
    sess.sql("insert into fa values (1,10),(2,20),(3,30)")
    sess.sql("create table fb (k int, b int)")
    sess.sql("insert into fb values (2,200),(3,300),(4,400),(2,201)")
    df = sess.sql("""select fa.k, a, b from fa full join fb on fa.k = fb.k
                     order by a, b""").to_pandas()
    # pairs: (2,20,200),(2,20,201),(3,30,300); probe-only (1,10,-);
    # build-only (-,-,400) — zeros stand in for NULL values, masks track
    assert len(df) == 5
    # IS NULL works on both sides
    df2 = sess.sql("""select a from fa full join fb on fa.k = fb.k
                      where b is null""").to_pandas()
    assert df2["a"].tolist() == [10]
    df3 = sess.sql("""select b from fa full join fb on fa.k = fb.k
                      where a is null""").to_pandas()
    assert df3["b"].tolist() == [400]
    # counts are null-aware on both sides
    df4 = sess.sql("""select count(a) as ca, count(b) as cb, count(*) as n
                      from fa full join fb on fa.k = fb.k""").to_pandas()
    assert (int(df4.ca[0]), int(df4.cb[0]), int(df4.n[0])) == (4, 4, 5)


def test_full_outer_join_distributed():
    s = cb.Session(cb.Config(n_segments=4))
    s.sql("create table fa (k bigint, a bigint) distributed by (k)")
    s.sql("insert into fa values " + ",".join(f"({i},{i})" for i in range(0, 30, 2)))
    s.sql("create table fb (k bigint, b bigint) distributed by (k)")
    s.sql("insert into fb values " + ",".join(f"({i},{i*10})" for i in range(0, 30, 3)))
    got = s.sql("""select count(*) as n, count(a) as ca, count(b) as cb
                   from fa full join fb on fa.k = fb.k""").to_pandas()
    # evens 15, multiples-of-3 10, both (mult of 6) 5 -> union 20 rows
    assert int(got.n[0]) == 20
    assert int(got.ca[0]) == 15 and int(got.cb[0]) == 10


def test_full_join_null_rendering_and_coalesce(sess):
    sess.sql("create table jl (k int, a text)")
    sess.sql("insert into jl values (1,'x'),(2,'y')")
    sess.sql("create table jr (k int, b text)")
    sess.sql("insert into jr values (2,'p'),(3,'q')")
    df = sess.sql("""select coalesce(jl.k, jr.k) as k, a, b
                     from jl full join jr on jl.k = jr.k
                     order by k""").to_pandas()
    def norm(vals):
        return [None if v is None or (isinstance(v, float) and v != v)
                else v for v in vals]

    assert df["k"].tolist() == [1, 2, 3]
    assert norm(df["a"]) == ["x", "y", None]
    assert norm(df["b"]) == [None, "p", "q"]
    # left join renders NULL for unmatched build columns
    df2 = sess.sql("select a, b from jl left join jr on jl.k = jr.k "
                   "order by a").to_pandas()
    assert norm(df2["b"]) == [None, "p"]


def test_coalesce_chains_and_insert_literals(sess):
    sess.sql("create table cbase (k int)")
    sess.sql("insert into cbase values (1),(2),(3)")
    sess.sql("create table cr1 (k int, x bigint)")
    sess.sql("insert into cr1 values (1, 10)")
    sess.sql("create table cr2 (k int, y bigint)")
    sess.sql("insert into cr2 values (3, 300)")
    df = sess.sql("""select cbase.k, coalesce(x, y) as v
                     from cbase left join cr1 on cbase.k = cr1.k
                                left join cr2 on cbase.k = cr2.k
                     order by cbase.k""").to_pandas()
    vals = [None if v is None or (isinstance(v, float) and v != v) else int(v)
            for v in df["v"]]
    assert vals == [10, None, 300]  # all-null row renders NULL, not 0
    # mixed-width coalesce keeps masks through coercion
    sess.sql("create table cw (k int, small integer)")
    sess.sql("insert into cw values (2, 7)")
    df2 = sess.sql("""select coalesce(small, x) as v
                      from cbase left join cw on cbase.k = cw.k
                                 left join cr1 on cbase.k = cr1.k
                      order by cbase.k""").to_pandas()
    v2 = [None if v is None or (isinstance(v, float) and v != v) else int(v)
          for v in df2["v"]]
    assert v2 == [10, 7, None]
    # INSERT literal coercions: rounding + clean errors
    sess.sql("create table ints (x int)")
    sess.sql("insert into ints values (2.5), (1e2)")
    # 2.5 rounds half-away like PostgreSQL -> 3
    assert sorted(sess.sql("select x from ints").to_pandas().x) == [3, 100]
    sess.sql("create table decs (v decimal(10,2))")
    sess.sql("insert into decs values (1.999)")
    assert sess.sql("select v from decs").to_pandas().v[0] == 2.0
    with pytest.raises(BindError):
        sess.sql("insert into ints values ('nope')")


def test_transactions(sess):
    sess.sql("create table tx (k int, s text)")
    sess.sql("insert into tx values (1,'a')")
    assert sess.sql("begin") == "BEGIN"
    sess.sql("insert into tx values (2,'brandnew')")
    sess.sql("update tx set s = 'changed' where k = 1")
    sess.sql("create table tx2 (x int)")
    sess.sql("create view txv as select k from tx")
    # read-your-writes inside the transaction
    assert len(sess.sql("select k from tx").to_pandas()) == 2
    assert sess.sql("rollback") == "ROLLBACK"
    df = sess.sql("select k, s from tx").to_pandas()
    assert list(zip(df.k, df.s)) == [(1, "a")]  # data AND dictionary restored
    with pytest.raises(Exception):
        sess.sql("select * from tx2")  # created table rolled back
    with pytest.raises(Exception):
        sess.sql("select * from txv")  # created view rolled back
    # commit path
    sess.sql("begin transaction")
    sess.sql("delete from tx where k = 1")
    assert sess.sql("commit") == "COMMIT"
    assert len(sess.sql("select k from tx").to_pandas()) == 0
    # protocol errors
    with pytest.raises(BindError):
        sess.sql("commit")
    sess.sql("begin")
    with pytest.raises(BindError):
        sess.sql("begin")
    sess.sql("abort")


def test_review_fixes_star_nested_coalesce_bigint(sess):
    sess.sql("create table ja (k int, a text)")
    sess.sql("insert into ja values (1,'x')")
    sess.sql("create table jb (k int, b text)")
    sess.sql("insert into jb values (2,'q')")
    df = sess.sql("select * from ja full join jb on ja.k = jb.k "
                  "order by ja.k").to_pandas()
    flat = [None if v is None or (isinstance(v, float) and v != v) else v
            for v in df.iloc[:, 1].tolist()]  # 'a' column
    assert None in flat  # star output renders NULLs, not placeholder 'x'

    # nested coalesce falls through to the terminal default
    sess.sql("create table nb (k int)")
    sess.sql("insert into nb values (1),(2)")
    sess.sql("create table n1 (k int, x bigint)")
    sess.sql("insert into n1 values (1, 10)")
    df2 = sess.sql("""select coalesce(coalesce(x, x), 777) as v
                      from nb left join n1 on nb.k = n1.k
                      order by nb.k""").to_pandas()
    assert [int(v) for v in df2.v] == [10, 777]

    # bigint literal beyond 2^53 survives digit-exact
    sess.sql("create table bigv (v bigint)")
    sess.sql("insert into bigv values (9007199254740993)")
    assert int(sess.sql("select v from bigv").to_pandas().v[0]) == 9007199254740993

    # long transaction spellings
    sess.sql("begin work"); sess.sql("commit work")
    sess.sql("begin"); sess.sql("rollback transaction")


def test_string_coalesce_cross_dict(sess):
    sess.sql("create table sc_a (k int, a text)")
    sess.sql("insert into sc_a values (1,'x')")
    sess.sql("create table sc_b (k int, b text)")
    sess.sql("insert into sc_b values (2,'q')")
    df = sess.sql("""select coalesce(a, b) as v
                     from sc_a full join sc_b on sc_a.k = sc_b.k
                     order by v""").to_pandas()
    assert sorted(df.v.tolist()) == ["q", "x"]  # codes re-based, not aliased
    df2 = sess.sql("""select coalesce(a, 'none') as v
                      from sc_a full join sc_b on sc_a.k = sc_b.k
                      order by v""").to_pandas()
    assert sorted(df2.v.tolist()) == ["none", "x"]
    # huge int literal -> clean BindError, not OverflowError
    sess.sql("create table ovf (v bigint)")
    with pytest.raises(BindError):
        sess.sql("insert into ovf values (99999999999999999999)")
