import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.plan.binder import BindError


@pytest.fixture
def sess():
    return cb.Session()


def test_create_insert_select(sess):
    sess.sql("""create table items (id bigint not null, price decimal(10,2),
                name text, sold date) distributed by (id)""")
    sess.sql("""insert into items values
                (1, 9.99, 'apple', '2024-01-05'),
                (2, 12.50, 'pear', '2024-02-01'),
                (3, 0.99, 'fig', '2024-01-20')""")
    out = sess.sql("select name, price from items where price > 5 order by price desc")
    df = out.to_pandas()
    assert df["name"].tolist() == ["pear", "apple"]
    assert df["price"].tolist() == [12.50, 9.99]


def test_group_and_having(sess):
    sess.sql("create table s (k text, v int) distributed randomly")
    sess.sql("insert into s values ('a',1),('a',2),('b',5),('b',7),('c',1)")
    df = sess.sql("""select k, sum(v) as total, count(*) as n from s
                     group by k having sum(v) > 2 order by total desc""").to_pandas()
    assert df["k"].tolist() == ["b", "a"]
    assert df["total"].tolist() == [12, 3]
    assert df["n"].tolist() == [2, 2]


def test_string_order_by_uses_collation(sess):
    sess.sql("create table t (s text) distributed randomly")
    sess.sql("insert into t values ('pear'),('apple'),('zebra'),('fig')")
    df = sess.sql("select s from t order by s").to_pandas()
    assert df["s"].tolist() == ["apple", "fig", "pear", "zebra"]


def test_distinct(sess):
    sess.sql("create table d (x int) distributed randomly")
    sess.sql("insert into d values (3),(1),(3),(2),(1)")
    df = sess.sql("select distinct x from d order by x").to_pandas()
    assert df["x"].tolist() == [1, 2, 3]


def test_case_expression(sess):
    sess.sql("create table c (v int) distributed randomly")
    sess.sql("insert into c values (1),(5),(10)")
    df = sess.sql("""select case when v < 3 then 'small'
                                when v < 8 then 'mid'
                                else 'big' end as bucket
                     from c order by v""").to_pandas()
    assert df["bucket"].tolist() == ["small", "mid", "big"]


def test_drop_and_errors(sess):
    sess.sql("create table gone (x int)")
    sess.sql("drop table gone")
    with pytest.raises(KeyError):
        sess.sql("select * from gone")
    sess.sql("create table there (x int)")
    with pytest.raises(BindError):
        sess.sql("select nosuchcol from there")


def test_decimal_exactness(sess):
    # classic float-sum trap: 0.1 + 0.2 — int64 fixed point stays exact
    sess.sql("create table m (v decimal(10,2))")
    rows = ",".join(["(0.10)"] * 100)
    sess.sql(f"insert into m values {rows}")
    df = sess.sql("select sum(v) as s from m").to_pandas()
    assert df["s"][0] == 10.0  # exactly, no 9.999999...
