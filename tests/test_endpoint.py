"""Parallel retrieve cursors / endpoints (cdbendpoint.c analog).

Reference: DECLARE ... PARALLEL RETRIEVE CURSOR keeps each segment's
result slice on the segment as a token-authenticated endpoint; clients
drain endpoints in parallel over retrieve-mode connections
(src/backend/cdb/endpoint/README, cdbendpointretrieve.c).
"""

from concurrent.futures import ThreadPoolExecutor

import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.plan.binder import BindError
from cloudberry_tpu.serve.client import Client, ServerError
from cloudberry_tpu.serve.server import Server


@pytest.fixture
def sess():
    s = cb.Session(Config(n_segments=8))
    s.sql("create table t (k bigint, v bigint) distributed by (k)")
    s.sql("insert into t values " +
          ", ".join(f"({i}, {i * 3})" for i in range(500)))
    return s


def test_declare_creates_per_segment_endpoints(sess):
    info = sess.sql("declare c1 parallel retrieve cursor for "
                    "select k, v from t where v % 2 = 0")
    assert info["parallel"] is True
    assert len(info["endpoints"]) == 8
    total = sum(e["rows"] for e in info["endpoints"])
    # oracle: count of even v
    want = sess.sql("select count(*) as c from t where v % 2 = 0") \
        .to_pandas()["c"].iloc[0]
    assert total == want


def test_retrieve_union_equals_direct_result(sess):
    sess.sql("declare c2 parallel retrieve cursor for select k, v from t")
    got = []
    for s in range(8):
        out = sess.retrieve("c2", s)
        got.extend(tuple(r) for r in out["rows"])
        assert out["remaining"] == 0
    direct = sess.sql("select k, v from t").to_pandas()
    assert sorted(got) == sorted(
        (int(a), int(b)) for a, b in direct.to_numpy())


def test_incremental_retrieve(sess):
    sess.sql("declare c3 parallel retrieve cursor for select k from t")
    first = sess.retrieve("c3", 0, limit=10)
    assert len(first["rows"]) == 10
    rest = sess.retrieve("c3", 0)
    assert rest["remaining"] == 0
    assert len(first["rows"]) + len(rest["rows"]) \
        == first["remaining"] + 10


def test_gathered_plan_falls_back_to_entry_endpoint(sess):
    info = sess.sql("declare c4 parallel retrieve cursor for "
                    "select k, v from t order by v desc limit 7")
    assert info["parallel"] is False
    assert len(info["endpoints"]) == 1
    out = sess.retrieve("c4", 0)
    assert len(out["rows"]) == 7


def test_close_and_errors(sess):
    sess.sql("declare c5 parallel retrieve cursor for select k from t")
    with pytest.raises(BindError):
        sess.sql("declare c5 parallel retrieve cursor for select k from t")
    sess.sql("close c5")
    with pytest.raises(Exception):
        sess.retrieve("c5", 0)


def test_cursor_respects_queue_max_cost(sess):
    from cloudberry_tpu.exec.resource import ResourceError

    sess.sql("create resource queue tiny with (max_cost=1024)")
    sess.config = sess.config.with_overrides(**{"resource.queue": "tiny"})
    with pytest.raises(ResourceError, match="MAX_COST"):
        sess.sql("declare cq parallel retrieve cursor for "
                 "select k, v from t")
    assert "cq" not in sess.parallel_cursors


def test_cursor_holds_vmem_until_close(sess):
    before = sess._vmem.used
    sess.sql("declare ch parallel retrieve cursor for select k, v from t")
    assert sess._vmem.used > before  # held results stay reserved
    sess.sql("close ch")
    assert sess._vmem.used == before


def test_wire_parallel_retrieval_with_token():
    session = cb.Session(Config(n_segments=8))
    session.sql("create table w (k bigint, v bigint) distributed by (k)")
    session.sql("insert into w values " +
                ", ".join(f"({i}, {i})" for i in range(256)))
    with Server(session=session) as srv:
        boss = Client(srv.host, srv.port)
        info = boss.sql("declare wc parallel retrieve cursor for "
                        "select k, v from w")
        token = info["token"]
        assert len(info["endpoints"]) == 8

        def drain(seg: int):
            with Client(srv.host, srv.port) as c:
                return c.retrieve("wc", seg, token)["rows"]

        # the reference's whole point: N connections drain N endpoints
        # concurrently
        with ThreadPoolExecutor(max_workers=8) as ex:
            chunks = list(ex.map(drain, range(8)))
        got = sorted(tuple(r) for ch in chunks for r in ch)
        assert got == [(i, i) for i in range(256)]
        # bad token is refused (EndpointTokenHash auth)
        with Client(srv.host, srv.port) as c:
            with pytest.raises(ServerError, match="token"):
                c.retrieve("wc", 0, "wrong-token")
        boss.close()
