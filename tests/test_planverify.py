"""planck — the plan-IR verifier (plan/verify.py), pinned three ways.

1. Seeded plan-mutation fuzzing: every corruption class in
   plan/mutate.py (drop a motion, wrong hash cols, lie about a rung,
   desync a param slot, ...) must be CAUGHT with a node-path finding
   carrying the expected rule — and the uncorrupted plan must verify
   clean first, so a finding is attributable to the mutation alone.
2. The ``config.debug.verify_plans`` session gate: clean statements
   run bit-identically with the gate on; a corrupted plan raises
   PlanVerifyError instead of compiling.
3. Contract surfaces: $params slot consistency against the paramplan
   signature, EXPLAIN's ``dist:`` derived-distribution annotation, the
   recovery-mode re-placement registry, and the rule-table coverage
   counters the bench's ``planverify`` record rides.
"""

import numpy as np
import pandas as pd
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from cloudberry_tpu.plan.mutate import MUTATIONS
from cloudberry_tpu.plan.planner import plan_statement
from cloudberry_tpu.plan.verify import (PlanVerifyError, Verifier,
                                        check_plan, verify_plan,
                                        verify_stats)
from cloudberry_tpu.sql.parser import parse_sql
from tools.tpch_queries import QUERIES
from tools.tpchgen import load_tpch


@pytest.fixture(scope="module")
def dist_session():
    s = cb.Session(Config(n_segments=8))
    load_tpch(s, sf=0.01, seed=7)
    return s


@pytest.fixture(scope="module")
def single_session():
    s = cb.Session()
    load_tpch(s, sf=0.01, seed=7)
    return s


def _plan(session, sql):
    return plan_statement(parse_sql(sql), session, {}).plan


# ------------------------------------------------- clean-plan baseline


@pytest.mark.parametrize("qname", ["q1", "q3", "q5", "q9", "q18"])
def test_tpch_plans_verify_clean(dist_session, single_session, qname):
    for s in (dist_session, single_session):
        findings = verify_plan(_plan(s, QUERIES[qname]), s)
        assert findings == [], [f.render() for f in findings]


def test_rule_table_covers_walked_nodes(dist_session):
    """Every node class the TPC-H corpus exercises hits a rule row —
    the coverage counters the bench planverify record reports."""
    stats = verify_stats(_plan(dist_session, QUERIES["q3"]),
                         dist_session)
    assert stats["findings"] == []
    assert stats["nodes"] > 10
    for want in ("PScan", "PJoin", "PMotion", "PAgg", "PSort",
                 "PLimit", "PFilter", "PProject"):
        assert want in stats["rules_hit"], stats["rules_hit"]


# ------------------------------------------- seeded mutation fuzzing


@pytest.mark.parametrize("mutation", sorted(MUTATIONS))
def test_mutation_caught(dist_session, mutation):
    sql, fn, expected = MUTATIONS[mutation]
    plan = _plan(dist_session, sql)
    pre = verify_plan(plan, dist_session)
    assert pre == [], (
        f"fixture query dirty before mutation: "
        f"{[f.render() for f in pre]}")
    out = fn(plan, dist_session)
    assert out is not None, (
        f"mutation {mutation!r} found no target in its fixture plan — "
        "the corpus went stale; update its SQL in plan/mutate.py")
    mutated, desc = out
    findings = verify_plan(mutated, dist_session)
    hit = [f for f in findings if f.rule in expected]
    assert hit, (
        f"{mutation!r} ({desc}) not caught: expected one of "
        f"{sorted(expected)}, got "
        f"{[f.render() for f in findings] or 'CLEAN'}")
    # every finding is a node-path diagnostic, not a bare message: the
    # path anchors at a node label (class-cased) and renders as
    # "path: rule: message"
    for f in hit:
        assert f.path and f.path[0].isupper(), f.render()
        assert f.render().startswith(f"{f.path}: {f.rule}: ")


def test_mutation_corpus_size():
    """The acceptance floor: >= 15 distinct corruption classes."""
    assert len(MUTATIONS) >= 15


# --------------------------------------------------- the session gate


def test_gate_clean_statement_bit_identical():
    base = cb.Session(Config(n_segments=8))
    load_tpch(base, sf=0.01, seed=7)
    gated = cb.Session(Config(n_segments=8).with_overrides(
        **{"debug.verify_plans": True}))
    load_tpch(gated, sf=0.01, seed=7)
    for qname in ("q3", "q6"):
        a = base.sql(QUERIES[qname]).to_pandas()
        b = gated.sql(QUERIES[qname]).to_pandas()
        pd.testing.assert_frame_equal(a, b)


def test_gate_raises_on_corrupt_plan(dist_session):
    sql, fn, expected = MUTATIONS["drop-motion-under-join"]
    plan = _plan(dist_session, sql)
    mutated, _ = fn(plan, dist_session)
    with pytest.raises(PlanVerifyError) as ei:
        check_plan(mutated, dist_session, "test")
    assert any(f.rule in expected for f in ei.value.findings)
    # the error text carries the node path (file:node-path diagnostic)
    assert "Join" in str(ei.value)


def test_gate_on_in_golden_sessions():
    from tools.golden_plans import _config

    assert _config(8).debug.verify_plans
    assert _config(1).debug.verify_plans


# ------------------------------------------------ paramplan slot gate


def test_param_slots_verify_against_signature(dist_session):
    from cloudberry_tpu.sched import paramplan

    plan = _plan(dist_session,
                 "select l_orderkey from lineitem where l_quantity > 17")
    sig, bindings, keyed, slots = paramplan.analyze(
        dist_session, plan, rewrite=True)
    assert slots, "expected a parameterized literal"
    assert verify_plan(plan, dist_session,
                       declared_slots=list(slots)) == []
    # declared signature shorter than the plan's slots: desync
    bad = verify_plan(plan, dist_session, declared_slots=[])
    assert any(f.rule == "param-slot-desync" for f in bad)
    # declared dtype disagrees with the plan's Param dtype: desync
    from cloudberry_tpu.types import BOOL

    bad = verify_plan(plan, dist_session,
                      declared_slots=[BOOL] * len(slots))
    assert any(f.rule == "param-slot-desync" for f in bad)


def test_nrw_slots_verify_against_signature(dist_session):
    from cloudberry_tpu.plan import nodes as N
    from cloudberry_tpu.sched import paramplan

    plan = _plan(dist_session,
                 "select count(*) as n from lineitem, orders "
                 "where l_orderkey = o_orderkey")
    sig, bindings, keyed, slots = paramplan.analyze(
        dist_session, plan, rewrite=True)
    nrw = sum(1 for k in bindings if k.startswith("$nrw"))
    assert nrw >= 2, bindings.keys()
    assert verify_plan(plan, dist_session, declared_slots=list(slots),
                       declared_nrw=nrw) == []
    # signature count desync
    bad = verify_plan(plan, dist_session, declared_nrw=nrw + 1)
    assert any(f.rule == "param-slot-desync" and "$nrw" in f.message
               for f in bad)
    # duplicate stamp: two scans feeding off one row-count input
    scans = [n for n, _ in
             __import__("cloudberry_tpu.plan.verify",
                        fromlist=["_walk_paths"])._walk_paths(plan)
             if isinstance(n, N.PScan)
             and getattr(n, "_nrows_key", None)]
    scans[1]._nrows_key = scans[0]._nrows_key
    bad = verify_plan(plan, dist_session, declared_nrw=nrw)
    assert any(f.rule == "param-slot-desync" and "stamped on" in
               f.message for f in bad)


def test_generic_plan_build_runs_gate():
    """The GenericPlan constructor verifies the rewritten ($params)
    form when the gate is on — and the statement still executes."""
    s = cb.Session(Config(n_segments=1).with_overrides(
        **{"debug.verify_plans": True}))
    load_tpch(s, sf=0.01, seed=7)
    q = "select count(*) as n from lineitem where l_quantity > 17"
    a = s.sql(q).to_pandas()
    b = s.sql(q.replace("17", "18")).to_pandas()  # rebind, same skeleton
    assert int(a["n"][0]) > int(b["n"][0]) > 0


# ------------------------------------------------- explain annotation


def test_explain_dist_annotation(dist_session, single_session):
    txt = dist_session.explain(QUERIES["q3"])
    assert "dist:hashed(" in txt
    assert "dist:singleton" in txt
    assert "dist:replicated" in txt
    # every node line carries the derived annotation at nseg > 1
    for line in txt.splitlines():
        if "-> " in line:
            assert "dist:" in line, line
    # single-segment plans have no distribution to derive
    assert "dist:" not in single_session.explain(QUERIES["q3"])


def test_explain_dist_matches_stamp(dist_session):
    """In a clean plan the derived annotation agrees with the stamped
    locus — the bracketed and dist: values are independent
    computations of the same property."""
    txt = dist_session.explain(QUERIES["q10"])
    for line in txt.splitlines():
        if "[" in line and "dist:" in line:
            head = line.split("dist:", 1)[0]
            stamped = head.rsplit("[", 1)[1].split("]", 1)[0]
            derived = line.split("dist:", 1)[1].strip()
            assert stamped == derived, line


# ------------------------------------------------- contract registries


def test_recovery_mode_drift_is_a_finding(dist_session, monkeypatch):
    import cloudberry_tpu.exec.recovery as R

    monkeypatch.setattr(
        R, "REPLACEABLE",
        {k: v for k, v in R.REPLACEABLE.items() if k != "topn"})
    findings = verify_plan(_plan(dist_session, QUERIES["q6"]),
                           dist_session)
    assert any(f.rule == "recovery-mode-unreplaceable"
               for f in findings)


def test_unruled_node_class_is_a_finding(dist_session):
    from cloudberry_tpu.plan import nodes as N

    class PRogue(N.PlanNode):
        pass

    rogue = PRogue()
    rogue.fields = []
    plan = _plan(dist_session, QUERIES["q6"])
    # graft the rogue node over the root: walking it must report the
    # missing rule row instead of crashing or silently passing
    rogue.children = lambda: [plan]
    findings = verify_plan(rogue, dist_session)
    assert any(f.rule == "planprops-unruled" for f in findings)


# ----------------------------------------------------- corpus helper


def test_verify_corpus_smoke(monkeypatch):
    """The lint_gate --plans / bench planverify entry point, on a
    TPC-H-only corpus (the full TPC-DS sweep rides the golden tests)."""
    import tools.golden_plans as G

    monkeypatch.setattr(
        G, "corpus",
        lambda: [("tpch", G.make_session,
                  {"q3": QUERIES["q3"], "q6": QUERIES["q6"]})])
    rec = G.verify_corpus(nsegs=(8,))
    assert rec["plans"] == 2
    assert rec["findings"] == []
    assert rec["nodes"] > 10 and rec["wall_s"] > 0
    assert "PMotion" in rec["rules_hit"]


def test_verifier_local_mode_skips_distribution(single_session):
    """Single-segment plans have no sharding stamps; the verifier
    still runs every lowering-contract check."""
    plan = _plan(single_session, QUERIES["q1"])
    v = Verifier(single_session, plan)
    assert v.local
    assert v.verify(plan) == []
    # a local-mode contract still fires: scan row overflow
    from cloudberry_tpu.plan import nodes as N

    def scans(p):
        if isinstance(p, N.PScan):
            yield p
        for c in p.children():
            yield from scans(c)
    sc = next(scans(plan))
    sc.num_rows = sc.capacity + 1
    findings = verify_plan(plan, single_session)
    assert any(f.rule == "scan-rows" for f in findings)
