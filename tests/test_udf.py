"""Scalar UDFs (exec/udf.py) — the procedural-language seam.

The reference runs PL functions per tuple (src/pl/plpgsql); here the
three compilable shapes are pinned: bind-time constant folding,
dictionary rewrite over a string column (the LIKE machinery), and
jax-traced functions compiled into the program. Distributed semantics
must match single-node exactly.
"""

import numpy as np
import pandas as pd
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu import types as T
from cloudberry_tpu.config import Config
from cloudberry_tpu.plan.binder import BindError
from cloudberry_tpu.exec.udf import (known_functions, register_function,
                                     unregister_function)


@pytest.fixture(scope="module", autouse=True)
def _funcs():
    register_function("initials", lambda s: "".join(
        w[0].upper() for w in s.split()), [T.STRING], T.STRING)
    register_function("name_len", lambda s: len(s), [T.STRING], T.INT64)
    register_function("double_it", lambda x: x * 2, [T.INT64], T.INT64,
                      jit=True)
    register_function("taxed", lambda x, r: x * (1.0 + r),
                      [T.FLOAT64, T.FLOAT64], T.FLOAT64, jit=True)
    register_function("const_ans", lambda: 42, [], T.INT64)
    register_function("odd_null", lambda s: None if len(s) % 2 else
                      s.upper(), [T.STRING], T.STRING)
    register_function("suffixed", lambda s, suf: s + suf,
                      [T.STRING, T.STRING], T.STRING)
    yield
    for n in ("initials", "name_len", "double_it", "taxed", "const_ans",
              "odd_null", "suffixed"):
        unregister_function(n)


def _mk(nseg):
    s = cb.Session(Config(n_segments=nseg)) if nseg > 1 else cb.Session()
    s.sql("create table p (k bigint, name text, sal double) "
          "distributed by (k)")
    s.sql("insert into p values (1, 'ada lovelace', 100.0), "
          "(2, 'alan turing', 200.0), (3, 'grace hopper', 300.0), "
          "(4, null, 400.0)")
    return s


@pytest.fixture(scope="module", params=[1, 8], ids=["single", "dist8"])
def s(request):
    return _mk(request.param)


def test_dictionary_rewrite_select_and_where(s):
    df = s.sql("select k, initials(name) as ini, name_len(name) as nl "
               "from p order by k").to_pandas()
    assert list(df["ini"])[:3] == ["AL", "AT", "GH"]
    assert pd.isna(df["ini"][3])
    assert list(df["nl"])[:3] == [12, 11, 12]
    assert pd.isna(df["nl"][3])
    df = s.sql("select k from p where initials(name) = 'AL'").to_pandas()
    assert list(df["k"]) == [1]
    # UDF output feeding another expression and GROUP BY
    df = s.sql("select name_len(name) as nl, count(*) as n from p "
               "where name is not null group by name_len(name) "
               "order by nl").to_pandas()
    assert list(df["nl"]) == [11, 12] and list(df["n"]) == [1, 2]


def test_jit_udf_compiles_into_program(s):
    df = s.sql("select k, double_it(k) as dk, taxed(sal, 0.1) as tx "
               "from p order by k").to_pandas()
    assert list(df["dk"]) == [2, 4, 6, 8]
    assert np.allclose(df["tx"], [110.0, 220.0, 330.0, 440.0])
    df = s.sql("select k from p where double_it(k) > 4 "
               "order by k").to_pandas()
    assert list(df["k"]) == [3, 4]


def test_constant_folding(s):
    df = s.sql("select const_ans() as c, name_len('abc') as n, "
               "initials('alan mathison turing') as i").to_pandas()
    assert df["c"][0] == 42 and df["n"][0] == 3 and df["i"][0] == "AMT"


def test_null_in_null_out(s):
    df = s.sql("select name_len(null) as n from p limit 1").to_pandas()
    assert df["n"][0] is None or pd.isna(df["n"][0])
    # per-value None from the function NULLs exactly those rows
    df = s.sql("select k, odd_null(name) as o from p order by k").to_pandas()
    assert df["o"][0] == "ADA LOVELACE"
    assert pd.isna(df["o"][1])  # 'alan turing' has odd length
    assert df["o"][2] == "GRACE HOPPER"
    assert pd.isna(df["o"][3])


def test_string_with_constant_extra_arg(s):
    df = s.sql("select suffixed(name, '!') as x from p "
               "where k = 2").to_pandas()
    assert df["x"][0] == "alan turing!"


def test_errors(s):
    with pytest.raises(BindError, match="argument"):
        s.sql("select name_len() from p")
    with pytest.raises(BindError, match="unknown function"):
        s.sql("select nope(k) from p")
    # non-jit numeric-column call has no compilable shape
    register_function("pyonly", lambda x: x + 1, [T.INT64], T.INT64)
    try:
        with pytest.raises(BindError, match="does not compile"):
            s.sql("select pyonly(k) from p")
    finally:
        unregister_function("pyonly")
    assert "initials" in known_functions()


def test_distributed_matches_single():
    a = _mk(1)
    b = _mk(8)
    q = ("select initials(name) as i, name_len(name) as n, "
         "double_it(k) as d from p order by k")
    assert a.sql(q).to_pandas().equals(b.sql(q).to_pandas())


def test_reregistration_invalidates_cached_statements():
    """Re-registering a function (CREATE OR REPLACE) must drop cached
    runners whose plans baked the OLD function's results in."""
    s = _mk(1)
    register_function("twist", lambda x: x + 1, [T.INT64], T.INT64,
                      jit=True)
    try:
        q = "select twist(k) as t from p order by k"
        assert list(s.sql(q).to_pandas()["t"]) == [2, 3, 4, 5]
        assert list(s.sql(q).to_pandas()["t"]) == [2, 3, 4, 5]  # cached
        register_function("twist", lambda x: x * 10, [T.INT64], T.INT64,
                          jit=True)
        assert list(s.sql(q).to_pandas()["t"]) == [10, 20, 30, 40]
    finally:
        unregister_function("twist")
