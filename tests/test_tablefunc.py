"""Set-returning table functions (exec/tablefunc.py) — the Function
Scan / TableFunction node analog (nodeFunctionscan.c): host-side
bind-time evaluation into a transient replicated table, refreshed per
referencing statement, with register_table_function as the
CustomScan-style extension hook."""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config
from cloudberry_tpu.exec.tablefunc import register_table_function
from cloudberry_tpu.plan.binder import BindError


def _mk(nseg=1):
    return cb.Session(get_config().with_overrides(n_segments=nseg))


@pytest.fixture(scope="module", params=[1, 8], ids=["single", "dist8"])
def s(request):
    return _mk(request.param)


def test_generate_series(s):
    out = s.sql("select * from generate_series(1, 5)").to_pandas()
    assert out.iloc[:, 0].tolist() == [1, 2, 3, 4, 5]  # inclusive stop
    out = s.sql("select * from generate_series(0, 10, 3)").to_pandas()
    assert out.iloc[:, 0].tolist() == [0, 3, 6, 9]
    out = s.sql("select * from generate_series(5, 1, -2)").to_pandas()
    assert out.iloc[:, 0].tolist() == [5, 3, 1]
    assert len(s.sql("select * from generate_series(5, 1)").to_pandas()) \
        == 0


def test_function_scan_joins_without_motion(s):
    s.sql("create table ft (a int) distributed by (a)")
    s.sql("insert into ft values (2), (4), (9)")
    df = s.sql("select a from ft join generate_series(1, 5) gs "
               "on a = gs.generate_series order by a").to_pandas()
    assert df["a"].tolist() == [2, 4]
    # replicated transient table: the General locus — no broadcast or
    # redistribute needed on the function side of the join
    plan = s.explain("select a from ft join generate_series(1, 5) gs "
                     "on a = gs.generate_series")
    assert "broadcast" not in plan and "redistribute" not in plan


def test_aggregate_over_function_scan(s):
    out = s.sql("select sum(g.generate_series) as t, count(*) as c "
                "from generate_series(1, 100) g").to_pandas()
    assert out["t"].iloc[0] == 5050 and out["c"].iloc[0] == 100


def test_function_scan_in_subquery(s):
    s.sql("create table fs (a int) distributed by (a)")
    s.sql("insert into fs values (1), (3), (7)")
    df = s.sql("select a from fs where a in "
               "(select generate_series from generate_series(1, 4)) "
               "order by a").to_pandas()
    assert df["a"].tolist() == [1, 3]


def test_custom_table_function(s):
    def colors(n):
        names = np.asarray(["red", "green", "blue"], dtype=object)
        idx = np.arange(int(n)) % 3
        return {"cid": np.arange(int(n), dtype=np.int64),
                "cname": names[idx], "w": np.linspace(0.0, 1.0, int(n))}

    register_table_function("colors", colors)
    df = s.sql("select cid, cname, w from colors(4) "
               "order by cid").to_pandas()
    assert df["cname"].tolist() == ["red", "green", "blue", "red"]
    assert df["w"].iloc[-1] == 1.0
    # strings dictionary-encode: predicates work
    df = s.sql("select count(*) as c from colors(9) "
               "where cname = 'blue'").to_pandas()
    assert df["c"].iloc[0] == 3


def test_rows_refresh_per_statement(s):
    calls = {"n": 0}

    def ticker():
        calls["n"] += 1
        return {"tick": np.arange(calls["n"], dtype=np.int64)}

    register_table_function("ticker", ticker)
    assert len(s.sql("select * from ticker()").to_pandas()) == 1
    # the FDW re-fetch discipline: every referencing statement re-runs
    # the function and sees current rows (no stale cached plan/data)
    assert len(s.sql("select * from ticker()").to_pandas()) == 2


def test_null_args_and_caps(s):
    # strict semantics: a NULL argument yields zero rows, not arg -> 0
    assert len(s.sql("select * from generate_series(null, 3)")
               .to_pandas()) == 0
    with pytest.raises(BindError, match="integer arguments"):
        s.sql("select * from generate_series(1.5, 3.5)")
    with pytest.raises(BindError, match="exceeds the cap"):
        s.sql("select * from generate_series(1, 10000000000)")


def test_transient_tables_bounded(s):
    from cloudberry_tpu.exec import tablefunc

    for i in range(tablefunc.MAX_TRANSIENT_TABLES + 5):
        s.sql(f"select count(*) as c from generate_series(1, {i + 200})")
    tfs = [n for n in s.catalog.tables if n.startswith("$tf_")]
    assert len(tfs) <= tablefunc.MAX_TRANSIENT_TABLES


def test_reuse_refreshes_eviction_order(s):
    """At the pool limit, a statement binding TWO function scans must not
    evict the first one's (just reused) table while materializing the
    second."""
    from cloudberry_tpu.exec import tablefunc

    for i in range(tablefunc.MAX_TRANSIENT_TABLES + 2):
        s.sql(f"select count(*) as c from generate_series(1, {i + 900})")
    # generate_series(1, 901) is now the FIFO-oldest survivor; reuse it
    # alongside a fresh materialization in one statement
    df = s.sql("select count(*) as c from generate_series(1, 901) a "
               "join generate_series(1, 12345) b "
               "on a.generate_series = b.generate_series").to_pandas()
    assert df["c"].iloc[0] == 901


def test_statement_pins_survive_pool_pressure(s, monkeypatch):
    """One statement binding several function scans while the pool is
    tiny must keep EVERY table it materialized alive through the bind —
    FIFO pressure may only evict other statements' leftovers."""
    from cloudberry_tpu.exec import tablefunc

    monkeypatch.setattr(tablefunc, "MAX_TRANSIENT_TABLES", 3)
    for i in range(5):  # fill the pool with stale transients
        s.sql(f"select count(*) as c from generate_series(1, {i + 50})")
    df = s.sql(
        "select count(*) as c from generate_series(1, 7) a "
        "join generate_series(1, 11) b on a.generate_series = "
        "b.generate_series join generate_series(1, 5) c "
        "on a.generate_series = c.generate_series").to_pandas()
    assert df["c"].iloc[0] == 5
    # but a single statement needing MORE than the whole pool reports
    # the pool, not a dangling catalog entry
    monkeypatch.setattr(tablefunc, "MAX_TRANSIENT_TABLES", 2)
    with pytest.raises(BindError, match="transient-table pool"):
        s.sql("select count(*) as c from generate_series(1, 21) a "
              "join generate_series(1, 22) b on a.generate_series = "
              "b.generate_series join generate_series(1, 23) c "
              "on a.generate_series = c.generate_series")


def test_errors(s):
    with pytest.raises(BindError, match="unknown table function"):
        s.sql("select * from no_such_fn(1)")
    # a column reference cannot resolve inside the function's argument
    # scope; an embedded subquery binds but is not a constant
    with pytest.raises(BindError, match="unknown column"):
        s.sql("select * from generate_series(1, a) "
              "join ft on 1 = 1")
    with pytest.raises(BindError, match="must be constants"):
        s.sql("select * from generate_series(1, (select 3))")
    with pytest.raises(BindError, match="step must not be zero"):
        s.sql("select * from generate_series(1, 5, 0)")
