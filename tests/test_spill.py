"""Tiled out-of-core execution (exec/tiled.py) — the workfile-manager /
spill analog (workfile_mgr.c, nodeHash.c batch discipline).

The contract under test: a statement whose plan-time memory estimate
exceeds ``resource.query_mem_bytes`` still completes — streamed in tiles
whose admitted per-step estimate stays inside the budget — and produces
exactly the same result as the all-in-memory path."""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config

JOIN_GROUP_Q = ("SELECT g, sum(v) AS sv, count(*) AS c "
                "FROM fact JOIN dim ON fact.k = dim.k "
                "GROUP BY g ORDER BY g")


def _load(session, n_fact=200_000, n_dim=500, seed=3):
    rng = np.random.default_rng(seed)
    session.sql("CREATE TABLE dim (k BIGINT, g BIGINT) DISTRIBUTED BY (k)")
    session.sql("CREATE TABLE fact (k BIGINT, v BIGINT) DISTRIBUTED BY (k)")
    session.catalog.table("dim").set_data(
        {"k": np.arange(n_dim), "g": np.arange(n_dim) % 9})
    session.catalog.table("fact").set_data(
        {"k": rng.integers(0, n_dim, n_fact),
         "v": rng.integers(0, 100, n_fact)})


def _mk(budget=None, **extra):
    ov = {"n_segments": 1}
    if budget is not None:
        ov["resource.query_mem_bytes"] = budget
    ov.update(extra)
    s = cb.Session(get_config().with_overrides(**ov))
    return s


@pytest.fixture(scope="module")
def expected():
    s = _mk()
    _load(s)
    return s.sql(JOIN_GROUP_Q).to_pandas()


def test_tiled_join_group_matches_in_memory(expected):
    s = _mk(budget=4 << 20)
    _load(s)
    got = s.sql(JOIN_GROUP_Q).to_pandas()
    assert expected.equals(got)
    rep = s.last_tiled_report
    assert rep["tiled"] and rep["n_tiles"] > 1
    assert rep["stream_table"] == "fact"
    # the admitted per-step estimate IS the peak bound: it must respect
    # the budget the admission gate enforced
    assert rep["est_step_bytes"] <= rep["budget_bytes"] == 4 << 20


def test_tiled_statement_cache_reuses_runner(expected):
    s = _mk(budget=4 << 20)
    _load(s)
    got1 = s.sql(JOIN_GROUP_Q).to_pandas()
    got2 = s.sql(JOIN_GROUP_Q).to_pandas()
    assert expected.equals(got1) and expected.equals(got2)


def test_spill_disabled_refuses():
    from cloudberry_tpu.exec.resource import ResourceError

    s = _mk(budget=4 << 20, **{"resource.enable_spill": False})
    _load(s)
    with pytest.raises(ResourceError, match="memory estimate"):
        s.sql(JOIN_GROUP_Q)


def test_tiled_global_agg(expected):
    q = ("SELECT sum(v) AS sv, min(v) AS mn, max(v) AS mx, "
         "count(*) AS c, avg(v) AS av FROM fact")
    big = _mk()
    _load(big)
    exp = big.sql(q).to_pandas()
    s = _mk(budget=1 << 20)
    _load(s)
    got = s.sql(q).to_pandas()
    assert s.last_tiled_report["n_tiles"] > 1
    for c in exp.columns:
        np.testing.assert_allclose(got[c].to_numpy().astype(float),
                                   exp[c].to_numpy().astype(float))


def test_merge_overflow_grows_accumulator():
    """An under-estimated group count grows the accumulator and retries
    (the increase-nbatch discipline) instead of truncating groups."""
    s = _mk(budget=4 << 20)
    _load(s, n_fact=200_000, n_dim=10_000)
    # expression group key: NDV unknown -> sqrt estimate (~450), but the
    # true group count is 7k — forces at least one growth round
    q = ("SELECT k % 7000 AS kk, count(*) AS c, sum(v) AS sv "
         "FROM fact GROUP BY k % 7000 ORDER BY kk LIMIT 50")
    big = _mk()
    _load(big, n_fact=200_000, n_dim=10_000)
    exp = big.sql(q).to_pandas()
    got = s.sql(q).to_pandas()
    assert exp.equals(got)
    assert s.last_tiled_report["acc_capacity"] >= 7000


def test_tiled_spine_expansion_join():
    """A many-to-many (expansion) join ON the tiled spine: per-tile pair
    buffers are floored by the tile-scaled NDV estimate, and the adaptive
    loop (grow buffer / halve tile) absorbs whatever the floor missed."""
    def load2(s):
        rng = np.random.default_rng(5)
        s.sql("CREATE TABLE dup (k BIGINT, g BIGINT) DISTRIBUTED BY (k)")
        s.sql("CREATE TABLE fact (k BIGINT, v BIGINT) DISTRIBUTED BY (k)")
        # 20 duplicate rows per key: every probe row matches 20 partners
        keys = np.repeat(np.arange(100), 20)
        s.catalog.table("dup").set_data({"k": keys, "g": keys % 7})
        s.catalog.table("fact").set_data(
            {"k": rng.integers(0, 100, 150_000),
             "v": rng.integers(0, 50, 150_000)})

    q = ("SELECT g, count(*) AS c, sum(v) AS sv "
         "FROM fact JOIN dup ON fact.k = dup.k GROUP BY g ORDER BY g")
    big = _mk()
    load2(big)
    exp = big.sql(q).to_pandas()
    s = _mk(budget=8 << 20)
    load2(s)
    got = s.sql(q).to_pandas()
    assert exp.equals(got)
    rep = s.last_tiled_report
    assert rep["n_tiles"] > 1
    assert rep["est_step_bytes"] <= rep["budget_bytes"]


def test_tiled_streams_cold_storage(tmp_path):
    """Cold tables stream tile-by-tile from micro-partition files: the
    device (and the tile feed) never materializes the whole table."""
    root = str(tmp_path / "store")
    cfg = get_config().with_overrides(
        n_segments=1, **{"storage.root": root,
                         "storage.rows_per_partition": 25_000})
    s = cb.Session(cfg)
    _load(s, n_fact=150_000)
    exp = s.sql(JOIN_GROUP_Q).to_pandas()

    cfg2 = get_config().with_overrides(
        n_segments=1, **{"storage.root": root,
                         "resource.query_mem_bytes": 3 << 20})
    s2 = cb.Session(cfg2)
    fact = s2.catalog.table("fact")
    assert fact.cold
    got = s2.sql(JOIN_GROUP_Q).to_pandas()
    assert exp.equals(got)
    rep = s2.last_tiled_report
    assert rep["n_tiles"] > 1
    # the stream table must still be cold: the tile feed read partition
    # files, never session RAM
    assert s2.catalog.table("fact").cold


TOPN_Q = ("SELECT fact.k AS k, v, g FROM fact JOIN dim ON fact.k = dim.k "
          "WHERE v < 90 ORDER BY v, fact.k, g LIMIT 25")


def test_tiled_topn_matches_in_memory():
    """ORDER BY + LIMIT over a join spine with no aggregation: streams
    through a bounded top-N accumulator (nodeSort.c bounded-heap role)."""
    big = _mk()
    _load(big)
    exp = big.sql(TOPN_Q).to_pandas()
    assert big.last_tiled_report is None  # in-memory baseline

    s = _mk(budget=4 << 20)
    _load(s)
    got = s.sql(TOPN_Q).to_pandas()
    assert exp.equals(got)
    rep = s.last_tiled_report
    assert rep["tiled"] and rep["n_tiles"] > 1
    assert rep["mode"] == "topn"
    assert rep["acc_capacity"] == 25
    assert rep["est_step_bytes"] <= rep["budget_bytes"] == 4 << 20


def test_tiled_topn_offset_and_desc():
    big = _mk()
    _load(big)
    q = ("SELECT v, fact.k AS k FROM fact JOIN dim ON fact.k = dim.k "
         "ORDER BY v DESC, fact.k DESC LIMIT 10 OFFSET 7")
    exp = big.sql(q).to_pandas()
    s = _mk(budget=4 << 20)
    _load(s)
    got = s.sql(q).to_pandas()
    assert exp.equals(got)
    rep = s.last_tiled_report
    assert rep["mode"] == "topn" and rep["acc_capacity"] == 17


def test_tiled_topn_empty_result():
    s = _mk(budget=4 << 20)
    _load(s)
    got = s.sql("SELECT v FROM fact JOIN dim ON fact.k = dim.k "
                "WHERE v < 0 ORDER BY v LIMIT 5").to_pandas()
    assert len(got) == 0
    assert s.last_tiled_report["mode"] == "topn"


def test_tpch_q5_q9_tiled():
    """VERDICT round-1 done-criterion: TPC-H join-heavy queries complete
    under an artificially small budget with in-budget tiles."""
    from tools.tpch_oracle import ORACLES
    from tools.tpch_queries import QUERIES
    from tools.tpchgen import load_tpch

    big = _mk()
    load_tpch(big, sf=0.02, seed=7)
    tables = {n: t.to_pandas() for n, t in big.catalog.tables.items()}

    s = _mk(budget=10 << 20)
    load_tpch(s, sf=0.02, seed=7)
    for qn in ("q5", "q9"):
        got = s.sql(QUERIES[qn]).to_pandas()
        rep = s.last_tiled_report
        assert rep and rep["n_tiles"] > 1, f"{qn} did not tile"
        assert rep["est_step_bytes"] <= 10 << 20
        exp = ORACLES[qn](tables)
        assert len(got) == len(exp)
        for gc, ec in zip(got.columns, exp.columns):
            g, e = got[gc].to_numpy(), exp[ec].to_numpy()
            if g.dtype.kind == "f" or e.dtype.kind == "f":
                np.testing.assert_allclose(
                    g.astype(np.float64), e.astype(np.float64),
                    rtol=1e-9, atol=1e-2, err_msg=f"{qn}.{gc}")
            else:
                np.testing.assert_array_equal(g, e, err_msg=f"{qn}.{gc}")
