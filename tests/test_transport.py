"""Swappable motion transports (parallel/transport.py) — ic_modules.c
vtable analog: the ring (ppermute-composed) backend must be bit-identical
to XLA's native collectives, on primitives and through whole queries."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config
from cloudberry_tpu.parallel.mesh import SEG_AXIS, segment_mesh
from cloudberry_tpu.parallel.transport import make_transport


def _run_collective(fn, nseg=8, rows=16):
    from cloudberry_tpu.exec.dist_executor import _shard_map
    from jax.sharding import PartitionSpec as P

    mesh = segment_mesh(nseg)
    x = np.arange(nseg * rows, dtype=np.int64).reshape(nseg, rows)
    f = jax.jit(_shard_map(fn, mesh, (P(SEG_AXIS, None),), P(SEG_AXIS)))
    return np.asarray(f(x))


@pytest.mark.parametrize("prim", ["all_gather", "psum", "all_to_all"])
def test_ring_matches_xla(prim):
    nseg, rows = 8, 16
    outs = {}
    for name in ("xla", "ring"):
        tx = make_transport(name, nseg)

        def fn(x, tx=tx):
            if prim == "all_gather":
                return tx.all_gather(x[0], SEG_AXIS)[None]
            if prim == "psum":
                return tx.psum(x[0], SEG_AXIS)[None]
            blocks = x[0].reshape(nseg, rows // nseg)
            return tx.all_to_all(blocks, SEG_AXIS).reshape(rows)[None]

        outs[name] = _run_collective(fn, nseg, rows)
    np.testing.assert_array_equal(outs["xla"], outs["ring"], err_msg=prim)


def test_query_results_identical_across_backends():
    n = 20_000
    results = {}
    for backend in ("xla", "ring"):
        rng = np.random.default_rng(17)  # same data for both backends
        s = cb.Session(get_config().with_overrides(
            **{"n_segments": 8, "interconnect.backend": backend}))
        s.sql("create table f (k bigint, v bigint) distributed by (k)")
        s.sql("create table d (k bigint, g bigint) distributed by (g)")
        s.catalog.table("f").set_data(
            {"k": rng.integers(0, 500, n), "v": rng.integers(0, 100, n)})
        s.catalog.table("d").set_data(
            {"k": np.arange(500), "g": np.arange(500) % 9})
        # the join redistributes, the final agg gathers — both motions
        # ride the selected transport
        results[backend] = s.sql(
            "select g, sum(v) as sv, count(*) as c from f "
            "join d on f.k = d.k group by g order by g").to_pandas()
    assert results["xla"].equals(results["ring"])


def test_unknown_backend_rejected():
    with pytest.raises(ValueError, match="unknown interconnect backend"):
        make_transport("carrier-pigeon", 4)
