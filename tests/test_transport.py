"""Swappable motion transports (parallel/transport.py) — ic_modules.c
vtable analog: the ring (ppermute-composed) backend must be bit-identical
to XLA's native collectives, on primitives and through whole queries."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config
from cloudberry_tpu.parallel.mesh import SEG_AXIS, segment_mesh
from cloudberry_tpu.parallel.transport import make_transport


def _run_collective(fn, nseg=8, rows=16):
    from cloudberry_tpu.exec.dist_executor import _shard_map
    from jax.sharding import PartitionSpec as P

    mesh = segment_mesh(nseg)
    x = np.arange(nseg * rows, dtype=np.int64).reshape(nseg, rows)
    f = jax.jit(_shard_map(fn, mesh, (P(SEG_AXIS, None),), P(SEG_AXIS)))
    return np.asarray(f(x))


@pytest.mark.parametrize("prim", ["all_gather", "psum", "all_to_all"])
def test_ring_matches_xla(prim):
    nseg, rows = 8, 16
    outs = {}
    for name in ("xla", "ring"):
        tx = make_transport(name, nseg)

        def fn(x, tx=tx):
            if prim == "all_gather":
                return tx.all_gather(x[0], SEG_AXIS)[None]
            if prim == "psum":
                return tx.psum(x[0], SEG_AXIS)[None]
            blocks = x[0].reshape(nseg, rows // nseg)
            return tx.all_to_all(blocks, SEG_AXIS).reshape(rows)[None]

        outs[name] = _run_collective(fn, nseg, rows)
    np.testing.assert_array_equal(outs["xla"], outs["ring"], err_msg=prim)


def test_query_results_identical_across_backends():
    n = 20_000
    results = {}
    for backend in ("xla", "ring"):
        rng = np.random.default_rng(17)  # same data for both backends
        s = cb.Session(get_config().with_overrides(
            **{"n_segments": 8, "interconnect.backend": backend}))
        s.sql("create table f (k bigint, v bigint) distributed by (k)")
        s.sql("create table d (k bigint, g bigint) distributed by (g)")
        s.catalog.table("f").set_data(
            {"k": rng.integers(0, 500, n), "v": rng.integers(0, 100, n)})
        s.catalog.table("d").set_data(
            {"k": np.arange(500), "g": np.arange(500) % 9})
        # the join redistributes, the final agg gathers — both motions
        # ride the selected transport
        results[backend] = s.sql(
            "select g, sum(v) as sv, count(*) as c from f "
            "join d on f.k = d.k group by g order by g").to_pandas()
    assert results["xla"].equals(results["ring"])


def test_unknown_backend_rejected():
    with pytest.raises(ValueError, match="unknown interconnect backend"):
        make_transport("carrier-pigeon", 4)


@pytest.mark.parametrize("nseg", [5, 6])
@pytest.mark.parametrize("chunks", [1, 4])
def test_ring_matches_xla_on_packed_buffer_nonpow2(nseg, chunks):
    """ring vs xla all_to_all must be bit-identical on the PACKED wire
    buffer (the shape every motion actually ships now), including
    non-power-of-two segment counts where the rotation distances wrap
    unevenly, and with the chunked (software-pipelined) ring variant."""
    from jax.sharding import PartitionSpec as P

    from cloudberry_tpu.exec.dist_executor import _shard_map

    B, W = 12, 7  # B divisible by 4 engages the chunked row-axis hops
                  # (W=7 matches a real packed layout width)
    rng = np.random.default_rng(nseg * 10 + chunks)
    x = rng.integers(0, 1 << 32, (nseg, nseg * B, W), dtype=np.uint32)
    mesh = segment_mesh(nseg)
    outs = {}
    for name in ("xla", "ring"):
        tx = make_transport(name, nseg, chunks=chunks)

        def fn(v, tx=tx):
            return tx.all_to_all(v[0].reshape(nseg, B, W), SEG_AXIS)\
                .reshape(nseg * B, W)[None]

        f = jax.jit(_shard_map(fn, mesh, (P(SEG_AXIS, None, None),),
                               P(SEG_AXIS)))
        outs[name] = np.asarray(f(x))
    np.testing.assert_array_equal(outs["xla"], outs["ring"])


@pytest.mark.parametrize("nseg", [6])
def test_packed_wire_roundtrip_through_both_transports(nseg):
    """pack → all_to_all → unpack restores every dtype bit-identically on
    BOTH transports (the packed analog of the unpacked cross-checks
    above, at a non-power-of-two segment count)."""
    from jax.sharding import PartitionSpec as P

    from cloudberry_tpu.exec import kernels as K
    from cloudberry_tpu.exec.dist_executor import _shard_map

    B = 8
    rng = np.random.default_rng(3)
    cols = {
        "i64": rng.integers(-1 << 62, 1 << 62, (nseg, nseg * B)),
        "f64": rng.standard_normal((nseg, nseg * B)),
        "i32": rng.integers(-1 << 31, 1 << 31, (nseg, nseg * B),
                            dtype=np.int64).astype(np.int32),
        "flag": rng.integers(0, 2, (nseg, nseg * B)).astype(np.bool_),
    }
    sel = rng.integers(0, 2, (nseg, nseg * B)).astype(np.bool_)
    lay = K.wire_layout({k: jnp.asarray(v[0]).dtype
                         for k, v in cols.items()})
    mesh = segment_mesh(nseg)
    outs = {}
    for name in ("xla", "ring"):
        tx = make_transport(name, nseg, chunks=2)

        def fn(x, tx=tx):
            c = {k: v[0] for k, v in x.items() if k != "$sel"}
            buf = K.pack_wire(c, x["$sel"][0], lay)
            recv = tx.all_to_all(buf.reshape(nseg, B, lay.width),
                                 SEG_AXIS)
            oc, osel = K.unpack_wire(
                recv.reshape(nseg * B, lay.width), lay)
            return ({k: v[None] for k, v in oc.items()}, osel[None])

        f = jax.jit(_shard_map(
            fn, mesh,
            ({**{k: P(SEG_AXIS, None) for k in cols},
              "$sel": P(SEG_AXIS, None)},),
            (P(SEG_AXIS), P(SEG_AXIS))))
        oc, osel = f({**cols, "$sel": sel})
        outs[name] = ({k: np.asarray(v) for k, v in oc.items()},
                      np.asarray(osel))
    xc, xs = outs["xla"]
    rc, rs = outs["ring"]
    np.testing.assert_array_equal(xs, rs)
    for k in xc:
        a, b = xc[k], rc[k]
        assert a.dtype == b.dtype
        w = np.uint8 if a.dtype == np.bool_ else f"u{a.dtype.itemsize}"
        np.testing.assert_array_equal(a.view(w), b.view(w), err_msg=k)
    # and the transport round-trip really restored the sent rows: each
    # received block equals the block the sender addressed to it
    exp = xc["i64"].reshape(nseg, nseg, B)
    for d in range(nseg):
        for src in range(nseg):
            np.testing.assert_array_equal(
                exp[d, src], cols["i64"].reshape(nseg, nseg, B)[src, d])
