"""TPC-DS round-5 families vs pandas oracles — single and 8-segment.

These force the surface added this round: mixed distinct aggregates
with EXISTS/NOT EXISTS fulfillment checks (q16/q94), INTERSECT count
(q38), CASE day-of-week pivots (q43/q59), cross-channel CTE unions
with IN-subqueries (q33/q56/q60), four-instance CTE self-join with
guarded ratios (q74), DQA inside scalar subqueries (q90), LEFT-join
actual-sales (q93), FULL-join channel overlap (q97), ship-delay
buckets (q99), correlated-average item filter (q6) and zip/state OR
filters (q15). Adaptations from the official text are noted in
tools/tpcds_queries.py.
"""

import numpy as np
import pandas as pd
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from tools.tpcds_queries import DS_QUERIES
from tools.tpcdsgen import load_tpcds

from tests.test_tpch import assert_frames_match

NEW = ["q6", "q15", "q16", "q33", "q38", "q43", "q56", "q59", "q60",
       "q74", "q90", "q93", "q94", "q97", "q99"]


@pytest.fixture(scope="module", params=[1, 8], ids=["single", "dist8"])
def ds5(request):
    s = cb.Session(Config(n_segments=request.param)) \
        if request.param > 1 else cb.Session()
    load_tpcds(s, scale=0.5, seed=11)
    tables = {n: t.to_pandas() for n, t in s.catalog.tables.items()}
    return s, tables


def oracle_q6(t):
    it = t["item"].copy()
    cat_avg = it.groupby("i_category")["i_current_price"].transform("mean")
    ok_items = it[cat_avg < it.i_current_price / 1.2]
    j = t["store_sales"].merge(t["date_dim"], left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    j = j[(j.d_year == 2000) & (j.d_moy == 5)]
    j = j.merge(ok_items, left_on="ss_item_sk", right_on="i_item_sk")
    j = j.merge(t["customer"], left_on="ss_customer_sk",
                right_on="c_customer_sk")
    j = j.merge(t["customer_address"], left_on="c_current_addr_sk",
                right_on="ca_address_sk")
    g = j.groupby("ca_state", as_index=False).agg(cnt=("ca_state", "size"))
    g = g[g.cnt >= 10].rename(columns={"ca_state": "state"})
    return g.sort_values(["cnt", "state"]).head(100).reset_index(drop=True)


def oracle_q15(t):
    j = t["catalog_sales"].merge(t["customer"],
                                 left_on="cs_bill_customer_sk",
                                 right_on="c_customer_sk")
    j = j.merge(t["customer_address"], left_on="c_current_addr_sk",
                right_on="ca_address_sk")
    j = j.merge(t["date_dim"], left_on="cs_sold_date_sk",
                right_on="d_date_sk")
    j = j[(j.d_year == 2001) & (j.d_moy == 1)]
    m = (j.ca_zip.str[:3].isin(["850", "856", "859", "834"])
         | j.ca_state.isin(["CA", "WA", "GA"])
         | (j.cs_ext_sales_price > 480))
    g = j[m].groupby("ca_zip", as_index=False).agg(
        total=("cs_ext_sales_price", "sum"))
    return g.sort_values("ca_zip").head(100).reset_index(drop=True)


def _fulfill_oracle(t, sales, pfx, returns, rpfx):
    s = t[sales]
    lo = pd.Timestamp("1999-02-01")
    hi = lo + pd.Timedelta(days=60)
    j = s.merge(t["date_dim"], left_on=f"{pfx}_ship_date_sk",
                right_on="d_date_sk")
    j = j[(j.d_date >= lo) & (j.d_date <= hi)]
    multi = s.groupby(f"{pfx}_order_number")[f"{pfx}_warehouse_sk"] \
        .nunique()
    multi_orders = set(multi[multi > 1].index)
    returned = set(t[returns][f"{rpfx}_order_number"])
    j = j[j[f"{pfx}_order_number"].isin(multi_orders)
          & ~j[f"{pfx}_order_number"].isin(returned)]
    return pd.DataFrame({
        "order_count": [j[f"{pfx}_order_number"].nunique()],
        "total_shipping_cost": [j[f"{pfx}_ext_ship_cost"].sum()],
        "total_net_profit": [j[f"{pfx}_net_profit"].sum()]})


def oracle_q16(t):
    return _fulfill_oracle(t, "catalog_sales", "cs",
                           "catalog_returns", "cr")


def oracle_q94(t):
    return _fulfill_oracle(t, "web_sales", "ws", "web_returns", "wr")


def _chan_cust(t, sales, datecol, custcol):
    j = t[sales].merge(t["date_dim"], left_on=datecol,
                       right_on="d_date_sk")
    j = j[j.d_year == 1999]
    j = j.merge(t["customer"], left_on=custcol, right_on="c_customer_sk")
    return j[["c_last_name", "c_first_name", "d_date"]].drop_duplicates()


def oracle_q38(t):
    a = _chan_cust(t, "store_sales", "ss_sold_date_sk", "ss_customer_sk")
    b = _chan_cust(t, "catalog_sales", "cs_sold_date_sk",
                   "cs_bill_customer_sk")
    c = _chan_cust(t, "web_sales", "ws_sold_date_sk",
                   "ws_bill_customer_sk")
    m = a.merge(b).merge(c).drop_duplicates()
    return pd.DataFrame({"cnt": [len(m)]})


_DAYS = [("sun_sales", "Sunday"), ("mon_sales", "Monday"),
         ("tue_sales", "Tuesday"), ("wed_sales", "Wednesday"),
         ("thu_sales", "Thursday"), ("fri_sales", "Friday"),
         ("sat_sales", "Saturday")]


def oracle_q43(t):
    j = t["date_dim"].merge(t["store_sales"], left_on="d_date_sk",
                            right_on="ss_sold_date_sk")
    j = j[j.d_year == 2000]
    j = j.merge(t["store"], left_on="ss_store_sk", right_on="s_store_sk")
    aggs = {out: (j.ss_ext_sales_price.where(j.d_day_name == day))
            for out, day in _DAYS}
    for out, series in aggs.items():
        j[out] = series
    g = j.groupby(["s_store_name", "s_store_id"], as_index=False)[
        [out for out, _ in _DAYS]].sum(min_count=1)
    return g.sort_values(["s_store_name", "s_store_id"]) \
        .head(100).reset_index(drop=True)


def _union_family(t, key, item_mask, year, moy):
    frames = []
    for sales, datecol, itemcol, price in (
            ("store_sales", "ss_sold_date_sk", "ss_item_sk",
             "ss_ext_sales_price"),
            ("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
             "cs_ext_sales_price"),
            ("web_sales", "ws_sold_date_sk", "ws_item_sk",
             "ws_ext_sales_price")):
        it = t["item"]
        keys = set(it[item_mask(it)][key])
        j = t[sales].merge(t["date_dim"], left_on=datecol,
                           right_on="d_date_sk")
        j = j[(j.d_year == year) & (j.d_moy == moy)]
        j = j.merge(t["item"], left_on=itemcol, right_on="i_item_sk")
        j = j[j[key].isin(keys)]
        g = j.groupby(key, as_index=False).agg(
            total_sales=(price, "sum"))
        frames.append(g)
    u = pd.concat(frames, ignore_index=True)
    return u.groupby(key, as_index=False).agg(
        total_sales=("total_sales", "sum"))


def oracle_q33(t):
    g = _union_family(t, "i_manufact_id",
                      lambda it: it.i_category == "Books", 1998, 5)
    return g.sort_values(["total_sales", "i_manufact_id"]) \
        .head(100).reset_index(drop=True)


def oracle_q56(t):
    g = _union_family(t, "i_item_id",
                      lambda it: it.i_class.isin(["alpha", "beta"]),
                      2000, 9)
    return g.sort_values(["total_sales", "i_item_id"]) \
        .head(100).reset_index(drop=True)


def oracle_q60(t):
    g = _union_family(t, "i_item_id",
                      lambda it: it.i_category == "Music", 1999, 9)
    return g[["i_item_id", "total_sales"]] \
        .sort_values(["i_item_id", "total_sales"]) \
        .head(100).reset_index(drop=True)


def oracle_q59(t):
    j = t["store_sales"].merge(t["date_dim"], left_on="ss_sold_date_sk",
                               right_on="d_date_sk")
    for out, day in _DAYS:
        j[out] = j.ss_ext_sales_price.where(j.d_day_name == day)
    wss = j.groupby(["d_week_seq", "ss_store_sk"], as_index=False)[
        ["sun_sales", "mon_sales", "fri_sales", "sat_sales"]] \
        .sum(min_count=1)
    st = t["store"]
    y = wss[(wss.d_week_seq >= 27) & (wss.d_week_seq <= 52)].merge(
        st, left_on="ss_store_sk", right_on="s_store_sk")
    x = wss[(wss.d_week_seq >= 79) & (wss.d_week_seq <= 104)].merge(
        st, left_on="ss_store_sk", right_on="s_store_sk")
    m = y.merge(x, left_on=["s_store_id"], right_on=["s_store_id"],
                suffixes=("1", "2"))
    m = m[m.d_week_seq1 == m.d_week_seq2 - 52]
    out = pd.DataFrame({
        "s_store_name1": m.s_store_name1,
        "s_store_id1": m.s_store_id,
        "d_week_seq1": m.d_week_seq1,
        "sun_r": m.sun_sales1 / m.sun_sales2,
        "mon_r": m.mon_sales1 / m.mon_sales2,
        "fri_r": m.fri_sales1 / m.fri_sales2,
        "sat_r": m.sat_sales1 / m.sat_sales2})
    return out.sort_values(["s_store_name1", "s_store_id1",
                            "d_week_seq1"]).head(100) \
        .reset_index(drop=True)


def oracle_q74(t):
    frames = []
    for sales, datecol, custcol, price, styp in (
            ("store_sales", "ss_sold_date_sk", "ss_customer_sk",
             "ss_ext_sales_price", 1),
            ("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk",
             "ws_ext_sales_price", 2)):
        j = t[sales].merge(t["date_dim"], left_on=datecol,
                           right_on="d_date_sk")
        j = j[j.d_year.isin([1999, 2000])]
        j = j.merge(t["customer"], left_on=custcol,
                    right_on="c_customer_sk")
        g = j.groupby(["c_customer_id", "c_first_name", "c_last_name",
                       "d_year"], as_index=False).agg(
            year_total=(price, "sum"))
        g["sale_type"] = styp
        frames.append(g)
    yt = pd.concat(frames, ignore_index=True).rename(
        columns={"c_customer_id": "customer_id", "d_year": "year_"})

    def pick(styp, year):
        return yt[(yt.sale_type == styp) & (yt.year_ == year)]

    sf, ss2 = pick(1, 1999), pick(1, 2000)
    wf, ws2 = pick(2, 1999), pick(2, 2000)
    m = ss2.merge(sf, on="customer_id", suffixes=("_ss", "_sf"))
    m = m.merge(wf.rename(columns={"year_total": "wf_total"})[
        ["customer_id", "wf_total"]], on="customer_id")
    m = m.merge(ws2.rename(columns={"year_total": "ws_total"})[
        ["customer_id", "ws_total"]], on="customer_id")
    m = m[(m.year_total_sf > 0) & (m.wf_total > 0)]
    m = m[(m.ws_total / m.wf_total) > (m.year_total_ss / m.year_total_sf)]
    out = pd.DataFrame({
        "customer_id": m.customer_id,
        "c_first_name": m.c_first_name_ss,
        "c_last_name": m.c_last_name_ss})
    return out.sort_values(["customer_id", "c_first_name",
                            "c_last_name"]).head(100) \
        .reset_index(drop=True)


def oracle_q90(t):
    j = t["web_sales"].merge(t["time_dim"], left_on="ws_sold_time_sk",
                             right_on="t_time_sk")
    j = j.merge(t["web_page"], left_on="ws_web_page_sk",
                right_on="wp_web_page_sk")
    j = j[(j.wp_char_count >= 2000) & (j.wp_char_count <= 5000)]
    amc = j[(j.t_hour >= 8) & (j.t_hour <= 9)].ws_order_number.nunique()
    pmc = j[(j.t_hour >= 19) & (j.t_hour <= 20)].ws_order_number.nunique()
    return pd.DataFrame({"am_pm_ratio": [amc / pmc]})


def oracle_q93(t):
    j = t["store_sales"].merge(
        t["store_returns"],
        left_on=["ss_item_sk", "ss_ticket_number"],
        right_on=["sr_item_sk", "sr_ticket_number"], how="left")
    act = np.where(j.sr_return_quantity.notna(),
                   (j.ss_quantity - j.sr_return_quantity)
                   * j.ss_ext_sales_price,
                   j.ss_quantity * j.ss_ext_sales_price)
    j["act_sales"] = act
    g = j.groupby("ss_customer_sk", as_index=False).agg(
        sumsales=("act_sales", "sum"))
    return g.sort_values(["sumsales", "ss_customer_sk"]).head(100) \
        .reset_index(drop=True)


def oracle_q97(t):
    def chan(sales, datecol, cust, item):
        j = t[sales].merge(t["date_dim"], left_on=datecol,
                           right_on="d_date_sk")
        j = j[j.d_year == 2000]
        return j[[cust, item]].drop_duplicates().rename(
            columns={cust: "customer_sk", item: "item_sk"})
    a = chan("store_sales", "ss_sold_date_sk", "ss_customer_sk",
             "ss_item_sk")
    b = chan("catalog_sales", "cs_sold_date_sk", "cs_bill_customer_sk",
             "cs_item_sk")
    m = a.merge(b, on=["customer_sk", "item_sk"], how="outer",
                indicator=True)
    return pd.DataFrame({
        "store_only": [(m._merge == "left_only").sum()],
        "catalog_only": [(m._merge == "right_only").sum()],
        "store_and_catalog": [(m._merge == "both").sum()]})


def oracle_q99(t):
    j = t["catalog_sales"].merge(t["warehouse"],
                                 left_on="cs_warehouse_sk",
                                 right_on="w_warehouse_sk")
    d = j.cs_ship_date_sk - j.cs_sold_date_sk
    j["d30"] = (d <= 30).astype(int)
    j["d60"] = ((d > 30) & (d <= 60)).astype(int)
    j["d90"] = ((d > 60) & (d <= 90)).astype(int)
    j["d120"] = ((d > 90) & (d <= 120)).astype(int)
    j["dmore"] = (d > 120).astype(int)
    g = j.groupby("w_warehouse_name", as_index=False)[
        ["d30", "d60", "d90", "d120", "dmore"]].sum()
    return g.sort_values("w_warehouse_name").head(100) \
        .reset_index(drop=True)


ORACLES5 = {"q6": oracle_q6, "q15": oracle_q15, "q16": oracle_q16,
            "q33": oracle_q33, "q38": oracle_q38, "q43": oracle_q43,
            "q56": oracle_q56, "q59": oracle_q59, "q60": oracle_q60,
            "q74": oracle_q74, "q90": oracle_q90, "q93": oracle_q93,
            "q94": oracle_q94, "q97": oracle_q97, "q99": oracle_q99}


@pytest.mark.parametrize("qname", NEW)
def test_tpcds_round5(ds5, qname):
    session, tables = ds5
    got = session.sql(DS_QUERIES[qname]).to_pandas()
    exp = ORACLES5[qname](tables)
    assert len(exp) > 0, "oracle result is vacuous — fix the generator"
    assert_frames_match(got, exp, qname)
