import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from cloudberry_tpu.exec import kernels as K


def _sel(n, cap):
    s = np.zeros(cap, dtype=bool)
    s[:n] = True
    return jnp.asarray(s)


def test_sort_indices_orders_and_pushes_invalid_last():
    cap = 8
    k = jnp.asarray(np.array([5, 1, 3, 2, 9, 0, 0, 0], dtype=np.int64))
    sel = _sel(5, cap)
    perm = K.sort_indices([k], sel)
    got = np.asarray(k[perm][:5])
    np.testing.assert_array_equal(got, [1, 2, 3, 5, 9])
    assert np.asarray(sel[perm])[5:].sum() == 0


def test_sort_descending_and_secondary():
    a = jnp.asarray(np.array([1, 2, 1, 2, 1], dtype=np.int64))
    b = jnp.asarray(np.array([10.0, 20.0, 30.0, 5.0, 20.0]))
    sel = jnp.ones(5, dtype=bool)
    perm = K.sort_indices([a, b], sel, descending=[False, True])
    rows = list(zip(np.asarray(a[perm]).tolist(), np.asarray(b[perm]).tolist()))
    assert rows == [(1, 30.0), (1, 20.0), (1, 10.0), (2, 20.0), (2, 5.0)]


def test_sort_negative_floats():
    v = jnp.asarray(np.array([0.5, -1.5, -0.25, 2.0, -1.5]))
    perm = K.sort_indices([v], jnp.ones(5, dtype=bool))
    got = np.asarray(v[perm])
    np.testing.assert_array_equal(got, np.sort(np.asarray(v)))


@pytest.mark.parametrize("jit", [False, True])
def test_group_aggregate_vs_pandas(jit):
    rng = np.random.default_rng(0)
    n, cap = 900, 1024
    k1 = rng.integers(0, 7, n).astype(np.int64)
    k2 = rng.integers(0, 3, n).astype(np.int32)
    v = rng.normal(size=n)
    df = pd.DataFrame({"k1": k1, "k2": k2, "v": v})
    expect = (
        df.groupby(["k1", "k2"])
        .agg(s=("v", "sum"), c=("v", "size"), mn=("v", "min"), a=("v", "mean"))
        .reset_index()
        .sort_values(["k1", "k2"])
    )

    key_cols = {
        "k1": jnp.asarray(np.pad(k1, (0, cap - n))),
        "k2": jnp.asarray(np.pad(k2, (0, cap - n))),
    }
    vals = jnp.asarray(np.pad(v, (0, cap - n)))
    sel = _sel(n, cap)
    aggs = [K.AggSpec("sum", "s"), K.AggSpec("count", "c"),
            K.AggSpec("min", "mn"), K.AggSpec("avg", "a")]
    agg_values = {"s": vals, "c": None, "mn": vals, "a": vals}

    fn = lambda kc, av, s: K.group_aggregate(kc, av, aggs, s, 64)
    if jit:
        fn = jax.jit(fn)
    out_keys, out_aggs, out_sel, n_groups = fn(key_cols, agg_values, sel)
    assert int(n_groups) == len(expect)

    m = np.asarray(out_sel)
    got = pd.DataFrame({
        "k1": np.asarray(out_keys["k1"])[m],
        "k2": np.asarray(out_keys["k2"])[m],
        "s": np.asarray(out_aggs["s"])[m],
        "c": np.asarray(out_aggs["c"])[m],
        "mn": np.asarray(out_aggs["mn"])[m],
        "a": np.asarray(out_aggs["a"])[m],
    })
    assert len(got) == len(expect)
    np.testing.assert_array_equal(got["k1"], expect["k1"].to_numpy())
    np.testing.assert_array_equal(got["k2"], expect["k2"].to_numpy())
    np.testing.assert_allclose(got["s"], expect["s"].to_numpy(), rtol=1e-12)
    np.testing.assert_array_equal(got["c"], expect["c"].to_numpy())
    np.testing.assert_allclose(got["mn"], expect["mn"].to_numpy(), rtol=1e-12)
    np.testing.assert_allclose(got["a"], expect["a"].to_numpy(), rtol=1e-12)


def test_global_aggregate():
    v = jnp.asarray(np.array([1.0, 2.0, 3.0, 100.0]))
    sel = jnp.asarray(np.array([True, True, True, False]))
    out = K.global_aggregate(
        {"s": v, "c": None, "mx": v},
        [K.AggSpec("sum", "s"), K.AggSpec("count", "c"), K.AggSpec("max", "mx")],
        sel,
    )
    assert float(out["s"][0]) == 6.0
    assert int(out["c"][0]) == 3
    assert float(out["mx"][0]) == 3.0


@pytest.mark.parametrize("jit", [False, True])
def test_join_lookup_pk_fk(jit):
    cap_b, cap_p = 8, 16
    bkey = np.array([10, 20, 30, 40, 0, 0, 0, 0], dtype=np.int64)
    bsel = _sel(4, cap_b)
    pkey = np.array([20, 20, 99, 40, 10, 30, 30, 7] + [0] * 8, dtype=np.int64)
    psel = _sel(8, cap_p)

    fn = K.join_lookup
    if jit:
        fn = jax.jit(fn)
    idx, matched, has_dup = fn([jnp.asarray(bkey)], bsel, [jnp.asarray(pkey)], psel)
    assert not bool(has_dup)
    m = np.asarray(matched)
    np.testing.assert_array_equal(
        m[:8], [True, True, False, True, True, True, True, False])
    picked = np.asarray(idx)[m]
    np.testing.assert_array_equal(bkey[picked], np.asarray(pkey[:8])[m[:8]])


def test_join_lookup_multikey():
    bk1 = np.array([1, 1, 2, 2], dtype=np.int64)
    bk2 = np.array([1, 2, 1, 2], dtype=np.int64)
    bsel = jnp.ones(4, dtype=bool)
    pk1 = np.array([1, 2, 2, 3], dtype=np.int64)
    pk2 = np.array([2, 1, 9, 1], dtype=np.int64)
    psel = jnp.ones(4, dtype=bool)
    idx, matched, _ = K.join_lookup(
        [jnp.asarray(bk1), jnp.asarray(bk2)], bsel,
        [jnp.asarray(pk1), jnp.asarray(pk2)], psel)
    np.testing.assert_array_equal(np.asarray(matched), [True, True, False, False])
    got = np.asarray(idx)[np.asarray(matched)]
    np.testing.assert_array_equal(bk1[got], [1, 2])
    np.testing.assert_array_equal(bk2[got], [2, 1])


def test_join_empty_build():
    bsel = jnp.zeros(4, dtype=bool)
    psel = jnp.ones(4, dtype=bool)
    k = jnp.asarray(np.array([1, 2, 3, 4], dtype=np.int64))
    _, matched, _ = K.join_lookup([k], bsel, [k], psel)
    assert not bool(np.asarray(matched).any())


def test_limit_mask():
    sel = jnp.asarray(np.array([True, False, True, True, True, False, True]))
    out = np.asarray(K.limit_mask(sel, 2, offset=1))
    np.testing.assert_array_equal(
        out, [False, False, True, True, False, False, False])


def test_compact():
    cols = {"x": jnp.asarray(np.array([9, 8, 7, 6], dtype=np.int64))}
    sel = jnp.asarray(np.array([False, True, False, True]))
    out, osel, n = K.compact(cols, sel, 2)
    assert np.asarray(osel).all()
    assert int(n) == 2
    np.testing.assert_array_equal(np.asarray(out["x"]), [8, 6])


def test_compact_overflow_reported():
    cols = {"x": jnp.asarray(np.arange(4, dtype=np.int64))}
    sel = jnp.ones(4, dtype=bool)
    _, _, n = K.compact(cols, sel, 2)
    assert int(n) == 4  # caller sees 4 > capacity 2 and errors


def test_group_overflow_reported():
    cols = {"k": jnp.asarray(np.arange(8, dtype=np.int64))}
    sel = jnp.ones(8, dtype=bool)
    *_, n_groups = K.group_aggregate(cols, {"c": None}, [K.AggSpec("count", "c")], sel, 4)
    assert int(n_groups) == 8  # caller sees 8 > capacity 4 and errors


def test_decimal_int_ingest():
    import pandas as pd
    from cloudberry_tpu.columnar import ColumnBatch
    from cloudberry_tpu.types import Schema, DECIMAL
    b = ColumnBatch.from_arrays({"p": np.array([100, 200], dtype=np.int64)},
                                Schema.of(p=DECIMAL(2)))
    np.testing.assert_array_equal(np.asarray(b.columns["p"]), [10000, 20000])
    assert b.to_pandas()["p"].tolist() == [100.0, 200.0]


def test_join_lookup_32bit_matches_64bit():
    """Stats-proven narrow packing (kernels.downcast32) must be
    bit-identical to the u64 path, including sentinel (no-match) rows."""
    rng = np.random.default_rng(2)
    bk = jnp.asarray(rng.permutation(1000).astype(np.int64))
    bs = jnp.asarray(rng.random(1000) < 0.9)
    pk = jnp.asarray(rng.integers(-50, 1100, 5000).astype(np.int64))
    ps = jnp.asarray(rng.random(5000) < 0.95)
    i64, m64, d64 = K.join_lookup([bk], bs, [pk], ps, bits=64)
    i32, m32, d32 = K.join_lookup([bk], bs, [pk], ps, bits=32)
    np.testing.assert_array_equal(np.asarray(m64), np.asarray(m32))
    np.testing.assert_array_equal(np.asarray(i64)[np.asarray(m64)],
                                  np.asarray(i32)[np.asarray(m32)])
    assert bool(d64) == bool(d32)


def test_join_expand_32bit_matches_64bit():
    rng = np.random.default_rng(3)
    bk = jnp.asarray(rng.integers(0, 200, 1000).astype(np.int64))
    bs = jnp.ones(1000, dtype=bool)
    pk = jnp.asarray(rng.integers(0, 250, 2000).astype(np.int64))
    ps = jnp.asarray(rng.random(2000) < 0.9)
    cap = 16384
    r64 = K.join_expand([bk], bs, [pk], ps, cap, bits=64)
    r32 = K.join_expand([bk], bs, [pk], ps, cap, bits=32)
    for a, b in zip(r64, r32):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pack_bits_annotation_tpch():
    """TPC-H integer-key joins (orderkey/custkey class) must be proven
    32-bit packable from table stats; the plan carries the proof."""
    import cloudberry_tpu as cb
    from cloudberry_tpu.config import get_config
    from cloudberry_tpu.exec.executor import all_nodes
    from cloudberry_tpu.plan import nodes as N
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sql.parser import parse_sql
    from tools.tpch_queries import QUERIES
    from tools.tpchgen import load_tpch

    s = cb.Session(get_config().with_overrides(n_segments=1))
    load_tpch(s, sf=0.01, seed=7)
    plan = plan_statement(parse_sql(QUERIES["q3"]), s, {}).plan
    joins = [n for n in all_nodes(plan) if isinstance(n, N.PJoin)]
    assert joins and all(j.pack_bits == 32 for j in joins), \
        [(j.title(), j.pack_bits) for j in joins]


def test_pack_bits_rejects_float_keys():
    """FLOAT keys pack by IEEE bit pattern (sort_key_u64), where a tiny
    value span covers ~2^52 patterns — the 32-bit proof must refuse them
    (narrowing would alias distinct keys)."""
    import cloudberry_tpu as cb
    from cloudberry_tpu.config import get_config
    from cloudberry_tpu.exec.executor import all_nodes
    from cloudberry_tpu.plan import nodes as N
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sql.parser import parse_sql

    s = cb.Session(get_config().with_overrides(n_segments=1))
    s.sql("CREATE TABLE fb (x DOUBLE, p BIGINT) DISTRIBUTED BY (p)")
    s.sql("CREATE TABLE fp (y DOUBLE, v BIGINT) DISTRIBUTED BY (v)")
    s.catalog.table("fb").set_data(
        {"x": np.array([1.5, 2.5, 3.5]), "p": np.arange(3)})
    s.catalog.table("fp").set_data(
        {"y": np.array([2.5, 3.5, 9.0, 1.5]), "v": np.arange(4)})
    plan = plan_statement(parse_sql(
        "SELECT sum(v) AS sv FROM fp JOIN fb ON fp.y = fb.x"), s, {}).plan
    joins = [n for n in all_nodes(plan) if isinstance(n, N.PJoin)]
    assert joins and all(j.pack_bits == 64 for j in joins)
    # and the join itself must still be correct
    assert s.sql("SELECT count(*) AS c FROM fp JOIN fb ON fp.y = fb.x"
                 ).to_pandas()["c"].tolist() == [3]


def test_sort_key_f64_two_word_path():
    """DOUBLE sort keys build their IEEE total-order u64 from two u32
    bitcast words (the TPU backend compiles no direct f64->u64 bitcast);
    the result must be bit-identical to the direct-view formulation and
    order exactly like SQL ascending floats."""
    import numpy as np

    from cloudberry_tpu.exec.kernels import sort_key_u64

    vals = np.array([0.0, -0.0, 1.5, -1.5, np.inf, -np.inf, 3.14e300,
                     -3.14e300, 5e-324, -5e-324, 123456.789],
                    dtype=np.float64)
    rng = np.random.default_rng(0)
    vals = np.concatenate([vals, rng.standard_normal(500) *
                           (10.0 ** rng.integers(-300, 300, 500)
                            .astype(np.float64))])
    got = np.asarray(jax.jit(sort_key_u64)(jnp.asarray(vals)))
    bits = vals.view(np.uint64)
    mask = np.where(bits >> 63 != 0, np.uint64(0xFFFFFFFFFFFFFFFF),
                    np.uint64(1) << 63)
    assert (got == (bits ^ mask)).all()
    assert (vals[np.argsort(vals, kind="stable")]
            == vals[np.argsort(got, kind="stable")]).all()


def test_double_order_by_end_to_end():
    """ORDER BY over a genuine DOUBLE column (the round-4 verdict's
    platform caveat: this must not depend on a CPU-only bitcast)."""
    import cloudberry_tpu as cb
    from cloudberry_tpu.config import Config

    s = cb.Session(Config(n_segments=8))
    s.sql("create table fd (k bigint, x double) distributed by (k)")
    s.sql("insert into fd values (1, 2.5), (2, -1.5), (3, 1e300), "
          "(4, -1e300), (5, 0.0), (6, 3.25), (7, null)")
    df = s.sql("select k from fd order by x").to_pandas()
    assert list(df["k"]) == [4, 2, 5, 1, 6, 3, 7]  # NULLs last


def test_join_expand_total_exact_past_2_16():
    """Regression: the pair-count cumsum and capacity comparison must run
    in int64 regardless of searchsorted's narrow index dtype — a fanout
    past 2^16 pairs must report its EXACT total (a wrapped count would
    defeat the overflow check itself)."""
    nb, np_ = 300, 300  # 90000 pairs > 2^16
    bk = [jnp.zeros(nb, dtype=jnp.int64)]
    pk = [jnp.zeros(np_, dtype=jnp.int64)]
    cap = 1 << 17
    pi, bi, osel, matched, total = K.join_expand(
        bk, jnp.ones(nb, dtype=bool), pk, jnp.ones(np_, dtype=bool), cap)
    assert total.dtype == jnp.int64
    assert int(total) == nb * np_
    assert int(np.asarray(osel).sum()) == nb * np_
    assert bool(np.asarray(matched).all())
    # each probe row pairs with every build row exactly once
    counts = np.bincount(np.asarray(pi)[np.asarray(osel)], minlength=np_)
    np.testing.assert_array_equal(counts, np.full(np_, nb))


def test_join_lookup_presorted_parity():
    """join_lookup fed a HOST-precomputed index (the join-index cache's
    numpy mirror) must be bit-identical to the in-program argsort path —
    order, matches, dup flag, at 64 and 32 bits."""
    from cloudberry_tpu.exec.joinindex import _np_index

    rng = np.random.default_rng(5)
    nb, np_ = 512, 1024
    bvals = rng.permutation(1 << 12)[:nb].astype(np.int64)
    pvals = rng.integers(0, 1 << 13, np_).astype(np.int64)
    n_build = 400  # tail rows unselected
    bsel = _sel(n_build, nb)
    psel = _sel(900, np_)
    for bits in (64, 32):
        idx0, m0, dup0 = K.join_lookup([jnp.asarray(bvals)], bsel,
                                       [jnp.asarray(pvals)], psel,
                                       bits=bits)
        jix = _np_index([bvals], n_build, nb, bits)
        ranges = [(jnp.asarray(jix["lo0"]), jnp.asarray(jix["span0"]))]
        idx1, m1, dup1 = K.join_lookup_sorted(
            jnp.asarray(jix["order"]), jnp.asarray(jix["skeys"]), ranges,
            [jnp.asarray(pvals)], psel, bits=bits)
        np.testing.assert_array_equal(np.asarray(m0), np.asarray(m1))
        np.testing.assert_array_equal(np.asarray(idx0)[np.asarray(m0)],
                                      np.asarray(idx1)[np.asarray(m1)])
        assert bool(dup0) == bool(dup1) == False  # noqa: E712


def test_join_expand_presorted_parity():
    """join_expand through a host-precomputed index: identical pair sets
    AND identical output order (stable ties mirror np argsort)."""
    from cloudberry_tpu.exec.joinindex import _np_index

    rng = np.random.default_rng(6)
    nb, np_ = 256, 512
    bvals = rng.integers(0, 64, nb).astype(np.int64)  # heavy dups
    pvals = rng.integers(0, 96, np_).astype(np.int64)
    n_build = 200
    bsel = _sel(n_build, nb)
    psel = _sel(480, np_)
    cap = 1 << 13
    r0 = K.join_expand([jnp.asarray(bvals)], bsel,
                       [jnp.asarray(pvals)], psel, cap)
    jix = _np_index([bvals], n_build, nb, 64)
    ranges = [(jnp.asarray(jix["lo0"]), jnp.asarray(jix["span0"]))]
    r1 = K.join_expand_sorted(jnp.asarray(jix["order"]),
                              jnp.asarray(jix["skeys"]), ranges,
                              [jnp.asarray(pvals)], psel, cap)
    for a, b in zip(r0, r1):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
