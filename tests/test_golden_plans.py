"""Plan-shape regression tests (the ORCA minidump-replay analog).

Every TPC-H and TPC-DS query's optimized plan — join order, motion
placement, capacities, share nodes, the ``dist:`` derived-distribution
annotations — must match its committed snapshot in tests/golden/. A
legitimate planner change regenerates them with
`python -m tools.golden_plans` and the diff is reviewed like any code.

The sessions here run with ``config.debug.verify_plans`` ON: every
plan is additionally checked by the planck verifier (plan/verify.py)
while planning, so a plan whose derived distribution properties no
longer match its stamps fails with a node-path diagnostic even before
the text comparison — a corrupted golden corpus is a loud failure, not
a silent replan.
"""

import os

import pytest

from tools.golden_plans import (GOLDEN_DIR, corpus, plan_text,
                                snapshot_name)
from tools.tpcds_queries import DS_QUERIES
from tools.tpch_queries import QUERIES

_SESSIONS = {}
_FACTORIES = {suite: factory for suite, factory, _ in corpus()}


def _session(suite, nseg):
    key = (suite, nseg)
    if key not in _SESSIONS:
        _SESSIONS[key] = _FACTORIES[suite](nseg)
    return _SESSIONS[key]


def _check(suite, queries, qname, nseg):
    path = os.path.join(GOLDEN_DIR, snapshot_name(qname, nseg, suite))
    assert os.path.exists(path), \
        f"missing golden plan {path}; run python -m tools.golden_plans"
    with open(path) as fh:
        expected = fh.read()
    # plan_text verifies (debug.verify_plans session) AND snapshots
    got = plan_text(_session(suite, nseg), queries[qname])
    assert got == expected, (
        f"plan shape changed for {suite} {qname} (nseg={nseg}).\n"
        f"--- expected ---\n{expected}\n--- got ---\n{got}\n"
        "If intentional, regenerate: python -m tools.golden_plans")


@pytest.mark.parametrize("nseg", [1, 8], ids=["single", "dist8"])
@pytest.mark.parametrize("qname", sorted(QUERIES))
def test_plan_shape(qname, nseg):
    _check("tpch", QUERIES, qname, nseg)


@pytest.mark.parametrize("nseg", [1, 8], ids=["single", "dist8"])
@pytest.mark.parametrize("qname", sorted(DS_QUERIES))
def test_ds_plan_shape(qname, nseg):
    _check("tpcds", DS_QUERIES, qname, nseg)


def test_golden_corpus_has_no_strays():
    """Every committed .plan file corresponds to a live corpus entry —
    a renamed query must not leave a stale snapshot that silently
    stops being compared."""
    want = {snapshot_name(q, nseg) for q in QUERIES for nseg in (1, 8)}
    want |= {snapshot_name(q, nseg, "tpcds")
             for q in DS_QUERIES for nseg in (1, 8)}
    have = {f for f in os.listdir(GOLDEN_DIR) if f.endswith(".plan")}
    assert have == want, (
        f"stale: {sorted(have - want)}; missing: {sorted(want - have)}")
