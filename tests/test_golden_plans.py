"""Plan-shape regression tests (the ORCA minidump-replay analog).

Every TPC-H query's optimized plan — join order, motion placement,
capacities, share nodes — must match its committed snapshot in
tests/golden/. A legitimate planner change regenerates them with
`python -m tools.golden_plans` and the diff is reviewed like any code.
"""

import os

import pytest

from tools.golden_plans import (GOLDEN_DIR, make_session, plan_text,
                                snapshot_name)
from tools.tpch_queries import QUERIES

_SESSIONS = {}


def _session(nseg):
    if nseg not in _SESSIONS:
        _SESSIONS[nseg] = make_session(nseg)
    return _SESSIONS[nseg]


@pytest.mark.parametrize("nseg", [1, 8], ids=["single", "dist8"])
@pytest.mark.parametrize("qname", sorted(QUERIES))
def test_plan_shape(qname, nseg):
    path = os.path.join(GOLDEN_DIR, snapshot_name(qname, nseg))
    assert os.path.exists(path), \
        f"missing golden plan {path}; run python -m tools.golden_plans"
    with open(path) as fh:
        expected = fh.read()
    got = plan_text(_session(nseg), QUERIES[qname])
    assert got == expected, (
        f"plan shape changed for {qname} (nseg={nseg}).\n"
        f"--- expected ---\n{expected}\n--- got ---\n{got}\n"
        "If intentional, regenerate: python -m tools.golden_plans")
