"""Parameterization correctness over TPC-H (ISSUE-3 satellite): every
query must produce BIT-IDENTICAL results through the parameterized
(generic-plan) path vs the literal-folded path, at 1 and 8 segments.

Tier-1 runs a representative subset (scan+agg, join, filter-heavy, CASE)
plus perturbed-literal rebinds; the full both-segment sweep over every
TPC-H query rides the ``slow`` tier (tier-1 wall-clock is capped)."""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import Config
from tools.tpch_queries import QUERIES
from tools.tpchgen import load_tpch

SUBSET = ["q1", "q3", "q6", "q14"]

# literal perturbations that keep each query valid — the REBIND path:
# same skeleton, different parameter vector
PERTURB = {
    "q1": ("'1998-12-01'", "'1998-11-15'"),
    "q3": ("'1995-03-15'", "'1995-03-01'"),
    "q6": ("24", "30"),
    "q14": ("'1995-09-01'", "'1995-06-01'"),
}


def _pair(nseg):
    on = cb.Session(Config(n_segments=nseg))
    off = cb.Session(Config(n_segments=nseg).with_overrides(
        **{"sched.generic_plans": False}))
    for s in (on, off):
        load_tpch(s, sf=0.01, seed=7)
    return on, off


@pytest.fixture(scope="module")
def pair1():
    return _pair(1)


@pytest.fixture(scope="module")
def pair8():
    return _pair(8)


def assert_bit_identical(got, want, name):
    gsel, wsel = np.asarray(got.sel), np.asarray(want.sel)
    assert int(gsel.sum()) == int(wsel.sum()), name
    gcols = got.decoded_columns()
    wcols = want.decoded_columns()
    assert list(gcols) == list(wcols), name
    for cname in gcols:
        g, w = np.asarray(gcols[cname]), np.asarray(wcols[cname])
        if g.dtype == object or w.dtype == object:
            np.testing.assert_array_equal(g, w, err_msg=f"{name}.{cname}")
        else:
            # bit-identical, floats included: the generic program runs
            # the SAME ops with literals as inputs instead of constants
            np.testing.assert_array_equal(
                g.view(np.uint8) if g.dtype.kind == "f" else g,
                w.view(np.uint8) if w.dtype.kind == "f" else w,
                err_msg=f"{name}.{cname}")


def _run_pair(on, off, qname, sql=None):
    sql = sql or QUERIES[qname]
    got = on.sql(sql)
    want = off.sql(sql)
    assert_bit_identical(got, want, qname)


@pytest.mark.parametrize("qname", SUBSET)
def test_subset_parity_single(pair1, qname):
    on, off = pair1
    _run_pair(on, off, qname)
    # rebind with a perturbed literal: zero recompiles AND bit-identity
    old, new = PERTURB[qname]
    assert old in QUERIES[qname]
    c0 = on.stmt_log.counter("compiles")
    _run_pair(on, off, qname + "-rebind",
              QUERIES[qname].replace(old, new))
    assert on.stmt_log.counter("compiles") == c0, \
        f"{qname}: perturbed literal recompiled"


@pytest.mark.parametrize("qname", ["q3", "q6"])
def test_subset_parity_dist8(pair8, qname):
    on, off = pair8
    _run_pair(on, off, qname)
    old, new = PERTURB[qname]
    c0 = on.stmt_log.counter("compiles")
    _run_pair(on, off, qname + "-rebind",
              QUERIES[qname].replace(old, new))
    assert on.stmt_log.counter("compiles") == c0


@pytest.mark.slow
@pytest.mark.parametrize("nseg", [1, 8])
@pytest.mark.parametrize("qname", sorted(QUERIES))
def test_full_parity_sweep(qname, nseg, request):
    """Every TPC-H query, both segment counts: parameterized vs
    literal-folded, bit-identical (the full satellite sweep; slow tier)."""
    key = f"_parity_pair_{nseg}"
    pair = getattr(request.session, key, None)  # reuse across params
    if pair is None:
        pair = _pair(nseg)
        setattr(request.session, key, pair)
    on, off = pair
    _run_pair(on, off, f"{qname}@{nseg}", QUERIES[qname])
