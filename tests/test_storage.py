import numpy as np
import pytest

from cloudberry_tpu import types as T
from cloudberry_tpu.columnar.dictionary import StringDictionary
from cloudberry_tpu.storage import micropartition as mp
from cloudberry_tpu.storage.table_store import TableStore
from cloudberry_tpu.types import Schema


@pytest.fixture
def schema():
    return Schema.of(k=T.INT64, v=T.DECIMAL(2), s=T.STRING, d=T.DATE)


def _data(n, seed=0):
    rng = np.random.default_rng(seed)
    d = StringDictionary()
    return {
        "k": np.arange(n, dtype=np.int64),
        "v": rng.integers(0, 10_000, n).astype(np.int64),
        "s": d.encode(rng.choice(["aa", "bb", "cc"], n)),
        "d": rng.integers(8000, 9000, n).astype(np.int32),
    }, {"s": d}


def test_micropartition_roundtrip(tmp_path, schema):
    data, dicts = _data(1000)
    path = str(tmp_path / "p1.cbmp")
    footer = mp.write_micropartition(path, data, schema, dicts)
    assert footer["num_rows"] == 1000
    got = mp.read_columns(path)
    for k in data:
        np.testing.assert_array_equal(got[k], data[k])
    # column projection reads only what's asked
    got_k = mp.read_columns(path, ["k"])
    assert set(got_k) == {"k"}
    # stats present and correct
    f2 = mp.read_footer(path)
    kcol = next(c for c in f2["columns"] if c["name"] == "k")
    assert kcol["min"] == 0 and kcol["max"] == 999
    scol = next(c for c in f2["columns"] if c["name"] == "s")
    assert scol["dictionary"] == dicts["s"].values


def test_rle_kicks_in(tmp_path, schema):
    data, dicts = _data(10_000)
    data["v"] = np.full(10_000, 777, dtype=np.int64)  # constant → RLE
    path = str(tmp_path / "p2.cbmp")
    footer = mp.write_micropartition(path, data, schema, dicts)
    vcol = next(c for c in footer["columns"] if c["name"] == "v")
    assert vcol["encoding"] == "rle"
    assert vcol["length"] < 200
    got = mp.read_columns(path, ["v"])
    assert (got["v"] == 777).all()


def test_prune_by_stats(tmp_path, schema):
    data, dicts = _data(100)
    path = str(tmp_path / "p3.cbmp")
    mp.write_micropartition(path, data, schema, dicts)
    f = mp.read_footer(path)
    assert mp.prune_by_stats(f, "k", lo=50, hi=60)
    assert not mp.prune_by_stats(f, "k", lo=1000, hi=None)
    assert not mp.prune_by_stats(f, "k", lo=None, hi=-1)
    assert mp.prune_by_stats(f, "nosuchcol", lo=0, hi=0)


def test_corrupt_file_detected(tmp_path, schema):
    data, dicts = _data(10)
    path = str(tmp_path / "p4.cbmp")
    mp.write_micropartition(path, data, schema, dicts)
    with open(path, "r+b") as fh:
        fh.seek(0)
        fh.write(b"XXXXXXXX")
    with pytest.raises(ValueError):
        mp.read_footer(path)


def test_store_append_scan_snapshot(tmp_path, schema):
    store = TableStore(str(tmp_path))
    d1, dicts = _data(500)
    v1 = store.append("t", d1, schema, dicts, rows_per_partition=200)
    assert v1 == 1
    cols, sch, dd = store.scan("t")
    assert len(cols["k"]) == 500
    assert sch.names == schema.names
    assert dd["s"].values == dicts["s"].values

    d2, _ = _data(300, seed=1)
    d2["s"] = dicts["s"].encode(np.asarray(["aa"] * 300))
    d2["k"] = d2["k"] + 10_000
    v2 = store.append("t", d2, schema, dicts)
    assert v2 == 2
    cols2, _, _ = store.scan("t")
    assert len(cols2["k"]) == 800
    # time travel: old snapshot still sees 500 rows
    old, _, _ = store.scan("t", version=1)
    assert len(old["k"]) == 500


def test_store_prune_and_delete(tmp_path, schema):
    store = TableStore(str(tmp_path))
    d1, dicts = _data(1000)
    store.append("t", d1, schema, dicts, rows_per_partition=100)
    # prune: only partitions overlapping k in [250, 260] are read
    cols, _, _ = store.scan("t", prune={"k": (250, 260)})
    assert len(cols["k"]) == 100  # exactly one 100-row partition survives
    assert 250 in cols["k"] and 260 in cols["k"]

    # delete-vector semantics (visimap analog)
    store.delete_rows("t", lambda c: c["k"] % 2 == 0)
    cols2, _, _ = store.scan("t")
    assert len(cols2["k"]) == 500
    assert (cols2["k"] % 2 == 1).all()
    # old snapshot unaffected (snapshot isolation)
    cols3, _, _ = store.scan("t", version=1)
    assert len(cols3["k"]) == 1000


def test_session_persistence_roundtrip(tmp_path):
    import cloudberry_tpu as cb

    s = cb.Session()
    s.sql("create table m (a bigint, b decimal(10,2), c text) distributed by (a)")
    s.sql("insert into m values (1, 1.5, 'x'), (2, 2.5, 'y'), (3, 3.5, 'x')")
    store = TableStore(str(tmp_path))
    store.save_table(s.catalog.table("m"))

    s2 = cb.Session()
    store.load_table(s2.catalog, "m")
    df = s2.sql("select c, sum(b) as t from m group by c order by c").to_pandas()
    assert df["c"].tolist() == ["x", "y"]
    assert df["t"].tolist() == [5.0, 2.5]


def test_append_dict_must_extend(tmp_path, schema):
    store = TableStore(str(tmp_path))
    d1, dicts = _data(50)
    store.append("t", d1, schema, dicts)
    bad = StringDictionary(["zz"])  # not an extension
    d2, _ = _data(50, seed=2)
    d2["s"] = np.zeros(50, dtype=np.int32)
    with pytest.raises(ValueError):
        store.append("t", d2, schema, {"s": bad})
    # extension is fine
    ext = StringDictionary(dicts["s"].values + ["dd"])
    d2["s"] = np.full(50, 3, dtype=np.int32)
    store.append("t", d2, schema, {"s": ext})
    cols, _, dd = store.scan("t")
    assert dd["s"].values[-1] == "dd"
    assert len(cols["k"]) == 100
