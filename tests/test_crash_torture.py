"""Process-kill torture matrix (tools/crash_torture.py) — ISSUE 19.

The real-process half of the crash-only story: a server subprocess is
killed at an armed durability seam (``os._exit(137)`` mid-write),
restarted clean, and the acked/unacked ledger is verified over the
wire, then offline via fsck. The full matrix (every seam) is slow-tier;
tier-1 keeps one end-to-end smoke so the harness itself — subprocess
launch, CBTPU_INJECT arming, banner sync, restart, verify, fsck —
cannot rot between full-suite runs.
"""

import pytest

from tools import crash_torture as ct


def _assert_clean(rec):
    assert rec["problems"] == [], (
        f"{rec['seam']}@{rec['hit']}: {rec['problems']}")
    assert rec["fired"], f"{rec['seam']} never fired"
    assert rec["exit_code"] == 137
    assert rec["acked_lost"] == 0
    assert rec["fsck_clean"] is True
    assert rec["recovery_ms"] is not None


def test_single_seam_smoke():
    """Tier-1 smoke: kill INSIDE the manifest commit (after the new
    v{N}.json is written, before CURRENT swings) — the classic torn-
    commit window. Zero acked loss, fsck clean, orphans collected."""
    rec = ct.run_seam("storage_commit_before_current", hit=14)
    _assert_clean(rec)
    assert rec["acked_inserts"] > 0  # the kill came after real acks


@pytest.mark.slow  # ~11 server lifecycles: minutes of wall clock
@pytest.mark.parametrize("seam,hit", ct.MATRIX_SEAMS,
                         ids=[s for s, _ in ct.MATRIX_SEAMS])
def test_matrix_seam(seam, hit):
    """The full crash matrix, one seam per test so a regression names
    its seam. Acceptance (ISSUE 19): >= 10 seams, zero acked loss,
    zero torn manifests/journals, bit-identical read set, fsck clean."""
    _assert_clean(ct.run_seam(seam, hit=hit))


def test_serve_bench_kill_at_row():
    """serve_bench --kill-at emits the crash pass as a CSV row: the
    recovery_ms column carries restart-to-first-answer and acked_lost
    is 0 — crash recovery rides the same dashboards as QPS."""
    import tools.serve_bench as SB

    rows = SB.main(["--kill-at", "io_manifest_write"])
    assert len(rows) == 1
    row = rows[0]
    assert row["mode"] == "killat" and row["mix"] == "io_manifest_write"
    assert row["acked_lost"] == 0
    assert row["recovery_ms"] > 0
    assert row["_torture"]["problems"] == []
    # the row is full-width: every CSV column renders
    assert len(SB.csv_row(row).split(",")) == \
        len(SB.CSV_HEADER.split(","))


def test_matrix_covers_ten_seams():
    """The acceptance floor is pinned here, not in prose: the matrix
    must keep >= 10 distinct durability seams."""
    assert len({s for s, _ in ct.MATRIX_SEAMS}) >= 10
