"""Chaos engineering over the fault-injection seams (faultinjector.c role).

The reference compiles ~230 named fault points and provokes races/failures
deterministically from isolation2 tests (gp_inject_fault). This suite
exercises the analog seams across the engine — dispatch, device loss,
degraded-mesh recovery (the FTS consumption point), tiled execution, the
OCC commit window, endpoints, serving, storage reads, admission — plus an
inventory test pinning the seam count so coverage cannot silently shrink.
"""

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config
from cloudberry_tpu.utils import faultinject as FI


@pytest.fixture(autouse=True)
def _clean_faults():
    FI.reset_fault()
    yield
    FI.reset_fault()


def _mk(nseg=1, **ov):
    over = {"n_segments": nseg}
    over.update(ov)
    return cb.Session(get_config().with_overrides(**over))


def _load(s, n=64):
    s.sql("create table t (k bigint, v bigint) distributed by (k)")
    s.catalog.table("t").set_data(
        {"k": np.arange(n, dtype=np.int64),
         "v": (np.arange(n, dtype=np.int64) * 7) % 13})


# ---------------------------------------------------- device-loss recovery


def test_device_loss_retries_and_succeeds():
    """One injected device loss -> health.recoverable -> re-dispatch wins
    (the stateless-segment recovery model: failed statements re-run)."""
    s = _mk()
    _load(s)
    FI.inject_fault("exec_device_lost", "error", start_hit=1, end_hit=1)
    df = s.sql("select sum(v) as sv from t").to_pandas()
    assert df["sv"][0] == int(((np.arange(64) * 7) % 13).sum())


def test_device_loss_exhausts_retries():
    s = _mk()
    _load(s)
    FI.inject_fault("exec_device_lost", "error")  # every hit
    with pytest.raises(FI.InjectedFault):
        s.sql("select sum(v) from t")


def test_non_recoverable_fault_not_retried():
    """dispatch_start is not a device-loss seam: no retry, one hit."""
    s = _mk()
    _load(s)
    FI.inject_fault("dispatch_start", "error")
    with pytest.raises(FI.InjectedFault):
        s.sql("select sum(v) from t")
    arm = FI._registry["dispatch_start"]
    assert arm.hits == 1


def test_degraded_mesh_replanning():
    """Device loss + a probe reporting one device gone -> the session
    shrinks the segment mesh and the statement completes on n-1 segments
    (fts.c probe -> configuration update; placement re-derives)."""
    s = _mk(nseg=8)
    _load(s, n=128)
    expect = s.sql("select k, v from t where v > 6 order by k").to_pandas()

    FI.inject_fault("exec_device_lost", "error", start_hit=1, end_hit=1)
    FI.inject_fault("probe_degraded", "skip")  # probe sees 7 devices
    got = s.sql("select k, v from t where v > 6 order by k").to_pandas()
    assert s.config.n_segments == 7
    assert expect.equals(got)
    # subsequent statements keep running on the degraded mesh
    FI.reset_fault()
    df = s.sql("select count(*) as c from t").to_pandas()
    assert df["c"][0] == 128


def test_degraded_mesh_skips_mid_list_hole():
    """A REAL device loss leaves a hole in the middle of jax.devices();
    recovery must mesh over the survivors, not devices[:n-1]."""
    s = _mk(nseg=8)
    _load(s, n=256)
    expect = s.sql("select v, count(*) as c from t group by v "
                   "order by v").to_pandas()
    # probe found device 3 dead: survivors are a non-prefix subset
    assert s.degrade_mesh(7, live_ids=[0, 1, 2, 4, 5, 6, 7])
    assert s.config.n_segments == 7
    assert s._live_device_ids == [0, 1, 2, 4, 5, 6, 7]
    got = s.sql("select v, count(*) as c from t group by v "
                "order by v").to_pandas()
    assert expect.equals(got)


def test_probe_reports_live_indices():
    from cloudberry_tpu.parallel import health

    r = health.probe()
    assert r.ok and r.live == list(range(r.n_devices))
    FI.inject_fault("probe_degraded", "skip")
    r2 = health.probe()
    assert r2.n_devices == r.n_devices - 1
    assert r2.live == list(range(r.n_devices - 1))


def test_read_only_classifier():
    from cloudberry_tpu.session import _read_only

    assert _read_only("select 1")
    assert _read_only("  (select 1) union (select 2)")
    assert _read_only("WITH q AS (select 1) select * from q")
    assert not _read_only("insert into t values (1)")
    assert not _read_only("create table t (x int)")
    # sequence allocation happens at plan time: a replay would burn values
    assert not _read_only("select nextval('s')")


def test_degrade_disabled_still_retries():
    s = _mk(nseg=4, **{"health.degrade": False})
    _load(s)
    FI.inject_fault("exec_device_lost", "error", start_hit=1, end_hit=1)
    FI.inject_fault("probe_degraded", "skip")
    df = s.sql("select count(*) as c from t").to_pandas()
    assert df["c"][0] == 64
    assert s.config.n_segments == 4  # mesh untouched


def test_dml_never_retried(monkeypatch):
    """A recoverable failure during DML must NOT re-dispatch: the mutation
    may already be applied, and re-execution would double-apply it. A
    recoverable failure during a SELECT retries."""

    class FakeXla(RuntimeError):
        pass

    FakeXla.__name__ = "XlaRuntimeError"
    s = _mk()
    _load(s)
    calls = []
    orig = type(s)._sql_once

    def flaky(self, query, **kw):
        calls.append(query)
        if len(calls) == 1:
            raise FakeXla("device lost mid-statement")
        return orig(self, query, **kw)

    monkeypatch.setattr(type(s), "_sql_once", flaky)
    with pytest.raises(FakeXla):
        s.sql("insert into t values (999, 1)")
    assert len(calls) == 1  # one attempt, no replay of the mutation

    calls.clear()
    df = s.sql("select count(*) as c from t").to_pandas()
    assert len(calls) == 2 and df["c"][0] == 64  # retried and answered


def test_retries_zero_disables_recovery():
    s = _mk(**{"health.retries": 0})
    _load(s)
    FI.inject_fault("exec_device_lost", "error", start_hit=1, end_hit=1)
    with pytest.raises(FI.InjectedFault):
        s.sql("select count(*) from t")


# ---------------------------------------------------------- tiled seams


def test_tile_step_fault_fails_clean_then_recovers():
    """A fault mid-tile-stream surfaces cleanly, releases the admission
    slot, and the same statement succeeds after disarm."""
    rng = np.random.default_rng(5)
    s = _mk(**{"resource.query_mem_bytes": 4 << 20, "health.retries": 0})
    s.sql("create table dim (k bigint, g bigint) distributed by (k)")
    s.sql("create table fact (k bigint, v bigint) distributed by (k)")
    s.catalog.table("dim").set_data(
        {"k": np.arange(500), "g": np.arange(500) % 9})
    s.catalog.table("fact").set_data(
        {"k": rng.integers(0, 500, 200_000),
         "v": rng.integers(0, 100, 200_000)})
    q = ("select g, sum(v) as sv from fact join dim on fact.k = dim.k "
         "group by g order by g")
    FI.inject_fault("tile_step", "error", start_hit=2)
    with pytest.raises(FI.InjectedFault):
        s.sql(q)
    FI.reset_fault()
    df = s.sql(q).to_pandas()
    assert s.last_tiled_report["n_tiles"] > 1
    assert len(df) == 9


# ------------------------------------------------------ OCC commit window


def test_occ_commit_window_fault_releases_lock(tmp_path):
    """An error inside the commit critical section must release the store
    lock: another session can still commit afterwards."""
    a = cb.Session(get_config().with_overrides(
        **{"storage.root": str(tmp_path)}))
    a.sql("create table ct (x bigint)")
    a.sql("insert into ct values (1)")
    a.sql("begin")
    a.sql("insert into ct values (2)")
    FI.inject_fault("occ_commit_window", "error")
    with pytest.raises(FI.InjectedFault):
        a.sql("commit")
    FI.reset_fault()
    b = cb.Session(get_config().with_overrides(
        **{"storage.root": str(tmp_path)}))
    b.sql("insert into ct values (3)")  # lock free -> this commits
    assert len(b.sql("select x from ct").to_pandas()) >= 2


# ----------------------------------------------------------- other seams


def test_admission_check_seam():
    s = _mk(**{"health.retries": 0})
    _load(s)
    FI.inject_fault("admission_check", "error")
    with pytest.raises(FI.InjectedFault):
        s.sql("select v from t")
    FI.reset_fault()
    assert len(s.sql("select v from t").to_pandas()) == 64


def test_store_read_partition_seam(tmp_path):
    s = cb.Session(get_config().with_overrides(
        **{"storage.root": str(tmp_path), "health.retries": 0}))
    s.sql("create table st (x bigint)")
    s.sql("insert into st values (1),(2),(3)")
    s2 = cb.Session(get_config().with_overrides(
        **{"storage.root": str(tmp_path), "health.retries": 0}))
    FI.inject_fault("store_read_partition", "error")
    with pytest.raises(FI.InjectedFault):
        s2.sql("select sum(x) from st").to_pandas()
    FI.reset_fault()
    assert s2.sql("select sum(x) as s from st").to_pandas()["s"][0] == 6


def test_matview_maintain_seam():
    s = _mk(**{"health.retries": 0})
    _load(s)
    s.sql("create incremental materialized view mv as "
          "select count(*) as c from t")
    FI.inject_fault("matview_maintain", "error")
    with pytest.raises(FI.InjectedFault):
        s.sql("insert into t values (1000, 1)")
    FI.reset_fault()
    s.sql("insert into t values (1001, 2)")


def test_seam_inventory():
    """Pin the declared seam count: the faultinjector.c analog loses its
    value if refactors silently drop seams. grep the package source for
    fault_point(\"name\") declarations."""
    import pathlib
    import re

    root = pathlib.Path(cb.__file__).parent
    names = set()
    for p in root.rglob("*.py"):
        names |= set(re.findall(r'fault_point\("([a-z_]+)"\)',
                                p.read_text()))
    assert len(names) >= 20, sorted(names)
    # the load-bearing seams must exist by exact name — including the
    # mid-statement recovery trio (exec/recovery.py): the deterministic/
    # probabilistic tile kill and the checkpoint/resume chaos arms
    for required in ("dispatch_start", "exec_device_lost", "probe_degraded",
                     "tile_step", "tile_step_dist", "occ_commit_window",
                     "storage_commit_before_current", "endpoint_drain",
                     "serve_handler", "store_read_partition",
                     "admission_check", "dml_update", "dml_delete",
                     "tile_device_lost", "ckpt_save", "ckpt_resume"):
        assert required in names, required
