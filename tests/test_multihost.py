"""Multi-host execution over jax.distributed — the DCN interconnect test.

The reference scales past one machine through its UDP interconnect
(contrib/interconnect/udp/ic_udpifc.c) and tests it with multi-postmaster
demo clusters; here two PROCESSES (each 4 virtual CPU devices) join one
cluster via ``mesh.init_distributed`` and run the same distributed plans
over an 8-segment mesh spanning both — motions become cross-process
collectives (Gloo on CPU; DCN on real TPU pods). The oracle is the
single-process 8-device run of the identical statements."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_mesh_topology_single_host(session):
    from cloudberry_tpu.parallel.mesh import mesh_topology

    topo = mesh_topology(8)
    assert topo["n_segments"] == 8 and topo["n_hosts"] == 1
    assert sum(len(v) for v in topo["segments_by_host"].values()) == 8


def test_ic_bench_standalone():
    """The ic_bench.c analog must run kernel-free on the test mesh and
    emit one JSON line per collective."""
    out = subprocess.run(
        [sys.executable, "-m", "tools.ic_bench",
         "--sizes", "65536", "--reps", "1"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    assert out.returncode == 0, out.stderr[-2000:]
    recs = [json.loads(ln) for ln in out.stdout.splitlines()
            if ln.startswith("{")]
    assert {r["collective"] for r in recs} == \
        {"all_gather", "all_to_all", "psum"}
    assert all(r["wall_ms"] > 0 for r in recs)


def test_two_host_cluster_matches_single_host(session):
    port = _free_port()
    env_base = {k: v for k, v in os.environ.items()
                if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = []
    for pid in range(2):
        env = dict(env_base)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["CBTPU_COORDINATOR"] = f"127.0.0.1:{port}"
        env["CBTPU_NUM_PROCS"] = "2"
        env["CBTPU_PROC_ID"] = str(pid)
        procs.append(subprocess.Popen(
            [sys.executable, WORKER], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=540)
        assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"
        line = [ln for ln in out.splitlines()
                if ln.startswith("RESULT ")][-1]
        outs.append(json.loads(line[len("RESULT "):]))
    assert {o["host"] for o in outs} == {0, 1}
    # both hosts observed identical results (the gathered top slice is
    # replicated across segments, hence across hosts)
    assert outs[0]["results"] == outs[1]["results"]
    # the TWO-LEVEL motion path ran the same statements on the real
    # 2-process cluster (hierarchical redistribute / gather / broadcast
    # + host-combined agg merge) — the worker already asserted
    # hier == flat per query; pin cross-host agreement here too
    assert outs[0]["hier_results"] == outs[0]["results"]
    assert outs[0]["hier_results"] == outs[1]["hier_results"]

    # oracle: the same statements on this process's single-host 8-seg mesh
    import cloudberry_tpu as cb
    from cloudberry_tpu.config import get_config
    from tests.multihost_worker import QUERIES, load

    oracle = cb.Session(get_config().with_overrides(n_segments=8))
    load(oracle)
    for q, got in zip(QUERIES, outs[0]["results"]):
        df = oracle.sql(q).to_pandas()
        exp = {c: df[c].tolist() for c in df.columns}
        assert got == exp, f"multi-host result differs for {q!r}"
