"""MCP server analog (serve/mcp.py) — the AI-agent surface.

Pins the JSON-RPC 2.0 protocol shape (initialize / tools / resources),
the read-only security gate, and both engines: in-process Session and a
live wire connection whose {"meta": ...} requests answer the metadata
tools (the mcp-server/src/cbmcp role)."""

import json

import numpy as np
import pytest

import cloudberry_tpu as cb
from cloudberry_tpu.config import get_config
from cloudberry_tpu.serve.mcp import McpServer, SessionEngine, WireEngine


@pytest.fixture(scope="module")
def srv():
    s = cb.Session(get_config().with_overrides(n_segments=1))
    s.sql("create table emp (id bigint, dept text, sal bigint) "
          "distributed by (id)")
    s.sql("insert into emp values (1,'eng',100),(2,'eng',90),(3,'ops',70)")
    s.sql("create table tiny (x int)")
    s.sql("create view v_eng as select * from emp where dept = 'eng'")
    s.sql("analyze emp")
    return McpServer(SessionEngine(s))


def rpc(m, method, params=None, rid=1):
    resp = m.handle({"jsonrpc": "2.0", "id": rid, "method": method,
                     "params": params or {}})
    assert resp["id"] == rid
    assert "error" not in resp, resp.get("error")
    return resp["result"]


def tool(m, name, **args):
    out = rpc(m, "tools/call", {"name": name, "arguments": args})
    assert out["isError"] is False, out
    return json.loads(out["content"][0]["text"])


def test_initialize_and_lists(srv):
    init = rpc(srv, "initialize")
    assert init["serverInfo"]["name"] == "cloudberry-tpu-mcp"
    assert "tools" in init["capabilities"]
    tools = {t["name"] for t in rpc(srv, "tools/list")["tools"]}
    assert {"list_tables", "execute_query", "explain_query",
            "get_table_stats"} <= tools
    # notifications get no response
    assert srv.handle({"jsonrpc": "2.0",
                       "method": "notifications/initialized"}) is None


def test_list_tables_and_columns(srv):
    tables = tool(srv, "list_tables")
    byname = {t["name"]: t for t in tables}
    assert byname["emp"]["rows"] == 3
    assert byname["emp"]["distribution"] == "DISTRIBUTED BY (id)"
    cols = tool(srv, "list_columns", table="emp")
    assert [c["name"] for c in cols] == ["id", "dept", "sal"]
    assert cols[0]["type"].lower().startswith("bigint") \
        or "int" in cols[0]["type"].lower()


def test_execute_query_and_stats(srv):
    out = tool(srv, "execute_query",
               sql="select dept, sum(sal) as s from emp group by dept "
                   "order by dept")
    assert out["columns"] == ["dept", "s"]
    assert out["rows"] == [["eng", 190], ["ops", 70]]
    st = tool(srv, "get_table_stats", table="emp")
    assert st["rows"] == 3 and "sal" in st["min_max"]
    plan = tool(srv, "explain_query", sql="select count(*) from emp")
    assert "Scan emp" in plan["plan"]


def test_read_only_gate(srv):
    resp = srv.handle({"jsonrpc": "2.0", "id": 7, "method": "tools/call",
                       "params": {"name": "execute_query",
                                  "arguments": {
                                      "sql": "drop table emp"}}})
    assert resp["error"]["code"] == -32602
    assert "read-only" in resp["error"]["message"]
    resp = srv.handle({"jsonrpc": "2.0", "id": 8, "method": "tools/call",
                       "params": {"name": "execute_query",
                                  "arguments": {
                                      "sql": "select 1; drop table emp"}}})
    assert "stacked" in resp["error"]["message"]
    # the table survived the attempts
    assert tool(srv, "get_table_stats", table="emp")["rows"] == 3


def test_read_only_gate_edge_cases(srv):
    # a semicolon inside a string literal is data, not a second statement
    out = tool(srv, "execute_query",
               sql="select count(*) as c from emp where dept = 'a;b'")
    assert out["rows"] == [[0]]
    # nextval is a WRITE despite the select head (plan-time allocation)
    resp = srv.handle({"jsonrpc": "2.0", "id": 11, "method": "tools/call",
                       "params": {"name": "execute_query",
                                  "arguments": {
                                      "sql": "select nextval('s1')"}}})
    assert "read-only" in resp["error"]["message"]


def test_max_rows_cap(srv):
    out = tool(srv, "execute_query", sql="select id from emp order by id",
               max_rows=2)
    assert len(out["rows"]) == 2 and out["truncated"] is True


def test_resources(srv):
    uris = {r["uri"] for r in rpc(srv, "resources/list")["resources"]}
    assert "cbtpu://database/info" in uris
    info = json.loads(rpc(srv, "resources/read",
                          {"uri": "cbtpu://database/info"}
                          )["contents"][0]["text"])
    assert info["engine"] == "cloudberry_tpu" and info["tables"] == 2
    schemas = json.loads(rpc(srv, "resources/read",
                             {"uri": "cbtpu://schemas"}
                             )["contents"][0]["text"])
    assert "emp" in schemas


def test_large_tables_and_views(srv):
    big = tool(srv, "list_large_tables", limit=1)
    assert big[0]["name"] == "emp"
    assert tool(srv, "list_views") == ["v_eng"]


def test_unknown_method_and_tool(srv):
    resp = srv.handle({"jsonrpc": "2.0", "id": 9, "method": "nope"})
    assert resp["error"]["code"] == -32602
    resp = srv.handle({"jsonrpc": "2.0", "id": 10, "method": "tools/call",
                       "params": {"name": "nope", "arguments": {}}})
    assert "unknown tool" in resp["error"]["message"]


def test_stdio_transport(srv):
    import io

    lines = [
        json.dumps({"jsonrpc": "2.0", "id": 1, "method": "initialize"}),
        json.dumps({"jsonrpc": "2.0", "method":
                    "notifications/initialized"}),
        "not json",
        json.dumps({"jsonrpc": "2.0", "id": 2, "method": "tools/call",
                    "params": {"name": "execute_query",
                               "arguments": {"sql":
                                             "select count(*) c from emp"
                                             }}}),
    ]
    out = io.StringIO()
    srv.serve_stdio(stdin=io.StringIO("\n".join(lines) + "\n"), stdout=out)
    resps = [json.loads(x) for x in out.getvalue().splitlines()]
    # notification dropped: init, parse error, tool result
    assert len(resps) == 3
    assert resps[0]["result"]["protocolVersion"]
    assert resps[1]["error"]["code"] == -32700
    body = json.loads(resps[2]["result"]["content"][0]["text"])
    assert body["rows"] == [[3]]


def test_meta_sees_other_sessions_ddl(tmp_path):
    """Metadata must sync the store first: a thin client that only asks
    metadata questions still sees other sessions' committed DDL."""
    cfg = get_config().with_overrides(**{"storage.root": str(tmp_path)})
    reader = cb.Session(cfg)
    m = McpServer(SessionEngine(reader))
    assert tool(m, "list_tables") == []
    writer = cb.Session(cfg)
    writer.sql("create table late (x bigint)")
    writer.sql("insert into late values (1)")
    names = [t["name"] for t in tool(m, "list_tables")]
    assert names == ["late"]
    assert tool(m, "get_table_stats", table="late")["rows"] == 1


def test_wire_engine_end_to_end(tmp_path):
    """An MCP server backed by a LIVE socket server: metadata rides the
    wire protocol's {"meta": ...} requests."""
    from cloudberry_tpu.serve.server import Server

    cfg = get_config().with_overrides(**{"storage.root": str(tmp_path)})
    with Server(config=cfg, port=0) as server:
        boot = cb.Session(cfg)
        boot.sql("create table wt (a bigint, b bigint) distributed by (a)")
        boot.sql("insert into wt values (1, 10), (2, 20)")
        m = McpServer(WireEngine(server.host, server.port))
        tables = tool(m, "list_tables")
        assert [t["name"] for t in tables] == ["wt"]
        out = tool(m, "execute_query",
                   sql="select sum(b) as s from wt")
        assert out["rows"] == [[30]]
        info = json.loads(rpc(m, "resources/read",
                              {"uri": "cbtpu://database/info"}
                              )["contents"][0]["text"])
        assert info["durable"] is True
