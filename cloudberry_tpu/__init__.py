"""cloudberry_tpu — a TPU-native MPP analytical SQL framework.

A ground-up re-design of the capabilities of Apache Cloudberry (the
Greenplum-lineage MPP PostgreSQL fork; see SURVEY.md) for TPU hardware:

- the per-segment executor (reference: ``src/backend/executor``) is a set of
  JAX/XLA kernels over Arrow-style columnar device buffers with static shapes;
- the Motion/interconnect shuffle (reference: ``src/backend/cdb/motion``,
  ``contrib/interconnect``) is expressed as ``jax.lax`` collectives
  (``all_to_all`` / ``all_gather`` / ``ppermute``) over an ICI device mesh;
- the locus model (reference: ``src/include/cdb/cdbpathlocus.h:41-68``) is a
  first-class ``Sharding`` annotation on every plan node, driving motion
  insertion exactly like ``cdbpath_motion_for_join``;
- storage is immutable columnar micro-partitions with footer stats
  (modeled on ``contrib/pax_storage``), not heap/WAL pages.

Everything under ``jit`` is traced once: no data-dependent Python control
flow, static shapes with selection masks, ``lax`` control flow only.
"""

import jax

# 64-bit support: analytical SQL needs int64 keys and f64 aggregates.
# On TPU f64 is emulated, so hot paths stay on int64 fixed-point / f32.
jax.config.update("jax_enable_x64", True)

from cloudberry_tpu.config import Config, get_config, set_config  # noqa: E402
from cloudberry_tpu.session import Session  # noqa: E402

__version__ = "0.1.0"
__all__ = ["Config", "get_config", "set_config", "Session", "__version__"]
