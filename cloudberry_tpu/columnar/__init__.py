from cloudberry_tpu.columnar.dictionary import StringDictionary
from cloudberry_tpu.columnar.batch import ColumnBatch

__all__ = ["StringDictionary", "ColumnBatch"]
