"""ColumnBatch — the Arrow-layout unit of execution.

The reference executor pulls one tuple at a time through ExecProcNode
(src/backend/executor/execProcnode.c) and serializes tuples for motion
(tupser.c). Here the unit is a fixed-capacity batch of columns — each column
a 1-D device array — plus a boolean selection mask ``sel``. Filters AND into
``sel`` instead of compacting (XLA static shapes); kernels that must compact
(sort, join build) do so with masked keys. This is the "vectorization is the
default, not an add-on" stance from SURVEY.md §2.8 item 13.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping

import numpy as np

from cloudberry_tpu import types
from cloudberry_tpu.columnar.dictionary import StringDictionary
from cloudberry_tpu.types import DType, Field, Schema, date_to_days


@dataclass
class ColumnBatch:
    """Host-facing container; executors work on the raw ``columns``/``sel``.

    ``validity``: per-column bool arrays for nullable (outer-join) columns —
    False rows render as NULL."""

    schema: Schema
    columns: dict[str, Any]          # name -> (capacity,) array (np or jax)
    sel: Any                         # (capacity,) bool array
    dicts: dict[str, StringDictionary] = field(default_factory=dict)
    validity: dict[str, Any] = field(default_factory=dict)

    @property
    def capacity(self) -> int:
        return int(self.sel.shape[0])

    def num_rows(self) -> int:
        return int(np.asarray(self.sel).sum())

    @staticmethod
    def from_arrays(
        data: Mapping[str, np.ndarray],
        schema: Schema,
        dicts: dict[str, StringDictionary] | None = None,
        capacity: int | None = None,
    ) -> "ColumnBatch":
        n = len(next(iter(data.values()))) if data else 0
        cap = capacity if capacity is not None else n
        if cap < n:
            raise ValueError(f"capacity {cap} < rows {n}")
        dicts = dict(dicts or {})
        cols: dict[str, Any] = {}
        for f in schema.fields:
            arr = encode_column(np.asarray(data[f.name]), f, dicts)
            if cap > n:
                pad = np.zeros(cap - n, dtype=arr.dtype)
                arr = np.concatenate([arr, pad])
            cols[f.name] = arr
        sel = np.zeros(cap, dtype=np.bool_)
        sel[:n] = True
        return ColumnBatch(schema, cols, sel, dicts)

    @staticmethod
    def from_pandas(df, schema: Schema | None = None,
                    dicts: dict[str, StringDictionary] | None = None,
                    capacity: int | None = None) -> "ColumnBatch":
        if schema is None:
            schema = _infer_schema(df)
        data = {f.name: df[f.name].to_numpy() for f in schema.fields}
        return ColumnBatch.from_arrays(data, schema, dicts, capacity)

    def decoded_columns(self) -> dict[str, np.ndarray]:
        """Selected rows as decoded host arrays (NULLs as None in object
        arrays) — pandas-free, safe off the main thread (the arrow-backed
        DataFrame constructor is not)."""
        sel = np.asarray(self.sel)
        out = {}
        for f in self.schema.fields:
            arr = np.asarray(self.columns[f.name])[sel]
            vm = self.validity.get(f.name)
            invalid = None
            if vm is not None:
                invalid = ~np.asarray(vm).astype(bool)[sel]
                if f.dtype == DType.STRING and invalid.any():
                    # NULL string lanes may hold out-of-dictionary codes
                    # (e.g. -1 from CASE NULL branches): clamp before decode
                    arr = np.where(invalid, 0, arr)
                    d = self.dicts.get(f.name)
                    if d is not None and len(d) == 0:
                        out[f.name] = np.full(len(arr), None, dtype=object)
                        continue
            col = decode_column(arr, f, self.dicts)
            if invalid is not None and invalid.any():
                col = np.asarray(col, dtype=object)
                col[invalid] = None
            out[f.name] = col
        return out

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame(self.decoded_columns())


def encode_column(arr: np.ndarray, f: Field,
                  dicts: dict[str, StringDictionary]) -> np.ndarray:
    """Host value array → physical device representation for field ``f``."""
    if f.dtype == DType.STRING and arr.dtype.kind in ("U", "S", "O"):
        d = dicts.setdefault(f.name, StringDictionary())
        arr = d.encode(arr)
    elif f.dtype == DType.DATE and arr.dtype.kind in ("U", "S", "O", "M"):
        if arr.dtype.kind == "M":
            arr = arr.astype("datetime64[D]").astype(np.int64)
        else:
            arr = np.fromiter((date_to_days(v) for v in arr), dtype=np.int64)
    elif f.dtype == DType.DECIMAL and arr.dtype.kind == "f":
        arr = np.rint(arr * (10.0 ** f.type.scale)).astype(np.int64)
    elif f.dtype == DType.DECIMAL and arr.dtype.kind in "iu":
        arr = arr.astype(np.int64) * np.int64(10 ** f.type.scale)
    return arr.astype(f.type.np_dtype)


def decode_column(arr: np.ndarray, f: Field,
                  dicts: dict[str, StringDictionary]) -> np.ndarray:
    """Physical representation → host values (dict decode, date, descale)."""
    if f.dtype == DType.STRING and f.name in dicts:
        return dicts[f.name].decode(arr)
    if f.dtype == DType.DATE:
        return arr.astype("datetime64[D]")
    if f.dtype == DType.DECIMAL:
        return arr.astype(np.float64) / (10.0 ** f.type.scale)
    return arr


def _infer_schema(df) -> Schema:
    fields = []
    for name in df.columns:
        k = df[name].dtype.kind
        if k == "b":
            t = types.BOOL
        elif k == "i" and df[name].dtype.itemsize <= 4:
            t = types.INT32
        elif k in ("i", "u"):
            t = types.INT64
        elif k == "f":
            t = types.FLOAT64
        elif k == "M":
            t = types.DATE
        else:
            t = types.STRING
        fields.append(Field(name, t))
    return Schema(tuple(fields))
