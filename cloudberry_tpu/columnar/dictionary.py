"""Host-side string dictionaries.

Variable-length data never enters device tensors: string columns are int32
codes on device; the dictionary (code → str) lives on the host. String
predicates (LIKE, =, IN) are evaluated once over the dictionary on the host,
producing either a literal code (equality) or a boolean lookup table that the
device gathers by code — O(|dict|) host work, O(1) per row on device.
The reference's PAX engine uses the same idea (dictionary encodings,
contrib/pax_storage README "encodings"); classic Cloudberry instead pays
per-tuple varlena serialization in tupser.c, which has no TPU analog.
"""

from __future__ import annotations

import fnmatch
import re
from typing import Callable, Iterable

import numpy as np


class StringDictionary:
    """Immutable-ish ordered dictionary: values[code] == string.

    Sorted insertion is NOT guaranteed; ordering comparisons on strings use
    a rank table (see ``rank_table``).
    """

    __slots__ = ("values", "_index")

    def __init__(self, values: Iterable[str] = ()):
        self.values: list[str] = list(values)
        self._index: dict[str, int] = {v: i for i, v in enumerate(self.values)}

    def __len__(self) -> int:
        return len(self.values)

    def code_of(self, value: str) -> int:
        """Code for value, or -1 if absent (absent ⇒ no row can equal it)."""
        return self._index.get(value, -1)

    def add(self, value: str) -> int:
        code = self._index.get(value)
        if code is None:
            code = len(self.values)
            self.values.append(value)
            self._index[value] = code
        return code

    def encode(self, arr: Iterable[str]) -> np.ndarray:
        return np.fromiter((self.add(v) for v in arr), dtype=np.int32)

    def decode(self, codes: np.ndarray) -> np.ndarray:
        vals = np.asarray(self.values, dtype=object)
        out = np.empty(codes.shape, dtype=object)
        valid = codes >= 0
        out[valid] = vals[codes[valid]]
        out[~valid] = None
        return out

    def predicate_table(self, pred: Callable[[str], bool]) -> np.ndarray:
        """bool[len(dict)] lookup table for an arbitrary string predicate."""
        return np.fromiter((bool(pred(v)) for v in self.values),
                           dtype=np.bool_, count=len(self.values))

    def like_table(self, pattern: str) -> np.ndarray:
        """SQL LIKE over the dictionary (% → .*, _ → .)."""
        rx = re.compile(_like_to_regex(pattern), re.DOTALL)
        return self.predicate_table(lambda v: rx.fullmatch(v) is not None)

    def rank_table(self) -> np.ndarray:
        """int32[len(dict)] such that rank[a] < rank[b] iff values[a] < values[b].

        Lets the device ORDER BY / compare string columns by gathering ranks.
        """
        order = np.argsort(np.asarray(self.values, dtype=object), kind="stable")
        ranks = np.empty(len(self.values), dtype=np.int32)
        ranks[order] = np.arange(len(self.values), dtype=np.int32)
        return ranks


def _like_to_regex(pattern: str) -> str:
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "\\" and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return "".join(out)
