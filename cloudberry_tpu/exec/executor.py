"""Single-program executor: plan tree → one jitted XLA computation.

The reference pulls tuples through a process-per-slice Volcano tree
(ExecProcNode, src/backend/executor/execProcnode.c); here the WHOLE plan
compiles into one XLA program over fixed-capacity column arrays — scans are
function inputs, operators are the kernels in exec/kernels.py, and (in
distributed mode) motions are collectives. Runtime "can't happen" conditions
(agg capacity overflow, duplicate build keys in a PK join) are returned as
scalar check outputs and raised host-side after the run — the shape-world
analog of ereport().
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from cloudberry_tpu.columnar.batch import ColumnBatch
from cloudberry_tpu.exec import kernels as K
from cloudberry_tpu.exec.expr_compile import compile_expr
from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.types import DType, Field, Schema


class ExecError(RuntimeError):
    pass


@dataclass
class Executable:
    plan: N.PlanNode
    fn: Callable  # (tables pytree) -> (cols dict, sel, checks dict)
    table_names: list[str]


def execute(plan: N.PlanNode, session) -> ColumnBatch:
    exe = compile_plan(plan, session)
    tables = prepare_tables(exe.table_names, session)
    return run_executable(exe, tables)


def compile_plan(plan: N.PlanNode, session) -> Executable:
    table_names = sorted({s.table_name for s in _scans(plan)})

    def run(tables):
        checks: dict[str, jnp.ndarray] = {}
        cols, sel = _compile_node(plan, tables, checks)
        out = {f.name: cols[f.name] for f in plan.fields}
        return out, sel, checks

    return Executable(plan, jax.jit(run), table_names)


def prepare_tables(table_names: list[str], session) -> dict:
    tables = {}
    for name in table_names:
        t = session.catalog.table(name)
        tables[name] = {c: jnp.asarray(v) for c, v in t.data.items()}
    return tables


def run_executable(exe: Executable, tables: dict) -> ColumnBatch:
    cols, sel, checks = exe.fn(tables)
    for msg, bad in checks.items():
        if bool(np.asarray(bad)):
            raise ExecError(msg)
    fields = tuple(Field(f.name, f.type) for f in exe.plan.fields)
    dicts = {f.name: f.sdict for f in exe.plan.fields if f.sdict is not None}
    return ColumnBatch(Schema(fields),
                       {k: np.asarray(v) for k, v in cols.items()},
                       np.asarray(sel), dicts)


def _scans(plan: N.PlanNode):
    if isinstance(plan, N.PScan) and plan.table_name != "$dual":
        yield plan
    for c in plan.children():
        yield from _scans(c)


# ------------------------------------------------------------- node lowering


def _compile_node(node: N.PlanNode, tables, checks) -> tuple[dict, jnp.ndarray]:
    if isinstance(node, N.PScan):
        if node.table_name == "$dual":
            return {}, jnp.ones((1,), dtype=jnp.bool_)
        data = tables[node.table_name]
        cols = {}
        for phys, out in node.column_map.items():
            arr = data[phys]
            if arr.shape[0] < node.capacity:  # empty table: 0 rows, cap 1
                arr = jnp.zeros((node.capacity,), dtype=arr.dtype)
            cols[out] = arr
        n = node.num_rows if node.num_rows >= 0 else node.capacity
        sel = jnp.arange(node.capacity) < n
        return cols, sel

    if isinstance(node, N.PFilter):
        cols, sel = _compile_node(node.child, tables, checks)
        mask = compile_expr(node.predicate)(cols)
        return cols, sel & mask

    if isinstance(node, N.PProject):
        cols, sel = _compile_node(node.child, tables, checks)
        out = {name: compile_expr(e)(cols) for name, e in node.exprs}
        return out, sel

    if isinstance(node, N.PJoin):
        return _compile_join(node, tables, checks)

    if isinstance(node, N.PAgg):
        return _compile_agg(node, tables, checks)

    if isinstance(node, N.PSort):
        cols, sel = _compile_node(node.child, tables, checks)
        keys, desc = [], []
        for e, asc in node.keys:
            keys.append(_sortable(e, node.child, cols))
            desc.append(not asc)
        perm = K.sort_indices(keys, sel, descending=desc)
        return {n: c[perm] for n, c in cols.items()}, sel[perm]

    if isinstance(node, N.PLimit):
        cols, sel = _compile_node(node.child, tables, checks)
        return cols, K.limit_mask(sel, node.limit, node.offset)

    if isinstance(node, N.PMotion):
        # single-program mode: loopback motion is the identity (the
        # MotionIPCLayer seam's test backend); collectives live in
        # exec/dist_executor.py
        return _compile_node(node.child, tables, checks)

    raise ExecError(f"cannot execute node {type(node).__name__}")


def _compile_join(node: N.PJoin, tables, checks):
    bcols, bsel = _compile_node(node.build, tables, checks)
    pcols, psel = _compile_node(node.probe, tables, checks)
    bkeys = [compile_expr(k)(bcols) for k in node.build_keys]
    pkeys = [compile_expr(k)(pcols) for k in node.probe_keys]
    idx, matched = K.join_lookup(bkeys, bsel, pkeys, psel)
    checks[f"join build side has duplicate keys (node {id(node)}); "
           "many-to-many joins need the expansion kernel"] = \
        _dup_keys_flag(bkeys, bsel)
    payload = K.gather_payload({n: bcols[n] for n in node.build_payload},
                               idx, matched)
    cols = {**pcols, **payload}
    if node.match_name:
        cols[node.match_name] = matched
    if node.kind == "inner" or node.kind == "semi":
        sel = matched
    elif node.kind == "left":
        sel = psel
    elif node.kind == "anti":
        sel = psel & ~matched
    else:
        raise ExecError(f"join kind {node.kind}")
    return cols, sel


def _dup_keys_flag(bkeys, bsel) -> jnp.ndarray:
    kb = K.pack_keys(list(bkeys), bsel)
    kb = jnp.where(bsel, kb, K._U64_MAX)
    s = jnp.sort(kb)
    eq = (s[1:] == s[:-1]) & (s[1:] != K._U64_MAX)
    return eq.any()


def _compile_agg(node: N.PAgg, tables, checks):
    cols, sel = _compile_node(node.child, tables, checks)
    agg_specs = []
    agg_values: dict[str, Any] = {}
    post_scale: dict[str, float] = {}
    for name, call in node.aggs:
        func = call.func
        if func == "count" and call.arg is None:
            agg_values[name] = None
        elif func in ("sum", "min", "max", "avg", "count"):
            agg_values[name] = compile_expr(call.arg)(cols) \
                if call.arg is not None else None
        else:
            raise ExecError(f"aggregate {func} not implemented yet")
        if func == "avg" and call.arg is not None \
                and call.arg.dtype.base == DType.DECIMAL:
            post_scale[name] = 10.0 ** call.arg.dtype.scale
        agg_specs.append(K.AggSpec(func, name))

    if not node.group_keys:
        out = K.global_aggregate(agg_values, agg_specs, sel)
        for name, div in post_scale.items():
            out[name] = out[name] / div
        return out, jnp.ones((1,), dtype=jnp.bool_)

    key_cols = {name: compile_expr(e)(cols) for name, e in node.group_keys}
    out_keys, out_aggs, out_sel, n_groups = K.group_aggregate(
        key_cols, agg_values, agg_specs, sel, node.capacity)
    checks[f"aggregation overflow: more groups than capacity "
           f"{node.capacity} (node {id(node)})"] = n_groups > node.capacity
    for name, div in post_scale.items():
        out_aggs[name] = out_aggs[name] / div
    return {**out_keys, **out_aggs}, out_sel


def _sortable(e: ex.Expr, child: N.PlanNode, cols) -> jnp.ndarray:
    """ORDER BY key array; string columns sort by host rank, not code."""
    arr = compile_expr(e)(cols)
    if e.dtype.base == DType.STRING:
        sdict = None
        if isinstance(e, ex.ColumnRef):
            try:
                sdict = child.field(e.name).sdict
            except KeyError:
                sdict = getattr(e, "_sdict", None)
        else:
            sdict = getattr(e, "_sdict", None) or getattr(e, "_out_dict", None)
        if sdict is not None and len(sdict):
            rank = jnp.asarray(sdict.rank_table())
            safe = jnp.clip(arr, 0, rank.shape[0] - 1)
            return jnp.where(arr >= 0, jnp.take(rank, safe), -1)
    return arr
