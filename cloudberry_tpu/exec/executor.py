"""Single-program executor: plan tree → one jitted XLA computation.

The reference pulls tuples through a process-per-slice Volcano tree
(ExecProcNode, src/backend/executor/execProcnode.c); here the WHOLE plan
compiles into one XLA program over fixed-capacity column arrays — scans are
function inputs, operators are the kernels in exec/kernels.py, and (in
distributed mode, exec/dist_executor.py) motions are collectives. Runtime
"can't happen" conditions (agg capacity overflow, duplicate build keys in a
PK join) are returned as scalar check outputs and raised host-side after the
run — the shape-world analog of ereport().
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from cloudberry_tpu.columnar.batch import ColumnBatch
from cloudberry_tpu.exec import kernels as K
from cloudberry_tpu.exec.expr_compile import compile_expr
from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.types import DType, Field, Schema


class ExecError(RuntimeError):
    pass


class DuplicateBuildKeyError(ExecError):
    """The planner assumed a unique (PK) build side but the data holds
    duplicate build keys — a semantic error (results would be wrong, so
    the statement aborts; never retryable). Raised from the runtime
    duplicate check every inner/left lookup join carries, instead of
    silently trusting the planner's uniqueness inference."""


@dataclass
class Executable:
    plan: N.PlanNode
    fn: Callable  # (tables pytree) -> (cols dict, sel, checks dict)
    table_names: list[str]
    # scans bound to pruned micro-partition reads (plan/scanprune.py);
    # their inputs key by scan identity, not table name
    store_scans: list = None  # type: ignore[assignment]
    # the unjitted trace function — the micro-batch dispatcher vmaps it
    # into stacked-parameter executables (sched/paramplan.py rung_fn)
    raw_fn: Callable = None  # type: ignore[assignment]
    # instrumented programs return a 4th output (per-node row counts);
    # EXPLAIN ANALYZE's pipeline path runs them directly (instrument.py)
    instrumented: bool = False


def execute(plan: N.PlanNode, session) -> ColumnBatch:
    seg = getattr(plan, "_direct_segment", None)
    if session.config.n_segments > 1 and seg is None:
        from cloudberry_tpu.exec.dist_executor import execute_distributed

        return execute_distributed(plan, session)
    exe = compile_plan(plan, session)
    return run_executable(exe, prepare_inputs(exe, session, segment=seg))


def keyed_scan(s: N.PScan) -> bool:
    """Scans whose input rides under a per-scan key instead of the
    table name: pruned store reads and point-lookup slices."""
    return hasattr(s, "_store_parts") or hasattr(s, "_point_rows")


def count_compile(session) -> None:
    """Record one XLA program construction on the engine's shared counters
    (exec/instrument.py StatementLog) — the compile-hit observability every
    plan-cache consumer reads (zero after warmup is the generic-plan
    contract, sched/paramplan.py)."""
    log = getattr(session, "stmt_log", None)
    if log is not None:
        log.bump("compiles")


def compile_plan(plan: N.PlanNode, session,
                 platform: str | None = None,
                 instrument: bool = False) -> Executable:
    """``instrument=True`` (EXPLAIN ANALYZE's pipeline path,
    exec/instrument.py run_pipeline) compiles THE SAME program through
    this same entry point with per-node row counts as a 4th output —
    no private lowerer."""
    scans = list(scans_of(plan))
    store_scans = [s for s in scans if keyed_scan(s)]
    table_names = sorted({s.table_name for s in scans
                          if not keyed_scan(s)})
    platform = platform or jax.default_backend()
    use_pallas = session.config.exec.use_pallas
    count_compile(session)

    if instrument:
        from cloudberry_tpu.exec.instrument import InstrumentingMixin

        class _InstrLowerer(InstrumentingMixin, Lowerer):
            def __init__(self, *a, **kw):
                Lowerer.__init__(self, *a, **kw)
                self.__init_instrument__()

        def run(tables):
            low = _InstrLowerer(tables, platform=platform,
                                use_pallas=use_pallas,
                                params=tables.get("$params"))
            cols, sel = low.lower(plan)
            out = {f.name: cols[f.name] for f in plan.fields}
            return out, sel, low.checks, low.node_counts

        return Executable(plan, jax.jit(run), table_names, store_scans,
                          run, instrumented=True)

    def run(tables):
        low = Lowerer(tables, platform=platform, use_pallas=use_pallas,
                      params=tables.get("$params"))
        cols, sel = low.lower(plan)
        out = {f.name: cols[f.name] for f in plan.fields}
        return out, sel, low.checks

    return Executable(plan, jax.jit(run), table_names, store_scans, run)


def prepare_tables(table_names: list[str], session,
                   segment: int | None = None) -> dict:
    """segment=None: whole tables (single-segment mode); otherwise ONE
    segment's shard (direct dispatch — cdbtargeteddispatch analog)."""
    tables = {}
    for name in table_names:
        t = session.catalog.table(name)
        t.ensure_loaded()  # safety: a cold table on the RAM path loads whole
        if segment is None or t.policy.kind == "replicated":
            tables[name] = {c: jnp.asarray(v) for c, v in t.data.items()}
            for c, vm in t.validity.items():
                tables[name][f"$nn:{c}"] = jnp.asarray(
                    np.asarray(vm, dtype=np.bool_))
        else:
            st = session.sharded_table(name)
            tables[name] = {c: jnp.asarray(v[segment])
                            for c, v in st.columns.items()}
    return tables


def prepare_inputs(exe: Executable, session,
                   segment: int | None = None) -> dict:
    """All inputs for one executable: RAM tables by name plus pruned
    store reads keyed by scan identity plus cached join indexes."""
    return _assemble_inputs(exe.table_names, exe.store_scans or (),
                            session, segment, plan=exe.plan)


def prepare_plan_inputs(plan: N.PlanNode, session,
                        segment: int | None = None) -> dict:
    """Same input assembly from a bare plan (instrumented execution)."""
    scans = list(scans_of(plan))
    return _assemble_inputs(
        sorted({s.table_name for s in scans if not keyed_scan(s)}),
        [s for s in scans if keyed_scan(s)],
        session, segment, plan=plan)


def _assemble_inputs(table_names, store_scans, session, segment,
                     plan=None) -> dict:
    tables = prepare_tables(table_names, session, segment=segment)
    for s in store_scans:
        if hasattr(s, "_point_rows"):
            tables[s._input_key] = _load_point_scan(s, session, segment)
        else:
            tables[s._input_key] = _load_store_scan(s, session)
    if plan is not None:
        # cached sorted-build join indexes ride next to the tables (the
        # $params discipline): same shapes every execution, so feeding a
        # fresh index never retraces — exec/joinindex.py
        from cloudberry_tpu.exec.joinindex import join_index_inputs

        tables.update(join_index_inputs(plan, session, segment))
    return tables


def _load_point_scan(scan: N.PScan, session, segment) -> dict:
    """Slice exactly the sidecar-matched rows (plan/pointlookup.py) out
    of the table — or its direct-dispatched shard — as the scan input."""
    return point_scan_slice(scan.table_name, scan._point_rows, session,
                            segment)


def point_scan_slice(table_name: str, rows, session, segment) -> dict:
    """One point-bound scan's input columns: the matched rows sliced from
    the table (or its direct-dispatched shard). Shared by normal input
    assembly and the generic-plan fast rebind (sched/paramplan.py), which
    re-slices per literal without re-planning. Slices stay HOST arrays —
    jit converts at dispatch, and the micro-batch path stacks many
    requests host-side before the single device transfer."""
    t = session.catalog.table(table_name)
    t.ensure_loaded()
    out = {}
    if segment is None or t.policy.kind == "replicated":
        for c, v in t.data.items():
            out[c] = np.asarray(v)[rows]
        for c, vm in t.validity.items():
            out[f"$nn:{c}"] = np.asarray(vm, dtype=np.bool_)[rows]
    else:
        st = session.sharded_table(table_name)
        for c, v in st.columns.items():
            out[c] = np.asarray(v[segment])[rows]
    return out


_STORE_SCAN_CACHE_MAX = 16


def _load_store_scan(scan: N.PScan, session) -> dict:
    """Read a pruned scan's columns from micro-partitions (column
    projection: ONLY column_map + mask_map physical columns are read),
    cached per (table, version, partitions, columns). Cache traffic is
    visible on the metrics plane (``store_scan_cache_*`` counters —
    meta "metrics"), and a cache miss consults the HBM buffer pool
    per partition before touching the store (exec/bufferpool.py)."""
    store = session.catalog.store
    key = (scan.table_name, store.effective_version(scan.table_name),
           tuple(p["file"] for p in scan._store_parts),
           tuple(sorted(scan.column_map)), tuple(sorted(scan.mask_map)))
    cache = session._store_scan_cache
    log = getattr(session, "stmt_log", None)
    # LRU, not FIFO: pop-and-reinsert moves a hit to the dict's end so a
    # hot table's scan survives a burst of one-off queries; eviction
    # takes the true least-recently-used head. Hits now MUTATE the dict,
    # and shared-session server mode runs concurrent readers — the lock
    # keeps reorder/evict/insert atomic (the store read itself runs
    # unlocked; two simultaneous misses read twice, harmlessly).
    lock = session._store_scan_lock
    with lock:
        hit = cache.pop(key, None)
        if hit is not None:
            cache[key] = hit
    if hit is not None:
        if log is not None:
            log.bump("store_scan_cache_hits")
        return hit
    if log is not None:
        log.bump("store_scan_cache_misses")
    hit = _read_scan_columns(scan, session, log)
    evicted = 0
    with lock:
        while len(cache) >= _STORE_SCAN_CACHE_MAX:
            cache.pop(next(iter(cache)))
            evicted += 1
        cache[key] = hit
    if evicted and log is not None:
        log.bump("store_scan_cache_evictions", evicted)
    return hit


def _read_scan_columns(scan: N.PScan, session, log) -> dict:
    """Assemble one pruned scan's input dict. With the buffer pool on,
    partitions are looked up (and admitted) individually and the chunks
    concatenated in part order — read_partitions does exactly that
    internally, so the assembly is bit-identical to one batched read;
    resident partitions skip the host read/decode entirely."""
    from cloudberry_tpu.exec import bufferpool as BUF

    store = session.catalog.store
    needed = sorted(set(scan.column_map) | set(scan.mask_map))
    parts = list(scan._store_parts)
    bpool = BUF.pool_for(session)
    if bpool is None or not parts:
        cols, validity = store.read_partitions(scan.table_name, parts,
                                               needed)
        if log is not None and parts:
            log.bump("host_decodes", len(parts))
        hit = {c: jnp.asarray(v) for c, v in cols.items()}
        for c, v in validity.items():
            hit[f"$nn:{c}"] = jnp.asarray(np.asarray(v, dtype=np.bool_))
        return hit
    cols_key = tuple(needed)
    col_chunks: dict[str, list] = {}
    val_chunks: dict[str, list] = {}
    for part in parts:
        pk = BUF.partition_key(session, scan.table_name, part, cols_key)
        ent = bpool.lookup(pk, log)
        if ent is None:
            cols, validity = store.read_partitions(
                scan.table_name, [part], needed)
            if log is not None:
                log.bump("host_decodes")
            ent = {"cols": {c: np.asarray(v) for c, v in cols.items()},
                   "validity": {c: np.asarray(v, dtype=np.bool_)
                                for c, v in validity.items()}}
            bpool.offer(pk, ent, table=scan.table_name, log=log)
        for c, v in ent["cols"].items():
            col_chunks.setdefault(c, []).append(v)
        for c, v in ent["validity"].items():
            val_chunks.setdefault(c, []).append(v)
    hit = {c: (jnp.asarray(vs[0]) if len(vs) == 1
               else jnp.concatenate([jnp.asarray(v) for v in vs]))
           for c, vs in col_chunks.items()}
    for c, vs in val_chunks.items():
        # chunks are bool by construction (pool entries and fresh
        # decodes both store np.bool_), so no re-cast is needed
        hit[f"$nn:{c}"] = (jnp.asarray(vs[0]) if len(vs) == 1
                           else jnp.concatenate(
                               [jnp.asarray(v) for v in vs]))
    return hit


def run_executable(exe: Executable, tables: dict) -> ColumnBatch:
    # device launch under the statement's trace span + a jax.profiler
    # annotation (obs/trace.py): an XLA profile of a traced statement
    # correlates with the host span names; both are no-ops untraced
    from cloudberry_tpu.obs import trace as OT

    with OT.span("launch", plan=type(exe.plan).__name__), \
            OT.device_annotation("launch"):
        cols, sel, checks = exe.fn(tables)
    raise_checks(checks)
    return make_batch(exe.plan, cols, sel)


def raise_checks(checks: dict) -> None:
    for msg, bad in checks.items():
        if bool(np.asarray(bad).any()):
            if "duplicate keys" in msg:
                raise DuplicateBuildKeyError(msg)
            raise ExecError(msg)


def make_batch(plan: N.PlanNode, cols, sel) -> ColumnBatch:
    shown = [f for f in plan.fields if not f.name.startswith("$vm")]
    fields = tuple(Field(f.name, f.type) for f in shown)
    dicts = {f.name: f.sdict for f in shown if f.sdict is not None}
    validity = {}
    for f in shown:
        ms = f.masks
        if ms and all(m in cols for m in ms):
            v = np.asarray(cols[ms[0]]).astype(bool)
            for m in ms[1:]:
                v = v & np.asarray(cols[m]).astype(bool)
            validity[f.name] = v
    return ColumnBatch(Schema(fields),
                       {f.name: np.asarray(cols[f.name]) for f in shown},
                       np.asarray(sel), dicts, validity=validity)


def _rank_better(mx: bool, v1, r1, c1, v2, r2, c2):
    """True where lane 2 beats lane 1 by (valid desc, sort rank, code) —
    THE extreme comparator: an invalid (NULL) lane never beats a valid
    one, strings compare by collation rank with code as the
    associativity tie-break. Shared by the running-extreme segmented
    scan and the ROWS-frame sparse-table query so the two min/max paths
    cannot diverge."""
    if mx:
        by_rank = (r2 > r1) | ((r2 == r1) & (c2 > c1))
    else:
        by_rank = (r2 < r1) | ((r2 == r1) & (c2 < c1))
    return (v2 & ~v1) | ((v2 == v1) & by_rank)


def _as_column(v, cap: int):
    """Broadcast a 0-d (constant) value to column shape — constant
    projections, sort keys, and window keys (e.g. grouping() folded to a
    literal per grouping-sets branch) all need full columns."""
    return jnp.broadcast_to(v, (cap,)) if v.ndim == 0 else v


def _vsearch(s, target, lo, hi, cap: int, lower: bool):
    """Vectorized per-row binary search over the (partition-wise sorted)
    array s restricted to per-row inclusive bounds [lo, hi]: returns the
    insertion point — first index j with s[j] >= target (lower) or
    s[j] > target (upper); hi+1 when every bounded element is smaller.
    O(log cap) unrolled lock-step halvings (no data-dependent trip
    counts, so the whole thing stays inside the one XLA program)."""
    l = jnp.asarray(lo)
    h = jnp.asarray(hi) + 1
    for _ in range(max(1, int(cap).bit_length()) + 1):
        active = l < h
        m = (l + h) // 2
        mv = s[jnp.clip(m, 0, cap - 1)]
        go_right = (mv < target) if lower else (mv <= target)
        l = jnp.where(active & go_right, m + 1, l)
        h = jnp.where(active & ~go_right, m, h)
    return l


def _rmq_extreme(ks, cs, va, lo, hi, cap: int, mx: bool):
    """Per-row range extreme over [lo, hi] via a sparse table: O(n log n)
    build (static level count — XLA unrolls it), two gathers per query.
    Lanes compare by (valid desc, sort rank, code): an invalid (NULL)
    lane never beats a valid one, and string ranks follow collation, not
    code order. Empty/all-NULL frames return an arbitrary code — the
    caller's masks nullify them."""
    import jax.lax as lax

    def better(a, b):
        v1, r1, c1 = a
        v2, r2, c2 = b
        take2 = _rank_better(mx, v1, r1, c1, v2, r2, c2)
        return (v1 | v2, jnp.where(take2, r2, r1),
                jnp.where(take2, c2, c1))

    levels = [(va, ks, cs)]
    step = 1
    pos = jnp.arange(cap)
    n_levels = max(1, int(cap).bit_length())
    for _ in range(1, n_levels):
        pv, pr, pc = levels[-1]
        j2 = jnp.minimum(pos + step, cap - 1)
        levels.append(better((pv, pr, pc), (pv[j2], pr[j2], pc[j2])))
        step *= 2
    V = jnp.stack([v for v, _, _ in levels])
    R = jnp.stack([r for _, r, _ in levels])
    C = jnp.stack([c for _, _, c in levels])
    w = jnp.maximum(hi - lo + 1, 1).astype(jnp.int32)
    k = (jnp.int32(31) - lax.clz(w)).astype(jnp.int32)
    p1 = jnp.clip(lo, 0, cap - 1)
    p2 = jnp.clip(hi - (jnp.int32(1) << k) + 1, 0, cap - 1)
    _, _, out = better((V[k, p1], R[k, p1], C[k, p1]),
                       (V[k, p2], R[k, p2], C[k, p2]))
    return out


def all_nodes(plan: N.PlanNode):
    """Every node in the plan, including scalar-subquery plans and runtime
    filters' shared build subtrees (via their joins)."""
    yield plan
    from cloudberry_tpu.plan.distribute import _node_exprs

    for e in _node_exprs(plan):
        for sub in ex.walk(e):
            if isinstance(sub, ex.SubqueryScalar):
                yield from all_nodes(sub.plan)
    for c in plan.children():
        yield from all_nodes(c)


def find_expansion_node(plan: N.PlanNode, message: str):
    """The join a detected expansion-overflow check message points at
    (messages embed the node id), or None."""
    import re

    m = re.search(r"\(node (\d+)\)", message)
    if m is None or "expansion overflow" not in message:
        return None
    nid = int(m.group(1))
    for node in all_nodes(plan):
        if id(node) == nid and isinstance(node, N.PJoin):
            return node
    return None


def _dedupe_nodes(nodes) -> list:
    """Unique by identity, preserving order — all_nodes re-walks shared
    (PShare) subtrees once per reference, and a buffer must be grown
    exactly once per retry."""
    seen: set[int] = set()
    out = []
    for nd in nodes:
        if id(nd) not in seen:
            seen.add(id(nd))
            out.append(nd)
    return out


def grow_expansion(plan: N.PlanNode, message: str, factor: int = 4,
                   allow_fallback: bool = False) -> bool:
    """Adaptive recovery from a detected join-expansion overflow (the
    increase-nbatch-and-retry discipline of nodeHash.c): grow the named
    join's pair buffer by ``factor`` and report success. The caller
    recompiles and re-runs — results are never truncated. A skew-blown
    redistribute bucket recovers the same way, except it promotes to
    the next CAPACITY RUNG that fits (``factor`` does not apply there —
    rung shapes are what the session's executable cache is keyed on).

    ``allow_fallback``: when the message's node id resolves nowhere in
    ``plan``, grow every candidate buffer instead of giving up. Only
    the statement retry loop sets this — there an unresolvable id means
    the program came from a rung-cached executable of an equivalent,
    since-collected plan, and blanket growth is padding at worst with
    guaranteed progress. Tiled callers keep it off: their id miss means
    the overflowing node is genuinely outside the plan at hand, and the
    original error must surface, not a mutated retry."""
    from cloudberry_tpu.lifecycle import check_cancel

    # cancel seam: each grow-and-retry round recompiles and re-runs the
    # whole program — a cancelled statement must stop climbing the
    # capacity ladder, not ride it to the ceiling first
    check_cancel()
    node = find_expansion_node(plan, message)
    join_hits = [node] if node is not None else []
    if not join_hits and allow_fallback \
            and "expansion overflow" in message:
        join_hits = _dedupe_nodes(
            nd for nd in all_nodes(plan)
            if isinstance(nd, N.PJoin)
            and (not nd.unique_build or nd.residual is not None))
    if join_hits:
        for nd in join_hits:
            nd.out_capacity = max(nd.out_capacity * factor, 64)
            # capacity re-derivations (e.g. tiled _retile) must never
            # shrink a runtime-grown buffer back below what overflowed
            nd._min_out_cap = nd.out_capacity
        return True
    if "host bucket overflow" in message:
        import re

        m = re.search(r"\(node (\d+)\)", message)
        nid = int(m.group(1)) if m is not None else -1
        hits = _dedupe_nodes(
            nd for nd in all_nodes(plan)
            if isinstance(nd, N.PMotion) and nd.kind == "redistribute"
            and nd.host_bucket_cap > 0 and id(nd) == nid)
        if not hits and allow_fallback:
            hits = _dedupe_nodes(
                nd for nd in all_nodes(plan)
                if isinstance(nd, N.PMotion)
                and nd.kind == "redistribute" and nd.host_bucket_cap > 0)
        for nd in hits:
            # the two-level DCN block climbs the SAME pow2 ladder as the
            # per-segment rung — straight to the observed demand's rung
            observed = getattr(nd, "_observed_host_bucket", 0)
            nd.host_bucket_cap = K.rung_up(
                max(nd.host_bucket_cap * 2, observed, 64))
            # no _min_* floor needed: nothing re-derives host_bucket_cap
            # on a live plan (tiled _retile_dist re-derives bucket_cap
            # only), so the promoted rung cannot be shrunk back
        return bool(hits)
    if "redistribute overflow" in message:
        import re

        m = re.search(r"\(node (\d+)\)", message)
        nid = int(m.group(1)) if m is not None else -1
        # kind filter matters: a stale id from a rung-cached executable
        # (compiled off an equivalent, since-collected plan) could alias
        # ANY current node's address — never promote a gather/broadcast
        hits = _dedupe_nodes(
            nd for nd in all_nodes(plan)
            if isinstance(nd, N.PMotion)
            and nd.kind == "redistribute" and id(nd) == nid)
        if not hits and allow_fallback:
            # the failing program was compiled from an EQUIVALENT plan
            # (rung-cache hit across a replan), so the embedded node id
            # does not resolve here: promote every redistribute — extra
            # padding at worst, and the retry is guaranteed progress
            hits = _dedupe_nodes(
                nd for nd in all_nodes(plan)
                if isinstance(nd, N.PMotion)
                and nd.kind == "redistribute")
        for nd in hits:
            # out_capacity tracks bucket_cap × nseg; recover the
            # factor so memory estimates see the grown buffer
            nseg = max(1, (nd.out_capacity or nd.bucket_cap)
                       // max(nd.bucket_cap, 1))
            # promote to the next capacity rung — or straight to the
            # rung fitting the observed global bucket demand when the
            # run reported one (dist_executor.record_motion_stats)
            observed = getattr(nd, "_observed_bucket", 0)
            nd.bucket_cap = K.rung_up(
                max(nd.bucket_cap * 2, observed, 64))
            nd.out_capacity = nd.bucket_cap * nseg
            # tiled re-derivations must never shrink it back
            nd._min_bucket_cap = nd.bucket_cap
            if nd.host_bucket_cap > 0:
                # keep the two-level invariant host_bucket_cap >=
                # bucket_cap (a pair bucket must fit its host block) and
                # fold in the host demand this run already observed —
                # otherwise the retry is a guaranteed host-rung overflow
                # costing one more full recompile+execute cycle
                nd.host_bucket_cap = K.rung_up(max(
                    nd.host_bucket_cap, nd.bucket_cap,
                    getattr(nd, "_observed_host_bucket", 0)))
        return bool(hits)
    return False


def scans_of(plan: N.PlanNode):
    if isinstance(plan, N.PScan) and plan.table_name != "$dual":
        yield plan
    # scalar subqueries ride inside expressions, not children — their scans
    # need table inputs too (a FROM-less outer SELECT may still scan)
    from cloudberry_tpu.plan.distribute import _node_exprs

    for e in _node_exprs(plan):
        for sub in ex.walk(e):
            if isinstance(sub, ex.SubqueryScalar):
                yield from scans_of(sub.plan)
    for c in plan.children():
        yield from scans_of(c)


# ------------------------------------------------------------- plan lowering


class Lowerer:
    """Traces a plan into jax ops. Subclassed by the distributed executor,
    which overrides scan (per-segment inputs) and motion (collectives)."""

    def __init__(self, tables, platform: str | None = None,
                 use_pallas: bool = False, params=None):
        self.tables = tables
        # runtime literal bindings for a generic plan (sched/paramplan.py):
        # "$prm<slot>" -> scalar array, injected next to the columns when
        # an expression carries Param leaves
        self.params = params
        self.checks: dict[str, jnp.ndarray] = {}
        # replicated observability scalars (e.g. each redistribute's
        # observed bucket demand) — the distributed executor returns
        # them next to checks for host-side capacity-rung promotion
        self.stats: dict[str, jnp.ndarray] = {}
        self._subcache: dict[int, jnp.ndarray] = {}
        # shared-subplan (PShare) results, keyed by child object identity
        self._sharecache: dict[int, tuple] = {}
        # scatter (segment ops) lower well on CPU; TPU serializes large
        # scatters, so it gets unrolled masked reductions instead
        platform = platform or jax.default_backend()
        self.platform = platform
        self.dense_strategy = "segment" if platform == "cpu" else "reduce"
        self.use_pallas = use_pallas

    def lower(self, node: N.PlanNode) -> tuple[dict, jnp.ndarray]:
        if isinstance(node, N.PScan):
            return self.scan(node)
        if isinstance(node, N.PFilter):
            cols, sel = self.lower(node.child)
            mask = self.expr(node.predicate, cols)
            return cols, sel & mask
        if isinstance(node, N.PProject):
            cols, sel = self.lower(node.child)
            out = {}
            for name, e in node.exprs:
                out[name] = _as_column(self.expr(e, cols), sel.shape[0])
            return out, sel
        if isinstance(node, N.PJoin):
            return self.join(node)
        if isinstance(node, N.PAgg):
            return self.agg(node)
        if isinstance(node, N.PSort):
            cols, sel = self.lower(node.child)
            keys, desc = [], []
            for e, asc in node.keys:
                keys.append(_as_column(_sortable(e, node.child, cols),
                                       sel.shape[0]))
                desc.append(not asc)
            perm = K.sort_indices(keys, sel, descending=desc)
            return {n: c[perm] for n, c in cols.items()}, sel[perm]
        if isinstance(node, N.PLimit):
            cols, sel = self.lower(node.child)
            return cols, K.limit_mask(sel, node.limit, node.offset)
        if isinstance(node, N.PMotion):
            return self.motion(node)
        if isinstance(node, N.PWindow):
            return self.window(node)
        if isinstance(node, N.PShare):
            return self.lower_shared(node.child)
        if isinstance(node, N.PRuntimeFilter):
            return self.runtime_filter(node)
        if isinstance(node, N.PConcat):
            outs = [self.lower(c) for c in node.inputs]
            cols = {f.name: jnp.concatenate([o[0][f.name] for o in outs])
                    for f in node.fields}
            sel = jnp.concatenate([o[1] for o in outs])
            return cols, sel
        raise ExecError(f"cannot execute node {type(node).__name__}")

    # ------------------------------------------------------------ hookable

    def scan(self, node: N.PScan):
        if node.table_name == "$dual":
            return {}, jnp.ones((1,), dtype=jnp.bool_)
        data = self.tables[getattr(node, "_input_key", node.table_name)]
        cols = {}
        for phys, out in node.column_map.items():
            arr = data[phys]
            if arr.shape[0] < node.capacity:  # empty table: 0 rows, cap 1
                arr = jnp.zeros((node.capacity,), dtype=arr.dtype)
            cols[out] = arr
        for phys, out in node.mask_map.items():
            arr = data[f"$nn:{phys}"]
            if arr.shape[0] < node.capacity:
                arr = jnp.zeros((node.capacity,), dtype=jnp.bool_)
            cols[out] = arr
        n = node.num_rows if node.num_rows >= 0 else node.capacity
        key = getattr(node, "_nrows_key", None)
        if key is not None and self.params is not None \
                and key in self.params:
            # generic plan: the row count rides the $params input, so one
            # compiled program serves every direct-dispatch segment (and
            # every table version at unchanged capacity) — the count is
            # data, the CAPACITY is the shape
            n = self.params[key]
        sel = jnp.arange(node.capacity) < n
        return cols, sel

    def motion(self, node: N.PMotion):
        # single-program mode: loopback motion is the identity (the
        # MotionIPCLayer seam's test backend). lower_shared: a runtime
        # filter may reference the motion's child (build side) too.
        return self.lower_shared(node.child)

    def global_any(self, x) -> jnp.ndarray:
        """Any() across ALL data — the distributed lowerer reduces over the
        segment axis too (null-aware NOT IN needs a cluster-wide answer)."""
        return jnp.any(x)

    def lower_shared(self, node: N.PlanNode):
        """Lower a subtree at most once (PShare / runtime-filter build
        sharing) — the materialize-once contract at trace level."""
        key = id(node)
        if key not in self._sharecache:
            self._sharecache[key] = self.lower(node)
        return self._sharecache[key]

    def runtime_filter(self, node: N.PRuntimeFilter):
        """Single-program mode: motions are loopback, so the filter would
        only duplicate the join's own matching — pass through."""
        return self.lower(node.child)

    # ----------------------------------------------------------- expressions

    def expr(self, e: ex.Expr, cols) -> jnp.ndarray:
        """Evaluate an expression; uncorrelated scalar subqueries (InitPlan
        analog) are lowered once inside the same program and broadcast;
        Param leaves (generic plans) read their runtime binding from the
        program's "$params" input."""
        subs = [n for n in ex.walk(e) if isinstance(n, ex.SubqueryScalar)]
        if self.params is not None \
                and any(isinstance(n, ex.Param) for n in ex.walk(e)):
            cols = {**cols, **self.params}
        if not subs:
            return compile_expr(e)(cols)
        aug = dict(cols)
        mapping = {}
        for sq in subs:
            key = id(sq)
            if key not in self._subcache:
                scols, ssel = self.lower(sq.plan)
                n = jnp.sum(ssel.astype(jnp.int64))
                if sq.mode == "exists":
                    # presence term: did the subplan select any row at
                    # all (the 0-rows→NULL half of scalar semantics)
                    self._subcache[key] = n > 0
                else:
                    arr = scols[sq.plan.fields[0].name]
                    self.checks[
                        f"scalar subquery returned more than one row "
                        f"(node {key})"] = n > 1
                    # 0 selected rows: argmax lands on row 0, whose value
                    # is arbitrary — the binder's presence term masks the
                    # result NULL, so it is never observed
                    idx = jnp.argmax(ssel)  # the single selected row
                    self._subcache[key] = arr[idx]
            name = f"$sqv{key}"
            mapping[key] = name
            aug[name] = self._subcache[key]
        return compile_expr(_substitute_subqueries(e, mapping))(aug)

    # ------------------------------------------------------------ operators

    def _join_index(self, node: N.PJoin):
        """Cached sorted-build index for this join (exec/joinindex.py):
        (order, sorted packed keys, packing ranges) fed as a program
        input, or None → compute the argsort in-program. Tiled/spill
        assemblies never provide the input, so the fallback is automatic
        there; distributed 'shard'-mode arrays arrive with a leading
        (1, …) segment axis inside shard_map and normalize here."""
        spec = getattr(node, "_jix", None)
        if spec is None:
            return None
        jix = self.tables.get(spec.key)
        if jix is None:
            return None
        order, skeys = jnp.asarray(jix["order"]), jnp.asarray(jix["skeys"])
        if order.ndim == 2:
            order, skeys = order[0], skeys[0]
        ranges = []
        for i in range(len(node.build_keys)):
            lo = jnp.asarray(jix[f"lo{i}"]).reshape(())
            span = jnp.asarray(jix[f"span{i}"]).reshape(())
            ranges.append((lo, span))
        return order, skeys, ranges

    def join(self, node: N.PJoin):
        # lower_shared: a runtime filter may reference the same build
        # subtree — it must trace once
        bcols, bsel = self.lower_shared(node.build)
        pcols, psel = self.lower(node.probe)
        bkeys = [self.expr(k, bcols) for k in node.build_keys]
        pkeys = [self.expr(k, pcols) for k in node.probe_keys]

        # SQL NULL-key semantics: a NULL key matches nothing. NULL-key build
        # rows leave the build set; NULL-key probe rows become unmatched
        # (they still flow through left/full/anti via the ORIGINAL psel).
        bkv = self.expr(node.build_key_valid, bcols) \
            if node.build_key_valid is not None else None
        pkv = self.expr(node.probe_key_valid, pcols) \
            if node.probe_key_valid is not None else None
        bselm = bsel & bkv if bkv is not None else bsel
        pselm = psel & pkv if pkv is not None else psel

        if node.kind in ("semi", "anti") and node.residual is not None:
            return self._join_semi_residual(node, bcols, bselm, bkeys,
                                            pcols, psel, pselm, pkeys)
        if not node.unique_build:
            return self._join_expand(node, bcols, bsel, bselm, bkeys,
                                     pcols, psel, pselm, pkeys)

        fused = self._probe_join_pallas(node, bcols, bselm, bkeys,
                                        pselm, pkeys)
        if fused is not None:
            matched, payload, has_dup = fused
        else:
            jix = self._join_index(node)
            if jix is not None:
                idx, matched, has_dup = K.join_lookup_sorted(
                    jix[0], jix[1], jix[2], pkeys, pselm,
                    bits=node.pack_bits)
            else:
                idx, matched, has_dup = K.join_lookup(
                    bkeys, bselm, pkeys, pselm, bits=node.pack_bits)
            payload = K.gather_payload(
                {n: bcols[n] for n in node.build_payload}, idx, matched)
        if node.kind in ("inner", "left"):
            # semi/anti only test membership; inner/left rely on the
            # planner's uniqueness proof — verify it at runtime. The XLA
            # path checks the build side itself (adjacent-equal on its
            # sorted keys); the fused path's >1 one-hot column sum is
            # weaker — it fires only when a probe row actually HITS the
            # duplicated key, i.e. exactly when results would be wrong
            self.checks[
                f"join build side has duplicate keys (node {id(node)}) but "
                "the planner assumed a unique (PK) build side"] = has_dup
        cols = {**pcols, **payload}
        if node.match_name:
            cols[node.match_name] = matched
        if node.kind in ("inner", "semi"):
            sel = matched
        elif node.kind == "left":
            sel = psel
        elif node.kind == "anti":
            sel = psel & ~matched
            if node.null_aware:
                # x NOT IN (...): never TRUE if x is NULL or ANY subquery
                # key is NULL — the build-side test must be GLOBAL across
                # segments (the NULL row may live on another shard)
                if pkv is not None:
                    sel = sel & pkv
                if bkv is not None:
                    sel = sel & ~self.global_any(bsel & ~bkv)
        else:
            raise ExecError(f"join kind {node.kind}")
        return cols, sel

    def window(self, node: N.PWindow):
        """Windows over sorted partitions — scatter-free: boundary flags,
        compacted starts, cumulative-sum differences (nodeWindowAgg analog;
        with ORDER BY the frame is RANGE UNBOUNDED PRECEDING..CURRENT ROW,
        peers included, per the SQL default)."""
        cols, sel = self.lower(node.child)
        cap = sel.shape[0]
        pk = [_as_column(self.expr(e, cols), cap)
              for e in node.partition_keys]
        # ORDER BY on strings sorts by collation rank, not dictionary code
        # (same rule PSort applies via _sortable)
        ok = [_as_column(_sortable(e, node.child, cols), cap)
              for e, _ in node.order_keys]
        desc = [not asc for _, asc in node.order_keys]
        perm = K.sort_indices(pk + ok, sel,
                              descending=[False] * len(pk) + desc)
        inv = jnp.argsort(perm)
        s_sel = sel[perm]
        n_sel = jnp.sum(s_sel.astype(jnp.int32))
        idx = jnp.arange(cap)

        def flags(keys):
            f = jnp.zeros(cap, dtype=jnp.bool_)
            for k in keys:
                ks = k[perm]
                f = f | (ks != jnp.roll(ks, 1))
            return (f.at[0].set(True)) & s_sel

        seg_flag = flags(pk) if pk else \
            (jnp.zeros(cap, dtype=jnp.bool_).at[0].set(True) & s_sel)
        run_flag = (seg_flag | flags(ok)) if ok else seg_flag

        seg_starts_c = jnp.argsort(~seg_flag, stable=True)
        seg_cum = jnp.cumsum(seg_flag.astype(jnp.int32))
        seg_id0 = jnp.clip(seg_cum - 1, 0, cap - 1)
        n_segs = jnp.sum(seg_flag.astype(jnp.int32))
        seg_start = seg_starts_c[seg_id0]
        nxt = seg_starts_c[jnp.clip(seg_id0 + 1, 0, cap - 1)]
        seg_end = jnp.where(seg_id0 + 1 < n_segs, nxt - 1, n_sel - 1)

        run_starts_c = jnp.argsort(~run_flag, stable=True)
        run_cum = jnp.cumsum(run_flag.astype(jnp.int32))
        run_id0 = jnp.clip(run_cum - 1, 0, cap - 1)
        n_runs = jnp.sum(run_flag.astype(jnp.int32))
        rnxt = run_starts_c[jnp.clip(run_id0 + 1, 0, cap - 1)]
        run_start = run_starts_c[run_id0]
        run_end = jnp.where(run_id0 + 1 < n_runs, rnxt - 1, n_sel - 1)

        def pref(vals):
            csum = jnp.cumsum(vals)
            return jnp.concatenate(
                [jnp.zeros((1,), dtype=csum.dtype), csum])

        # explicit frame (node.frame): per-row [flo, fhi] bounds in sorted
        # coordinates. The SQL default keeps the peer-inclusive RANGE
        # semantics (run_end); ROWS frames are purely positional and can
        # be EMPTY at partition edges (fempty)
        if node.frame is None:
            flo = seg_start
            fhi = run_end if node.order_keys else seg_end
            fempty = None
        elif node.frame[0] == "whole":
            flo, fhi = seg_start, seg_end
            fempty = None
        elif node.frame[0] == "rangepos":
            # positional RANGE (CURRENT ROW / UNBOUNDED bounds only):
            # peer-group or partition edges, never empty; the start is
            # always the peer-group head (UNBOUNDED-lo shapes reduced
            # to the default/whole frames at bind time). Without ORDER
            # BY every row is a peer (run_* == seg_*), the SQL rule.
            flo = run_start
            fhi = run_end if node.frame[2] == "peer" else seg_end
            fempty = None
        elif node.frame[0] == "rangeoff":
            # value-distance frame: per-row binary search for the key
            # interval [k+lo, k+hi] inside the partition's non-NULL span.
            # NULL-key rows frame exactly their peer group (the SQL rule:
            # NULL ± offset stays NULL, NULLs are peers of NULLs), while
            # UNBOUNDED sides keep the positional partition edge — which
            # includes NULL rows, matching nodeWindowAgg.c.
            _, lo_off, hi_off, knull = node.frame
            asc = node.order_keys[-1][1]
            kv_s = ok[-1][perm]
            if knull:
                keyvalid = (ok[0][perm] == 0) & s_sel
                # NULLs sort last ASC / first DESC (PSort's rule), so
                # valid keys are a prefix (asc) or suffix (desc) of the
                # partition
                C = pref(keyvalid.astype(jnp.int32))
                nv = C[jnp.clip(seg_end + 1, 0, cap)] - \
                    C[jnp.clip(seg_start, 0, cap)]
                vlo = seg_start if asc else seg_end - nv + 1
                vhi = seg_start + nv - 1 if asc else seg_end
            else:
                keyvalid = s_sel
                vlo, vhi = seg_start, seg_end
            # search in frame direction: DESC negates so "PRECEDING"
            # stays the -offset side of a nondecreasing array
            s = kv_s if asc else -kv_s
            knullrow = s_sel & ~keyvalid

            def _target(off):
                # numeric offsets are same-domain distances; a
                # ("months", n) offset is a CALENDAR shift of each
                # row's civil date (timestamp.c interval_pl: month
                # arithmetic with the day-of-month clamped), computed
                # in-program via the Hinnant civil<->days round trip.
                # DESC negates the search domain, so the month count
                # must flip too (s + off ≡ -(v - off) there): PRECEDING
                # under DESC reaches LATER dates.
                if isinstance(off, tuple):
                    sh = _shift_months_days(kv_s.astype(jnp.int64),
                                            off[1] if asc else -off[1])
                    return sh if asc else -sh
                return s + off
            if lo_off is None:
                flo = seg_start
            else:
                f = _vsearch(s, _target(lo_off), vlo, vhi, cap,
                             lower=True)
                flo = jnp.where(knullrow, run_start, f)
            if hi_off is None:
                fhi = seg_end
            else:
                f = _vsearch(s, _target(hi_off), vlo, vhi, cap,
                             lower=False) - 1
                fhi = jnp.where(knullrow, run_end, f)
            fempty = flo > fhi
        else:
            _, lo_off, hi_off = node.frame
            flo = seg_start if lo_off is None \
                else jnp.maximum(idx + lo_off, seg_start)
            fhi = seg_end if hi_off is None \
                else jnp.minimum(idx + hi_off, seg_end)
            fempty = flo > fhi

        out_cols = dict(cols)
        valids = node.valids or [None] * len(node.calls)
        params_list = node.params or [None] * len(node.calls)
        for (name, func, arg), valid, params in zip(node.calls, valids,
                                                    params_list):
            # per-call argument validity in sorted row order: count counts
            # only valid rows, avg divides by the valid count, 'anyvalid'
            # is the null mask for nullable agg outputs
            va = (s_sel & self.expr(valid, cols)[perm]) \
                if valid is not None else s_sel
            base = func.split("@", 1)[0]
            if func == "row_number":
                o = (idx - seg_start + 1).astype(jnp.int64)
            elif func == "ntile":
                # SQL ntile: larger buckets first — with s rows and n
                # buckets, the first s%n buckets get s//n+1 rows
                n = params["n"]
                rip = idx - seg_start
                psize = seg_end - seg_start + 1
                base_sz = psize // n
                rem = psize % n
                thresh = rem * (base_sz + 1)
                o = (jnp.where(rip < thresh,
                               rip // jnp.maximum(base_sz + 1, 1),
                               rem + (rip - thresh)
                               // jnp.maximum(base_sz, 1))
                     + 1).astype(jnp.int64)
            elif base in ("lead", "lag", "first_value", "last_value"):
                # positional reads within the sorted partition. The source
                # row index is computed per row; '<func>@mask' re-runs the
                # same gather over the argument's validity (plus the
                # in-partition range test) to produce the output null mask
                if base in ("lead", "lag"):
                    k = params["offset"]
                    src = idx + k if base == "lead" else idx - k
                    inrange = (src >= seg_start) & (src <= seg_end)
                elif base == "first_value":
                    # frame start (the partition head under the default)
                    src = flo
                    inrange = None if fempty is None else ~fempty
                else:
                    # last_value: frame end — under the default frame the
                    # current row's peer group, not the partition tail
                    src = fhi
                    inrange = None if fempty is None else ~fempty
                srcc = jnp.clip(src, 0, cap - 1)
                if func.endswith("@mask"):
                    o = va[srcc]
                    if inrange is not None:
                        if (params or {}).get("default") is not None:
                            # out-of-range rows take the (non-NULL) default
                            o = jnp.where(inrange, o, True)
                        else:
                            o = inrange & o
                else:
                    v = self.expr(arg, cols)[perm]
                    o = v[srcc]
                    if inrange is not None:
                        dflt = (params or {}).get("default")
                        fill = self.expr(dflt, cols).astype(v.dtype) \
                            if dflt is not None \
                            else jnp.zeros((), v.dtype)
                        o = jnp.where(inrange, o, fill)
            elif func == "rank":
                o = (run_start - seg_start + 1).astype(jnp.int64)
            elif func == "dense_rank":
                o = (run_cum - run_cum[seg_start] + 1).astype(jnp.int64)
            elif func in ("sum", "count", "avg", "anyvalid"):
                if func in ("count", "anyvalid") or arg is None:
                    v = va.astype(jnp.int64)
                else:
                    v = jnp.where(va, self.expr(arg, cols)[perm], 0)
                S = pref(v)
                hip = jnp.clip(fhi + 1, 0, cap)
                lop = jnp.clip(flo, 0, cap)
                o = S[hip] - S[lop]
                if fempty is not None:
                    o = jnp.where(fempty, jnp.zeros((), o.dtype), o)
                if func == "avg":
                    C = pref(va.astype(jnp.int64))
                    cnt = C[hip] - C[lop]
                    if fempty is not None:
                        cnt = jnp.where(fempty, 0, cnt)
                    o = o.astype(jnp.float64) / jnp.maximum(cnt, 1)
                    if arg is not None and arg.dtype.base == DType.DECIMAL:
                        o = o / (10.0 ** arg.dtype.scale)
                elif func == "anyvalid":
                    o = o > 0
            elif func in ("min", "max") and node.frame is not None \
                    and node.frame[0] in ("rows", "rangeoff", "rangepos"):
                # ROWS/RANGE-offset-frame extreme: sparse-table range
                # query over [flo, fhi] — the prefix-sum trick does not
                # invert for min/max, and the running scan only covers
                # suffix-anchored frames
                ks = _sortable(arg, node.child, cols)[perm]
                cs = self.expr(arg, cols)[perm]
                o = _rmq_extreme(ks, cs, va, flo, fhi, cap,
                                 mx=(func == "max"))
                if fempty is not None:
                    o = jnp.where(fempty, jnp.zeros((), o.dtype), o)
            elif func in ("min", "max") and node.frame is None \
                    and node.order_keys:
                # running extreme (RANGE UNBOUNDED PRECEDING..CURRENT ROW,
                # peers included via run_end): segmented scan over sorted
                # rows. The combine is the standard segmented-scan operator
                # (reset flag ? right : extreme(left, right)) with the
                # extreme taken lexicographically over (validity desc,
                # sort rank, code) so it stays associative on ties and an
                # invalid (NULL) lane can NEVER beat a valid one — not
                # even when a valid value equals the dtype extreme (an
                # all-NULL prefix is nullified by the 'anyvalid' mask).
                v = self.expr(arg, cols)
                ks = _sortable(arg, node.child, cols)[perm]
                cs = v[perm]
                mx = func == "max"

                def comb(a, b, mx=mx):
                    f1, w1, r1, c1 = a
                    f2, w2, r2, c2 = b
                    # segment reset flag ? right : extreme (the shared
                    # comparator keeps this path and the ROWS-frame RMQ
                    # ordering identical)
                    take2 = f2 | _rank_better(mx, w1, r1, c1, w2, r2, c2)
                    return (f1 | f2, jnp.where(take2, w2, w1),
                            jnp.where(take2, r2, r1),
                            jnp.where(take2, c2, c1))

                _, _, _, runext = jax.lax.associative_scan(
                    comb, (seg_flag, va, ks, cs))
                o = runext[run_end]
            elif func in ("min", "max"):
                # whole-partition extreme: re-sort with the value last; the
                # extreme lands on each partition's boundary row (strings
                # order by collation rank, output keeps the code). Invalid
                # (NULL) lanes sort behind every valid row in their
                # partition, so they reach the boundary only for all-NULL
                # partitions — which the 'anyvalid' mask nullifies.
                v = self.expr(arg, cols)
                vkey = _sortable(arg, node.child, cols)
                extra = [] if valid is None else \
                    [(~self.expr(valid, cols)).astype(jnp.int32)]
                p2 = K.sort_indices(pk + extra + [vkey], sel,
                                    descending=[False] * (len(pk)
                                                          + len(extra))
                                    + [func == "max"])
                o = v[p2][seg_start]
            else:
                raise ExecError(f"window function {func}")
            o = jnp.where(s_sel, o, jnp.zeros((), dtype=o.dtype))
            out_cols[name] = o[inv]  # back to the child's row order
        return out_cols, sel

    def _join_semi_residual(self, node: N.PJoin, bcols, bselm, bkeys,
                            pcols, psel, pselm, pkeys):
        """Correlated EXISTS with extra non-equi conditions (Q21 shape):
        expand equi-match pairs, evaluate the residual per pair, then
        OR-reduce back onto probe rows."""
        cap = node.out_capacity
        pi, bi, osel, _matched, total = self._expand_pairs(
            node, bkeys, bselm, pkeys, pselm, cap)
        self.checks[
            f"semi-join expansion overflow: match pairs exceed capacity "
            f"{cap} (node {id(node)})"] = total > cap
        paircols = {name: jnp.take(c, pi, axis=0) for name, c in pcols.items()}
        for name in node.build_payload:
            paircols[name] = jnp.take(bcols[name], bi, axis=0)
        rmask = self.expr(node.residual, paircols) & osel
        hit = jnp.zeros(psel.shape, dtype=jnp.bool_)
        hit = hit.at[pi].max(rmask, mode="drop")
        sel = psel & hit if node.kind == "semi" else psel & ~hit
        return dict(pcols), sel

    def _expand_pairs(self, node: N.PJoin, bkeys, bselm, pkeys, pselm,
                      cap: int):
        """join_expand through the cached sorted-build index when one is
        fed (skips the build argsort), else the full kernel."""
        jix = self._join_index(node)
        if jix is not None:
            return K.join_expand_sorted(jix[0], jix[1], jix[2], pkeys,
                                        pselm, cap, bits=node.pack_bits)
        return K.join_expand(bkeys, bselm, pkeys, pselm, cap,
                             bits=node.pack_bits)

    def _join_expand(self, node: N.PJoin, bcols, bsel, bselm, bkeys,
                     pcols, psel, pselm, pkeys):
        """Many-to-many expansion: one output row per match pair; LEFT joins
        append unmatched (preserved) probe rows after the pairs; FULL joins
        append unmatched rows from BOTH sides (NULL-key rows of either side
        are unmatched by construction — bselm/pselm exclude them from
        matching, bsel/psel keep them in the preserved regions)."""
        cap = node.out_capacity
        pi, bi, osel, matched, total = self._expand_pairs(
            node, bkeys, bselm, pkeys, pselm, cap)
        need = total
        is_pair = osel
        j = jnp.arange(cap, dtype=total.dtype)
        probe_valid = osel  # rows whose probe columns are real
        if node.kind in ("left", "full"):
            um = psel & ~matched
            um_rank = jnp.cumsum(um.astype(total.dtype)) - 1
            n_um = jnp.sum(um.astype(total.dtype))
            slot = jnp.where(um, total + um_rank, cap)
            pi = pi.at[slot].set(jnp.arange(um.shape[0], dtype=pi.dtype),
                                 mode="drop")
            osel = j < (total + n_um)
            is_pair = j < total
            probe_valid = osel
            need = total + n_um
            if node.kind == "full":
                bmatched = jnp.zeros(bsel.shape, dtype=jnp.bool_)
                bmatched = bmatched.at[bi].max(is_pair, mode="drop")
                um_b = bsel & ~bmatched
                umb_rank = jnp.cumsum(um_b.astype(total.dtype)) - 1
                n_umb = jnp.sum(um_b.astype(total.dtype))
                slot_b = jnp.where(um_b, total + n_um + umb_rank, cap)
                bi = bi.at[slot_b].set(
                    jnp.arange(um_b.shape[0], dtype=bi.dtype), mode="drop")
                osel = j < (total + n_um + n_umb)
                # build columns are real for pairs AND the build-only region
                is_pair = (j < total) | (j >= total + n_um)
                probe_valid = j < (total + n_um)
                need = total + n_um + n_umb
        elif node.kind != "inner":
            raise ExecError(f"expansion join does not support {node.kind}")
        self.checks[
            f"join expansion overflow: match pairs exceed capacity {cap} "
            f"(node {id(node)})"] = need > cap

        cols = {}
        for name, c in pcols.items():
            g = jnp.take(c, pi, axis=0)
            if node.kind == "full":
                # zero the build-only region; other kinds exclude those
                # rows via the selection mask already
                g = jnp.where(probe_valid, g, jnp.zeros((), dtype=g.dtype))
            cols[name] = g
        for name in node.build_payload:
            g = jnp.take(bcols[name], bi, axis=0)
            cols[name] = jnp.where(is_pair, g,
                                   jnp.zeros((), dtype=g.dtype))
        if node.match_name:
            cols[node.match_name] = is_pair
        if node.probe_match_name:
            cols[node.probe_match_name] = probe_valid
        return cols, osel

    def agg(self, node: N.PAgg):
        cols, sel = self.lower(node.child)
        agg_specs = []
        agg_values: dict[str, Any] = {}
        post_scale: dict[str, float] = {}
        for name, call in node.aggs:
            # NULL semantics are compiled away by the binder: nullable args
            # arrive identity-filled with companion valid-count aggregates
            # (Binder._mask_nullable_aggs), so only standard funcs remain.
            func = call.func
            if func in ("sum", "min", "max", "avg", "count"):
                agg_values[name] = self.expr(call.arg, cols) \
                    if call.arg is not None else None
            else:
                raise ExecError(f"aggregate {func} not implemented yet")
            if func == "avg" and call.arg is not None \
                    and call.arg.dtype.base == DType.DECIMAL:
                post_scale[name] = 10.0 ** call.arg.dtype.scale
            agg_specs.append(K.AggSpec(func, name))

        if not node.group_keys:
            out = K.global_aggregate(agg_values, agg_specs, sel)
            for name, div in post_scale.items():
                out[name] = out[name] / div
            return out, jnp.ones((1,), dtype=jnp.bool_)

        dense = self._dense_agg(node, cols, sel, agg_specs, agg_values,
                                post_scale)
        if dense is not None:
            return dense

        key_cols = {name: self.expr(e, cols)
                    for name, e in node.group_keys}
        out_keys, out_aggs, out_sel, n_groups = merge_group_aggregate(
            key_cols, agg_values, agg_specs, sel, node.capacity,
            self.use_pallas, self.platform)
        self.checks[
            f"aggregation overflow: more groups than capacity "
            f"{node.capacity} (node {id(node)})"] = n_groups > node.capacity
        for name, div in post_scale.items():
            out_aggs[name] = out_aggs[name] / div
        return {**out_keys, **out_aggs}, out_sel


    def _dense_agg_pallas(self, gid, n_cells, agg_specs, agg_values, sel):
        """Fused one-pass Pallas path (config.exec.use_pallas) for
        sum/count/avg over a small cell domain. Integer-carried values
        (BIGINT, DECIMAL cents) ride 13-bit f32 limbs through the MXU
        one-hot matmul and recombine EXACTLY in int64 — bit-identical to
        the XLA path, so Q1's money sums are A/B-eligible. Float values
        keep the single-f32-row transport (approximate analytics).
        Returns None when ineligible (min/max) → XLA path."""
        if not self.use_pallas:
            return None
        if any(s.func not in ("sum", "count", "avg") for s in agg_specs):
            return None
        from cloudberry_tpu.exec import pallas_kernels as PK

        tile = 2048
        sum_specs = [s for s in agg_specs if s.func in ("sum", "avg")]
        rows: list = []
        layout = []  # (spec, first row, "int"|"float", value dtype)
        for s in sum_specs:
            v = agg_values[s.out_name]
            if jnp.issubdtype(v.dtype, jnp.integer):
                layout.append((s, len(rows), "int", v.dtype))
                rows.extend(PK.int64_to_agg_limbs(v))
            else:
                layout.append((s, len(rows), "float", v.dtype))
                rows.append(v.astype(jnp.float32))
        stacked = jnp.stack(rows) if rows else \
            jnp.zeros((0, gid.shape[0]), jnp.float32)
        tiles = PK.dense_agg_tiles_pallas(
            _pallas_pad(gid.astype(jnp.int32), tile),
            _pallas_pad(stacked, tile),
            _pallas_pad(sel, tile),
            n_cells=n_cells, tile=tile,
            interpret=(self.platform == "cpu"))
        # per-tile counts are exact integers in f32 (≤ tile < 2^24);
        # the cross-tile combine runs in int64, exact for any N
        counts = jnp.sum(jnp.round(tiles[:, 0]).astype(jnp.int64), axis=0)
        out = {}
        n_limbs = len(PK.AGG_LIMB_BITS)
        for s, row0, kind, dt in layout:
            if kind == "int":
                totals = [jnp.sum(jnp.round(tiles[:, 1 + row0 + i])
                                  .astype(jnp.int64), axis=0)
                          for i in range(n_limbs)]
                ssum = PK.agg_limbs_to_int64(totals)
                out[s.out_name] = ssum.astype(jnp.float64) \
                    / jnp.maximum(counts, 1) if s.func == "avg" \
                    else ssum.astype(dt)
            else:
                ssum = jnp.sum(tiles[:, 1 + row0].astype(jnp.float64),
                               axis=0)
                out[s.out_name] = ssum / jnp.maximum(counts, 1) \
                    if s.func == "avg" else ssum.astype(dt)
        for s in agg_specs:
            if s.func == "count":
                out[s.out_name] = counts
        return out, counts > 0

    _PALLAS_PROBE_MAX_BUILD = 2048

    def _probe_join_pallas(self, node: N.PJoin, bcols, bselm, bkeys,
                           pselm, pkeys):
        """Fused probe join (config.exec.use_pallas): for a SMALL unique
        build whose keys pack to 32 bits, stream probe tiles once —
        compare-all match on the VPU, payload gather as ONE one-hot
        matmul on the MXU, integer payloads transported exactly through
        21/21/22-bit f32 limbs (pallas_kernels.probe_join_pallas).
        Returns (matched, payload cols, has_dup) or None → XLA path."""
        if not self.use_pallas or node.pack_bits != 32:
            return None
        b = int(bselm.shape[0])
        if b > self._PALLAS_PROBE_MAX_BUILD:
            return None
        for nm in node.build_payload:
            if not (jnp.issubdtype(bcols[nm].dtype, jnp.integer)
                    or bcols[nm].dtype == jnp.bool_):
                return None  # float payload: exactness needs the XLA path
        from cloudberry_tpu.exec import pallas_kernels as PK

        ranges = K.key_ranges(bkeys, bselm)
        bp = K.downcast32(K.pack_with_ranges(bkeys, ranges))
        pp = K.downcast32(K.pack_with_ranges(pkeys, ranges))
        rows = []
        for nm in node.build_payload:
            rows.extend(PK.int64_to_limbs(bcols[nm]))
        if not rows:  # membership-only joins still fuse the match
            rows = [jnp.zeros((b,), jnp.float32)]
        tile = 1024
        n = int(pselm.shape[0])
        match_f, gathered = PK.probe_join_pallas(
            _pallas_pad(bp, 256), _pallas_pad(bselm, 256),
            _pallas_pad(pp, tile), _pallas_pad(pselm, tile),
            _pallas_pad(jnp.stack(rows), 256), tile=tile,
            interpret=(self.platform == "cpu"))
        matched = match_f[:n] > 0.5
        has_dup = jnp.any(match_f > 1.5)
        payload = {}
        for i, nm in enumerate(node.build_payload):
            v = PK.limbs_to_int64(gathered[3 * i, :n],
                                  gathered[3 * i + 1, :n],
                                  gathered[3 * i + 2, :n])
            payload[nm] = v.astype(bcols[nm].dtype)
        return matched, payload, has_dup

    def _dense_agg(self, node: N.PAgg, cols, sel, agg_specs, agg_values,
                   post_scale):
        """Perfect-hash aggregation when ALL group keys are dictionary-coded
        strings with a small static domain (nodeAgg's hashed strategy with a
        compile-time-perfect hash) — skips the sort entirely."""
        sizes = []
        for name, e in node.group_keys:
            f = node.field(name)
            if f.type.base != DType.STRING or f.sdict is None \
                    or len(f.sdict) == 0:
                return None
            sizes.append(len(f.sdict))
        prod = 1
        for s in sizes:
            prod *= s
        # 'reduce' unrolls one masked reduction per cell — cap the unroll
        # hard or XLA program size / compile time explodes; 'segment' (CPU
        # scatter) scales to larger domains
        max_cells = 4096 if self.dense_strategy == "segment" else 64
        if prod > min(node.capacity, max_cells):
            return None

        strides = []
        acc = 1
        for s in reversed(sizes):
            strides.append(acc)
            acc *= s
        strides.reverse()

        gid = jnp.zeros(sel.shape, dtype=jnp.int32)
        for (name, e), stride in zip(node.group_keys, strides):
            gid = gid + self.expr(e, cols).astype(jnp.int32) \
                * np.int32(stride)
        pallas_out = self._dense_agg_pallas(gid, prod, agg_specs,
                                            agg_values, sel)
        if pallas_out is not None:
            out_aggs, occupied = pallas_out
        else:
            out_aggs, occupied = K.group_aggregate_dense(
                gid, prod, agg_values, agg_specs, sel,
                strategy=self.dense_strategy)
        for name, div in post_scale.items():
            out_aggs[name] = out_aggs[name] / div

        cell = jnp.arange(prod, dtype=jnp.int32)
        out_keys = {}
        for (name, _), stride, size in zip(node.group_keys, strides, sizes):
            out_keys[name] = (cell // np.int32(stride)) % np.int32(size)

        cap = node.capacity
        if cap > prod:
            pad = cap - prod
            out_keys = {n: jnp.pad(c, (0, pad)) for n, c in out_keys.items()}
            out_aggs = {n: jnp.pad(c, (0, pad)) for n, c in out_aggs.items()}
            occupied = jnp.pad(occupied, (0, pad))
        return {**out_keys, **out_aggs}, occupied


def merge_group_aggregate(key_cols, agg_values, specs, sel, capacity: int,
                          use_pallas: bool, platform: str):
    """Grouped-aggregation dispatch shared by the one-shot Lowerer and
    the tiled/tiled-dist merge steps: the fused sorted-segment Pallas
    kernel when eligible (sum/avg over integer-carried values + count,
    ≤ 2^23 rows — pallas_kernels.sorted_segment_eligible), else the XLA
    sort path. The two produce BIT-IDENTICAL results for eligible aggs
    (int sums exact in both), so per-tile partials and one-shot runs
    agree exactly whichever side fires."""
    if use_pallas:
        from cloudberry_tpu.exec import pallas_kernels as PK

        if PK.sorted_segment_eligible(specs, agg_values,
                                      int(sel.shape[0])):
            return PK.sorted_segment_aggregate(
                key_cols, agg_values, specs, sel, capacity,
                interpret=(platform == "cpu"))
    return K.group_aggregate(key_cols, agg_values, specs, sel, capacity)


def _sortable(e: ex.Expr, child: N.PlanNode, cols) -> jnp.ndarray:
    """ORDER BY key array; string columns sort by host rank, not code."""
    arr = compile_expr(e)(cols)
    if e.dtype.base == DType.STRING:
        sdict = None
        if isinstance(e, ex.ColumnRef):
            try:
                sdict = child.field(e.name).sdict
            except KeyError:
                sdict = getattr(e, "_sdict", None)
        else:
            sdict = getattr(e, "_sdict", None) or getattr(e, "_out_dict", None)
        if sdict is not None and len(sdict):
            rank = jnp.asarray(sdict.rank_table())
            safe = jnp.clip(arr, 0, rank.shape[0] - 1)
            return jnp.where(arr >= 0, jnp.take(rank, safe), -1)
    return arr


def _days_from_civil(y, m, d):
    """(year, month, day) → days since 1970-01-01; Howard Hinnant's
    branchless days-from-civil (the inverse of
    expr_compile._civil_from_days)."""
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    doy = (153 * (m + jnp.where(m > 2, -3, 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _shift_months_days(days, n_months: int):
    """Shift day-numbers by n calendar months, clamping the day of month
    (Mar 31 - 1 month = Feb 28) — PG's date + interval 'n months'
    semantics (src/backend/utils/adt/timestamp.c interval_pl role),
    vectorized for the RANGE frame search."""
    from cloudberry_tpu.exec.expr_compile import _civil_from_days

    y, m, d = _civil_from_days(days)
    mm = m.astype(jnp.int64) - 1 + n_months
    y2 = y.astype(jnp.int64) + jnp.floor_divide(mm, 12)
    m2 = jnp.mod(mm, 12) + 1
    leap = ((y2 % 4 == 0) & ((y2 % 100 != 0) | (y2 % 400 == 0)))
    dim = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                      dtype=jnp.int64)[m2 - 1]
    dim = jnp.where((m2 == 2) & leap, 29, dim)
    d2 = jnp.minimum(d.astype(jnp.int64), dim)
    return _days_from_civil(y2, m2, d2)


def _pallas_pad(a, tile):
    n = a.shape[-1]
    pad = (-n) % tile
    if pad == 0:
        return a
    widths = [(0, 0)] * (a.ndim - 1) + [(0, pad)]
    return jnp.pad(a, widths)


def _substitute_subqueries(e: ex.Expr, mapping: dict[int, str]) -> ex.Expr:
    """Replace SubqueryScalar nodes with ColumnRefs into the augmented
    column dict (generic rewriter: new node types flow through)."""
    return ex.rewrite(
        e, lambda n: ex.ColumnRef(mapping[id(n)], n.dtype)
        if isinstance(n, ex.SubqueryScalar) else None)
